"""Benchmark of record: batched all-sources SPF at the BASELINE.md scale
points, measured against a native C++ Dijkstra baseline.

Configs (BASELINE.json):
  #1 grid 1024 (32x32, unit metric)      — all-sources, continuity metric
  #2 fat-tree ~10k switches (4-plane)    — all-sources, THE HEADLINE
  #3 WAN 100k small-world, dual metrics  — router-view SPF (self+neighbors,
     the per-router production question) + a 1024-source tile for the
     all-sources scaling story

The baseline is an in-repo native binary-heap Dijkstra (benchmarks/cpp/
spf_baseline.cpp, g++ -O3) with the reference's runSpf semantics
(openr/decision/LinkState.cpp:809-878), run sequentially per source exactly
as the reference computes per-source SPF.  It is conformance-checked
bit-exact against the TPU kernel before timing.  For the 10k all-sources
row the C++ time is measured on a 64-source sample and scaled linearly
(per-source cost is constant); noted in details.

The TPU kernel additionally extracts the full tie-retaining shortest-path
DAG (ECMP structure) in the same measured call — work the C++ baseline does
not even attempt.

Timing: min over reps after warmup.  The shared TPU tunnel in this
environment has a bimodal dispatch mode that can add a flat ~100ms penalty
per call in degraded windows (measured: identical compiled programs flip
between 0.04ms and ~100ms across sessions); min-over-reps reports the
hardware's actual capability.  Full per-rep samples land in
bench_details.json.

Wedge-proofing: the same tunnel can wedge device init or a dispatch
*forever* (round-2 bench lost every device row to this).  All device rows
therefore run in a CHILD process (`--device-child`) that appends each
completed row to a JSONL side file and flushes per row; the parent
enforces a per-row progress timeout, kills a stalled child, merges
whatever landed, and respawns the child (skipping finished rows) across
several attempts spread over the run.  A wedge can now cost at most one
row per attempt, never the whole bench.

Prints ONE JSON line (headline), writes bench_details.json with all rows.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import Optional

import numpy as np

DETAILS_PATH = "bench_details.json"
DEVICE_ROWS_PATH = "bench_device_rows.jsonl"
# per-row progress timeout for the child: covers device init (~15s),
# topology build (100k WAN ~60s) and first-compile (~40s) with slack
ROW_TIMEOUT_S = float(os.environ.get("OPENR_BENCH_ROW_TIMEOUT_S", "900"))
DEVICE_ATTEMPTS = int(os.environ.get("OPENR_BENCH_DEVICE_ATTEMPTS", "4"))
RETRY_SLEEP_S = float(os.environ.get("OPENR_BENCH_RETRY_SLEEP_S", "60"))
# split timed reps across two tunnel latency windows (see _time_device)
WINDOW_SPLIT_S = float(os.environ.get("OPENR_BENCH_WINDOW_SPLIT_S", "45"))
# global wall budget for the WHOLE bench run (0 = uncapped).  When the
# driver runs this under its own timeout, set the cap slightly below it:
# the bench then sheds remaining rows, reuses HEAD-committed rows for
# code paths that didn't change, and still exits 0 with the headline
# JSON printed — instead of being killed mid-row (rc 124, parsed null).
BUDGET_S = float(os.environ.get("OPENR_BENCH_BUDGET_S", "0"))
_START = time.monotonic()


def _budget_left() -> float:
    if BUDGET_S <= 0:
        return float("inf")
    return BUDGET_S - (time.monotonic() - _START)


def _shed_marker(section: str) -> dict:
    """Pre-check shed row: emitted INSTEAD OF starting a compile-heavy
    section when the remaining wall budget cannot cover it — the row
    dies cleanly in the artifact rather than the whole run dying at
    rc=124 mid-compile (BENCH_r05)."""
    return {
        "error": (
            f"skipped: wall budget exhausted before {section} "
            f"(shed marker, OPENR_BENCH_BUDGET_S)"
        )
    }


def _child_env(**extra: str) -> dict:
    """Environment for a child process: the global budget var is
    rewritten to the REMAINING budget so the child's own shed
    pre-checks measure from the right clock (a child restarts
    time.monotonic() accounting from its own import)."""
    env = {**os.environ, **extra}
    if BUDGET_S > 0:
        env["OPENR_BENCH_BUDGET_S"] = str(max(_budget_left(), 1.0))
    return env


def _attach_bw(row: dict, bytes_moved: Optional[float], wall_ms) -> dict:
    """Record the utilization lens on a device row: estimated HBM bytes
    moved by one timed call and the achieved fraction of peak BW
    (benchmarks.util.achieved_bw_frac).  Estimates are traffic models
    (dist matrix passes + outputs), not profiler counts — named *_est."""
    from benchmarks.util import achieved_bw_frac

    row["bytes_moved_est"] = int(bytes_moved) if bytes_moved else None
    row["achieved_bw_frac"] = achieved_bw_frac(bytes_moved, wall_ms)
    return row


def _flush_details(details: dict) -> None:
    """Incremental flush so a crash/wedge mid-run never loses prior rows."""
    tmp = DETAILS_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(details, f, indent=1)
    os.replace(tmp, DETAILS_PATH)


def _time_device(
    fn, reps: int, warmup: int = 2, window_split_s: float = WINDOW_SPLIT_S
) -> list[float]:
    """min-over-reps, with the reps SPLIT across two tunnel latency
    windows: the flat per-dispatch fee is bimodal on ~30s timescales, so
    taking all samples inside one degraded window would report the
    window, not the hardware.  The sleep costs bench wall time, not
    measured time."""
    import jax

    for _ in range(warmup):
        jax.block_until_ready(fn())
    out = []
    for i in range(reps):
        if window_split_s and reps > 1 and i == (reps + 1) // 2:
            time.sleep(window_split_s)
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        out.append((time.perf_counter() - t0) * 1e3)
    return out


def _time_amortized(make_loop, runs: int, reps: int = 3) -> Optional[float]:
    """Per-run ms with the flat per-dispatch tunnel tax divided out.

    The shared TPU tunnel charges a bimodal flat fee per dispatch (~0.04ms
    or ~100ms depending on the window) that min-over-reps cannot shake when
    the window stays degraded for minutes.  `make_loop(runs)` must return a
    jitted thunk executing the kernel `runs` times INSIDE one dispatch
    (inputs rotated per iteration so XLA cannot hoist the loop body); the
    per-run time then reflects what the hardware sustains, which is the
    number production batching achieves (the daemon pipelines many SPF
    questions per dispatch).  Reported alongside the wall numbers, never
    instead of them."""
    import jax

    loop = make_loop(runs)
    jax.block_until_ready(loop())  # compile + warm
    single = make_loop(1)
    jax.block_until_ready(single())
    # min over each series separately: pairing a fast-window loop() with a
    # degraded-window single() (or vice versa) would corrupt the
    # difference; the two mins are each fast-window samples
    many, one = [], []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(loop())
        many.append((time.perf_counter() - t0) * 1e3)
        t0 = time.perf_counter()
        jax.block_until_ready(single())
        one.append((time.perf_counter() - t0) * 1e3)
    per_run = (min(many) - min(one)) / (runs - 1)
    if per_run <= 0:
        # the windows flipped against the estimator (single landed in a
        # worse window than every loop); report "inconclusive", never a
        # fabricated 0
        return None
    return per_run


def _make_kernel_loop(run_i):
    """Shared scaffolding for the amortized loops: `run_i(i)` returns
    (dist, dag) for rotated-input iteration i; both outputs are reduced
    into the fori carry so nothing is dead code."""
    import jax
    import jax.numpy as jnp

    def make_loop(runs):
        @jax.jit
        def loop():
            def body(i, acc):
                dist, dag = run_i(i)
                return acc + jnp.sum(dist) + jnp.sum(dag.astype(jnp.int32))

            return jax.lax.fori_loop(0, runs, body, jnp.int32(0))

        return loop

    return make_loop


def bench_all_sources(topo, sources, reps, cpp_sample=None):
    """Returns dict row: kernel ms (dist + SP-DAG), C++ baseline ms.

    Runs the PRODUCTION fixed-sweep path (ops.banded.SpfRunner): the
    band-aware kernel where the topology has circulant structure (grid,
    WAN ring) and the bucketed ELL elsewhere (fat-tree), at the learned
    per-topology sweep hint with the in-dispatch convergence verdict —
    no data-dependent while_loop, whose per-iteration host sync used to
    dominate these rows on the tunneled transport."""
    import jax

    from benchmarks import cpp_baseline

    sources = np.asarray(sources, dtype=np.int32)
    runner = topo.runner

    # warmup learns the sweep hint + compiles; then timed runs execute at
    # the fixed hint and the verdict is asserted after timing
    runner.forward(sources)
    hint = runner.hint

    # rotate the source batch per timed rep: identical inputs re-run
    # could be served from a transport-level result cache (observed
    # anomalous ~0ms walls on repeat-identical dispatches), which would
    # fake the wall number; a rolled batch is cost-equivalent fresh work
    rep_counter = [0]
    # shifts must stay below the batch length or a wrapped roll would
    # re-dispatch a byte-identical input (replay-guard degeneracy);
    # a single-source batch has no distinct rolls — modulo-1 keeps the
    # shift harmlessly constant instead of dividing by zero
    max_calls = max(1, len(sources) - 1)

    def run():
        rep_counter[0] = rep_counter[0] % max_calls + 1
        return runner.run_once(np.roll(sources, rep_counter[0]), hint)

    # parity check (small sample) before timing
    sample = np.asarray(sources[:: max(1, len(sources) // 8)][:8], np.int32)
    _, cdist = cpp_baseline.spf_all_sources(
        topo.n_nodes,
        topo.edge_src[: topo.n_edges],
        topo.edge_dst[: topo.n_edges],
        topo.edge_metric[: topo.n_edges],
        topo.edge_up[: topo.n_edges],
        topo.node_overloaded[: topo.n_nodes],
        sample,
        want_dist=True,
    )
    dist, _ = runner.forward(sample)
    np.testing.assert_array_equal(dist[:, : topo.n_nodes], cdist)

    times = _time_device(run, reps)
    _, _, ok = run()
    assert bool(ok), "timed runs did not reach the fixed point"

    # amortized per-run cost (tax-free): R forwards in ONE dispatch with
    # rotated sources
    import jax.numpy as jnp

    src_dev = jnp.asarray(sources)
    amortized = _time_amortized(
        _make_kernel_loop(
            lambda i: runner.run_once(jnp.roll(src_dev, i), hint)[:2]
        ),
        runs=8,
    )

    # C++ baseline timing
    cpp_sources = sources
    scale = 1.0
    if cpp_sample is not None and cpp_sample < len(sources):
        cpp_sources = sources[:: len(sources) // cpp_sample][:cpp_sample]
        scale = len(sources) / len(cpp_sources)
    cpp_secs, _ = cpp_baseline.spf_all_sources(
        topo.n_nodes,
        topo.edge_src[: topo.n_edges],
        topo.edge_dst[: topo.n_edges],
        topo.edge_metric[: topo.n_edges],
        topo.edge_up[: topo.n_edges],
        topo.node_overloaded[: topo.n_nodes],
        np.asarray(cpp_sources, dtype=np.int32),
    )
    # traffic model: the [S, N] distance matrix is read+written once per
    # relax supersweep plus one verification pass; the SP-DAG adds one
    # output write of the edge-mask words
    itemsize = 2 if getattr(runner, "small_dist", False) else 4
    dist_bytes = len(sources) * topo.n_nodes * itemsize
    bytes_moved = dist_bytes * 2 * (hint + 1)
    return _attach_bw(
        {
            "topology": topo.name,
            "n_nodes": topo.n_nodes,
            "n_directed_edges": topo.n_edges,
            "n_sources": len(sources),
            "device_ms_min": round(min(times), 3),
            "device_ms_amortized": (
                round(amortized, 3) if amortized is not None else None
            ),
            "device_ms_all": [round(t, 2) for t in times],
            "cpp_baseline_ms": round(cpp_secs * 1e3 * scale, 3),
            "cpp_sources_measured": len(cpp_sources),
            "cpp_scaled": scale != 1.0,
        },
        bytes_moved,
        min(times),
    )


def _pctl(xs, p: float) -> float:
    return float(np.percentile(np.asarray(xs, dtype=np.float64), p))


def bench_allsrc_full_wan100k(topo, n_prefixes: int = 1024) -> dict:
    """The 100k-node all-sources product, REDUCED-OUTPUT formulation
    (round-4): route building never reads an [N, N] matrix — per router
    it reads distances + ECMP next-hops toward the P prefix-originating
    nodes (reference: createRouteForPrefix / getNextHopsThrift,
    Decision.cpp:615-793, 1296-1300).  All-sources-to-P-destinations is
    ONE P-source SSSP on the reversed graph, and the next-hop bitmaps
    for ALL 100k routers follow from the reverse distances in a fused
    gather-only pass (ops.allsources) — so the fleet-wide route-building
    input is a single device round, not ceil(N/1024)=98 tiled dispatches
    of an output nobody consumes (r3: 197.7 s end-to-end).

    Output ([N,P] dist + [N,P,W] uint32 bitmaps, ~600 MB at
    P=1024) stays on device; each router's route build reads its own
    row, exactly as the per-tile distances did before."""
    import jax

    from benchmarks.synthetic import reversed_topology
    from openr_tpu.ops import allsources as asrc

    n = topo.n_nodes
    rev = reversed_topology(topo)
    rng = np.random.default_rng(7)
    dests = np.sort(
        rng.choice(n, size=n_prefixes, replace=False).astype(np.int32)
    )
    import jax.numpy as _jnp

    out = asrc.build_out_ell(
        topo.edge_src, topo.edge_dst, topo.n_edges, n
    )
    runner = rev.runner
    # device-resident forward arrays for the bitmap pass (the reverse
    # runner's own arrays are staged by Topology.runner): per-dispatch
    # numpy re-upload is pure tunnel wall (round-5 tune: ~130ms for the
    # runner's ~11MB)
    fwd_metric = _jnp.asarray(topo.edge_metric)
    fwd_up = _jnp.asarray(topo.edge_up)
    fwd_ov = _jnp.asarray(topo.node_overloaded)

    # warm + compile the FUSED PROGRESSIVE program (the production
    # default since round 6): relax supersweeps early-exit at the actual
    # fixed point via an on-device while_loop over supersweep blocks,
    # and the ECMP bitmap is folded into the final verification pass so
    # the [N, P] product is read once — no separate bitmap dispatch
    maps = asrc.build_epilogue_maps(runner.bg, out)
    dist, bitmap, ok = asrc.reduced_all_sources(
        dests, runner, out, fwd_metric, fwd_up, fwd_ov, maps=maps
    )
    assert bool(ok)
    # minimal fixed-sweep count that converges (attribution probes only;
    # the timed path runs the progressive program, which needs no hint)
    hint = None
    for s in (4, 6, 8, 12, 16, 24, 32, 48, 64):
        _, _, okp = runner.run_once(
            dests, s, want_dag=False, raw_u16=True, transpose=False
        )
        if bool(okp):
            hint = s
            break
    assert hint is not None

    # spot parity: reverse distances == forward oracle rows
    from benchmarks import cpp_baseline

    sample_v = rng.choice(n, size=4, replace=False).astype(np.int32)
    _, cdist = cpp_baseline.spf_all_sources(
        n,
        topo.edge_src[: topo.n_edges],
        topo.edge_dst[: topo.n_edges],
        topo.edge_metric[: topo.n_edges],
        topo.edge_up[: topo.n_edges],
        topo.node_overloaded[:n],
        sample_v,
        want_dist=True,
    )
    from openr_tpu.decision.fleet import _row_i32

    # raw uint16 product -> the int32/INF32 oracle domain ([N*, P]
    # native layout: row v = dist(v -> every dest))
    dist_np = _row_i32(np.asarray(dist))
    for i, v in enumerate(sample_v):
        np.testing.assert_array_equal(dist_np[v], cdist[i, dests])

    rep_counter = [0]

    def run_reduced():
        # roll the destination rows per rep (transport replay guard —
        # see bench_all_sources)
        rep_counter[0] += 1
        dist, bitmap, ok = asrc.reduced_all_sources(
            np.roll(dests, rep_counter[0]),
            runner,
            out,
            fwd_metric,
            fwd_up,
            fwd_ov,
            maps=maps,
        )
        jax.block_until_ready((dist, bitmap))
        return ok

    times = _time_device(run_reduced, reps=6, warmup=0)
    assert bool(run_reduced())
    end_to_end_ms = min(times)

    # gap attribution (r3 next #2): where does the distance to the 50 ms
    # target go?  A true zero-work dispatch doesn't exist (even
    # n_supersweeps=1 runs one relax + the verification sweep), so
    # derive per-sweep cost from the (1, hint) pair and attribute:
    #   per_sweep     = (t(hint) - t(1)) / (hint - 1)
    #   dispatch tax  = t(1) - 2*per_sweep   (1 relax + 1 verify sweep)
    #   relax total   = (hint + 1) * per_sweep
    #   bitmap pass   = end-to-end minus the epilogue-free progressive run
    # every attribution sample gets a DISTINCT input (rolled dests /
    # rolled distance rows): repeat-identical dispatches can be served
    # from a transport result cache, which once produced physically
    # impossible per-sweep numbers here
    attr_counter = [0]

    def _min_t(make_call):
        def fn():
            attr_counter[0] += 1
            return make_call(attr_counter[0])

        return min(_time_device(fn, reps=3, warmup=1, window_split_s=0))

    t_one = _min_t(
        lambda i: runner.run_once(
            np.roll(dests, i), 1, want_dag=False, raw_u16=True,
            transpose=False,
        )
    )
    t_kernel = _min_t(
        lambda i: runner.run_once(
            np.roll(dests, i), hint, want_dag=False, raw_u16=True,
            transpose=False,
        )
    )
    per_sweep = max(t_kernel - t_one, 0.0) / max(hint - 1, 1)
    t_tax = max(t_one - 2 * per_sweep, 0.0)
    # progressive relax WITHOUT the fused bitmap epilogue: the difference
    # vs end-to-end is the true marginal of the in-relax bitmap pass
    # (round-5's separate ecmp_bitmap_from_reverse_dist dispatch no
    # longer exists on the production path)
    t_relax_prog = _min_t(
        lambda i: runner.run_once(
            np.roll(dests, i), None, want_dag=False, raw_u16=True,
            transpose=False, progressive=True,
        )
    )
    t_bitmap = end_to_end_ms - t_relax_prog
    # traffic model: each relax supersweep streams the [N, P] state
    # twice (read + write), the fused verify/epilogue pass reads it
    # once more, and the epilogue writes the [N, P, W] uint32 bitmaps
    itemsize = 2 if getattr(runner, "small_dist", False) else 4
    dist_bytes = n * n_prefixes * itemsize
    bytes_moved = (
        dist_bytes * (2 * hint + 1) + n * n_prefixes * out.n_words * 4
    )
    return _attach_bw(
        {
            "topology": topo.name,
            "n_nodes": n,
            "n_prefix_destinations": n_prefixes,
            "nh_bitmap_words": out.n_words,
            "end_to_end_ms": round(end_to_end_ms, 1),
            "end_to_end_ms_all": [round(t, 1) for t in times],
            "gap_attribution_ms": {
                "dispatch_tax_est": round(t_tax, 1),
                "relax_sweeps_total": round(per_sweep * (hint + 1), 1),
                "nh_bitmap_pass_marginal": round(max(t_bitmap, 0), 1),
                "per_supersweep": round(per_sweep, 2),
                "n_supersweeps": hint,
                "in_dispatch_est": round(max(end_to_end_ms - t_tax, 0), 1),
            },
            "progressive": {"check_every": 4, "max_blocks": 64},
            "fused_epilogue": True,
            "north_star_target_ms": 50.0,
            "note": (
                "round-6 production path: fused progressive program — "
                "on-device while_loop over supersweep blocks early-exits "
                "at the certified fixed point, and the fleet-wide ECMP "
                "bitmap is folded into the final verification pass (no "
                "separate bitmap dispatch). The [N,N] product remains "
                "un-materializable (40 GB) and unconsumed by route "
                "building; outputs stay on device for per-router builds."
            ),
        },
        bytes_moved,
        end_to_end_ms,
    )


def bench_fleet_warm_wan100k(topo, n_prefixes: int = 1024) -> dict:
    """Warm-started fleet rebuild, BOTH gate directions (round-6).
    Improvement-only (flap recovery — a downed ring link comes back up):
    the previous product is an elementwise upper bound, so the relax
    seeds from it directly.  Worsening (the link goes DOWN): the
    affected set — every entry some old tight chain reaches across the
    worsened edge — is re-initialized to INF and the rest of the
    previous product kept (ops.banded.affected_mask, certified
    fixpoint; gates in decision.fleet).  Reports cold vs warm end-to-end
    for the SAME final topology in each direction; warm == cold
    distances are asserted bit-exact before timing.  The reference has
    no equivalent: its SPF memo is invalidated wholesale on any
    topology change (openr/decision/LinkState.cpp:714-719)."""
    import jax
    import jax.numpy as jnp

    from benchmarks.synthetic import reversed_topology
    from openr_tpu.ops import allsources as asrc
    from openr_tpu.ops.banded import SpfRunner

    n = topo.n_nodes
    rev = reversed_topology(topo)
    rng = np.random.default_rng(7)
    dests = np.sort(
        rng.choice(n, size=n_prefixes, replace=False).astype(np.int32)
    )
    out = asrc.build_out_ell(topo.edge_src, topo.edge_dst, topo.n_edges, n)
    fwd_metric = jnp.asarray(topo.edge_metric)
    fwd_up = jnp.asarray(topo.edge_up)
    fwd_ov = jnp.asarray(topo.node_overloaded)

    # "down" topology: one ring link down (both directions), in BOTH the
    # reverse runner (relax) and the forward masks (fused bitmap pass)
    down_up = rev.edge_up.copy()
    down_eids = np.flatnonzero(
        ((rev.edge_src[: rev.n_edges] == 0) & (rev.edge_dst[: rev.n_edges] == 1))
        | ((rev.edge_src[: rev.n_edges] == 1) & (rev.edge_dst[: rev.n_edges] == 0))
    )
    down_up[down_eids] = False
    runner_down = SpfRunner(
        rev.ell, rev.banded, rev.edge_src, rev.edge_dst, rev.edge_metric,
        down_up, rev.node_overloaded, rev.n_edges,
    )
    runner_down.stage()
    fwd_down = np.asarray(topo.edge_up).copy()
    fwd_down_eids = np.flatnonzero(
        ((topo.edge_src[: topo.n_edges] == 0) & (topo.edge_dst[: topo.n_edges] == 1))
        | ((topo.edge_src[: topo.n_edges] == 1) & (topo.edge_dst[: topo.n_edges] == 0))
    )
    fwd_down[fwd_down_eids] = False
    fwd_up_down = jnp.asarray(fwd_down)

    runner = rev.runner
    maps = asrc.build_epilogue_maps(runner.bg, out)

    dist_before, _, ok = asrc.reduced_all_sources(
        dests, runner_down, out, fwd_metric, fwd_up_down, fwd_ov, maps=maps
    )
    assert bool(ok)
    # pristine cold product (the link-UP "after" state)
    dist_cold, _, ok = asrc.reduced_all_sources(
        dests, runner, out, fwd_metric, fwd_up, fwd_ov, maps=maps
    )
    assert bool(ok)

    # -- link UP (flap recovery, improvement-only): warm from the downed
    # product on the pristine graph; exactness vs the cold fixed point
    dist_w, _, okw = asrc.reduced_all_sources(
        dests, runner, out, fwd_metric, fwd_up, fwd_ov,
        init_dist=dist_before, maps=maps,
    )
    assert bool(okw)
    assert bool(jnp.all(dist_w == dist_cold))

    # -- link DOWN (worsening): affected-set re-init from the pristine
    # product (decision.fleet._affected_init discipline): propagate the
    # worsened-edge seed along OLD tight reverse chains to a certified
    # fixpoint, re-set affected entries to INF, keep the rest
    from openr_tpu.ops.banded import INF16, INF32, affected_mask

    bg = runner.bg
    nb = bg.n_nodes
    rn = np.asarray(bg.resid_nbr)
    re_ = np.asarray(bg.resid_eid)
    v_ids = np.arange(nb, dtype=np.int64)
    # reverse edge u -> v is forward edge v -> u: the downed forward
    # pairs (0,1) and (1,0) mark reverse slots (v=0,u=1) and (v=1,u=0)
    wr = (re_ >= 0) & (
        ((v_ids[:, None] == 0) & (rn == 1))
        | ((v_ids[:, None] == 1) & (rn == 0))
    )
    be = np.asarray(bg.band_eid)
    rows = []
    for b, c in enumerate(bg.offsets):
        u = (v_ids - c) % nb
        rows.append(
            (be[b] >= 0)
            & (((v_ids == 0) & (u == 1)) | ((v_ids == 1) & (u == 0)))
        )
    wb = np.stack(rows)
    _, _, r_met, r_up, r_ov = runner.call_arrays()
    small = dist_cold.dtype == np.uint16
    aff, done = affected_mask(
        dist_cold, bg, r_up, r_met, r_ov,
        jnp.asarray(wr), jnp.asarray(wb),
        small_dist=bool(small), max_iters=128,
    )
    assert bool(done), "affected-set propagation must certify its fixpoint"
    inf = jnp.uint16(INF16) if small else jnp.int32(INF32)
    init_down = jnp.where(aff, inf, dist_cold[:nb])
    affected_frac = float(jnp.mean(aff.astype(jnp.float32)))
    dist_wd, _, okd = asrc.reduced_all_sources(
        dests, runner_down, out, fwd_metric, fwd_up_down, fwd_ov,
        init_dist=init_down, maps=maps,
    )
    assert bool(okd)
    # exactness: warm-down fixed point == the cold downed product
    assert bool(jnp.all(dist_wd == dist_before))

    # relax-only sweep counts (reporting + the bw traffic model): the
    # timed path is progressive and never sees a fixed count
    def _probe_sweeps(rnr, ladder, dist0=None):
        for s in ladder:
            _, _, okp = rnr.run_once(
                dests, s, want_dag=False, raw_u16=True, transpose=False,
                dist0=dist0,
            )
            if bool(okp):
                return s
        return None

    ladder = (1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64)
    cold_sweeps = _probe_sweeps(runner, ladder[3:])
    warm_sweeps = _probe_sweeps(runner, ladder, dist0=dist_before)
    cold_down_sweeps = _probe_sweeps(runner_down, ladder[3:])
    warm_down_sweeps = _probe_sweeps(runner_down, ladder, dist0=init_down)

    # timing: distinct pre-staged (dests, init) pairs per rep (transport
    # replay guard); init columns roll WITH the dest roll so each warm
    # rep is the same question under a permuted dest order
    # reps+warmup+1 distinct pairs per timing fn: a wrapped cycle would
    # re-dispatch byte-identical inputs inside the timed window (replay
    # guard degeneracy)
    staged = [
        (
            np.roll(dests, i),
            jnp.roll(dist_before, i, axis=1),
            jnp.roll(init_down, i, axis=1),
        )
        for i in range(1, 9)
    ]
    jax.block_until_ready([s[1] for s in staged] + [s[2] for s in staged])
    rep = [0]

    def _run(rnr, up_mask, init_col):
        d = staged[rep[0] % len(staged)]
        init = None if init_col is None else d[init_col]
        rep[0] += 1
        dist, bm, ok = asrc.reduced_all_sources(
            d[0], rnr, out, fwd_metric, up_mask, fwd_ov,
            init_dist=init, maps=maps,
        )
        jax.block_until_ready((dist, bm))
        return ok

    run_warm = lambda: _run(runner, fwd_up, 1)           # noqa: E731
    run_cold = lambda: _run(runner, fwd_up, None)        # noqa: E731
    run_warm_down = lambda: _run(runner_down, fwd_up_down, 2)  # noqa: E731
    run_cold_down = lambda: _run(runner_down, fwd_up_down, None)  # noqa: E731

    warm_times = _time_device(run_warm, reps=5, warmup=1)
    assert bool(run_warm())
    cold_times = _time_device(run_cold, reps=5, warmup=1)
    assert bool(run_cold())
    warm_down_times = _time_device(run_warm_down, reps=5, warmup=1)
    assert bool(run_warm_down())
    cold_down_times = _time_device(run_cold_down, reps=5, warmup=1)
    assert bool(run_cold_down())
    itemsize = 2 if small else 4
    dist_bytes = n * n_prefixes * itemsize
    bytes_cold = (
        dist_bytes * (2 * (cold_sweeps or 0) + 1)
        + n * n_prefixes * out.n_words * 4
    )
    return _attach_bw(
        {
            "topology": topo.name,
            "n_nodes": n,
            "n_prefix_destinations": n_prefixes,
            "scenario": "ring link 0-1 flap: DOWN (worsening) + recovery",
            "warm_sweeps": warm_sweeps,
            "cold_sweeps": cold_sweeps,
            "warm_ms_min": round(min(warm_times), 1),
            "warm_ms_all": [round(t, 1) for t in warm_times],
            "cold_ms_min": round(min(cold_times), 1),
            "cold_ms_all": [round(t, 1) for t in cold_times],
            "warm_down_sweeps": warm_down_sweeps,
            "cold_down_sweeps": cold_down_sweeps,
            "warm_down_ms_min": round(min(warm_down_times), 1),
            "warm_down_ms_all": [round(t, 1) for t in warm_down_times],
            "cold_down_ms_min": round(min(cold_down_times), 1),
            "cold_down_ms_all": [round(t, 1) for t in cold_down_times],
            "affected_frac": round(affected_frac, 6),
            "note": (
                "round-6 warm starts, BOTH directions: improvement-only "
                "changes seed the relax from the previous product "
                "(upper-bound init); link-DOWN/worsening changes re-init "
                "only the certified affected set to INF and keep the "
                "rest (ops.banded.affected_mask).  Warm == cold "
                "asserted bit-exact above before timing, each direction."
            ),
        },
        bytes_cold if cold_sweeps else None,
        min(cold_times),
    )


def bench_flap_storm_wan100k(
    topo,
    n_prefixes: int = 1024,
    events: int = 1000,
    chunks: int = 4,
    seed: int = 7,
) -> dict:
    """Incremental delta dataflow under a seeded 1k-event flap storm
    (round-8 tentpole).  Four high-metric (backup-grade) +1 ring links
    flap between their base metric and 90; each chunk of 250 coalesced
    events becomes ONE frontier certification + ONE frontier-bucketed
    relax (ops.delta) against the resident product — never a full
    restage.  Headline: events_per_dispatch, ms_per_event, and
    delta_work_ratio (delta relax sweeps*columns vs the full-width cold
    product's), with every intermediate product asserted bit-exact
    against a cold host-oracle rebuild of that chunk's topology state.

    The flappy links are HIGH-metric on purpose: a live low-metric edge
    is the SPT parent of its endpoint for ~1/degree of ALL destination
    columns (probed: 822/1024 here), so storms on primary links
    correctly overflow the frontier bound and take the bit-exact full
    fallback; backup links at the metric ceiling are tight almost
    nowhere (probed: 29/1024 for all four worsened at once), which is
    the regime the delta rung turns into ~P/32-width work."""
    import jax
    import jax.numpy as jnp

    from benchmarks.synthetic import reversed_topology
    from openr_tpu.device.engine import DeviceResidencyEngine
    from openr_tpu.ops import allsources as asrc
    from openr_tpu.ops import delta as dops
    from openr_tpu.ops.banded import SpfRunner

    n = topo.n_nodes
    e = topo.n_edges
    rev = reversed_topology(topo)
    rng = np.random.default_rng(seed)
    dests = np.sort(
        rng.choice(n, size=n_prefixes, replace=False).astype(np.int32)
    )
    out = asrc.build_out_ell(topo.edge_src, topo.edge_dst, topo.n_edges, n)
    runner = rev.runner
    maps = asrc.build_epilogue_maps(runner.bg, out)
    fwd_up = jnp.asarray(topo.edge_up)
    fwd_ov = jnp.asarray(topo.node_overloaded)

    # flappy set: 4 spread +1 ring directed edges already at the metric
    # ceiling (10) — operationally, flap storms live on backup links
    fsrc, fdst, fmet = topo.edge_src[:e], topo.edge_dst[:e], topo.edge_metric[:e]
    ring10 = np.flatnonzero((fdst == (fsrc + 1) % n) & (fmet == 10))
    flappy = [int(ring10[i * len(ring10) // 4]) for i in range(4)]
    rsrc, rdst = rev.edge_src[:e], rev.edge_dst[:e]
    rev_eid = {}
    for fe in flappy:
        m = np.flatnonzero((rsrc == fdst[fe]) & (rdst == fsrc[fe]))
        assert len(m) == 1
        rev_eid[fe] = int(m[0])

    bg = runner.bg
    re_ = np.asarray(bg.resid_eid)
    be = np.asarray(bg.band_eid)
    _, _, _, r_up, r_ov = runner.call_arrays()

    # initial (pristine) cold product: the one-and-only full upload
    dist, bitmap, ok = asrc.reduced_all_sources(
        dests, runner, out, jnp.asarray(topo.edge_metric), fwd_up, fwd_ov,
        maps=maps,
    )
    jax.block_until_ready((dist, bitmap))
    assert bool(ok)
    small = dist.dtype == jnp.uint16
    dist0_h = np.asarray(dist)
    bm0_h = np.asarray(bitmap)
    engine = DeviceResidencyEngine()
    engine.delta_register(dist.nbytes + bitmap.nbytes)

    # denominator of delta_work_ratio: sweeps the full-width cold
    # product needs (probe the runner's ladder once, pristine state)
    cold_sweeps = None
    for s in (8, 12, 16, 24, 32, 48):
        _, _, okp = runner.run_once(
            dests, s, want_dag=False, raw_u16=True, transpose=False
        )
        if bool(okp):
            cold_sweeps = s
            break
    assert cold_sweeps is not None

    # seeded storm event stream, replayed identically by every pass
    ev_rng = np.random.default_rng(seed + 1)
    per_chunk = events // chunks
    chunk_targets = []
    metric_now = {fe: int(fmet[fe]) for fe in flappy}
    for _c in range(chunks):
        for _ in range(per_chunk):
            fe = flappy[int(ev_rng.integers(len(flappy)))]
            metric_now[fe] = (
                90 if int(ev_rng.integers(2)) else int(fmet[fe])
            )
        chunk_targets.append(dict(metric_now))

    def run_storm(dist, bitmap, col_roll, verify):
        """One full replay of the storm against (donated) dist/bitmap.
        Returns (dist, bitmap, per-chunk stats, per-chunk ms)."""
        r_met = np.asarray(rev.edge_metric).copy()
        f_met = np.asarray(topo.edge_metric).copy()
        d_roll = np.roll(dests, col_roll)
        stats, times = [], []
        for c in range(chunks):
            r_new, f_new = r_met.copy(), f_met.copy()
            for fe, m in chunk_targets[c].items():
                r_new[rev_eid[fe]] = m
                f_new[fe] = m
            worse = np.flatnonzero(r_new > r_met)
            better = np.flatnonzero(r_new < r_met)
            w_resid = (re_ >= 0) & np.isin(re_, worse)
            w_band = (be >= 0) & np.isin(be, worse)
            i_resid = (re_ >= 0) & np.isin(re_, better)
            i_band = (be >= 0) & np.isin(be, better)
            t0 = time.perf_counter()
            aff, col_mask, done = engine.delta_dispatch(
                "frontier",
                dops.delta_frontier,
                dist,
                bg,
                r_up,
                jnp.asarray(r_met),
                r_ov,
                jnp.asarray(w_resid),
                jnp.asarray(w_band),
                bg,
                r_up,
                jnp.asarray(r_new),
                r_ov,
                jnp.asarray(i_resid),
                jnp.asarray(i_band),
                small_dist=bool(small),
                max_iters=128,
            )
            done_h, col_mask_h = jax.device_get((done, col_mask))
            assert bool(done_h), "frontier must certify its fixpoint"
            col_idx = np.flatnonzero(col_mask_h).astype(np.int32)
            blocks_h, pb = 0, 0
            if len(col_idx):
                pb = engine.delta_bucket(len(col_idx), n_prefixes)
                assert pb is not None, (
                    f"chunk {c}: frontier {len(col_idx)} cols overflowed "
                    "the bucket ladder — the storm design regressed"
                )
                col_pad = np.full(pb, col_idx[0], dtype=np.int32)
                col_pad[: len(col_idx)] = col_idx
                dist, bitmap, conv, blocks = engine.delta_dispatch(
                    "relax",
                    dops.delta_relax,
                    dist,
                    bitmap,
                    aff,
                    jnp.asarray(col_pad),
                    jnp.asarray(d_roll),
                    bg,
                    r_up,
                    jnp.asarray(r_new),
                    r_ov,
                    maps.resid_slot,
                    maps.band_slot,
                    depth=runner.depth,
                    resid_rounds=runner.resid_rounds,
                    small_dist=bool(small),
                    chord_mode=runner.chord_mode,
                    n_words=out.n_words,
                    bucket_key=("relax", (n, e, n_prefixes), pb,
                                out.n_words, bool(small)),
                )
                conv_h, blocks_h = jax.device_get((conv, blocks))
                assert bool(conv_h), "delta relax must converge on device"
                blocks_h = int(blocks_h)
            jax.block_until_ready(dist)
            times.append((time.perf_counter() - t0) * 1e3)
            stats.append({"cols": int(len(col_idx)), "pb": int(pb),
                          "blocks": blocks_h})
            r_met, f_met = r_new, f_new
            if verify:
                oracle_runner = SpfRunner(
                    rev.ell, rev.banded, rev.edge_src, rev.edge_dst,
                    r_met, rev.edge_up, rev.node_overloaded, rev.n_edges,
                )
                oracle_runner.stage()
                dist_o, bm_o, ok_o = asrc.reduced_all_sources(
                    d_roll, oracle_runner, out, jnp.asarray(f_met),
                    fwd_up, fwd_ov, maps=maps,
                )
                assert bool(ok_o)
                assert bool(jnp.all(dist == dist_o)), (
                    f"chunk {c}: delta product diverged from host oracle"
                )
                assert bool(jnp.all(bitmap == bm_o)), (
                    f"chunk {c}: delta bitmap diverged from host oracle"
                )
                del dist_o, bm_o, oracle_runner
        return dist, bitmap, stats, times

    # pass A: live storm, every intermediate product verified bit-exact
    # against a cold oracle of that chunk's topology (compiles included
    # in its chunk times)
    dist, bitmap, stats, times_a = run_storm(dist, bitmap, 0, verify=True)
    # pass B: warm replay from a rolled pristine product (distinct bytes
    # per dispatch; same programs) — the steady-state timing
    dist_b = jax.device_put(np.roll(dist0_h, 1, axis=1))
    bm_b = jax.device_put(np.roll(bm0_h, 1, axis=1))
    jax.block_until_ready((dist_b, bm_b))
    dist_b, bm_b, _, times_b = run_storm(dist_b, bm_b, 1, verify=False)
    del dist_b, bm_b

    dispatches = engine.counters["device.engine.delta_dispatches"] // 2
    assert dispatches <= 2 * chunks, "storm exceeded its dispatch budget"
    assert engine.counters["device.engine.full_restages"] == 1
    assert engine.counters["device.engine.delta_overflow_fallbacks"] == 0
    delta_sweep_cols = sum(s["blocks"] * 4 * s["pb"] for s in stats)
    work_ratio = delta_sweep_cols / (chunks * cold_sweeps * n_prefixes)
    assert work_ratio < 0.05, f"delta_work_ratio regressed: {work_ratio}"
    storm_ms = min(sum(times_a), sum(times_b))
    # traffic model for the storm's relax work: each relax block makes 4
    # sweeps over the pb-column slab (read+write), each chunk writes the
    # slab's bitmap once and the frontier pass reads the full dist once
    itemsize = 2 if small else 4
    bytes_storm = (
        2 * delta_sweep_cols * n * itemsize
        + sum(s["pb"] for s in stats) * n * out.n_words * 4
        + chunks * n * n_prefixes * itemsize
    )
    return _attach_bw({
        "topology": topo.name,
        "n_nodes": n,
        "n_prefix_destinations": n_prefixes,
        "events": events,
        "chunks": chunks,
        "scenario": (
            "seeded 1k-event flap storm on 4 backup (metric-10) ring "
            "links, coalesced into one delta chain per 250-event chunk"
        ),
        "events_per_dispatch": round(events / dispatches, 1),
        "ms_per_event": round(storm_ms / events, 3),
        "delta_work_ratio": round(work_ratio, 5),
        "storm_ms_live": [round(t, 1) for t in times_a],
        "storm_ms_warm": [round(t, 1) for t in times_b],
        "frontier_cols": [s["cols"] for s in stats],
        "bucket_pb": [s["pb"] for s in stats],
        "relax_blocks": [s["blocks"] for s in stats],
        "cold_sweeps": cold_sweeps,
        "delta_dispatches": dispatches,
        "full_restages": engine.counters["device.engine.full_restages"],
        "overflow_fallbacks": engine.counters[
            "device.engine.delta_overflow_fallbacks"
        ],
        "note": (
            "every chunk's product asserted bit-exact against a cold "
            "host-oracle rebuild of that chunk's topology before the "
            "next chunk ran; full_restages stays 1 (the initial upload) "
            "and delta_work_ratio counts relax sweeps*columns vs the "
            "full-width cold product's.  ms_per_event is min over the "
            "live pass and a rolled-product warm replay (distinct bytes "
            "per dispatch, replay-guard discipline)."
        ),
    }, bytes_storm, storm_ms)


def bench_ocs_rewire_wan100k(
    n: int = 100_000,
    rounds: int = 16,
    swaps_per_round: int = 4,
    seed: int = 13,
) -> dict:
    """OCS reconfiguration economics at WAN scale (round-11 tentpole):
    rolling optical-circuit swaps against ONE resident graph through the
    CSR slot freelist + engine rewire rung.  The headline is the byte
    asymmetry — a bounded rewire stages a handful of masked-write rows
    (KBs) where a restage re-uploads the whole edge set (MBs) — plus
    rewire_us per dispatch.  full_restages must stay 1 (the initial
    upload): every circuit swap rides the rewire rung or the row fails.

    The topology mirrors OcsController's chorded WAN ring (ring +-1/+-2
    under deterministic asymmetric metrics, one chord per node) but at
    wan100k node count, driven through the real LinkState -> CsrTopology
    refresh path; only the swap endpoints' adjacency databases are
    re-pushed per round (LinkState preserves Link identity for untouched
    adjacencies).  Chord picks are rejection-sampled — the controller's
    exhaustive candidate scan is O(n^2) and only meant for test scale.

    Honors OPENR_BENCH_BUDGET_S: sheds remaining rounds (and the final
    cold bit-exact sweep) when the global wall budget runs low, and says
    so in the row."""
    import random

    from openr_tpu.chaos.ocs import _CHORD_DEG_CAP, OcsController
    from openr_tpu.decision.csr import CsrTopology
    from openr_tpu.device.engine import DeviceResidencyEngine

    ctl = OcsController(seed=seed, n=n, rounds=rounds, fault_round=-1)
    rng = random.Random(seed)
    chords = ctl._initial_chords()
    deg = {i: 1 for i in range(n)}  # perfect matching: one chord each

    t0 = time.perf_counter()
    ls = ctl._build_ls(chords, {})
    ls_build_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    csr = CsrTopology.from_link_state(ls)
    csr_build_s = time.perf_counter() - t0

    engine = DeviceResidencyEngine()
    t0 = time.perf_counter()
    engine.sync(csr)  # the one legitimate full staging
    stage_s = time.perf_counter() - t0
    restage_bytes = engine.get_counters()["device.engine.bytes_staged"]

    def pick_chord():
        # rejection-sample a fresh capacity-bounded non-ring chord
        while True:
            a, b = rng.randrange(n), rng.randrange(n)
            if a == b:
                continue
            a, b = min(a, b), max(a, b)
            d = b - a
            if d in (1, 2) or n - d in (1, 2):
                continue  # ring +-1/+-2 edge
            if (a, b) in chords:
                continue
            if (
                deg.get(a, 0) >= _CHORD_DEG_CAP
                or deg.get(b, 0) >= _CHORD_DEG_CAP
            ):
                continue
            return (a, b)

    def push_nodes(touched):
        for i in sorted(touched):
            ls.update_adjacency_database(ctl._node_db(i, chords, {}))

    shed_note = None
    round_ms = []
    done_rounds = 0
    for _r in range(rounds):
        if _budget_left() < 120:
            shed_note = (
                f"budget: shed {rounds - done_rounds} of {rounds} rounds"
            )
            break
        touched = set()
        for _ in range(swaps_per_round):
            victim = rng.choice(sorted(chords))
            chords.discard(victim)
            for v in victim:
                deg[v] -= 1
            fresh = pick_chord()
            chords.add(fresh)
            for v in fresh:
                deg[v] += 1
            touched.update(victim)
            touched.update(fresh)
        push_nodes(touched)
        t0 = time.perf_counter()
        rewired = csr.refresh(ls)
        assert rewired, "bounded swap fell off the rewire path"
        engine.sync(csr)
        round_ms.append((time.perf_counter() - t0) * 1e3)
        done_rounds += 1

    c = engine.get_counters()
    assert c["device.engine.full_restages"] == 1, c
    assert c["device.engine.rewire_fallbacks"] == 0, c
    assert c["device.engine.rewire_dispatches"] == done_rounds, c
    rewire_bytes = c["device.engine.rewire_bytes_staged"]
    per_rewire = rewire_bytes / max(done_rounds, 1)

    # acceptance spot-check: the incrementally-rewired resident must be
    # bit-exact vs a cold rebuild+restage of the final topology
    exact = None
    if _budget_left() > 180 and done_rounds:
        names = ls.node_names
        sources = [names[(seed * 977 + k * 40503) % n] for k in range(3)]
        got = engine.spf_results(csr, sources)
        cold = DeviceResidencyEngine()
        expect = cold.spf_results(CsrTopology.from_link_state(ls), sources)

        def view(result):
            return {
                k: (v.metric, frozenset(v.next_hops))
                for k, v in result.items()
            }

        exact = all(view(got[s]) == view(expect[s]) for s in sources)
        assert exact, "rewired resident diverged from cold rebuild"
    else:
        shed_note = (shed_note or "") + "; budget: skipped cold sweep"

    # utilization lens on the rewire rung itself: H2D bytes the masked
    # writes staged over the engine-side staging wall (rewire_us)
    rewire_ms = c["device.engine.rewire_us"] / 1e3
    return _attach_bw({
        "topology": f"wan{n // 1000}k-ocs-ring",
        "n_nodes": n,
        "rounds": done_rounds,
        "links_swapped": done_rounds * swaps_per_round,
        "scenario": (
            f"rolling OCS circuit swaps, {swaps_per_round} chords "
            "retired+programmed per round, one rewire dispatch per round"
        ),
        "rewire_dispatches": c["device.engine.rewire_dispatches"],
        "rewire_slots": c["device.engine.rewire_slots"],
        "rewire_rows": c["device.engine.rewire_rows"],
        "bytes_per_rewire": round(per_rewire),
        "full_restage_bytes": restage_bytes,
        "restage_vs_rewire_bytes": (
            round(restage_bytes / per_rewire, 1) if per_rewire else None
        ),
        "rewire_us_per_dispatch": round(
            c["device.engine.rewire_us"] / max(done_rounds, 1), 1
        ),
        "round_ms_p50": round(_pctl(round_ms, 50), 2) if round_ms else None,
        "round_ms_p95": round(_pctl(round_ms, 95), 2) if round_ms else None,
        "full_restages": c["device.engine.full_restages"],
        "rewire_fallbacks": c["device.engine.rewire_fallbacks"],
        "initial_stage_s": round(stage_s, 2),
        "ls_build_s": round(ls_build_s, 1),
        "csr_build_s": round(csr_build_s, 1),
        "cold_sweep_exact": exact,
        "note": (
            "restage_vs_rewire_bytes is the headline: H2D bytes a full "
            "re-upload costs per byte the masked-write rewire rung "
            "stages for one bounded circuit swap.  round_ms includes "
            "the host-side LinkState->CSR refresh (identity diff + slot "
            "freelist patch), not just device time; rewire_us is the "
            "engine-side staging alone (also the achieved_bw_frac wall)."
            + (f"  {shed_note}" if shed_note else "")
        ),
    }, rewire_bytes, rewire_ms)


def bench_pallas_vs_xla(reps: int = 5) -> dict:
    """Round-14 Pallas rung: both hand-tiled kernels (fused
    verify+bitmap epilogue, blocked rank-B outer update) against XLA
    twins of the same fused math on identical inputs, with the roofline
    column.  Bytes prefer the compiled program's own cost_analysis()
    over the traffic model (bytes_source records which); peak_bw_source
    records the roofline denominator's provenance so rows compare
    across machines.  Off-TPU the kernels run in the interpreter, whose
    wall measures the interpreter loop, not the hardware — `mode`
    disambiguates."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax import lax

    from benchmarks.util import achieved_bw_frac, peak_bw_source
    from openr_tpu.ops import pallas_kernels as pk
    import openr_tpu.parallel.blocked as blk

    mode = pk.pallas_mode()
    if mode == "off":
        # the bench row forces the kernels on; policy-off machines still
        # get a comparison, in interpreter mode
        mode = "compiled" if jax.default_backend() == "tpu" else "interpret"
    interp = mode == "interpret"
    rng = np.random.default_rng(14)

    def _cost_bytes(lowerable, *args, **kwargs):
        """cost_analysis 'bytes accessed' of the compiled program, or
        None when the backend/version doesn't expose it."""
        try:
            ca = lowerable.lower(*args, **kwargs).compile().cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            v = ca.get("bytes accessed") if hasattr(ca, "get") else None
            return float(v) if v and v > 0 else None
        except Exception:
            return None

    # -- kernel 1: fused verify+bitmap epilogue ---------------------------
    n, p, g, n_words = 1024, 512, 8, 1
    d_h = rng.integers(0, 2000, size=(n, p)).astype(np.uint16)
    d_h[rng.random((n, p)) < 0.1] = pk._INF16  # unreached entries
    d = jnp.asarray(d_h)
    idx = jnp.asarray(rng.integers(0, n, size=(g, n)), dtype=jnp.int32)
    w = jnp.asarray(rng.integers(1, 100, size=(g, n)), dtype=jnp.int32)
    ov = jnp.asarray(rng.random((g, n)) < 0.05, dtype=jnp.int32)
    slot = jnp.asarray(
        np.where(
            rng.random((g, n)) < 0.05,
            -1,
            rng.integers(0, 32 * n_words, size=(g, n)),
        ),
        dtype=jnp.int32,
    )

    @jax.jit
    def epi_xla(d, idx, w, ov, slot):
        # generic-lax twin of the fused epilogue: same math, no tiling
        inf = jnp.asarray(pk._INF16, d.dtype)
        fin = d < inf
        du = jnp.take(d, idx, axis=0)  # [G, N, P]
        allow = (w < pk._WBIG16)[:, :, None] & ((ov == 0)[:, :, None] | (du == 0))
        cand = jnp.where(
            allow & (du < inf), du + w.astype(d.dtype)[:, :, None], inf
        )
        on = fin[None] & (cand == d[None])
        bits = jnp.where(
            slot >= 0,
            jnp.uint32(1) << jnp.maximum(slot, 0).astype(jnp.uint32) % 32,
            jnp.uint32(0),
        )
        contrib = jnp.where(on, bits[:, :, None], jnp.uint32(0))
        bitmap = lax.reduce(
            contrib, np.uint32(0), lax.bitwise_or, dimensions=(0,)
        )
        vmin = jnp.minimum(d, cand.min(axis=0))
        return bitmap, vmin

    epi_pallas = functools.partial(
        pk.fused_epilogue_pallas, n_groups=g, n_words=n_words,
        interpret=interp,
    )
    epi_pallas_ms = min(_time_device(
        lambda: epi_pallas(d, idx, w, ov, slot), reps=reps, warmup=1
    ))
    epi_xla_ms = min(_time_device(
        lambda: epi_xla(d, idx, w, ov, slot), reps=reps, warmup=1
    ))
    # bit-exactness spot check rides along (tier-1 owns the real sweep)
    bm_p, vmin_p = epi_pallas(d, idx, w, ov, slot)
    bm_x, vmin_x = epi_xla(d, idx, w, ov, slot)
    assert bool(jnp.all(bm_p[0] == bm_x)) and bool(jnp.all(vmin_p == vmin_x))
    # traffic model: d read + vmin written per tile pass, bitmap written,
    # the four group tables re-read per 128-wide column tile
    epi_tm = (
        2 * n * p * d_h.itemsize
        + n_words * n * p * 4
        + (p // 128) * 4 * g * n * 4
    )
    epi_bytes, epi_src = epi_tm, "traffic_model"
    if not interp:
        cb = _cost_bytes(
            pk.fused_epilogue_pallas, d, idx, w, ov, slot,
            n_groups=g, n_words=n_words, interpret=False,
        )
        if cb:
            epi_bytes, epi_src = cb, "cost_analysis"
    epi_xla_bytes = _cost_bytes(epi_xla, d, idx, w, ov, slot) or epi_tm

    # -- kernel 2: blocked rank-B outer update ----------------------------
    s, t, b = 1, 8, 128
    np_ = t * b
    k = 3
    dist_h = rng.integers(0, 1 << 20, size=(s, t, b, t, b)).astype(np.uint32)
    row_p = jnp.asarray(
        rng.integers(0, 1 << 20, size=(s, b, t, b)).astype(np.uint32)
    )
    col_p = jnp.asarray(
        rng.integers(0, 1 << 20, size=(s, t, b, b)).astype(np.uint32)
    )
    ov_n = jnp.asarray(rng.random(np_) < 0.05)
    mesh = blk.make_blocked_mesh(jax.devices()[:1])
    xla_outer = jax.jit(
        lambda dd, rp, cp, o, kk: blk.blocked_outer(
            dd, rp, cp, o, kk, mesh=mesh
        )
    )
    # blocked_outer_pallas donates dist: rotate pre-staged copies so no
    # rep re-submits a deleted buffer (and no rep dispatches twice on
    # identical bytes — replay-guard discipline)
    staged = [jax.device_put(dist_h) for _ in range(reps + 2)]
    jax.block_until_ready(staged)
    it = iter(staged)
    blk_pallas_ms = min(_time_device(
        lambda: pk.blocked_outer_pallas(
            next(it), row_p, col_p, ov_n, k, interpret=interp
        ),
        reps=reps, warmup=1,
    ))
    dist0 = jax.device_put(dist_h)
    blk_xla_ms = min(_time_device(
        lambda: xla_outer(dist0, row_p, col_p, ov_n, k), reps=reps, warmup=1
    ))
    out_p = pk.blocked_outer_pallas(
        jax.device_put(dist_h), row_p, col_p, ov_n, k, interpret=interp
    )
    assert bool(jnp.all(out_p == xla_outer(dist0, row_p, col_p, ov_n, k)))
    # traffic model: dist read+written once; each panel re-read per tile
    # row/column of the grid
    blk_tm = 2 * s * np_ * np_ * 4 + 2 * t * s * np_ * b * 4
    blk_bytes, blk_src = blk_tm, "traffic_model"
    if not interp:
        cb = _cost_bytes(
            pk.blocked_outer_pallas,
            jax.ShapeDtypeStruct(dist_h.shape, jnp.uint32),
            row_p, col_p, ov_n, k, interpret=False,
        )
        if cb:
            blk_bytes, blk_src = cb, "cost_analysis"
    blk_xla_bytes = _cost_bytes(
        xla_outer, jax.ShapeDtypeStruct(dist_h.shape, jnp.uint32),
        row_p, col_p, ov_n, k,
    ) or blk_tm

    row = {
        "scenario": (
            "hand-tiled Pallas kernels vs generic-XLA twins of the same "
            "fused math, identical inputs, bit-exactness asserted"
        ),
        "mode": mode,
        "backend": jax.default_backend(),
        "peak_bw_source": peak_bw_source(),
        "fused_epilogue": {
            "n_nodes": n, "n_prefixes": p, "groups": g,
            "pallas_ms": round(epi_pallas_ms, 3),
            "xla_ms": round(epi_xla_ms, 3),
            "speedup_vs_xla": round(epi_xla_ms / epi_pallas_ms, 2),
            "bytes_moved": int(epi_bytes),
            "bytes_source": epi_src,
            "achieved_bw_frac": achieved_bw_frac(epi_bytes, epi_pallas_ms),
            "xla_achieved_bw_frac": achieved_bw_frac(
                epi_xla_bytes, epi_xla_ms
            ),
        },
        "blocked_outer": {
            "tiles": [s, t, b],
            "pallas_ms": round(blk_pallas_ms, 3),
            "xla_ms": round(blk_xla_ms, 3),
            "speedup_vs_xla": round(blk_xla_ms / blk_pallas_ms, 2),
            "bytes_moved": int(blk_bytes),
            "bytes_source": blk_src,
            "achieved_bw_frac": achieved_bw_frac(blk_bytes, blk_pallas_ms),
            "xla_achieved_bw_frac": achieved_bw_frac(
                blk_xla_bytes, blk_xla_ms
            ),
        },
        "note": (
            "per-kernel sub-rows; achieved_bw_frac under mode=interpret "
            "times the Pallas interpreter loop, not the hardware — only "
            "compiled-mode fractions are roofline statements (the slow-"
            "gated device test asserts those).  XLA twins materialize "
            "the [G,N,P] candidate tensor the fused kernel never writes."
        ),
    }
    # headline utilization columns for the uniform device-row surface
    return _attach_bw(row, epi_bytes, epi_pallas_ms)


def bench_ksp_dual_metric_wan100k(topo, n_dests: int = 8) -> dict:
    """BASELINE config #3: dual-metric (IGP + TE) KSP at 100k nodes.
    Round-5 formulation: base SPF, ON-DEVICE path trace, and the masked
    k=2 edge-disjoint re-run batch for BOTH cost planes run as ONE fused
    dispatch (ops.ksp.fused_ksp2_banded) — round 4's 4-dispatch chain
    with host traces between paid the flat transport fee per hop and
    lost 3.1x on wall.  The C++ baseline runs the same (1 + D) Dijkstras
    per plane sequentially (sampled + scaled like the other 100k rows)."""
    import jax

    from benchmarks import cpp_baseline
    from openr_tpu.ops.ksp import FusedKsp2Runner
    from openr_tpu.ops.protection import build_reverse_edge_ids

    e = topo.n_edges
    rng = np.random.default_rng(17)
    te_metric = topo.edge_metric.copy()
    te_metric[:e] = rng.integers(1, 101, size=e).astype(np.int32)
    dests = rng.choice(
        np.arange(1, topo.n_nodes), size=n_dests, replace=False
    ).astype(np.int32)
    runner = topo.runner
    planes = [topo.edge_metric, te_metric]
    rev = np.asarray(
        build_reverse_edge_ids(topo.edge_src[:e], topo.edge_dst[:e])
    )
    fk = FusedKsp2Runner(runner, topo.edge_dst, e, topo.n_nodes, rev, planes)

    # warmup: learn base + masked hints through the adaptive fused path
    res = fk.run(0, dests, adaptive=True)

    # parity BEFORE timing: k1 vs the C++ oracle; k2 vs a host Dijkstra
    # run under the device's own exclusions; excluded edges must form a
    # shortest path (sum of metrics == k1)
    for p, metric in enumerate(planes):
        r = res[p]
        _, cd = cpp_baseline.spf_all_sources(
            topo.n_nodes,
            topo.edge_src[:e],
            topo.edge_dst[:e],
            metric[:e],
            topo.edge_up[:e],
            topo.node_overloaded[: topo.n_nodes],
            np.zeros(1, np.int32),
            want_dist=True,
        )
        np.testing.assert_array_equal(np.asarray(r.k1), cd[0, dests])
        excl = np.asarray(r.excl)
        for i in range(0, n_dests, max(1, n_dests // 2)):
            ee = excl[i]
            ee = ee[ee < e]
            assert metric[ee].sum() == cd[0, dests[i]], "trace not shortest"
            up = topo.edge_up.copy()
            up[ee] = False
            rv = rev[ee]
            up[rv[rv >= 0]] = False
            _, cd2 = cpp_baseline.spf_all_sources(
                topo.n_nodes,
                topo.edge_src[:e],
                topo.edge_dst[:e],
                metric[:e],
                up[:e],
                topo.node_overloaded[: topo.n_nodes],
                np.zeros(1, np.int32),
                want_dist=True,
            )
            assert int(np.asarray(r.k2)[i]) == int(cd2[0, dests[i]])

    def run_fused(rep: int) -> float:
        # replay guard: distinct destination order per rep
        t0 = time.perf_counter()
        out = fk.run(0, np.roll(dests, rep + 1), adaptive=False)
        jax.block_until_ready([r.k2 for r in out])
        elapsed = (time.perf_counter() - t0) * 1e3
        for r in out:
            assert bool(r.ok_base) and bool(r.ok_masked) and bool(r.trace_ok)
        return elapsed

    times = []
    for i in range(3):
        if i == 2:
            time.sleep(WINDOW_SPLIT_S)
        times.append(run_fused(i))

    # C++ baseline: 1 base + 2 sampled masked Dijkstras per plane, masked
    # runs scaled to D
    cpp_ms = 0.0
    for metric in (topo.edge_metric, te_metric):
        secs, cdist = cpp_baseline.spf_all_sources(
            topo.n_nodes,
            topo.edge_src[:e],
            topo.edge_dst[:e],
            metric[:e],
            topo.edge_up[:e],
            topo.node_overloaded[: topo.n_nodes],
            np.zeros(1, dtype=np.int32),
            want_dist=True,
        )
        cpp_ms += secs * 1e3
        masked_secs = 0.0
        for _d in dests[:2]:
            # per-destination exclusions do not change Dijkstra's cost
            # profile; the sampled re-runs time the same full SPF the
            # reference's getKthPaths would re-run per destination
            secs2, _ = cpp_baseline.spf_all_sources(
                topo.n_nodes,
                topo.edge_src[:e],
                topo.edge_dst[:e],
                metric[:e],
                topo.edge_up[:e],
                topo.node_overloaded[: topo.n_nodes],
                np.asarray([0], np.int32),
            )
            masked_secs += secs2
        cpp_ms += masked_secs * 1e3 * (n_dests / 2)
    return {
        "topology": topo.name,
        "n_nodes": topo.n_nodes,
        "planes": 2,
        "ksp_destinations": n_dests,
        "device_ms_min": round(min(times), 3),
        "device_ms_all": [round(t, 1) for t in times],
        "cpp_baseline_ms": round(cpp_ms, 3),
        "cpp_scaled": True,
        "note": (
            "ONE fused dispatch for both planes: base SPF + on-device "
            "path trace + masked k=2 edge-disjoint batch "
            "(ops.ksp.fused_ksp2_banded); k1/k2 parity-checked against "
            "the C++ oracle under the device's own exclusions before "
            "timing"
        ),
    }


def bench_srlg_whatif(topo, n_variants: int, reps: int, cpp_sample: int) -> dict:
    """Config #4: batched SRLG what-if — n_variants single-link failure
    scenarios x 1 source on `topo`, ONE masked-ELL device call (the
    variant axis IS the batch axis).  The C++ baseline re-runs a full
    Dijkstra per scenario, which is what the reference would have to do
    (one Decision re-run per what-if, Decision.cpp:1866)."""
    from benchmarks import cpp_baseline
    from openr_tpu.ops import sssp as ops
    from openr_tpu.ops.protection import build_reverse_edge_ids

    e = topo.n_edges
    rng = np.random.default_rng(42)
    rev = np.asarray(
        build_reverse_edge_ids(topo.edge_src[:e], topo.edge_dst[:e])
    )
    fail = rng.integers(0, e, size=n_variants)
    mask = np.ones((n_variants, topo.edge_capacity), dtype=bool)
    rows = np.arange(n_variants)
    mask[rows, fail] = False
    rev_of_fail = rev[fail]
    valid = rev_of_fail >= 0
    mask[rows[valid], rev_of_fail[valid]] = False
    sources = np.zeros(n_variants, dtype=np.int32)  # router-view what-if

    import jax.numpy as _jnp

    runner = topo.runner
    # warmup learns the hint under the masked batch (distances only: the
    # what-if reachability analysis never reads the DAG)
    dist, _ = runner.forward(sources, extra_edge_mask=mask, want_dag=False)
    hint = runner.hint_masked

    # device-resident inputs for the timed runs: the scenario masks (tens
    # of MB at 10k variants) derive from topology state that already
    # lives on device in production — re-uploading them per dispatch
    # would time the tunnel's transfer path, not the what-if kernel
    mask_res = _jnp.asarray(mask)
    src_res = _jnp.asarray(sources)
    # replay guard with ONE dispatch per timed rep: pre-stage a few
    # distinct variant orders OUTSIDE the timed window (an in-window
    # roll would add a dispatch + a full-mask copy to every rep)
    n_staged = min(9, n_variants - 1)
    assert n_staged >= 2, "need at least 2 distinct staged masks"
    staged_masks = [
        _jnp.roll(mask_res, i, axis=0) for i in range(1, n_staged + 1)
    ]
    import jax as _jax

    _jax.block_until_ready(staged_masks)
    rep_counter = [0]

    def run():
        rep_counter[0] += 1
        return runner.run_once(
            src_res,
            hint,
            extra_edge_mask=staged_masks[rep_counter[0] % n_staged],
            want_dag=False,
        )

    # parity on a sample of variants vs C++ with the link removed
    for v in range(0, n_variants, max(1, n_variants // 4))[:4]:
        up = topo.edge_up.copy()
        up[fail[v]] = False
        if rev_of_fail[v] >= 0:
            up[rev_of_fail[v]] = False
        _, cdist = cpp_baseline.spf_all_sources(
            topo.n_nodes,
            topo.edge_src[:e],
            topo.edge_dst[:e],
            topo.edge_metric[:e],
            up[:e],
            topo.node_overloaded[: topo.n_nodes],
            np.zeros(1, dtype=np.int32),
            want_dist=True,
        )
        np.testing.assert_array_equal(dist[v, : topo.n_nodes], cdist[0])

    times = _time_device(run, reps)

    import jax
    import jax.numpy as jnp

    _, _, ok = run()
    assert bool(ok), "timed SRLG runs did not reach the fixed point"
    # reuse the already-resident device buffers — no second ~40MB upload
    mask_dev = mask_res
    src_dev = src_res

    def _amort_loop(runs):
        @jax.jit
        def loop():
            def body(i, acc):
                dist, _, _ = runner.run_once(
                    src_dev,
                    hint,
                    extra_edge_mask=jnp.roll(mask_dev, i, axis=0),
                    want_dag=False,
                )
                return acc + jnp.sum(dist)

            return jax.lax.fori_loop(0, runs, body, jnp.int32(0))

        return loop

    amortized = _time_amortized(_amort_loop, runs=3)

    # C++ baseline: one full SPF per scenario (sampled + scaled)
    sample = min(cpp_sample, n_variants)
    cpp_secs = 0.0
    for v in range(0, n_variants, n_variants // sample)[:sample]:
        up = topo.edge_up.copy()
        up[fail[v]] = False
        if rev_of_fail[v] >= 0:
            up[rev_of_fail[v]] = False
        secs, _ = cpp_baseline.spf_all_sources(
            topo.n_nodes,
            topo.edge_src[:e],
            topo.edge_dst[:e],
            topo.edge_metric[:e],
            up[:e],
            topo.node_overloaded[: topo.n_nodes],
            np.zeros(1, dtype=np.int32),
        )
        cpp_secs += secs
    scale = n_variants / sample
    return {
        "topology": topo.name,
        "n_variants": n_variants,
        "n_nodes": topo.n_nodes,
        "device_ms_min": round(min(times), 3),
        "device_ms_amortized": (
            round(amortized, 3) if amortized is not None else None
        ),
        "device_ms_all": [round(t, 2) for t in times],
        "cpp_baseline_ms": round(cpp_secs * 1e3 * scale, 3),
        "cpp_variants_measured": sample,
        "cpp_scaled": True,
    }


def bench_tilfa(topo, source: int, reps: int) -> dict:
    """Config #5: TI-LFA backup-path computation at scale — per out-edge
    post-convergence SPF (+ SP-DAG) for one protected node, one batched
    device call over the failure dimension."""
    from benchmarks import cpp_baseline
    from openr_tpu.ops import protection as prot

    e = topo.n_edges
    out_edges = np.where(topo.edge_src[:e] == source)[0].astype(np.int32)
    rev = np.asarray(
        prot.build_reverse_edge_ids(topo.edge_src[:e], topo.edge_dst[:e])
    )
    rev_full = np.full(topo.edge_capacity, -1, dtype=np.int32)
    rev_full[:e] = rev

    import jax.numpy as _jnp

    runner = topo.runner
    # transport-replay guard: every timed rep protects a DIFFERENT node
    # of the same out-degree (a genuinely distinct TI-LFA question of
    # identical cost), pre-staged device-resident so the timed window
    # holds exactly one dispatch.  Repeat-identical dispatches can be
    # served from a transport result cache, faking the wall number.
    degree = len(out_edges)
    deg_all = np.bincount(topo.edge_src[:e], minlength=topo.n_nodes)
    candidates = np.flatnonzero(deg_all == degree)
    # even 2 distinct staged questions defeat repeat-identical replay;
    # 16 keeps every rep distinct on rich topologies
    n_staged = min(16, len(candidates))
    assert n_staged >= 2, "too few equal-degree sources to stage"
    staged = []
    for cand in candidates[:n_staged]:
        oe = np.where(topo.edge_src[:e] == cand)[0].astype(np.int32)
        staged.append(
            (
                _jnp.asarray(
                    np.full(degree, cand, dtype=np.int32)
                ),
                _jnp.asarray(
                    prot.build_edge_failure_masks(
                        oe, rev_full, topo.edge_capacity
                    )
                ),
            )
        )
    survives = staged[0][1]
    src_rows = staged[0][0]

    # warmup: learn hint via the production protection API (runner path)
    dist, _ = prot.ti_lfa_backups(
        np.int32(source),
        out_edges,
        topo.edge_src,
        topo.edge_dst,
        topo.edge_metric,
        topo.edge_up,
        topo.node_overloaded,
        rev_full,
        max_degree=len(out_edges),
        runner=runner,
    )
    hint = runner.hint_masked

    rep_counter = [0]

    def run():
        rep_counter[0] += 1
        srcs_i, mask_i = staged[rep_counter[0] % len(staged)]
        return runner.run_once(srcs_i, hint, extra_edge_mask=mask_i)

    # parity: each row vs C++ with that edge pair down
    for d in range(min(2, len(out_edges))):
        up = topo.edge_up.copy()
        up[out_edges[d]] = False
        if rev[out_edges[d]] >= 0:
            up[rev[out_edges[d]]] = False
        _, cdist = cpp_baseline.spf_all_sources(
            topo.n_nodes,
            topo.edge_src[:e],
            topo.edge_dst[:e],
            topo.edge_metric[:e],
            up[:e],
            topo.node_overloaded[: topo.n_nodes],
            np.asarray([source], dtype=np.int32),
            want_dist=True,
        )
        np.testing.assert_array_equal(dist[d, : topo.n_nodes], cdist[0])

    # every staged candidate must converge at the source-learned hint
    # BEFORE timing: the timed reps cycle through them, and an
    # unconverged candidate would time cheaper, unfinished work
    for srcs_i, mask_i in staged:
        _, _, ok_i = runner.run_once(
            srcs_i, hint, extra_edge_mask=mask_i
        )
        assert bool(ok_i), "staged TI-LFA candidate missed the hint"

    times = _time_device(run, reps)

    import jax.numpy as jnp

    amortized = _time_amortized(
        _make_kernel_loop(
            lambda i: runner.run_once(
                src_rows,
                hint,
                extra_edge_mask=jnp.roll(survives, i, axis=0),
            )[:2]
        ),
        runs=3,
    )

    # C++ baseline: one full SPF per protected out-edge
    cpp_secs = 0.0
    for d in range(len(out_edges)):
        up = topo.edge_up.copy()
        up[out_edges[d]] = False
        if rev[out_edges[d]] >= 0:
            up[rev[out_edges[d]]] = False
        secs, _ = cpp_baseline.spf_all_sources(
            topo.n_nodes,
            topo.edge_src[:e],
            topo.edge_dst[:e],
            topo.edge_metric[:e],
            up[:e],
            topo.node_overloaded[: topo.n_nodes],
            np.asarray([source], dtype=np.int32),
        )
        cpp_secs += secs
    return {
        "topology": topo.name,
        "n_nodes": topo.n_nodes,
        "protected_out_edges": int(len(out_edges)),
        "device_ms_min": round(min(times), 3),
        "device_ms_amortized": (
            round(amortized, 3) if amortized is not None else None
        ),
        "device_ms_all": [round(t, 2) for t in times],
        "cpp_baseline_ms": round(cpp_secs * 1e3, 3),
        "cpp_scaled": False,
    }


def bench_decision_cold_start(
    n_side: int = 10, reps: int = 3, dbs=None, name: Optional[str] = None
) -> dict:
    """Decision-module cold start: initial adj+prefix publications pushed
    into a LIVE Decision event base -> debounce -> full route build ->
    DecisionRouteUpdate emitted (reference: BM_DecisionGridInitialUpdate,
    DecisionBenchmark.cpp:19-33, which measures the accumulated
    DECISION_DEBOUNCE -> ROUTE_UPDATE perf-event span).  With `dbs`,
    benchmarks an arbitrary topology (fabric rows, BM_DecisionFabric)."""
    from openr_tpu.decision.decision import Decision
    from openr_tpu.runtime.queue import ReplicateQueue
    from openr_tpu.serializer import dumps
    from openr_tpu.types import (
        PrefixDatabase,
        PrefixEntry,
        Publication,
        Value,
        adj_key,
        prefix_key,
    )
    from openr_tpu.utils.topo import grid_topology

    if dbs is None:
        dbs = grid_topology(n_side)
        name = name or f"grid{n_side * n_side}"
    n_nodes = len(dbs)
    kv = {}
    for i, db in enumerate(dbs):
        kv[adj_key(db.this_node_name)] = Value(
            version=1, originator_id=db.this_node_name, value=dumps(db)
        )
        pdb = PrefixDatabase(
            this_node_name=db.this_node_name,
            prefix_entries=[PrefixEntry(prefix=f"fc00:{i:x}::/96")],
        )
        kv[
            prefix_key(
                db.this_node_name, pdb.prefix_entries[0].prefix, "0"
            )
        ] = Value(version=1, originator_id=db.this_node_name, value=dumps(pdb))

    times = []
    for _ in range(reps):
        kvq: ReplicateQueue = ReplicateQueue()
        routeq: ReplicateQueue = ReplicateQueue()
        reader = routeq.get_reader()
        decision = Decision(
            dbs[0].this_node_name,
            kvq.get_reader(),
            None,
            routeq,
            debounce_min_s=0.001,
            debounce_max_s=0.005,
        )
        decision.run()
        try:
            t0 = time.perf_counter()
            kvq.push(Publication(key_vals=dict(kv), area="0"))
            update = reader.get(timeout=60)
            elapsed = (time.perf_counter() - t0) * 1e3
            # routes for every other node's prefix
            assert (
                len(update.unicast_routes_to_update) == n_nodes - 1
            ), len(update.unicast_routes_to_update)
            times.append(elapsed)
        finally:
            kvq.close()
            routeq.close()
            decision.stop()
            decision.wait_until_stopped(5)
    return {
        "topology": name or f"grid{n_nodes}",
        "n_nodes": n_nodes,
        "cold_start_ms_min": round(min(times), 3),
        "cold_start_ms_all": [round(t, 2) for t in times],
    }


def bench_incremental_prefix_updates(
    n_prefixes: int = 100,
    reps: int = 50,
    dbs=None,
    name: str = "grid100",
    own_node: str = "node-0-0",
) -> dict:
    """Per-prefix incremental route update latency (reference:
    BM_DecisionGridPrefixUpdates,
    openr/decision/tests/DecisionBenchmark.cpp:63-76): one advertised
    prefix changes -> only that route recomputes (the reference's
    incremental path, Decision.cpp:1903-1912).  Defaults to the
    100-node grid; `dbs` benchmarks the larger scale points (grid10000,
    fattree10k — r4 verdict bench-grid residue)."""
    from openr_tpu.decision import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.spf_solver import SpfSolver
    from openr_tpu.types import PrefixEntry, normalize_prefix
    from openr_tpu.utils.topo import grid_topology

    if dbs is None:
        dbs = grid_topology(10)  # 100 nodes
    ls = LinkState()
    for db in dbs:
        ls.update_adjacency_database(db)
    ps = PrefixState()
    # advertisers exclude the solver's own node: a self-originated best
    # entry correctly yields no route, which is not what this row measures
    nodes = [db.this_node_name for db in dbs if db.this_node_name != own_node]
    for i in range(n_prefixes):
        ps.update_prefix(
            nodes[i % len(nodes)], "0", PrefixEntry(prefix=f"fc00:{i:x}::/64")
        )
    solver = SpfSolver(own_node)
    solver.build_route_db({"0": ls}, ps)  # warm SPF memo

    times = []
    for r in range(reps):
        i = r % n_prefixes
        prefix = normalize_prefix(f"fc00:{i:x}::/64")
        node = nodes[(i + 7) % len(nodes)]  # re-home the prefix
        t0 = time.perf_counter()
        ps.update_prefix(node, "0", PrefixEntry(prefix=prefix))
        # incremental path: recompute just this prefix
        route = solver.create_route_for_prefix_or_get_static_route(
            {"0": ls}, ps, prefix
        )
        times.append((time.perf_counter() - t0) * 1e3)
        assert route is not None
    return {
        "topology": name,
        "n_nodes": len(dbs),
        "n_prefixes": n_prefixes,
        "per_prefix_ms_min": round(min(times), 4),
        "per_prefix_ms_all": [round(t, 3) for t in times],
    }


def bench_reconvergence(
    dbs,
    name: str,
    own_node: str,
    flap_node: str,
    n_prefixes: int = 128,
    host_reps: int = 8,
    device_reps: int = 20,
) -> dict:
    """End-to-end Decision reconvergence after an adjacency flap
    (reference: BM_DecisionGridAdjUpdates,
    openr/decision/tests/DecisionBenchmark.cpp:43-54): toggle one node's
    overload bit, then rebuild the full route DB through SpfSolver —
    host-Dijkstra backend vs device backend, identical outputs asserted."""
    from openr_tpu.decision import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.spf_solver import DeviceSpfBackend, SpfSolver
    from openr_tpu.types import PrefixEntry

    ls = LinkState()
    for db in dbs:
        ls.update_adjacency_database(db)
    ps = PrefixState()
    step = max(1, len(dbs) // n_prefixes)
    advertised = 0
    for i in range(0, len(dbs), step):
        node = dbs[i].this_node_name
        if node == own_node:
            continue
        ps.update_prefix(node, "0", PrefixEntry(prefix=f"::{i:x}:0/112"))
        advertised += 1

    flap_db = next(d for d in dbs if d.this_node_name == flap_node)

    def run(solver):
        flap_db.is_overloaded = not flap_db.is_overloaded
        ls.update_adjacency_database(flap_db)
        return solver.build_route_db({"0": ls}, ps)

    host = SpfSolver(own_node)
    device = SpfSolver(
        own_node, spf_backend=DeviceSpfBackend(min_device_nodes=64, min_device_sources=1)
    )
    # warm both (compile device kernels, prime caches) + assert parity
    rdb_h = run(host)
    rdb_h2 = run(host)
    rdb_d = run(device)
    rdb_d2 = run(device)
    assert rdb_d.unicast_routes == rdb_h.unicast_routes or (
        rdb_d.unicast_routes == rdb_h2.unicast_routes
    )

    def ms(solver, reps):
        out = []
        for _ in range(reps):
            t0 = time.perf_counter()
            run(solver)
            out.append((time.perf_counter() - t0) * 1e3)
        return out

    # >=20 device reps: the claim to retire is about the dispatch-latency
    # *distribution* (the shared tunnel's bimodal flat tax), so p50/p95
    # matter here, not just min
    host_times = ms(host, reps=host_reps)
    engine = getattr(device.spf, "engine", None)
    snap = dict(engine.get_counters()) if engine is not None else {}
    device_times = ms(device, reps=device_reps)
    engine_cols = _engine_attribution(
        engine, snap, min(host_times), device_reps
    )
    return {
        "topology": name,
        "advertised_prefixes": advertised,
        "host_ms_min": round(min(host_times), 3),
        "host_ms_p50": round(_pctl(host_times, 50), 3),
        "host_ms_all": [round(t, 2) for t in host_times],
        "device_ms_min": round(min(device_times), 3),
        "device_ms_p50": round(_pctl(device_times, 50), 3),
        "device_ms_p95": round(_pctl(device_times, 95), 3),
        "device_ms_all": [round(t, 2) for t in device_times],
        "device_vs_host": round(min(host_times) / min(device_times), 2),
        **engine_cols,
        "note": (
            "measures the FORCED device path (min_device_sources=1); the "
            "shipped default policy routes these small-batch flows to the "
            "host below the measured batch crossover "
            "(DeviceSpfBackend docstring)"
        ),
    }


def _engine_attribution(engine, snap, host_ms_min, reps) -> dict:
    """device.engine.* counter deltas over the timed device reps, folded
    into the row: how much of the device wall is engine time (staging +
    dispatch), what was staged, and whether updates stayed incremental."""
    if engine is None:
        return {}
    now = engine.get_counters()
    delta = {k: now[k] - snap.get(k, 0) for k in now}
    engine_ms = (
        delta["device.engine.stage_us"] + delta["device.engine.dispatch_us"]
    ) / 1e3 / max(reps, 1)
    return {
        "engine_vs_host": (
            round(host_ms_min / engine_ms, 2) if engine_ms else None
        ),
        "engine_ms_per_rep": round(engine_ms, 3),
        "bytes_staged_per_rep": delta["device.engine.bytes_staged"]
        // max(reps, 1),
        "engine_counters_delta": {
            k.removeprefix("device.engine."): v
            for k, v in delta.items()
            if v
            and k
            in (
                "device.engine.queries",
                "device.engine.bucket_hits",
                "device.engine.bucket_misses",
                "device.engine.compiles",
                "device.engine.incremental_updates",
                "device.engine.full_restages",
            )
        },
    }


def bench_reconvergence_grid1024() -> dict:
    from openr_tpu.utils.topo import grid_topology

    return bench_reconvergence(
        grid_topology(32), "grid1024", "node-0-0", "node-16-16"
    )


def bench_reconvergence_fattree10k() -> dict:
    """Crossover evidence at production scale (r3 weak #3): the same
    end-to-end reconvergence pipeline on a ~10k-switch fabric, where the
    host Dijkstra pays ~10x the 1k-grid graph work per SPF while the
    device batch cost barely moves."""
    from openr_tpu.utils.topo import fabric_topology

    dbs = fabric_topology(96, planes=4, ssw_per_plane=24, rsw_per_pod=100)
    own = next(d.this_node_name for d in dbs if d.this_node_name.startswith("rsw"))
    flap = next(d.this_node_name for d in dbs if d.this_node_name.startswith("fsw"))
    return bench_reconvergence(
        dbs,
        f"fattree{len(dbs)}",
        own,
        flap,
        n_prefixes=128,
        host_reps=3,
        device_reps=8,
    )


def bench_reconvergence_fabric5000() -> dict:
    """The reference BM's largest fabric reconvergence point
    (BM_DecisionFabric 5000, DecisionBenchmark.cpp:78-86) on the same
    end-to-end flap pipeline as the grid1024/fattree10k rows."""
    from openr_tpu.utils.topo import fabric_topology

    dbs = fabric_topology(156, rsw_per_pod=28)  # 5008 switches
    own = next(
        d.this_node_name for d in dbs if d.this_node_name.startswith("rsw")
    )
    flap = next(
        d.this_node_name for d in dbs if d.this_node_name.startswith("fsw")
    )
    return bench_reconvergence(
        dbs,
        f"fabric{len(dbs)}",
        own,
        flap,
        n_prefixes=128,
        host_reps=3,
        device_reps=8,
    )


def bench_chaos_fuzz_smoke(n: int = 8, seed: int = 20260807) -> dict:
    """Throughput of the coverage-guided chaos fuzzer's inner loop
    (openr_tpu/chaos/fuzz.py): one small fixed-seed session, reporting
    runs/s and the coverage the search discovered beyond its seed
    timelines.  The row exists so a regression that slows the oracle
    bundle (each run replays the full dispatch ladder + fleet + kv
    fabric) or kills coverage growth shows up in the artifact, not just
    as a slower soak."""
    from openr_tpu.chaos.fuzz import FUZZ_COUNTERS, fuzz

    c0 = FUZZ_COUNTERS.get_counters()
    t0 = time.monotonic()
    # leave the harness its exit slack; the session sheds inside itself
    session = fuzz(n, seed=seed, budget_s=max(_budget_left() - 120, 30.0))
    wall = time.monotonic() - t0
    c1 = FUZZ_COUNTERS.get_counters()
    ran = len(session.results)
    hist = session.coverage_history
    return {
        "runs": ran,
        "shed": session.shed,
        "wall_s": round(wall, 3),
        "runs_per_s": round(ran / wall, 3) if wall > 0 else None,
        "coverage_tokens": hist[-1] if hist else 0,
        "coverage_from_search": (hist[-1] - hist[2]) if len(hist) > 3 else 0,
        "corpus_size": len(session.corpus),
        "oracle_failures": (
            c1["chaos.fuzz.oracle_failures"] - c0["chaos.fuzz.oracle_failures"]
        ),
        "note": f"fuzz(n={n}, seed={seed}); oracle bundle on every run",
    }


def bench_sched_explore_smoke(budget_s: float = 30.0, seed: int = 0) -> dict:
    """Throughput of the deterministic schedule explorer
    (openr_tpu/analysis/sched.py): one budgeted library sweep (exhaustive
    DPOR on the small scenarios, POS sampling on the rest), reporting
    schedules/s and the DPOR prune ratio on the exhaustive pair.  The
    row exists so a regression that slows the controlled scheduler's
    round trip (every step is a cross-thread handoff) or weakens the
    reduction (prune ratio collapsing toward 1x means DPOR degenerated
    to naive enumeration) shows up in the artifact."""
    from openr_tpu.analysis import sched

    t0 = time.monotonic()
    out = sched.tier1_smoke(
        total_budget_s=min(budget_s, max(_budget_left() - 120, 10.0)),
        seed=seed,
    )
    wall = time.monotonic() - t0
    schedules = sum(r["schedules"] for r in out["scenarios"].values())
    prunes = sum(r["prunes"] for r in out["scenarios"].values())
    # reduction evidence on the exhaustive scenarios: explored vs the
    # full interleaving count (explored + pruned sleep-set skips)
    dpor = {
        n: out["scenarios"][n]
        for n in sched.EXHAUSTIVE_SCENARIOS
        if n in out["scenarios"] and out["scenarios"][n]["complete"]
    }
    explored = sum(r["schedules"] for r in dpor.values())
    return {
        "scenarios": len(out["scenarios"]),
        "shed": out["shed"],
        "schedules": schedules,
        "prunes": prunes,
        "wall_s": round(wall, 3),
        "schedules_per_s": round(schedules / wall, 3) if wall > 0 else None,
        "dpor_certificates": sorted(dpor),
        "dpor_prune_ratio": (
            round((explored + sum(r["prunes"] for r in dpor.values()))
                  / explored, 2)
            if explored
            else None
        ),
        "failures": len(out["failures"]),
        "note": f"tier1_smoke(seed={seed}); unplanted library must be clean",
    }


def bench_ksp2(
    dbs,
    name: str,
    own_node: str,
    n_prefixes: int,
    host_reps: int = 4,
    device_reps: int = 4,
) -> dict:
    """KSP2_ED_ECMP route build (reference: BM_DecisionGridAdjUpdates
    KSP2 rows, DecisionBenchmark.cpp:48-54): k=1/k=2 edge-disjoint paths
    for every best node — host per-destination recursion vs ONE masked
    batched device run."""
    from openr_tpu.decision import LinkState
    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.spf_solver import DeviceSpfBackend, SpfSolver
    from openr_tpu.types import (
        PrefixEntry,
        PrefixForwardingAlgorithm,
        PrefixForwardingType,
    )

    step = max(1, len(dbs) // n_prefixes)
    advertisers = [
        db.this_node_name
        for db in dbs[:: step]
        if db.this_node_name != own_node
    ][:n_prefixes]

    def fresh_state():
        ls = LinkState()
        for db in dbs:
            ls.update_adjacency_database(db)
        ps = PrefixState()
        for i, node in enumerate(advertisers):
            ps.update_prefix(
                node,
                "0",
                PrefixEntry(
                    prefix=f"fc00:{i:x}::/64",
                    forwarding_type=PrefixForwardingType.SR_MPLS,
                    forwarding_algorithm=PrefixForwardingAlgorithm.KSP2_ED_ECMP,
                ),
            )
        return ls, ps

    def ms(backend, reps):
        out = []
        rdb = None
        for _ in range(reps):
            ls, ps = fresh_state()  # cold caches each rep (the honest cost)
            solver = SpfSolver(own_node, spf_backend=backend)
            t0 = time.perf_counter()
            rdb = solver.build_route_db({"0": ls}, ps)
            out.append((time.perf_counter() - t0) * 1e3)
        return out, rdb

    host_times, host_rdb = ms(None, host_reps)
    dev_backend = DeviceSpfBackend(min_device_nodes=64, min_device_sources=1)
    snap = (
        dict(dev_backend.engine.get_counters())
        if dev_backend.engine is not None
        else {}
    )
    device_times, device_rdb = ms(dev_backend, device_reps)
    # cold caches each rep -> a fresh CSR mirror each rep, so the engine
    # restages the graph per rep; bytes_staged_per_rep records that cold
    # staging cost (the warm rows live in bench_reconvergence)
    engine_cols = _engine_attribution(
        dev_backend.engine, snap, min(host_times), device_reps
    )
    assert host_rdb.unicast_routes == device_rdb.unicast_routes
    return {
        "topology": name,
        "ksp2_prefixes": len(advertisers),
        "host_ms_min": round(min(host_times), 3),
        "host_ms_all": [round(t, 2) for t in host_times],
        "device_ms_min": round(min(device_times), 3),
        "device_ms_all": [round(t, 2) for t in device_times],
        "device_vs_host": round(min(host_times) / min(device_times), 2),
        **engine_cols,
        "note": (
            "measures the FORCED device path (min_device_sources=1); the "
            "shipped default policy routes these small-batch flows to the "
            "host below the measured batch crossover "
            "(DeviceSpfBackend docstring)"
        ),
    }


def bench_ksp2_grid1024() -> dict:
    from openr_tpu.utils.topo import grid_topology

    return bench_ksp2(grid_topology(32), "grid1024", "node-0-0", 32)


def bench_ksp2_fattree10k() -> dict:
    """KSP2 crossover evidence at production scale (r3 weak #3).  Host
    KSP2 at 10k pays two full Dijkstras plus path tracing per prefix;
    the device batches every (prefix, k) re-run into one masked call."""
    from openr_tpu.utils.topo import fabric_topology

    dbs = fabric_topology(96, planes=4, ssw_per_plane=24, rsw_per_pod=100)
    own = next(
        d.this_node_name for d in dbs if d.this_node_name.startswith("rsw")
    )
    return bench_ksp2(
        dbs,
        f"fattree{len(dbs)}",
        own,
        n_prefixes=8,
        host_reps=1,
        device_reps=3,
    )


class _WanServingBackend:
    """Serving batch-backend contract straight over the synthetic
    wan arrays: run_paths returns {source: [N] distance row}.  Every
    dispatch pads its source batch to one fixed S bucket, so the
    whole run reuses a single compiled program (the engine ladder's
    S-bucket discipline — a fresh S shape is a fresh XLA compile at
    100k and would dominate the row).  Shared by the single-scheduler
    serving row and the replica-fleet row (every replica dispatches
    into the same compiled program, like K daemons on one device)."""

    def __init__(self, topo, s_pad: int) -> None:
        self.runner = topo.runner
        self.n_nodes = topo.n_nodes
        self.s_pad = s_pad
        self._epoch = 0

    def epoch(self, area: str) -> int:
        return self._epoch

    def run_paths(
        self, area, sources, use_link_metric=True, expect_epoch=0
    ) -> dict:
        from openr_tpu.device.engine import EpochMismatchError

        if int(expect_epoch) != self._epoch:
            raise EpochMismatchError(int(expect_epoch), self._epoch)
        srcs = [int(s) for s in sources]
        out: dict = {}
        for lo in range(0, len(srcs), self.s_pad):
            chunk = srcs[lo : lo + self.s_pad]
            padded = chunk + [chunk[0]] * (self.s_pad - len(chunk))
            dist, _ = self.runner.forward(
                np.asarray(padded, np.int32), want_dag=False
            )
            dist = np.asarray(dist)[:, : self.n_nodes]
            for i, s in enumerate(chunk):
                out[s] = dist[i].copy()
        return out


def bench_serving_load_wan100k(
    topo, clients: int = 6, qps_per_client: float = 30.0, duration_s: float = 3.0
) -> dict:
    """Open-loop query serving at wan100k through the QueryScheduler
    (admission -> epoch-keyed coalescing -> double-buffered dispatch):
    N clients submit single-source distance queries at a fixed cadence
    regardless of replies; coalesced batches ride ONE padded-S runner
    dispatch.  Reports sustained qps, per-query p50/p99 latency, mean
    batch occupancy, and the shed/overflow ledger — plus a bit-exact
    parity sample of batched replies against serial single-query
    dispatches of the same backend."""
    from openr_tpu.chaos.overload import OpenLoopLoadGen
    from openr_tpu.serving import QueryScheduler

    s_pad = 16
    backend = _WanServingBackend(topo, s_pad)
    # warm: compile the padded program + learn the sweep hint before the
    # clock starts (every later dispatch reuses it)
    backend.run_paths("0", list(range(s_pad)))

    # source population: node 0's router view plus a spread of chords
    nodes = [int(s) for s in _wan_router_sources(topo)]
    nodes += [int(x) for x in range(0, topo.n_nodes, topo.n_nodes // 64)]

    sched = QueryScheduler(backend, max_pending=8192, max_coalesce=s_pad)
    sched.run()
    try:
        gen = OpenLoopLoadGen(sched, nodes=nodes, seed=7, clients=clients)
        report = gen.run_paced(
            duration_s, qps_per_client, gather_timeout_s=300.0
        )

        # bit-exact parity: batched replies vs serial single-query
        # dispatches of the same backend (one source per dispatch)
        sample = nodes[:: max(1, len(nodes) // 6)][:6]
        futs = [(s, sched.submit("paths", sources=(s,))) for s in sample]
        parity_ok = True
        for s, fut in futs:
            got = fut.result(120).value[s]
            serial = backend.run_paths("0", [s])[s]
            parity_ok &= bool(np.array_equal(got, serial))

        counters = sched.get_counters()
    finally:
        sched.stop()

    return {
        "clients": clients,
        "offered_qps": round(clients * qps_per_client, 1),
        "duration_s": duration_s,
        "submitted": report.submitted,
        "replied": report.replied,
        "shed": report.shed,
        "errors": report.errors,
        "zero_silent_drops": report.accounted == report.submitted,
        "sustained_qps": round(report.qps, 1),
        "p50_us": report.pctl_us(50),
        "p99_us": report.pctl_us(99),
        "mean_batch_occupancy": round(report.mean_batch_occupancy, 2),
        "batches": counters["serving.batches"],
        "coalesced": counters["serving.coalesced"],
        "admission_overflows": sched.admission.stats()["overflows"],
        "parity_sample": len(sample),
        "parity_ok": parity_ok,
    }


def bench_trace_overhead_wan100k(
    topo, clients: int = 6, qps_per_client: float = 30.0, duration_s: float = 2.0
) -> dict:
    """Span-tracing overhead on the wan100k serving path: the SAME
    open-loop load twice — tracing unarmed (the shipped default: one
    module-attribute load per seam), then armed at 1-in-8 sampling —
    reporting the qps and p99 deltas.  The armed segment sheds whole
    under OPENR_BENCH_BUDGET_S (an overhead row with only a baseline is
    useless, so the baseline sheds too)."""
    from openr_tpu.chaos.overload import OpenLoopLoadGen
    from openr_tpu.obs import trace as _trace
    from openr_tpu.serving import QueryScheduler

    if _budget_left() < 3 * (3 * duration_s + 10):
        return _shed_marker("trace_overhead_wan100k")

    s_pad = 16
    backend = _WanServingBackend(topo, s_pad)
    backend.run_paths("0", list(range(s_pad)))
    nodes = [int(s) for s in _wan_router_sources(topo)]
    nodes += [int(x) for x in range(0, topo.n_nodes, topo.n_nodes // 64)]

    def segment() -> dict:
        sched = QueryScheduler(backend, max_pending=8192, max_coalesce=s_pad)
        sched.run()
        try:
            gen = OpenLoopLoadGen(sched, nodes=nodes, seed=7, clients=clients)
            report = gen.run_paced(
                duration_s, qps_per_client, gather_timeout_s=300.0
            )
            return {
                "sustained_qps": round(report.qps, 1),
                "p50_us": report.pctl_us(50),
                "p99_us": report.pctl_us(99),
                "replied": report.replied,
            }
        finally:
            sched.stop()

    was_armed = _trace.TRACE is not None
    _trace.disable()
    try:
        # throwaway warm segment: the first paced run pays dispatch-path
        # warm-up (program cache, thread spin-up) that would otherwise
        # land entirely in the unarmed baseline and bias the delta
        segment()
        off = segment()
        tr = _trace.enable(sample_every=8, ring=512)
        armed = segment()
        obs_counters = tr.get_counters()
    finally:
        if not was_armed:
            _trace.disable()

    qps_delta_pct = (
        round(100.0 * (off["sustained_qps"] - armed["sustained_qps"])
              / off["sustained_qps"], 2)
        if off["sustained_qps"] > 0
        else None
    )
    return {
        "clients": clients,
        "offered_qps": round(clients * qps_per_client, 1),
        "duration_s": duration_s,
        "sample_every": 8,
        "unarmed": off,
        "armed": armed,
        "qps_delta_pct": qps_delta_pct,
        "p99_delta_us": armed["p99_us"] - off["p99_us"],
        "traces_started": obs_counters["obs.traces_started"],
        "spans_total": obs_counters["obs.spans_total"],
    }


def bench_serving_fleet_wan100k(
    topo,
    clients: int = 6,
    qps_per_client: float = 30.0,
    duration_s: float = 2.0,
) -> dict:
    """Replica-fleet front door at wan100k: the SAME open-loop load as
    serving_load_wan100k, submitted through a ReplicaRouter over K
    QueryScheduler replicas sharing one compiled program.  Reports
    aggregate qps/p50/p99 at 1 vs 2 vs 4 replicas (the router-overhead
    and spread curve), then a mid-run replica-kill segment at K=2: one
    replica's scheduler stops while clients keep submitting, and the
    row records the p99 delta vs the undisturbed K=2 segment plus the
    zero-silent-drops ledger and the router's failover/retry counters.
    Honors OPENR_BENCH_BUDGET_S: later fleet sizes (and the kill
    segment) shed whole rather than being killed mid-segment."""
    import threading

    from openr_tpu.chaos.overload import OpenLoopLoadGen
    from openr_tpu.serving import (
        QueryScheduler,
        ReplicaRouter,
        SchedulerReplica,
    )

    s_pad = 16
    backend = _WanServingBackend(topo, s_pad)
    # warm: compile the padded program before any segment's clock starts
    backend.run_paths("0", list(range(s_pad)))

    nodes = [int(s) for s in _wan_router_sources(topo)]
    nodes += [int(x) for x in range(0, topo.n_nodes, topo.n_nodes // 64)]

    def fleet(k: int):
        scheds = [
            QueryScheduler(backend, max_pending=8192, max_coalesce=s_pad)
            for _ in range(k)
        ]
        for s in scheds:
            s.run()
        router = ReplicaRouter(
            [SchedulerReplica(f"rep-{i}", s) for i, s in enumerate(scheds)],
            hedge_after_s=0.05 if k > 1 else None,
        )
        return router, scheds

    def segment(k: int, kill_at_s: Optional[float] = None):
        router, scheds = fleet(k)
        killer = None
        try:
            gen = OpenLoopLoadGen(
                router, nodes=nodes, seed=7, clients=clients, sessions=True
            )
            if kill_at_s is not None:
                killer = threading.Timer(kill_at_s, scheds[-1].stop)
                killer.start()
            report = gen.run_paced(
                duration_s, qps_per_client, gather_timeout_s=300.0
            )
            counters = router.get_counters()
        finally:
            if killer is not None:
                killer.cancel()
            router.stop()
            for s in scheds:
                s.stop()
        return report, counters

    scaling: dict = {}
    for k in (1, 2, 4):
        if _budget_left() < 3 * duration_s + 10:
            scaling[str(k)] = None  # shed whole
            continue
        report, _counters = segment(k)
        scaling[str(k)] = {
            "submitted": report.submitted,
            "sustained_qps": round(report.qps, 1),
            "p50_us": report.pctl_us(50),
            "p99_us": report.pctl_us(99),
            "shed": report.shed,
            "errors": report.errors,
            "zero_silent_drops": report.accounted == report.submitted,
        }

    kill_segment = None
    base2 = scaling.get("2")
    if base2 is not None and _budget_left() >= 3 * duration_s + 10:
        report, counters = segment(2, kill_at_s=duration_s / 2)
        kill_segment = {
            "killed_at_s": round(duration_s / 2, 2),
            "submitted": report.submitted,
            "replied": report.replied,
            "shed": report.shed,
            "errors": report.errors,
            "zero_silent_drops": report.accounted == report.submitted,
            "p99_us": report.pctl_us(99),
            "p99_delta_us": report.pctl_us(99) - base2["p99_us"],
            "router_retries": counters["serving.router.retries"],
            "router_failovers": counters["serving.router.failovers"],
            "router_replica_deaths": counters[
                "serving.router.replica_deaths"
            ],
        }

    return {
        "clients": clients,
        "offered_qps": round(clients * qps_per_client, 1),
        "duration_s": duration_s,
        "replica_scaling": scaling,
        "replica_kill": kill_segment,
    }


def bench_fleet_scaleout_wan100k(
    topo,
    n: int = 100_000,
    seed: int = 13,
    clients: int = 6,
    qps_per_client: float = 30.0,
    duration_s: float = 2.0,
) -> dict:
    """Elastic scale-out economics (round-20 tentpole): what a joining
    replica pays before it serves its first query, cold vs
    snapshot-restored, plus the router's qps/p99 curve across live
    scale(1 -> 2 -> 4) membership transitions.

    Segment A builds the OCS chorded ring at wan scale, checkpoints the
    donor engine (EngineSnapshot, serialized blob) and brings the SAME
    fresh mirror up twice on fresh engines: once cold (the first served
    query pays restage + XLA compile + query) and once restored (the
    install rung + manifest prewarm run at bring-up, OFF the serving
    path, so the first served query pays only the query).  The headline
    is time-to-first-served-query: restore must beat cold, and the
    restored replica's answers must match the donor's bit-exact.

    Segment B reuses the serving-fleet open-loop harness but keeps ONE
    router alive across the whole run and grows membership in place via
    `add_replica` (the fleet join path): per-k qps/p50/p99 plus the
    exactly-closing dispatch ledger over the union of all segments —
    the join transition may not leak a single unaccounted dispatch.

    Honors OPENR_BENCH_BUDGET_S: each segment sheds whole, and says so
    in the row."""
    from openr_tpu.chaos.ocs import OcsController
    from openr_tpu.chaos.overload import OpenLoopLoadGen
    from openr_tpu.decision.csr import CsrTopology
    from openr_tpu.device.engine import DeviceResidencyEngine
    from openr_tpu.serving import (
        QueryScheduler,
        ReplicaRouter,
        SchedulerReplica,
    )
    from openr_tpu.serving.router import dispatch_ledger_closes
    from openr_tpu.snapshot import SNAPSHOT_COUNTERS, EngineSnapshot

    def view(result):
        return {
            k: (v.metric, frozenset(v.next_hops)) for k, v in result.items()
        }

    # -- segment A: cold vs snapshot-restored bring-up ----------------------
    snapshot_section: dict
    if _budget_left() < 300:
        snapshot_section = _shed_marker("fleet_scaleout_wan100k:snapshot")
    else:
        ctl = OcsController(seed=seed, n=n, rounds=1, fault_round=-1)
        ls = ctl._build_ls(ctl._initial_chords(), {})
        names = ls.node_names
        sources = [names[(seed * 977 + k * 40503) % n] for k in range(8)]

        donor_csr = CsrTopology.from_link_state(ls)
        donor = DeviceResidencyEngine()
        donor.sync(donor_csr)
        donor_view = {
            s: view(r) for s, r in donor.spf_results(donor_csr, sources).items()
        }  # compiles the serving ladder key the manifest will carry

        c0 = SNAPSHOT_COUNTERS.get_counters()
        t0 = time.perf_counter()
        blob = EngineSnapshot.take(donor, donor_csr).to_bytes()
        take_s = time.perf_counter() - t0

        # ONE fresh mirror, brought up twice on fresh engines: identical
        # starting state for both paths (cold runs first, so any global
        # caching would help cold, not the restore being measured)
        t0 = time.perf_counter()
        joiner_csr = CsrTopology.from_link_state(ls)
        mirror_build_s = time.perf_counter() - t0

        cold = DeviceResidencyEngine()
        t0 = time.perf_counter()
        cold_res = cold.spf_results(joiner_csr, sources)
        cold_first_query_s = time.perf_counter() - t0

        warm = DeviceResidencyEngine()
        t0 = time.perf_counter()
        mode = EngineSnapshot.from_bytes(blob).restore(warm, joiner_csr)
        bringup_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        warm_res = warm.spf_results(joiner_csr, sources)
        warm_first_query_s = time.perf_counter() - t0

        assert mode == "install", mode
        parity = all(
            view(warm_res[s]) == donor_view[s]
            and view(cold_res[s]) == donor_view[s]
            for s in sources
        )
        assert parity, "restored replica diverged from donor"
        assert warm_first_query_s < cold_first_query_s, (
            warm_first_query_s,
            cold_first_query_s,
        )
        c1 = SNAPSHOT_COUNTERS.get_counters()
        snapshot_section = {
            "n_nodes": n,
            "snapshot_bytes": len(blob),
            "take_s": round(take_s, 3),
            "mirror_build_s": round(mirror_build_s, 3),
            "restore_mode": mode,
            "restore_bringup_s": round(bringup_s, 3),
            "manifest_programs": c1["snapshot.manifest_programs"]
            - c0["snapshot.manifest_programs"],
            "prewarmed_programs": c1["snapshot.prewarmed_programs"]
            - c0["snapshot.prewarmed_programs"],
            "cold_first_query_s": round(cold_first_query_s, 3),
            "restored_first_query_s": round(warm_first_query_s, 3),
            "first_query_speedup": round(
                cold_first_query_s / max(warm_first_query_s, 1e-9), 1
            ),
            "restored_vs_donor_parity": parity,
        }

    # -- segment B: live 1 -> 2 -> 4 membership transitions -----------------
    transitions: dict = {}
    ledger = None
    if _budget_left() < 3 * (3 * duration_s + 10):
        transitions = _shed_marker("fleet_scaleout_wan100k:transitions")
    else:
        s_pad = 16
        backend = _WanServingBackend(topo, s_pad)
        backend.run_paths("0", list(range(s_pad)))  # warm the program
        nodes = [int(s) for s in _wan_router_sources(topo)]
        nodes += [int(x) for x in range(0, topo.n_nodes, topo.n_nodes // 64)]

        scheds = [
            QueryScheduler(backend, max_pending=8192, max_coalesce=s_pad)
            for _ in range(4)
        ]
        scheds[0].run()
        started = [scheds[0]]
        router = ReplicaRouter(
            [SchedulerReplica("rep-0", scheds[0])], hedge_after_s=None
        )
        total_submitted = 0
        try:
            for k in (1, 2, 4):
                while len(started) < k:
                    s = scheds[len(started)]
                    s.run()
                    router.add_replica(
                        SchedulerReplica(f"rep-{len(started)}", s)
                    )
                    started.append(s)
                if _budget_left() < 3 * duration_s + 10:
                    transitions[str(k)] = None  # shed whole
                    continue
                gen = OpenLoopLoadGen(
                    router, nodes=nodes, seed=7, clients=clients, sessions=True
                )
                report = gen.run_paced(
                    duration_s, qps_per_client, gather_timeout_s=300.0
                )
                total_submitted += report.submitted
                transitions[str(k)] = {
                    "submitted": report.submitted,
                    "sustained_qps": round(report.qps, 1),
                    "p50_us": report.pctl_us(50),
                    "p99_us": report.pctl_us(99),
                    "shed": report.shed,
                    "errors": report.errors,
                    "zero_silent_drops": report.accounted == report.submitted,
                }
            counters = router.get_counters()
        finally:
            router.stop()
            for s in started:
                s.stop()
        # ONE ledger over the union of segments: the two join
        # transitions happened under this router and must not have
        # leaked a single unaccounted dispatch
        ledger = {
            "submitted": total_submitted,
            "dispatches": counters["serving.router.dispatches"],
            "closes_exactly": dispatch_ledger_closes(
                counters, total_submitted
            ),
        }
        assert ledger["closes_exactly"], (counters, total_submitted)

    return {
        "snapshot_bringup": snapshot_section,
        "clients": clients,
        "offered_qps": round(clients * qps_per_client, 1),
        "duration_s": duration_s,
        "scale_transitions": transitions,
        "dispatch_ledger": ledger,
    }


def bench_te_wan100k(
    topo,
    n_sources: int = 512,
    n_dests: int = 4,
    steps: int = 12,
    round_trips: int = 3,
) -> dict:
    """Differentiable TE at wan100k: time-to-optimized-metrics for the
    gradient-descent optimizer (soft float32 descent + exact uint32
    validation gate, openr_tpu/te) on a seeded demand matrix, against a
    host hill-climb baseline given the SAME number of exact-solver
    evaluations.  Headline: optimizer wall seconds, exact objective
    before/after for both searches, and descent steps taken.  Honors
    OPENR_BENCH_BUDGET_S through the optimizer's budget hook (stages
    shed, never a mid-stage kill)."""
    from openr_tpu.te import TeOptimizer, TeProblem, hill_climb

    rng = np.random.RandomState(0)
    n = topo.n_nodes
    dests = np.linspace(0, n - 1, n_dests).astype(np.int32)
    sources = rng.choice(n, size=n_sources, replace=False)
    demand = np.zeros((topo.node_capacity, n_dests), dtype=np.float32)
    demand[sources] = rng.uniform(
        0.5, 2.0, size=(n_sources, n_dests)
    ).astype(np.float32)
    demand[dests, np.arange(n_dests)] = 0.0
    problem = TeProblem.from_topology(
        topo, dests, demand, metric_lo=1, metric_hi=16
    )

    def room() -> float:
        return _budget_left() - 120  # leave the harness its exit slack

    opt = TeOptimizer()
    t0 = time.perf_counter()
    res = opt.optimize(
        problem,
        steps=steps,
        round_trips=round_trips,
        n_sweeps=64,
        flow_sweeps=48,
        budget_left=room,
    )
    te_wall_s = time.perf_counter() - t0

    # host baseline: hill-climb spending the same exact-evaluation count
    # the optimizer's validation gate spent (its only search oracle)
    t0 = time.perf_counter()
    _hm, hill_obj, hill_evals = hill_climb(
        problem, rounds=res.round_trips, seed=1, budget_left=room
    )
    hill_wall_s = time.perf_counter() - t0

    return {
        "n_sources": n_sources,
        "n_dests": n_dests,
        "te_wall_s": round(te_wall_s, 2),
        "te_steps": res.steps,
        "te_round_trips": res.round_trips,
        "te_accepted": res.accepted,
        "exact_objective_before": round(res.objective_before, 4),
        "exact_objective_after": round(res.objective_after, 4),
        "te_improvement_frac": round(
            1.0 - res.objective_after / res.objective_before, 4
        )
        if res.objective_before
        else 0.0,
        "hill_wall_s": round(hill_wall_s, 2),
        "hill_evals": hill_evals,
        "hill_objective_after": round(hill_obj, 4),
        "te_beats_or_matches_hill": bool(
            res.objective_after <= hill_obj + 1e-9
        ),
        "counters": {
            k: v
            for k, v in opt.get_counters().items()
            if not k.endswith("_milli")
        },
    }


class _Topos:
    """Lazy shared topology cache for the device-row child."""

    def __init__(self) -> None:
        self._cache: dict = {}

    def __getattr__(self, name: str):
        if name not in self._cache:
            from benchmarks import synthetic

            if name == "grid":
                self._cache[name] = synthetic.grid(32)
            elif name == "fat_tree":
                self._cache[name] = synthetic.fat_tree()  # 10080, 4-plane
            elif name == "wan":
                self._cache[name] = synthetic.wan(100_000)
            else:
                raise AttributeError(name)
        return self._cache[name]


def _wan_router_sources(wan) -> np.ndarray:
    from benchmarks import synthetic

    # router-view: self + every neighbor (the per-router production SPF
    # set — LFA-free ECMP needs distances from each neighbor)
    return np.concatenate([[0], synthetic.neighbors_of(wan, 0)]).astype(
        np.int32
    )


# Device rows, headline first so a wedge loses the least important rows.
# Each entry: name -> fn(topos) returning the row dict.
DEVICE_ROWS = {
    "allsrc_spf_fattree10k": lambda t: bench_all_sources(
        t.fat_tree, np.arange(t.fat_tree.n_nodes), reps=5, cpp_sample=64
    ),
    "allsrc_spf_grid1024": lambda t: bench_all_sources(
        t.grid, np.arange(t.grid.n_nodes), reps=10
    ),
    "router_spf_wan100k": lambda t: bench_all_sources(
        t.wan, _wan_router_sources(t.wan), reps=5
    ),
    "allsrc_tile1024_wan100k": lambda t: bench_all_sources(
        t.wan, np.arange(1024, dtype=np.int32), reps=3, cpp_sample=32
    ),
    "allsrc_full_wan100k": lambda t: bench_allsrc_full_wan100k(t.wan),
    # the literal north-star shape: <50ms single-chip for the fleet-wide
    # route-building input at a production-plausible prefix count
    "allsrc_reduced_p128_wan100k": lambda t: bench_allsrc_full_wan100k(
        t.wan, n_prefixes=128
    ),
    # round-5 warm start: flap-recovery rebuild from the previous product
    "fleet_warm_rebuild_wan100k": lambda t: bench_fleet_warm_wan100k(t.wan),
    # round-8 incremental delta dataflow: 1k-event storm -> 8 dispatches
    "flap_storm_wan100k": lambda t: bench_flap_storm_wan100k(t.wan),
    # round-11 OCS circuit swaps: slot-freelist rewires vs full restage
    # byte economics on one resident graph (builds its own LinkState)
    "ocs_rewire_wan100k": lambda t: bench_ocs_rewire_wan100k(),
    # round-14 Pallas kernels vs their XLA twins, roofline column per
    # kernel (compiled on TPU; interpreter elsewhere, labeled)
    "pallas_vs_xla": lambda t: bench_pallas_vs_xla(),
    # BASELINE config #3: dual-metric KSP at 100k (r3 next #6)
    "ksp_dual_metric_wan100k": lambda t: bench_ksp_dual_metric_wan100k(
        t.wan
    ),
    "srlg_whatif_10kx1k": lambda t: bench_srlg_whatif(
        t.grid, n_variants=10_000, reps=5, cpp_sample=64
    ),
    "tilfa_wan100k": lambda t: bench_tilfa(t.wan, source=0, reps=5),
    "reconverge_flap_grid1024": lambda t: bench_reconvergence_grid1024(),
    "ksp2_grid1024": lambda t: bench_ksp2_grid1024(),
    # production-scale host/device crossover rows (r3 next #3)
    "reconverge_flap_fattree10k": lambda t: bench_reconvergence_fattree10k(),
    "ksp2_fattree10k": lambda t: bench_ksp2_fattree10k(),
    # the reference BM's largest fabric reconvergence point
    # (BM_DecisionFabric 5000, DecisionBenchmark.cpp:78-86; r4 verdict
    # bench-grid residue)
    "reconverge_flap_fabric5000": lambda t: bench_reconvergence_fabric5000(),
    # query-serving layer under open-loop load: sustained qps, p50/p99,
    # batch occupancy through admission/coalescing/double-buffering
    "serving_load_wan100k": lambda t: bench_serving_load_wan100k(t.wan),
    # replica-fleet front door: aggregate qps at 1/2/4 replicas through
    # the ReplicaRouter, plus a mid-run replica-kill segment (p99 delta,
    # zero-silent-drops ledger, failover counters)
    "serving_fleet_wan100k": lambda t: bench_serving_fleet_wan100k(t.wan),
    # round-20 elastic scale-out: cold vs snapshot-restored replica
    # bring-up (time-to-first-served-query, restored-vs-donor parity)
    # plus live 1->2->4 add_replica transitions under open-loop load
    # with the union dispatch ledger closing exactly
    "fleet_scaleout_wan100k": lambda t: bench_fleet_scaleout_wan100k(t.wan),
    # differentiable TE: gradient-descent metric optimization with the
    # exact-solver acceptance gate vs host hill-climb at equal exact
    # evaluations (openr_tpu/te; docs/OPERATIONS.md "TE runbook")
    "te_wan100k": lambda t: bench_te_wan100k(t.wan),
    # span-tracing overhead: the serving load row twice, unarmed vs
    # armed at 1-in-8 sampling (qps/p99 delta; docs/OPERATIONS.md
    # "Tracing runbook")
    "trace_overhead_wan100k": lambda t: bench_trace_overhead_wan100k(t.wan),
}

DEVICE_NOTES = [
    "device times include shortest-path-DAG extraction; the C++ "
    "baseline computes distances only",
    "min-over-reps: the shared TPU tunnel adds a flat ~100ms penalty "
    "per dispatch in degraded windows (flips on ~30s timescales, "
    "independent of program content — measured identical compiled "
    "programs at 0.04ms and 100ms minutes apart); per-rep samples "
    "retained above; p50/p95 reported for the latency-sensitive rows",
    "device_ms_amortized: per-run time with the flat per-dispatch "
    "tunnel fee divided out — R rotated-input runs inside ONE "
    "dispatch, (T_R - T_1)/(R-1), null when the latency windows "
    "flipped against the estimator.  This is the sustained "
    "per-question cost production batching achieves; wall numbers "
    "(device_ms_min) are reported alongside and still include the fee",
    "reconverge_flap/ksp2 are host+device END-TO-END pipelines whose "
    "single small dispatch pays the full tunnel fee, so the host "
    "backend wins their WALL time at 1k-node scale; see "
    "docs/TPU_DESIGN.md 'Host/device crossover' for the analysis and "
    "the production batching posture",
    "every timed rep dispatches a DISTINCT pre-staged input (rolled "
    "batches / masks / equal-degree sources): repeat-identical "
    "dispatches can be served from a transport-level result cache, "
    "which fabricated sub-ms walls for 100k kernels before the guard",
    "achieved_bw_frac: bytes-moved-estimate / (wall x peak HBM BW, "
    "OPENR_PEAK_HBM_BW, default v5e 819 GB/s) — the utilization lens "
    "on every device row; null where no traffic model exists for the "
    "row (bytes_moved_est null).  A memory-bound kernel near 1.0 is "
    "done; a small fraction says the wall is dispatch/latency, not "
    "bandwidth",
    "pallas_vs_xla carries per-kernel sub-rows (fused_epilogue, "
    "blocked_outer) with their own bytes_source — compiled-program "
    "cost_analysis when available, traffic model otherwise — and "
    "peak_bw_source so roofline fractions compare across machines; "
    "mode=interpret rows time the Pallas interpreter, not the hardware",
]


def _device_child(rows_file: str, skip: set[str]) -> None:
    """Run device rows in order, appending one JSON line per finished row.
    Runs until done or killed by the parent's progress watchdog."""
    topos = _Topos()
    # a child killed mid-write leaves a torn line with no trailing
    # newline; terminate it so this attempt's first row isn't glued on
    if os.path.exists(rows_file) and os.path.getsize(rows_file):
        with open(rows_file, "rb") as f:
            f.seek(-1, os.SEEK_END)
            torn = f.read(1) != b"\n"
        if torn:
            with open(rows_file, "a") as f:
                f.write("\n")
    with open(rows_file, "a") as out:
        for name, fn in DEVICE_ROWS.items():
            if name in skip:
                continue
            if _budget_left() < 90:
                # pre-check BEFORE starting a compile-heavy row: a row
                # begun with seconds left gets killed mid-compile by
                # the parent watchdog (or the driver's rc=124 timeout)
                record = {"row": name, **_shed_marker(name)}
                out.write(json.dumps(record) + "\n")
                out.flush()
                os.fsync(out.fileno())
                continue
            # stderr: the bench contract is ONE JSON line on stdout
            print(f"[device-child] row {name} ...", file=sys.stderr, flush=True)
            t0 = time.perf_counter()
            try:
                record = {"row": name, "data": fn(topos)}
            except Exception as exc:  # a failing row must not kill the rest
                record = {"row": name, "error": f"{type(exc).__name__}: {exc}"}
            data = record.get("data")
            if isinstance(data, dict) and "achieved_bw_frac" not in data:
                # rows without a traffic model still carry the field
                # (null): every device row reports utilization uniformly
                data["bytes_moved_est"] = data.get("bytes_moved_est")
                data["achieved_bw_frac"] = None
            record["wall_s"] = round(time.perf_counter() - t0, 1)
            out.write(json.dumps(record) + "\n")
            out.flush()
            os.fsync(out.fileno())


def _read_device_rows(rows_file: str) -> dict:
    rows: dict = {}
    if not os.path.exists(rows_file):
        return rows
    with open(rows_file) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn final line from a killed child
            rows[rec["row"]] = rec
    return rows


def _head_details() -> dict:
    """Rows of the HEAD-committed bench_details.json — the reuse pool
    when the wall budget runs out before a row gets a live attempt.
    Empty dict when HEAD has no parseable details file."""
    try:
        proc = subprocess.run(
            ["git", "show", "HEAD:bench_details.json"],
            capture_output=True,
            text=True,
            timeout=30,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        if proc.returncode != 0:
            return {}
        rows = json.loads(proc.stdout).get("rows", {})
        return rows if isinstance(rows, dict) else {}
    except Exception:
        return {}


_HEADLINE = {"emitted": False}


def _maybe_emit_headline(details: dict) -> None:
    """Print the bench contract's ONE stdout JSON line as soon as the
    headline row has data — not at process end.  A driver that kills
    this process at its own wall cap then still has the headline
    (rc:124 with parsed:null is the failure mode this buys out of).
    Idempotent; later calls are no-ops."""
    if _HEADLINE["emitted"]:
        return
    headline = details["rows"].get("allsrc_spf_fattree10k")
    if isinstance(headline, dict) and "device_ms_min" in headline:
        print(
            json.dumps(
                {
                    "metric": "allsrc_spf_fattree10k_ms",
                    "value": headline["device_ms_min"],
                    "unit": "ms",
                    "vs_baseline": round(
                        headline["cpp_baseline_ms"]
                        / headline["device_ms_min"],
                        2,
                    ),
                }
            ),
            flush=True,
        )
        _HEADLINE["emitted"] = True


def _run_device_rows(details: dict) -> None:
    """Parent-side orchestration: spawn the device child, watch the rows
    file for progress, kill on per-row stall, merge, retry with completed
    rows skipped.  Attempts are spread across the run (sleep between), so
    a transiently wedged tunnel gets several windows to come back.
    Budget-aware: no new attempt starts (and the child is killed) once
    OPENR_BENCH_BUDGET_S is nearly spent."""
    if os.path.exists(DEVICE_ROWS_PATH):
        os.remove(DEVICE_ROWS_PATH)
    attempt_log: list[str] = []
    for attempt in range(DEVICE_ATTEMPTS):
        done = _read_device_rows(DEVICE_ROWS_PATH)
        # only successful rows are final; errored rows get retried in
        # later attempt windows (a transient tunnel failure can raise
        # instead of hanging — both deserve the retry windows)
        succeeded = [n for n in done if "data" in done[n]]
        remaining = [n for n in DEVICE_ROWS if n not in succeeded]
        if not remaining:
            break
        if _budget_left() < 120:
            attempt_log.append(
                f"attempt {attempt + 1}: skipped, wall budget exhausted"
            )
            break
        if attempt:
            time.sleep(min(RETRY_SLEEP_S, max(0.0, _budget_left() - 120)))
        proc = subprocess.Popen(
            [
                sys.executable,
                os.path.abspath(__file__),
                "--device-child",
                "--rows-file",
                DEVICE_ROWS_PATH,
                "--skip",
                ",".join(succeeded),
            ],
            env=_child_env(),
        )
        last_size = -1
        last_progress = time.monotonic()
        while True:
            rc = proc.poll()
            size = (
                os.path.getsize(DEVICE_ROWS_PATH)
                if os.path.exists(DEVICE_ROWS_PATH)
                else 0
            )
            if size != last_size:
                last_size = size
                last_progress = time.monotonic()
                # merge incrementally: a later wedge keeps earlier rows
                for name, rec in _read_device_rows(DEVICE_ROWS_PATH).items():
                    details["rows"][name] = rec.get(
                        "data", {"error": rec.get("error")}
                    )
                _flush_details(details)
                _maybe_emit_headline(details)
            if rc is not None:
                if rc != 0:
                    attempt_log.append(f"attempt {attempt + 1}: exit rc={rc}")
                break
            stalled = time.monotonic() - last_progress > ROW_TIMEOUT_S
            if stalled or _budget_left() <= 0:
                attempt_log.append(
                    f"attempt {attempt + 1}: "
                    + (
                        f"no row progress in {ROW_TIMEOUT_S:.0f}s"
                        if stalled
                        else "wall budget exhausted mid-row"
                    )
                    + "; killed child"
                )
                proc.kill()
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass  # D-state child: abandon it rather than block
                break
            time.sleep(2)
    done = _read_device_rows(DEVICE_ROWS_PATH)
    for name, rec in done.items():
        details["rows"][name] = rec.get("data", {"error": rec.get("error")})
    missing = [n for n in DEVICE_ROWS if n not in done]
    if missing:
        details["device_rows_missing"] = missing
    if attempt_log:
        details["device_attempt_log"] = attempt_log
    _maybe_emit_headline(details)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--device-child", action="store_true")
    parser.add_argument("--rows-file", default=DEVICE_ROWS_PATH)
    parser.add_argument("--skip", default="")
    args = parser.parse_args()
    if args.device_child:
        _device_child(
            args.rows_file, {s for s in args.skip.split(",") if s}
        )
        return

    details: dict = {"rows": {}, "notes": list(DEVICE_NOTES)}

    # --- device rows FIRST: the headline row (allsrc_spf_fattree10k)
    # --- leads DEVICE_ROWS and its stdout JSON line is emitted the
    # --- moment it lands (_maybe_emit_headline) — under a tight wall
    # --- budget the host rows below are the ones sacrificed, never the
    # --- headline
    _run_device_rows(details)
    _flush_details(details)

    # --- host-only rows: no device needed; each is skipped (not run
    # --- half-way) once the wall budget is nearly spent
    def _fabric_cold(pods: int, label: str, reps: int = 3):
        from openr_tpu.utils.topo import fabric_topology

        dbs = fabric_topology(pods, rsw_per_pod=28)
        return bench_decision_cold_start(reps=reps, dbs=dbs, name=label)

    def _incremental_grid10000():
        from openr_tpu.utils.topo import grid_topology

        return bench_incremental_prefix_updates(
            reps=20, dbs=grid_topology(100), name="grid10000"
        )

    def _incremental_fattree10k():
        from openr_tpu.utils.topo import fabric_topology

        dbs = fabric_topology(96, planes=4, ssw_per_plane=24, rsw_per_pod=100)
        own = next(
            d.this_node_name
            for d in dbs
            if d.this_node_name.startswith("rsw")
        )
        return bench_incremental_prefix_updates(
            reps=20, dbs=dbs, name=f"fattree{len(dbs)}", own_node=own
        )

    host_names: list[str] = []
    for name, fn in (
        ("incremental_prefix_grid100", bench_incremental_prefix_updates),
        # the larger reference scale points for the incremental path
        # (r4 verdict bench-grid residue)
        ("incremental_prefix_grid10000", _incremental_grid10000),
        ("incremental_prefix_fattree10k", _incremental_fattree10k),
        ("decision_cold_start_grid100", bench_decision_cold_start),
        # reference scale points (BM_DecisionGridInitialUpdate 1k grid,
        # BM_DecisionFabric 344/1000 switches, DecisionBenchmark.cpp:19-86)
        (
            "decision_cold_start_grid1024",
            lambda: bench_decision_cold_start(n_side=32, reps=2),
        ),
        (
            "decision_cold_start_fabric336",
            lambda: _fabric_cold(10, "fabric336"),
        ),
        (
            "decision_cold_start_fabric1008",
            lambda: _fabric_cold(31, "fabric1008"),
        ),
        # the reference BM's largest fabric point (BM_DecisionFabric 5000,
        # DecisionBenchmark.cpp:78-86): 156 pods x 32 + 16 ssw = 5008
        (
            "decision_cold_start_fabric5000",
            lambda: _fabric_cold(156, "fabric5008", reps=3),
        ),
        # the reference BM's largest grid; single rep (~3s measured after
        # the publication-parse fix — it was ~2.9s for 1k BEFORE it)
        # >=3 samples (r4 verdict: the single-sample rows)
        (
            "decision_cold_start_grid10000",
            lambda: bench_decision_cold_start(n_side=100, reps=3),
        ),
        # chaos-fuzzer inner-loop throughput (oracle bundle per run)
        ("chaos_fuzz_smoke", bench_chaos_fuzz_smoke),
        # schedule-explorer throughput + DPOR reduction evidence
        ("sched_explore_smoke", bench_sched_explore_smoke),
    ):
        host_names.append(name)
        if _budget_left() < 60:
            details["rows"][name] = _shed_marker(name)
            _flush_details(details)
            continue
        try:
            details["rows"][name] = fn()
        except Exception as exc:
            details["rows"][name] = {"error": f"{type(exc).__name__}: {exc}"}
        _flush_details(details)
    # virtual-mesh scaling evidence (r3 next #8): child process so the
    # 8-device CPU mesh env never touches this process's TPU platform
    if _budget_left() < 60:
        details["rows"]["virtual_mesh_scaling"] = _shed_marker(
            "virtual_mesh_scaling"
        )
    else:
        try:
            proc = subprocess.run(
                [sys.executable, "-m", "benchmarks.mesh_scaling"],
                capture_output=True,
                text=True,
                timeout=min(900.0, max(_budget_left(), 60.0)),
                env=_child_env(
                    JAX_PLATFORMS="cpu",
                    XLA_FLAGS="--xla_force_host_platform_device_count=8",
                ),
            )
            details["rows"]["virtual_mesh_scaling"] = json.loads(
                proc.stdout.strip().splitlines()[-1]
            )
        except Exception as exc:
            details["rows"]["virtual_mesh_scaling"] = {
                "error": f"{type(exc).__name__}: {exc}"
            }
    _flush_details(details)

    # run_all contains per-row failures; guard the whole call too so a
    # host-side regression can never sink the details file
    from benchmarks import host_subsystems

    if _budget_left() < 60:
        details["rows"]["host_subsystems"] = _shed_marker("host_subsystems")
    else:
        try:
            details["rows"]["host_subsystems"] = host_subsystems.run_all()
        except Exception as exc:
            details["rows"]["host_subsystems"] = {
                "error": f"{type(exc).__name__}: {exc}"
            }
    _flush_details(details)

    # --- backfill: rows that never got a live completion this run reuse
    # --- the HEAD-committed bench_details.json row, marked as such —
    # --- a budget-squeezed capture still ships a full table
    expected = (
        list(DEVICE_ROWS)
        + host_names
        + ["virtual_mesh_scaling", "host_subsystems"]
    )
    head_rows = None
    reused = []
    for name in expected:
        row = details["rows"].get(name)
        live = isinstance(row, dict) and "error" not in row
        if live:
            continue
        if head_rows is None:
            head_rows = _head_details()
        h = head_rows.get(name)
        if isinstance(h, dict) and "error" not in h:
            details["rows"][name] = {**h, "reused_from_head": True}
            reused.append(name)
    if reused:
        details["rows_reused_from_head"] = reused
        _flush_details(details)

    _maybe_emit_headline(details)
    if not _HEADLINE["emitted"]:
        headline = details["rows"].get("allsrc_spf_fattree10k")
        error = (
            headline.get("error")
            if isinstance(headline, dict)
            else "headline device row did not complete in any attempt window"
        )
        print(
            json.dumps(
                {
                    "metric": "allsrc_spf_fattree10k_ms",
                    "value": None,
                    "unit": "ms",
                    "vs_baseline": None,
                    "error": error,
                }
            )
        )


if __name__ == "__main__":
    main()
