#!/bin/bash
# Recurring budgeted chaos-fuzz soak (docs/OPERATIONS.md "Chaos
# fuzzing").  Runs the `-m slow` soak suite under a wall budget and a
# logged (hence replayable) session seed; on failure the same seed is
# re-run through the fuzzer CLI, which shrinks each violation and
# deposits the reproducer under tests/chaos_corpus/ where tier-1
# replays it forever once committed.
#
# Usage: fuzz_soak.sh [repo-dir]
#
# Environment (all optional):
#   OPENR_FUZZ_BUDGET_S  wall budget for the soak (default 900); the
#                        session sheds remaining runs loudly at the
#                        deadline instead of being killed mid-timeline
#   OPENR_FUZZ_SEED      session seed (default: days-since-epoch, so a
#                        daily timer walks the seed space one seed per
#                        day and any day's failure replays exactly)
#   OPENR_TRACE          set to 1 to also feed span-tree structure
#                        tokens into the coverage fingerprint

set -euo pipefail

REPO="${1:-$(cd "$(dirname "$0")/.." && pwd)}"
cd "$REPO"

: "${OPENR_FUZZ_BUDGET_S:=900}"
: "${OPENR_FUZZ_SEED:=$(( $(date +%s) / 86400 ))}"
export OPENR_FUZZ_BUDGET_S OPENR_FUZZ_SEED

# the seed is the whole reproduction recipe — make it impossible to lose
echo "fuzz_soak: seed=${OPENR_FUZZ_SEED} budget=${OPENR_FUZZ_BUDGET_S}s"

python -m pytest tests/test_fuzz.py -m slow -q -p no:cacheprovider \
    2>&1 | tee /tmp/openr-fuzz-soak.log
status=${PIPESTATUS[0]}

if [ "$status" -ne 0 ]; then
    # replay the SAME seed through the CLI: sessions are deterministic,
    # so the failures recur, get ddmin-shrunk, and land as committed-
    # corpus candidates (contract: tests/chaos_corpus/README.md)
    echo "fuzz_soak: FAILED (seed=${OPENR_FUZZ_SEED}); shrinking" \
         "reproducers into tests/chaos_corpus/"
    python -m openr_tpu.chaos.fuzz --fuzz-n 200 \
        --seed "${OPENR_FUZZ_SEED}" \
        --budget-s "${OPENR_FUZZ_BUDGET_S}" \
        --out tests/chaos_corpus || true
    echo "fuzz_soak: reproduce with OPENR_FUZZ_SEED=${OPENR_FUZZ_SEED}" \
         "pytest tests/test_fuzz.py -m slow"
fi
exit "$status"
