#!/usr/bin/env python3
"""Run the openr-tpu static invariant checker (see docs/ARCHITECTURE.md).

Equivalent to ``python -m openr_tpu.analysis openr_tpu/`` from the repo
root, but runnable from anywhere in the tree.  All CLI flags pass
through — e.g. ``scripts/lint.py --changed-only`` for a fast pre-commit
pass scoped to the files you touched (lock-order / guarded-by /
thread-shutdown-order findings always survive the filter: they are
whole-tree properties), ``scripts/lint.py --programs`` for the full
jaxpr-contract audit, ``scripts/lint.py --races tests/test_chaos.py``
to run tests under the OPENR_TSAN dynamic race detector, or
``scripts/lint.py --sched`` for the deterministic schedule explorer
(``--sched-replay``/``--sched-shrink`` take a schedule id).  Exit codes
are uniform across all modes: 0 clean, 1 findings, 2 infra failure.
"""

import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from openr_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    argv = sys.argv[1:]
    # default target only when no positional path was given (flags pass
    # through untouched)
    if not any(not a.startswith("-") for a in argv):
        argv = argv + [str(REPO_ROOT / "openr_tpu")]
    sys.exit(main(argv))
