#!/usr/bin/env python3
"""Run the openr-tpu static invariant checker (see docs/ARCHITECTURE.md).

Equivalent to ``python -m openr_tpu.analysis openr_tpu/`` from the repo
root, but runnable from anywhere in the tree.
"""

import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from openr_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    argv = sys.argv[1:] or [str(REPO_ROOT / "openr_tpu")]
    sys.exit(main(argv))
