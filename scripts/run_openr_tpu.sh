#!/bin/bash
# Launch wrapper for the openr-tpu daemon under a supervisor
# (reference: openr/scripts/run_openr.sh — sources an env file of
# OPENR_* overrides, then execs the daemon so signals pass through).
#
# Usage: run_openr_tpu.sh [/etc/sysconfig/openr-tpu]
#
# The env file may set:
#   OPENR_CONFIG   path to the JSON config (default /etc/openr-tpu.conf)
#   OPENR_ARGS     extra daemon flags (flags override config fields)

set -eu

ENV_FILE="${1:-/etc/sysconfig/openr-tpu}"
if [ -f "$ENV_FILE" ]; then
    # shellcheck disable=SC1090
    . "$ENV_FILE"
fi

OPENR_CONFIG="${OPENR_CONFIG:-/etc/openr-tpu.conf}"
OPENR_ARGS="${OPENR_ARGS:-}"

# exec: the supervisor's signals (systemd stop, watchdog restart) must
# reach the daemon, not this wrapper
# shellcheck disable=SC2086
exec openr-tpu --config "$OPENR_CONFIG" $OPENR_ARGS
