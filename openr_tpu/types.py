"""Wire types for openr_tpu.

Functional equivalents of the reference Thrift IDL (reference:
openr/if/Types.thrift, openr/if/Network.thrift) as slotted dataclasses with a
canonical byte serialization (see openr_tpu.serializer).  String node ids live
at this layer; the Decision compute plane interns them to dense int32 ids
before anything touches the device.
"""

from __future__ import annotations

import enum
import ipaddress
import time
from dataclasses import dataclass, field
from typing import Optional


# ---------------------------------------------------------------------------
# Perf events (reference: openr/if/Types.thrift:29-52, openr/common/Util.h:134)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class PerfEvent:
    node_name: str
    event_name: str
    unix_ts_ms: int


@dataclass(slots=True)
class PerfEvents:
    events: list[PerfEvent] = field(default_factory=list)

    def add(self, node_name: str, event_name: str, ts_ms: Optional[int] = None) -> None:
        ts = ts_ms if ts_ms is not None else int(time.time() * 1000)
        self.events.append(PerfEvent(node_name, event_name, ts))

    def total_duration_ms(self) -> int:
        if len(self.events) < 2:
            return 0
        return self.events[-1].unix_ts_ms - self.events[0].unix_ts_ms

    def duration_between_ms(self, start_event: str, end_event: str) -> int:
        """Reference: getDurationBetweenPerfEvents, openr/common/Util.h:147."""
        start = next(
            (e for e in self.events if e.event_name == start_event), None
        )
        end = next((e for e in self.events if e.event_name == end_event), None)
        if start is None or end is None:
            missing = start_event if start is None else end_event
            raise ValueError(f"perf event {missing!r} not recorded")
        if end.unix_ts_ms < start.unix_ts_ms:
            raise ValueError(f"{end_event} precedes {start_event}")
        return end.unix_ts_ms - start.unix_ts_ms


def add_perf_event(perf_events: Optional[PerfEvents], node: str, event: str) -> None:
    if perf_events is not None:
        perf_events.add(node, event)


# ---------------------------------------------------------------------------
# Adjacency / link state (reference: openr/if/Types.thrift:96-175)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class Adjacency:
    other_node_name: str
    if_name: str
    metric: int = 1
    adj_label: int = 0
    is_overloaded: bool = False
    rtt_us: int = 0
    timestamp_s: int = 0
    weight: int = 1
    other_if_name: str = ""
    next_hop_v6: str = ""
    next_hop_v4: str = ""


@dataclass(slots=True)
class AdjacencyDatabase:
    this_node_name: str
    adjacencies: list[Adjacency] = field(default_factory=list)
    is_overloaded: bool = False
    node_label: int = 0
    area: str = "0"
    perf_events: Optional[PerfEvents] = None
    # soft-drain (reference: nodeMetricIncrementVal, Types.thrift field 9):
    # added to every adjacency metric this node originates, steering
    # traffic away WITHOUT the hard is_overloaded transit cutoff
    node_metric_increment_val: int = 0


# ---------------------------------------------------------------------------
# Prefixes (reference: openr/if/Types.thrift:200-420, OpenrConfig.thrift)
# ---------------------------------------------------------------------------


class PrefixType(enum.IntEnum):
    LOOPBACK = 1
    DEFAULT = 2
    BGP = 3
    PREFIX_ALLOCATOR = 4
    BREEZE = 5
    RIB = 6
    CONFIG = 7
    VIP = 8


class PrefixForwardingType(enum.IntEnum):
    IP = 0
    SR_MPLS = 1


class PrefixForwardingAlgorithm(enum.IntEnum):
    SP_ECMP = 0
    KSP2_ED_ECMP = 1
    # UCMP: shortest-path routing with weighted next-hops (reference:
    # OpenrConfig.thrift PrefixForwardingAlgorithm; value 2 is unused
    # there too)
    SP_UCMP_ADJ_WEIGHT_PROPAGATION = 3
    SP_UCMP_PREFIX_WEIGHT_PROPAGATION = 4


@dataclass(slots=True)
class PrefixMetrics:
    """Reference: openr/if/OpenrConfig.thrift PrefixMetrics — ordered
    comparison chain for best-route selection (higher is better for
    preferences, lower is better for distance)."""

    version: int = 1
    path_preference: int = 1000
    source_preference: int = 100
    distance: int = 0


@dataclass(slots=True)
class PrefixEntry:
    prefix: str  # CIDR string, canonicalized
    type: PrefixType = PrefixType.LOOPBACK
    forwarding_type: PrefixForwardingType = PrefixForwardingType.IP
    forwarding_algorithm: PrefixForwardingAlgorithm = PrefixForwardingAlgorithm.SP_ECMP
    metrics: PrefixMetrics = field(default_factory=PrefixMetrics)
    tags: tuple[str, ...] = ()
    area_stack: tuple[str, ...] = ()
    min_nexthop: Optional[int] = None
    prepend_label: Optional[int] = None
    # UCMP capacity weight (reference: Types.thrift PrefixEntry.weight):
    # consumed by SP_UCMP_PREFIX_WEIGHT_PROPAGATION, ignored otherwise
    weight: Optional[int] = None
    # BGP best-path metric vector (reference: Types.thrift:389 `mv`,
    # compared by MetricVectorUtils::compareMetricVectors, Util.h:479).
    # When absent on BGP-typed entries, selection falls back to the
    # PrefixMetrics ordered compare.
    mv: Optional["MetricVector"] = None


class CompareType(enum.IntEnum):
    """How a metric entity present in only one vector is handled
    (reference: Types.thrift:235 CompareType)."""

    WIN_IF_PRESENT = 1
    WIN_IF_NOT_PRESENT = 2
    IGNORE_IF_NOT_PRESENT = 3


@dataclass(slots=True)
class MetricEntity:
    """One BGP path attribute in a MetricVector
    (reference: Types.thrift:237)."""

    type: int
    priority: int  # higher compares first
    op: CompareType = CompareType.IGNORE_IF_NOT_PRESENT
    is_best_path_tie_breaker: bool = False
    metric: tuple[int, ...] = ()  # lexicographic, larger wins


@dataclass(slots=True)
class MetricVector:
    """BGP-style best-path metric vector (reference: Types.thrift:273);
    entries compared in decreasing priority order."""

    version: int = 1
    metrics: list[MetricEntity] = field(default_factory=list)


@dataclass(slots=True)
class PrefixDatabase:
    this_node_name: str
    prefix_entries: list[PrefixEntry] = field(default_factory=list)
    delete_prefix: bool = False
    area: str = "0"
    perf_events: Optional[PerfEvents] = None


# ---------------------------------------------------------------------------
# KvStore (reference: openr/if/Types.thrift:555-1000)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class Value:
    """Versioned CRDT value (reference: openr/if/Types.thrift:555).

    `value is None` encodes a version-only advertisement (TTL refresh /
    anti-entropy digest), exactly like an unset thrift optional binary.
    """

    version: int
    originator_id: str
    value: Optional[bytes] = None
    ttl_ms: int = -1  # -1 == infinity (Constants::kTtlInfinity)
    ttl_version: int = 0
    hash: Optional[int] = None


@dataclass(slots=True)
class Publication:
    key_vals: dict[str, Value] = field(default_factory=dict)
    expired_keys: list[str] = field(default_factory=list)
    node_ids: Optional[list[str]] = None
    tobe_updated_keys: Optional[list[str]] = None
    area: str = "0"
    flood_root_id: Optional[str] = None


class KvStorePeerState(enum.IntEnum):
    """Reference: openr/kvstore/KvStore.h:278 peer FSM."""

    IDLE = 0
    SYNCING = 1
    INITIALIZED = 2


@dataclass(slots=True)
class PeerSpec:
    peer_addr: str = ""
    ctrl_port: int = 0
    state: KvStorePeerState = KvStorePeerState.IDLE


@dataclass(slots=True)
class PeerEvent:
    area: str = "0"
    peers_to_add: dict[str, PeerSpec] = field(default_factory=dict)
    peers_to_del: list[str] = field(default_factory=list)


@dataclass(slots=True)
class KvStoreSyncEvent:
    node_name: str
    area: str


# ---------------------------------------------------------------------------
# Spark neighbor discovery messages
# (reference: openr/if/Types.thrift:1276-1384)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class SparkHelloMsg:
    domain_name: str
    node_name: str
    if_name: str
    seq_num: int
    # NOTE: no quotes around the value type — `from __future__ import
    # annotations` already defers evaluation, and an INNER string literal
    # would survive typing.get_type_hints as a plain str, which the wire
    # deserializer cannot resolve to the dataclass
    neighbor_infos: dict[str, ReflectedNeighborInfo] = field(default_factory=dict)
    version: int = 1
    solicit_response: bool = False
    restarting: bool = False
    sent_ts_us: int = 0


@dataclass(slots=True)
class ReflectedNeighborInfo:
    last_nbr_msg_sent_ts_us: int = 0
    last_my_msg_rcvd_ts_us: int = 0


@dataclass(slots=True)
class SparkHandshakeMsg:
    node_name: str
    is_adjacency_established: bool
    hold_time_ms: int
    gr_hold_time_ms: int
    transport_addr_v6: str
    transport_addr_v4: str
    openr_ctrl_port: int
    kvstore_cmd_port: int = 0
    area: str = "0"
    neighbor_node_name: Optional[str] = None


@dataclass(slots=True)
class SparkHeartbeatMsg:
    node_name: str
    seq_num: int
    hold_time_ms: int = 0


@dataclass(slots=True)
class SparkPacket:
    """One-of wrapper for the three Spark messages (reference:
    thrift::SparkPacket — exactly one member populated at a time)."""

    hello: Optional[SparkHelloMsg] = None
    handshake: Optional[SparkHandshakeMsg] = None
    heartbeat: Optional[SparkHeartbeatMsg] = None
    version: int = 1


class NeighborEventType(enum.IntEnum):
    NEIGHBOR_UP = 1
    NEIGHBOR_DOWN = 2
    NEIGHBOR_RESTARTED = 3
    NEIGHBOR_RTT_CHANGE = 4
    NEIGHBOR_RESTARTING = 5
    NEIGHBOR_ADJ_SYNCED = 6


@dataclass(slots=True)
class NeighborEvent:
    event_type: NeighborEventType
    node_name: str
    if_name: str
    remote_if_name: str = ""
    area: str = "0"
    neighbor_addr_v6: str = ""
    neighbor_addr_v4: str = ""
    ctrl_port: int = 0
    rtt_us: int = 0
    kvstore_port: int = 0
    adj_only_used_by_other_node: bool = False


# ---------------------------------------------------------------------------
# Interfaces (reference: openr/if/Types.thrift:1100-1150)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class InterfaceInfo:
    if_name: str
    is_up: bool
    if_index: int
    networks: list[str] = field(default_factory=list)


@dataclass(slots=True)
class InterfaceDatabase:
    this_node_name: str
    interfaces: dict[str, InterfaceInfo] = field(default_factory=dict)


# ---------------------------------------------------------------------------
# Netlink / platform events (reference: openr/nl/NetlinkTypes.h,
# fbnl::Link/IfAddress — consumed by LinkMonitor via netlinkEventsQueue)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class LinkEvent:
    if_name: str
    if_index: int
    is_up: bool


@dataclass(slots=True)
class AddrEvent:
    if_name: str
    prefix: str  # CIDR
    is_valid: bool  # False == address removed


@dataclass(slots=True)
class PrefixUpdateRequest:
    """Advertise/withdraw origination requests into PrefixManager
    (reference: PrefixUpdateRequest via prefixUpdatesQueue)."""

    prefixes_to_add: list[PrefixEntry] = field(default_factory=list)
    prefixes_to_del: list[str] = field(default_factory=list)
    type: Optional[PrefixType] = None  # origination source
    dst_areas: tuple[str, ...] = ()


# ---------------------------------------------------------------------------
# Routes (reference: openr/if/Network.thrift:66-160)
# ---------------------------------------------------------------------------


class MplsActionCode(enum.IntEnum):
    PUSH = 0
    SWAP = 1
    PHP = 2  # Penultimate hop popping => POP_AND_LOOKUP for last hop
    POP_AND_LOOKUP = 3


@dataclass(slots=True, frozen=True)
class MplsAction:
    action: MplsActionCode
    swap_label: Optional[int] = None
    push_labels: Optional[tuple[int, ...]] = None


@dataclass(slots=True, frozen=True)
class NextHop:
    """Reference: NextHopThrift openr/if/Network.thrift:66."""

    address: str
    if_name: Optional[str] = None
    metric: int = 0
    weight: int = 0
    area: Optional[str] = None
    neighbor_node_name: Optional[str] = None
    mpls_action: Optional[MplsAction] = None


@dataclass(slots=True)
class UnicastRoute:
    dest: str
    next_hops: list[NextHop] = field(default_factory=list)


@dataclass(slots=True)
class MplsRoute:
    top_label: int
    next_hops: list[NextHop] = field(default_factory=list)


@dataclass(slots=True)
class RouteDatabase:
    this_node_name: str
    unicast_routes: list[UnicastRoute] = field(default_factory=list)
    mpls_routes: list[MplsRoute] = field(default_factory=list)
    perf_events: Optional[PerfEvents] = None


# ---------------------------------------------------------------------------
# Helpers
# ---------------------------------------------------------------------------


def normalize_prefix(prefix: str) -> str:
    """Canonicalize a CIDR string (reference relies on thrift IpPrefix binary
    form being canonical; we rely on the ipaddress module)."""
    return str(ipaddress.ip_network(prefix, strict=False))


def prefix_key(node: str, prefix: str, area: str) -> str:
    """KvStore key for a prefix advertisement.

    Reference: Constants::kPrefixDbMarker + PrefixKey format
    (openr/common/Constants.h:212, openr/common/Util.h).
    """
    return f"prefix:[{node}]:[{area}]:[{normalize_prefix(prefix)}]"


def parse_prefix_key(key: str) -> Optional[tuple[str, str, str]]:
    """Parse `prefix:[node]:[area]:[cidr]` -> (node, area, prefix).

    Reference: PrefixKey::fromStr (openr/common/Util.cpp)."""
    if not key.startswith("prefix:"):
        return None
    body = key[len("prefix:") :]
    parts = body.split("]:[")
    if len(parts) != 3 or not parts[0].startswith("[") or not parts[2].endswith("]"):
        return None
    node = parts[0][1:]
    area = parts[1]
    prefix = parts[2][:-1]
    try:
        return node, area, normalize_prefix(prefix)
    except ValueError:
        return None


def node_name_from_key(key: str) -> str:
    """Second ':'-separated token (reference: getNodeNameFromKey,
    openr/common/Util.cpp:891)."""
    parts = key.split(":")
    if len(parts) < 2:
        return ""
    node = parts[1]
    if node.startswith("[") and node.endswith("]"):
        return node[1:-1]
    return node[1:] if node.startswith("[") else node


def adj_key(node: str) -> str:
    """Reference: Constants::kAdjDbMarker (openr/common/Constants.h:209)."""
    return f"adj:{node}"


ADJ_MARKER = "adj:"
PREFIX_MARKER = "prefix:"
TTL_INFINITY = -1


# -- DUAL flood-topology wire types (reference: openr/if/Types.thrift:461-846)


class DualMessageType(enum.IntEnum):
    """Reference: thrift::DualMessageType (Types.thrift:461-468)."""

    UPDATE = 1
    QUERY = 2
    REPLY = 3


@dataclass(slots=True)
class DualMessage:
    """One DUAL protocol message for a given root
    (reference: thrift::DualMessage, Types.thrift:470-485)."""

    dst_id: str = ""  # root id this message is about
    distance: int = 0  # sender's report distance (INT64_MAX = infinity)
    type: DualMessageType = DualMessageType.UPDATE


@dataclass(slots=True)
class DualMessages:
    """Batch of DUAL messages from one neighbor
    (reference: thrift::DualMessages, Types.thrift:490-500)."""

    src_id: str = ""
    messages: list[DualMessage] = field(default_factory=list)


@dataclass(slots=True)
class FloodTopoSetParams:
    """Set/unset myself as a child of a peer's SPT
    (reference: thrift::FloodTopoSetParams, Types.thrift:787-805)."""

    root_id: str = ""
    src_id: str = ""
    set_child: bool = False
    all_roots: Optional[bool] = None


@dataclass(slots=True)
class SptInfo:
    """Per-root SPT view (reference: thrift::SptInfo, Types.thrift:819-835)."""

    passive: bool = False
    cost: int = 0
    parent: Optional[str] = None
    children: list[str] = field(default_factory=list)


@dataclass(slots=True)
class SptInfos:
    """FLOOD_TOPO_GET response
    (reference: thrift::SptInfos, Types.thrift:838-860)."""

    infos: dict[str, SptInfo] = field(default_factory=dict)
    flood_root_id: Optional[str] = None
    flood_peers: list[str] = field(default_factory=list)
