"""breeze: the operator CLI (reference: openr/py/openr/cli/breeze.py)."""
