"""breeze — operator CLI for openr_tpu.

Functional equivalent of the reference's click-based breeze
(openr/py/openr/cli/breeze.py + clis/*): per-module command groups over the
ctrl API.  argparse-based (no third-party CLI dependency).

    breeze [-H host] [-p port] <group> <command> [args]

Groups: kvstore, decision, fib, lm, prefixmgr, spark, monitor, config.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any

from ..ctrl.client import CtrlClient
from ..fib.fib import FIB_CLIENT_OPENR
from ..serializer import to_wire
from ..types import (
    ADJ_MARKER,
    AdjacencyDatabase,
    PrefixDatabase,
    PrefixEntry,
    PrefixType,
    PREFIX_MARKER,
)
from ..serializer import loads


def _print_json(obj: Any) -> None:
    print(json.dumps(to_wire(obj), indent=2, sort_keys=True))


def _table(rows: list[list[str]], header: list[str]) -> None:
    widths = [
        max(len(str(r[i])) for r in rows + [header]) for i in range(len(header))
    ]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    print(fmt.format(*header))
    print(fmt.format(*["-" * w for w in widths]))
    for row in rows:
        print(fmt.format(*[str(c) for c in row]))


# -- command implementations -------------------------------------------------


def cmd_kvstore_keys(client: CtrlClient, args) -> None:
    pub = client.call(
        "getKvStoreKeyValsFilteredArea",
        area=args.area,
        prefixes=[args.prefix] if args.prefix else [],
        hash_only=True,
    )
    rows = [
        [k, v.originator_id, v.version, v.ttl_version, v.ttl_ms]
        for k, v in sorted(pub.key_vals.items())
    ]
    _table(rows, ["Key", "Originator", "Version", "TTL Version", "TTL (ms)"])


def cmd_kvstore_keyvals(client: CtrlClient, args) -> None:
    pub = client.call("getKvStoreKeyValsArea", area=args.area, keys=args.keys)
    for key, val in sorted(pub.key_vals.items()):
        print(f"> {key}")
        if val.value is None:
            print("  (no value)")
            continue
        try:
            _print_json(loads(val.value))
        except Exception:
            print(f"  {val.value!r}")


def cmd_kvstore_peers(client: CtrlClient, args) -> None:
    peers = client.call("getKvStorePeersArea", area=args.area)
    rows = [
        [name, spec.peer_addr, spec.ctrl_port, spec.state.name]
        for name, spec in sorted(peers.items())
    ]
    _table(rows, ["Peer", "Address", "Port", "State"])


def cmd_kvstore_summary(client: CtrlClient, args) -> None:
    _print_json(client.call("getKvStoreAreaSummary"))


def cmd_kvstore_floodtopo(client: CtrlClient, args) -> None:
    """DUAL SPT view (reference: OpenrCtrlHandler
    semifuture_getSpanningTreeInfos, OpenrCtrlHandler.h:220)."""
    infos = client.call("getSpanningTreeInfos", area=args.area)
    print(f"flood-root: {infos.flood_root_id}")
    print(f"flood-peers: {', '.join(infos.flood_peers) or '(full mesh)'}")
    rows = [
        [root, "PASSIVE" if spt.passive else "ACTIVE", spt.cost,
         spt.parent or "-", ",".join(spt.children) or "-"]
        for root, spt in sorted(infos.infos.items())
    ]
    _table(rows, ["Root", "State", "Cost", "Parent", "Children"])


def cmd_kvstore_snoop(client: CtrlClient, args) -> None:
    """Stream KvStore deltas (reference: KvStoreSnooper tool)."""
    for pub in client.stream(
        "subscribeKvStore", area=args.area, prefixes=args.prefixes or []
    ):
        for key, val in sorted(pub.key_vals.items()):
            print(f"UPDATE {key} v={val.version} from={val.originator_id}")
        for key in pub.expired_keys:
            print(f"EXPIRE {key}")


def cmd_decision_routes(client: CtrlClient, args) -> None:
    db = client.call("getRouteDb", node=args.node)
    print(f"== Unicast Routes ({len(db.unicast_routes)}) ==")
    for prefix, entry in sorted(db.unicast_routes.items()):
        print(f"> {prefix}")
        for nh in sorted(entry.nexthops, key=lambda n: n.address):
            label = f" mpls {nh.mpls_action.action.name}" if nh.mpls_action else ""
            print(
                f"  via {nh.address}%{nh.if_name} metric {nh.metric}{label}"
            )
    if db.mpls_routes:
        print(f"== MPLS Routes ({len(db.mpls_routes)}) ==")
        for label, entry in sorted(db.mpls_routes.items()):
            nhs = ", ".join(
                f"{nh.address}({nh.mpls_action.action.name if nh.mpls_action else '-'})"
                for nh in sorted(entry.nexthops, key=lambda n: n.address)
            )
            print(f"> {label} via {nhs}")


def cmd_decision_fleet_routes(client: CtrlClient, args) -> None:
    """Fleet-wide route dump: every router's unicast routes from ONE
    reduced all-sources device round (getFleetRoutes)."""
    dbs = client.call("getFleetRoutes", nodes=args.nodes or None)
    for node in sorted(dbs):
        db = dbs[node]
        print(
            f"== {node}: {len(db.unicast_routes)} unicast, "
            f"{len(db.mpls_routes)} mpls =="
        )
        if not args.summary:
            for prefix, entry in sorted(db.unicast_routes.items()):
                nhs = ", ".join(
                    f"{nh.neighbor_node_name or nh.address}"
                    for nh in sorted(entry.nexthops, key=lambda n: n.address)
                )
                print(f"> {prefix} via {nhs}")


def cmd_decision_adj(client: CtrlClient, args) -> None:
    dbs = client.call(
        "getDecisionAdjacenciesFiltered", areas=[args.area] if args.area else None
    )
    rows = []
    for db in sorted(dbs, key=lambda d: d.this_node_name):
        for adj in db.adjacencies:
            rows.append(
                [
                    db.this_node_name,
                    adj.other_node_name,
                    adj.if_name,
                    adj.metric,
                    "overloaded" if db.is_overloaded else "",
                ]
            )
    _table(rows, ["Node", "Neighbor", "Interface", "Metric", "Flags"])


def cmd_decision_received_routes(client: CtrlClient, args) -> None:
    _print_json(client.call("getReceivedRoutesFiltered", prefixes=args.prefixes))


def cmd_decision_what_if(client: CtrlClient, args) -> None:
    """Batched SRLG what-if failure analysis.  Each LINK is "nodeA/nodeB";
    by default all listed links form ONE scenario (a shared-risk group);
    --each makes every link its own scenario."""
    links = []
    for spec in args.links:
        if "/" not in spec:
            print(f"error: bad link spec {spec!r} (expected nodeA/nodeB)")
            raise SystemExit(2)
        links.append(tuple(spec.split("/", 1)))
    scenarios = [[list(l)] for l in links] if args.each else [[list(l) for l in links]]
    rows = client.call(
        "decisionWhatIf", scenarios=scenarios, area=args.area
    )
    table = []
    for row in rows:
        table.append(
            [
                row["scenario"],
                " ".join(f"{a}/{b}" for a, b in row["links"]) or "-",
                row["newly_unreachable_pairs"],
                row["degraded_pairs"],
                " ".join(f"{a}/{b}" for a, b in row["unknown_links"]) or "-",
            ]
        )
    _table(
        table,
        ["Scenario", "Failed links", "Unreachable pairs", "Degraded pairs", "Unknown"],
    )


def cmd_decision_tilfa(client: CtrlClient, args) -> None:
    """Per-adjacency TI-LFA backup analysis for a node."""
    report = client.call("decisionTiLfa", node=args.node, area=args.area)
    if "error" in report:
        print(f"error: {report['error']}")
        return
    print(f"node: {report['node']}")
    rows = []
    for adj in report["adjacencies"]:
        rows.append(
            [
                adj["neighbor"],
                adj["protected_destinations"],
                adj["unprotected_count"],
            ]
        )
    _table(rows, ["Failed adjacency", "Protected dests", "Unprotected dests"])
    if args.verbose:
        for adj in report["adjacencies"]:
            print(f"-- via {adj['neighbor']} failed:")
            for dest, hops in sorted(adj["backup_first_hops"].items()):
                print(f"   {dest}: {', '.join(hops) or '(none)'}")


def cmd_decision_path(client: CtrlClient, args) -> None:
    """Client-side path computation over adj DBs (reference:
    breeze decision path, openr/py/openr/cli/commands/decision.py:293)."""
    from ..decision.link_state import LinkState

    dbs = client.call("getDecisionAdjacenciesFiltered", areas=None)
    ls = LinkState(area=dbs[0].area if dbs else "0")
    for db in dbs:
        ls.update_adjacency_database(db)
    src = args.src or client.call("getMyNodeName")
    result = ls.get_spf_result(src)
    if args.dst not in result:
        print(f"no path from {src} to {args.dst}")
        sys.exit(1)
    # walk one shortest path backwards
    hops = [args.dst]
    node = args.dst
    while node != src:
        node = result[node].path_links[0][1]
        hops.append(node)
    hops.reverse()
    print(
        f"path from {src} to {args.dst} (metric {result[args.dst].metric}): "
        + " -> ".join(hops)
    )


def cmd_fib_routes(client: CtrlClient, args) -> None:
    db = client.call("getRouteDbFib")
    for route in sorted(db["unicastRoutes"], key=lambda r: r.dest):
        nhs = ", ".join(
            f"{nh.address}%{nh.if_name}" for nh in route.next_hops
        )
        print(f"{route.dest} via {nhs}")
    for route in sorted(db["mplsRoutes"], key=lambda r: r.top_label):
        print(f"label {route.top_label} nexthops {len(route.next_hops)}")


def cmd_fib_validate(client: CtrlClient, args) -> None:
    """Audit daemon FIB state against the platform agent's programmed
    table (reference: breeze fib validate against the FibService agent)."""
    from ..platform import TcpFibAgent

    # programmedOnly: do_not_install prefixes and (with segment routing
    # off) label routes are tracked by Fib but never sent to the agent
    db = client.call("getRouteDbFib", programmedOnly=True)
    daemon_unicast = {r.dest: r for r in db["unicastRoutes"]}
    daemon_mpls = {r.top_label: r for r in db["mplsRoutes"]}

    agent = TcpFibAgent(host=args.agent_host, port=args.agent_port)
    try:
        agent_unicast = {
            r.dest: r for r in agent.get_route_table_by_client(args.client_id)
        }
        agent_mpls = {
            r.top_label: r
            for r in agent.get_mpls_route_table_by_client(args.client_id)
        }
    except OSError as e:
        print(
            f"cannot reach fib agent at "
            f"[{args.agent_host}]:{args.agent_port}: {e}"
        )
        raise SystemExit(1)
    finally:
        agent.close()

    ok = True
    for label, daemon_table, agent_table in (
        ("unicast", daemon_unicast, agent_unicast),
        ("mpls", daemon_mpls, agent_mpls),
    ):
        missing = sorted(set(daemon_table) - set(agent_table))
        extra = sorted(set(agent_table) - set(daemon_table))
        mismatched = sorted(
            k
            for k in set(daemon_table) & set(agent_table)
            if set(daemon_table[k].next_hops) != set(agent_table[k].next_hops)
        )
        for kind, keys in (
            ("missing from agent", missing),
            ("extra in agent", extra),
            ("nexthop mismatch", mismatched),
        ):
            for key in keys:
                ok = False
                print(f"FAIL [{label}] {kind}: {key}")
        print(
            f"{label}: daemon={len(daemon_table)} agent={len(agent_table)}"
        )
    print("PASS" if ok else "FAIL")
    if not ok:
        raise SystemExit(1)


def cmd_fib_perf(client: CtrlClient, args) -> None:
    for perf in client.call("getPerfDb"):
        print(f"== convergence {perf.total_duration_ms()}ms ==")
        base = perf.events[0].unix_ts_ms if perf.events else 0
        for event in perf.events:
            print(f"  {event.event_name:<32} +{event.unix_ts_ms - base}ms")


def cmd_lm_links(client: CtrlClient, args) -> None:
    interfaces = client.call("getInterfaces")
    rows = [
        [name, "UP" if info.is_up else "DOWN", info.if_index, ",".join(info.networks)]
        for name, info in sorted(interfaces.items())
    ]
    _table(rows, ["Interface", "Status", "Index", "Addresses"])
    state = client.call("getLinkMonitorState")
    print(f"\nnode overloaded: {state['is_overloaded']}")
    if state["overloaded_links"]:
        print(f"overloaded links: {', '.join(state['overloaded_links'])}")
    if state["link_metric_overrides"]:
        print(f"metric overrides: {state['link_metric_overrides']}")


def cmd_lm_set_node_overload(client: CtrlClient, args) -> None:
    client.call("setNodeOverload")
    print("node overload set")


def cmd_lm_unset_node_overload(client: CtrlClient, args) -> None:
    client.call("unsetNodeOverload")
    print("node overload unset")


def cmd_lm_set_link_overload(client: CtrlClient, args) -> None:
    client.call("setInterfaceOverload", interface=args.interface)
    print(f"link overload set on {args.interface}")


def cmd_lm_unset_link_overload(client: CtrlClient, args) -> None:
    client.call("unsetInterfaceOverload", interface=args.interface)
    print(f"link overload unset on {args.interface}")


def cmd_lm_set_link_metric(client: CtrlClient, args) -> None:
    client.call(
        "setInterfaceMetric", interface=args.interface, metric=args.metric
    )
    print(f"metric {args.metric} set on {args.interface}")


def cmd_lm_unset_link_metric(client: CtrlClient, args) -> None:
    client.call("unsetInterfaceMetric", interface=args.interface)
    print(f"metric override removed from {args.interface}")


def cmd_prefixmgr_view(client: CtrlClient, args) -> None:
    entries = client.call("getPrefixes")
    rows = [
        [
            e.prefix,
            e.type.name,
            e.forwarding_type.name,
            e.forwarding_algorithm.name,
        ]
        for e in sorted(entries, key=lambda e: e.prefix)
    ]
    _table(rows, ["Prefix", "Type", "Forwarding", "Algorithm"])


def cmd_prefixmgr_advertise(client: CtrlClient, args) -> None:
    client.call(
        "advertisePrefixes",
        type=PrefixType[args.type],
        prefixes=[PrefixEntry(prefix=p, type=PrefixType[args.type]) for p in args.prefixes],
    )
    print(f"advertised {len(args.prefixes)} prefixes")


def cmd_prefixmgr_withdraw(client: CtrlClient, args) -> None:
    client.call(
        "withdrawPrefixes", type=PrefixType[args.type], prefixes=args.prefixes
    )
    print(f"withdrew {len(args.prefixes)} prefixes")


def cmd_prefixmgr_originated(client: CtrlClient, args) -> None:
    _print_json(client.call("getOriginatedPrefixes"))


def cmd_spark_neighbors(client: CtrlClient, args) -> None:
    neighbors = client.call("getSparkNeighbors")
    rows = [
        [
            n["nodeName"],
            n["state"],
            n["ifName"],
            n["remoteIfName"],
            n["area"],
            n["rttUs"],
        ]
        for n in neighbors
    ]
    _table(rows, ["Neighbor", "State", "Local If", "Remote If", "Area", "RTT (us)"])


def cmd_monitor_counters(client: CtrlClient, args) -> None:
    counters = (
        client.call("getRegexCounters", regex=args.regex)
        if args.regex
        else client.call("getCounters")
    )
    for key in sorted(counters):
        print(f"{key} : {counters[key]}")


def _print_span(span: dict, depth: int = 0) -> None:
    tags = " ".join(f"{k}={v}" for k, v in sorted(span["tags"].items()))
    dur = span["duration_us"]
    dur_s = "?" if dur is None else f"{dur}us"
    pad = "  " * depth
    print(f"{pad}{span['name']} [{dur_s}]" + (f" {tags}" if tags else ""))
    for child in sorted(span["children"], key=lambda c: c["t_offset_us"]):
        _print_span(child, depth + 1)


def cmd_monitor_traces(client: CtrlClient, args) -> None:
    traces = client.call("dumpTraces", n=args.n)
    if not traces:
        print("no traces (is the daemon running with OPENR_TRACE=1?)")
        return
    for i, root in enumerate(traces):
        if i:
            print()
        _print_span(root)


def cmd_monitor_histograms(client: CtrlClient, args) -> None:
    counters = client.call("getCounters")
    families = sorted(
        k[: -len(".p50_us")] for k in counters if k.endswith(".p50_us")
    )
    if not families:
        print("no histogram families exported")
        return
    rows = [
        [
            fam,
            counters.get(f"{fam}.hist_us.count", 0),
            counters[f"{fam}.p50_us"],
            counters.get(f"{fam}.p99_us", 0),
            counters.get(f"{fam}.p999_us", 0),
        ]
        for fam in families
    ]
    _table(rows, ["Family", "Count", "p50 (us)", "p99 (us)", "p99.9 (us)"])


def cmd_config(client: CtrlClient, args) -> None:
    _print_json(client.call("getRunningConfig"))


def cmd_config_dryrun(client: CtrlClient, args) -> None:
    """Validate a config file through the daemon WITHOUT applying it
    (reference: dryrunConfig RPC, OpenrCtrlHandler.h:69-78)."""
    try:
        with open(args.file) as f:
            contents = f.read()
    except OSError as exc:
        # distinguish a bad file path from main()'s "cannot reach ctrl
        # server" OSError handler
        print(f"cannot read {args.file}: {exc}")
        raise SystemExit(2)
    try:
        parsed = client.call("dryrunConfig", file_contents=contents)
    except RuntimeError as exc:
        print(f"INVALID: {exc}")
        raise SystemExit(1)
    print("VALID")
    if args.verbose:
        _print_json(parsed)


def cmd_kvstore_compare(client: CtrlClient, args) -> None:
    """Diff this node's store against another node's (reference:
    breeze kvstore compare, openr/py/openr/cli/commands/kvstore.py)."""
    other = CtrlClient(args.other_host, args.other_port, tls=client.tls)
    try:
        # hash_only: the compare is on (version, originator, hash) —
        # fetching every value blob from both nodes would be waste
        mine = client.call(
            "getKvStoreKeyValsFilteredArea",
            area=args.area,
            match_all=True,
            hash_only=True,
        ).key_vals
        try:
            theirs = other.call(
                "getKvStoreKeyValsFilteredArea",
                area=args.area,
                match_all=True,
                hash_only=True,
            ).key_vals
        except OSError as exc:
            print(
                f"cannot reach remote ctrl server at "
                f"[{args.other_host}]:{args.other_port}: {exc}"
            )
            raise SystemExit(2)
    finally:
        other.close()
    rows = []
    for key in sorted(set(mine) | set(theirs)):
        a, b = mine.get(key), theirs.get(key)
        if a is None:
            rows.append([key, "MISSING-LOCAL", "", f"v{b.version}@{b.originator_id}"])
        elif b is None:
            rows.append([key, "MISSING-REMOTE", f"v{a.version}@{a.originator_id}", ""])
        elif (a.version, a.originator_id, a.hash) != (
            b.version,
            b.originator_id,
            b.hash,
        ):
            rows.append(
                [
                    key,
                    "DIFFERS",
                    f"v{a.version}@{a.originator_id}",
                    f"v{b.version}@{b.originator_id}",
                ]
            )
    if not rows:
        print(f"stores agree on {len(mine)} keys")
        return
    _table(rows, ["Key", "Status", "Local", "Remote"])
    raise SystemExit(1)


def cmd_fib_mpls(client: CtrlClient, args) -> None:
    routes = client.call(
        "getMplsRoutesFiltered", labels=args.labels or None
    )
    rows = [
        [
            r.top_label,
            ", ".join(
                f"{nh.address}@{nh.if_name or '-'}"
                + (
                    f" {nh.mpls_action.action.name}"
                    if nh.mpls_action is not None
                    else ""
                )
                for nh in r.next_hops
            ),
        ]
        for r in routes
    ]
    _table(rows, ["Label", "NextHops"])


def cmd_prefixmgr_withdraw_by_type(client: CtrlClient, args) -> None:
    client.call("withdrawPrefixesByType", type=PrefixType[args.type])
    print(f"withdrew all {args.type} prefixes")


def cmd_tech_support(client: CtrlClient, args) -> None:
    """One-shot operational snapshot (reference: breeze tech-support):
    every section is best-effort so a wedged module doesn't hide the
    others."""
    sections = [
        ("VERSION", lambda: client.call("getOpenrVersion")),
        ("NODE", lambda: client.call("getMyNodeName")),
        ("RUNNING CONFIG", lambda: client.call("getRunningConfig")),
        ("INTERFACES", lambda: client.call("getInterfaces")),
        ("SPARK NEIGHBORS", lambda: client.call("getSparkNeighbors")),
        (
            "KVSTORE SUMMARY",
            lambda: client.call("getKvStoreAreaSummary"),
        ),
        ("KVSTORE PEERS", lambda: client.call("getKvStorePeersArea")),
        (
            "ADJACENCIES",
            lambda: client.call("getDecisionAdjacenciesFiltered"),
        ),
        ("PREFIXES", lambda: client.call("getPrefixes")),
        ("DECISION ROUTES", lambda: client.call("getRouteDb", node="")),
        ("FIB ROUTES", lambda: client.call("getRouteDbFib")),
        ("FIB PERF", lambda: client.call("getPerfDb")),
        ("COUNTERS", lambda: client.call("getCounters")),
    ]
    for title, fetch in sections:
        print(f"\n======== {title} ========")
        try:
            _print_json(fetch())
        except Exception as exc:  # a dead module must not hide the rest
            print(f"<unavailable: {exc}>")


def cmd_version(client: CtrlClient, args) -> None:
    _print_json(client.call("getOpenrVersion"))


# -- parser ------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="breeze", description=__doc__)
    parser.add_argument("-H", "--host", default="::1")
    parser.add_argument("-p", "--port", type=int, default=2018)
    # mTLS against a TLS-enabled ctrl server (cert CN must pass its ACL)
    parser.add_argument("--tls-cert", default=None)
    parser.add_argument("--tls-key", default=None)
    parser.add_argument("--tls-ca", default=None)
    sub = parser.add_subparsers(dest="group", required=True)

    kv = sub.add_parser("kvstore").add_subparsers(dest="cmd", required=True)
    p = kv.add_parser("keys")
    p.add_argument("--prefix", default="")
    p.add_argument("--area", default="0")
    p.set_defaults(fn=cmd_kvstore_keys)
    p = kv.add_parser("keyvals")
    p.add_argument("keys", nargs="+")
    p.add_argument("--area", default="0")
    p.set_defaults(fn=cmd_kvstore_keyvals)
    p = kv.add_parser("peers")
    p.add_argument("--area", default="0")
    p.set_defaults(fn=cmd_kvstore_peers)
    p = kv.add_parser("summary")
    p.set_defaults(fn=cmd_kvstore_summary)
    p = kv.add_parser("floodtopo")
    p.add_argument("--area", default="0")
    p.set_defaults(fn=cmd_kvstore_floodtopo)
    p = kv.add_parser("compare")
    p.add_argument("other_host")
    p.add_argument("--other-port", type=int, default=2018)
    p.add_argument("--area", default="0")
    p.set_defaults(fn=cmd_kvstore_compare)
    p = kv.add_parser("snoop")
    p.add_argument("--area", default="0")
    p.add_argument("--prefixes", nargs="*")
    p.set_defaults(fn=cmd_kvstore_snoop)

    dec = sub.add_parser("decision").add_subparsers(dest="cmd", required=True)
    p = dec.add_parser("routes")
    p.add_argument("--node", default="")
    p.set_defaults(fn=cmd_decision_routes)
    p = dec.add_parser("fleet-routes")
    p.add_argument("--nodes", nargs="*")
    p.add_argument("--summary", action="store_true")
    p.set_defaults(fn=cmd_decision_fleet_routes)
    p = dec.add_parser("adj")
    p.add_argument("--area", default="")
    p.set_defaults(fn=cmd_decision_adj)
    p = dec.add_parser("received-routes")
    p.add_argument("prefixes", nargs="*")
    p.set_defaults(fn=cmd_decision_received_routes)
    p = dec.add_parser("path")
    p.add_argument("--src", default="")
    p.add_argument("dst")
    p.set_defaults(fn=cmd_decision_path)
    p = dec.add_parser("what-if")
    p.add_argument("links", nargs="+", metavar="LINK", help="nodeA/nodeB")
    p.add_argument("--each", action="store_true")
    p.add_argument("--area", default="0")
    p.set_defaults(fn=cmd_decision_what_if)
    p = dec.add_parser("tilfa")
    p.add_argument("node", nargs="?", default="")
    p.add_argument("--area", default="0")
    p.add_argument("-v", "--verbose", action="store_true")
    p.set_defaults(fn=cmd_decision_tilfa)

    fib = sub.add_parser("fib").add_subparsers(dest="cmd", required=True)
    p = fib.add_parser("validate")
    p.add_argument("--agent-host", default="::1")
    p.add_argument("--agent-port", type=int, default=60100)
    p.add_argument("--client-id", type=int, default=FIB_CLIENT_OPENR)
    p.set_defaults(fn=cmd_fib_validate)
    p = fib.add_parser("routes")
    p.set_defaults(fn=cmd_fib_routes)
    p = fib.add_parser("mpls")
    p.add_argument("--labels", nargs="*", type=int, default=None)
    p.set_defaults(fn=cmd_fib_mpls)
    p = fib.add_parser("perf")
    p.set_defaults(fn=cmd_fib_perf)

    lm = sub.add_parser("lm").add_subparsers(dest="cmd", required=True)
    p = lm.add_parser("links")
    p.set_defaults(fn=cmd_lm_links)
    p = lm.add_parser("set-node-overload")
    p.set_defaults(fn=cmd_lm_set_node_overload)
    p = lm.add_parser("unset-node-overload")
    p.set_defaults(fn=cmd_lm_unset_node_overload)
    p = lm.add_parser("set-link-overload")
    p.add_argument("interface")
    p.set_defaults(fn=cmd_lm_set_link_overload)
    p = lm.add_parser("unset-link-overload")
    p.add_argument("interface")
    p.set_defaults(fn=cmd_lm_unset_link_overload)
    p = lm.add_parser("set-link-metric")
    p.add_argument("interface")
    p.add_argument("metric", type=int)
    p.set_defaults(fn=cmd_lm_set_link_metric)
    p = lm.add_parser("unset-link-metric")
    p.add_argument("interface")
    p.set_defaults(fn=cmd_lm_unset_link_metric)

    pm = sub.add_parser("prefixmgr").add_subparsers(dest="cmd", required=True)
    p = pm.add_parser("view")
    p.set_defaults(fn=cmd_prefixmgr_view)
    p = pm.add_parser("advertise")
    p.add_argument("prefixes", nargs="+")
    p.add_argument("--type", default="BREEZE")
    p.set_defaults(fn=cmd_prefixmgr_advertise)
    p = pm.add_parser("withdraw")
    p.add_argument("prefixes", nargs="+")
    p.add_argument("--type", default="BREEZE")
    p.set_defaults(fn=cmd_prefixmgr_withdraw)
    p = pm.add_parser("withdraw-by-type")
    p.add_argument("--type", required=True)
    p.set_defaults(fn=cmd_prefixmgr_withdraw_by_type)
    p = pm.add_parser("originated")
    p.set_defaults(fn=cmd_prefixmgr_originated)

    spark = sub.add_parser("spark").add_subparsers(dest="cmd", required=True)
    p = spark.add_parser("neighbors")
    p.set_defaults(fn=cmd_spark_neighbors)

    mon = sub.add_parser("monitor").add_subparsers(dest="cmd", required=True)
    p = mon.add_parser("counters")
    p.add_argument("--regex", default="")
    p.set_defaults(fn=cmd_monitor_counters)
    p = mon.add_parser("traces")
    p.add_argument("-n", type=int, default=16)
    p.set_defaults(fn=cmd_monitor_traces)
    p = mon.add_parser("histograms")
    p.set_defaults(fn=cmd_monitor_histograms)

    cfg = sub.add_parser("config").add_subparsers(dest="cmd")
    p = cfg.add_parser("show")
    p.set_defaults(fn=cmd_config)
    p = cfg.add_parser("dryrun")
    p.add_argument("file")
    p.add_argument("-v", "--verbose", action="store_true")
    p.set_defaults(fn=cmd_config_dryrun)
    # bare `breeze config` keeps showing the running config
    sub.choices["config"].set_defaults(fn=cmd_config, cmd=None)
    p = sub.add_parser("tech-support")
    p.set_defaults(fn=cmd_tech_support)
    p = sub.add_parser("version")
    p.set_defaults(fn=cmd_version)
    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    tls = None
    if args.tls_cert or args.tls_key or args.tls_ca:
        if not (args.tls_cert and args.tls_key and args.tls_ca):
            print("error: --tls-cert, --tls-key and --tls-ca are all required")
            return 2
        from ..ctrl.tls import TlsConfig

        tls = TlsConfig(
            cert_path=args.tls_cert,
            key_path=args.tls_key,
            ca_path=args.tls_ca,
        )
    client = CtrlClient(args.host, args.port, tls=tls)
    try:
        args.fn(client, args)
        return 0
    except OSError as e:
        # covers ConnectionError, ssl.SSLError (cert rejected / wrong CA),
        # and FileNotFoundError for bad cert paths
        print(f"cannot reach ctrl server at [{args.host}]:{args.port}: {e}")
        return 1
    finally:
        client.close()


if __name__ == "__main__":
    sys.exit(main())
