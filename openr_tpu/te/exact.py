"""Exact uint32 evaluation of a TE metric candidate.

The acceptance gate of the TE optimizer: a rounded integer metric
vector is scored by running the SAME exact solver the decision plane
publishes from — `ops.allsources.reduced_all_sources` over a reverse
SpfRunner built for the candidate metrics — and pushing the demand
matrix over the resulting hard-ECMP splits (equal division over
min-cost out-edges, the reference nextHops rule) in distance order.
No float enters the distance computation; the load push is plain host
numpy over the integer distances.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from ..ops import allsources as asrc

# host-int mirrors of the kernel sentinels (ops.sssp exports jnp scalars)
INF32 = 1 << 30
INF16 = 40000


def _normalize_dist(dist, n_cap: int) -> np.ndarray:
    """reduced_all_sources dist -> int64 [n_cap, P] with INF32 sentinel
    (uint16 small-distance mode re-widens; banded kernels return n_nodes
    rows, the ELL fallback node_capacity — pad the former)."""
    d = np.asarray(dist)
    if d.dtype == np.uint16:
        d = np.where(d >= INF16, np.int64(INF32), d.astype(np.int64))
    else:
        d = d.astype(np.int64)
    if d.shape[0] < n_cap:
        pad = np.full((n_cap - d.shape[0], d.shape[1]), INF32, np.int64)
        d = np.concatenate([d, pad], axis=0)
    return d


def push_loads(
    dist: np.ndarray,  # [>=n_nodes, P] int64, INF32 sentinel
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    edge_metric: np.ndarray,
    edge_up: np.ndarray,
    node_overloaded: np.ndarray,
    n_edges: int,
    demand: np.ndarray,  # [n_cap, P] float
) -> np.ndarray:
    """Per-edge load [n_edges] under exact ECMP splits.

    For every destination column: an edge u->v is a next-hop edge iff
    metric + dist(v) == dist(u) (LFA-free equality,
    openr/decision/Decision.cpp:1296-1300) with the drain exception
    (overloaded v relays only as the destination itself).  Demand is
    pushed in strictly descending dist(u) order — next-hop edges
    strictly decrease the distance, so one vectorized pass per distance
    level conserves flow exactly."""
    e = int(n_edges)
    src = np.asarray(edge_src[:e], dtype=np.int64)
    dst = np.asarray(edge_dst[:e], dtype=np.int64)
    met = np.asarray(edge_metric[:e], dtype=np.int64)
    up = np.asarray(edge_up[:e], dtype=bool)
    over = np.asarray(node_overloaded, dtype=bool)
    load = np.zeros(e, dtype=np.float64)
    for p in range(dist.shape[1]):
        d = dist[:, p]
        ecmp = (
            up
            & (d[src] > 0)
            & (d[src] < INF32)
            & (d[dst] < INF32)
            & (met + d[dst] == d[src])
            & ~(over[dst] & (d[dst] > 0))
        )
        eidx = np.nonzero(ecmp)[0]
        if not len(eidx):
            continue
        deg = np.bincount(src[eidx], minlength=len(over))
        f = np.asarray(demand[:, p], dtype=np.float64).copy()
        order = np.argsort(-d[src[eidx]], kind="stable")
        eidx = eidx[order]
        dsrc = d[src[eidx]]
        _, starts = np.unique(-dsrc, return_index=True)
        bounds = np.append(starts, len(eidx))
        for gi in range(len(starts)):
            es = eidx[bounds[gi]: bounds[gi + 1]]
            fe = f[src[es]] / deg[src[es]]
            load[es] += fe
            np.add.at(f, dst[es], fe)
    return load


class ExactEvaluator:
    """Scores integer metric candidates for one (topology, demand) pair.

    Structure-only artifacts (reversed edge permutation, banded
    decomposition, forward out-ELL) are built once; each ``evaluate``
    builds the candidate's reversed ELL + runner (a metric change IS a
    topology restage) and runs the exact product — through the
    residency engine's dispatch front-end when one is attached, so
    chaos faults and device.engine.* accounting apply like any fleet
    product."""

    def __init__(
        self,
        edge_src: np.ndarray,
        edge_dst: np.ndarray,
        edge_up: np.ndarray,
        node_overloaded: np.ndarray,
        n_edges: int,
        n_nodes: int,
        dest_ids: np.ndarray,
        demand: np.ndarray,
        capacity: np.ndarray,
        engine=None,
    ) -> None:
        from ..ops.banded import build_banded

        self.edge_src = np.asarray(edge_src, dtype=np.int32)
        self.edge_dst = np.asarray(edge_dst, dtype=np.int32)
        self.edge_up = np.asarray(edge_up, dtype=bool)
        self.node_overloaded = np.asarray(node_overloaded, dtype=bool)
        self.n_edges = int(n_edges)
        self.n_nodes = int(n_nodes)
        self.n_cap = len(self.node_overloaded)
        self.e_cap = len(self.edge_src)
        self.dest_ids = np.asarray(dest_ids, dtype=np.int32)
        self.demand = np.asarray(demand, dtype=np.float64)
        self.capacity = np.asarray(capacity, dtype=np.float64)
        self.engine = engine
        e = self.n_edges
        pad = self.n_cap - 1
        # reversed-edge layout, sorted by (dst, src) like every mirror
        rsrc, rdst = self.edge_dst[:e], self.edge_src[:e]
        self._rev_order = np.lexsort((rsrc, rdst))
        self._rev_src = np.full(self.e_cap, pad, dtype=np.int32)
        self._rev_dst = np.full(self.e_cap, pad, dtype=np.int32)
        self._rev_up = np.zeros(self.e_cap, dtype=bool)
        self._rev_src[:e] = rsrc[self._rev_order]
        self._rev_dst[:e] = rdst[self._rev_order]
        self._rev_up[:e] = self.edge_up[:e][self._rev_order]
        self._rev_banded = build_banded(
            self._rev_src, self._rev_dst, e, self.n_nodes
        )
        self._out = asrc.build_out_ell(
            self.edge_src, self.edge_dst, e, self.n_nodes
        )
        self._hint: Optional[int] = None

    def distances(self, metric: np.ndarray) -> np.ndarray:
        """Exact int64 [n_cap, P] distances for integer metrics [E_cap]."""
        from ..ops.banded import SpfRunner
        from ..ops.sssp import build_ell

        e = self.n_edges
        met = np.asarray(metric, dtype=np.int32)
        rev_metric = np.ones(self.e_cap, dtype=np.int32)
        rev_metric[:e] = met[:e][self._rev_order]
        ell = build_ell(
            self._rev_src, self._rev_dst, rev_metric, self._rev_up,
            self.node_overloaded, e,
        )
        runner = SpfRunner(
            ell, self._rev_banded, self._rev_src, self._rev_dst,
            rev_metric, self._rev_up, self.node_overloaded, e,
        )
        if self._hint is not None:
            runner.hint = self._hint
        runner.stage()
        if self.engine is not None:
            dist, _bitmap, ok = self.engine.dispatch(
                "te_exact",
                asrc.reduced_all_sources,
                self.dest_ids, runner, self._out,
                met, self.edge_up, self.node_overloaded,
            )
        else:
            dist, _bitmap, ok = asrc.reduced_all_sources(
                self.dest_ids, runner, self._out,
                met, self.edge_up, self.node_overloaded,
            )
        # one explicit batched fetch: dist is consumed on the host by the
        # load push anyway, and ok must not sync implicitly via assert
        dist_h, ok_h = jax.device_get((dist, ok))
        assert bool(
            ok_h
        ), "te: exact reverse SSSP did not reach its fixed point"
        self._hint = runner.hint  # learned sweep depth carries over
        return _normalize_dist(dist_h, self.n_cap)

    def evaluate(self, metric: np.ndarray) -> float:
        """Exact max-utilization of an integer metric candidate."""
        dist = self.distances(metric)
        load = push_loads(
            dist, self.edge_src, self.edge_dst, metric, self.edge_up,
            self.node_overloaded, self.n_edges, self.demand,
        )
        util = load / self.capacity[: self.n_edges]
        util = np.where(self.edge_up[: self.n_edges], util, 0.0)
        return float(util.max()) if len(util) else 0.0
