"""Differentiable traffic engineering on the fleet product.

The relax pipeline answers routing queries; this package *optimizes*
the network: link metrics become parameters, a smoothed (softmin /
log-sum-exp, temperature-annealed) float32 variant of the fleet
min-plus product feeds a traffic-matrix load model, and projected
gradient descent minimizes max-utilization on device.  Rounded integer
candidates are validated through the EXACT uint32 solver
(ops.allsources.reduced_all_sources) and only an exactly-improving
candidate is ever published — the smoothed model is a search direction,
never a source of truth.  Ground: gradient-descent TE with learned
differentiable routing (PAPERS.md, arxiv 2209.10380).
"""

from .optimizer import (  # noqa: F401
    TE_COUNTER_KEYS,
    TeOptimizer,
    TeProblem,
    TeResult,
    hill_climb,
)
