"""Smoothed float32 fleet product + traffic load model (the TE forward
pass).

Three jit roots, the ONLY float-allowlisted programs in the tree
(pyproject `program_float_allowed`); everything they feed downstream —
candidate acceptance, publication — goes through the exact uint32
solver in te.exact, never through these.

- `soft_sssp` — temperature-annealed softmin relaxation of the reverse
  all-sources product: dist[v, p] smoothly approximates the exact
  min-plus distance v -> dest p, and converges to it as tau -> 0
  (softmin <= min <= softmin + tau * log(#paths)).  Same orientation
  and drain rule as ops.allsources: an overloaded node relays nothing
  but remains a valid endpoint (its own distance-0 row).
- `soft_objective_value` — the load model + objective without the
  backward pass (temperature sweeps, acceptance diagnostics).
- `te_descent_step` — one fused Adam step: value_and_grad of the
  objective w.r.t. the metric vector, moment updates, and projection
  onto the [lo, hi] box, all in one program so the descent loop stays
  on device between exact-validation round trips.

Load model: demand[n, p] (traffic from node n to destination p) splits
at every hop over soft-ECMP gate weights
``w(e) = exp(-(metric(e) + dist(v,p) - dist(u,p)) / tau)`` (normalized
per source node), propagated a fixed number of hop-sweeps; per-link
utilization is the dest-summed load over capacity, and the objective is
the log-sum-exp softmax of utilization over links — max-utilization
with a usable gradient everywhere.

Numerical discipline: every softmin is computed against a
stop-gradient exact-min shift, so the log-sum-exp argument always
contains a term with exponent 0 — no underflow-to-log(0), no NaN in
the backward pass, at any temperature in the schedule.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# float INF sentinel: far above any reachable distance (metrics are
# bounded by the integer box, paths by the sweep count) yet small enough
# that INF / tau never overflows exp's argument range in float32
INF_F = np.float32(1.0e7)

# strong-typed float32 scalars for every constant that enters a traced
# program: a bare Python float literal traces as a WEAK float32, which
# the program-dtype auditor bans even for float-allowlisted roots (weak
# types are how accidental promotions propagate)
_ZERO = np.float32(0.0)
_HALF = np.float32(0.5)
_ONE = np.float32(1.0)
_NEG_INF = np.float32(-np.inf)
_TINY = np.float32(1e-20)

# Adam moments (fixed; the schedule knobs that matter — lr, tau — are
# traced operands so one compiled program serves the whole anneal)
_ADAM_B1 = np.float32(0.9)
_ADAM_B2 = np.float32(0.999)
_ADAM_EPS = np.float32(1e-8)


def _softmin_sweep(dist, edge_src, edge_dst, metric_f, edge_up,
                   node_overloaded, dest_ids, tau):
    """One softmin relaxation sweep of dist [N_cap, P] (float32)."""
    n_cap = dist.shape[0]
    p_dim = dist.shape[1]
    dv = dist[edge_dst]  # [E, P]
    # drain rule: an overloaded node is excluded as a relay unless it is
    # the destination itself (its distance-0 row) — metrics are >= 1 so
    # the 0.5 threshold is exact even under softmin erosion
    drained = node_overloaded[edge_dst][:, None] & (dv > _HALF)
    ok = edge_up[:, None] & ~drained
    cand = jnp.where(ok, metric_f[:, None] + dv, INF_F)
    # pure Bellman relaxation: new_u = softmin_e(metric_e + dist_v) over
    # u's out-edges ONLY.  Folding the previous dist into the softmin
    # would re-count the incumbent at every sweep and erode all
    # distances by tau*log(2) per iteration; the out-edge-only form has
    # the proper fixed point d_u = -tau*log(sum_paths exp(-len/tau)),
    # which the sweeps approach monotonically from the INF start.
    shift = lax.stop_gradient(
        jax.ops.segment_min(cand, edge_src, num_segments=n_cap)
    )
    contrib = jnp.exp((shift[edge_src] - cand) / tau)
    seg_sum = jax.ops.segment_sum(contrib, edge_src, num_segments=n_cap)
    # no usable out-edge -> stay unreachable; the safe-log double-where
    # keeps NaN out of the backward pass
    reach = seg_sum > _ZERO
    safe = jnp.where(reach, seg_sum, _ONE)
    new = jnp.where(reach, shift - tau * jnp.log(safe), INF_F)
    new = jnp.clip(new, _ZERO, INF_F)
    return new.at[dest_ids, jnp.arange(p_dim)].set(_ZERO)


def _soft_sssp(edge_src, edge_dst, metric_f, edge_up, node_overloaded,
               dest_ids, tau, n_sweeps, n_cap):
    p_dim = dest_ids.shape[0]
    dist = jnp.full((n_cap, p_dim), INF_F, dtype=jnp.float32)
    dist = dist.at[dest_ids, jnp.arange(p_dim)].set(_ZERO)

    def body(carry, _):
        return (
            _softmin_sweep(carry, edge_src, edge_dst, metric_f, edge_up,
                           node_overloaded, dest_ids, tau),
            None,
        )

    # scan (not fori/while): the descent root reverse-differentiates
    # through these sweeps
    dist, _ = lax.scan(body, dist, None, length=n_sweeps)
    return dist


@functools.partial(jax.jit, static_argnames=("n_sweeps",))
def soft_sssp(edge_src, edge_dst, metric_f, edge_up, node_overloaded,
              dest_ids, tau, *, n_sweeps):
    """dist [N_cap, P] float32 — softmin distances to each destination
    column at temperature ``tau`` (a traced scalar: annealing never
    recompiles)."""
    return _soft_sssp(
        edge_src, edge_dst, metric_f, edge_up, node_overloaded, dest_ids,
        jnp.float32(tau), n_sweeps, node_overloaded.shape[0],
    )


def _soft_loads(dist, edge_src, edge_dst, metric_f, edge_up,
                node_overloaded, demand, tau, flow_sweeps):
    """Per-edge dest-summed load [E_cap] from soft-ECMP demand splits."""
    n_cap = dist.shape[0]
    du = dist[edge_src]  # [E, P]
    dv = dist[edge_dst]
    drained = node_overloaded[edge_dst][:, None] & (dv > _HALF)
    # a destination forwards nothing (du ~ 0) and an unreachable source
    # carries nothing; both gates keep the normalizer honest
    fwd = (
        edge_up[:, None]
        & ~drained
        & (du > _HALF)
        & (du < np.float32(INF_F * _HALF))
    )
    gap = metric_f[:, None] + dv - du
    w = jnp.where(fwd, jnp.exp(-gap / tau), _ZERO)
    z = jax.ops.segment_sum(w, edge_src, num_segments=n_cap)
    wn = w / (z[edge_src] + _TINY)

    def body(carry, _):
        f, load = carry
        fe = f[edge_src] * wn  # [E, P] flow pushed over each edge
        return (jax.ops.segment_sum(fe, edge_dst, num_segments=n_cap),
                load + fe), None

    (_, load), _ = lax.scan(
        body, (demand, jnp.zeros_like(w)), None, length=flow_sweeps
    )
    return jnp.sum(load, axis=1)


def _objective(metric_f, edge_src, edge_dst, edge_up, node_overloaded,
               dest_ids, demand, capacity, tau, tau_obj, n_sweeps,
               flow_sweeps):
    """Soft max-utilization: log-sum-exp over per-link utilization."""
    dist = _soft_sssp(
        edge_src, edge_dst, metric_f, edge_up, node_overloaded, dest_ids,
        tau, n_sweeps, node_overloaded.shape[0],
    )
    load = _soft_loads(
        dist, edge_src, edge_dst, metric_f, edge_up, node_overloaded,
        demand, tau, flow_sweeps,
    )
    util = load / capacity
    masked = jnp.where(edge_up, util, _NEG_INF)
    return tau_obj * jax.nn.logsumexp(masked / tau_obj)


@functools.partial(jax.jit, static_argnames=("n_sweeps", "flow_sweeps"))
def soft_objective_value(metric_f, edge_src, edge_dst, edge_up,
                         node_overloaded, dest_ids, demand, capacity,
                         tau, tau_obj, *, n_sweeps, flow_sweeps):
    """Forward-only objective (temperature sweeps, diagnostics)."""
    return _objective(
        metric_f, edge_src, edge_dst, edge_up, node_overloaded, dest_ids,
        demand, capacity, jnp.float32(tau), jnp.float32(tau_obj),
        n_sweeps, flow_sweeps,
    )


@functools.partial(jax.jit, static_argnames=("n_sweeps", "flow_sweeps"))
def te_descent_step(metric_f, adam_m, adam_v, t, edge_src, edge_dst,
                    edge_up, node_overloaded, dest_ids, demand, capacity,
                    tau, tau_obj, lr, lo, hi, *, n_sweeps, flow_sweeps):
    """One projected-Adam step on the metric vector.

    Returns (objective, metric', m', v').  ``t`` (1-based step index,
    float32) drives the bias correction; lr/tau/lo/hi ride as traced
    scalars so the whole anneal reuses one compiled program.
    """
    obj, grad = jax.value_and_grad(_objective)(
        metric_f, edge_src, edge_dst, edge_up, node_overloaded, dest_ids,
        demand, capacity, jnp.float32(tau), jnp.float32(tau_obj),
        n_sweeps, flow_sweeps,
    )
    grad = jnp.where(edge_up, grad, _ZERO)  # padding metrics stay put
    m = _ADAM_B1 * adam_m + (_ONE - _ADAM_B1) * grad
    v = _ADAM_B2 * adam_v + (_ONE - _ADAM_B2) * grad * grad
    mh = m / (_ONE - jnp.power(_ADAM_B1, t))
    vh = v / (_ONE - jnp.power(_ADAM_B2, t))
    step = lr * mh / (jnp.sqrt(vh) + _ADAM_EPS)
    new = jnp.clip(metric_f - step, lo, hi)
    return obj, new, m, v
