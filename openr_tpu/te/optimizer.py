"""TE optimizer loop: descend soft, validate exact, publish only wins.

`TeOptimizer.optimize` runs temperature-annealed projected-Adam on the
smoothed objective (te.soft — the only float programs in the tree),
and after each anneal stage rounds the float metric vector to the
integer box and scores it through the EXACT uint32 solver
(te.exact.ExactEvaluator).  A candidate is accepted only when the
exact max-utilization strictly improves; the best exactly-validated
candidate is what `publish` receives — route state never derives from
the smoothed model.

Epoch discipline: when `epoch_fn`/`expect_epoch` are supplied (the
serving layer pins them at admission), every descent step and every
exact round trip re-checks the topology version and raises
`EpochMismatchError` on a flap — an optimization against a moved
topology aborts loudly (`te.aborted`), it never publishes stale
metrics.

Counters (`te.*`) are pre-seeded at construction and exported through
`OpenrCtrlHandler._all_counters` and the fb303 shim like every module:
steps, round_trips, accepted/rejected candidates, objective
before/after (milli-units, integer wire format), optimize_us.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

from ..device.engine import EpochMismatchError
from .exact import ExactEvaluator

TE_COUNTER_KEYS = (
    "te.runs",
    "te.steps",
    "te.round_trips",
    "te.accepted",
    "te.rejected",
    "te.aborted",
    "te.objective_before_milli",
    "te.objective_after_milli",
    "te.optimize_us",
)

# strict-improvement epsilon for exact objectives (float equality of
# host-float64 utilizations from identical splits is exact; this only
# guards residual rounding in the division)
_IMPROVE_EPS = 1e-12


@dataclass
class TeProblem:
    """One TE instance: padded edge arrays + demand matrix + metric box.

    `demand[n, p]` is the traffic volume node n sends toward
    `dest_ids[p]`; `capacity[e]` scales per-link utilization (uniform
    1.0 when link capacities are unknown — the objective then ranks
    metric vectors by raw max-load, which preserves the argmin)."""

    edge_src: np.ndarray  # [E_cap] int32
    edge_dst: np.ndarray  # [E_cap] int32
    edge_metric: np.ndarray  # [E_cap] int32 — initial metrics
    edge_up: np.ndarray  # [E_cap] bool
    node_overloaded: np.ndarray  # [N_cap] bool
    n_edges: int
    n_nodes: int
    dest_ids: np.ndarray  # [P] int32
    demand: np.ndarray  # [N_cap, P] float
    capacity: Optional[np.ndarray] = None  # [E_cap] float (default 1.0)
    metric_lo: int = 1
    metric_hi: int = 64

    def __post_init__(self) -> None:
        if self.capacity is None:
            self.capacity = np.ones(len(self.edge_src), dtype=np.float32)
        if not (0 < self.metric_lo <= self.metric_hi):
            raise ValueError(
                f"te: bad metric bounds [{self.metric_lo}, {self.metric_hi}]"
            )

    @classmethod
    def from_topology(
        cls, topo, dest_ids, demand, capacity=None, metric_lo=1,
        metric_hi=64,
    ) -> "TeProblem":
        """From a benchmarks.synthetic.Topology (or csr.CsrTopology —
        both carry the padded edge-array contract)."""
        return cls(
            edge_src=np.asarray(topo.edge_src, dtype=np.int32),
            edge_dst=np.asarray(topo.edge_dst, dtype=np.int32),
            edge_metric=np.asarray(topo.edge_metric, dtype=np.int32),
            edge_up=np.asarray(topo.edge_up, dtype=bool),
            node_overloaded=np.asarray(topo.node_overloaded, dtype=bool),
            n_edges=int(topo.n_edges),
            n_nodes=int(topo.n_nodes),
            dest_ids=np.asarray(dest_ids, dtype=np.int32),
            demand=np.asarray(demand),
            capacity=capacity,
            metric_lo=metric_lo,
            metric_hi=metric_hi,
        )


@dataclass
class TeResult:
    """Outcome of one optimize run; `metrics` is always integer, within
    bounds, and exactly validated (it equals the initial metrics when
    nothing improved)."""

    metrics: np.ndarray  # [E_cap] int32
    objective_before: float
    objective_after: float
    improved: bool
    steps: int
    round_trips: int
    accepted: int
    rejected: int
    wall_us: int
    changed_edges: list = field(default_factory=list)  # [(src, dst, m)]


def _clip_int(metric_f, problem: TeProblem) -> np.ndarray:
    """Round + project a float metric vector into the integer box;
    padding edges keep metric 1 (the mirror convention)."""
    cand = np.clip(
        np.rint(np.asarray(metric_f)), problem.metric_lo, problem.metric_hi
    ).astype(np.int32)
    return np.where(problem.edge_up, cand, np.int32(1))


class TeOptimizer:
    """Gradient-descent TE over the fleet product with an exact gate."""

    def __init__(self, engine=None) -> None:
        self.engine = engine
        self._lock = threading.Lock()
        self.counters: dict[str, int] = {k: 0 for k in TE_COUNTER_KEYS}

    # -- counters (module contract: get_counters on both wire surfaces) --

    def _bump(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + delta

    def get_counters(self) -> dict[str, int]:
        with self._lock:
            return dict(self.counters)

    # -- exact round trip ---------------------------------------------------

    def _evaluator(self, problem: TeProblem) -> ExactEvaluator:
        return ExactEvaluator(
            problem.edge_src, problem.edge_dst, problem.edge_up,
            problem.node_overloaded, problem.n_edges, problem.n_nodes,
            problem.dest_ids, problem.demand, problem.capacity,
            engine=self.engine,
        )

    def _check_epoch(self, epoch_fn, expect_epoch) -> None:
        if epoch_fn is None or expect_epoch is None:
            return
        actual = int(epoch_fn())
        if actual != int(expect_epoch):
            self._bump("te.aborted")
            raise EpochMismatchError(int(expect_epoch), actual)

    # -- the optimizer ------------------------------------------------------

    def optimize(
        self,
        problem: TeProblem,
        *,
        steps: int = 48,
        round_trips: int = 4,
        lr: float = 0.75,
        tau0: float = 1.0,
        tau_min: float = 0.1,
        tau_obj: float = 0.1,
        n_sweeps: Optional[int] = None,
        flow_sweeps: Optional[int] = None,
        epoch_fn: Optional[Callable[[], int]] = None,
        expect_epoch: Optional[int] = None,
        publish: Optional[Callable[[np.ndarray, float], None]] = None,
        budget_left: Optional[Callable[[], float]] = None,
    ) -> TeResult:
        """Anneal tau0 -> tau_min over `round_trips` stages of
        `steps // round_trips` Adam steps each; every stage boundary is
        one exact-solver round trip gating acceptance.  `publish` fires
        at most once, with the best exactly-improving integer metrics —
        never with smoothed-model output."""
        import jax.numpy as jnp

        from . import soft

        t_start = time.perf_counter()
        n_sweeps = int(n_sweeps or min(96, max(8, problem.n_nodes)))
        flow_sweeps = int(flow_sweeps or n_sweeps)
        round_trips = max(1, int(round_trips))
        per_stage = max(1, int(steps) // round_trips)

        ev = self._evaluator(problem)
        metric0 = _clip_int(
            np.asarray(problem.edge_metric, dtype=np.float64), problem
        )
        self._check_epoch(epoch_fn, expect_epoch)
        obj_before = ev.evaluate(metric0)
        self._bump("te.round_trips")
        with self._lock:
            self.counters["te.objective_before_milli"] = int(
                round(obj_before * 1000)
            )

        # device-resident descent state
        e_src = jnp.asarray(problem.edge_src, dtype=jnp.int32)
        e_dst = jnp.asarray(problem.edge_dst, dtype=jnp.int32)
        e_up = jnp.asarray(problem.edge_up)
        n_over = jnp.asarray(problem.node_overloaded)
        dests = jnp.asarray(problem.dest_ids, dtype=jnp.int32)
        demand = jnp.asarray(problem.demand, dtype=jnp.float32)
        capacity = jnp.asarray(problem.capacity, dtype=jnp.float32)
        metric_f = jnp.asarray(metric0, dtype=jnp.float32)
        adam_m = jnp.zeros_like(metric_f)
        adam_v = jnp.zeros_like(metric_f)
        lo_f, hi_f = float(problem.metric_lo), float(problem.metric_hi)

        step_fn = soft.te_descent_step
        if self.engine is not None:
            import functools

            step_fn = functools.partial(
                self.engine.dispatch, "te_step", soft.te_descent_step
            )

        taus = np.geomspace(max(tau0, 1e-3), max(tau_min, 1e-3),
                            round_trips)
        best_metric, best_obj = metric0, obj_before
        n_steps = accepted = rejected = trips = t_adam = 0
        for stage in range(round_trips):
            if budget_left is not None and budget_left() <= 0:
                break
            tau = float(taus[stage])
            for _ in range(per_stage):
                self._check_epoch(epoch_fn, expect_epoch)
                n_steps += 1
                t_adam += 1
                _obj, metric_f, adam_m, adam_v = step_fn(
                    metric_f, adam_m, adam_v, np.float32(t_adam),
                    e_src, e_dst, e_up, n_over, dests, demand, capacity,
                    np.float32(tau), np.float32(tau_obj), np.float32(lr),
                    np.float32(lo_f), np.float32(hi_f),
                    n_sweeps=n_sweeps, flow_sweeps=flow_sweeps,
                )
                self._bump("te.steps")
            candidate = _clip_int(metric_f, problem)
            self._check_epoch(epoch_fn, expect_epoch)
            cand_obj = ev.evaluate(candidate)
            trips += 1
            self._bump("te.round_trips")
            if cand_obj < best_obj - _IMPROVE_EPS:
                best_metric, best_obj = candidate, cand_obj
                accepted += 1
                self._bump("te.accepted")
            else:
                rejected += 1
                self._bump("te.rejected")
                # trust-region fallback: a rejected stage re-centers the
                # relaxation on the best exactly-validated point instead
                # of compounding a drift the exact solver already vetoed
                metric_f = jnp.asarray(best_metric, dtype=jnp.float32)
                adam_m = jnp.zeros_like(metric_f)
                adam_v = jnp.zeros_like(metric_f)
                t_adam = 0  # bias correction restarts with the moments

        improved = best_obj < obj_before - _IMPROVE_EPS
        if improved and publish is not None:
            # the one and only publication seam: exactly-validated
            # integer metrics, routed to the normal Decision/route path
            publish(best_metric.copy(), best_obj)
        wall_us = int((time.perf_counter() - t_start) * 1e6)
        with self._lock:
            self.counters["te.objective_after_milli"] = int(
                round(best_obj * 1000)
            )
        self._bump("te.optimize_us", wall_us)
        self._bump("te.runs")
        e = problem.n_edges
        changed = np.nonzero(
            (best_metric[:e] != metric0[:e]) & problem.edge_up[:e]
        )[0]
        return TeResult(
            metrics=best_metric,
            objective_before=obj_before,
            objective_after=best_obj,
            improved=improved,
            steps=n_steps,
            round_trips=trips + 1,  # + the baseline evaluation
            accepted=accepted,
            rejected=rejected,
            wall_us=wall_us,
            changed_edges=[
                (
                    int(problem.edge_src[i]),
                    int(problem.edge_dst[i]),
                    int(best_metric[i]),
                )
                for i in changed
            ],
        )


def hill_climb(
    problem: TeProblem,
    *,
    rounds: int = 32,
    seed: int = 0,
    engine=None,
    budget_left: Optional[Callable[[], float]] = None,
) -> tuple[np.ndarray, float, int]:
    """Host baseline for the bench row: random single-metric moves
    through the SAME exact evaluator, keep-if-improves.  Returns
    (metrics, exact objective, exact evaluations spent)."""
    rng = np.random.RandomState(seed)
    ev = ExactEvaluator(
        problem.edge_src, problem.edge_dst, problem.edge_up,
        problem.node_overloaded, problem.n_edges, problem.n_nodes,
        problem.dest_ids, problem.demand, problem.capacity, engine=engine,
    )
    best = _clip_int(
        np.asarray(problem.edge_metric, dtype=np.float64), problem
    )
    best_obj = ev.evaluate(best)
    evals = 1
    up_edges = np.nonzero(problem.edge_up[: problem.n_edges])[0]
    for _ in range(rounds):
        if budget_left is not None and budget_left() <= 0:
            break
        if not len(up_edges):
            break
        cand = best.copy()
        e = up_edges[rng.randint(len(up_edges))]
        cand[e] = rng.randint(problem.metric_lo, problem.metric_hi + 1)
        if cand[e] == best[e]:
            continue
        obj = ev.evaluate(cand)
        evals += 1
        if obj < best_obj - _IMPROVE_EPS:
            best, best_obj = cand, obj
    return best, best_obj, evals
