"""Ctrl server: NDJSON-RPC over TCP with server streaming.

Wire protocol (one JSON object per line):
    request:   {"id": N, "method": "...", "params": {...}}
    response:  {"id": N, "result": <wire-encoded>}
             | {"id": N, "error": "..."}
    streaming: {"id": N, "stream": <item>} ... ; client sends
               {"id": N, "cancel": true} to stop.

Dataclass values are wire-tagged via serializer.to_wire/from_wire.
"""

from __future__ import annotations

import asyncio
import fnmatch
import json
import logging
import re
from typing import Any, Awaitable, Callable, Optional

from ..runtime.eventbase import OpenrEventBase
from ..runtime.queue import QueueClosedError, ReplicateQueue
from ..serializer import from_wire, to_wire
from ..types import ADJ_MARKER, Publication

log = logging.getLogger(__name__)

OPENR_VERSION = 20
OPENR_LOWEST_SUPPORTED_VERSION = 20


class CtrlError(RuntimeError):
    pass


class OpenrCtrlHandler:
    """Method registry over the module set (reference:
    OpenrCtrlHandler.h:53 — raw pointers to every module)."""

    def __init__(
        self,
        node_name: str,
        *,
        kvstore=None,
        decision=None,
        fib=None,
        link_monitor=None,
        prefix_manager=None,
        spark=None,
        monitor=None,
        netlink=None,
        device=None,
        serving=None,
        mesh=None,
        te=None,
        fuzz=None,
        sched=None,
        obs=None,
        snapshot=None,
        config=None,
        kvstore_updates_queue: Optional[ReplicateQueue[Publication]] = None,
        fib_updates_queue: Optional[ReplicateQueue] = None,
        config_store=None,
        watchdog=None,
        queues: Optional[dict[str, ReplicateQueue]] = None,
    ) -> None:
        self.node_name = node_name
        self.config_store = config_store
        self.watchdog = watchdog
        self.queues = queues
        self.kvstore = kvstore
        self.decision = decision
        self.fib = fib
        self.link_monitor = link_monitor
        self.prefix_manager = prefix_manager
        self.spark = spark
        self.monitor = monitor
        self.netlink = netlink
        # device-residency engine (openr_tpu.device.DeviceResidencyEngine):
        # exports device.engine.* through get_counters like any module
        self.device = device
        # query scheduler (openr_tpu.serving.QueryScheduler): async query
        # methods below submit into its admission queue; exports serving.*
        self.serving = serving
        # blocked-APSP node-sharding rung (openr_tpu.parallel.blocked
        # .BlockedApspEngine): exports mesh.blocked.* the same way
        self.mesh = mesh
        # differentiable-TE optimizer (openr_tpu.te.TeOptimizer): exports
        # te.* counters (pre-seeded at construction) the same way
        self.te = te
        # chaos fuzzer registry (openr_tpu.chaos.fuzz.FUZZ_COUNTERS):
        # exports chaos.fuzz.* (pre-seeded zeros) the same way
        self.fuzz = fuzz
        # schedule-exploration registry (openr_tpu.analysis.sched
        # .SCHED_COUNTERS): exports sched.* (pre-seeded zeros) the same way
        self.sched = sched
        # observability surface (openr_tpu.obs.ObsStats): exports obs.*
        # trace counters (zeroed when unarmed) plus the dumpTraces /
        # getSpanSamples methods below
        self.obs = obs
        # engine-snapshot registry (openr_tpu.snapshot.SNAPSHOT_COUNTERS):
        # exports snapshot.* (pre-seeded zeros) the same way
        self.snapshot = snapshot
        self.config = config
        self.kvstore_updates_queue = kvstore_updates_queue
        self.fib_updates_queue = fib_updates_queue
        self.methods: dict[str, Callable[[dict], Any]] = {}
        # coroutine-valued methods awaited on the server loop instead of
        # the executor: serving queries park on the scheduler's future,
        # so an executor thread per in-flight query would defeat the
        # admission queue's purpose
        self.async_methods: dict[str, Callable[[dict], Awaitable[Any]]] = {}
        self._register_methods()

    def _need(self, module, name: str):
        if module is None:
            raise CtrlError(f"module {name} not available")
        return module

    def _register_methods(self) -> None:
        m = self.methods
        # -- meta ------------------------------------------------------------
        m["getMyNodeName"] = lambda p: self.node_name
        m["getOpenrVersion"] = lambda p: {
            "version": OPENR_VERSION,
            "lowestSupportedVersion": OPENR_LOWEST_SUPPORTED_VERSION,
        }
        m["getRunningConfig"] = lambda p: (
            self.config.to_dict() if self.config is not None else {}
        )
        # parse+validate config file CONTENTS without applying anything
        # (reference: dryrunConfig, OpenrCtrlHandler.h:69-78)
        m["dryrunConfig"] = self._dryrun_config
        m["getCounters"] = lambda p: self._all_counters()
        m["getRegexCounters"] = lambda p: {
            k: v
            for k, v in self._all_counters().items()
            if re.search(p["regex"], k)
        }
        m["getBuildInfo"] = lambda p: {
            "buildPackageName": "openr_tpu",
            "buildPackageVersion": OPENR_VERSION,
            "buildMode": "tpu",
        }
        # -- observability (span traces; empty lists when unarmed) -----------
        m["dumpTraces"] = lambda p: (
            [] if self.obs is None else self.obs.dump_traces(p.get("n", 16))
        )
        m["getSpanSamples"] = lambda p: (
            [] if self.obs is None else self.obs.span_samples(p.get("n", 32))
        )

        # -- persistent config store (reference: set/get/eraseConfigKey,
        #    OpenrCtrlHandler.h:60-67 over PersistentStore)
        m["setConfigKey"] = lambda p: self._need(
            self.config_store, "config-store"
        ).store(p["key"], p["value"])
        m["getConfigKey"] = lambda p: self._need(
            self.config_store, "config-store"
        ).load(p["key"])
        m["eraseConfigKey"] = lambda p: self._need(
            self.config_store, "config-store"
        ).erase(p["key"])

        # -- kvstore ----------------------------------------------------------
        m["getKvStoreKeyValsArea"] = lambda p: self._need(
            self.kvstore, "kvstore"
        ).get_key_vals(p.get("area", "0"), p["keys"])
        m["getKvStoreKeyValsFilteredArea"] = self._kvstore_dump_filtered
        m["getKvStoreHashFilteredArea"] = lambda p: self._need(
            self.kvstore, "kvstore"
        ).dump_hashes(
            p.get("area", "0"),
            p.get("prefixes", []),
            p.get("originators", []),
        )
        m["setKvStoreKeyVals"] = self._kvstore_set
        m["getKvStorePeersArea"] = lambda p: self._need(
            self.kvstore, "kvstore"
        ).dump_peers(p.get("area", "0"))
        m["getKvStoreAreaSummary"] = self._kvstore_summary
        # DUAL flood-topology (reference: OpenrCtrl.thrift getSpanningTreeInfos
        # + updateFloodTopologyChild; dual messages rode the ZMQ channel in
        # the reference, here they are plain ctrl methods)
        m["processKvStoreDualMessage"] = lambda p: self._need(
            self.kvstore, "kvstore"
        ).process_dual_messages(p.get("area", "0"), p["messages"])
        m["updateFloodTopologyChild"] = lambda p: self._need(
            self.kvstore, "kvstore"
        ).process_flood_topo_set(p.get("area", "0"), p["params"])
        m["getSpanningTreeInfos"] = lambda p: self._need(
            self.kvstore, "kvstore"
        ).get_flood_topo(p.get("area", "0"))

        # -- decision ---------------------------------------------------------
        m["getRouteDb"] = lambda p: self._need(
            self.decision, "decision"
        ).get_route_db(p.get("node", ""))
        # fleet-wide route dump from the reduced all-sources product (new
        # capability vs the reference's one-node-at-a-time
        # getRouteDbComputed, Decision.cpp:1510-1530)
        m["getFleetRoutes"] = lambda p: self._need(
            self.decision, "decision"
        ).get_fleet_route_dbs(p.get("nodes"))
        m["getDecisionAdjacenciesFiltered"] = lambda p: self._need(
            self.decision, "decision"
        ).get_adjacency_databases(
            set(p["areas"]) if p.get("areas") else None
        )
        m["getReceivedRoutesFiltered"] = lambda p: self._need(
            self.decision, "decision"
        ).get_received_routes(
            prefixes=p.get("prefixes"),
            node_name=p.get("node"),
            area_name=p.get("area"),
        )
        # failure-protection analysis (new capabilities; no reference RPC)
        m["decisionWhatIf"] = lambda p: self._need(
            self.decision, "decision"
        ).what_if(
            [[tuple(link) for link in sc] for sc in p["scenarios"]],
            area=p.get("area", "0"),
            sources=p.get("sources"),
        )
        m["decisionTiLfa"] = lambda p: self._need(
            self.decision, "decision"
        ).get_ti_lfa(p.get("node", ""), area=p.get("area", "0"))
        m["setRibPolicy"] = lambda p: self._need(
            self.decision, "decision"
        ).set_rib_policy(p["policy"])
        m["getRibPolicy"] = lambda p: self._need(
            self.decision, "decision"
        ).get_rib_policy()
        m["clearRibPolicy"] = lambda p: self._need(
            self.decision, "decision"
        ).clear_rib_policy()

        # -- serving (async: admission-queued, coalesced, batched) ------------
        a = self.async_methods
        a["queryPaths"] = lambda p: self._serving_query("paths", p)
        a["queryWhatIf"] = lambda p: self._serving_query("what_if", p)
        a["queryKsp"] = lambda p: self._serving_query("ksp", p)
        # differentiable TE: demand matrix + bounds in, exactly-validated
        # proposed metrics + objective delta out; rides the scheduler's
        # admission/epoch machinery (a flap mid-run aborts, never retries)
        a["optimizeMetrics"] = self._optimize_metrics

        # -- fib --------------------------------------------------------------
        m["getRouteDbFib"] = self._fib_route_db
        m["getUnicastRoutesFiltered"] = lambda p: self._need(
            self.fib, "fib"
        ).get_unicast_routes(p.get("prefixes"))
        # MPLS route dumps (reference: getMplsRoutes/getMplsRoutesFiltered)
        m["getMplsRoutes"] = lambda p: self._need(self.fib, "fib").get_route_db()[1]
        m["getMplsRoutesFiltered"] = self._mpls_routes_filtered
        m["getPerfDb"] = lambda p: self._need(self.fib, "fib").get_perf_db()

        # -- link-monitor -----------------------------------------------------
        lm = lambda: self._need(self.link_monitor, "link-monitor")  # noqa: E731
        m["getInterfaces"] = lambda p: lm().get_interfaces()
        m["getLinkMonitorAdjacenciesFiltered"] = lambda p: lm().get_adjacencies(
            p.get("area", "0")
        )
        m["getLinkMonitorState"] = lambda p: self._lm_state()
        m["setNodeOverload"] = lambda p: lm().set_node_overload(True)
        m["unsetNodeOverload"] = lambda p: lm().set_node_overload(False)
        # soft-drain (reference: semiDrainNode / nodeMetricIncrementVal)
        m["setNodeInterfaceMetricIncrease"] = lambda p: (
            lm().set_node_metric_increment(p["metricIncrementVal"])
        )
        m["unsetNodeInterfaceMetricIncrease"] = lambda p: (
            lm().set_node_metric_increment(0)
        )
        m["setInterfaceOverload"] = lambda p: lm().set_link_overload(
            p["interface"], True
        )
        m["unsetInterfaceOverload"] = lambda p: lm().set_link_overload(
            p["interface"], False
        )
        m["setInterfaceMetric"] = lambda p: lm().set_link_metric(
            p["interface"], p["metric"]
        )
        m["unsetInterfaceMetric"] = lambda p: lm().set_link_metric(
            p["interface"], None
        )
        m["setAdjacencyMetric"] = lambda p: lm().set_adj_metric(
            p["interface"], p["node"], p["metric"]
        )
        m["unsetAdjacencyMetric"] = lambda p: lm().set_adj_metric(
            p["interface"], p["node"], None
        )

        # -- prefix-manager ---------------------------------------------------
        pm = lambda: self._need(self.prefix_manager, "prefix-manager")  # noqa: E731
        m["advertisePrefixes"] = lambda p: pm().advertise_prefixes(
            p["type"], p["prefixes"]
        )
        m["withdrawPrefixes"] = lambda p: pm().withdraw_prefixes(
            p["type"], [e.prefix if hasattr(e, "prefix") else e for e in p["prefixes"]]
        )
        m["syncPrefixesByType"] = lambda p: pm().sync_prefixes_by_type(
            p["type"], p["prefixes"]
        )
        m["withdrawPrefixesByType"] = lambda p: pm().withdraw_prefixes_by_type(
            p["type"]
        )
        m["getPrefixes"] = lambda p: pm().get_prefixes()
        m["getPrefixesByType"] = lambda p: pm().get_prefixes(p["type"])
        m["getOriginatedPrefixes"] = lambda p: pm().get_originated_prefixes()

        # -- spark ------------------------------------------------------------
        m["getSparkNeighbors"] = self._spark_neighbors
        m["getNeighbors"] = self._spark_neighbors  # deprecated ref alias
        # announce our own graceful restart to all neighbors (reference:
        # floodRestartingMsg, OpenrCtrlHandler.h / Spark.h:99)
        m["floodRestartingMsg"] = lambda p: self._need(
            self.spark, "spark"
        ).flood_restarting_msg()

        # -- deprecated area-less reference names: every area-taking
        # handler above defaults to area "0", so these are pure aliases
        # (the reference kept both during its area migration,
        # OpenrCtrlHandler.h getKvStoreKeyVals vs ...Area etc.)
        m["getKvStoreKeyVals"] = m["getKvStoreKeyValsArea"]
        m["getKvStoreKeyValsFiltered"] = m["getKvStoreKeyValsFilteredArea"]
        m["getKvStoreHashFiltered"] = m["getKvStoreHashFilteredArea"]
        m["getKvStorePeers"] = m["getKvStorePeersArea"]
        m["getLinkMonitorAdjacencies"] = m["getLinkMonitorAdjacenciesFiltered"]
        m["getReceivedRoutes"] = m["getReceivedRoutesFiltered"]
        m["getUnicastRoutes"] = m["getUnicastRoutesFiltered"]
        m["getDecisionAdjacencyDbs"] = m["getDecisionAdjacenciesFiltered"]
        m["getAdvertisedRoutes"] = self._advertised_routes
        m["getAdvertisedRoutesFiltered"] = self._advertised_routes
        m["getRouteDetailDb"] = self._route_detail_db

    # -- serving queries ------------------------------------------------------

    async def _serving_query(self, op: str, p: dict) -> dict:
        """Submit one query into the scheduler's admission queue and park
        on its future (no executor thread held while queued/coalesced).
        Sheds surface as explicit QueryShedError wire errors."""
        serving = self._need(self.serving, "serving")
        kw: dict = {}
        if p.get("session") and getattr(serving, "supports_sessions", False):
            # fleet front-door (serving.ReplicaRouter): a client-supplied
            # session id opts into epoch pinning — replies only ever move
            # forward in topology version for that session
            kw["session"] = str(p["session"])
        fut = serving.submit(
            op,
            area=p.get("area", "0"),
            sources=p.get("sources") or (),
            scenarios=[
                [tuple(link) for link in sc]
                for sc in (p.get("scenarios") or [])
            ],
            dests=p.get("dests") or (),
            k=p.get("k", 2),
            use_link_metric=p.get("useLinkMetric", True),
            **kw,
        )
        res = await asyncio.wrap_future(fut)
        return {
            "result": self._shape_query_value(op, res.value),
            "epoch": res.epoch,
            "batchSize": res.batch_size,
            "latencyUs": res.latency_us,
        }

    async def _optimize_metrics(self, p: dict) -> dict:
        """Wire surface of the TE optimizer.  Params: ``demand`` as
        [[src, dest, volume], ...], ``metricLo``/``metricHi`` bounds,
        ``steps`` descent budget, ``area``.  The reply's proposed
        metrics come from the exact uint32 validation gate — never from
        the smoothed model."""
        serving = self._need(self.serving, "serving")
        fut = serving.submit(
            "optimize_metrics",
            area=p.get("area", "0"),
            demand=[
                (row[0], row[1], row[2]) for row in (p.get("demand") or [])
            ],
            bounds=(p.get("metricLo", 1), p.get("metricHi", 64)),
            steps=p.get("steps", 32),
        )
        res = await asyncio.wrap_future(fut)
        return {
            "result": res.value,
            "epoch": res.epoch,
            "batchSize": res.batch_size,
            "latencyUs": res.latency_us,
        }

    @staticmethod
    def _shape_query_value(op: str, value) -> Any:
        if op == "paths":
            # {source: SpfResult} -> JSON-able metric + next-hop sets
            return {
                src: {
                    dest: {
                        "metric": int(r.metric),
                        "nextHops": sorted(r.next_hops),
                    }
                    for dest, r in spf.items()
                }
                for src, spf in value.items()
            }
        if op == "ksp":
            # {dest: [Path]} -> hop-pair lists
            return {
                dest: [
                    [[link.n1, link.n2] for link in path] for path in paths
                ]
                for dest, paths in value.items()
            }
        return value  # what_if rows are already wire-safe dicts

    # -- non-lambda handlers --------------------------------------------------

    def _all_counters(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for module in (
            self.kvstore,
            self.decision,
            self.fib,
            self.link_monitor,
            self.prefix_manager,
            self.spark,
            self.monitor,
            self.netlink,
            self.device,
            self.serving,
            self.mesh,
            self.te,
            self.fuzz,
            self.sched,
            self.obs,
            self.snapshot,
        ):
            if module is None:
                continue
            get = getattr(module, "get_counters", None)
            if callable(get):
                out.update(get())
            elif hasattr(module, "counters"):
                out.update(module.counters)
        if self.watchdog is not None:
            out.update(self.watchdog.get_counters())
        if self.queues:
            from ..runtime.queue import queue_counters

            out.update(queue_counters(self.queues))
        return out

    def _kvstore_dump_filtered(self, p: dict) -> Any:
        from ..kvstore.kvstore import KeyDumpParams

        kvstore = self._need(self.kvstore, "kvstore")
        area = p.get("area", "0")
        if p.get("match_all") or p.get("hash_only"):
            # display-oriented dump variants (no 3-way semantics)
            return kvstore.dump_all(
                area,
                key_prefixes=p.get("prefixes", []),
                originator_ids=p.get("originators", []),
                match_all=p.get("match_all", False),
                do_not_publish_value=p.get("hash_only", False),
            )
        # the same path the in-process peer transport uses (3-way diff when
        # key_val_hashes is present, remaining-TTL adjustment always)
        return kvstore.process_full_dump(
            area,
            KeyDumpParams(
                keys=p.get("prefixes", []),
                originator_ids=p.get("originators", []),
                key_val_hashes=p.get("key_val_hashes"),
            ),
        )

    def _dryrun_config(self, p: dict) -> dict:
        """Validate config-file CONTENTS; returns the parsed config dict
        or raises (surfaced to the client as the RPC error) — nothing is
        applied (reference: dryrunConfig)."""
        import json as _json

        from ..config import config_from_dict

        data = _json.loads(p["file_contents"])
        return config_from_dict(data).to_dict()

    def _mpls_routes_filtered(self, p: dict) -> list:
        routes = self._need(self.fib, "fib").get_route_db()[1]
        labels = p.get("labels")
        if not labels:
            return routes
        wanted = set(labels)
        return [r for r in routes if r.top_label in wanted]

    def _kvstore_set(self, p: dict) -> None:
        kvstore = self._need(self.kvstore, "kvstore")
        kvstore.set_key_vals(
            p.get("area", "0"),
            p["key_vals"],
            node_ids=p.get("node_ids"),
            flood_root_id=p.get("flood_root_id"),
        )

    def _kvstore_summary(self, p: dict) -> list[dict]:
        kvstore = self._need(self.kvstore, "kvstore")
        out = []
        for area in kvstore.areas:
            pub = kvstore.dump_all(area)
            out.append(
                {
                    "area": area,
                    "keyValsCount": len(pub.key_vals),
                    "keyValsBytes": sum(
                        len(v.value or b"") for v in pub.key_vals.values()
                    ),
                    "peersCount": len(kvstore.dump_peers(area)),
                }
            )
        return out

    def _lm_state(self) -> dict:
        state = self._need(self.link_monitor, "link-monitor").get_state()
        return {
            "is_overloaded": state.is_overloaded,
            "overloaded_links": sorted(state.overloaded_links),
            "link_metric_overrides": dict(state.link_metric_overrides),
            "node_label": state.node_label,
            "adj_metric_overrides": {
                f"{if_name}|{node}": metric
                for (if_name, node), metric in state.adj_metric_overrides.items()
            },
        }

    def _fib_route_db(self, p: dict) -> dict:
        fib = self._need(self.fib, "fib")
        unicast, mpls = fib.get_route_db(
            programmed_only=bool(p.get("programmedOnly"))
        )
        return {"unicastRoutes": unicast, "mplsRoutes": mpls}

    def _advertised_routes(self, p: dict) -> list[dict]:
        """Per-prefix advertisement detail from PrefixManager (reference:
        getAdvertisedRoutesFiltered, OpenrCtrlHandler.h:129-140 — one row
        per prefix with every per-type entry; filterable by prefixes)."""
        pm = self._need(self.prefix_manager, "prefix-manager")
        from ..types import PrefixType, normalize_prefix

        wanted = (
            {normalize_prefix(x) for x in p["prefixes"]}
            if p.get("prefixes")
            else None
        )
        by_prefix: dict[str, list[tuple[int, Any]]] = {}
        for ptype in PrefixType:
            for entry in pm.get_prefixes(ptype):
                prefix = normalize_prefix(entry.prefix)
                if wanted is not None and prefix not in wanted:
                    continue
                by_prefix.setdefault(prefix, []).append(
                    (int(ptype), entry)
                )
        return [
            {"prefix": prefix, "routes": rows}
            for prefix, rows in sorted(by_prefix.items())
        ]

    def _route_detail_db(self, p: dict) -> dict:
        """Computed unicast/MPLS entries WITH their best-prefix-entry
        detail (reference: getRouteDetailDb, OpenrCtrlHandler.h:98 —
        the Fib view annotated with route provenance).  Served from
        Decision's RibEntries, which carry best_prefix_entry/best_area."""
        decision = self._need(self.decision, "decision")
        db = decision.get_route_db()
        return {
            "unicastRoutes": db.unicast_routes,
            "mplsRoutes": db.mpls_routes,
        }

    def _spark_neighbors(self, p: dict) -> list[dict]:
        spark = self._need(self.spark, "spark")
        return [
            {
                "nodeName": n.node_name,
                "ifName": n.if_name,
                "remoteIfName": n.remote_if_name,
                "state": n.state.name,
                "area": n.area,
                "rttUs": n.rtt_us,
                "transportAddressV6": n.transport_addr_v6,
                "openrCtrlThriftPort": n.ctrl_port,
            }
            for n in spark.get_neighbors()
        ]


class CtrlServer(OpenrEventBase):
    """TCP server event base (reference: ThriftServer setup,
    openr/Main.cpp:546-612; deliberately few worker threads — handlers
    marshal onto the owning modules)."""

    def __init__(
        self,
        handler: OpenrCtrlHandler,
        host: str = "::1",
        port: int = 2018,
        tls=None,  # Optional[tls.TlsConfig] — mTLS + peer-name ACL
    ) -> None:
        super().__init__(name="ctrl-server")
        self.handler = handler
        self.host = host
        self.port = port
        self.tls = tls
        self._server: Optional[asyncio.AbstractServer] = None

    def run(self) -> None:
        super().run()
        self.wait_until_running()
        fut = self.run_coroutine(self._start())
        fut.result(timeout=10)

    async def _start(self) -> None:
        ssl_ctx = None
        if self.tls is not None:
            from .tls import server_context

            ssl_ctx = server_context(self.tls)
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port, ssl=ssl_ctx
        )
        if self.port == 0:  # ephemeral: record the real port
            self.port = self._server.sockets[0].getsockname()[1]

    def stop(self) -> None:
        if self._server is not None and self._loop is not None:
            server, self._server = self._server, None

            def _close() -> None:
                server.close()

            try:
                self.run_in_event_base_thread(_close).result(timeout=5)
            except Exception:
                pass
        super().stop()

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        # peer-name ACL (reference: Main.cpp:546-612 wires the client-CN
        # allowlist into the thrift server's TLS policy)
        if self.tls is not None:
            from .tls import check_acl, peer_common_name

            ssl_object = writer.get_extra_info("ssl_object")
            peer_cn = peer_common_name(ssl_object) if ssl_object else None
            if not check_acl(self.tls, peer_cn):
                log.warning(
                    "ctrl: rejecting peer %r (ACL %r)",
                    peer_cn,
                    self.tls.acl_regex,
                )
                writer.close()
                return

        streams: dict[int, asyncio.Task] = {}
        write_lock = asyncio.Lock()

        async def send(obj: dict) -> None:
            async with write_lock:
                writer.write(json.dumps(obj).encode() + b"\n")
                await writer.drain()

        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    await send({"id": None, "error": "bad json"})
                    continue
                if not isinstance(msg, dict):
                    await send({"id": None, "error": "bad request"})
                    continue
                msg_id = msg.get("id")
                if msg.get("cancel"):
                    task = streams.pop(msg_id, None)
                    if task is not None:
                        task.cancel()
                    continue
                method = msg.get("method", "")
                try:
                    params = from_wire(msg.get("params") or {})
                except Exception as e:  # bad payload must not kill the conn
                    await send(
                        {"id": msg_id, "error": f"bad params: {e}"}
                    )
                    continue
                # reference stream names accepted as aliases
                # (subscribeAndGetKvStore[Filtered] / subscribeAndGetFib,
                # OpenrCtrlHandler.h:240-267)
                if method in (
                    "subscribeKvStore",
                    "subscribeAndGetKvStore",
                    "subscribeAndGetKvStoreFiltered",
                ):
                    streams[msg_id] = asyncio.ensure_future(
                        self._stream_kvstore(msg_id, params, send)
                    )
                    self._track(streams[msg_id])
                elif method in ("subscribeFib", "subscribeAndGetFib"):
                    streams[msg_id] = asyncio.ensure_future(
                        self._stream_fib(msg_id, params, send)
                    )
                    self._track(streams[msg_id])
                elif method in (
                    "longPollKvStoreAdjArea",
                    "longPollKvStoreAdj",
                ):
                    streams[msg_id] = asyncio.ensure_future(
                        self._long_poll_adj(msg_id, params, send)
                    )
                    self._track(streams[msg_id])
                else:
                    await self._dispatch(msg_id, method, params, send)
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            for task in streams.values():
                task.cancel()
            writer.close()

    async def _dispatch(self, msg_id, method, params, send) -> None:
        afn = self.handler.async_methods.get(method)
        if afn is not None:
            # serving queries park on the scheduler future for the whole
            # admission->coalesce->dispatch pipeline: run them as tracked
            # tasks so one connection can pipeline many in-flight queries
            task = asyncio.ensure_future(
                self._run_async_method(msg_id, afn, params, send)
            )
            self._track(task)
            return
        fn = self.handler.methods.get(method)
        if fn is None:
            await send({"id": msg_id, "error": f"unknown method {method!r}"})
            return
        try:
            # module APIs block on cross-thread futures: keep them off the
            # server loop
            result = await asyncio.get_running_loop().run_in_executor(
                None, fn, params
            )
            await send({"id": msg_id, "result": to_wire(result)})
        except Exception as e:  # noqa: BLE001
            log.debug("ctrl: %s failed", method, exc_info=True)
            await send({"id": msg_id, "error": f"{type(e).__name__}: {e}"})

    async def _run_async_method(self, msg_id, afn, params, send) -> None:
        try:
            result = await afn(params)
            await send({"id": msg_id, "result": to_wire(result)})
        except (asyncio.CancelledError, ConnectionResetError):
            pass
        except Exception as e:  # noqa: BLE001
            log.debug("ctrl: async method failed", exc_info=True)
            try:
                await send({"id": msg_id, "error": f"{type(e).__name__}: {e}"})
            except (ConnectionResetError, RuntimeError):
                pass

    # -- streaming (reference: OpenrCtrlHandler.h:240-273) --------------------

    async def _stream_kvstore(self, msg_id, params, send) -> None:
        """subscribeAndGetKvStore: snapshot + filtered delta stream."""
        queue = self.handler.kvstore_updates_queue
        if queue is None:
            await send({"id": msg_id, "error": "kvstore stream unavailable"})
            return
        area = params.get("area", "0")
        prefixes = params.get("prefixes") or []
        reader = queue.get_reader()
        try:
            if self.handler.kvstore is not None:
                snapshot = self.handler.kvstore.dump_all(
                    area, key_prefixes=prefixes
                )
                await send({"id": msg_id, "stream": to_wire(snapshot)})
            while True:
                pub = await reader.aget()
                if pub.area != area:
                    continue
                if prefixes:
                    filtered = Publication(
                        key_vals={
                            k: v
                            for k, v in pub.key_vals.items()
                            if any(k.startswith(p) for p in prefixes)
                        },
                        expired_keys=[
                            k
                            for k in pub.expired_keys
                            if any(k.startswith(p) for p in prefixes)
                        ],
                        area=pub.area,
                    )
                    if not filtered.key_vals and not filtered.expired_keys:
                        continue
                    pub = filtered
                await send({"id": msg_id, "stream": to_wire(pub)})
        except (QueueClosedError, asyncio.CancelledError, ConnectionResetError):
            pass
        finally:
            queue.close_reader(reader)

    async def _stream_fib(self, msg_id, params, send) -> None:
        queue = self.handler.fib_updates_queue
        if queue is None:
            await send({"id": msg_id, "error": "fib stream unavailable"})
            return
        reader = queue.get_reader()
        try:
            while True:
                update = await reader.aget()
                await send({"id": msg_id, "stream": to_wire(update)})
        except (QueueClosedError, asyncio.CancelledError, ConnectionResetError):
            pass
        finally:
            queue.close_reader(reader)

    async def _long_poll_adj(self, msg_id, params, send) -> None:
        """longPollKvStoreAdjArea: resolve when any adj: key changes beyond
        the client's snapshot (reference: OpenrCtrlHandler.h:269)."""
        queue = self.handler.kvstore_updates_queue
        if queue is None:
            await send({"id": msg_id, "error": "kvstore stream unavailable"})
            return
        area = params.get("area", "0")
        snapshot: dict[str, int] = params.get("snapshot") or {}
        reader = queue.get_reader()
        try:
            # immediate resolution if current state already differs
            if self.handler.kvstore is not None:
                current = self.handler.kvstore.dump_all(
                    area, key_prefixes=[ADJ_MARKER]
                )
                for key, val in current.key_vals.items():
                    if snapshot.get(key) != val.version:
                        await send({"id": msg_id, "result": True})
                        return
            while True:
                pub = await reader.aget()
                if pub.area != area:
                    continue
                changed = any(
                    k.startswith(ADJ_MARKER)
                    and snapshot.get(k) != v.version
                    for k, v in pub.key_vals.items()
                ) or any(k.startswith(ADJ_MARKER) for k in pub.expired_keys)
                if changed:
                    await send({"id": msg_id, "result": True})
                    return
        except (QueueClosedError, asyncio.CancelledError, ConnectionResetError):
            pass
        finally:
            queue.close_reader(reader)
