"""Ctrl clients: synchronous (CLI) and the TCP KvStore peer transport.

Reference equivalents: openr/py/openr/clients/openr_client.py (CLI thrift
client) and the KvStore thrift peer client (KvStore.h:429-453).
"""

from __future__ import annotations

import asyncio
import json
import socket
import threading
from typing import Any, Callable, Iterator, Optional

from ..serializer import from_wire, to_wire
from ..types import PeerSpec, Publication


class CtrlClient:
    """Blocking NDJSON-RPC client (one TCP connection, serial requests)."""

    def __init__(
        self,
        host: str = "::1",
        port: int = 2018,
        timeout_s: float = 10.0,
        tls=None,  # Optional[tls.TlsConfig] — client cert for mTLS
    ) -> None:
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self.tls = tls
        self._sock: Optional[socket.socket] = None
        self._rfile = None
        self._next_id = 0
        self._lock = threading.Lock()

    def _connect(self) -> None:
        if self._sock is not None:
            return
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout_s
        )
        if self.tls is not None:
            from .tls import client_context, verify_peer

            try:
                sock = client_context(self.tls).wrap_socket(sock)
                verify_peer(self.tls, sock)
            except Exception:
                sock.close()  # don't leak the raw fd on handshake failure
                raise
        self._sock = sock
        self._rfile = self._sock.makefile("rb")

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
                self._rfile = None

    def __enter__(self) -> "CtrlClient":
        self._connect()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def call(self, method: str, **params: Any) -> Any:
        with self._lock:
            self._connect()
            self._next_id += 1
            msg_id = self._next_id
            request = {"id": msg_id, "method": method, "params": to_wire(params)}
            self._sock.sendall(json.dumps(request).encode() + b"\n")
            while True:
                line = self._rfile.readline()
                if not line:
                    raise ConnectionError("ctrl server closed connection")
                msg = json.loads(line)
                if msg.get("id") != msg_id:
                    continue  # stale stream frame from a prior subscription
                if "error" in msg:
                    raise RuntimeError(msg["error"])
                return from_wire(msg.get("result"))

    def stream(
        self, method: str, **params: Any
    ) -> Iterator[Any]:
        """Server-stream iterator (subscribeKvStore / subscribeFib)."""
        with self._lock:
            self._connect()
            self._next_id += 1
            msg_id = self._next_id
            request = {"id": msg_id, "method": method, "params": to_wire(params)}
            self._sock.sendall(json.dumps(request).encode() + b"\n")

        def _iter() -> Iterator[Any]:
            while True:
                line = self._rfile.readline()
                if not line:
                    return
                msg = json.loads(line)
                if msg.get("id") != msg_id:
                    continue
                if "error" in msg:
                    raise RuntimeError(msg["error"])
                if "stream" in msg:
                    yield from_wire(msg["stream"])
                elif "result" in msg:
                    yield from_wire(msg["result"])
                    return

        return _iter()

    def cancel_streams(self) -> None:
        self.close()


class TcpKvStoreTransport:
    """KvStore peer transport over peers' ctrl servers (the reference's
    thrift peer-sync path).  Async, used from the KvStore event base; one
    short-lived connection per request (reconnect cost is absorbed by the
    peer FSM's backoff)."""

    def __init__(
        self,
        default_port: int = 2018,
        timeout_s: float = 10.0,
        tls=None,  # Optional[tls.TlsConfig] — peers require our cert too
    ) -> None:
        self.default_port = default_port
        self.timeout_s = timeout_s
        self.tls = tls
        # built eagerly: cert loading is blocking disk I/O that must not
        # run on the KvStore event loop, and bad paths should fail here
        self._ssl_ctx = None
        if tls is not None:
            from .tls import client_context

            self._ssl_ctx = client_context(tls)

    async def _call(self, peer: PeerSpec, method: str, params: dict) -> Any:
        host = peer.peer_addr
        port = peer.ctrl_port or self.default_port
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port, ssl=self._ssl_ctx),
            self.timeout_s,
        )
        if self._ssl_ctx is not None:
            from .tls import verify_peer

            try:
                verify_peer(self.tls, writer.get_extra_info("ssl_object"))
            except Exception:
                writer.close()
                raise
        try:
            request = {"id": 1, "method": method, "params": to_wire(params)}
            writer.write(json.dumps(request).encode() + b"\n")
            await writer.drain()
            line = await asyncio.wait_for(reader.readline(), self.timeout_s)
            if not line:
                raise ConnectionError("peer closed connection")
            msg = json.loads(line)
            if "error" in msg:
                raise RuntimeError(msg["error"])
            return from_wire(msg.get("result"))
        finally:
            writer.close()

    async def full_dump(self, peer: PeerSpec, area: str, params) -> Publication:
        result = await self._call(
            peer,
            "getKvStoreKeyValsFilteredArea",
            {
                "area": area,
                "prefixes": list(params.keys),
                "originators": list(params.originator_ids),
                "key_val_hashes": params.key_val_hashes,
            },
        )
        assert isinstance(result, Publication), type(result)
        return result

    async def key_set(self, peer: PeerSpec, area: str, params) -> None:
        await self._call(
            peer,
            "setKvStoreKeyVals",
            {
                "area": area,
                "key_vals": params.key_vals,
                "node_ids": params.node_ids,
                "flood_root_id": params.flood_root_id,
            },
        )

    async def dual_messages(self, peer: PeerSpec, area: str, msgs) -> None:
        await self._call(
            peer,
            "processKvStoreDualMessage",
            {"area": area, "messages": msgs},
        )

    async def flood_topo_set(self, peer: PeerSpec, area: str, params) -> None:
        await self._call(
            peer,
            "updateFloodTopologyChild",
            {"area": area, "params": params},
        )
