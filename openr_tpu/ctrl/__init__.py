"""Control API: the single RPC surface fronting every module.

Functional equivalent of the reference's OpenrCtrlHandler + ThriftServer
(openr/ctrl-server/OpenrCtrlHandler.h:53-381, served on port 2018): ~60
RPCs spanning KvStore (get/set/dump/subscribe/long-poll), Decision
(routes/adjacencies/RibPolicy), Fib (routes/perf), LinkMonitor
(drain/metric control), PrefixManager (advertise/withdraw), Spark
(neighbors), and counters — over a newline-delimited JSON protocol with
server streaming.  The same server doubles as the KvStore peer transport
(the reference's thrift peer sync path, SURVEY §2.3).
"""

from .client import CtrlClient, TcpKvStoreTransport
from .server import CtrlServer, OpenrCtrlHandler

__all__ = ["CtrlClient", "CtrlServer", "OpenrCtrlHandler", "TcpKvStoreTransport"]
