"""mTLS for the ctrl transport (reference: wangle TLS + peer-name ACL on
the thrift server, openr/Main.cpp:546-612).

Both sides present CA-signed certificates; the server additionally gates
connections on the client certificate's CommonName matching an ACL regex
(the reference's peer-name allowlist).  Hostname verification is
deliberately off on the client — routers connect by link-local/loopback
address, and identity is the certificate name, exactly as in the
reference's deployment model.
"""

from __future__ import annotations

import re
import ssl
from dataclasses import dataclass
from typing import Optional


@dataclass(slots=True)
class TlsConfig:
    cert_path: str
    key_path: str
    ca_path: str
    acl_regex: str = ".*"  # peer-CN allowlist (server side, and clients
    # verify the server's CN against it too — hostname checking is off,
    # so without this any CA-signed cert could impersonate a ctrl server)


def server_context(cfg: TlsConfig) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cfg.cert_path, cfg.key_path)
    ctx.load_verify_locations(cfg.ca_path)
    ctx.verify_mode = ssl.CERT_REQUIRED  # mTLS: clients must present certs
    return ctx


def client_context(cfg: TlsConfig) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.load_cert_chain(cfg.cert_path, cfg.key_path)
    ctx.load_verify_locations(cfg.ca_path)
    ctx.check_hostname = False  # identity = certificate name, not address
    ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def peer_common_name(ssl_object) -> Optional[str]:
    """CommonName of the peer certificate, or None."""
    cert = ssl_object.getpeercert()
    if not cert:
        return None
    for rdn in cert.get("subject", ()):
        for key, value in rdn:
            if key == "commonName":
                return value
    return None


def check_acl(cfg: TlsConfig, common_name: Optional[str]) -> bool:
    if common_name is None:
        return False
    return re.fullmatch(cfg.acl_regex, common_name) is not None


def verify_peer(cfg: TlsConfig, ssl_object) -> str:
    """Post-handshake peer-identity check for *clients*.

    With check_hostname off, the CA signature alone says nothing about
    *which* node we reached — a CA-signed cert the server-side ACL would
    reject (e.g. a decommissioned or rogue node) could otherwise
    impersonate a ctrl server / KvStore peer.  Mirrors the server's
    check_acl gate in the other direction; returns the verified CN.
    """
    cn = peer_common_name(ssl_object)
    if not check_acl(cfg, cn):
        raise ssl.SSLCertVerificationError(
            f"server certificate CN {cn!r} rejected by ACL {cfg.acl_regex!r}"
        )
    return cn
