"""mTLS for the ctrl transport (reference: wangle TLS + peer-name ACL on
the thrift server, openr/Main.cpp:546-612).

Both sides present CA-signed certificates; the server additionally gates
connections on the client certificate's CommonName matching an ACL regex
(the reference's peer-name allowlist).  Hostname verification is
deliberately off on the client — routers connect by link-local/loopback
address, and identity is the certificate name, exactly as in the
reference's deployment model.
"""

from __future__ import annotations

import re
import ssl
from dataclasses import dataclass
from typing import Optional


@dataclass(slots=True)
class TlsConfig:
    cert_path: str
    key_path: str
    ca_path: str
    acl_regex: str = ".*"  # client-CN allowlist (server side only)


def server_context(cfg: TlsConfig) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cfg.cert_path, cfg.key_path)
    ctx.load_verify_locations(cfg.ca_path)
    ctx.verify_mode = ssl.CERT_REQUIRED  # mTLS: clients must present certs
    return ctx


def client_context(cfg: TlsConfig) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.load_cert_chain(cfg.cert_path, cfg.key_path)
    ctx.load_verify_locations(cfg.ca_path)
    ctx.check_hostname = False  # identity = certificate name, not address
    ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def peer_common_name(ssl_object) -> Optional[str]:
    """CommonName of the peer certificate, or None."""
    cert = ssl_object.getpeercert()
    if not cert:
        return None
    for rdn in cert.get("subject", ()):
        for key, value in rdn:
            if key == "commonName":
                return value
    return None


def check_acl(cfg: TlsConfig, common_name: Optional[str]) -> bool:
    if common_name is None:
        return False
    return re.fullmatch(cfg.acl_regex, common_name) is not None
