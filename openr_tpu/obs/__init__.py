"""Observability: end-to-end span tracing + shared log-bucketed
histograms (docs/ARCHITECTURE.md "Tracing & histograms").

``obs.trace`` is imported late-bound by every seam (the OPENR_TSAN
arming discipline); ``obs.histogram`` replaces the tree's ad-hoc
percentile sites.  Neither imports jax.
"""

from .histogram import Histogram, export_histogram
from .trace import OBS_COUNTER_KEYS, ObsStats, Span, Tracer

__all__ = [
    "Histogram",
    "export_histogram",
    "OBS_COUNTER_KEYS",
    "ObsStats",
    "Span",
    "Tracer",
]
