"""End-to-end span tracing across queues, modules, and engine rungs.

Arming discipline (mirrors ``analysis/race.py`` OPENR_TSAN exactly):

- ``TRACE`` is a module-level constant, ``None`` unless armed.  Every
  seam in the tree reads it LATE-BOUND (``_trace.TRACE``, never
  ``from ... import TRACE``) and guards with a single
  ``if tr is not None`` — an attribute load per seam when off, no
  wrappers installed, no tokens allocated.
- ``OPENR_TRACE=1`` arms at import; ``OPENR_TRACE_SAMPLE=N`` keeps one
  in N roots (deterministic modulo counter, NOT random — the
  determinism contract below depends on it); ``OPENR_TRACE_RING=N``
  bounds completed-trace storage.
- Tests arm/disarm explicitly via :func:`enable` / :func:`disable`.

Span model: a trace is born at an entry point (serving query submit,
KvStore publication, Spark neighbor event) as a *root* span and flows
through the existing concurrency seams — RWQueue put→get carries the
active scope positionally next to the item (the ``_tsan_tokens``
pattern), OpenrEventBase handoffs re-activate the captured scope on the
loop thread, and batch execution activates EVERY coalesced query's span
at once so one engine annotation lands on each (fan-in scope).

Determinism contract: :meth:`Span.structure` serializes ONLY stage
names, structural tags (engine rung, dispatch kind, outcome), and the
child set — children sorted lexicographically, timers and ``note``
metadata excluded — so same-seed chaos replays produce byte-identical
structures and the fuzzer can ingest them as coverage tokens.

This module never imports jax (or anything heavier than stdlib).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import contextmanager, nullcontext
from typing import Any, Callable, Iterator, Optional, Sequence

# Pre-seeded registry (analysis: counter-unbumped checks seeds vs bumps).
OBS_COUNTER_KEYS = (
    "obs.traces_started",
    "obs.traces_sampled_out",
    "obs.traces_finished",
    "obs.spans_total",
    "obs.trace_ring_evictions",
)


def _now_us() -> int:
    return time.perf_counter_ns() // 1_000


class Span:
    """One stage of one traced request.

    ``tags`` are STRUCTURAL (part of the determinism contract:
    stages, rungs, retry/hedge edges); ``notes`` are informational
    (sizes, epochs, timings) and excluded from :meth:`structure`.
    Mutations go through the tracer's lock: spans cross threads
    (submit thread → eventbase → executor → reply thread) and a hedged
    call can have two replicas annotating the same span concurrently.
    """

    __slots__ = (
        "name",
        "parent",
        "children",
        "tags",
        "notes",
        "t_start_us",
        "t_end_us",
    )

    def __init__(self, name: str, parent: Optional["Span"] = None) -> None:
        self.name = name
        self.parent = parent
        self.children: list[Span] = []
        self.tags: dict[str, Any] = {}
        self.notes: dict[str, Any] = {}
        self.t_start_us = _now_us()
        self.t_end_us: Optional[int] = None

    # -- mutation (armed paths only; guarded by Tracer._lock) ---------------

    def root(self) -> "Span":
        sp = self
        while sp.parent is not None:
            sp = sp.parent
        return sp

    def finish(self) -> None:
        if self.t_end_us is None:
            self.t_end_us = _now_us()

    # -- canonical structure (the determinism contract) ---------------------

    def structure(self) -> str:
        tags = ",".join(f"{k}={v}" for k, v in sorted(self.tags.items()))
        kids = ",".join(sorted(c.structure() for c in self.children))
        return f"{self.name}({tags})[{kids}]"

    def to_dict(self, t0_us: Optional[int] = None) -> dict:
        """JSON-able tree with timings relative to the root start."""
        base = self.t_start_us if t0_us is None else t0_us
        end = self.t_end_us
        return {
            "name": self.name,
            "t_offset_us": self.t_start_us - base,
            "duration_us": None if end is None else end - self.t_start_us,
            "tags": dict(self.tags),
            "notes": dict(self.notes),
            "children": [c.to_dict(base) for c in self.children],
        }


class Tracer:
    """Span factory + thread-local scope stack + bounded trace ring."""

    def __init__(self, sample_every: int = 1, ring: int = 256) -> None:
        self.sample_every = max(1, int(sample_every))
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._ring: deque[Span] = deque(maxlen=max(1, int(ring)))
        self._structure_tokens: set[str] = set()
        self._n_roots = 0
        self._counters: dict[str, int] = {k: 0 for k in OBS_COUNTER_KEYS}

    # -- scope (thread-local) -----------------------------------------------

    def scope(self) -> tuple:
        return getattr(self._tls, "scope", ())

    @contextmanager
    def activate(self, spans: Sequence[Span]) -> Iterator[None]:
        """Make `spans` the current scope on this thread (replaces, does
        not nest-merge: a queue hop or batch activation IS the new
        attribution set)."""
        prev = getattr(self._tls, "scope", ())
        self._tls.scope = tuple(spans)
        try:
            yield
        finally:
            self._tls.scope = prev

    def bind_scope(self, fn: Callable[..., Any]) -> Callable[..., Any]:
        """Capture the current scope for a closure about to be marshalled
        to another thread (eventbase handoffs).  Identity when there is
        nothing to carry."""
        scope = self.scope()
        if not scope:
            return fn

        def _with_scope(*args: Any, **kwargs: Any) -> Any:
            with self.activate(scope):
                return fn(*args, **kwargs)

        return _with_scope

    # -- span creation ------------------------------------------------------

    def root(self, name: str, **tags: Any) -> Optional[Span]:
        """Trace-context birth at an entry point.  If a scope is already
        active (e.g. router → scheduler submit on the same thread) the
        trace EXTENDS instead: the new span is a child of the first
        active span.  True roots are sampled 1-in-``sample_every`` with
        a deterministic modulo counter."""
        scope = self.scope()
        if scope:
            return self.child_open(scope[0], name, **tags)
        with self._lock:
            self._n_roots += 1
            if (self._n_roots - 1) % self.sample_every:
                self._counters["obs.traces_sampled_out"] += 1
                return None
            self._counters["obs.traces_started"] += 1
            self._counters["obs.spans_total"] += 1
        sp = Span(name)
        sp.tags.update(tags)
        return sp

    def child_open(self, parent: Span, name: str, **tags: Any) -> Span:
        """Open (unfinished) child span; caller finishes it."""
        sp = Span(name, parent=parent)
        sp.tags.update(tags)
        with self._lock:
            parent.children.append(sp)
            self._counters["obs.spans_total"] += 1
        return sp

    @contextmanager
    def child(self, name: str, **tags: Any) -> Iterator[None]:
        """Completed child under EVERY span in the current scope; the
        children become the scope for the duration (so nested seams
        attribute under the stage, not beside it)."""
        scope = self.scope()
        if not scope:
            yield
            return
        kids = [self.child_open(sp, name, **tags) for sp in scope]
        try:
            with self.activate(kids):
                yield
        finally:
            now = _now_us()
            for k in kids:
                if k.t_end_us is None:
                    k.t_end_us = now

    def stage(
        self, span: Span, name: str, t0_us: int, t1_us: int, **tags: Any
    ) -> Span:
        """Append a completed child with explicit bounds (used when a
        stage's start was only timestamped, e.g. admission → drain)."""
        sp = Span(name, parent=span)
        sp.tags.update(tags)
        sp.t_start_us = t0_us
        sp.t_end_us = t1_us
        with self._lock:
            span.children.append(sp)
            self._counters["obs.spans_total"] += 1
        return sp

    def event(self, name: str, **tags: Any) -> None:
        """Zero-duration structural edge (retry, hedge, failover) on
        every span in the current scope."""
        now = _now_us()
        for sp in self.scope():
            ev = Span(name, parent=sp)
            ev.tags.update(tags)
            ev.t_start_us = ev.t_end_us = now
            with self._lock:
                sp.children.append(ev)
                self._counters["obs.spans_total"] += 1

    def annotate(self, key: str, value: Any) -> None:
        """Structural tag on every span in the current scope (engine
        rung attribution rides this)."""
        for sp in self.scope():
            with self._lock:
                sp.tags[key] = value

    def note(self, key: str, value: Any) -> None:
        """Non-structural metadata (sizes, epochs); excluded from
        :meth:`Span.structure`."""
        for sp in self.scope():
            with self._lock:
                sp.notes[key] = value

    # -- queue carry (put→get token, the _tsan_tokens pattern) --------------

    def carry(self) -> Optional[tuple]:
        """Token stored positionally next to a queued item at push."""
        scope = self.scope()
        return scope or None

    def set_carried(self, token: tuple) -> None:
        """Queue pop side: stash the popped token; the consumer adopts
        it with :meth:`take_carried` immediately after get() returns
        (same thread, no interleave before the adoption point)."""
        self._tls.carried = token

    def take_carried(self) -> tuple:
        tok = getattr(self._tls, "carried", None)
        self._tls.carried = None
        return tok or ()

    # -- completion ---------------------------------------------------------

    def finish(self, span: Span) -> None:
        """Finish a span; a ROOT lands in the bounded ring and its
        canonical structure joins the fuzzer-facing token set."""
        span.finish()
        if span.parent is not None:
            return
        with self._lock:
            self._counters["obs.traces_finished"] += 1
            if len(self._ring) == self._ring.maxlen:
                self._counters["obs.trace_ring_evictions"] += 1
            self._ring.append(span)
            self._structure_tokens.add(span.structure())

    def finish_root(self, span: Span) -> None:
        """Finish the ROOT of a carried span exactly once (terminal
        seams: reply delivered, Fib programmed)."""
        root = span.root()
        already = root.t_end_us is not None
        if not already:
            self.finish(root)

    # -- export -------------------------------------------------------------

    def dump(self, n: int = 16) -> list[dict]:
        with self._lock:
            recent = list(self._ring)[-max(0, int(n)):]
        return [sp.to_dict() for sp in recent]

    def span_samples(self, n: int = 32) -> list[dict]:
        """Recent traces grouped by canonical structure, with counts and
        duration attribution per distinct shape."""
        with self._lock:
            recent = list(self._ring)
        groups: dict[str, dict] = {}
        for sp in recent:
            key = sp.structure()
            g = groups.get(key)
            dur = (sp.t_end_us or sp.t_start_us) - sp.t_start_us
            if g is None:
                groups[key] = {"structure": key, "count": 1, "max_us": dur}
            else:
                g["count"] += 1
                g["max_us"] = max(g["max_us"], dur)
        out = sorted(groups.values(), key=lambda g: -g["count"])
        return out[: max(0, int(n))]

    def drain_structure_tokens(self) -> frozenset:
        """Pop the accumulated canonical-structure set (fuzzer coverage
        fingerprint ingestion; each run drains its own tokens)."""
        with self._lock:
            toks, self._structure_tokens = frozenset(self._structure_tokens), set()
        return toks

    def get_counters(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counters)


class ObsStats:
    """The ctrl handler's ``obs`` surface.  Reads ``TRACE`` late-bound so
    the daemon dumps zeroed ``obs.*`` counters (and empty trace lists)
    when tracing is unarmed — the wire shape is arming-independent."""

    def get_counters(self) -> dict[str, int]:
        tr = TRACE
        if tr is None:
            return {k: 0 for k in OBS_COUNTER_KEYS}
        return tr.get_counters()

    def dump_traces(self, n: int = 16) -> list[dict]:
        tr = TRACE
        return [] if tr is None else tr.dump(n)

    def span_samples(self, n: int = 32) -> list[dict]:
        tr = TRACE
        return [] if tr is None else tr.span_samples(n)


# -- arming ------------------------------------------------------------------

TRACE: Optional[Tracer] = None

_NULL = nullcontext()


def maybe_child(name: str, **tags: Any):
    """Seam helper for cold paths: a completed child under the current
    scope when armed, a shared no-op context when off (one module
    function call; hot paths use the explicit ``if tr is not None``
    guard instead)."""
    tr = TRACE
    return _NULL if tr is None else tr.child(name, **tags)


def enable(sample_every: int = 1, ring: int = 256) -> Tracer:
    """Arm tracing (tests, bench, ops).  Returns the installed tracer."""
    global TRACE
    TRACE = Tracer(sample_every=sample_every, ring=ring)
    return TRACE


def disable() -> None:
    global TRACE
    TRACE = None


def maybe_enable() -> Optional[Tracer]:
    """Arm from the environment (OPENR_TRACE=1); no-op when already
    armed or unrequested."""
    if TRACE is not None:
        return TRACE
    if os.environ.get("OPENR_TRACE", "") != "1":
        return None
    return enable(
        sample_every=int(os.environ.get("OPENR_TRACE_SAMPLE", "1")),
        ring=int(os.environ.get("OPENR_TRACE_RING", "256")),
    )


maybe_enable()
