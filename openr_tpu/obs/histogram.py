"""Shared power-of-two-bucket latency histogram.

Replaces every ad-hoc percentile site (the serving scheduler's
``sorted(deque)``-per-``getCounters`` snapshot was the worst offender):
``record_us`` is O(1) — one ``bit_length`` and one bucket increment —
and percentile reads walk at most ``N_BUCKETS`` counts instead of
sorting a ring.

Bucket ``i`` holds values whose ``int.bit_length() == i``, i.e. the
half-open range ``[2^(i-1), 2^i)`` microseconds (bucket 0 holds exact
zeros).  Percentiles report the bucket's inclusive upper bound
(``2^i - 1``) — a <=2x overestimate by construction, monotone, and
cheap; wire keys stay ``<family>.p50_us/p99_us/p999_us`` so dashboards
keyed on the old exact-percentile names keep working.

Export goes through :func:`export_histogram` with a LITERAL family
string at every call site — the static analyzer recognizes that call
shape and credits the derived ``<family>.p*_us`` keys as bump sites
(see analysis/counters.py), keeping the counter-unbumped rule honest
for keys built with f-strings.

Cross-replica roll-up: bucket counts (``<family>.hist_us.b<i>``) and
``<family>.hist_us.count`` are plain sums; only the derived ``p*_us``
gauges need max-aggregation (serving/router.py ``_GAUGE_KEYS``).

Never imports jax.
"""

from __future__ import annotations

import threading

# 2^39 us ~= 6.4 days: anything slower is a bug, not a latency.
N_BUCKETS = 40

_PCTLS = ((50, "p50_us"), (99, "p99_us"), (99.9, "p999_us"))


class Histogram:
    """Thread-safe log2-bucketed microsecond histogram."""

    __slots__ = ("counts", "n", "_lock")

    def __init__(self) -> None:
        self.counts = [0] * N_BUCKETS
        self.n = 0
        self._lock = threading.Lock()

    def record_us(self, us: int) -> None:
        i = min(int(us).bit_length(), N_BUCKETS - 1) if us > 0 else 0
        with self._lock:
            self.counts[i] += 1
            self.n += 1

    def snapshot(self) -> tuple[list[int], int]:
        with self._lock:
            return list(self.counts), self.n

    def percentile_us(self, p: float) -> int:
        counts, n = self.snapshot()
        return _pctl_from_counts(counts, n, p)

    def merge(self, other: "Histogram") -> None:
        counts, n = other.snapshot()
        with self._lock:
            for i, c in enumerate(counts):
                self.counts[i] += c
            self.n += n


def _pctl_from_counts(counts: list[int], n: int, p: float) -> int:
    if n <= 0:
        return 0
    rank = max(1, int(n * p / 100.0 + 0.999999))
    seen = 0
    for i, c in enumerate(counts):
        seen += c
        if seen >= rank:
            return (1 << i) - 1 if i else 0
    return (1 << (N_BUCKETS - 1)) - 1


def export_histogram(counters: dict, family: str, hist: Histogram) -> None:
    """Dump one histogram family into a counters dict: the three derived
    percentile gauges plus the non-empty buckets and the total count.
    Call sites MUST pass ``family`` as a string literal (analyzer
    contract, see module docstring)."""
    counts, n = hist.snapshot()
    for p, suffix in _PCTLS:
        counters[f"{family}.{suffix}"] = _pctl_from_counts(counts, n, p)
    counters[f"{family}.hist_us.count"] = n
    for i, c in enumerate(counts):
        if c:
            counters[f"{family}.hist_us.b{i}"] = c
