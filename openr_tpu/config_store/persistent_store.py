"""PersistentStore: disk-backed KV (TLV append log + periodic full rewrite).

Functional equivalent of the reference's PersistentStore
(openr/config-store/PersistentStore.{h,cpp}): a TLV file starting with
'TlvFormatMarker', holding encoded PersistentObjects (ADD key/data, DEL
key).  Mutations append to the log; a debounced/backed-off timer rewrites
the full database periodically to bound file growth.  Used by LinkMonitor
(drain state) and PrefixAllocator (allocated prefix index).

File format (little-endian):
    b"TlvFormatMarker"
    repeated records: [type u8][key_len u32][key][has_data u8][data_len u32][data]
"""

from __future__ import annotations

import enum
import logging
import os
import struct
import threading
from dataclasses import dataclass
from typing import Optional

log = logging.getLogger(__name__)

TLV_MARKER = b"TlvFormatMarker"
# reference: Constants::kPersistentStoreInitialBackoff / kMaxBackoff
SAVE_INITIAL_BACKOFF_S = 0.1
SAVE_MAX_BACKOFF_S = 10.0


class ActionType(enum.IntEnum):
    ADD = 1
    DEL = 2


@dataclass(slots=True)
class PersistentObject:
    type: ActionType
    key: str
    data: Optional[bytes] = None


def encode_persistent_object(obj: PersistentObject) -> bytes:
    key = obj.key.encode()
    out = struct.pack("<BI", int(obj.type), len(key)) + key
    if obj.data is not None:
        out += struct.pack("<BI", 1, len(obj.data)) + obj.data
    else:
        out += struct.pack("<BI", 0, 0)
    return out


def decode_persistent_objects(
    buf: bytes, tolerate_truncation: bool = False
) -> list[PersistentObject]:
    """Decode records; with tolerate_truncation a torn final append yields
    the clean prefix instead of raising."""
    objs: list[PersistentObject] = []
    off = 0
    n = len(buf)
    while off < n:
        try:
            if off + 5 > n:
                raise ValueError("truncated record header")
            typ, key_len = struct.unpack_from("<BI", buf, off)
            noff = off + 5
            if noff + key_len + 5 > n:
                raise ValueError("truncated key")
            key = buf[noff : noff + key_len].decode()
            noff += key_len
            has_data, data_len = struct.unpack_from("<BI", buf, noff)
            noff += 5
            data = None
            if has_data:
                if noff + data_len > n:
                    raise ValueError("truncated data")
                data = buf[noff : noff + data_len]
                noff += data_len
            objs.append(PersistentObject(ActionType(typ), key, data))
            off = noff
        except ValueError:
            if tolerate_truncation:
                return objs
            raise
    return objs


class PersistentStore:
    """Thread-safe; no event loop needed (callers are module threads, I/O
    is tiny and synchronous — the reference's async API exists because of
    folly, not semantics)."""

    def __init__(
        self,
        storage_file_path: str,
        dryrun: bool = False,
        periodic_save_s: Optional[float] = None,
    ) -> None:
        self.path = storage_file_path
        self.dryrun = dryrun
        self._lock = threading.RLock()
        self._db: dict[str, bytes] = {}
        self.num_writes_to_disk = 0
        self._load_from_disk()
        self._periodic_save_s = periodic_save_s
        self._timer: Optional[threading.Timer] = None
        if periodic_save_s:
            self._schedule_periodic_save()

    # -- public API (reference: store/load/erase) ----------------------------

    def store(self, key: str, value: bytes) -> None:
        with self._lock:
            self._db[key] = bytes(value)
            self._append(PersistentObject(ActionType.ADD, key, bytes(value)))

    def load(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._db.get(key)

    def erase(self, key: str) -> bool:
        with self._lock:
            existed = self._db.pop(key, None) is not None
            if existed:
                self._append(PersistentObject(ActionType.DEL, key))
            return existed

    def keys(self) -> list[str]:
        with self._lock:
            return sorted(self._db)

    def close(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        self.save_database_to_disk()

    # -- disk I/O ------------------------------------------------------------

    def _append(self, obj: PersistentObject) -> None:
        if self.dryrun:
            return
        try:
            with open(self.path, "ab") as f:
                if f.tell() == 0:
                    f.write(TLV_MARKER)
                f.write(encode_persistent_object(obj))
            # guarded by the caller: set()/erase() enter _append inside
            # `with self._lock` (RLock), so this increment never runs bare
            self.num_writes_to_disk += 1  # openr: disable=guarded-by
        except OSError:
            # _db already holds the mutation; the next full rewrite
            # reconciles the file
            log.exception("config-store: append failed")

    def save_database_to_disk(self) -> bool:
        """Full rewrite (reference: saveDatabaseToDisk)."""
        if self.dryrun:
            return True
        with self._lock:
            blob = TLV_MARKER + b"".join(
                encode_persistent_object(
                    PersistentObject(ActionType.ADD, key, data)
                )
                for key, data in sorted(self._db.items())
            )
            try:
                tmp = f"{self.path}.tmp.{os.getpid()}"
                with open(tmp, "wb") as f:
                    f.write(blob)
                os.replace(tmp, self.path)
                self.num_writes_to_disk += 1
                return True
            except OSError:
                log.exception("config-store: full rewrite failed")
                return False

    def _load_from_disk(self) -> None:
        try:
            with open(self.path, "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            return
        except OSError:
            log.exception("config-store: read failed")
            return
        if not blob.startswith(TLV_MARKER):
            log.error("config-store: bad marker in %s; ignoring file", self.path)
            return
        objs = decode_persistent_objects(
            blob[len(TLV_MARKER) :], tolerate_truncation=True
        )
        for obj in objs:
            if obj.type == ActionType.ADD:
                self._db[obj.key] = obj.data or b""
            else:
                self._db.pop(obj.key, None)

    def _schedule_periodic_save(self) -> None:
        def _tick() -> None:
            self.save_database_to_disk()
            with self._lock:
                if self._timer is not None:
                    self._schedule_periodic_save()

        self._timer = threading.Timer(self._periodic_save_s, _tick)
        self._timer.daemon = True
        self._timer.start()
