"""PersistentStore: disk-backed KV surviving restarts."""

from .persistent_store import PersistentObject, PersistentStore

__all__ = ["PersistentObject", "PersistentStore"]
