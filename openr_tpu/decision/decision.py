"""Decision module: KvStore publications in, route-update deltas out.

Functional equivalent of the reference's Decision event base
(openr/decision/Decision.{h,cpp}:1398-2050): fiber readers over the KvStore
publication and static-routes queues, per-key publication parsing
("adj:" / "prefix:" / "fibTime:"), pending-update batching with oldest-wins
perf events, debounced full/incremental route rebuild, cold-start hold,
RibPolicy application with TTL expiry, and ordered-FIB hold decrements.
"""

from __future__ import annotations

import logging
from typing import Optional

log = logging.getLogger(__name__)

from ..obs import trace as _trace
from ..runtime.async_util import AsyncDebounce
from ..runtime.eventbase import OpenrEventBase
from ..runtime.queue import QueueClosedError, ReplicateQueue, RQueue
from ..serializer import loads
from ..types import (
    ADJ_MARKER,
    AdjacencyDatabase,
    PerfEvents,
    Publication,
    PREFIX_MARKER,
    PrefixDatabase,
    add_perf_event,
    node_name_from_key,
    normalize_prefix,
    parse_prefix_key,
)
from .link_state import LinkState, LinkStateChange
from .prefix_state import PrefixState
from .rib import DecisionRouteDb, DecisionRouteUpdate
from .rib_policy import PolicyError, RibPolicy, RibPolicyConfig
from .spf_solver import HostSpfBackend, SpfBackend, SpfSolver

FIB_TIME_MARKER = "fibTime:"


class DecisionPendingUpdates:
    """Reference: detail::DecisionPendingUpdates
    (openr/decision/Decision.h:121-196, Decision.cpp:45-107)."""

    def __init__(self, my_node_name: str) -> None:
        self.my_node_name = my_node_name
        self.count = 0
        self.perf_events: Optional[PerfEvents] = None
        self.needs_full_rebuild = False
        self.updated_prefixes: set[str] = set()

    def needs_route_update(self) -> bool:
        return self.needs_full_rebuild or bool(self.updated_prefixes)

    def set_needs_full_rebuild(self) -> None:
        self.needs_full_rebuild = True

    def apply_link_state_change(
        self,
        node_name: str,
        change: LinkStateChange,
        perf_events: Optional[PerfEvents],
    ) -> None:
        self.needs_full_rebuild |= (
            change.topology_changed
            or change.node_label_changed
            # link attribute changes only matter locally (nexthop/label)
            or (change.link_attributes_changed and node_name == self.my_node_name)
        )
        self._add_update(perf_events)

    def apply_prefix_state_change(
        self, change: set[str], perf_events: Optional[PerfEvents] = None
    ) -> None:
        self.updated_prefixes |= change
        self._add_update(perf_events)

    def reset(self) -> None:
        self.count = 0
        self.perf_events = None
        self.needs_full_rebuild = False
        self.updated_prefixes = set()

    def add_event(self, event: str) -> None:
        if self.perf_events is not None:
            add_perf_event(self.perf_events, self.my_node_name, event)

    def move_out_events(self) -> Optional[PerfEvents]:
        events, self.perf_events = self.perf_events, None
        return events

    def _add_update(self, perf_events: Optional[PerfEvents]) -> None:
        self.count += 1
        # keep the OLDEST event list in the batch for convergence measurement
        if self.perf_events is None or (
            perf_events is not None
            and perf_events.events
            and self.perf_events.events
            and self.perf_events.events[0].unix_ts_ms
            > perf_events.events[0].unix_ts_ms
        ):
            self.perf_events = (
                PerfEvents(list(perf_events.events)) if perf_events else PerfEvents()
            )
            self.add_event("DECISION_RECEIVED")


class Decision(OpenrEventBase):
    """The Decision event base."""

    def __init__(
        self,
        my_node_name: str,
        kvstore_updates: RQueue[Publication],
        static_routes_updates: Optional[RQueue[DecisionRouteUpdate]],
        route_updates_queue: ReplicateQueue[DecisionRouteUpdate],
        *,
        debounce_min_s: float = 0.01,
        debounce_max_s: float = 0.25,
        eor_time_s: Optional[float] = None,
        enable_v4: bool = True,
        enable_ordered_fib: bool = False,
        bgp_dry_run: bool = False,
        enable_best_route_selection: bool = False,
        enable_rib_policy: bool = False,
        spf_backend: Optional[SpfBackend] = None,
        fleet_delta: Optional[bool] = None,
    ) -> None:
        super().__init__(name="decision")
        self.my_node_name = my_node_name
        self._kvstore_updates = kvstore_updates
        self._static_routes_updates = static_routes_updates
        self._route_updates_queue = route_updates_queue
        self._debounce_bounds = (debounce_min_s, debounce_max_s)
        self._eor_time_s = eor_time_s
        self._enable_ordered_fib = enable_ordered_fib
        self._enable_rib_policy = enable_rib_policy

        self.spf_solver = SpfSolver(
            my_node_name,
            enable_v4=enable_v4,
            bgp_dry_run=bgp_dry_run,
            enable_best_route_selection=enable_best_route_selection,
            spf_backend=spf_backend,
            fleet_delta=fleet_delta,
        )
        self.area_link_states: dict[str, LinkState] = {}
        self.prefix_state = PrefixState()
        self.pending_updates = DecisionPendingUpdates(my_node_name)
        self.route_db = DecisionRouteDb()
        self.rib_policy: Optional[RibPolicy] = None
        self._rib_policy_timeout = None
        self._fib_times: dict[str, float] = {}  # node -> fib time (s)
        self._rebuild_debounced: Optional[AsyncDebounce] = None
        self._cold_start_pending = eor_time_s is not None
        self._ordered_fib_timeout = None
        # topology events admitted since the last route rebuild — the
        # serving layer's admission defer hint (QueryScheduler
        # defer_hint): while events are pending, freshly coalesced query
        # batches briefly hold so they pin the POST-storm epoch and ride
        # the delta-updated product instead of dispatching against a
        # topology about to be invalidated
        self._pending_events = 0
        # OPENR_TRACE: publication spans carried across kvstore_updates
        # and awaiting the (debounced) rebuild that folds them in.
        # Eventbase-thread only — no lock needed.
        self._trace_pending: list = []
        self.counters: dict[str, int] = {}

    def _bump(self, counter: str, n: int = 1) -> None:
        self.counters[counter] = self.counters.get(counter, 0) + n

    def pending_event_hint(self) -> int:
        """Topology events admitted but not yet folded into routes —
        non-zero while a flap storm is mid-coalesce.  Thread-safe enough
        for its purpose (an int read; the serving defer wait is bounded
        either way)."""
        return self._pending_events

    def get_counters(self) -> dict[str, int]:
        """Module + solver counters merged (fb303-style export)."""
        out = dict(self.spf_solver.counters)
        for k, v in self.counters.items():
            out[k] = out.get(k, 0) + v
        return out

    # -- lifecycle -----------------------------------------------------------

    def run(self) -> None:
        super().run()
        self.wait_until_running()
        self.run_in_event_base_thread(self._setup).result()

    def _setup(self) -> None:
        self._rebuild_debounced = AsyncDebounce(
            self._debounce_bounds[0],
            self._debounce_bounds[1],
            lambda: self.rebuild_routes("DECISION_DEBOUNCE"),
        )
        if self._cold_start_pending:
            self.schedule_timeout(self._eor_time_s, self._cold_start_expired)
        self.add_fiber_task(self._kvstore_fiber(), name="kvStoreUpdates")
        if self._static_routes_updates is not None:
            self.add_fiber_task(self._static_routes_fiber(), name="staticRoutes")

    def _cold_start_expired(self) -> None:
        self._cold_start_pending = False
        self.pending_updates.set_needs_full_rebuild()
        self.rebuild_routes("COLD_START_UPDATE")

    async def _kvstore_fiber(self) -> None:
        while True:
            try:
                pub = await self._kvstore_updates.aget()
            except QueueClosedError:
                return
            tr = _trace.TRACE
            if tr is not None:
                self._trace_pending.extend(tr.take_carried())
            self.process_publication(pub)
            if self.pending_updates.needs_route_update():
                self._pending_events += 1
                self._rebuild_debounced()

    async def _static_routes_fiber(self) -> None:
        while True:
            try:
                update = await self._static_routes_updates.aget()
            except QueueClosedError:
                return
            self.process_static_routes_update(update)

    # -- publication processing ---------------------------------------------

    def process_publication(self, pub: Publication) -> None:
        """Reference: Decision::processPublication (Decision.cpp:1683-1790)."""
        area = pub.area
        if not area:
            log.error("decision: dropping publication without area")
            self._bump("decision.error")
            return
        link_state = self.area_link_states.setdefault(area, LinkState(area))

        if not pub.key_vals and not pub.expired_keys:
            return

        for key, val in pub.key_vals.items():
            if val.value is None:
                continue  # TTL-refresh only
            try:
                self._process_key_val(key, val, area, link_state)
            except Exception:  # corrupt value: skip key, keep the fiber alive
                # (reference: per-key try/catch, Decision.cpp:1786-1789)
                log.exception("decision: failed to process key %r", key)
                self._bump("decision.error")

        for key in pub.expired_keys:
            try:
                self._process_expired_key(key, area, link_state)
            except Exception:
                log.exception("decision: failed to process expired key %r", key)
                self._bump("decision.error")

    def _process_expired_key(
        self, key: str, area: str, link_state: LinkState
    ) -> None:
        node = node_name_from_key(key)
        if key.startswith(ADJ_MARKER):
            self.pending_updates.apply_link_state_change(
                node, link_state.delete_adjacency_database(node), None
            )
        elif key.startswith(PREFIX_MARKER):
            parsed = parse_prefix_key(key)
            if parsed is None:
                return
            pnode, _parea, prefix = parsed
            self.pending_updates.apply_prefix_state_change(
                self.prefix_state.delete_prefix(pnode, area, prefix), None
            )

    def _process_key_val(
        self, key: str, val, area: str, link_state: LinkState
    ) -> None:
        if key.startswith(ADJ_MARKER):
            adj_db = loads(val.value, AdjacencyDatabase)
            adj_db.area = area
            hold_up_ttl = hold_down_ttl = 0
            if self._enable_ordered_fib:
                hops = link_state.get_hops_from_a_to_b(
                    self.my_node_name, adj_db.this_node_name
                )
                if hops is not None:
                    hold_up_ttl = int(hops)
                    hold_down_ttl = (
                        link_state.get_max_hops_to_node(adj_db.this_node_name)
                        - hold_up_ttl
                    )
            self._bump("decision.adj_db_update")
            self.pending_updates.apply_link_state_change(
                adj_db.this_node_name,
                link_state.update_adjacency_database(
                    adj_db, hold_up_ttl, hold_down_ttl
                ),
                adj_db.perf_events,
            )
            if (
                self._enable_ordered_fib
                and link_state.has_holds()
                and self._ordered_fib_timeout is None
            ):
                self._schedule_ordered_fib_decrement()
        elif key.startswith(PREFIX_MARKER):
            prefix_db = loads(val.value, PrefixDatabase)
            if len(prefix_db.prefix_entries) != 1:
                self._bump("decision.error")
                return
            entry = prefix_db.prefix_entries[0]
            # ignore self-redistributed route reflection
            if (
                prefix_db.this_node_name == self.my_node_name
                and entry.area_stack
                and entry.area_stack[-1] in self.area_link_states
            ):
                return
            self._bump("decision.prefix_db_update")
            node = prefix_db.this_node_name
            change = (
                self.prefix_state.delete_prefix(node, area, entry.prefix)
                if prefix_db.delete_prefix
                else self.prefix_state.update_prefix(node, area, entry)
            )
            self.pending_updates.apply_prefix_state_change(
                change, prefix_db.perf_events
            )
        elif key.startswith(FIB_TIME_MARKER):
            try:
                self._fib_times[node_name_from_key(key)] = (
                    float(val.value.decode()) / 1000.0
                )
            except (ValueError, AttributeError):
                pass

    def process_static_routes_update(self, delta: DecisionRouteUpdate) -> None:
        """Reference: processStaticRoutesUpdate (Decision.cpp:1829-1864)."""
        if delta.unicast_routes_to_update or delta.unicast_routes_to_delete:
            to_update = [
                e.to_unicast_route() for e in delta.unicast_routes_to_update.values()
            ]
            self.spf_solver.update_static_unicast_routes(
                to_update, delta.unicast_routes_to_delete
            )
            change = {normalize_prefix(p) for p in delta.unicast_routes_to_update}
            change |= {
                normalize_prefix(p) for p in delta.unicast_routes_to_delete
            }
            self.pending_updates.apply_prefix_state_change(change, None)
        if delta.mpls_routes_to_update or delta.mpls_routes_to_delete:
            self.spf_solver.update_static_mpls_routes(
                [e.to_mpls_route() for e in delta.mpls_routes_to_update],
                delta.mpls_routes_to_delete,
            )
            self.pending_updates.set_needs_full_rebuild()
        if self._rebuild_debounced is not None:
            self._rebuild_debounced()

    # -- route rebuild -------------------------------------------------------

    def rebuild_routes(self, event: str) -> None:
        """Reference: rebuildRoutes (Decision.cpp:1866-1935)."""
        if self._cold_start_pending:
            return
        tr = _trace.TRACE
        pending, self._trace_pending = self._trace_pending, []
        if tr is not None and pending:
            # fan-in: the debounced rebuild folds every carried
            # publication at once — open a "decision" stage under each
            # and activate them all so the route push carries them on
            spans = [
                tr.child_open(sp, "decision", event=event)
                for sp in dict.fromkeys(pending)
            ]
            try:
                with tr.activate(spans):
                    self._rebuild_routes_impl(event)
            finally:
                for sp in spans:
                    sp.finish()
            return
        self._rebuild_routes_impl(event)

    def _rebuild_routes_impl(self, event: str) -> None:
        self.pending_updates.add_event(event)

        try:
            update = self._compute_route_update()
        except Exception:
            # degradation ladder bottom rung: the solver's own device->
            # host fallbacks should make this unreachable, but a rebuild
            # failure must NEVER drop the route publication — demote the
            # solver to the host oracle permanently and recompute full
            log.exception(
                "decision: route rebuild failed; recomputing on host oracle"
            )
            self.spf_solver._bump("decision.device_fallbacks")
            self._bump("decision.route_rebuild_fallbacks")
            self.spf_solver.spf = HostSpfBackend()
            self.pending_updates.set_needs_full_rebuild()
            update = self._compute_route_update()

        self.route_db.update(update)
        self.pending_updates.add_event("ROUTE_UPDATE")
        update.perf_events = self.pending_updates.move_out_events()
        self.pending_updates.reset()
        # the rebuild folded every admitted event (delta rung or full):
        # deferred query batches may pin the fresh epoch now
        self._pending_events = 0
        self._route_updates_queue.push(update)

    def _compute_route_update(self) -> DecisionRouteUpdate:
        update = DecisionRouteUpdate()
        if self.pending_updates.needs_full_rebuild:
            maybe_db = self.spf_solver.build_route_db(
                self.area_link_states, self.prefix_state
            )
            db = maybe_db if maybe_db is not None else DecisionRouteDb()
            if self.rib_policy is not None:
                self.rib_policy.apply_policy(db.unicast_routes)
            update = self.route_db.calculate_update(db)
        else:
            for prefix in self.pending_updates.updated_prefixes:
                route = self.spf_solver.create_route_for_prefix_or_get_static_route(
                    self.area_link_states, self.prefix_state, prefix
                )
                if route is not None:
                    update.add_route_to_update(route)
                else:
                    update.unicast_routes_to_delete.append(prefix)
            if self.rib_policy is not None:
                changes = self.rib_policy.apply_policy(
                    update.unicast_routes_to_update
                )
                update.unicast_routes_to_delete.extend(changes.deleted_routes)
        return update

    # -- ordered-FIB holds ---------------------------------------------------

    def _max_fib_time_s(self) -> float:
        return max(self._fib_times.values(), default=0.001)

    def _schedule_ordered_fib_decrement(self) -> None:
        self._ordered_fib_timeout = self.schedule_timeout(
            self._max_fib_time_s(), self._decrement_ordered_fib_holds
        )

    def _decrement_ordered_fib_holds(self) -> None:
        """Reference: decrementOrderedFibHolds (Decision.cpp:1938-1955)."""
        self._ordered_fib_timeout = None
        still_has_holds = False
        for link_state in self.area_link_states.values():
            self.pending_updates.apply_link_state_change(
                self.my_node_name, link_state.decrement_holds(), None
            )
            still_has_holds |= link_state.has_holds()
        if self.pending_updates.needs_route_update():
            self.rebuild_routes("ORDERED_FIB_HOLDS_EXPIRED")
        if still_has_holds:
            self._schedule_ordered_fib_decrement()

    # -- thread-safe control API (reference: Decision.cpp:1510-1680) ---------

    def get_route_db(self, node_name: str = "") -> DecisionRouteDb:
        """Compute any node's routes (reference: getDecisionRouteDb).
        Other-node queries go through the fleet-product path
        (spf_solver.any_node_route_db): a warm reduced all-sources view
        answers them with zero device work."""

        def _compute() -> DecisionRouteDb:
            target = node_name or self.my_node_name
            if target != self.my_node_name:
                db = self.spf_solver.any_node_route_db(
                    self.area_link_states, self.prefix_state, target
                )
            else:
                db = self.spf_solver.build_route_db(
                    self.area_link_states,
                    self.prefix_state,
                    my_node_name=target,
                )
            return db if db is not None else DecisionRouteDb()

        return self.run_in_event_base_thread(_compute).result()

    # Fleet dumps build one DecisionRouteDb per node and serialize as a
    # single response: an unbounded dump at 100k-node scale is a
    # multi-GB allocation on the Decision thread (the Watchdog RSS
    # limit would abort the daemon).  Operators page with `nodes=`.
    MAX_FLEET_DUMP_NODES = 8192

    def get_fleet_route_dbs(
        self, nodes: Optional[list[str]] = None
    ) -> dict[str, DecisionRouteDb]:
        """Fleet-wide route dump from ONE reverse-SSSP device round per
        area (spf_solver.fleet_route_dbs; consumer of ops.allsources).
        `nodes` defaults to every known node, bounded by
        MAX_FLEET_DUMP_NODES."""

        def _compute() -> dict[str, DecisionRouteDb]:
            if nodes is None:
                total = len(
                    {
                        n
                        for ls in self.area_link_states.values()
                        for n in ls.node_names
                    }
                )
                if total > self.MAX_FLEET_DUMP_NODES:
                    raise ValueError(
                        f"fleet dump of {total} nodes exceeds "
                        f"{self.MAX_FLEET_DUMP_NODES}; pass an explicit "
                        "node list (breeze: --nodes)"
                    )
            elif len(nodes) > self.MAX_FLEET_DUMP_NODES:
                raise ValueError(
                    f"fleet dump of {len(nodes)} nodes exceeds "
                    f"{self.MAX_FLEET_DUMP_NODES}"
                )
            return self.spf_solver.fleet_route_dbs(
                self.area_link_states, self.prefix_state, nodes=nodes
            )

        return self.run_in_event_base_thread(_compute).result()

    def get_adjacency_databases(
        self, select_areas: Optional[set[str]] = None
    ) -> list[AdjacencyDatabase]:
        def _get() -> list[AdjacencyDatabase]:
            out: list[AdjacencyDatabase] = []
            for area, ls in self.area_link_states.items():
                if not select_areas or area in select_areas:
                    out.extend(ls.get_adjacency_databases().values())
            return out

        return self.run_in_event_base_thread(_get).result()

    def what_if(
        self,
        scenarios: list[list[tuple[str, str]]],
        area: str = "0",
        sources: Optional[list[str]] = None,
    ) -> list[dict]:
        """Batched SRLG what-if failure analysis (operator surface over
        ops.protection.srlg_what_if; new capability vs the reference)."""

        def _compute() -> list[dict]:
            from .protection_api import what_if as run

            ls = self.area_link_states.get(area)
            if ls is None:
                return []
            # default the impact view to this router (all-sources at scale
            # is cubic output and would stall the Decision thread)
            srcs = sources if sources is not None else [self.my_node_name]
            return run(ls, scenarios, srcs, csr=self._protection_csr(ls))

        return self.run_in_event_base_thread(_compute).result()

    def _protection_csr(self, ls):
        """Reuse the device backend's incrementally-maintained CSR mirror
        when available (spf_solver.DeviceSpfBackend.csr_mirror)."""
        mirror = getattr(self.spf_solver.spf, "csr_mirror", None)
        return mirror(ls) if mirror is not None else None

    def get_ti_lfa(self, node: str = "", area: str = "0") -> dict:
        """Per-adjacency TI-LFA backup analysis (operator surface over
        ops.protection.ti_lfa_backups; new capability vs the reference)."""

        def _compute() -> dict:
            from .protection_api import ti_lfa as run

            ls = self.area_link_states.get(area)
            if ls is None:
                return {"node": node or self.my_node_name, "error": "no area"}
            return run(
                ls, node or self.my_node_name, csr=self._protection_csr(ls)
            )

        return self.run_in_event_base_thread(_compute).result()

    def get_received_routes(self, **filters) -> list:
        return self.run_in_event_base_thread(
            lambda: self.prefix_state.get_received_routes_filtered(**filters)
        ).result()

    def set_rib_policy(self, cfg: RibPolicyConfig) -> None:
        if not self._enable_rib_policy:
            raise PolicyError("RibPolicy feature is not enabled")
        policy = RibPolicy(cfg)  # validate on caller thread

        def _set() -> None:
            self.rib_policy = policy
            if self._rib_policy_timeout is not None:
                self._rib_policy_timeout.cancel()
            self._rib_policy_timeout = self.schedule_timeout(
                policy.get_ttl_duration_s(), self._rib_policy_expired
            )
            self.pending_updates.set_needs_full_rebuild()
            self.rebuild_routes("RIB_POLICY_SET")

        self.run_in_event_base_thread(_set).result()

    def _rib_policy_expired(self) -> None:
        self._rib_policy_timeout = None
        self.pending_updates.set_needs_full_rebuild()
        self.rebuild_routes("RIB_POLICY_EXPIRED")

    def get_rib_policy(self) -> RibPolicyConfig:
        if not self._enable_rib_policy:
            raise PolicyError("RibPolicy feature is not enabled")

        def _get() -> RibPolicyConfig:
            if self.rib_policy is None:
                raise PolicyError("No RIB policy configured")
            return self.rib_policy.to_config()

        return self.run_in_event_base_thread(_get).result()

    def clear_rib_policy(self) -> None:
        if not self._enable_rib_policy:
            raise PolicyError("RibPolicy feature is not enabled")

        def _clear() -> None:
            if self.rib_policy is None:
                raise PolicyError("No RIB policy configured")
            self.rib_policy = None
            if self._rib_policy_timeout is not None:
                self._rib_policy_timeout.cancel()
                self._rib_policy_timeout = None
            self.pending_updates.set_needs_full_rebuild()
            self.rebuild_routes("RIB_POLICY_CLEARED")

        self.run_in_event_base_thread(_clear).result()
