"""RibPolicy: dynamic TTL'd transformation of computed routes.

Functional equivalent of the reference's RibPolicy
(openr/decision/RibPolicy.{h,cpp}; thrift types openr/if/OpenrCtrl.thrift:82-164):
match routes by prefix/tag, then re-weight next-hops (neighbor weight >
area weight > default weight; weight 0 drops the next-hop).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field, replace

from ..serializer import register_type
from ..types import normalize_prefix
from .rib import RibUnicastEntry


@register_type
@dataclass(slots=True)
class RibRouteActionWeight:
    """Reference: thrift::RibRouteActionWeight (OpenrCtrl.thrift:95)."""

    default_weight: int = 0
    area_to_weight: dict[str, int] = field(default_factory=dict)
    neighbor_to_weight: dict[str, int] = field(default_factory=dict)


@register_type
@dataclass(slots=True)
class RibPolicyStatementConfig:
    """Reference: thrift::RibPolicyStatement (OpenrCtrl.thrift:120)."""

    name: str = ""
    prefixes: list[str] | None = None
    tags: list[str] | None = None
    set_weight: RibRouteActionWeight | None = None


@register_type
@dataclass(slots=True)
class RibPolicyConfig:
    """Reference: thrift::RibPolicy (OpenrCtrl.thrift:140)."""

    statements: list[RibPolicyStatementConfig] = field(default_factory=list)
    ttl_secs: int = 0


class PolicyError(ValueError):
    pass


class RibPolicyStatement:
    """Reference: RibPolicyStatement (RibPolicy.cpp:19-160)."""

    def __init__(self, cfg: RibPolicyStatementConfig) -> None:
        if cfg.set_weight is None:
            raise PolicyError("Missing policy_statement.action.set_weight")
        if cfg.prefixes is None and cfg.tags is None:
            raise PolicyError(
                "Missing policy_statement.matcher.prefixes or tags"
            )
        self.name = cfg.name
        self.prefix_set = {normalize_prefix(p) for p in cfg.prefixes or ()}
        self.tag_set = set(cfg.tags or ())
        self.action = cfg.set_weight

    def to_config(self) -> RibPolicyStatementConfig:
        return RibPolicyStatementConfig(
            name=self.name,
            prefixes=sorted(self.prefix_set) or None,
            tags=sorted(self.tag_set) or None,
            set_weight=RibRouteActionWeight(
                default_weight=self.action.default_weight,
                area_to_weight=dict(self.action.area_to_weight),
                neighbor_to_weight=dict(self.action.neighbor_to_weight),
            ),
        )

    def match(self, route: RibUnicastEntry) -> bool:
        if not self.tag_set and not self.prefix_set:
            return False
        tag_match = not self.tag_set or bool(
            route.best_prefix_entry
            and self.tag_set.intersection(route.best_prefix_entry.tags)
        )
        prefix_match = not self.prefix_set or route.prefix in self.prefix_set
        return tag_match and prefix_match

    def apply_action(self, route: RibUnicastEntry) -> bool:
        """Re-weight next-hops in place; returns True iff transformed."""
        if not self.match(route):
            return False
        new_nexthops = set()
        for nh in route.nexthops:
            weight = self.action.default_weight
            if nh.area is not None:
                weight = self.action.area_to_weight.get(nh.area, weight)
            if nh.neighbor_node_name is not None:
                weight = self.action.neighbor_to_weight.get(
                    nh.neighbor_node_name, weight
                )
            if weight > 0:
                new_nexthops.add(replace(nh, weight=weight))
        if not new_nexthops:
            # retain existing next-hops rather than blackhole
            # (RibPolicy.cpp:146-158)
            return False
        route.nexthops = frozenset(new_nexthops)
        return True


@dataclass(slots=True)
class PolicyChange:
    updated_routes: list[str] = field(default_factory=list)
    deleted_routes: list[str] = field(default_factory=list)


class RibPolicy:
    """Reference: RibPolicy (RibPolicy.cpp:165-240)."""

    def __init__(self, cfg: RibPolicyConfig) -> None:
        if not cfg.statements:
            raise PolicyError("Missing policy.statements")
        self.statements = [RibPolicyStatement(s) for s in cfg.statements]
        self._valid_until = time.monotonic() + cfg.ttl_secs

    def to_config(self) -> RibPolicyConfig:
        return RibPolicyConfig(
            statements=[s.to_config() for s in self.statements],
            ttl_secs=max(0, int(self.get_ttl_duration_s())),
        )

    def get_ttl_duration_s(self) -> float:
        return self._valid_until - time.monotonic()

    def is_active(self) -> bool:
        return self.get_ttl_duration_s() > 0

    def match(self, route: RibUnicastEntry) -> bool:
        return any(s.match(route) for s in self.statements)

    def apply_action(self, route: RibUnicastEntry) -> bool:
        """First matching statement wins."""
        return any(s.apply_action(route) for s in self.statements)

    def apply_policy(
        self, unicast_entries: dict[str, RibUnicastEntry]
    ) -> PolicyChange:
        change = PolicyChange()
        if not self.is_active():
            return change
        for prefix, entry in unicast_entries.items():
            if self.apply_action(entry):
                assert entry.nexthops
                change.updated_routes.append(prefix)
        return change
