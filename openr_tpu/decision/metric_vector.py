"""BGP MetricVector comparison (reference: MetricVectorUtils,
openr/common/Util.h:455-480 / Util.cpp:945-1093).

Two vectors are walked in decreasing entity priority.  Entities present in
both vectors compare their metric lists lexicographically; an entity
present in only one vector resolves through its CompareType ("loner"
handling).  Entities flagged is_best_path_tie_breaker produce TIE_WINNER/
TIE_LOOSER instead of WINNER/LOOSER: a tie-breaker result orders the best
path but keeps the looser in the ECMP set (runBestPathSelectionBgp,
openr/decision/Decision.cpp:865-903).
"""

from __future__ import annotations

import enum

from ..types import CompareType, MetricEntity, MetricVector


class CompareResult(enum.Enum):
    WINNER = "WINNER"
    TIE_WINNER = "TIE_WINNER"
    TIE = "TIE"
    TIE_LOOSER = "TIE_LOOSER"
    LOOSER = "LOOSER"
    ERROR = "ERROR"


_NEGATE = {
    CompareResult.WINNER: CompareResult.LOOSER,
    CompareResult.TIE_WINNER: CompareResult.TIE_LOOSER,
    CompareResult.TIE: CompareResult.TIE,
    CompareResult.TIE_LOOSER: CompareResult.TIE_WINNER,
    CompareResult.LOOSER: CompareResult.WINNER,
    CompareResult.ERROR: CompareResult.ERROR,
}


def negate(result: CompareResult) -> CompareResult:
    """Reference: operator! (Util.cpp:946)."""
    return _NEGATE[result]


def is_decisive(result: CompareResult) -> bool:
    """WINNER/LOOSER/ERROR terminate the walk; TIE_* keep scanning for a
    decisive lower-priority entity (Util.cpp:971)."""
    return result in (
        CompareResult.WINNER,
        CompareResult.LOOSER,
        CompareResult.ERROR,
    )


def _sorted_metrics(mv: MetricVector) -> list[MetricEntity]:
    """Decreasing priority (reference sorts in place, Util.cpp:990;
    stable like std::sort is not required to be, but determinism is)."""
    return sorted(mv.metrics, key=lambda e: -e.priority)


def compare_metrics(
    l: tuple[int, ...], r: tuple[int, ...], tie_breaker: bool
) -> CompareResult:
    """Lexicographic metric-list compare (Util.cpp:1005-1023): longer-
    vs-shorter lists are an ERROR, larger element wins."""
    if len(l) != len(r):
        return CompareResult.ERROR
    for lv, rv in zip(l, r):
        if lv > rv:
            return (
                CompareResult.TIE_WINNER if tie_breaker else CompareResult.WINNER
            )
        if lv < rv:
            return (
                CompareResult.TIE_LOOSER if tie_breaker else CompareResult.LOOSER
            )
    return CompareResult.TIE


def result_for_loner(entity: MetricEntity) -> CompareResult:
    """Resolution for an entity present in only one vector
    (Util.cpp:1026-1038)."""
    if entity.op == CompareType.WIN_IF_PRESENT:
        return (
            CompareResult.TIE_WINNER
            if entity.is_best_path_tie_breaker
            else CompareResult.WINNER
        )
    if entity.op == CompareType.WIN_IF_NOT_PRESENT:
        return (
            CompareResult.TIE_LOOSER
            if entity.is_best_path_tie_breaker
            else CompareResult.LOOSER
        )
    return CompareResult.TIE  # IGNORE_IF_NOT_PRESENT


def _maybe_update(target: CompareResult, update: CompareResult) -> CompareResult:
    """A decisive update always sticks; a TIE_* update only replaces a
    plain TIE (the first tie-breaker seen wins the tie, Util.cpp:1041)."""
    if is_decisive(update) or target == CompareResult.TIE:
        return update
    return target


def compare_metric_vectors(
    l: MetricVector, r: MetricVector
) -> CompareResult:
    """Reference: compareMetricVectors (Util.cpp:1047-1093)."""
    if l.version != r.version:
        return CompareResult.ERROR
    lm = _sorted_metrics(l)
    rm = _sorted_metrics(r)
    result = CompareResult.TIE
    li = ri = 0
    while not is_decisive(result) and li < len(lm) and ri < len(rm):
        le, re = lm[li], rm[ri]
        if le.type == re.type:
            if le.is_best_path_tie_breaker != re.is_best_path_tie_breaker:
                result = _maybe_update(result, CompareResult.ERROR)
            else:
                result = _maybe_update(
                    result,
                    compare_metrics(
                        tuple(le.metric),
                        tuple(re.metric),
                        le.is_best_path_tie_breaker,
                    ),
                )
            li += 1
            ri += 1
        elif le.priority > re.priority:
            result = _maybe_update(result, result_for_loner(le))
            li += 1
        elif le.priority < re.priority:
            result = _maybe_update(result, negate(result_for_loner(re)))
            ri += 1
        else:
            # same priority, different type: vectors are not comparable
            result = _maybe_update(result, CompareResult.ERROR)
            li += 1
            ri += 1
    while not is_decisive(result) and li < len(lm):
        result = _maybe_update(result, result_for_loner(lm[li]))
        li += 1
    while not is_decisive(result) and ri < len(rm):
        result = _maybe_update(result, negate(result_for_loner(rm[ri])))
        ri += 1
    return result
