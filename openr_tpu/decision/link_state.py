"""Link-state graph: the host-side topology model.

Functional equivalent of the reference's LinkState
(openr/decision/LinkState.{h,cpp}) with identical semantics:

- only bidirectional links exist (both ends advertise the adjacency with
  matching interface names — maybeMakeLink, LinkState.cpp:703)
- HoldableValue-based ordered-FIB holds (RFC 6976 style) on link metrics,
  link overloads and node overloads (LinkState.cpp:53-120)
- updateAdjacencyDatabase computes a precise topology/attribute diff via
  ordered link-set merge (LinkState.cpp:565-717)
- SPF keeps ECMP ties: the relax step uses >= so equal-cost predecessors and
  first-hop sets accumulate (runSpf, LinkState.cpp:809-878)
- k-edge-disjoint paths via repeated SPF with link exclusion
  (getKthPaths/traceOnePath, LinkState.cpp:763-793,399-418)
- SPF and k-path results are memoized until the topology changes

The per-source Dijkstra here is the *conformance oracle* and the
small-topology fast path; bulk computation (all sources at once) runs on TPU
through openr_tpu.ops (see openr_tpu.decision.csr for the tensor mirror),
which must produce bit-identical distances / first-hop sets.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Generic, Iterable, Optional, TypeVar

from ..types import Adjacency, AdjacencyDatabase

INF = float("inf")

T = TypeVar("T")


class HoldableValue(Generic[T]):
    """Reference: openr/decision/LinkState.cpp:53-120.

    updateValue() holds the previous value for `ttl` decrements (hold-up ttl
    when the change improves reachability, hold-down otherwise); an update
    while a hold is active cancels the hold (fast fallback)."""

    __slots__ = ("_val", "_held_val", "_hold_ttl", "_is_bringing_up")

    _NO_HOLD = object()  # sentinel: held value may legitimately be False/0

    def __init__(self, val: T, is_bringing_up=None) -> None:
        self._val = val
        self._held_val = HoldableValue._NO_HOLD
        self._hold_ttl = 0
        # (old, new) -> bool: does this change "bring up" (improve) things?
        if is_bringing_up is None:
            # bool specialization: True->False is bringing up (un-overloading)
            # metric specialization: lower metric is bringing up
            def is_bringing_up(old, new):
                if isinstance(old, bool):
                    return old and not new
                return new < old

        self._is_bringing_up = is_bringing_up

    def set(self, val: T) -> None:
        """Unconditional assignment (operator=): clears any hold."""
        self._val = val
        self._held_val = HoldableValue._NO_HOLD
        self._hold_ttl = 0

    @property
    def value(self) -> T:
        return self._val if self._held_val is HoldableValue._NO_HOLD else self._held_val

    def has_hold(self) -> bool:
        return self._held_val is not HoldableValue._NO_HOLD

    def decrement_ttl(self) -> bool:
        if self.has_hold():
            self._hold_ttl -= 1
            if self._hold_ttl == 0:
                self._held_val = HoldableValue._NO_HOLD
                return True
        return False

    def update_value(self, val: T, hold_up_ttl: int, hold_down_ttl: int) -> bool:
        """Returns True iff the *visible* value changed."""
        if val != self._val:
            if self.has_hold():
                # fall back to fast update to avoid longer transient loops
                self._held_val = HoldableValue._NO_HOLD
                self._hold_ttl = 0
            else:
                ttl = (
                    hold_up_ttl
                    if self._is_bringing_up(self._val, val)
                    else hold_down_ttl
                )
                if ttl != 0:
                    self._held_val = self._val
                    self._hold_ttl = ttl
            self._val = val
            return not self.has_hold()
        return False


class Link:
    """A single bidirectional network link (reference: openr/decision/
    LinkState.h:82-175).  One object shared by both endpoint nodes; keyed by
    the unordered pair of (node, iface) ordered pairs."""

    __slots__ = (
        "area",
        "n1",
        "n2",
        "if1",
        "if2",
        "_metric1",
        "_metric2",
        "_overload1",
        "_overload2",
        "adj_label1",
        "adj_label2",
        "nh_v4_1",
        "nh_v4_2",
        "nh_v6_1",
        "nh_v6_2",
        "weight1",
        "weight2",
        "_hold_up_ttl",
        "ordered_names",
        "_hash",
    )

    def __init__(
        self,
        area: str,
        node1: str,
        adj1: Adjacency,
        node2: str,
        adj2: Adjacency,
        metric_inc1: int = 0,
        metric_inc2: int = 0,
    ) -> None:
        self.area = area
        self.n1 = node1
        self.n2 = node2
        self.if1 = adj1.if_name
        self.if2 = adj2.if_name
        # soft-drain: each endpoint's nodeMetricIncrementVal is folded into
        # the metric it originates, so every consumer of metric_from_node()
        # (host Dijkstra and the CSR device mirror alike) sees the drained
        # cost without a separate lookup
        self._metric1 = HoldableValue(adj1.metric + metric_inc1)
        self._metric2 = HoldableValue(adj2.metric + metric_inc2)
        self._overload1 = HoldableValue(adj1.is_overloaded)
        self._overload2 = HoldableValue(adj2.is_overloaded)
        self.adj_label1 = adj1.adj_label
        self.adj_label2 = adj2.adj_label
        self.nh_v4_1 = adj1.next_hop_v4
        self.nh_v4_2 = adj2.next_hop_v4
        self.nh_v6_1 = adj1.next_hop_v6
        self.nh_v6_2 = adj2.next_hop_v6
        # UCMP adjacency weights (SP_UCMP_ADJ_WEIGHT_PROPAGATION);
        # captured at link construction like the label/next-hop fields
        self.weight1 = adj1.weight
        self.weight2 = adj2.weight
        self._hold_up_ttl = 0
        a, b = (self.n1, self.if1), (self.n2, self.if2)
        self.ordered_names = (a, b) if a <= b else (b, a)
        self._hash = hash(self.ordered_names)

    # -- identity -----------------------------------------------------------

    def __hash__(self) -> int:
        return self._hash

    def __eq__(self, other) -> bool:
        return isinstance(other, Link) and self.ordered_names == other.ordered_names

    def __lt__(self, other: "Link") -> bool:
        return self.ordered_names < other.ordered_names

    def __repr__(self) -> str:
        return f"Link({self.area} - {self.n1}%{self.if1} <---> {self.n2}%{self.if2})"

    # -- endpoint-keyed accessors ------------------------------------------

    def _side(self, node: str) -> int:
        if node == self.n1:
            return 1
        if node == self.n2:
            return 2
        raise ValueError(f"{node} not an endpoint of {self!r}")

    def other_node_name(self, node: str) -> str:
        return self.n2 if self._side(node) == 1 else self.n1

    def first_node_name(self) -> str:
        return self.ordered_names[0][0]

    def second_node_name(self) -> str:
        return self.ordered_names[1][0]

    def iface_from_node(self, node: str) -> str:
        return self.if1 if self._side(node) == 1 else self.if2

    def weight_from_node(self, node: str) -> int:
        return self.weight1 if self._side(node) == 1 else self.weight2

    def metric_from_node(self, node: str) -> int:
        return (self._metric1 if self._side(node) == 1 else self._metric2).value

    def overload_from_node(self, node: str) -> bool:
        return (self._overload1 if self._side(node) == 1 else self._overload2).value

    def adj_label_from_node(self, node: str) -> int:
        return self.adj_label1 if self._side(node) == 1 else self.adj_label2

    def set_adj_label_from_node(self, node: str, label: int) -> None:
        if self._side(node) == 1:
            self.adj_label1 = label
        else:
            self.adj_label2 = label

    def nh_v4_from_node(self, node: str) -> str:
        return self.nh_v4_1 if self._side(node) == 1 else self.nh_v4_2

    def nh_v6_from_node(self, node: str) -> str:
        return self.nh_v6_1 if self._side(node) == 1 else self.nh_v6_2

    def set_nh_v4_from_node(self, node: str, nh: str) -> None:
        if self._side(node) == 1:
            self.nh_v4_1 = nh
        else:
            self.nh_v4_2 = nh

    def set_nh_v6_from_node(self, node: str, nh: str) -> None:
        if self._side(node) == 1:
            self.nh_v6_1 = nh
        else:
            self.nh_v6_2 = nh

    def set_metric_from_node(
        self, node: str, metric: int, hold_up_ttl: int, hold_down_ttl: int
    ) -> bool:
        hv = self._metric1 if self._side(node) == 1 else self._metric2
        return hv.update_value(metric, hold_up_ttl, hold_down_ttl)

    def set_overload_from_node(
        self, node: str, overload: bool, hold_up_ttl: int, hold_down_ttl: int
    ) -> bool:
        was_up = self.is_up()
        hv = self._overload1 if self._side(node) == 1 else self._overload2
        hv.update_value(overload, hold_up_ttl, hold_down_ttl)
        # simplex overloads unsupported: only report topo change on up<->down
        return was_up != self.is_up()

    # -- holds --------------------------------------------------------------

    def set_hold_up_ttl(self, ttl: int) -> None:
        self._hold_up_ttl = ttl

    def is_up(self) -> bool:
        return (
            self._hold_up_ttl == 0
            and not self._overload1.value
            and not self._overload2.value
        )

    def decrement_holds(self) -> bool:
        expired = False
        if self._hold_up_ttl != 0:
            self._hold_up_ttl -= 1
            expired |= self._hold_up_ttl == 0
        expired |= self._metric1.decrement_ttl()
        expired |= self._metric2.decrement_ttl()
        expired |= self._overload1.decrement_ttl()
        expired |= self._overload2.decrement_ttl()
        return expired

    def has_holds(self) -> bool:
        return (
            self._hold_up_ttl != 0
            or self._metric1.has_hold()
            or self._metric2.has_hold()
            or self._overload1.has_hold()
            or self._overload2.has_hold()
        )


@dataclass(slots=True)
class LinkStateChange:
    """Reference: LinkState::LinkStateChange (LinkState.h:306)."""

    topology_changed: bool = False
    link_attributes_changed: bool = False
    node_label_changed: bool = False

    def __or__(self, other: "LinkStateChange") -> "LinkStateChange":
        return LinkStateChange(
            self.topology_changed or other.topology_changed,
            self.link_attributes_changed or other.link_attributes_changed,
            self.node_label_changed or other.node_label_changed,
        )


@dataclass(slots=True)
class NodeSpfResult:
    """Reference: LinkState::NodeSpfResult (LinkState.h:210-260).

    path_links: (link, prev_node) pairs — SP-DAG in-edges toward this node.
    next_hops: first-hop neighbor node names of shortest paths from source.
    """

    metric: float
    path_links: list[tuple[Link, str]] = field(default_factory=list)
    next_hops: set[str] = field(default_factory=set)


SpfResult = dict[str, NodeSpfResult]
Path = list[Link]


def trace_one_path(
    src: str,
    dest: str,
    result: SpfResult,
    links_to_ignore: set[Link],
) -> Optional[Path]:
    """Extract one not-yet-visited shortest path from an SpfResult's
    path_links DAG, consuming its links (reference: LinkState::traceOnePath,
    LinkState.cpp:399-418).  Works on any SpfResult — host Dijkstra or
    device-kernel reconstruction."""
    if src == dest:
        return []
    for link, prev_node in result[dest].path_links:
        if link in links_to_ignore:
            continue
        links_to_ignore.add(link)
        path = trace_one_path(src, prev_node, result, links_to_ignore)
        if path is not None:
            path.append(link)
            return path
    return None


def path_a_in_path_b(a: Path, b: Path) -> bool:
    """True if path A appears contiguously inside path B
    (reference: LinkState::pathAInPathB, LinkState.h:396)."""
    if len(a) > len(b):
        return False
    for i in range(len(b) - len(a) + 1):
        if all(a[j] == b[i + j] for j in range(len(a))):
            return True
    return False


class LinkState:
    """Host-side link-state graph for one area."""

    def __init__(self, area: str = "0") -> None:
        self.area = area
        self._link_map: dict[str, set[Link]] = {}
        self._all_links: set[Link] = set()
        self._node_overloads: dict[str, HoldableValue] = {}
        self._adjacency_databases: dict[str, AdjacencyDatabase] = {}
        self._spf_results: dict[tuple[str, bool], SpfResult] = {}
        self._kth_path_results: dict[tuple[str, str, int], list[Path]] = {}
        # device mirror invalidation hook (set by csr.CsrTopology)
        self._version = 0

    # -- read API -----------------------------------------------------------

    def has_node(self, node: str) -> bool:
        return node in self._adjacency_databases

    def links_from_node(self, node: str) -> set[Link]:
        return self._link_map.get(node, set())

    def ordered_links_from_node(self, node: str) -> list[Link]:
        return sorted(self._link_map.get(node, set()))

    def is_node_overloaded(self, node: str) -> bool:
        hv = self._node_overloads.get(node)
        return hv is not None and hv.value

    @property
    def all_links(self) -> set[Link]:
        return self._all_links

    def num_links(self) -> int:
        return len(self._all_links)

    def num_nodes(self) -> int:
        return len(self._link_map)

    def get_adjacency_databases(self) -> dict[str, AdjacencyDatabase]:
        return self._adjacency_databases

    @property
    def node_names(self) -> list[str]:
        return sorted(
            set(self._adjacency_databases.keys()) | set(self._link_map.keys())
        )

    @property
    def version(self) -> int:
        """Monotonic counter bumped on every topology change — used by the
        CSR device mirror to know when to refresh."""
        return self._version

    def has_holds(self) -> bool:
        return any(l.has_holds() for l in self._all_links) or any(
            hv.has_hold() for hv in self._node_overloads.values()
        )

    # -- graph mutation (reference: LinkState.cpp:421-447,565-737) ----------

    def _add_link(self, link: Link) -> None:
        self._link_map.setdefault(link.first_node_name(), set()).add(link)
        self._link_map.setdefault(link.second_node_name(), set()).add(link)
        self._all_links.add(link)

    def _remove_link(self, link: Link) -> None:
        self._link_map[link.first_node_name()].discard(link)
        self._link_map[link.second_node_name()].discard(link)
        self._all_links.discard(link)

    def _remove_node(self, node: str) -> None:
        links = self._link_map.pop(node, set())
        for link in links:
            other = link.other_node_name(node)
            self._link_map.get(other, set()).discard(link)
            self._all_links.discard(link)
        self._node_overloads.pop(node, None)

    def _update_node_overloaded(
        self, node: str, is_overloaded: bool, hold_up_ttl: int, hold_down_ttl: int
    ) -> bool:
        hv = self._node_overloads.get(node)
        if hv is not None:
            return hv.update_value(is_overloaded, hold_up_ttl, hold_down_ttl)
        self._node_overloads[node] = HoldableValue(is_overloaded)
        return False  # new node: not a link-state change

    def _maybe_make_link(self, node: str, adj: Adjacency) -> Optional[Link]:
        """Only bidirectional links: the far node must advertise the reverse
        adjacency with matching interface names
        (reference: maybeMakeLink, LinkState.cpp:703)."""
        other_db = self._adjacency_databases.get(adj.other_node_name)
        if other_db is None:
            return None
        for other_adj in other_db.adjacencies:
            if (
                node == other_adj.other_node_name
                and adj.other_if_name == other_adj.if_name
                and adj.if_name == other_adj.other_if_name
            ):
                return Link(
                    self.area,
                    node,
                    adj,
                    adj.other_node_name,
                    other_adj,
                    metric_inc1=self._metric_increment(node),
                    metric_inc2=self._metric_increment(adj.other_node_name),
                )
        return None

    def _metric_increment(self, node: str) -> int:
        """The node's current soft-drain increment (nodeMetricIncrementVal).
        Looked up from the stored database so both sides of a link get their
        own originator's value; update_adjacency_database stores the new db
        before rebuilding links, so a drain change flows through the ordinary
        metric diff (set_metric_from_node) and invalidates SPF memos."""
        db = self._adjacency_databases.get(node)
        return db.node_metric_increment_val if db is not None else 0

    def _get_ordered_link_set(self, adj_db: AdjacencyDatabase) -> list[Link]:
        links = []
        for adj in adj_db.adjacencies:
            link = self._maybe_make_link(adj_db.this_node_name, adj)
            if link is not None:
                links.append(link)
        links.sort()
        return links

    def _invalidate(self) -> None:
        self._spf_results.clear()
        self._kth_path_results.clear()
        self._version += 1

    def update_adjacency_database(
        self,
        new_adj_db: AdjacencyDatabase,
        hold_up_ttl: int = 0,
        hold_down_ttl: int = 0,
    ) -> LinkStateChange:
        """Reference: updateAdjacencyDatabase, LinkState.cpp:565-717."""
        change = LinkStateChange()
        node = new_adj_db.this_node_name
        assert new_adj_db.area == self.area, (new_adj_db.area, self.area)

        prior_db = self._adjacency_databases.get(node)
        self._adjacency_databases[node] = new_adj_db
        if prior_db is None:
            # node-set change: SPF memos stay valid (no links yet) but the
            # CSR device mirror must refresh its interning tables
            self._version += 1

        old_links = self.ordered_links_from_node(node)
        new_links = self._get_ordered_link_set(new_adj_db)

        change.topology_changed |= self._update_node_overloaded(
            node, new_adj_db.is_overloaded, hold_up_ttl, hold_down_ttl
        )
        prior_label = prior_db.node_label if prior_db is not None else 0
        change.node_label_changed = prior_label != new_adj_db.node_label

        i = j = 0
        while i < len(new_links) or j < len(old_links):
            if i < len(new_links) and (
                j >= len(old_links) or new_links[i] < old_links[j]
            ):
                # link came up: apply hold-up, add
                nl = new_links[i]
                nl.set_hold_up_ttl(hold_up_ttl)
                change.topology_changed |= nl.is_up()
                self._add_link(nl)
                i += 1
                continue
            if j < len(old_links) and (
                i >= len(new_links) or old_links[j] < new_links[i]
            ):
                ol = old_links[j]
                change.topology_changed |= ol.is_up()
                self._remove_link(ol)
                j += 1
                continue
            # same link: check attribute changes on the *existing* object
            nl, ol = new_links[i], old_links[j]
            if nl.metric_from_node(node) != ol.metric_from_node(node):
                change.topology_changed |= ol.set_metric_from_node(
                    node, nl.metric_from_node(node), hold_up_ttl, hold_down_ttl
                )
            if nl.overload_from_node(node) != ol.overload_from_node(node):
                change.topology_changed |= ol.set_overload_from_node(
                    node, nl.overload_from_node(node), hold_up_ttl, hold_down_ttl
                )
            if nl.adj_label_from_node(node) != ol.adj_label_from_node(node):
                change.link_attributes_changed = True
                ol.set_adj_label_from_node(node, nl.adj_label_from_node(node))
            if nl.nh_v4_from_node(node) != ol.nh_v4_from_node(node):
                change.link_attributes_changed = True
                ol.set_nh_v4_from_node(node, nl.nh_v4_from_node(node))
            if nl.nh_v6_from_node(node) != ol.nh_v6_from_node(node):
                change.link_attributes_changed = True
                ol.set_nh_v6_from_node(node, nl.nh_v6_from_node(node))
            i += 1
            j += 1

        if change.topology_changed:
            self._invalidate()
        return change

    def delete_adjacency_database(self, node: str) -> LinkStateChange:
        change = LinkStateChange()
        if node in self._adjacency_databases:
            self._remove_node(node)
            del self._adjacency_databases[node]
            self._invalidate()
            change.topology_changed = True
        return change

    def decrement_holds(self) -> LinkStateChange:
        change = LinkStateChange()
        for link in self._all_links:
            change.topology_changed |= link.decrement_holds()
        for hv in self._node_overloads.values():
            change.topology_changed |= hv.decrement_ttl()
        if change.topology_changed:
            self._invalidate()
        return change

    # -- SPF (reference: runSpf, LinkState.cpp:809-878) ---------------------

    def run_spf(
        self,
        src: str,
        use_link_metric: bool = True,
        links_to_ignore: Optional[set[Link]] = None,
    ) -> SpfResult:
        """Dijkstra with ECMP tie retention — the conformance oracle.

        Pop order is (metric, node_name); the relax step uses >= so all
        equal-cost predecessors/next-hops are kept.  Overloaded nodes other
        than the source are recorded but never relaxed from (drained)."""
        links_to_ignore = links_to_ignore or set()
        result: SpfResult = {}
        # heap entries: (metric, node_name); node state kept separately
        pending: dict[str, NodeSpfResult] = {src: NodeSpfResult(0)}
        heap: list[tuple[float, str]] = [(0, src)]
        while heap:
            metric, node = heapq.heappop(heap)
            state = pending.get(node)
            if state is None or node in result or metric > state.metric:
                continue  # stale heap entry
            result[node] = state
            del pending[node]
            if self.is_node_overloaded(node) and node != src:
                continue  # no transit through drained node
            for link in sorted(self.links_from_node(node)):
                other = link.other_node_name(node)
                if not link.is_up() or other in result or link in links_to_ignore:
                    continue
                m = link.metric_from_node(node) if use_link_metric else 1
                cand = metric + m
                other_state = pending.get(other)
                if other_state is None:
                    other_state = pending[other] = NodeSpfResult(cand)
                    heapq.heappush(heap, (cand, other))
                if other_state.metric >= cand:
                    if other_state.metric > cand:
                        other_state.metric = cand
                        other_state.path_links = []
                        other_state.next_hops = set()
                        heapq.heappush(heap, (cand, other))
                    other_state.path_links.append((link, node))
                    other_state.next_hops |= state.next_hops
                    if not other_state.next_hops:
                        other_state.next_hops.add(other)  # directly connected
        return result

    def get_spf_result(self, node: str, use_link_metric: bool = True) -> SpfResult:
        key = (node, use_link_metric)
        res = self._spf_results.get(key)
        if res is None:
            res = self._spf_results[key] = self.run_spf(node, use_link_metric)
        return res

    def get_metric_from_a_to_b(
        self, a: str, b: str, use_link_metric: bool = True
    ) -> Optional[float]:
        if a == b:
            return 0
        res = self.get_spf_result(a, use_link_metric)
        return res[b].metric if b in res else None

    def get_hops_from_a_to_b(self, a: str, b: str) -> Optional[float]:
        return self.get_metric_from_a_to_b(a, b, use_link_metric=False)

    def get_max_hops_to_node(self, node: str) -> int:
        res = self.get_spf_result(node, use_link_metric=False)
        return max((int(r.metric) for r in res.values()), default=0)

    # -- k edge-disjoint paths (reference: LinkState.cpp:399-418,763-793) ---

    def _trace_one_path(
        self,
        src: str,
        dest: str,
        result: SpfResult,
        links_to_ignore: set[Link],
    ) -> Optional[Path]:
        return trace_one_path(src, dest, result, links_to_ignore)

    def get_kth_paths(self, src: str, dest: str, k: int) -> list[Path]:
        assert k >= 1
        key = (src, dest, k)
        cached = self._kth_path_results.get(key)
        if cached is not None:
            return cached
        links_to_ignore: set[Link] = set()
        for i in range(1, k):
            for path in self.get_kth_paths(src, dest, i):
                links_to_ignore.update(path)
        paths: list[Path] = []
        res = (
            self.get_spf_result(src, True)
            if not links_to_ignore
            else self.run_spf(src, True, links_to_ignore)
        )
        if dest in res:
            visited: set[Link] = set()
            path = self._trace_one_path(src, dest, res, visited)
            while path:
                paths.append(path)
                path = self._trace_one_path(src, dest, res, visited)
        self._kth_path_results[key] = paths
        return paths
