"""SpfSolver: per-prefix route construction over SPF results.

Functional equivalent of the reference's SpfSolver::SpfSolverImpl
(openr/decision/Decision.cpp:164-1395): reachability filtering, best-route
selection, drained-node filtering, SP_ECMP / KSP2_ED_ECMP forwarding
algorithms, MPLS node/adjacency label routes, min-nexthop thresholds, and
static route overlays.

The route-selection control flow is data-dependent (per-prefix algorithm
switches, label stacks) so it runs on host over SPF results; the SPF results
themselves come through a pluggable backend seam (`SpfBackend`) so bulk
distance/DAG computation can run batched on TPU (openr_tpu.ops.sssp via
openr_tpu.decision.csr) while small topologies use the host oracle —
mirroring the reference's plugin seam for drop-in solvers
(openr/plugin/Plugin.h:23).
"""

from __future__ import annotations

import ipaddress
import logging
import math
import weakref
from dataclasses import replace
from typing import Optional, Protocol

import numpy as np

from ..types import (
    MplsAction,
    MplsActionCode,
    MplsRoute,
    NextHop,
    PrefixEntry,
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
    PrefixType,
    UnicastRoute,
    normalize_prefix,
)
from .delta import DELTA_COUNTER_KEYS
from .fleet import (
    INF32 as FLEET_INF,
    FleetRouteView,
    FleetViewCache,
    fleet_destinations,
)
from .link_state import LinkState, Path, SpfResult
from .prefix_state import NodeAndArea, PrefixEntries, PrefixState
from .rib import DecisionRouteDb, RibMplsEntry, RibUnicastEntry

log = logging.getLogger(__name__)

MPLS_LABEL_MIN = 16
MPLS_LABEL_MAX = (1 << 20) - 1


def is_mpls_label_valid(label: int) -> bool:
    """Reference: isMplsLabelValid (openr/common/Util.h)."""
    return MPLS_LABEL_MIN <= label <= MPLS_LABEL_MAX


def select_best_prefix_metrics(entries: PrefixEntries) -> set[NodeAndArea]:
    """Reference: selectBestPrefixMetrics (openr/common/Util.h:434,493):
    ordered compare on (path_preference desc, source_preference desc,
    distance asc); ties all kept."""
    best: Optional[tuple[int, int, int]] = None
    best_keys: set[NodeAndArea] = set()
    for key, entry in entries.items():
        m = entry.metrics
        t = (m.path_preference, m.source_preference, -m.distance)
        if best is None or t > best:
            best = t
            best_keys = {key}
        elif t == best:
            best_keys.add(key)
    return best_keys


def select_best_node_area(
    all_node_areas: set[NodeAndArea], my_node_name: str
) -> NodeAndArea:
    """Deterministic representative: prefer self, else smallest key
    (reference: selectBestNodeArea, openr/common/Util.cpp:902)."""
    for node_area in sorted(all_node_areas):
        if node_area[0] == my_node_name:
            return node_area
    return min(all_node_areas)


class BestRouteSelectionResult:
    """Reference: BestRouteSelectionResult (openr/decision/Decision.h:96)."""

    __slots__ = ("success", "all_node_areas", "best_node_area")

    def __init__(self) -> None:
        self.success = False
        self.all_node_areas: set[NodeAndArea] = set()
        self.best_node_area: NodeAndArea = ("", "")

    def has_node(self, node: str) -> bool:
        return any(n == node for n, _ in self.all_node_areas)


class SpfBackend(Protocol):
    """Seam for SPF computation: host Dijkstra oracle or batched TPU kernel."""

    def get_spf_result(self, link_state: LinkState, src: str) -> SpfResult: ...

    def get_kth_paths(
        self, link_state: LinkState, src: str, dest: str, k: int
    ) -> list: ...


class HostSpfBackend:
    """Memoized host Dijkstra (the reference's exact behavior)."""

    def get_spf_result(self, link_state: LinkState, src: str) -> SpfResult:
        return link_state.get_spf_result(src)

    def get_kth_paths(
        self, link_state: LinkState, src: str, dest: str, k: int
    ) -> list:
        return link_state.get_kth_paths(src, dest, k)


class DeviceSpfBackend:
    """TPU SPF backend over a persistent CSR/ELL device mirror.

    Replaces the reference's per-source sequential Dijkstra memo
    (openr/decision/LinkState.h:279-282).  Per LinkState it keeps ONE
    mirror that refreshes incrementally on topology version bumps
    (attribute flaps touch only the runtime arrays; edge-set changes
    rebuild tables at stable shapes, so compiled kernels are reused —
    csr.refresh).  Queries are LAZY: the hot path asks only for the
    daemon's own node per area (getNextHopsWithMetric), so each uncached
    source costs one small device call (distances + SP-DAG + bit-packed
    first hops); batch consumers (what-if, KSP, ctrl any-node queries)
    go through `prefetch` to amortize one call over many sources.

    Dispatch policy (defaulted from round-4 measurement, bench_details
    reconverge/ksp2 + srlg/allsrc rows): through a latency-bound
    transport the wall-clock discriminator is BATCH SIZE, not node
    count — batched questions (what-if fleets, all-sources tiles, KSP
    destination sets; S >= ~256) win on the device at every measured
    scale, while single-question flows (S <= ~9) lose to the host
    Dijkstra even at 10k nodes (device_vs_host 0.47 at fattree10k) and
    only the amortized per-question cost wins (16x at wan100k).  So:

    - below `min_device_nodes` (tiny topologies): always host.
    - batches of >= `min_device_sources` (default 32 — the measured
      per-question host cost at 10k is ~70 ms while a forced device
      flow costs ~750 ms wall, putting the crossover near S~11; 32
      sits safely above it without cliffing mid-size batches onto S
      sequential host Dijkstras): device.
    - smaller batches: host, unless the topology is at/above
      `force_device_nodes` — a bound the measurements did NOT reach
      (host still won wall at 100k for S=9 through the tunnel), kept as
      an escape hatch for untunneled deployments where the per-dispatch
      fee is ~0.04 ms and the device wins everywhere above tiny.
    """

    def __init__(
        self,
        min_device_nodes: int = 64,
        min_device_sources: int = 32,
        force_device_nodes: int = 131072,
        engine=None,
    ) -> None:
        self.min_device_nodes = min_device_nodes
        self.min_device_sources = min_device_sources
        self.force_device_nodes = force_device_nodes
        # device-residency engine (openr_tpu.device): resident graph
        # mirrors + bucketed program cache.  All SPF dispatch goes through
        # it; csr.spf_from remains only as the engine-less fallback.
        if engine is None:
            from ..device import DeviceResidencyEngine

            engine = DeviceResidencyEngine()
        self.engine = engine
        # Keyed on the LinkState object itself (weakly) rather than id():
        # ids are recycled after GC, so an id-keyed cache could serve
        # another topology's results and leaks entries for dead
        # LinkStates.
        self._mirrors: "weakref.WeakKeyDictionary[LinkState, object]" = (
            weakref.WeakKeyDictionary()
        )
        self._results: "weakref.WeakKeyDictionary[LinkState, tuple[int, dict[str, SpfResult]]]" = (
            weakref.WeakKeyDictionary()
        )
        # (src, dest, k) -> list[Path], version-guarded like _results
        self._kth_results: "weakref.WeakKeyDictionary[LinkState, tuple[int, dict]]" = (
            weakref.WeakKeyDictionary()
        )
        # topology fingerprint -> learned fixed-sweep hint (see _hint_key)
        self._hint_by_shape: dict[tuple, int] = {}
        # jitted sharded SPF step per Mesh (re-jitting per prefetch would
        # pay a full retrace+compile each call)
        self._mesh_steps: dict = {}

    def _mirror(self, link_state: LinkState):
        from .csr import CsrTopology

        csr = self._mirrors.get(link_state)
        if csr is None:
            csr = CsrTopology.from_link_state(link_state)
            # the relax depth is a property of the topology SHAPE, so a
            # fresh mirror of a same-shaped topology starts from the
            # learned fixed-sweep hint instead of re-learning it by
            # doubling (each failed guess costs a full device dispatch)
            learned = self._hint_by_shape.get(self._hint_key(csr))
            if learned is not None:
                csr._sweep_hint = learned
            self._mirrors[link_state] = csr
        elif csr.version != link_state.version:
            csr.refresh(link_state)
        return csr

    @staticmethod
    def _hint_key(csr) -> tuple:
        # node/edge COUNTS, not just padded capacities: capacities are
        # power-of-two roundings, and hints only ever grow — a deep
        # chain-like topology must not poison a shallow fabric that
        # happens to round to the same capacity bucket
        return (csr.n_nodes, csr.n_edges, csr.node_capacity, csr.edge_capacity)

    def _harvest_hint(self, csr) -> None:
        # max, not overwrite: two coexisting same-key topologies must not
        # ping-pong the stored value downward (a too-small seed costs a
        # failed dispatch; a too-large one only extra sweeps)
        key = self._hint_key(csr)
        self._hint_by_shape[key] = max(
            self._hint_by_shape.get(key, 0), csr._sweep_hint
        )

    def csr_mirror(self, link_state: LinkState):
        """Public access to the incrementally-maintained CSR mirror (used
        by the protection operator surface to avoid per-RPC rebuilds)."""
        return self._mirror(link_state)

    def _result_cache(self, link_state: LinkState) -> dict[str, SpfResult]:
        cached = self._results.get(link_state)
        if cached is None or cached[0] != link_state.version:
            cached = (link_state.version, {})
            self._results[link_state] = cached
        return cached[1]

    def _device_worthwhile(self, link_state: LinkState, n_sources: int) -> bool:
        """The measured dispatch policy (class docstring)."""
        n = link_state.num_nodes()
        if n < self.min_device_nodes:
            return False
        if (
            n_sources >= self.min_device_sources
            or n >= self.force_device_nodes
        ):
            return True
        # engine-warm branch: the batch crossover above prices in per-call
        # staging + jit-cache entry.  With the graph already resident in
        # the engine, a small-S dispatch pays only the padded bucket
        # program call, so the comparison flips in the device's favor.
        if self.engine is not None:
            csr = self._mirrors.get(link_state)
            if csr is not None and self.engine.has_residency(csr):
                return True
        return False

    def _spf_from(self, csr, sources: list[str], use_link_metric: bool = True):
        """SPF dispatch front-end: the engine serves from device residency
        (no per-call staging, bucketed programs); csr.spf_from is the
        engine-less host-staged fallback."""
        if self.engine is not None:
            return self.engine.spf_results(
                csr, sources, use_link_metric=use_link_metric
            )
        return csr.spf_from(sources, use_link_metric=use_link_metric)

    def prefetch(self, link_state: LinkState, sources: list[str]) -> None:
        """Compute many sources in one device call and cache them (host
        memo below the measured batch crossover)."""
        if link_state.num_nodes() < self.min_device_nodes:
            return
        cache = self._result_cache(link_state)
        missing = [
            s
            for s in sources
            if s not in cache and link_state.links_from_node(s)
        ]
        if not missing:
            return
        if not self._device_worthwhile(link_state, len(missing)):
            # small batch: the host memo answers ahead of wall-losing
            # small dispatches; results land in the same cache
            for s in missing:
                cache[s] = link_state.get_spf_result(s)
            return
        csr = self._mirror(link_state)
        cache.update(self._spf_from(csr, missing))
        self._harvest_hint(csr)

    def prefetch_via_mesh(
        self, link_state: LinkState, sources: list[str], mesh
    ) -> None:
        """Batch-prefetch over a multi-chip `jax.sharding.Mesh`: the
        source axis is sharded over the mesh's batch dimension
        (parallel/mesh.py spf_step_sharded), so the device side of an
        all-node route view on an n-chip mesh costs ~1/n of the
        single-chip call.  Results land in the same per-LinkState cache
        the solver reads, so build_route_db after a mesh prefetch never
        re-dispatches.

        The mesh step returns distances + SP-DAG only; first-hop sets are
        decoded host-side (to_spf_results' propagation fallback), which is
        fine for control-plane views at fabric scale but is NOT the
        per-tile 100k pipeline (that stays on the single-chip
        spf_forward_full path with device-bit-packed first hops)."""
        from ..parallel.mesh import spf_step_sharded

        if link_state.num_nodes() < self.min_device_nodes:
            return  # get_spf_result serves the host path below this size
        cache = self._result_cache(link_state)
        missing = [
            s
            for s in sources
            if s not in cache and link_state.links_from_node(s)
        ]
        if not missing:
            return
        csr = self._mirror(link_state)
        step = self._mesh_steps.get(mesh)
        if step is None:
            # jit once per mesh; re-jitting per prefetch would retrace
            # and recompile the sharded program every call
            step = self._mesh_steps[mesh] = spf_step_sharded(mesh)
        batch = mesh.devices.shape[0]
        src_ids = np.asarray(
            [csr.node_id[s] for s in missing], dtype=np.int32
        )
        pad = (-len(src_ids)) % batch
        if pad:
            src_ids = np.concatenate(
                [src_ids, np.zeros(pad, dtype=np.int32)]
            )
        dist, dag = step(
            src_ids,
            csr.ell,
            csr.edge_src,
            csr.edge_dst,
            csr.edge_metric,
            csr.edge_up,
            csr.node_overloaded,
        )
        cache.update(
            csr.to_spf_results(
                missing,
                np.asarray(dist)[: len(missing)],
                np.asarray(dag)[: len(missing)],
            )
        )

    def get_spf_result(self, link_state: LinkState, src: str) -> SpfResult:
        if link_state.num_nodes() < self.min_device_nodes:
            return link_state.get_spf_result(src)
        cache = self._result_cache(link_state)
        hit = cache.get(src)
        if hit is not None:
            return hit
        if not link_state.links_from_node(src):
            # isolated/unknown node: empty-but-self result via host path
            return link_state.get_spf_result(src)
        if not self._device_worthwhile(link_state, 1):
            # single-question miss below the measured crossover: host
            # memo (a batch prefetch would have populated the cache)
            res = link_state.get_spf_result(src)
            cache[src] = res
            return res
        csr = self._mirror(link_state)
        cache.update(self._spf_from(csr, [src]))
        self._harvest_hint(csr)
        return cache[src]

    # -- batched k-shortest edge-disjoint paths -----------------------------

    def _kth_cache(self, link_state: LinkState) -> dict:
        cached = self._kth_results.get(link_state)
        if cached is None or cached[0] != link_state.version:
            cached = (link_state.version, {})
            self._kth_results[link_state] = cached
        return cached[1]

    def get_kth_paths(
        self, link_state: LinkState, src: str, dest: str, k: int
    ) -> list:
        if link_state.num_nodes() < self.min_device_nodes:
            return link_state.get_kth_paths(src, dest, k)
        cache = self._kth_cache(link_state)
        hit = cache.get((src, dest, k))
        if hit is not None:
            return hit  # a batch prefetch populated it
        if not self._device_worthwhile(link_state, 1):
            # single-question miss below the measured batch crossover
            return link_state.get_kth_paths(src, dest, k)
        # single miss: batch of one (the solver prefetches the full
        # destination set ahead of per-prefix queries)
        self.prefetch_kth_paths(link_state, src, [dest])
        res = cache.get((src, dest, k))
        # [] is a valid answer (unreachable dest), not a miss
        return res if res is not None else link_state.get_kth_paths(
            src, dest, k
        )

    def prefetch_kth_paths(
        self, link_state: LinkState, src: str, dests: list[str]
    ) -> None:
        """k=1 and k=2 edge-disjoint paths for many destinations in ONE
        masked device run.

        The reference recurses per destination — k=2 is a fresh
        LinkState::runSpf with that destination's first-path links excluded
        (LinkState.cpp:763-793).  The exclusion sets differ per
        destination, which is exactly the kernel's per-row mask axis
        (ops.sssp.spf_forward_ell_masked): row d = SPF from src with
        dest-d's first-path links down."""
        from .link_state import trace_one_path

        if not self._device_worthwhile(link_state, len(dests)):
            return  # host recursion serves the per-prefix queries
        csr = self._mirror(link_state)
        if src not in csr.node_id:
            return  # unknown/linkless source: host fallback serves it
        cache = self._kth_cache(link_state)
        base = self.get_spf_result(link_state, src)

        # k=1: trace from the (cached, device-computed) base SP-DAG
        need_second: list[tuple[str, set]] = []
        for dest in dests:
            if (src, dest, 1) not in cache:
                paths = []
                if dest in base:
                    visited: set = set()
                    # empty path (src == dest) is falsy and not collected,
                    # matching LinkState.get_kth_paths
                    while p := trace_one_path(src, dest, base, visited):
                        paths.append(p)
                cache[(src, dest, 1)] = paths
            if (src, dest, 2) not in cache:
                ignore = {
                    link for path in cache[(src, dest, 1)] for link in path
                }
                if ignore:
                    need_second.append((dest, ignore))
                else:
                    cache[(src, dest, 2)] = []

        if not need_second:
            return
        link_edges = csr.edges_of_links()
        mask = np.ones((len(need_second), csr.edge_capacity), dtype=bool)
        for row, (_dest, ignore) in enumerate(need_second):
            for link in ignore:
                for e in link_edges.get(link, ()):
                    mask[row, e] = False
        dist, dag = csr.run_batched_spf(
            [src] * len(need_second), extra_edge_mask=mask
        )
        for row, (dest, _ignore) in enumerate(need_second):
            res = csr.row_path_links(dist[row], dag[row])
            paths = []
            if dest in res:
                visited = set()
                while p := trace_one_path(src, dest, res, visited):
                    paths.append(p)
            cache[(src, dest, 2)] = paths


class SpfSolver:
    """Reference: SpfSolver (openr/decision/Decision.h:199-266)."""

    def __init__(
        self,
        my_node_name: str,
        enable_v4: bool = True,
        bgp_dry_run: bool = False,
        enable_best_route_selection: bool = False,
        spf_backend: Optional[SpfBackend] = None,
        fleet_delta: Optional[bool] = None,
    ) -> None:
        self.my_node_name = my_node_name
        self.enable_v4 = enable_v4
        self.bgp_dry_run = bgp_dry_run
        self.enable_best_route_selection = enable_best_route_selection
        self.spf = spf_backend or HostSpfBackend()
        # degradation ladder rung 1: any device-backend dispatch failure
        # is served from this host oracle instead (memoized Dijkstra) —
        # route correctness is never hostage to the accelerator
        self._host_fallback: Optional[HostSpfBackend] = None
        # fleet-product views (reduced all-sources reverse-SSSP consumer;
        # active per build via build_route_db(fleet_views=...)).
        # `fleet_delta` opts in to the incremental delta rung
        # (decision.delta): None keeps the FleetViewCache default
        # (OPENR_FLEET_DELTA env), so direct constructions stay on the
        # legacy paths unless the daemon asks.
        self.fleet = FleetViewCache(delta=fleet_delta, bump=self._bump)
        self._fleet_views: dict[str, FleetRouteView] = {}
        # static route overlays (reference: Decision.cpp:372-425)
        self.static_unicast_routes: dict[str, list[NextHop]] = {}
        self.static_mpls_routes: dict[int, list[NextHop]] = {}
        # best-route selection cache (reference: bestRoutesCache_)
        self.best_routes_cache: dict[str, BestRouteSelectionResult] = {}
        # the decision.delta.* family is pre-seeded so both wire surfaces
        # expose it from daemon start even before the rung ever engages
        self.counters: dict[str, int] = {k: 0 for k in DELTA_COUNTER_KEYS}

    def _bump(self, counter: str, n: int = 1) -> None:
        self.counters[counter] = self.counters.get(counter, 0) + n

    # -- degradation ladder (device -> host oracle) --------------------------

    def _host_oracle(self, why: str) -> HostSpfBackend:
        """Account a device fallback and return the host oracle backend."""
        if self._host_fallback is None:
            self._host_fallback = HostSpfBackend()
        self._bump("decision.device_fallbacks")
        log.warning("decision: device SPF failed (%s); using host oracle", why)
        return self._host_fallback

    def _spf_result(self, link_state: LinkState, src: str):
        try:
            return self.spf.get_spf_result(link_state, src)
        except Exception:
            return self._host_oracle("get_spf_result").get_spf_result(
                link_state, src
            )

    def _kth_paths(self, link_state: LinkState, src: str, dest: str, k: int):
        try:
            return self.spf.get_kth_paths(link_state, src, dest, k)
        except Exception:
            return self._host_oracle("get_kth_paths").get_kth_paths(
                link_state, src, dest, k
            )

    # -- static route overlays ----------------------------------------------

    def update_static_unicast_routes(
        self,
        routes_to_update: list[UnicastRoute],
        routes_to_delete: list[str],
    ) -> None:
        for route in routes_to_update:
            self.static_unicast_routes[normalize_prefix(route.dest)] = list(
                route.next_hops
            )
        for prefix in routes_to_delete:
            self.static_unicast_routes.pop(normalize_prefix(prefix), None)

    def update_static_mpls_routes(
        self,
        routes_to_update: list[MplsRoute],
        routes_to_delete: list[int],
    ) -> None:
        for route in routes_to_update:
            self.static_mpls_routes[route.top_label] = list(route.next_hops)
        for label in routes_to_delete:
            self.static_mpls_routes.pop(label, None)

    # -- per-prefix route construction --------------------------------------

    def create_route_for_prefix_or_get_static_route(
        self,
        area_link_states: dict[str, LinkState],
        prefix_state: PrefixState,
        prefix: str,
    ) -> Optional[RibUnicastEntry]:
        """Reference: createRouteForPrefixOrGetStaticRoute
        (Decision.cpp:427-449): computed routes win over static."""
        route = self.create_route_for_prefix(area_link_states, prefix_state, prefix)
        if route is not None:
            return route
        nhs = self.static_unicast_routes.get(normalize_prefix(prefix))
        if nhs is not None:
            return RibUnicastEntry(
                prefix=normalize_prefix(prefix), nexthops=frozenset(nhs)
            )
        return None

    def create_route_for_prefix(
        self,
        area_link_states: dict[str, LinkState],
        prefix_state: PrefixState,
        prefix: str,
    ) -> Optional[RibUnicastEntry]:
        """Reference: createRouteForPrefix (Decision.cpp:445-613)."""
        self._bump("decision.get_route_for_prefix")
        prefix = normalize_prefix(prefix)
        all_prefix_entries = prefix_state.prefixes.get(prefix)
        if not all_prefix_entries:
            return None

        self.best_routes_cache.pop(prefix, None)

        # keep entries of reachable nodes only (per area)
        prefix_entries: PrefixEntries = dict(all_prefix_entries)
        for area, link_state in area_link_states.items():
            view = self._fleet_views.get(area)
            if view is not None and view.covers(self.my_node_name) and all(
                view.is_dest(node)
                for (node, parea) in prefix_entries
                if parea == area and view.covers(node)
            ):
                try:
                    # fleet product answers reachability without a per-
                    # source SPF: dist(me -> advertiser) < INF (fleet.py)
                    prefix_entries = {
                        (node, parea): entry
                        for (node, parea), entry in prefix_entries.items()
                        if area != parea
                        or (
                            view.covers(node)
                            and view.reachable(self.my_node_name, node)
                        )
                    }
                    continue
                except Exception:
                    # device row fetch died mid-query: fall through to the
                    # per-source path (itself host-oracle-backed)
                    self._bump("decision.device_fallbacks")
                    log.warning(
                        "decision: fleet view query failed for area %s; "
                        "per-source fallback",
                        area,
                    )
            my_spf = self._spf_result(link_state, self.my_node_name)
            prefix_entries = {
                (node, parea): entry
                for (node, parea), entry in prefix_entries.items()
                if area != parea or node in my_spf
            }
        if not prefix_entries:
            self._bump("decision.no_route_to_prefix")
            return None

        is_v4 = ipaddress.ip_network(prefix).version == 4
        if is_v4 and not self.enable_v4:
            self._bump("decision.skipped_unicast_route")
            return None

        has_bgp = has_non_bgp = False
        has_self_prepend_label = True
        for (node, _area), entry in prefix_entries.items():
            is_bgp = entry.type == PrefixType.BGP
            has_bgp |= is_bgp
            has_non_bgp |= not is_bgp
            if node == self.my_node_name:
                has_self_prepend_label &= entry.prepend_label is not None
        if has_bgp and has_non_bgp and not self.enable_best_route_selection:
            # mixed BGP/non-BGP advertisement is rejected (Decision.cpp:527)
            self._bump("decision.skipped_unicast_route")
            return None

        best = self.select_best_routes(prefix_entries, has_bgp, area_link_states)
        if not best.success:
            return None
        if not best.all_node_areas:
            self._bump("decision.no_route_to_prefix")
            return None
        self.best_routes_cache[prefix] = best

        # skip self-advertised prefixes unless advertised w/ prepend label
        # (Decision.cpp:570-579)
        if best.has_node(self.my_node_name) and not has_self_prepend_label:
            return None

        forwarding_type, forwarding_algo = self._forwarding_type_and_algorithm(
            prefix_entries, best.all_node_areas
        )
        if forwarding_algo != PrefixForwardingAlgorithm.KSP2_ED_ECMP:
            # SP_ECMP and both SP_UCMP_* algorithms share the
            # shortest-path machinery; UCMP only re-weights the set
            return self._select_best_paths_spf(
                prefix,
                best,
                prefix_entries,
                has_bgp,
                forwarding_type,
                area_link_states,
                forwarding_algo,
            )
        return self._select_best_paths_ksp2(
            prefix,
            best,
            prefix_entries,
            has_bgp,
            forwarding_type,
            area_link_states,
        )

    @staticmethod
    def _forwarding_type_and_algorithm(
        prefix_entries: PrefixEntries, best_node_areas: set[NodeAndArea]
    ) -> tuple[PrefixForwardingType, PrefixForwardingAlgorithm]:
        """Minimum over best entries — most-compatible wins (reference:
        getPrefixForwardingTypeAndAlgorithm, openr/common/Util.cpp)."""
        f_type: Optional[PrefixForwardingType] = None
        f_algo: Optional[PrefixForwardingAlgorithm] = None
        for node_area in best_node_areas:
            entry = prefix_entries[node_area]
            if f_type is None or entry.forwarding_type < f_type:
                f_type = entry.forwarding_type
            if f_algo is None or entry.forwarding_algorithm < f_algo:
                f_algo = entry.forwarding_algorithm
        assert f_type is not None and f_algo is not None
        return f_type, f_algo

    # -- best route selection -----------------------------------------------

    def select_best_routes(
        self,
        prefix_entries: PrefixEntries,
        has_bgp: bool,
        area_link_states: dict[str, LinkState],
    ) -> BestRouteSelectionResult:
        """Reference: selectBestRoutes (Decision.cpp:795-827)."""
        assert prefix_entries
        result = BestRouteSelectionResult()
        if self.enable_best_route_selection:
            # PrefixMetrics-ordered selection
            result.all_node_areas = select_best_prefix_metrics(prefix_entries)
            result.best_node_area = select_best_node_area(
                result.all_node_areas, self.my_node_name
            )
            result.success = True
        elif has_bgp:
            return self._run_best_path_selection_bgp(
                prefix_entries, area_link_states
            )
        else:
            result.all_node_areas = set(prefix_entries)
            result.best_node_area = min(result.all_node_areas)
            result.success = True
        return self._maybe_filter_drained_nodes(result, area_link_states)

    def _run_best_path_selection_bgp(
        self,
        prefix_entries: PrefixEntries,
        area_link_states: dict[str, LinkState],
    ) -> BestRouteSelectionResult:
        """BGP best-path selection over advertised MetricVectors
        (reference: runBestPathSelectionBgp, Decision.cpp:865-903):
        WINNER resets the ECMP set, TIE_WINNER re-points the best entry
        while keeping prior ties, TIE_LOOSER joins the set; TIE/ERROR
        abort the route.  The running `best_vector` is the cached
        comparison target, exactly as the reference's bestVector.

        Deviation for robustness: if no advertiser attached a MetricVector
        at all, fall back to the PrefixMetrics ordered compare (our
        PrefixEntry always carries metrics; the reference would throw on
        the unset thrift optional)."""
        from .metric_vector import CompareResult, compare_metric_vectors

        result = BestRouteSelectionResult()
        if all(e.mv is None for e in prefix_entries.values()):
            result.all_node_areas = select_best_prefix_metrics(prefix_entries)
            result.best_node_area = select_best_node_area(
                result.all_node_areas, self.my_node_name
            )
            result.success = True
            return self._maybe_filter_drained_nodes(result, area_link_states)

        best_vector = None
        # deterministic iteration (the reference walks an unordered_map)
        for node_area in sorted(prefix_entries):
            entry = prefix_entries[node_area]
            if entry.mv is None:
                # mixed mv/no-mv advertisement is not comparable
                # (reference: can_throw on the unset optional)
                log.error(
                    "BGP entry without metric vector from %s; skipping route",
                    node_area,
                )
                self._bump("decision.no_route_to_prefix")
                return BestRouteSelectionResult()
            cmp = (
                compare_metric_vectors(entry.mv, best_vector)
                if best_vector is not None
                else CompareResult.WINNER
            )
            if cmp in (CompareResult.TIE, CompareResult.ERROR):
                log.error(
                    "%s ordering BGP prefix entries; skipping route",
                    cmp.value,
                )
                self._bump("decision.no_route_to_prefix")
                return BestRouteSelectionResult()
            if cmp == CompareResult.WINNER:
                result.all_node_areas.clear()
            if cmp in (CompareResult.WINNER, CompareResult.TIE_WINNER):
                best_vector = entry.mv
                result.best_node_area = node_area
            if cmp in (
                CompareResult.WINNER,
                CompareResult.TIE_WINNER,
                CompareResult.TIE_LOOSER,
            ):
                result.all_node_areas.add(node_area)
        result.success = True
        return self._maybe_filter_drained_nodes(result, area_link_states)

    def _maybe_filter_drained_nodes(
        self,
        result: BestRouteSelectionResult,
        area_link_states: dict[str, LinkState],
    ) -> BestRouteSelectionResult:
        """Drop overloaded advertisers unless all are overloaded
        (reference: maybeFilterDrainedNodes, Decision.cpp:847-870)."""
        filtered = BestRouteSelectionResult()
        filtered.success = result.success
        filtered.best_node_area = result.best_node_area
        filtered.all_node_areas = {
            (node, area)
            for node, area in result.all_node_areas
            if not area_link_states[area].is_node_overloaded(node)
        }
        if not filtered.all_node_areas:
            return result
        if filtered.best_node_area not in filtered.all_node_areas:
            filtered.best_node_area = min(filtered.all_node_areas)
        return filtered

    @staticmethod
    def _min_nexthop_threshold(
        best: BestRouteSelectionResult, prefix_entries: PrefixEntries
    ) -> Optional[int]:
        """Max over best entries' min_nexthop (reference:
        getMinNextHopThreshold, Decision.cpp:830-845)."""
        threshold: Optional[int] = None
        for node_area in best.all_node_areas:
            mn = prefix_entries[node_area].min_nexthop
            if mn is not None and (threshold is None or mn > threshold):
                threshold = mn
        return threshold

    # -- SP_ECMP -------------------------------------------------------------

    def _select_best_paths_spf(
        self,
        prefix: str,
        best: BestRouteSelectionResult,
        prefix_entries: PrefixEntries,
        is_bgp: bool,
        forwarding_type: PrefixForwardingType,
        area_link_states: dict[str, LinkState],
        forwarding_algo: PrefixForwardingAlgorithm = (
            PrefixForwardingAlgorithm.SP_ECMP
        ),
    ) -> Optional[RibUnicastEntry]:
        """Reference: selectBestPathsSpf (Decision.cpp:905-963)."""
        is_v4 = ipaddress.ip_network(prefix).version == 4
        per_destination = forwarding_type == PrefixForwardingType.SR_MPLS

        # self-originated SR prefix w/ prepend label: compute next-hops to
        # the *other* advertisers (Decision.cpp:917-933)
        filtered_node_areas = set(best.all_node_areas)
        if best.has_node(self.my_node_name) and per_destination:
            for node_area, entry in prefix_entries.items():
                if (
                    node_area[0] == self.my_node_name
                    and entry.prepend_label is not None
                ):
                    # every self-advertised (node, area) must be excluded —
                    # a multi-area self anycast advertisement would otherwise
                    # keep one entry at SPF distance 0 and kill the route
                    filtered_node_areas.discard(node_area)

        min_metric, nexthop_nodes = self._get_next_hops_with_metric(
            filtered_node_areas, per_destination, area_link_states
        )
        if not nexthop_nodes:
            self._bump("decision.no_route_to_prefix")
            return None

        nexthops = self._get_next_hops(
            best.all_node_areas,
            is_v4,
            per_destination,
            min_metric,
            nexthop_nodes,
            None,
            area_link_states,
            prefix_entries,
        )
        if forwarding_algo != PrefixForwardingAlgorithm.SP_ECMP:
            nexthops = self._apply_ucmp_weights(
                forwarding_algo,
                filtered_node_areas,
                nexthops,
                area_link_states,
                prefix_entries,
            )
        return self._add_best_paths(
            prefix, best, prefix_entries, is_bgp, nexthops
        )

    def _apply_ucmp_weights(
        self,
        algo: PrefixForwardingAlgorithm,
        dst_node_areas: set[NodeAndArea],
        nexthops: set[NextHop],
        area_link_states: dict[str, LinkState],
        prefix_entries: PrefixEntries,
    ) -> set[NextHop]:
        """UCMP next-hop weights over the already-selected ECMP set
        (reference: the DecisionTest Ucmp tranche semantics).

        SP_UCMP_PREFIX_WEIGHT_PROPAGATION: every first-hop neighbor
        accumulates `PrefixEntry.weight` from each min-metric advertiser
        it reaches on a shortest path; parallel links to one neighbor
        share the neighbor's weight.  Attribution reuses
        getNextHopsWithMetric's per-destination keys, which are the
        documented parity surface between the host SPF and the fleet
        product (`_fleet_next_hops_with_metric`), so both backends
        assign identical weights.

        SP_UCMP_ADJ_WEIGHT_PROPAGATION: each next-hop takes its own
        first-hop adjacency weight (`Adjacency.weight` via the link).

        Weights are normalized by their gcd.  If no positive weight
        survives (no advertiser set one, or every weighted path lost
        the metric race), the set is returned unweighted — plain ECMP
        instead of a black hole."""
        link_w: dict[tuple[str, str], int] = {}
        if algo == PrefixForwardingAlgorithm.SP_UCMP_ADJ_WEIGHT_PROPAGATION:
            for area, link_state in area_link_states.items():
                for link in link_state.links_from_node(self.my_node_name):
                    link_w[(area, link.iface_from_node(self.my_node_name))] = (
                        link.weight_from_node(self.my_node_name)
                    )

        acc: dict[str, int] = {}
        if algo == PrefixForwardingAlgorithm.SP_UCMP_PREFIX_WEIGHT_PROPAGATION:
            _, per_dst = self._get_next_hops_with_metric(
                dst_node_areas, True, area_link_states
            )
            by_dst: dict[str, int] = {}
            for node, area in dst_node_areas:
                w = prefix_entries[(node, area)].weight or 0
                by_dst[node] = max(by_dst.get(node, 0), w)
            for (nh_name, dst_node), _dist in per_dst.items():
                acc[nh_name] = acc.get(nh_name, 0) + by_dst.get(dst_node, 0)

        raw: list[tuple[NextHop, int]] = []
        for nh in nexthops:
            if algo == PrefixForwardingAlgorithm.SP_UCMP_ADJ_WEIGHT_PROPAGATION:
                w = link_w.get((nh.area, nh.if_name), 0)
            else:
                w = acc.get(nh.neighbor_node_name, 0)
            raw.append((nh, max(w, 0)))
        norm = math.gcd(*(w for _nh, w in raw))
        if norm == 0:
            return nexthops
        return {replace(nh, weight=w // norm) for nh, w in raw}

    # -- KSP2_ED_ECMP --------------------------------------------------------

    def _select_best_paths_ksp2(
        self,
        prefix: str,
        best: BestRouteSelectionResult,
        prefix_entries: PrefixEntries,
        is_bgp: bool,
        forwarding_type: PrefixForwardingType,
        area_link_states: dict[str, LinkState],
    ) -> Optional[RibUnicastEntry]:
        """Reference: selectBestPathsKsp2 (Decision.cpp:966-1087)."""
        if forwarding_type != PrefixForwardingType.SR_MPLS:
            self._bump("decision.incompatible_forwarding_type")
            return None

        is_v4 = ipaddress.ip_network(prefix).version == 4
        nexthops: set[NextHop] = set()
        paths: list[tuple[str, Path]] = []  # (area, path)

        for area, link_state in area_link_states.items():
            # batched device prefetch of k=1/k=2 for every best node (one
            # masked kernel run instead of per-destination host recursion)
            prefetch = getattr(self.spf, "prefetch_kth_paths", None)
            if prefetch is not None:
                try:
                    prefetch(
                        link_state,
                        self.my_node_name,
                        sorted({node for node, _ in best.all_node_areas}),
                    )
                except Exception:
                    # prefetch is an optimization: per-path queries below
                    # fall back to the host oracle individually
                    self._bump("decision.device_fallbacks")
            # shortest paths first
            for node, best_area in sorted(best.all_node_areas):
                if node == self.my_node_name and best_area == area:
                    continue
                for path in self._kth_paths(
                    link_state, self.my_node_name, node, 1
                ):
                    paths.append((area, path))
            # second shortest, skipping those containing a first path
            # (anti double-spray, Decision.cpp:1006-1037)
            first_paths_size = len(paths)
            for node, best_area in sorted(best.all_node_areas):
                if area != best_area:
                    continue
                for sec_path in self._kth_paths(
                    link_state, self.my_node_name, node, 2
                ):
                    from .link_state import path_a_in_path_b

                    if any(
                        path_a_in_path_b(paths[i][1], sec_path)
                        for i in range(first_paths_size)
                    ):
                        continue
                    paths.append((area, sec_path))

        if not paths:
            return None

        for area, path in paths:
            link_state = area_link_states[area]
            adj_dbs = link_state.get_adjacency_databases()
            cost = 0
            labels: list[int] = []  # front == bottom of stack
            next_node = self.my_node_name
            ok = True
            for link in path:
                cost += link.metric_from_node(next_node)
                next_node = link.other_node_name(next_node)
                if next_node not in adj_dbs:
                    ok = False
                    break
                labels.insert(0, adj_dbs[next_node].node_label)
            if not ok:
                continue
            labels.pop()  # drop first-hop node's label (PHP)
            entry = prefix_entries.get((next_node, area))
            if entry is None:
                continue
            if entry.prepend_label is not None:
                if not is_mpls_label_valid(entry.prepend_label):
                    continue
                labels.insert(0, entry.prepend_label)

            first_link = path[0]
            mpls_action = (
                MplsAction(MplsActionCode.PUSH, push_labels=tuple(labels))
                if labels
                else None
            )
            nexthops.add(
                NextHop(
                    address=(
                        first_link.nh_v4_from_node(self.my_node_name)
                        if is_v4
                        else first_link.nh_v6_from_node(self.my_node_name)
                    ),
                    if_name=first_link.iface_from_node(self.my_node_name),
                    metric=cost,
                    mpls_action=mpls_action,
                    area=first_link.area,
                    neighbor_node_name=first_link.other_node_name(
                        self.my_node_name
                    ),
                )
            )

        return self._add_best_paths(
            prefix, best, prefix_entries, is_bgp, nexthops
        )

    def _add_best_paths(
        self,
        prefix: str,
        best: BestRouteSelectionResult,
        prefix_entries: PrefixEntries,
        is_bgp: bool,
        nexthops: set[NextHop],
    ) -> Optional[RibUnicastEntry]:
        """Reference: addBestPaths (Decision.cpp:1090-1150)."""
        min_nexthop = self._min_nexthop_threshold(best, prefix_entries)
        if min_nexthop is not None and min_nexthop > len(nexthops):
            return None

        # self-advertised anycast w/ prepend label: merge in the static
        # next-hops registered for that label (Decision.cpp:1113-1141)
        if best.has_node(self.my_node_name):
            prepend_label = next(
                (
                    entry.prepend_label
                    for (node, _a), entry in prefix_entries.items()
                    if node == self.my_node_name
                    and entry.prepend_label is not None
                ),
                None,
            )
            if prepend_label is not None:
                for nh in self.static_mpls_routes.get(prepend_label, ()):
                    nexthops.add(NextHop(address=nh.address, metric=0))

        return RibUnicastEntry(
            prefix=prefix,
            nexthops=frozenset(nexthops),
            best_prefix_entry=prefix_entries[best.best_node_area],
            best_area=best.best_node_area[1],
            do_not_install=is_bgp and self.bgp_dry_run,
        )

    # -- nexthop computation -------------------------------------------------

    def _get_min_cost_nodes(
        self, spf_result: SpfResult, dst_node_areas: set[NodeAndArea]
    ) -> tuple[float, set[str]]:
        """Reference: getMinCostNodes (Decision.cpp:1153-1178)."""
        shortest = float("inf")
        min_cost_nodes: set[str] = set()
        for dst_node, _area in dst_node_areas:
            res = spf_result.get(dst_node)
            if res is None:
                continue
            if shortest >= res.metric:
                if shortest > res.metric:
                    shortest = res.metric
                    min_cost_nodes = set()
                min_cost_nodes.add(dst_node)
        return shortest, min_cost_nodes

    def _get_next_hops_with_metric(
        self,
        dst_node_areas: set[NodeAndArea],
        per_destination: bool,
        area_link_states: dict[str, LinkState],
    ) -> tuple[float, dict[tuple[str, str], float]]:
        """Reference: getNextHopsWithMetric (Decision.cpp:1182-1228).
        Returns (min metric, {(nexthop node, dst | "") -> dist from nexthop
        to dst})."""
        nexthop_nodes: dict[tuple[str, str], float] = {}
        shortest = float("inf")
        for area, link_state in area_link_states.items():
            view = self._fleet_views.get(area)
            if view is not None and self._fleet_usable(view, dst_node_areas):
                try:
                    shortest = self._fleet_next_hops_with_metric(
                        view,
                        link_state,
                        dst_node_areas,
                        per_destination,
                        shortest,
                        nexthop_nodes,
                    )
                    continue
                except Exception:
                    self._bump("decision.device_fallbacks")
                    log.warning(
                        "decision: fleet next-hop query failed for area %s; "
                        "per-source fallback",
                        area,
                    )
            spf = self._spf_result(link_state, self.my_node_name)
            min_metric, min_cost_nodes = self._get_min_cost_nodes(
                spf, dst_node_areas
            )
            if shortest < min_metric:
                continue
            if shortest > min_metric:
                shortest = min_metric
                nexthop_nodes = {}
            if not min_cost_nodes:
                continue
            for dst_node in min_cost_nodes:
                dst_ref = dst_node if per_destination else ""
                for nh_name in spf[dst_node].next_hops:
                    nexthop_nodes[(nh_name, dst_ref)] = (
                        shortest - spf[nh_name].metric
                    )
        return shortest, nexthop_nodes

    def _fleet_usable(
        self, view: FleetRouteView, dst_node_areas: set[NodeAndArea]
    ) -> bool:
        """The fleet snapshot can answer this query iff it covers the
        querying node and every destination it knows about is in the
        product's destination set (nodes outside the area's graph are
        skipped by both paths identically)."""
        return view.covers(self.my_node_name) and all(
            view.is_dest(node) or not view.covers(node)
            for node, _area in dst_node_areas
        )

    def _fleet_next_hops_with_metric(
        self,
        view: FleetRouteView,
        link_state: LinkState,
        dst_node_areas: set[NodeAndArea],
        per_destination: bool,
        shortest: float,
        nexthop_nodes: dict[tuple[str, str], float],
    ) -> float:
        """One area's contribution to getNextHopsWithMetric, answered from
        the fleet product instead of a per-source SPF.

        Stores dist(nh -> dst) under each qualifying (nh, dst_ref) key —
        provably the value the host path stores (shortest - dist(me, nh))
        for every qualifying pair, see fleet.py module doc — so the
        unchanged _get_next_hops equality test
        (metric(link) + value == min_metric, Decision.cpp:1296-1300)
        selects identical links on either path."""
        me = self.my_node_name
        inf32 = FLEET_INF
        # min over reachable destinations (mirrors _get_min_cost_nodes)
        min_metric = float("inf")
        min_cost_nodes: set[str] = set()
        for dst_node, _area in dst_node_areas:
            if not view.covers(dst_node):
                continue
            d = view.dist(me, dst_node)
            if d >= inf32:
                continue
            if min_metric >= d:
                if min_metric > d:
                    min_metric = d
                    min_cost_nodes = set()
                min_cost_nodes.add(dst_node)
        if shortest < min_metric:
            return shortest
        if shortest > min_metric:
            shortest = min_metric
            nexthop_nodes.clear()
        for dst_node in min_cost_nodes:
            dst_ref = dst_node if per_destination else ""
            d_me = view.dist(me, dst_node)
            for link in link_state.links_from_node(me):
                if not link.is_up():
                    continue
                u = link.other_node_name(me)
                if not view.covers(u):
                    continue
                d_u = view.dist(u, dst_node)
                if d_u >= inf32:
                    continue
                # drain: overloaded neighbor only as the destination
                # itself (the d == 0 source exception of the kernels)
                if view.is_overloaded_id(u) and d_u != 0:
                    continue
                if link.metric_from_node(me) + d_u != d_me:
                    continue
                key = (u, dst_ref)
                prev = nexthop_nodes.get(key)
                if prev is None or d_u < prev:
                    nexthop_nodes[key] = d_u
        return shortest

    def _get_next_hops(
        self,
        dst_node_areas: set[NodeAndArea],
        is_v4: bool,
        per_destination: bool,
        min_metric: float,
        nexthop_nodes: dict[tuple[str, str], float],
        swap_label: Optional[int],
        area_link_states: dict[str, LinkState],
        prefix_entries: PrefixEntries,
    ) -> set[NextHop]:
        """Reference: getNextHopsThrift (Decision.cpp:1231-1338) — LFA-free
        ECMP: keep a link iff metric(link) + dist(neighbor, dst) equals the
        overall min metric."""
        assert nexthop_nodes
        nexthops: set[NextHop] = set()
        for area, link_state in area_link_states.items():
            adj_dbs = link_state.get_adjacency_databases()
            for link in link_state.links_from_node(self.my_node_name):
                dst_iter = (
                    sorted(dst_node_areas) if per_destination else [("", "")]
                )
                for dst_node, dst_area in dst_iter:
                    if dst_area and area != dst_area:
                        continue
                    neighbor = link.other_node_name(self.my_node_name)
                    dist = nexthop_nodes.get((neighbor, dst_node))
                    if dist is None or not link.is_up():
                        continue
                    # don't reach dst via a neighbor that is itself another
                    # destination (Decision.cpp:1285-1291)
                    if (
                        dst_node
                        and (neighbor, area) in dst_node_areas
                        and neighbor != dst_node
                    ):
                        continue
                    dist_over_link = (
                        link.metric_from_node(self.my_node_name) + dist
                    )
                    if dist_over_link != min_metric:
                        continue

                    mpls_action: Optional[MplsAction] = None
                    if swap_label is not None:
                        nh_is_dst = (neighbor, area) in dst_node_areas
                        mpls_action = MplsAction(
                            MplsActionCode.PHP
                            if nh_is_dst
                            else MplsActionCode.SWAP,
                            swap_label=None if nh_is_dst else swap_label,
                        )
                    if dst_node:
                        push_labels: list[int] = []
                        dst_entry = prefix_entries.get((dst_node, area))
                        if (
                            dst_entry is not None
                            and dst_entry.prepend_label is not None
                        ):
                            push_labels.append(dst_entry.prepend_label)
                            if not is_mpls_label_valid(push_labels[-1]):
                                continue
                        if dst_node != neighbor:
                            push_labels.append(adj_dbs[dst_node].node_label)
                            if not is_mpls_label_valid(push_labels[-1]):
                                continue
                        if push_labels:
                            assert mpls_action is None
                            mpls_action = MplsAction(
                                MplsActionCode.PUSH,
                                push_labels=tuple(push_labels),
                            )

                    nexthops.add(
                        NextHop(
                            address=(
                                link.nh_v4_from_node(self.my_node_name)
                                if is_v4
                                else link.nh_v6_from_node(self.my_node_name)
                            ),
                            if_name=link.iface_from_node(self.my_node_name),
                            metric=int(dist_over_link),
                            mpls_action=mpls_action,
                            area=link.area,
                            neighbor_node_name=neighbor,
                        )
                    )
        return nexthops

    # -- full route DB -------------------------------------------------------

    def build_route_db(
        self,
        area_link_states: dict[str, LinkState],
        prefix_state: PrefixState,
        my_node_name: Optional[str] = None,
        fleet_views: Optional[dict[str, FleetRouteView]] = None,
    ) -> Optional[DecisionRouteDb]:
        """Reference: buildRouteDb (Decision.cpp:615-793).  Source-
        parameterized: `my_node_name` may be any node (the axis the TPU
        backend batches over; see OpenrCtrlHandler getRouteDbComputed).

        With `fleet_views` (area -> FleetRouteView), SP_ECMP reachability
        and next-hop selection are answered from the reduced all-sources
        product instead of per-source SPF — the daemon consumer of
        ops.allsources (KSP2 prefixes still go through the per-source
        path machinery; the views don't carry per-destination masked
        re-runs)."""
        me = my_node_name or self.my_node_name
        if not any(ls.has_node(me) for ls in area_link_states.values()):
            return None
        self._bump("decision.route_build_runs")

        prev_me, self.my_node_name = self.my_node_name, me
        prev_fleet, self._fleet_views = (
            self._fleet_views,
            fleet_views or {},
        )
        try:
            route_db = DecisionRouteDb()
            self.best_routes_cache.clear()

            # batched KSP pre-pass: union every KSP2 prefix's advertising
            # nodes (a superset of the best-route winners) and prefetch
            # k=1/k=2 for all of them in ONE masked device run per area —
            # the per-prefix loop then only hits the backend's cache.
            # Without this, each prefix's miss dispatched its own masked
            # kernel run (measured: 31 dispatches instead of 1 on the
            # 32-prefix KSP2 bench).
            prefetch = getattr(self.spf, "prefetch_kth_paths", None)
            if prefetch is not None:
                ksp2_dests: set[str] = set()
                for entries in prefix_state.prefixes.values():
                    for (node, _area), entry in entries.items():
                        if (
                            entry.forwarding_algorithm
                            == PrefixForwardingAlgorithm.KSP2_ED_ECMP
                            and node != me
                        ):
                            ksp2_dests.add(node)
                if ksp2_dests:
                    for link_state in area_link_states.values():
                        try:
                            prefetch(link_state, me, sorted(ksp2_dests))
                        except Exception:
                            self._bump("decision.device_fallbacks")

            for prefix in prefix_state.prefixes:
                route = self.create_route_for_prefix(
                    area_link_states, prefix_state, prefix
                )
                if route is not None:
                    route_db.add_unicast_route(route)

            for prefix, nhs in self.static_unicast_routes.items():
                if prefix in route_db.unicast_routes:
                    continue
                route_db.add_unicast_route(
                    RibUnicastEntry(prefix=prefix, nexthops=frozenset(nhs))
                )

            self._build_node_label_routes(area_link_states, route_db)
            self._build_adj_label_routes(area_link_states, route_db)

            for label, nhs in self.static_mpls_routes.items():
                if label not in route_db.mpls_routes:
                    route_db.add_mpls_route(
                        RibMplsEntry(label=label, nexthops=frozenset(nhs))
                    )
            return route_db
        finally:
            self.my_node_name = prev_me
            self._fleet_views = prev_fleet

    def _build_fleet_views(
        self,
        area_link_states: dict[str, LinkState],
        prefix_state: PrefixState,
        explicit: bool,
    ) -> dict[str, FleetRouteView]:
        """Per-area fleet views.  `explicit` (operator asked for the fleet
        product by name) always computes; otherwise a cold view is only
        computed when the measured dispatch policy says the device round
        beats per-source work (DeviceSpfBackend docstring) — host backends
        never compute one implicitly."""
        views: dict[str, FleetRouteView] = {}
        mirror = getattr(self.spf, "csr_mirror", None)
        min_nodes = getattr(self.spf, "min_device_nodes", None)
        min_sources = getattr(self.spf, "min_device_sources", None)
        engine = getattr(self.spf, "engine", None)
        for area, ls in area_link_states.items():
            dests = fleet_destinations(ls, prefix_state)
            if not dests:
                continue
            if not explicit and not self.fleet.is_warm(ls, dests):
                if min_nodes is None or ls.num_nodes() < min_nodes:
                    continue
                if min_sources is not None and len(dests) < min_sources:
                    continue
            cached = self.fleet.is_warm(ls, dests)
            try:
                view = self.fleet.view(
                    ls,
                    dests,
                    csr=mirror(ls) if mirror is not None else None,
                    engine=engine,
                )
            except Exception:
                # fleet-product dispatch failed outright (mirror build or
                # both cold attempts): serve this area per-source off the
                # host oracle instead of dropping the rebuild
                self._bump("decision.device_fallbacks")
                self._bump("decision.fleet_view_failures")
                log.warning(
                    "decision: fleet product failed for area %s; "
                    "serving per-source from host oracle",
                    area,
                )
                continue
            if view is not None:
                views[area] = view
                if not cached:
                    # fb303-style observability: operators watch the
                    # warm-start hit rate of fleet rebuilds, split by
                    # change direction (link-DOWN warm starts are the
                    # newer, riskier gate)
                    self._bump(
                        "decision.fleet_rebuild_warm"
                        if view.warm
                        else "decision.fleet_rebuild_cold"
                    )
                    if view.warm_mode == "worsen":
                        self._bump("decision.fleet_rebuild_warm_down")
                    if getattr(view, "cold_fallback", False):
                        # warm-start gate blew up and the cache retried
                        # cold (ladder rung 2, FleetViewCache.view)
                        self._bump("decision.fleet_warm_fallbacks")
        return views

    def any_node_route_db(
        self,
        area_link_states: dict[str, LinkState],
        prefix_state: PrefixState,
        node: str,
    ) -> Optional[DecisionRouteDb]:
        """Any-node ctrl query (reference: getDecisionRouteDb,
        Decision.cpp:1510-1530), served from the fleet product when the
        per-area view is warm (zero device work) or worth computing under
        the measured dispatch policy; per-source path otherwise."""
        views = self._build_fleet_views(
            area_link_states, prefix_state, explicit=False
        )
        # the build touches the queried router and its neighbors: fetch
        # those distance columns in ONE device gather per area instead of
        # one taxed dispatch each
        for area, view in views.items():
            if not view.covers(node):
                continue
            ls = area_link_states[area]
            wanted = {node}
            for link in ls.links_from_node(node):
                wanted.add(link.other_node_name(node))
            try:
                view.prefetch_rows(sorted(wanted))
            except Exception:
                self._bump("decision.device_fallbacks")
        return self.build_route_db(
            area_link_states,
            prefix_state,
            my_node_name=node,
            fleet_views=views,
        )

    def fleet_route_dbs(
        self,
        area_link_states: dict[str, LinkState],
        prefix_state: PrefixState,
        nodes: Optional[list[str]] = None,
    ) -> dict[str, DecisionRouteDb]:
        """Fleet-wide route dump: ONE reverse-SSSP device round per area
        answers every requested router's route build (default: every node).
        This is the daemon consumer of the reduced all-sources product —
        the reference's equivalent is N sequential buildRouteDb calls
        (Decision.cpp:615-793) over the per-source SPF memo.

        Views are cached per (LinkState version, destination set), so a
        warm cache serves any-node ctrl queries with zero device work.
        This is the operator's EXPLICIT fleet request: views are computed
        regardless of backend (a cold compute at scale runs a P-source
        device round — and, first time, its XLA compile — on the calling
        thread; the implicit any-node path applies the dispatch policy
        instead, see _build_fleet_views)."""
        views = self._build_fleet_views(
            area_link_states, prefix_state, explicit=True
        )
        if nodes is None:
            nodes = sorted(
                {
                    n
                    for ls in area_link_states.values()
                    for n in ls.node_names
                }
            )
        # queries touch each router and its neighbors: fetch the distance
        # columns for the whole dump in one device gather per area
        for area, view in views.items():
            ls = area_link_states[area]
            wanted = set()
            for n in nodes:
                if not view.covers(n):
                    continue
                wanted.add(n)
                for link in ls.links_from_node(n):
                    wanted.add(link.other_node_name(n))
            try:
                view.prefetch_rows(sorted(wanted))
            except Exception:
                self._bump("decision.device_fallbacks")
        out: dict[str, DecisionRouteDb] = {}
        for node in nodes:
            db = self.build_route_db(
                area_link_states,
                prefix_state,
                my_node_name=node,
                fleet_views=views,
            )
            out[node] = db if db is not None else DecisionRouteDb()
        return out

    def _build_node_label_routes(
        self,
        area_link_states: dict[str, LinkState],
        route_db: DecisionRouteDb,
    ) -> None:
        """MPLS routes for every node label (Decision.cpp:655-745)."""
        label_to_node: dict[int, tuple[str, RibMplsEntry]] = {}
        for area, link_state in area_link_states.items():
            for node, adj_db in sorted(
                link_state.get_adjacency_databases().items()
            ):
                top_label = adj_db.node_label
                if top_label == 0:
                    continue
                if not is_mpls_label_valid(top_label):
                    self._bump("decision.skipped_mpls_route")
                    continue
                existing = label_to_node.get(top_label)
                if existing is not None:
                    self._bump("decision.duplicate_node_label")
                    # collision: smaller node name retained
                    # (Decision.cpp:679-689)
                    if existing[0] < node:
                        continue
                if node == self.my_node_name:
                    nh = NextHop(
                        address="::",
                        area=area,
                        mpls_action=MplsAction(MplsActionCode.POP_AND_LOOKUP),
                    )
                    label_to_node[top_label] = (
                        node,
                        RibMplsEntry(top_label, frozenset({nh})),
                    )
                    continue
                min_metric, nexthop_nodes = self._get_next_hops_with_metric(
                    {(node, area)}, False, area_link_states
                )
                if not nexthop_nodes:
                    self._bump("decision.no_route_to_label")
                    continue
                label_to_node[top_label] = (
                    node,
                    RibMplsEntry(
                        top_label,
                        frozenset(
                            self._get_next_hops(
                                {(node, area)},
                                False,
                                False,
                                min_metric,
                                nexthop_nodes,
                                top_label,
                                area_link_states,
                                {},
                            )
                        ),
                    ),
                )
        for _label, (_node, entry) in label_to_node.items():
            route_db.add_mpls_route(entry)

    def _build_adj_label_routes(
        self,
        area_link_states: dict[str, LinkState],
        route_db: DecisionRouteDb,
    ) -> None:
        """MPLS routes for our adjacency labels (Decision.cpp:748-775)."""
        for _area, link_state in area_link_states.items():
            for link in sorted(link_state.links_from_node(self.my_node_name)):
                top_label = link.adj_label_from_node(self.my_node_name)
                if top_label == 0:
                    continue
                if not is_mpls_label_valid(top_label):
                    self._bump("decision.skipped_mpls_route")
                    continue
                nh = NextHop(
                    address=link.nh_v6_from_node(self.my_node_name),
                    if_name=link.iface_from_node(self.my_node_name),
                    metric=link.metric_from_node(self.my_node_name),
                    mpls_action=MplsAction(MplsActionCode.PHP),
                    area=link.area,
                    neighbor_node_name=link.other_node_name(self.my_node_name),
                )
                route_db.add_mpls_route(
                    RibMplsEntry(top_label, frozenset({nh}))
                )
