"""Operator-facing failure-protection analysis over a LinkState.

Wraps the batched device kernels in `openr_tpu.ops.protection` with
name-level inputs/outputs so they are drivable from the ctrl API and the
breeze CLI (VERDICT round-1: the kernels existed but had no operator
surface).  These are NEW capabilities relative to the reference — its
solver answers one source at a time, so a what-if sweep would need a full
Decision re-run per scenario (openr/decision/Decision.cpp:1866).

- `what_if`: F failure scenarios (each a set of links, e.g. one SRLG) in
  one batched device call -> per-scenario reachability impact.
- `ti_lfa`: per out-adjacency post-convergence SPF for one node -> backup
  first hops per destination, the input to TI-LFA repair-path selection.

All results are plain JSON-able dicts (the ctrl wire format).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..ops.sssp import INF32
from .csr import CsrTopology
from .link_state import LinkState

# element budget for one what-if call: F x S x N_cap int32 outputs
_WHAT_IF_MAX_ELEMENTS = 1 << 28  # 1 GiB of int32


def _pair_edge_ids(csr: CsrTopology) -> dict[tuple[str, str], list[int]]:
    """(sorted node pair) -> directed edge ids of every parallel link
    between them — one O(E) pass, O(1) per scenario-link lookup."""
    out: dict[tuple[str, str], list[int]] = {}
    for e, pair in enumerate(csr.edge_links):
        if pair is None:  # retired freelist slot
            continue
        link = pair[0]
        key = (link.n1, link.n2) if link.n1 <= link.n2 else (link.n2, link.n1)
        out.setdefault(key, []).append(e)
    return out


def what_if(
    link_state: LinkState,
    scenarios: list[list[tuple[str, str]]],
    sources: Optional[list[str]] = None,
    csr: Optional[CsrTopology] = None,
) -> list[dict]:
    """Evaluate failure scenarios; each scenario is a list of (node, node)
    links that fail together (a shared-risk link group).

    Returns one dict per scenario: the links resolved, how many
    (source, destination) pairs became unreachable, and how many degraded
    (still reachable, higher metric).  `sources` bounds the impact view
    (callers default it to the querying router); passing None means every
    node, which is refused beyond a size budget — the [F, S, N] output is
    cubic-ish and this runs on the Decision event thread."""
    from ..ops import protection as prot

    if csr is None:
        csr = CsrTopology.from_link_state(link_state)
    if sources is None:
        source_names = csr.node_names
    else:
        source_names = [s for s in sources if s in csr.node_id]
    if not source_names or not scenarios:
        return []
    # budget BOTH the [F*S, N_cap] distance output and the [F*S, E_cap]
    # per-row exclusion masks the ELL path materializes
    total = (len(scenarios) + 1) * len(source_names) * (
        csr.node_capacity + csr.edge_capacity
    )
    if total > _WHAT_IF_MAX_ELEMENTS:
        raise ValueError(
            f"what-if request too large ({len(scenarios)} scenarios x "
            f"{len(source_names)} sources x {csr.node_capacity} nodes); "
            f"restrict `sources`"
        )
    src_ids = np.asarray(
        [csr.node_id[s] for s in source_names], dtype=np.int32
    )

    # row 0 = no-failure baseline, rows 1.. = scenarios: one device call
    pair_ids = _pair_edge_ids(csr)
    masks = np.ones((len(scenarios) + 1, csr.edge_capacity), dtype=bool)
    resolved: list[dict] = []
    for f, links in enumerate(scenarios):
        known: list[list[str]] = []
        unknown: list[list[str]] = []
        for a, b in links:
            key = (a, b) if a <= b else (b, a)
            ids = pair_ids.get(key)
            if ids:
                masks[f + 1, ids] = False
                known.append([a, b])
            else:
                unknown.append([a, b])
        resolved.append({"links": known, "unknown_links": unknown})

    all_dist = prot.srlg_what_if(
        src_ids,
        csr.edge_src,
        csr.edge_dst,
        csr.edge_metric,
        csr.edge_up,
        csr.node_overloaded,
        masks,
        ell=csr.ell,
    )
    # restrict impact counting to real nodes (padding cols are unreachable
    # in baseline too, so they never count, but be explicit)
    real = np.asarray([csr.node_id[n] for n in csr.node_names])
    # offline what-if analysis over one fixed scenario batch, not the SPF
    # hot path — no residency or bucket ladder for the engine to apply
    # openr: disable=jit-unbucketed-dispatch
    unreachable, degraded = prot.srlg_reachability_loss(
        all_dist[0][:, real], all_dist[1:][:, :, real]
    )
    out = []
    for f in range(len(scenarios)):
        row = dict(resolved[f])
        row["scenario"] = f
        row["newly_unreachable_pairs"] = int(unreachable[f])
        row["degraded_pairs"] = int(degraded[f])
        out.append(row)
    return out


def ti_lfa(
    link_state: LinkState,
    node: str,
    csr: Optional[CsrTopology] = None,
    max_report_destinations: int = 1000,
) -> dict:
    """Per-out-adjacency backup analysis for `node`.

    For each up out-edge (node -> neighbor), runs the post-convergence SPF
    with that edge (and its reverse) failed, and reports per-destination
    backup first hops — the loop-free alternates TI-LFA encodes as repair
    segments.  Destinations unreachable even BEFORE the failure are
    excluded (they are a topology problem, not a protection gap).

    Counts always cover every destination; the per-destination
    backup/unprotected LISTS are truncated to `max_report_destinations`
    per adjacency (this runs on the Decision event thread and returns
    over the ctrl wire — an unbounded 100k-node report would stall both)."""
    from ..ops import protection as prot

    if csr is None:
        csr = CsrTopology.from_link_state(link_state)
    if node not in csr.node_id:
        return {"node": node, "error": "unknown node"}
    src_id = csr.node_id[node]

    out_edges = [
        e
        for e in range(csr.n_edges)
        if csr.edge_src[e] == src_id and csr.edge_up[e]
    ]
    if not out_edges:
        return {"node": node, "adjacencies": []}

    rev = prot.build_reverse_edge_ids(
        csr.edge_src[: csr.n_edges], csr.edge_dst[: csr.n_edges]
    )
    rev_full = np.full(csr.edge_capacity, -1, dtype=np.int32)
    rev_full[: csr.n_edges] = np.asarray(rev)

    # final row -1: nothing failed -> the pre-failure baseline, from the
    # same batched call (ti_lfa_backups masks nothing for ids < 0)
    dist, dag = prot.ti_lfa_backups(
        np.int32(src_id),
        np.asarray(out_edges + [-1], dtype=np.int32),
        csr.edge_src,
        csr.edge_dst,
        csr.edge_metric,
        csr.edge_up,
        csr.node_overloaded,
        rev_full,
        max_degree=len(out_edges) + 1,
        ell=csr.ell,
    )
    dist = np.asarray(dist)  # [D+1, N_cap]
    dag = np.asarray(dag)  # [D+1, E_cap]
    baseline = dist[-1]

    adjacencies = []
    for d, e_failed in enumerate(out_edges):
        failed_nbr = csr.node_names[int(csr.edge_dst[e_failed])]
        backups = _first_hops_from_dag(csr, src_id, dist[d], dag[d])
        reachable = 0
        lost = 0
        truncated = False
        unprotected: list[str] = []
        backup_map: dict[str, list[str]] = {}
        for v_name in csr.node_names:
            v = csr.node_id[v_name]
            if v == src_id or baseline[v] >= INF32:
                continue  # self, or already unreachable pre-failure
            if dist[d, v] < INF32:
                reachable += 1
                if len(backup_map) < max_report_destinations:
                    backup_map[v_name] = sorted(backups.get(v, ()))
                else:
                    truncated = True
            else:
                lost += 1
                if len(unprotected) < max_report_destinations:
                    unprotected.append(v_name)
                else:
                    truncated = True
        adjacencies.append(
            {
                "neighbor": failed_nbr,
                "protected_destinations": reachable,
                "unprotected_count": lost,
                "unprotected_destinations": unprotected,
                "backup_first_hops": backup_map,
                "truncated": truncated,
            }
        )
    return {"node": node, "adjacencies": adjacencies}


def _first_hops_from_dag(
    csr: CsrTopology, src_id: int, dist_row: np.ndarray, dag_row: np.ndarray
) -> dict[int, set[str]]:
    """Propagate first-hop sets along the SP-DAG (host, one row).

    Edges processed in ascending head-distance order so predecessors are
    final before their successors — mirrors the device first-hop kernel's
    fixed-point semantics on a single row."""
    first_hops: dict[int, set[str]] = {}
    edges = [e for e in range(csr.n_edges) if dag_row[e]]
    edges.sort(key=lambda e: int(dist_row[csr.edge_dst[e]]))
    for e in edges:
        u, v = int(csr.edge_src[e]), int(csr.edge_dst[e])
        if u == src_id:
            first_hops.setdefault(v, set()).add(csr.node_names[v])
        elif u in first_hops:
            first_hops.setdefault(v, set()).update(first_hops[u])
    return first_hops
