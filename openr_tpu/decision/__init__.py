from .link_state import HoldableValue, Link, LinkState, LinkStateChange, NodeSpfResult
from .prefix_state import PrefixState

__all__ = [
    "HoldableValue",
    "Link",
    "LinkState",
    "LinkStateChange",
    "NodeSpfResult",
    "PrefixState",
]
