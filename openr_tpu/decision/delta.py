"""Decision-side coalescer for the incremental delta SPF rung.

`DeltaProductUpdater` folds every LinkState mutation that landed since
the previous converged fleet view — k pending events (adj up/down,
metric change, overload flip) — into ONE batched frontier certification
+ ONE frontier-sized relax through `DeviceResidencyEngine.delta_dispatch`
(openr_tpu.ops.delta kernels), instead of k (or even one) full [N, P]
fused products.  Work on device is proportional to the affected columns,
not k*N*P.

The safety story is entirely the existing warm-start machinery,
generalized to MIXED batches:

- worsened slots (removed/metric-increased pairs, newly-drained transit)
  seed the certified tight-chain propagation over the OLD graph
  (decision.fleet._worsened_masks -> ops.banded.affected_mask);
- improved slots (new/metric-decreased pairs, un-drained transit) are
  checked by firing the NEW graph's exact relax candidates at those
  slots against the old distances (`_improved_masks`, NEW layout);
  `cand <= d` — an equality-creating improvement moves the ECMP bitmap
  without moving the distance, so equality must mark the column too;
- every destination column outside either set is PROVEN unchanged and
  keeps its old device column verbatim; flagged columns re-relax from
  the `_affected_init` upper bound and re-certify on device.

Every gate failure — uncertified propagation, frontier over the bucket
ladder (engine.delta_bucket -> None), dtype/layout drift, non-converged
relax — falls back to the legacy full path by returning False: the
caller (FleetViewCache.view) then runs exactly the code it would have
run without this module, which is the bit-exact fallback the tentpole
requires.  An optional parity gate (OPENR_DELTA_PARITY=1) recomputes
the full cold product after every delta update and adopts it on any
mismatch, bumping decision.delta.parity_failures.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import numpy as np

from .fleet import (
    FleetRouteView,
    _in_sorted,
    _reverse_runner,
    _worsened_masks,
)

log = logging.getLogger(__name__)

# pre-seeded into SpfSolver.counters so both wire surfaces (ctrl handler
# + fb303 shim) expose the family from daemon start (counter-hygiene
# discipline: registered keys are bumped via these exact literals below)
DELTA_COUNTER_KEYS = (
    "decision.delta.updates",
    "decision.delta.noop_updates",
    "decision.delta.events_coalesced",
    "decision.delta.dispatches",
    "decision.delta.affected_cols",
    "decision.delta.fallbacks",
    "decision.delta.parity_checks",
    "decision.delta.parity_failures",
)


def _improved_masks(prev: FleetRouteView, new: FleetRouteView, new_runner):
    """Per-reverse-slot masks of IMPROVED forward edges, in the layout of
    the NEW view's reverse runner — the improvement-direction mirror of
    decision.fleet._worsened_masks (which marks worsened slots in the
    OLD layout).

    Improved means the edge can only SHORTEN paths or create new ties:
    - usable directed pair absent from the old table (link/adjacency up),
    - pair present with a smaller min metric,
    - transit through a node that DROPPED its overload bit: every
      reverse slot whose neighbor is that node regained its relax-allow
      (conservatively including the destination-row exception — over-
      marking improved slots only adds candidate checks, never error).
    The NEW layout is the right frame: these slots exist in the new
    graph (a brand-new pair has no old slot at all), and the frontier
    kernel evaluates their NEW exact candidates against the old
    distances (ops.delta.delta_frontier)."""
    old_keys, old_met = prev._edge_keys, prev._edge_met
    new_keys, new_met = new._edge_keys, new._edge_met
    present = _in_sorted(old_keys, new_keys)
    better = ~present
    if len(old_keys):
        pos = np.minimum(
            np.searchsorted(old_keys, new_keys), len(old_keys) - 1
        )
        better |= present & (new_met < old_met[pos])
    good_keys = new_keys[better]  # sorted (subset of sorted new_keys)
    ov_drop = prev._overloaded & ~new._overloaded
    bg = new_runner.bg
    n = bg.n_nodes
    rn = np.asarray(bg.resid_nbr)
    re_ = np.asarray(bg.resid_eid)
    v_ids = np.arange(n, dtype=np.int64)
    # reverse slot (v, k) with neighbor u is forward edge v -> u
    qk = (v_ids[:, None] << 32) | rn.astype(np.int64)
    improved_resid = (re_ >= 0) & (_in_sorted(good_keys, qk) | ov_drop[rn])
    be = np.asarray(bg.band_eid)
    rows = []
    for b, c in enumerate(bg.offsets):
        u = (v_ids - c) % n
        qk = (v_ids << 32) | u
        rows.append((be[b] >= 0) & (_in_sorted(good_keys, qk) | ov_drop[u]))
    return improved_resid, np.stack(rows)


def _changed_out_rows(prev_out, new_out) -> Optional[np.ndarray]:
    """Node ids whose OutEll row content changed — their bitmap words
    need re-encoding even when no route changed, because OutEll.slot is
    the rank among sorted unique out-neighbors and gaining/losing an
    out-edge (even a DOWN one) re-ranks the survivors.  Returns None
    when the table shapes diverged (caller falls back); row-order drift
    inside a node only over-marks (re-encoding an unchanged row is
    idempotent)."""
    on, nn = np.asarray(prev_out.nbr), np.asarray(new_out.nbr)
    oe, ne = np.asarray(prev_out.eid), np.asarray(new_out.eid)
    os_, ns = np.asarray(prev_out.slot), np.asarray(new_out.slot)
    if on.shape != nn.shape:
        return None
    ov, nv = oe >= 0, ne >= 0
    diff = (ov != nv) | (nv & ((on != nn) | (os_ != ns)))
    return np.flatnonzero(diff.any(axis=1)).astype(np.int32)


class DeltaProductUpdater:
    """One attempt = one coalesced event batch folded into the previous
    view's device product, or False (caller takes the legacy path)."""

    def __init__(
        self,
        bump=None,
        min_p: int = 32,
        parity: Optional[bool] = None,
        max_iters: int = 128,
    ) -> None:
        # counter sink (SpfSolver._bump); None is a no-op sink so the
        # updater works engine-style in tests/bench without a solver
        self._bump_fn = bump
        # below this product width the full fused product is already a
        # single cheap dispatch — the bucket ladder has no room to win
        self.min_p = min_p
        self.max_iters = max_iters
        if parity is None:
            parity = os.environ.get("OPENR_DELTA_PARITY", "0") == "1"
        self.parity = parity
        # last-update work attribution, read by bench/chaos:
        # (relax while-loop blocks, padded column bucket) or None
        self.last_blocks: Optional[int] = None
        self.last_pb: Optional[int] = None
        self.last_cols: int = 0

    def _bump(self, name: str, delta: int = 1) -> None:
        if self._bump_fn is not None:
            self._bump_fn(name, delta)

    # -- gates ---------------------------------------------------------------

    def eligible(self, prev: Optional[FleetRouteView]) -> bool:
        """Cheap host-only screen over the PREVIOUS view — the full
        update() re-checks everything it needs; this exists so callers
        can skip building masks for hopeless cases."""
        return (
            prev is not None
            and prev.converged
            and prev._dist_dev is not None
            and prev._bitmap_dev is not None
            and prev._runner is not None
            and prev._runner.bg is not None
            and prev._out is not None
            and len(prev.dest_names) >= self.min_p
        )

    # -- the update ----------------------------------------------------------

    def update(self, prev: FleetRouteView, view: FleetRouteView, engine) -> bool:
        """Fold the prev->view LinkState delta into prev's device product
        and finalize `view` from it (warm_mode == "delta").  False means
        nothing was changed and the caller must run the legacy path; the
        ONE exception is a post-donation relax failure, which kills
        prev's arrays (prev.converged flips False so the legacy warm
        gates skip it and the rebuild goes cold — correct, one extra
        cold run)."""
        import jax
        import jax.numpy as jnp

        from ..ops import allsources as asrc
        from ..ops import delta as dops

        if engine is None or not self.eligible(prev):
            return False
        if (
            prev.dest_names != view.dest_names
            or prev._node_id != view._node_id
            or prev._overloaded.shape != view._overloaded.shape
        ):
            return False  # universe changed: columns are not comparable
        csr = view.csr
        prev_small = prev._dist_dev.dtype == np.uint16
        try:
            runner = _reverse_runner(csr)
        except Exception:
            log.warning("delta: reverse runner build failed", exc_info=True)
            self._bump("decision.delta.fallbacks")
            return False
        if runner.bg is None or runner.small_dist != prev_small:
            # no band structure, or the distance dtype must change
            # (saturation risk either way): donation-in-place is off
            self._bump("decision.delta.fallbacks")
            return False
        out = asrc.build_out_ell(
            csr.edge_src,
            csr.edge_dst,
            csr.n_edges,
            csr.n_nodes,
            out_slot=csr.out_slot,
        )
        if out.n_words != prev._out.n_words:
            self._bump("decision.delta.fallbacks")
            return False
        changed_rows = _changed_out_rows(prev._out, out)
        if changed_rows is None or 2 * len(changed_rows) > csr.n_nodes:
            # out-table shape drift, or so many rows re-ranked the row
            # re-encode would rival a full bitmap pass
            self._bump("decision.delta.fallbacks")
            return False
        events = max(1, int(view.version) - int(prev.version))

        worsened_resid, worsened_band = _worsened_masks(
            prev, view._edge_keys, view._edge_met, view._overloaded
        )
        improved_resid, improved_band = _improved_masks(prev, view, runner)

        p = len(view.dest_names)
        epoch = int(csr.version)
        _, _, o_met, o_up, o_ov = prev._runner.call_arrays()
        _, _, n_met, n_up, n_ov = runner.call_arrays()
        topo_key = (csr.n_nodes, csr.n_edges, p)
        try:
            aff, col_mask, done = engine.delta_dispatch(
                "frontier",
                dops.delta_frontier,
                prev._dist_dev,
                prev._runner.bg,
                o_up,
                o_met,
                o_ov,
                jnp.asarray(worsened_resid),
                jnp.asarray(worsened_band),
                runner.bg,
                n_up,
                n_met,
                n_ov,
                jnp.asarray(improved_resid),
                jnp.asarray(improved_band),
                small_dist=prev_small,
                max_iters=self.max_iters,
                csr=csr,
                expect_epoch=epoch,
            )
            self._bump("decision.delta.dispatches")
            # one fused fetch: the certification verdict + the column
            # frontier drive host control flow (bucket pick / fallback)
            done_h, col_mask_h = jax.device_get((done, col_mask))
        except Exception:
            log.warning("delta: frontier dispatch failed", exc_info=True)
            self._bump("decision.delta.fallbacks")
            return False
        if not bool(done_h):
            # propagation ran out of iterations before its fixpoint: an
            # under-propagated frontier is silently wrong — fall back
            self._bump("decision.delta.fallbacks")
            return False
        col_idx = np.flatnonzero(col_mask_h).astype(np.int32)
        n_cols = len(col_idx)
        self.last_cols = n_cols
        if n_cols == 0 and len(changed_rows) == 0:
            # certified no-op: every column keeps its proof, every bitmap
            # row keeps its encoding — adopt the previous arrays verbatim
            self._adopt(prev, view, runner, out, prev._dist_dev,
                        prev._bitmap_dev)
            self.last_blocks, self.last_pb = 0, 0
            self._bump("decision.delta.noop_updates")
            self._bump("decision.delta.events_coalesced", events)
            return True

        new_dist, new_bm = prev._dist_dev, prev._bitmap_dev
        blocks_h = 0
        pb = 0
        if n_cols:
            pb = engine.delta_bucket(n_cols, p)
            if pb is None:
                # frontier bound exceeded — the full fused product is
                # the cheaper (and bit-exact) program for this batch
                self._bump("decision.delta.fallbacks")
                return False
            col_pad = np.full(pb, col_idx[0], dtype=np.int32)
            col_pad[:n_cols] = col_idx
            dest_ids = np.asarray(
                [view._node_id[d] for d in view.dest_names], dtype=np.int32
            )
            maps = asrc.build_epilogue_maps(runner.bg, out)
            try:
                new_dist, new_bm, conv, blocks = engine.delta_dispatch(
                    "relax",
                    dops.delta_relax,
                    new_dist,
                    new_bm,
                    aff,
                    jnp.asarray(col_pad),
                    jnp.asarray(dest_ids),
                    runner.bg,
                    n_up,
                    n_met,
                    n_ov,
                    maps.resid_slot,
                    maps.band_slot,
                    depth=runner.depth,
                    resid_rounds=runner.resid_rounds,
                    small_dist=prev_small,
                    chord_mode=runner.chord_mode,
                    n_words=out.n_words,
                    csr=csr,
                    expect_epoch=epoch,
                    bucket_key=(
                        "relax", topo_key, pb, out.n_words, prev_small,
                        runner.depth, runner.chord_mode,
                    ),
                )
                self._bump("decision.delta.dispatches")
                conv_h, blocks_h = jax.device_get((conv, blocks))
            except Exception:
                log.warning("delta: relax dispatch failed", exc_info=True)
                self._kill(prev)
                self._bump("decision.delta.fallbacks")
                return False
            finally:
                # the relax DONATED prev's buffers: dead either way
                prev._dist_dev = None
                prev._bitmap_dev = None
                prev._rows = {}
            if not bool(conv_h):
                # block budget ran out without the on-device certificate
                self._kill(prev)
                self._bump("decision.delta.fallbacks")
                return False
        if len(changed_rows):
            rb = 1
            while rb < len(changed_rows):
                rb *= 2
            row_pad = np.full(rb, changed_rows[0], dtype=np.int32)
            row_pad[: len(changed_rows)] = changed_rows
            try:
                new_bm = engine.delta_dispatch(
                    "rows_bitmap",
                    dops.delta_rows_bitmap,
                    new_bm,
                    new_dist,
                    jnp.asarray(row_pad),
                    out.nbr,
                    out.eid,
                    out.slot,
                    jnp.asarray(csr.edge_metric),
                    jnp.asarray(csr.edge_up),
                    jnp.asarray(csr.node_overloaded),
                    n_words=out.n_words,
                    csr=csr,
                    expect_epoch=epoch,
                    bucket_key=("rows", topo_key, rb, out.n_words),
                )
                self._bump("decision.delta.dispatches")
            except Exception:
                log.warning("delta: row re-encode failed", exc_info=True)
                self._kill(prev)
                self._bump("decision.delta.fallbacks")
                return False
            finally:
                prev._dist_dev = None
                prev._bitmap_dev = None
                prev._rows = {}

        self._adopt(prev, view, runner, out, new_dist, new_bm)
        self.last_blocks = int(blocks_h)
        self.last_pb = int(pb)
        self._bump("decision.delta.updates")
        self._bump("decision.delta.events_coalesced", events)
        self._bump("decision.delta.affected_cols", n_cols)
        if self.parity:
            self._parity_gate(view)
        return True

    # -- internals -----------------------------------------------------------

    @staticmethod
    def _kill(prev: FleetRouteView) -> None:
        """Post-donation failure: prev's device arrays are gone, so mark
        it unusable for the legacy warm gates (they require converged +
        live arrays) — the rebuild then cold-starts, which is correct."""
        prev._dist_dev = None
        prev._bitmap_dev = None
        prev._rows = {}
        prev.converged = False

    def _adopt(self, prev, view, runner, out, dist, bitmap) -> None:
        view._dist_dev = dist
        view._bitmap_dev = bitmap
        view._out = out
        view._runner = runner
        view.converged = True
        view.warm = True
        view.warm_mode = "delta"
        # the delta path never learns a cold sweep budget; carry the
        # previous view's so a later cold rebuild keeps its head start
        view.sweep_hint = prev.sweep_hint
        prev._dist_dev = None
        prev._bitmap_dev = None
        prev._rows = {}

    def _parity_gate(self, view: FleetRouteView) -> None:
        """Host-oracle parity: recompute the full cold product for the
        same snapshot and require bit-exact equality.  On mismatch the
        oracle's arrays replace the delta result (serve correct routes)
        and parity_failures records the bug."""
        import jax

        self._bump("decision.delta.parity_checks")
        oracle = FleetRouteView(view.csr, view.dest_names)
        oracle.compute()
        d_a, b_a = jax.device_get((view._dist_dev, view._bitmap_dev))
        d_o, b_o = jax.device_get((oracle._dist_dev, oracle._bitmap_dev))
        n = oracle._runner.bg.n_nodes if oracle._runner.bg is not None else (
            d_o.shape[0]
        )
        if (
            d_a.dtype != d_o.dtype
            or not np.array_equal(d_a[:n], d_o[:n])
            or not np.array_equal(b_a, b_o)
        ):
            log.error("delta: parity gate FAILED; adopting oracle product")
            self._bump("decision.delta.parity_failures")
            view._dist_dev = oracle._dist_dev
            view._bitmap_dev = oracle._bitmap_dev
            view._out = oracle._out
            view._runner = oracle._runner
            view._rows = {}
