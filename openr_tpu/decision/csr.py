"""Device-resident tensor mirror of the link-state graph.

The reference walks a pointer graph (LinkState::linkMap_) per Dijkstra run;
the TPU build mirrors the topology once into padded directed-edge arrays
(CSR-style, sorted by destination for segment ops) and batches every SPF
question over it (openr_tpu.ops.sssp).

Shape discipline: node/edge capacities are padded to power-of-two buckets so
incremental topology changes re-use compiled kernels; a rebuild only grows
capacity when the bucket overflows.  Padding edges carry edge_up=False and
point at the last padding node, keeping the dst-sorted invariant.

String node ids are interned to dense int32 here — nothing above this layer
touches the device, nothing below it sees a string.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from .link_state import Link, LinkState, NodeSpfResult, SpfResult


def _next_pow2(n: int, floor: int = 8) -> int:
    c = floor
    while c < n:
        c *= 2
    return c


@dataclass
class CsrTopology:
    """Padded directed-edge arrays + host-side interning tables."""

    node_names: list[str]  # dense id -> name (sorted)
    node_id: dict[str, int]
    n_nodes: int  # real node count
    node_capacity: int
    edge_capacity: int
    # numpy host arrays (device transfer happens at kernel call sites)
    edge_src: np.ndarray  # [E_cap] int32
    edge_dst: np.ndarray  # [E_cap] int32
    edge_metric: np.ndarray  # [E_cap] int32
    edge_up: np.ndarray  # [E_cap] bool
    node_overloaded: np.ndarray  # [N_cap] bool
    # directed edge id -> (Link, from_node_name); len == real edge count
    edge_links: list[tuple[Link, str]]
    n_edges: int = 0
    version: int = -1  # LinkState.version this mirror was built from
    # degree-bucketed ELL mirror (ops.sssp.EllGraph) — the production
    # relaxation tables; rebuilt with the edge arrays
    ell: object = None

    # -- construction -------------------------------------------------------

    @classmethod
    def from_link_state(
        cls,
        ls: LinkState,
        node_capacity: Optional[int] = None,
        edge_capacity: Optional[int] = None,
    ) -> "CsrTopology":
        names = ls.node_names
        node_id = {n: i for i, n in enumerate(names)}
        n = len(names)
        n_cap = node_capacity or _next_pow2(n + 1)
        assert n_cap > n, "node capacity must exceed node count (padding node)"

        # two directed edges per link; deterministic order: sort by (dst, src)
        rows: list[tuple[int, int, int, bool, Link, str]] = []
        for link in sorted(ls.all_links):
            for u_name in (link.n1, link.n2):
                v_name = link.other_node_name(u_name)
                rows.append(
                    (
                        node_id[v_name],  # dst first: sort key
                        node_id[u_name],
                        link.metric_from_node(u_name),
                        link.is_up(),
                        link,
                        u_name,
                    )
                )
        rows.sort(key=lambda r: (r[0], r[1]))
        e = len(rows)
        assert all(r[2] >= 1 for r in rows), (
            "edge metrics must be >= 1 (distance-ordered DAG propagation "
            "and int32 distance math rely on positive metrics)"
        )
        e_cap = edge_capacity or _next_pow2(e)
        assert e_cap >= e

        pad_node = n_cap - 1
        edge_src = np.full(e_cap, pad_node, dtype=np.int32)
        edge_dst = np.full(e_cap, pad_node, dtype=np.int32)
        edge_metric = np.ones(e_cap, dtype=np.int32)
        edge_up = np.zeros(e_cap, dtype=bool)
        for i, (dst, src, metric, up, _link, _from) in enumerate(rows):
            edge_src[i] = src
            edge_dst[i] = dst
            edge_metric[i] = metric
            edge_up[i] = up

        node_overloaded = np.zeros(n_cap, dtype=bool)
        for name, i in node_id.items():
            node_overloaded[i] = ls.is_node_overloaded(name)

        from ..ops.sssp import build_ell

        ell = build_ell(
            edge_src, edge_dst, edge_metric, edge_up, node_overloaded, e
        )

        return cls(
            node_names=names,
            node_id=node_id,
            n_nodes=n,
            node_capacity=n_cap,
            edge_capacity=e_cap,
            edge_src=edge_src,
            edge_dst=edge_dst,
            edge_metric=edge_metric,
            edge_up=edge_up,
            node_overloaded=node_overloaded,
            edge_links=[(r[4], r[5]) for r in rows],
            n_edges=e,
            version=ls.version,
            ell=ell,
        )

    # -- SPF execution ------------------------------------------------------

    def run_batched_spf(
        self,
        sources: list[str],
        use_link_metric: bool = True,
        extra_edge_mask: Optional[np.ndarray] = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Run the device kernel (bucketed-ELL relaxation); returns
        (dist [S, N_cap], dag [S, E_cap]) as numpy."""
        from ..ops import sssp as ops

        src_ids = np.asarray(
            [self.node_id[s] for s in sources], dtype=np.int32
        )
        if extra_edge_mask is None:
            dist, dag = ops.spf_forward_ell(
                src_ids,
                self.ell,
                self.edge_src,
                self.edge_dst,
                self.edge_metric,
                self.edge_up,
                self.node_overloaded,
                use_link_metric=use_link_metric,
            )
        else:
            dist, dag = ops.spf_forward_ell_masked(
                src_ids,
                self.ell,
                self.edge_src,
                self.edge_dst,
                self.edge_metric,
                self.edge_up,
                self.node_overloaded,
                np.asarray(extra_edge_mask),
                use_link_metric=use_link_metric,
            )
        return np.asarray(dist), np.asarray(dag)

    # -- result reconstruction (parity with the host oracle) ----------------

    def to_spf_results(
        self,
        sources: list[str],
        dist: np.ndarray,
        dag: np.ndarray,
    ) -> dict[str, SpfResult]:
        """Convert kernel output into reference-shaped SpfResults: per node
        metric, tie-retaining path_links, and first-hop `next_hops` sets
        (computed by host propagation along the SP-DAG in topological
        order)."""
        from ..ops.sssp import INF32

        inf = int(INF32)
        out: dict[str, SpfResult] = {}
        for row, src_name in enumerate(sources):
            d = dist[row]
            mask = dag[row]
            result: SpfResult = {}
            reachable = [
                i for i in range(self.n_nodes) if d[i] < inf
            ]
            for i in reachable:
                result[self.node_names[i]] = NodeSpfResult(int(d[i]))
            # path links from DAG edges
            for e in np.nonzero(mask[: self.n_edges])[0]:
                link, from_name = self.edge_links[e]
                v = self.node_names[int(self.edge_dst[e])]
                result[v].path_links.append((link, from_name))
            # First hops: propagate along the DAG in increasing-distance
            # order (metrics are >= 1 so this is a topological order).  A
            # direct shortest edge src->v always contributes v itself as a
            # first hop (reference: addNextHop(otherNodeName) fires while
            # v's set is still empty at src's pop, and survives unless a
            # strictly shorter path resets it — i.e. iff src->v is a DAG
            # edge).
            src_id = self.node_id[src_name]
            order = sorted(reachable, key=lambda i: (int(d[i]), self.node_names[i]))
            for i in order:
                if i == src_id:
                    continue
                name = self.node_names[i]
                res = result[name]
                for link, prev in res.path_links:
                    if prev == src_name:
                        res.next_hops.add(name)
                    else:
                        res.next_hops |= result[prev].next_hops
            out[src_name] = result
        return out

    def spf_from(
        self, sources: list[str], use_link_metric: bool = True
    ) -> dict[str, SpfResult]:
        dist, dag = self.run_batched_spf(sources, use_link_metric)
        return self.to_spf_results(sources, dist, dag)

    # -- device first-hop support -------------------------------------------

    def build_edge_slots(
        self, sources: list[str]
    ) -> tuple[np.ndarray, list[list[str]]]:
        """Per source row: map each out-edge of the row's source to a dense
        'first hop slot' (index into that row's sorted unique neighbor
        list).  Feeds ops.sssp.first_hop_matrix; slot lists translate device
        output back to neighbor node names."""
        slot_names: list[list[str]] = []
        edge_slot = np.full(
            (len(sources), self.edge_capacity), -1, dtype=np.int32
        )
        links_of = self._links_of
        edges_by_src: dict[int, list[int]] = {}
        for e in range(self.n_edges):
            edges_by_src.setdefault(int(self.edge_src[e]), []).append(e)
        for row, src in enumerate(sources):
            src_id = self.node_id[src]
            neighbors = sorted(
                {link.other_node_name(src) for link in links_of.get(src, ())}
            )
            slot_of = {n: i for i, n in enumerate(neighbors)}
            slot_names.append(neighbors)
            for e in edges_by_src.get(src_id, ()):
                v = self.node_names[int(self.edge_dst[e])]
                edge_slot[row, e] = slot_of[v]
        return edge_slot, slot_names

    @property
    def _links_of(self) -> dict[str, list[Link]]:
        links: dict[str, list[Link]] = {}
        for link, from_name in self.edge_links:
            links.setdefault(from_name, []).append(link)
        return links

    @property
    def max_degree(self) -> int:
        deg: dict[str, set[str]] = {}
        for link, from_name in self.edge_links:
            deg.setdefault(from_name, set()).add(link.other_node_name(from_name))
        return max((len(v) for v in deg.values()), default=0)
