"""Device-resident tensor mirror of the link-state graph.

The reference walks a pointer graph (LinkState::linkMap_) per Dijkstra run;
the TPU build mirrors the topology once into padded directed-edge arrays
(CSR-style, sorted by destination for segment ops) and batches every SPF
question over it (openr_tpu.ops.sssp).

Shape discipline: node/edge capacities are padded to power-of-two buckets so
incremental topology changes re-use compiled kernels; a rebuild only grows
capacity when the bucket overflows.  Padding edges carry edge_up=False and
point at the last padding node, keeping the dst-sorted invariant.

String node ids are interned to dense int32 here — nothing above this layer
touches the device, nothing below it sees a string.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from .link_state import Link, LinkState, NodeSpfResult, SpfResult


def _next_pow2(n: int, floor: int = 8) -> int:
    c = floor
    while c < n:
        c *= 2
    return c


def _build_out_slots(
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    n_edges: int,
    live: Optional[np.ndarray] = None,
) -> tuple[np.ndarray, int]:
    """out_slot[e] = rank of edge e's dst among src(e)'s sorted unique
    out-neighbors (parallel links share the slot); -1 for padding.
    Node ids are assigned in sorted-name order, so id rank == the
    reference's name-sorted neighbor ordering.  Vectorized numpy.

    `live` (the freelist's per-slot mask) excludes retired edge slots
    inside [:n_edges]: dead slots rank as padding (-1), never as
    out-neighbors of the padding node."""
    e_cap = len(edge_src)
    out_slot = np.full(e_cap, -1, dtype=np.int32)
    if n_edges == 0:
        return out_slot, 0
    if live is None:
        ids = np.arange(n_edges, dtype=np.int64)
    else:
        ids = np.flatnonzero(live[:n_edges]).astype(np.int64)
        if ids.size == 0:
            return out_slot, 0
    src = edge_src[ids].astype(np.int64)
    dst = edge_dst[ids].astype(np.int64)
    order = np.lexsort((dst, src))
    s_o, d_o = src[order], dst[order]
    new_grp = np.r_[True, s_o[1:] != s_o[:-1]]
    new_nbr = new_grp | np.r_[False, d_o[1:] != d_o[:-1]]
    nbr_rank = np.cumsum(new_nbr) - 1  # global distinct-neighbor counter
    grp_id = np.cumsum(new_grp) - 1
    first_rank = nbr_rank[new_grp]  # [n_groups]
    slots = (nbr_rank - first_rank[grp_id]).astype(np.int32)
    out_slot[ids[order]] = slots
    return out_slot, int(slots.max()) + 1


@dataclass
class RewireDelta:
    """One bounded in-place edge-set change (an OCS rewire) applied by
    CsrTopology._try_rewire.  Everything the device-residency engine
    needs to patch its mirror with masked writes instead of a restage:
    the rewritten edge-array slots (post-rewire values), the out_slot
    entries whose rank moved, and the full post-rewire contents of every
    re-encoded ELL destination row."""

    seq: int  # csr.rewire_seq after this rewire (contiguous chain)
    version: int  # LinkState.version the rewire landed at
    slots: np.ndarray  # [M] int32 — edge slots rewritten in place
    src: np.ndarray  # [M] int32
    dst: np.ndarray  # [M] int32
    metric: np.ndarray  # [M] int32
    up: np.ndarray  # [M] bool
    live: np.ndarray  # [M] bool
    out_idx: np.ndarray  # int32 — out_slot entries whose rank changed
    out_val: np.ndarray  # int32
    # [(bucket index, local row, nbr, w, eid, ok, transit_ok)] — full
    # post-rewire row contents in the ELL bucket layout
    ell_rows: list
    n_edges: int  # post-rewire high-water edge count
    max_out_slots: int  # post-rewire first-hop slot ceiling
    links_added: int
    links_removed: int


@dataclass
class CsrTopology:
    """Padded directed-edge arrays + host-side interning tables."""

    node_names: list[str]  # dense id -> name (sorted)
    node_id: dict[str, int]
    n_nodes: int  # real node count
    node_capacity: int
    edge_capacity: int
    # numpy host arrays (device transfer happens at kernel call sites)
    edge_src: np.ndarray  # [E_cap] int32
    edge_dst: np.ndarray  # [E_cap] int32
    edge_metric: np.ndarray  # [E_cap] int32
    edge_up: np.ndarray  # [E_cap] bool
    node_overloaded: np.ndarray  # [N_cap] bool
    # directed edge id -> (Link, from_node_name), or None for a retired
    # slot; len == n_edges (the high-water edge count)
    edge_links: list[Optional[tuple[Link, str]]]
    n_edges: int = 0
    version: int = -1  # LinkState.version this mirror was built from
    # edge-slot freelist (OCS rewires): live mask over [:n_edges] — a
    # retired slot keeps its position (styled like padding: src = dst =
    # pad node, up False) so the edge arrays, ELL tables and compiled
    # kernels all survive a bounded edge-set change in place
    edge_live: Optional[np.ndarray] = None  # [E_cap] bool
    n_live: int = 0  # live directed edges (2 x live links)
    rewire_seq: int = 0  # bumped once per applied in-place rewire
    _free_slots: list = field(default_factory=list)
    # bounded chain of RewireDeltas for engine consumption; a resident
    # that fell behind the window restages (engine._rewire_sync)
    _rewire_log: list = field(default_factory=list)
    # degree-bucketed ELL mirror (ops.sssp.EllGraph) — the production
    # relaxation tables; rebuilt with the edge arrays
    ell: object = None
    # out_slot[e]: index of edge e's destination among its source node's
    # sorted unique out-neighbors (-1 padding) — feeds the bit-packed
    # device first-hop kernel (ops.sssp.first_hops_ell)
    out_slot: Optional[np.ndarray] = None
    max_out_slots: int = 0  # max distinct out-neighbors over all nodes
    # adaptive fixed-sweep hint for the relax loops (see spf_from); grows
    # by doubling when a run fails to reach the fixed point
    _sweep_hint: int = 16
    # circulant-band decomposition (ops.banded.BandedGraph) — present when
    # the topology has band structure; drives the banded relax kernel
    banded: object = None
    _runner: object = None

    @property
    def runner(self):
        """ops.banded.SpfRunner over this mirror: band-aware fixed-sweep
        execution for dist/dag batches (KSP re-runs, what-if, TI-LFA).
        Reads the SAME numpy arrays the mirror refreshes in place, so
        attribute-only refreshes need no runner rebuild."""
        if self._runner is None:
            from ..ops.banded import SpfRunner

            self._runner = SpfRunner(
                self.ell,
                self.banded,
                self.edge_src,
                self.edge_dst,
                self.edge_metric,
                self.edge_up,
                self.node_overloaded,
                self.n_edges,
            )
            # device-pin the runtime arrays (re-staged by refresh())
            self._runner.stage()
        return self._runner

    # -- construction -------------------------------------------------------

    @classmethod
    def from_link_state(
        cls,
        ls: LinkState,
        node_capacity: Optional[int] = None,
        edge_capacity: Optional[int] = None,
    ) -> "CsrTopology":
        names = ls.node_names
        node_id = {n: i for i, n in enumerate(names)}
        n = len(names)
        n_cap = node_capacity or _next_pow2(n + 1)
        assert n_cap > n, "node capacity must exceed node count (padding node)"

        # two directed edges per link; deterministic order: sort by (dst, src)
        rows: list[tuple[int, int, int, bool, Link, str]] = []
        for link in sorted(ls.all_links):
            for u_name in (link.n1, link.n2):
                v_name = link.other_node_name(u_name)
                rows.append(
                    (
                        node_id[v_name],  # dst first: sort key
                        node_id[u_name],
                        link.metric_from_node(u_name),
                        link.is_up(),
                        link,
                        u_name,
                    )
                )
        rows.sort(key=lambda r: (r[0], r[1]))
        e = len(rows)
        assert all(r[2] >= 1 for r in rows), (
            "edge metrics must be >= 1 (distance-ordered DAG propagation "
            "and int32 distance math rely on positive metrics)"
        )
        e_cap = edge_capacity or _next_pow2(e)
        assert e_cap >= e

        pad_node = n_cap - 1
        edge_src = np.full(e_cap, pad_node, dtype=np.int32)
        edge_dst = np.full(e_cap, pad_node, dtype=np.int32)
        edge_metric = np.ones(e_cap, dtype=np.int32)
        edge_up = np.zeros(e_cap, dtype=bool)
        for i, (dst, src, metric, up, _link, _from) in enumerate(rows):
            edge_src[i] = src
            edge_dst[i] = dst
            edge_metric[i] = metric
            edge_up[i] = up

        node_overloaded = np.zeros(n_cap, dtype=bool)
        for name, i in node_id.items():
            node_overloaded[i] = ls.is_node_overloaded(name)
        edge_live = np.zeros(e_cap, dtype=bool)
        edge_live[:e] = True

        from ..ops.banded import build_banded
        from ..ops.sssp import build_ell

        ell = build_ell(
            edge_src, edge_dst, edge_metric, edge_up, node_overloaded, e
        )
        banded = build_banded(edge_src, edge_dst, e, n)
        out_slot, max_out_slots = _build_out_slots(edge_src, edge_dst, e)

        return cls(
            node_names=names,
            node_id=node_id,
            n_nodes=n,
            node_capacity=n_cap,
            edge_capacity=e_cap,
            edge_src=edge_src,
            edge_dst=edge_dst,
            edge_metric=edge_metric,
            edge_up=edge_up,
            node_overloaded=node_overloaded,
            edge_links=[(r[4], r[5]) for r in rows],
            n_edges=e,
            edge_live=edge_live,
            n_live=e,
            version=ls.version,
            ell=ell,
            banded=banded,
            out_slot=out_slot,
            max_out_slots=max_out_slots,
        )

    # directed-edge slots one rewire may touch before the masked-write
    # delta rivals a restage and the full rebuild is the cheaper path
    REWIRE_MAX_SLOTS = 256
    # RewireDeltas retained for engine catch-up; a resident more than
    # this many rewires behind restages instead of replaying
    REWIRE_LOG_DEPTH = 32

    def refresh(self, ls: LinkState) -> bool:
        """Bring the mirror to `ls.version`, in place when possible.

        Returns True when the mirror stayed in place: either only
        link/node ATTRIBUTES changed (metric, up, overload) — the edge
        arrays are updated in place and neither the ELL tables nor
        compiled kernels are touched, because the relaxation reads
        edge_up / node_overloaded at call time — or the edge-set change
        was a BOUNDED rewire (links added/removed/swapped within
        edge_capacity): retired slots are recycled through the edge-slot
        freelist, out_slot is re-ranked and only the affected ELL
        destination rows are re-encoded (_try_rewire), all against the
        same array/ELL objects, so device residency survives too.

        Returns False when the mirror was REBUILT: node-set changes,
        capacity overflow, or an oversized rewire.  Capacities are
        re-used when the new topology still fits, so kernel shapes — and
        therefore XLA compilations — are stable until a capacity bucket
        overflows.  The rebuild path never errors on a rewire the
        freelist could not absorb; it is the graceful fallback."""
        if ls.version == self.version:
            return True
        names = ls.node_names
        same_topology = names == self.node_names and len(
            ls.all_links
        ) * 2 == self.n_live
        if same_topology:
            # identical link OBJECTS?  Identity, not set equality:
            # Link.__eq__ keys on (node, iface) pairs only, so a link that
            # was removed and re-added as a new object would compare equal
            # while our edge_links still points at the retired object
            # (whose metric/up state no longer updates).
            current = {
                id(lp[0]) for lp in self.edge_links if lp is not None
            }
            same_topology = current == {id(link) for link in ls.all_links}
        if not same_topology:
            if self._try_rewire(ls):
                return True
            hint = self._sweep_hint
            rebuilt = CsrTopology.from_link_state(
                ls,
                node_capacity=(
                    self.node_capacity
                    if len(names) < self.node_capacity
                    else None
                ),
                edge_capacity=(
                    self.edge_capacity
                    if len(ls.all_links) * 2 <= self.edge_capacity
                    else None
                ),
            )
            self.__dict__.update(rebuilt.__dict__)
            # the relax depth is a property of the topology shape; keep
            # the learned hint across rebuilds
            self._sweep_hint = hint
            return False

        self._refresh_attributes(ls)
        self.version = ls.version
        if self._runner is not None:
            # re-pin the refreshed values (a stale staged runner would
            # read pre-refresh state); one upload per topology change,
            # amortized over every later dispatch
            self._runner.stage()
        return True

    def _refresh_attributes(self, ls: LinkState) -> None:
        """Re-read metric/up/overload from the shared link objects into
        the arrays, in place (retired slots stay padding)."""
        for e, lp in enumerate(self.edge_links):
            if lp is None:
                continue
            link, from_name = lp
            self.edge_metric[e] = link.metric_from_node(from_name)
            self.edge_up[e] = link.is_up()
        for name, i in self.node_id.items():
            self.node_overloaded[i] = ls.is_node_overloaded(name)

    def _try_rewire(self, ls: LinkState) -> bool:
        """Bounded in-place edge-set change — the OCS slot freelist.

        Retires the removed links' edge slots (styled as padding inside
        [:n_edges]), re-points recycled/appended slots at the added
        links, re-reads attributes, re-ranks out_slot and re-encodes
        only the affected ELL destination rows — all against the SAME
        numpy/ELL objects, so compiled kernels and device residency
        (keyed on object identity) survive.  Appends a RewireDelta to
        the bounded rewire log for the engine's masked-write rung.

        Returns False — leaving the caller to take the full-rebuild
        path, which never errors — on a node-set change, freelist +
        tail-capacity exhaustion, an affected ELL row outgrowing its
        bucket's K headroom, or an oversized delta.  A False return may
        leave the arrays partially patched: the rebuild replaces every
        field from `ls`, so no torn state survives it."""
        if ls.node_names != self.node_names:
            return False
        cur_slots: dict[int, list[int]] = {}
        cur_links: dict[int, Link] = {}
        for e, lp in enumerate(self.edge_links):
            if lp is None:
                continue
            cur_slots.setdefault(id(lp[0]), []).append(e)
            cur_links[id(lp[0])] = lp[0]
        new_links = {id(link): link for link in ls.all_links}
        retiring = sorted(
            s
            for lid, slots in cur_slots.items()
            if lid not in new_links
            for s in slots
        )
        added = sorted(
            link for lid, link in new_links.items() if lid not in cur_slots
        )
        if not retiring and not added:
            return False  # count drift without identity drift: rebuild
        pool = sorted(set(self._free_slots) | set(retiring))
        tail = self.edge_capacity - self.n_edges
        if 2 * len(added) > len(pool) + tail:
            return False  # capacity overflow: rebuild (may grow buckets)
        if len(retiring) + 2 * len(added) > self.REWIRE_MAX_SLOTS:
            return False  # oversized delta: the restage is cheaper

        pad_node = self.node_capacity - 1
        touched: list[int] = []
        affected_dst: set[int] = set()
        for s in retiring:
            affected_dst.add(int(self.edge_dst[s]))
            self.edge_src[s] = pad_node
            self.edge_dst[s] = pad_node
            self.edge_metric[s] = 1
            self.edge_up[s] = False
            self.edge_live[s] = False
            self.edge_links[s] = None
            touched.append(s)
        for link in added:
            for u_name in (link.n1, link.n2):
                v_name = link.other_node_name(u_name)
                metric = link.metric_from_node(u_name)
                assert metric >= 1, (
                    "edge metrics must be >= 1 (distance-ordered DAG "
                    "propagation and int32 distance math rely on "
                    "positive metrics)"
                )
                if pool:
                    s = pool.pop(0)
                else:
                    s = self.n_edges
                    self.n_edges += 1
                    self.edge_links.append(None)
                self.edge_src[s] = self.node_id[u_name]
                self.edge_dst[s] = self.node_id[v_name]
                self.edge_metric[s] = metric
                self.edge_up[s] = link.is_up()
                self.edge_live[s] = True
                self.edge_links[s] = (link, u_name)
                affected_dst.add(int(self.edge_dst[s]))
                touched.append(s)
        self._free_slots = pool
        self.n_live = int(self.edge_live[: self.n_edges].sum())

        # attribute flaps batched into the same version ride along, so
        # the delta's per-slot values and the ELL snapshots below are
        # read from post-refresh state
        self._refresh_attributes(ls)

        # re-encode the affected ELL destination rows in place (same
        # bucket arrays — residency identity survives); the relabeling
        # (new_of_old) is frozen at build time, so a node's row never
        # moves — only its contents change
        new_of_old = np.asarray(self.ell.new_of_old)
        row_lo = []
        lo = 0
        for b in self.ell.buckets:
            row_lo.append(lo)
            lo += b.nbr.shape[0]
        dst_v = self.edge_dst[: self.n_edges]
        live_v = self.edge_live[: self.n_edges]
        rows_patch = []
        for d in sorted(affected_dst):
            eids = np.flatnonzero((dst_v == d) & live_v)
            r = int(new_of_old[d])
            b_idx = bisect.bisect_right(row_lo, r) - 1
            bkt = self.ell.buckets[b_idx]
            k_cap = bkt.nbr.shape[1]
            if len(eids) > k_cap:
                return False  # in-degree outgrew the row's K headroom
            row_nbr = np.zeros(k_cap, dtype=np.int32)
            row_w = np.ones(k_cap, dtype=np.int32)
            row_eid = np.full(k_cap, -1, dtype=np.int32)
            row_ok = np.zeros(k_cap, dtype=bool)
            row_tok = np.zeros(k_cap, dtype=bool)
            k = len(eids)
            if k:
                row_nbr[:k] = new_of_old[self.edge_src[eids]]
                row_w[:k] = self.edge_metric[eids]
                row_eid[:k] = eids.astype(np.int32)
                row_ok[:k] = self.edge_up[eids]
                row_tok[:k] = ~self.node_overloaded[self.edge_src[eids]]
            rows_patch.append(
                (b_idx, r - row_lo[b_idx], row_nbr, row_w, row_eid,
                 row_ok, row_tok)
            )
        # feasibility proven — apply the row patches in place
        for b_idx, lr, rn, rw, re_, ro, rt in rows_patch:
            bkt = self.ell.buckets[b_idx]
            bkt.nbr[lr] = rn
            bkt.w[lr] = rw
            bkt.edge_id[lr] = re_
            bkt.ok[lr] = ro
            bkt.transit_ok[lr] = rt

        new_out, new_max = _build_out_slots(
            self.edge_src, self.edge_dst, self.n_edges, live=self.edge_live
        )
        out_changed = np.flatnonzero(new_out != self.out_slot).astype(
            np.int32
        )
        self.out_slot[:] = new_out
        self.max_out_slots = new_max

        # band structure is host-only (SpfRunner): rebuild it from the
        # live edges and let the runner re-materialize lazily
        from ..ops.banded import build_banded

        self.banded = build_banded(
            self.edge_src, self.edge_dst, self.n_edges, self.n_nodes
        )
        self._runner = None

        # a slot retired and recycled in the same rewire is touched
        # twice; the delta reads final array state, so dedupe (the
        # masked-write kernels require unique indices)
        slots_v = np.asarray(sorted(set(touched)), dtype=np.int32)
        self.rewire_seq += 1
        self._rewire_log.append(
            RewireDelta(
                seq=self.rewire_seq,
                version=ls.version,
                slots=slots_v,
                src=self.edge_src[slots_v].copy(),
                dst=self.edge_dst[slots_v].copy(),
                metric=self.edge_metric[slots_v].copy(),
                up=self.edge_up[slots_v].copy(),
                live=self.edge_live[slots_v].copy(),
                out_idx=out_changed,
                out_val=new_out[out_changed].copy(),
                ell_rows=rows_patch,
                n_edges=self.n_edges,
                max_out_slots=new_max,
                links_added=len(added),
                links_removed=len(retiring) // 2,
            )
        )
        del self._rewire_log[: -self.REWIRE_LOG_DEPTH]
        self.version = ls.version
        return True

    # -- SPF execution ------------------------------------------------------

    def run_batched_spf(
        self,
        sources: list[str],
        use_link_metric: bool = True,
        extra_edge_mask: Optional[np.ndarray] = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Run the device kernel (band-aware fixed-sweep relaxation);
        returns (dist [S, N*], dag [S, E_cap]) as numpy.  N* is n_nodes
        on the banded path and node_capacity on the ELL path — consumers
        index [: n_nodes] either way."""
        src_ids = np.asarray(
            [self.node_id[s] for s in sources], dtype=np.int32
        )
        return self.runner.forward(
            src_ids,
            use_link_metric=use_link_metric,
            extra_edge_mask=(
                None if extra_edge_mask is None else np.asarray(extra_edge_mask)
            ),
        )

    # -- result reconstruction (parity with the host oracle) ----------------

    def slot_neighbors(self, node: str) -> list[str]:
        """Sorted unique out-neighbor names of `node` — slot order of the
        bit-packed device first-hop masks (ids are assigned in sorted-name
        order, so id rank == name rank)."""
        return self._slot_neighbors(self._links_of, node)

    @staticmethod
    def _slot_neighbors(
        links_of: dict[str, list[Link]], node: str
    ) -> list[str]:
        return sorted(
            {link.other_node_name(node) for link in links_of.get(node, ())}
        )

    def to_spf_results(
        self,
        sources: list[str],
        dist: np.ndarray,
        dag: np.ndarray,
        nh_words: Optional[np.ndarray] = None,  # [S, N_cap, W] uint32
    ) -> dict[str, SpfResult]:
        """Convert kernel output into reference-shaped SpfResults: per node
        metric, tie-retaining path_links, and first-hop `next_hops` sets.

        With `nh_words` (ops.sssp.first_hops_ell output) the next-hop sets
        are decoded from the device bitmasks — O(reachable x set bits)
        host work.  Without it, falls back to host DAG propagation
        (O(S x N) — the round-1 bottleneck; kept for dist/dag-only
        callers)."""
        from ..ops.sssp import INF32

        inf = int(INF32)
        out: dict[str, SpfResult] = {}
        links_of = self._links_of  # hoisted: the property walks edge_links
        for row, src_name in enumerate(sources):
            d = dist[row]
            mask = dag[row]
            result: SpfResult = {}
            reachable = [
                i for i in range(self.n_nodes) if d[i] < inf
            ]
            for i in reachable:
                result[self.node_names[i]] = NodeSpfResult(int(d[i]))
            # path links from DAG edges, in host-Dijkstra append order
            for e in np.nonzero(mask[: self.n_edges])[0]:
                link, from_name = self.edge_links[e]
                v = self.node_names[int(self.edge_dst[e])]
                result[v].path_links.append((link, from_name))
            self._host_order_path_links(result)
            src_id = self.node_id[src_name]
            if nh_words is not None:
                slot_names = self._slot_neighbors(links_of, src_name)
                words = nh_words[row]
                for i in reachable:
                    if i == src_id:
                        continue
                    hops = result[self.node_names[i]].next_hops
                    for w in range(words.shape[1]):
                        bits = int(words[i, w])
                        base = 32 * w
                        while bits:
                            b = bits & -bits
                            hops.add(slot_names[base + b.bit_length() - 1])
                            bits ^= b
            else:
                # First hops by host propagation along the DAG in
                # increasing-distance order (metrics >= 1 makes this a
                # topological order).  A direct shortest edge src->v
                # contributes v itself (reference: addNextHop fires while
                # v's set is empty at src's pop and survives unless a
                # strictly shorter path resets it — i.e. iff src->v is a
                # DAG edge).
                order = sorted(
                    reachable, key=lambda i: (int(d[i]), self.node_names[i])
                )
                for i in order:
                    if i == src_id:
                        continue
                    name = self.node_names[i]
                    res = result[name]
                    for link, prev in res.path_links:
                        if prev == src_name:
                            res.next_hops.add(name)
                        else:
                            res.next_hops |= result[prev].next_hops
            out[src_name] = result
        return out

    @staticmethod
    def _host_order_path_links(result: SpfResult) -> None:
        """Order each node's path_links exactly as the host Dijkstra
        appends them — by (dist(prev), prev_name, link): run_spf pops the
        heap by (metric, node name) and iterates each node's links sorted
        (link_state.py run_spf).  trace_one_path's greedy link consumption
        is order-sensitive, so KSP parity with the host needs this."""
        for res in result.values():
            res.path_links.sort(
                key=lambda lp: (result[lp[1]].metric, lp[1], lp[0])
            )

    def row_path_links(self, dist_row: np.ndarray, dag_row: np.ndarray) -> SpfResult:
        """One kernel row -> SpfResult with metric + path_links only (no
        first-hop sets) — the shape `trace_one_path` walks for KSP path
        extraction."""
        from ..ops.sssp import INF32

        inf = int(INF32)
        result: SpfResult = {}
        for i in range(self.n_nodes):
            if dist_row[i] < inf:
                result[self.node_names[i]] = NodeSpfResult(int(dist_row[i]))
        for e in np.nonzero(dag_row[: self.n_edges])[0]:
            link, from_name = self.edge_links[e]
            v = self.node_names[int(self.edge_dst[e])]
            result[v].path_links.append((link, from_name))
        self._host_order_path_links(result)
        return result

    def edges_of_links(self) -> dict:
        """Link -> [directed edge ids] (both directions; parallel links map
        to their own instances)."""
        out: dict = {}
        for e in range(self.n_edges):
            lp = self.edge_links[e]
            if lp is None:  # retired slot (edge freelist)
                continue
            out.setdefault(lp[0], []).append(e)
        return out

    def spf_from(
        self, sources: list[str], use_link_metric: bool = True
    ) -> dict[str, SpfResult]:
        """Full production pipeline: one device call (distances + SP-DAG +
        bit-packed first hops) -> reference-shaped SpfResults."""
        from ..ops import sssp as ops

        src_ids = np.asarray(
            [self.node_id[s] for s in sources], dtype=np.int32
        )
        n_words = max(1, -(-self._max_slots_of(sources) // 32))
        s = len(sources)
        args = (
            src_ids,
            self.ell,
            self.edge_src,
            self.edge_dst,
            self.edge_metric,
            self.edge_up,
            self.node_overloaded,
            self.out_slot,
            n_words,
        )
        # Fixed-sweep execution with an adaptive per-topology hint: a
        # data-dependent while_loop syncs host<->device per iteration on
        # latency-bound transports, so we run `sweep_hint` sweeps (fori) +
        # an in-program convergence verdict and double until it reads 1.
        # The hint tracks the topology's relax depth (weighted-path hop
        # count), which is stable across flaps.
        small = s * self.node_capacity <= (1 << 21)
        while True:
            n_sweeps = self._sweep_hint
            if small:
                # small control-plane query: ONE packed transfer.  This is
                # the host fallback of the degradation ladder — the exact
                # computation the engine's bucketed programs mirror — so
                # there is no engine front-end to route through here.
                packed = np.asarray(
                    # openr: disable=jit-unbucketed-dispatch
                    ops.spf_forward_full_packed(
                        *args,
                        use_link_metric=use_link_metric,
                        n_sweeps=n_sweeps,
                    )
                )
                converged = packed[-1] == 1
            else:
                # bulk batch: int32-widening the dag for packing would
                # dominate memory; take separate fetches instead.  Same
                # ladder-fallback rationale as the packed branch above.
                # openr: disable=jit-unbucketed-dispatch
                dist_j, dag_j, nh_j, ok_j = ops.spf_forward_full(
                    *args,
                    use_link_metric=use_link_metric,
                    n_sweeps=n_sweeps,
                )
                converged = bool(ok_j)
            if converged:
                break
            self._sweep_hint = n_sweeps * 2
        if small:
            n_dist = s * self.node_capacity
            n_dag = s * self.edge_capacity
            dist = packed[:n_dist].reshape(s, self.node_capacity)
            dag = packed[n_dist : n_dist + n_dag].reshape(
                s, self.edge_capacity
            ) != 0
            nh = (
                packed[n_dist + n_dag : -1]
                .view(np.uint32)
                .reshape(s, self.node_capacity, n_words)
            )
        else:
            dist = np.asarray(dist_j)
            dag = np.asarray(dag_j)
            nh = np.asarray(nh_j)
        return self.to_spf_results(sources, dist, dag, nh)

    def _max_slots_of(self, sources: list[str]) -> int:
        """Max distinct out-neighbors over the batch's sources — sizes the
        first-hop bitmask words for this call."""
        links_of = self._links_of
        best = 1
        for s in sources:
            n = len({l.other_node_name(s) for l in links_of.get(s, ())})
            if n > best:
                best = n
        return best

    @property
    def _links_of(self) -> dict[str, list[Link]]:
        links: dict[str, list[Link]] = {}
        for lp in self.edge_links:
            if lp is None:
                continue
            links.setdefault(lp[1], []).append(lp[0])
        return links

    @property
    def max_degree(self) -> int:
        deg: dict[str, set[str]] = {}
        for lp in self.edge_links:
            if lp is None:
                continue
            link, from_name = lp
            deg.setdefault(from_name, set()).add(link.other_node_name(from_name))
        return max((len(v) for v in deg.values()), default=0)
