"""PrefixState: prefix -> {(node, area) -> PrefixEntry} with change deltas.

Functional equivalent of the reference's PrefixState
(openr/decision/PrefixState.{h,cpp}:22-71).
"""

from __future__ import annotations

from typing import Optional

from ..types import (
    PrefixEntry,
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
    normalize_prefix,
)

NodeAndArea = tuple[str, str]
PrefixEntries = dict[NodeAndArea, PrefixEntry]


class PrefixState:
    def __init__(self) -> None:
        self._prefixes: dict[str, PrefixEntries] = {}

    @property
    def prefixes(self) -> dict[str, PrefixEntries]:
        return self._prefixes

    def update_prefix(
        self, node: str, area: str, entry: PrefixEntry
    ) -> set[str]:
        """Returns the set of changed prefixes (reference:
        PrefixState::updatePrefix, PrefixState.cpp:16-38)."""
        prefix = normalize_prefix(entry.prefix)
        entries = self._prefixes.setdefault(prefix, {})
        key = (node, area)
        if key in entries and entries[key] == entry:
            return set()
        entries[key] = entry
        return {prefix}

    def delete_prefix(self, node: str, area: str, prefix: str) -> set[str]:
        """Returns the changed prefix set; empty if (node, area) wasn't
        advertising (reference: PrefixState::deletePrefix)."""
        prefix = normalize_prefix(prefix)
        entries = self._prefixes.get(prefix)
        if entries is None or entries.pop((node, area), None) is None:
            return set()
        if not entries:
            del self._prefixes[prefix]
        return {prefix}

    def delete_all_from_node(self, node: str, area: str) -> set[str]:
        """Withdraw everything a (node, area) advertised — used when a
        prefix DB key expires from the KvStore."""
        changed: set[str] = set()
        for prefix in list(self._prefixes):
            changed |= self.delete_prefix(node, area, prefix)
        return changed

    def get_received_routes_filtered(
        self,
        prefixes: Optional[list[str]] = None,
        node_name: Optional[str] = None,
        area_name: Optional[str] = None,
    ) -> list[tuple[str, list[tuple[NodeAndArea, PrefixEntry]]]]:
        """Reference: getReceivedRoutesFiltered (PrefixState.cpp:59-88)."""
        out: list[tuple[str, list[tuple[NodeAndArea, PrefixEntry]]]] = []
        targets = (
            [normalize_prefix(p) for p in prefixes]
            if prefixes is not None
            else sorted(self._prefixes)
        )
        for prefix in targets:
            entries = self._prefixes.get(prefix)
            if not entries:
                continue
            rows = [
                (na, e)
                for na, e in sorted(entries.items())
                if (node_name is None or na[0] == node_name)
                and (area_name is None or na[1] == area_name)
            ]
            if rows:
                out.append((prefix, rows))
        return out

    @staticmethod
    def has_conflicting_forwarding_info(entries: PrefixEntries) -> bool:
        """True if entries disagree on forwarding type/algorithm
        (reference: hasConflictingForwardingInfo)."""
        infos = {
            (e.forwarding_type, e.forwarding_algorithm) for e in entries.values()
        }
        return len(infos) > 1
