"""RIB entries, route DB, and route-update deltas.

Functional equivalents of the reference's RibEntry.h, RouteUpdate.h and
DecisionRouteDb (openr/decision/RibEntry.h, openr/decision/RouteUpdate.h,
openr/decision/Decision.cpp:109-160 calculateUpdate/update).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..serializer import register_type
from ..types import (
    MplsRoute,
    NextHop,
    PerfEvents,
    PrefixEntry,
    PrefixType,
    UnicastRoute,
)


@register_type
@dataclass(slots=True)
class RibUnicastEntry:
    """Reference: RibUnicastEntry (openr/decision/RibEntry.h:38-100)."""

    prefix: str  # canonical CIDR
    nexthops: frozenset[NextHop] = frozenset()
    best_prefix_entry: Optional[PrefixEntry] = None
    best_area: str = ""
    do_not_install: bool = False

    def __eq__(self, other) -> bool:
        # bestArea intentionally excluded, matching the reference's
        # operator== (RibEntry.h:66-70)
        return (
            isinstance(other, RibUnicastEntry)
            and self.prefix == other.prefix
            and self.best_prefix_entry == other.best_prefix_entry
            and self.do_not_install == other.do_not_install
            and self.nexthops == other.nexthops
        )

    def to_unicast_route(self) -> UnicastRoute:
        return UnicastRoute(
            dest=self.prefix,
            next_hops=sorted(self.nexthops, key=_nh_sort_key),
        )

    @property
    def is_bgp(self) -> bool:
        return (
            self.best_prefix_entry is not None
            and self.best_prefix_entry.type == PrefixType.BGP
        )


@register_type
@dataclass(slots=True)
class RibMplsEntry:
    """Reference: RibMplsEntry (openr/decision/RibEntry.h:102-145)."""

    label: int
    nexthops: frozenset[NextHop] = frozenset()

    def to_mpls_route(self) -> MplsRoute:
        return MplsRoute(
            top_label=self.label,
            next_hops=sorted(self.nexthops, key=_nh_sort_key),
        )


def _nh_sort_key(nh: NextHop):
    return (
        nh.address,
        nh.if_name or "",
        nh.metric,
        nh.neighbor_node_name or "",
        nh.area or "",
    )


@register_type
@dataclass(slots=True)
class DecisionRouteUpdate:
    """Delta published by Decision, consumed by Fib / PrefixManager / plugin
    (reference: openr/decision/RouteUpdate.h:23)."""

    unicast_routes_to_update: dict[str, RibUnicastEntry] = field(
        default_factory=dict
    )
    unicast_routes_to_delete: list[str] = field(default_factory=list)
    mpls_routes_to_update: list[RibMplsEntry] = field(default_factory=list)
    mpls_routes_to_delete: list[int] = field(default_factory=list)
    perf_events: Optional[PerfEvents] = None

    def add_route_to_update(self, route: RibUnicastEntry) -> None:
        assert route.prefix not in self.unicast_routes_to_update
        self.unicast_routes_to_update[route.prefix] = route

    def empty(self) -> bool:
        return not (
            self.unicast_routes_to_update
            or self.unicast_routes_to_delete
            or self.mpls_routes_to_update
            or self.mpls_routes_to_delete
        )


@register_type
@dataclass(slots=True)
class DecisionRouteDb:
    """Computed route state (reference: DecisionRouteDb,
    openr/decision/Decision.h:56-88)."""

    unicast_routes: dict[str, RibUnicastEntry] = field(default_factory=dict)
    mpls_routes: dict[int, RibMplsEntry] = field(default_factory=dict)

    def add_unicast_route(self, route: RibUnicastEntry) -> None:
        assert route.prefix not in self.unicast_routes, route.prefix
        self.unicast_routes[route.prefix] = route

    def add_mpls_route(self, route: RibMplsEntry) -> None:
        assert route.label not in self.mpls_routes, route.label
        self.mpls_routes[route.label] = route

    def calculate_update(self, new_db: "DecisionRouteDb") -> DecisionRouteUpdate:
        """Reference: DecisionRouteDb::calculateUpdate
        (openr/decision/Decision.cpp:111-147)."""
        delta = DecisionRouteUpdate()
        for prefix, entry in new_db.unicast_routes.items():
            old = self.unicast_routes.get(prefix)
            if old is None or old != entry:
                delta.add_route_to_update(entry)
        for prefix in self.unicast_routes:
            if prefix not in new_db.unicast_routes:
                delta.unicast_routes_to_delete.append(prefix)
        for label, entry in new_db.mpls_routes.items():
            old = self.mpls_routes.get(label)
            if old is None or old != entry:
                delta.mpls_routes_to_update.append(entry)
        for label in self.mpls_routes:
            if label not in new_db.mpls_routes:
                delta.mpls_routes_to_delete.append(label)
        return delta

    def update(self, delta: DecisionRouteUpdate) -> None:
        """Apply a delta (reference: DecisionRouteDb::update,
        Decision.cpp:149-163)."""
        for prefix in delta.unicast_routes_to_delete:
            self.unicast_routes.pop(prefix, None)
        for prefix, entry in delta.unicast_routes_to_update.items():
            self.unicast_routes[prefix] = entry
        for label in delta.mpls_routes_to_delete:
            self.mpls_routes.pop(label, None)
        for entry in delta.mpls_routes_to_update:
            self.mpls_routes[entry.label] = entry
