"""Fleet route view: the daemon consumer of the reduced all-sources product.

One reverse-SSSP device round (openr_tpu.ops.allsources) answers every
router's route build toward the destination set that route construction
actually reads — the prefix-advertising nodes plus every labeled node.
This is the in-daemon consumer of the round-4 flagship product; the
reference's equivalent consumer is the per-prefix route build
(openr/decision/Decision.cpp:615-793, createRouteForPrefix reads best-entry
node distances) and the any-node ctrl query
(openr/decision/Decision.cpp:1510-1530, getDecisionRouteDb).

Why the product suffices: the reverse distances dist[v, p] == dist(v -> p)
cover EVERY router v, so for any router `me` the route build has
- reachability:  dist(me -> advertiser) < INF
- best-metric:   min over advertisers of dist(me -> advertiser)
- LFA-free ECMP: link (me -l-> u) is a next hop toward p iff
                 metric(l) + dist(u -> p) == dist(me -> p)
                 (openr/decision/Decision.cpp:1296-1300), with the drain
                 exception (overloaded u only as the destination itself,
                 dist(u -> p) == 0) — all reads of the same [N, P] matrix.
The fused [N, P, W] bitmap is the device-side fleet-wide evaluation of the
same condition (ops.allsources.ecmp_bitmap_from_reverse_dist); the host
hooks in SpfSolver evaluate it per link so parallel links keep their
per-link metric semantics, and tests cross-check the two.

A view is a SNAPSHOT of one LinkState version: the runtime arrays are
copied at build time (the CSR mirror refreshes its arrays in place), and
the cache invalidates on version or destination-set change.
"""

from __future__ import annotations

import functools
import logging
import weakref
from typing import Optional

import numpy as np

from .link_state import LinkState

# mirrors ops.sssp.INF32 / ops.banded.INF16 (plain ints here so importing
# the decision layer does not pull jax; tests/test_fleet.py asserts both
# stay equal to the ops constants)
INF32 = 1 << 30
INF16 = 40000


def _row_i32(row: np.ndarray) -> np.ndarray:
    """Normalize a fetched distance row to the int32/INF32 contract —
    the device product runs raw uint16 (INF16 sentinel) when the banded
    kernel's small-distance mode engages (ops.banded raw_u16)."""
    if row.dtype == np.uint16:
        return np.where(row >= INF16, INF32, row.astype(np.int32))
    return row

log = logging.getLogger(__name__)


def _usable_edge_table(csr):
    """Canonical (directed-pair key, min metric) table of USABLE edges —
    the improvement-only gate's comparison unit.  Distances depend only
    on the min metric per usable directed (src, dst) pair (parallel
    links matter for next-hop slots, not distances)."""
    e = csr.n_edges
    up = np.asarray(csr.edge_up[:e], dtype=bool)
    src = np.asarray(csr.edge_src[:e], dtype=np.int64)[up]
    dst = np.asarray(csr.edge_dst[:e], dtype=np.int64)[up]
    met = np.asarray(csr.edge_metric[:e], dtype=np.int64)[up]
    key = (src << 32) | dst
    order = np.argsort(key, kind="stable")
    key, met = key[order], met[order]
    first = np.r_[True, key[1:] != key[:-1]]
    uniq = key[first]
    min_met = np.minimum.reduceat(met, np.flatnonzero(first))
    return uniq, min_met


def _improvement_only(
    old_keys, old_met, old_ov, new_keys, new_met, new_ov
) -> bool:
    """True iff the new graph can only have SHORTER-OR-EQUAL distances
    than the old one: every old usable directed pair is still usable
    with metric <= old, and no node gained the overload bit.  This is
    the warm-start proof obligation of ops.banded.spf_forward_banded —
    under it the previous product is an elementwise upper bound."""
    if np.any(new_ov & ~old_ov):
        return False
    pos = np.searchsorted(new_keys, old_keys)
    if np.any(pos >= len(new_keys)) or np.any(
        new_keys[np.minimum(pos, max(len(new_keys) - 1, 0))] != old_keys
    ):
        return False
    return bool(np.all(new_met[pos] <= old_met))


def _in_sorted(keys: np.ndarray, q: np.ndarray) -> np.ndarray:
    """Vectorized membership of q in the sorted key array."""
    if len(keys) == 0:
        return np.zeros(q.shape, dtype=bool)
    pos = np.searchsorted(keys, q)
    pos_c = np.minimum(pos, len(keys) - 1)
    return (pos < len(keys)) & (keys[pos_c] == q)


def _worsened_masks(prev: "FleetRouteView", new_keys, new_met, new_ov):
    """Per-reverse-slot masks of WORSENED forward edges, in the layout of
    the previous view's reverse runner (bg.resid slots + band
    positions) — the seed of the affected-set propagation
    (ops.banded.affected_mask).

    Worsened means the edge can only LENGTHEN paths that used it:
    - old usable directed pair now unusable (link down / all parallel
      links down),
    - old pair still usable but its min metric increased,
    - transit through a newly-overloaded node (drain): every reverse
      edge SOURCED at that node — conservatively including the
      destination-row exception, which only over-marks.
    Edges that improved or appeared are NOT worsened: the old product
    stays an upper bound wherever no worsened edge is on every old
    shortest path, even when improvements happen in the same delta
    (improvements only loosen the bound, and the relax fixes looseness;
    the verification certifies exactness either way)."""
    old_keys, old_met = prev._edge_keys, prev._edge_met
    present = _in_sorted(new_keys, old_keys)
    pos = np.minimum(
        np.searchsorted(new_keys, old_keys), max(len(new_keys) - 1, 0)
    )
    worse = ~present
    if len(new_keys):
        worse |= present & (new_met[pos] > old_met)
    bad_keys = old_keys[worse]  # sorted (subset of sorted old_keys)
    newly_ov = new_ov & ~prev._overloaded
    bg = prev._runner.bg
    n = bg.n_nodes
    rn = np.asarray(bg.resid_nbr)
    re_ = np.asarray(bg.resid_eid)
    # reverse edge u -> v is forward edge v -> u: forward key (v, u)
    v_ids = np.arange(n, dtype=np.int64)
    qk = (v_ids[:, None] << 32) | rn.astype(np.int64)
    worsened_resid = (re_ >= 0) & (
        _in_sorted(bad_keys, qk) | newly_ov[rn]
    )
    be = np.asarray(bg.band_eid)
    rows = []
    for b, c in enumerate(bg.offsets):
        u = (v_ids - c) % n
        qk = (v_ids << 32) | u
        rows.append(
            (be[b] >= 0) & (_in_sorted(bad_keys, qk) | newly_ov[u])
        )
    return worsened_resid, np.stack(rows)


def _affected_init(prev: "FleetRouteView", new: "FleetRouteView"):
    """Device init for a worsening-direction warm start: the previous
    distances with every possibly-affected entry re-set to INF, or None
    when the affected-set propagation could not certify its fixpoint
    (the caller must cold-start).

    Safety argument (the worsening mirror of _improvement_only): an
    entry is re-relaxed from INF whenever ANY old tight chain into it
    crosses a worsened edge (affected_mask, certified fixpoint), so
    every kept entry has an old shortest path that survives un-worsened
    — its old value is still an elementwise UPPER bound in the new
    graph — and the warm relax plus verification then reproduce the
    cold fixed point bit-for-bit (ops.banded.spf_forward_banded)."""
    import jax.numpy as jnp

    from ..ops.banded import affected_mask

    runner = prev._runner
    if runner is None or runner.bg is None or prev._dist_dev is None:
        return None
    worsened_resid, worsened_band = _worsened_masks(
        prev, new._edge_keys, new._edge_met, new._overloaded
    )
    small = prev._dist_dev.dtype == np.uint16
    _, _, r_met, r_up, r_ov = runner.call_arrays()
    aff, done = affected_mask(
        prev._dist_dev,
        runner.bg,
        r_up,
        r_met,
        r_ov,
        jnp.asarray(worsened_resid),
        jnp.asarray(worsened_band),
        small_dist=bool(small),
        max_iters=128,
    )
    # explicit single-scalar fetch: the certification verdict decides
    # warm-start vs cold rebuild on the host
    import jax

    if not jax.device_get(done):
        return None
    inf = jnp.uint16(INF16) if small else jnp.int32(INF32)
    return jnp.where(aff, inf, prev._dist_dev[: runner.bg.n_nodes])


def _reverse_runner(csr, hint: Optional[int] = None):
    """SpfRunner over the REVERSED directed edges of a CsrTopology
    snapshot (same construction as benchmarks.synthetic.reversed_topology,
    but from the daemon's mirror).  `hint` seeds the learned fixed-sweep
    count — the relax depth is a property of the topology shape, so
    re-learning it by doubling on every rebuild would pay failed
    full-P-source dispatches per link flap (DeviceSpfBackend._hint_by_shape
    discipline)."""
    from ..ops.banded import SpfRunner, build_banded
    from ..ops.sssp import build_ell

    # retired freelist slots (csr rewires) are padding inside
    # [:n_edges]; the reversed snapshot renumbers edges into its own
    # dense space anyway, so compact them away here
    live = getattr(csr, "edge_live", None)
    if live is None:
        ids = np.arange(csr.n_edges)
    else:
        ids = np.flatnonzero(live[: csr.n_edges])
    e = len(ids)
    src = csr.edge_dst[ids].copy()
    dst = csr.edge_src[ids].copy()
    met = csr.edge_metric[ids].copy()
    up = csr.edge_up[ids].copy()
    order = np.lexsort((src, dst))
    pad_node = csr.node_capacity - 1
    edge_src = np.full(csr.edge_capacity, pad_node, dtype=np.int32)
    edge_dst = np.full(csr.edge_capacity, pad_node, dtype=np.int32)
    edge_metric = np.ones(csr.edge_capacity, dtype=np.int32)
    edge_up = np.zeros(csr.edge_capacity, dtype=bool)
    edge_src[:e] = src[order]
    edge_dst[:e] = dst[order]
    edge_metric[:e] = met[order]
    edge_up[:e] = up[order]
    node_overloaded = csr.node_overloaded.copy()
    ell = build_ell(
        edge_src, edge_dst, edge_metric, edge_up, node_overloaded, e
    )
    banded = build_banded(edge_src, edge_dst, e, csr.n_nodes)
    runner = SpfRunner(
        ell,
        banded,
        edge_src,
        edge_dst,
        edge_metric,
        edge_up,
        node_overloaded,
        e,
    )
    if hint is not None:
        runner.hint = hint
    # snapshot arrays are immutable for the view's lifetime: pin them
    # device-resident so repeat computes/queries skip the re-upload
    runner.stage()
    return runner


class FleetRouteView:
    """Snapshot answering dist/ECMP queries for every (router, dest) pair.

    `dest_names` must cover every node route construction asks distances
    to: prefix advertisers + labeled nodes (fleet_destinations)."""

    def __init__(self, csr, dest_names: list[str], engine=None) -> None:
        self.csr = csr
        self.version = csr.version
        # device-residency engine (openr_tpu.device): when present, the
        # fleet product dispatches through its front-end (chaos fault
        # hook + device.engine.* dispatch accounting)
        self._engine = engine
        self.dest_names = list(dest_names)
        self.p_index = {name: i for i, name in enumerate(self.dest_names)}
        self._node_id = dict(csr.node_id)
        # runtime-state snapshot for the host-side per-link checks
        self._overloaded = csr.node_overloaded.copy()
        # canonical usable-edge table for the warm-start improvement gate
        # (the next view compares against it; ~10ms host work at 800k)
        self._edge_keys, self._edge_met = _usable_edge_table(csr)
        self._dist_dev = None  # jax [N*, P] — row per router (native
        #   kernel layout; a router's fetch is one contiguous row)
        self._bitmap_dev = None  # jax [N, P, W]
        self._out = None  # ops.allsources.OutEll
        self._rows: dict[int, np.ndarray] = {}  # node id -> [P] int32
        self.converged = False
        self.cold_fallback = False  # warm gate failed; cache retried cold
        self.warm = False  # computed from a previous view's distances
        # None | "improve" | "worsen" — which warm gate admitted the seed
        self.warm_mode: Optional[str] = None
        self.sweep_hint: Optional[int] = None
        # True when the blocked node-sharded rung served this view: its
        # [N, P] int32 product is NOT a valid warm/delta seed for the
        # banded relax (dtype/shape contract differs), so the cache
        # skips seeding from it
        self.node_sharded = False
        self._runner = None  # retained for the NEXT view's worsening
        #   warm start: affected-set propagation runs over THIS view's
        #   reverse graph and distances (_affected_init)

    # -- device round --------------------------------------------------------

    def compute(
        self,
        hint_seed: Optional[int] = None,
        init_from: Optional["FleetRouteView"] = None,
        warm_seed: Optional[int] = None,
        down_from: Optional["FleetRouteView"] = None,
    ) -> None:
        """One device ROUND — the P-source reverse relax with the ECMP
        bitmap folded into its final verification supersweep
        (reduced_all_sources' fused progressive fast path; the product
        is read once and convergence is certified on-device).
        `hint_seed` carries the previous view's learned COLD sweep
        count across topology versions (same-shape seeding, legacy
        fixed-sweep paths only).

        `init_from` warm-starts the relax from a previous view's device
        distances.  The CALLER (FleetViewCache.view) must have proven
        the improvement-only gate (_improvement_only) plus node/dest
        universe equality — an un-gated init can silently fix-point
        below the true distances (ops.banded.spf_forward_banded).
        `down_from` is the WORSENING-direction counterpart: the same
        universe equality, but the change removed/worsened edges — the
        seed is the previous distances with the certified affected set
        re-set to INF (_affected_init); when the certification fails
        the run silently cold-starts.  `warm_seed` is the sweep seed
        used ONLY when a warm path actually engages; whether it does
        depends on the runner's bandedness, which is known only after
        the runner is built here (the ELL fallback ignores dist0 and
        must keep the cold seed).  Callers read `self.warm` /
        `self.warm_mode` afterwards to route hint harvesting and
        counters."""
        from ..ops import allsources as asrc

        dest_ids = np.asarray(
            [self._node_id[d] for d in self.dest_names], dtype=np.int32
        )
        self._out = asrc.build_out_ell(
            self.csr.edge_src,
            self.csr.edge_dst,
            self.csr.n_edges,
            self.csr.n_nodes,
            out_slot=self.csr.out_slot,
        )
        # third rung: node-axis sharded blocked APSP (parallel.blocked)
        # when N outgrows the single-chip [N, P] ceiling (or the env
        # forces it).  Any failure — mesh-shape mismatch, tile/device
        # mismatch, an injected chaos fault mid-run — falls through to
        # the dest-sharded fused product below, which is the bit-exact
        # fallback.
        blocked = (
            getattr(self._engine, "blocked", None)
            if self._engine is not None
            else None
        )
        if blocked is not None and blocked.should_engage(self.csr.n_nodes):
            try:
                dist, bitmap, ok = blocked.fleet_product(
                    self.csr, dest_ids, self._out
                )
            except Exception:
                blocked._bump("mesh.blocked.fallbacks")
                log.warning(
                    "fleet: blocked-APSP rung failed; falling back to "
                    "the dest-sharded fused product",
                    exc_info=True,
                )
            else:
                # `ok` is host-side by the rung's contract (the closure
                # is exact after T rounds; no convergence certificate
                # to fetch)
                assert ok
                self._dist_dev = dist
                self._bitmap_dev = bitmap
                self.converged = True
                self.warm = False
                self.warm_mode = None
                self.sweep_hint = None
                self._runner = None
                self.node_sharded = True
                return
        runner = _reverse_runner(self.csr, hint=hint_seed)
        init = None
        self.warm_mode = None
        if runner.bg is not None:
            # the ELL fallback ignores dist0 (cold run): claiming warm
            # would mislabel the view AND poison _warm_hints with a cold
            # sweep count
            if init_from is not None:
                init = init_from._dist_dev
                self.warm_mode = "improve"
            elif down_from is not None:
                init = _affected_init(down_from, self)
                if init is not None:
                    self.warm_mode = "worsen"
        if init is not None and warm_seed is not None:
            runner.hint = warm_seed
        maps = (
            asrc.build_epilogue_maps(runner.bg, self._out)
            if runner.bg is not None
            else None
        )
        # engine front-end (openr_tpu.device): fault-hook + dispatch
        # accounting around the fused product; the direct call remains
        # the engine-less fallback path
        product = (
            functools.partial(
                self._engine.dispatch,
                "fleet_product",
                asrc.reduced_all_sources,
            )
            if self._engine is not None
            else asrc.reduced_all_sources
        )
        # Pallas rung: engine-routed products run the fused epilogue
        # through the engine's demotion contract (counters + chaos
        # seam); engine-less calls keep the env-policy default
        pallas_run = (
            self._engine.run_pallas if self._engine is not None else None
        )
        dist, bitmap, ok = product(
            dest_ids,
            runner,
            self._out,
            self.csr.edge_metric,
            self.csr.edge_up,
            self.csr.node_overloaded,
            init_dist=init,
            maps=maps,
            pallas_run=pallas_run,
        )
        # `ok` is a host bool by reduced_all_sources' contract (fetched
        # inside, fused with the block-counter read)
        if not ok and init is not None:
            # the warm relax exhausted its block budget without the
            # on-device certificate: the seed bought nothing — pay the
            # cold run rather than serve an uncertified product
            init = None
            self.warm_mode = None
            if hint_seed is not None:
                runner.hint = hint_seed
            dist, bitmap, ok = product(
                dest_ids,
                runner,
                self._out,
                self.csr.edge_metric,
                self.csr.edge_up,
                self.csr.node_overloaded,
                maps=maps,
                pallas_run=pallas_run,
            )
        # host bool per the same contract
        assert ok, "fleet reverse SSSP did not reach its fixed point"
        self._dist_dev = dist
        self._bitmap_dev = bitmap
        self.converged = True
        self.warm = init is not None
        self.sweep_hint = runner.hint
        self._runner = runner

    # -- host queries --------------------------------------------------------

    def covers(self, node: str) -> bool:
        return node in self._node_id

    def is_dest(self, node: str) -> bool:
        return node in self.p_index

    def _row(self, node: str) -> np.ndarray:
        """dist(node -> every dest), [P] int32; fetched lazily and cached
        (one device row fetch per new node — a ctrl query touches only
        the queried router and its neighbors)."""
        import jax

        i = self._node_id[node]
        hit = self._rows.get(i)
        if hit is None:
            hit = _row_i32(jax.device_get(self._dist_dev[i]))
            self._rows[i] = hit
        return hit

    def prefetch_rows(self, nodes: list[str]) -> None:
        """Fetch many routers' rows in one device gather (fleet dumps)."""
        import jax
        import jax.numpy as jnp

        ids = [self._node_id[n] for n in nodes if n in self._node_id]
        missing = [i for i in ids if i not in self._rows]
        if not missing:
            return
        rows = _row_i32(
            jax.device_get(
                jnp.take(
                    self._dist_dev, jnp.asarray(missing, jnp.int32), axis=0
                )
            )
        )
        for k, i in enumerate(missing):
            self._rows[i] = rows[k]

    def dist(self, node: str, dest: str) -> int:
        """dist(node -> dest); INF32 when unreachable."""
        d = self._row(node)[self.p_index[dest]]
        return int(d)

    def reachable(self, node: str, dest: str) -> bool:
        return self.dist(node, dest) < INF32

    def is_overloaded_id(self, node: str) -> bool:
        return bool(self._overloaded[self._node_id[node]])

    def next_hop_neighbors(self, node: str, dest: str) -> set[str]:
        """Decode the device bitmap row: slot-named ECMP next-hop
        neighbors of `node` toward `dest` (unique neighbors; parallel
        links share a slot).  Used by tests/dumps to cross-check the
        host-side per-link evaluation."""
        import jax

        i = self._node_id[node]
        p = self.p_index[dest]
        words = jax.device_get(self._bitmap_dev[i, p])
        slot_names = self.csr.slot_neighbors(node)
        out: set[str] = set()
        for w in range(words.shape[0]):
            bits = int(words[w])
            base = 32 * w
            while bits:
                b = bits & -bits
                out.add(slot_names[base + b.bit_length() - 1])
                bits ^= b
        return out


def fleet_destinations(ls: LinkState, prefix_state) -> list[str]:
    """The destination set route construction reads distances to, for one
    area: prefix-advertising nodes (reachability filter + unicast ECMP,
    Decision.cpp:445-613) + labeled nodes (MPLS node-label routes,
    Decision.cpp:655-745).  Sorted for a deterministic cache key."""
    dests: set[str] = set()
    for entries in prefix_state.prefixes.values():
        for node, _area in entries:
            if ls.has_node(node):
                dests.add(node)
    for node, adj_db in ls.get_adjacency_databases().items():
        if adj_db.node_label != 0 and ls.has_node(node):
            dests.add(node)
    return sorted(dests)


class FleetViewCache:
    """Per-LinkState cached FleetRouteView, invalidated on topology
    version or destination-set change.  Weakly keyed like
    DeviceSpfBackend's mirrors (ids recycle after GC).

    `delta` opts in to the incremental delta rung (decision.delta +
    ops.delta through the engine's delta_dispatch): a rebuild over the
    same universe first tries to fold the whole pending event batch into
    the previous device product at frontier-proportional cost, falling
    back to the legacy warm/cold paths below on any gate failure.
    Default OFF (None reads OPENR_FLEET_DELTA): the rung re-labels
    warm_mode and shifts counters, so existing deployments and the
    warm-path tests keep their exact behavior unless asked."""

    def __init__(
        self,
        delta: Optional[bool] = None,
        bump=None,
        delta_min_p: int = 32,
        delta_parity: Optional[bool] = None,
    ) -> None:
        import os

        if delta is None:
            delta = os.environ.get("OPENR_FLEET_DELTA", "0") == "1"
        self._delta = None
        if delta:
            from .delta import DeltaProductUpdater

            self._delta = DeltaProductUpdater(
                bump=bump, min_p=delta_min_p, parity=delta_parity
            )
        self._views: "weakref.WeakKeyDictionary[LinkState, FleetRouteView]" = (
            weakref.WeakKeyDictionary()
        )
        # learned reverse-relax sweep hints keyed by topology shape
        # (node/edge counts — the DeviceSpfBackend._hint_key discipline):
        # a rebuilt view of a same-shaped topology starts from the learned
        # count instead of re-learning it by doubling
        self._hints: dict[tuple[int, int], int] = {}
        # warm (previous-product-seeded) rebuilds converge in far fewer
        # sweeps than cold ones; learning them into _hints would poison
        # every later cold rebuild, so they get their own store
        self._warm_hints: dict[tuple[int, int], int] = {}

    def is_warm(self, ls: LinkState, dest_names: list[str]) -> bool:
        """True when a cached view already answers this (version, dests) —
        i.e. using the fleet path costs zero device work."""
        cached = self._views.get(ls)
        return (
            cached is not None
            and cached.version == ls.version
            and cached.dest_names == list(dest_names)
        )

    def view(
        self, ls: LinkState, dest_names: list[str], csr=None, engine=None
    ) -> Optional[FleetRouteView]:
        """Computed view for this (version, dests); None when empty.

        A rebuild WARM-STARTS from the previous view's device distances
        in BOTH change directions over the same node/dest universe:
        improvement-only changes (link up, metric decrease, overload
        clear) seed the full previous product — the upper-bound
        condition ops.banded.spf_forward_banded requires — while
        worsening/mixed changes (link down, metric increase, drain)
        seed the previous product with the certified affected set
        re-set to INF (_affected_init), the mirror-image upper bound.
        Either way reconvergence pays a few relax sweeps instead of the
        full cold count; only universe changes and uncertifiable
        affected sets still cold-start."""
        if not dest_names:
            return None
        if self.is_warm(ls, dest_names):
            return self._views[ls]
        if csr is None:
            from .csr import CsrTopology

            csr = CsrTopology.from_link_state(ls)
        elif csr.version != ls.version:
            csr.refresh(ls)
        prev = self._views.get(ls)
        view = FleetRouteView(csr, dest_names, engine=engine)
        # incremental rung first: fold the whole pending event batch
        # into the previous device product at frontier-proportional
        # cost; any gate failure falls through to the legacy warm/cold
        # paths below, which are the bit-exact fallback
        if (
            self._delta is not None
            and engine is not None
            and (prev is None or not prev.node_sharded)
            and self._delta.eligible(prev)
            and self._delta.update(prev, view, engine)
        ):
            self._views[ls] = view
            return view
        key = (csr.n_nodes, csr.n_edges)
        init_from = None
        down_from = None
        if (
            prev is not None
            and prev.converged
            and not prev.node_sharded
            and prev._dist_dev is not None
            and prev.dest_names == view.dest_names
            and prev._node_id == view._node_id
            and prev._overloaded.shape == view._overloaded.shape
        ):
            if _improvement_only(
                prev._edge_keys,
                prev._edge_met,
                prev._overloaded,
                view._edge_keys,
                view._edge_met,
                view._overloaded,
            ):
                init_from = prev
            elif prev._runner is not None and prev._runner.bg is not None:
                down_from = prev
        # cold seed always flows in; the warm seed applies only if the
        # warm path engages (compute() decides — ELL fallbacks stay
        # cold), and harvesting routes by what actually ran
        try:
            view.compute(
                hint_seed=self._hints.get(key),
                init_from=init_from,
                warm_seed=self._warm_hints.get(key, 4),
                down_from=down_from,
            )
        except Exception:
            if init_from is None and down_from is None:
                raise  # cold run failed: nothing softer to retry with
            # warm-start gate failure (bad seed, uncertifiable affected
            # set, device error during the seeded relax): retry COLD on a
            # fresh view — the caller reads cold_fallback for counters
            log.warning("fleet: warm-started rebuild failed; retrying cold")
            view = FleetRouteView(csr, dest_names, engine=engine)
            view.compute(hint_seed=self._hints.get(key))
            view.cold_fallback = True
        if view.sweep_hint is not None:
            store = self._warm_hints if view.warm else self._hints
            # max-merge, like DeviceSpfBackend._harvest_hint
            store[key] = max(store.get(key, 0), view.sweep_hint)
        self._views[ls] = view
        return view
