"""Device-residency engine: long-lived device state + bucketed programs.

The serving-engine shape from inference stacks applied to SPF: graph
mirrors stay resident on the device and are updated incrementally from
LinkState deltas; variable source-set sizes pad up a small bucket ladder
of persistently compiled programs with donated scratch, so a control-
plane query never pays per-call staging or retracing.
"""

from .engine import (
    DeviceResidencyEngine,
    ENGINE_COUNTER_KEYS,
    EpochMismatchError,
    S_BUCKETS,
)
from .sanitizer import EngineSanitizer, SanitizerViolation

__all__ = [
    "DeviceResidencyEngine",
    "ENGINE_COUNTER_KEYS",
    "EngineSanitizer",
    "EpochMismatchError",
    "S_BUCKETS",
    "SanitizerViolation",
]
