"""Runtime sanitizer for engine dispatch paths.

The static program auditor (analysis/programs.py) proves contracts of the
*compiled* programs; this module polices the *dispatch* that feeds them,
at test time:

- :meth:`EngineSanitizer.transfer_guard` wraps a dispatch in
  ``jax.transfer_guard("disallow")``: any IMPLICIT host->device transfer
  (a numpy array or Python scalar leaking straight into a compiled call
  instead of going through the engine's explicit, accounted
  ``jax.device_put`` staging) surfaces as :class:`SanitizerViolation`.
  Explicit ``device_put`` / ``device_get`` remain allowed — they are the
  engine's sanctioned, byte-counted staging path.

  CPU-CI caveat: on CPU, device->host reads (``np.asarray`` on a device
  array, ``device_get``) are zero-copy and are NOT flagged by the guard;
  only the implicit host->device direction is enforced here.  On real
  accelerators the same guard also catches stray D2H syncs.

- :meth:`EngineSanitizer.compile_budget` asserts the engine compiles at
  most ``allowed`` new programs inside the block (default 0): after
  warmup, a steady-state query must be a bucket hit.  A recompile in the
  hot loop means the bucket key leaked per-query state (a fresh sweep
  hint, an unpadded source count) — the exact regression the AOT ladder
  exists to prevent.

Used by tier-1 (tests/test_sanitizer.py wires both checks around real
engine queries and proves each catches a seeded violation; the 25-flap
acceptance sequence runs its warm queries under the transfer guard).
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Iterator

__all__ = ["EngineSanitizer", "SanitizerViolation"]

_GUARD_MARKER = "Disallowed host-to-device transfer"

COMPILES_KEY = "device.engine.compiles"


class SanitizerViolation(AssertionError):
    """An engine dispatch broke a runtime residency contract."""


class EngineSanitizer:
    """Wraps a :class:`DeviceResidencyEngine`'s dispatches in runtime
    contract checks.  Stateless between blocks; cheap to construct."""

    def __init__(self, engine) -> None:
        self.engine = engine

    @contextmanager
    def transfer_guard(self) -> Iterator[None]:
        """Fail the block on any implicit host->device transfer."""
        import jax

        try:
            with jax.transfer_guard("disallow"):
                yield
        except Exception as e:
            if _GUARD_MARKER in str(e):
                raise SanitizerViolation(
                    "implicit host->device transfer inside an engine "
                    "dispatch — a host array reached a compiled program "
                    "without going through the engine's explicit "
                    f"device_put staging: {e}"
                ) from e
            raise

    @contextmanager
    def compile_budget(self, allowed: int = 0) -> Iterator[None]:
        """Fail the block if the engine compiles more than ``allowed``
        new programs (default: none — steady state is all bucket hits)."""
        before = self.engine.get_counters()[COMPILES_KEY]
        yield
        spent = self.engine.get_counters()[COMPILES_KEY] - before
        if spent > allowed:
            raise SanitizerViolation(
                f"engine compiled {spent} program(s) inside a "
                f"compile_budget({allowed}) block; a steady-state query "
                "must hit the AOT bucket cache — check that the bucket "
                "key doesn't include per-query state"
            )

    @contextmanager
    def sanitized(self, allowed_compiles: int = 0) -> Iterator[None]:
        """Both checks at once: the steady-state dispatch contract."""
        with self.transfer_guard():
            with self.compile_budget(allowed_compiles):
                yield
