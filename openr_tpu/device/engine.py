"""Persistent device-residency engine for small-batch SPF dispatch.

The measured dispatch policy loses every single-event reconvergence to
the host because each device call re-stages the graph and re-enters the
jit cache (VERDICT "What's weak" §3).  This engine removes both taxes:

- **Residency**: one `_Resident` per CsrTopology mirror holds the ELL
  tables and edge/node attribute arrays on the device.  Attribute flaps
  (link up/down, metric, drain) are applied *on device* by scatter-free
  masked writes against host shadow copies — an adjacency flap never
  re-uploads the graph.  Only an edge-set/node-set rebuild (a new
  `csr.ell` object) forces a full restage.
- **Shape-bucketed program cache**: a query for S sources pads up the
  `S_BUCKETS` ladder and dispatches a persistently compiled program
  keyed by (topology bucket, S bucket, word count, sweep count, dtype
  mode, metric mode).  Programs are AOT-compiled
  (`jax.jit(...).lower(...).compile()`) so LRU eviction actually frees
  the executable, and the per-query distance scratch is donated
  (`donate_argnums`) back to the runtime.
- **Accounting**: every byte that crosses host->device and every
  staging/compile/dispatch interval is recorded under `device.engine.*`
  and exported through `OpenrCtrlHandler._all_counters` / the fb303
  shim.

Failure discipline: any exception thrown here rides the existing
degradation ladder (SpfSolver catches and falls back to the host
oracle); the chaos harness injects faults through `fault_hook`.
"""

from __future__ import annotations

import functools
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import trace as _trace
from ..ops import sssp as ops

# source-batch padding ladder; above the last rung, next power of two
S_BUCKETS = (1, 8, 64, 512)

ENGINE_COUNTER_KEYS = (
    "device.engine.compiles",
    "device.engine.bucket_hits",
    "device.engine.bucket_misses",
    "device.engine.evictions",
    "device.engine.bytes_staged",
    "device.engine.incremental_updates",
    "device.engine.full_restages",
    "device.engine.queries",
    "device.engine.dispatches",
    "device.engine.stage_us",
    "device.engine.compile_us",
    "device.engine.dispatch_us",
    "device.engine.epoch_invalidations",
    "device.engine.delta_dispatches",
    "device.engine.delta_dispatch_us",
    "device.engine.delta_bucket_hits",
    "device.engine.delta_bucket_misses",
    "device.engine.delta_overflow_fallbacks",
    "device.engine.rewires",
    "device.engine.rewire_dispatches",
    "device.engine.rewire_slots",
    "device.engine.rewire_rows",
    "device.engine.rewire_bytes_staged",
    "device.engine.rewire_us",
    "device.engine.rewire_fallbacks",
    # Pallas kernel rung (ops.pallas_kernels): launches that ran the
    # hand-tiled kernels, demotions to the XLA path, and policy-off
    # skips.  Pre-seeded like every family so both wire surfaces dump
    # the keys before the first dispatch.
    "device.engine.pallas_products",
    "device.engine.pallas_outer_updates",
    "device.engine.pallas_fallbacks",
    "device.engine.pallas_skips",
)

# affected-column padding ladder for the delta rung: a frontier of
# n_cols columns dispatches at the smallest rung >= n_cols so storms of
# similar size share one compiled program
DELTA_P_BUCKETS = (8, 16, 32, 64, 128, 256, 512)


class EpochMismatchError(RuntimeError):
    """The caller pinned a topology epoch (`expect_epoch`) that no longer
    matches the CsrTopology — a flap landed between coalescing and
    dispatch.  The serving layer catches this and recomputes against the
    fresh topology instead of serving stale routes."""

    def __init__(self, expected: int, actual: int) -> None:
        super().__init__(
            f"topology epoch moved: expected {expected}, now {actual}"
        )
        self.expected = expected
        self.actual = actual


def _s_bucket(s: int) -> int:
    for b in S_BUCKETS:
        if s <= b:
            return b
    b = S_BUCKETS[-1]
    while b < s:
        b *= 2
    return b


def _nbytes(*arrays) -> int:
    return sum(int(a.size) * int(a.dtype.itemsize) for a in arrays)


@functools.partial(jax.jit, static_argnames=("n_cap",))
def _dist0_T_device(sources, new_of_old, n_cap):
    # device-built initial distances: the only per-query upload stays the
    # [S] source-id vector
    return ops.make_dist0_T(sources, new_of_old, n_cap)


@functools.partial(jax.jit, donate_argnums=(0,))
def _masked_write_i32(arr, idx, vals):
    """arr[idx] = vals without a scatter (one scatter knocks the TPU
    runtime off its fast dispatch path; see ops.sssp.make_dist0_T).
    `idx` is padded with -1 (never matches), indices are unique."""
    hit = jnp.arange(arr.shape[0], dtype=jnp.int32)[:, None] == idx[None, :]
    picked = (hit * vals[None, :]).sum(axis=1)
    return jnp.where(hit.any(axis=1), picked.astype(arr.dtype), arr)


@functools.partial(jax.jit, donate_argnums=(0,))
def _masked_write_bool(arr, idx, vals):
    hit = jnp.arange(arr.shape[0], dtype=jnp.int32)[:, None] == idx[None, :]
    picked = (hit & vals[None, :]).any(axis=1)
    return jnp.where(hit.any(axis=1), picked, arr)


@functools.partial(jax.jit, donate_argnums=(0,))
def _masked_write_rows_i32(arr, row_idx, rows):
    """arr[row_idx, :] = rows without a scatter.  `arr` is [N, K],
    `row_idx` is [R] padded with -1 (never matches), `rows` is [R, K].
    Same fast-dispatch discipline as the element masked writes — the
    rewire rung patches whole re-encoded ELL destination rows."""
    hit = jnp.arange(arr.shape[0], dtype=jnp.int32)[:, None] == row_idx[None, :]
    picked = (hit[:, :, None] * rows[None, :, :]).sum(axis=1)
    return jnp.where(hit.any(axis=1)[:, None], picked.astype(arr.dtype), arr)


@functools.partial(jax.jit, donate_argnums=(0,))
def _masked_write_rows_bool(arr, row_idx, rows):
    hit = jnp.arange(arr.shape[0], dtype=jnp.int32)[:, None] == row_idx[None, :]
    picked = (hit[:, :, None] & rows[None, :, :]).any(axis=1)
    return jnp.where(hit.any(axis=1)[:, None], picked, arr)


def _pad_updates(idx: np.ndarray, vals: np.ndarray, pad_val):
    """Pad (idx, vals) to a small power-of-two K so the masked-write
    programs bucket by update count instead of retracing per flap."""
    k = 8
    while k < len(idx):
        k *= 2
    pad = k - len(idx)
    if pad:
        idx = np.concatenate([idx, np.full(pad, -1, dtype=np.int32)])
        vals = np.concatenate([vals, np.full(pad, pad_val, dtype=vals.dtype)])
    return idx, vals


def _pad_rows(row_idx: np.ndarray, *row_arrays):
    """Row-update analogue of `_pad_updates`: pad the [R] index vector
    with -1 and each [R, K] payload with zero rows up to a small
    power-of-two R so the row-write programs bucket by row count."""
    k = 8
    while k < len(row_idx):
        k *= 2
    pad = k - len(row_idx)
    if pad:
        row_idx = np.concatenate(
            [row_idx, np.full(pad, -1, dtype=np.int32)]
        )
        row_arrays = tuple(
            np.concatenate(
                [a, np.zeros((pad,) + a.shape[1:], dtype=a.dtype)]
            )
            for a in row_arrays
        )
    return (row_idx,) + row_arrays


def _forward_body(
    small: bool, use_link_metric: bool, n_sweeps: int, n_words: int
):
    """Program body for one (S bucket, mode) cell — mirrors
    ops.sssp.spf_forward_full(_packed) but takes the donated distance
    scratch as its first argument so the runtime reuses its pages."""

    def fn(
        dist0_T,  # [N_cap, S_bucket] int32 — DONATED
        sources,  # [S_bucket] int32
        ell,
        edge_src,
        edge_dst,
        edge_metric,
        edge_up,
        node_overloaded,
        out_slot,
    ):
        dist_T, dist_ok = ops.batched_sssp_ell(
            dist0_T,
            ell,
            unit_metric=not use_link_metric,
            edge_up=edge_up,
            node_overloaded=node_overloaded,
            edge_metric=edge_metric,
            n_sweeps=n_sweeps,
        )
        dist_old_T = ops.ell_dist_to_old_T(dist_T, ell)
        metric = (
            edge_metric if use_link_metric else jnp.ones_like(edge_metric)
        )
        allowed_T = ops.make_relax_allowed_T(
            sources, edge_src, edge_up, node_overloaded
        )
        d_u = jnp.take(dist_old_T, edge_src, axis=0)
        d_v = jnp.take(dist_old_T, edge_dst, axis=0)
        dag_T = allowed_T & (d_u < ops.INF32) & (
            d_u + metric[:, None] == d_v
        )
        nh, nh_ok = ops.first_hops_ell(
            ell, dag_T, out_slot, sources, edge_src, n_words,
            n_sweeps=n_sweeps,
        )
        ok = dist_ok & nh_ok
        if not small:
            # dist stays in the donated [N_cap, S] layout: the output aval
            # must equal the donated input's for XLA to alias the buffer
            # (a transposed return silently drops the donation); the host
            # transposes the fetched view for free after device_get
            return dist_old_T, dag_T.T, nh, ok
        # small control-plane query: ONE packed device->host transfer
        return jnp.concatenate(
            [
                dist_old_T.T.ravel(),
                dag_T.T.ravel().astype(jnp.int32),
                jax.lax.bitcast_convert_type(nh, jnp.int32).ravel(),
                ok.astype(jnp.int32)[None],
            ]
        )

    return fn


@dataclass
class _Resident:
    """Device-resident mirror of one CsrTopology + host shadows for
    diffing.  `ell_host` pins the host ELL object: identity change means
    csr.refresh() rebuilt the topology and residency must restage."""

    topo_key: tuple
    ell_host: Any
    version: int
    # device arrays
    ell: Any
    edge_src: Any
    edge_dst: Any
    edge_metric: Any
    edge_up: Any
    node_overloaded: Any
    out_slot: Any
    # host shadows of the three mutable attribute arrays
    shadow_metric: np.ndarray = field(repr=False, default=None)
    shadow_up: np.ndarray = field(repr=False, default=None)
    shadow_overloaded: np.ndarray = field(repr=False, default=None)
    sweep_hint: int = 16
    # last CsrTopology.rewire_seq applied to the device mirror; a gap
    # against csr.rewire_seq routes sync() through the rewire rung
    rewire_seq: int = 0


class DeviceResidencyEngine:
    """Owns device residency, the bucketed program cache and the
    `device.engine.*` accounting.  One instance serves every area's
    CsrTopology mirror (residents key on mirror identity)."""

    def __init__(
        self,
        max_programs: int = 16,
        s_buckets: tuple = S_BUCKETS,
        small_threshold: int = 1 << 21,
    ) -> None:
        self.max_programs = max_programs
        self.s_buckets = tuple(s_buckets)
        # S_bucket * node_capacity at or below this dispatches the packed
        # single-transfer program shape; the program auditor forces it to 0
        # to exercise the full (donation-aliased) shape on tiny topologies
        self.small_threshold = small_threshold
        self.counters: dict[str, int] = {k: 0 for k in ENGINE_COUNTER_KEYS}
        # (topo_key, s_bucket, n_words, n_sweeps, small, use_link_metric)
        #   -> AOT-compiled executable; OrderedDict as LRU
        self._programs: "OrderedDict[tuple, Any]" = OrderedDict()
        # key -> (program body fn, arg ShapeDtypeStructs, donate_argnums):
        # enough for the program auditor to re-trace every ladder cell it
        # saw compiled, without holding example arrays alive
        self._program_specs: dict[tuple, tuple] = {}
        # id(csr) -> _Resident (csr mirrors are long-lived per area)
        self._residents: dict[int, _Resident] = {}
        # delta-rung bucket cells already traced (hit/miss accounting)
        self._delta_buckets_seen: set = set()
        # chaos seam: called with an op name at every engine entry point
        self.fault_hook: Optional[Callable[[str], None]] = None
        # Pallas policy override: None resolves the OPENR_PALLAS env
        # knob (ops.pallas_kernels.pallas_mode); tests and the program
        # auditor pin "interpret"/"off" here instead of mutating the
        # environment (the _drive_blocked threshold discipline)
        self.pallas_mode: Optional[str] = None
        # third dispatch rung (delta < fused full < blocked): node-axis
        # sharded blocked APSP (parallel.blocked).  Eagerly constructed
        # so its pre-seeded mesh.blocked.* counters dump before the
        # first dispatch; the device mesh itself stays lazy.  It reads
        # THIS engine's fault_hook, so chaos faults armed here fire
        # inside the blocked rounds too.
        from ..parallel.blocked import BlockedApspEngine

        self.blocked = BlockedApspEngine(parent=self)
        # per-query attribution (read by bench rows)
        self.last_query_bytes = 0
        self.last_query_us = 0

    # -- counters -----------------------------------------------------------

    def get_counters(self) -> dict[str, int]:
        return dict(self.counters)

    def _bump(self, name: str, delta: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + delta

    # -- residency ----------------------------------------------------------

    def has_residency(self, csr) -> bool:
        """True when `csr`'s graph is resident (attribute drift is fine —
        the next sync applies it incrementally, which is cheap; only a
        topology rebuild forces a restage)."""
        res = self._residents.get(id(csr))
        return res is not None and res.ell_host is csr.ell

    def is_warm(self, csr) -> bool:
        """True when `csr`'s graph is resident and current — the measured
        dispatch policy flips small-S queries to the device only then."""
        res = self._residents.get(id(csr))
        return (
            res is not None
            and res.ell_host is csr.ell
            and res.version == csr.version
        )

    def sync(self, csr) -> _Resident:
        """Bring `csr`'s device residency to csr.version.

        Full restage only when the ELL object changed (topology
        rebuild); bounded edge-set rewires replay the CsrTopology rewire
        log through masked slot/row writes; attribute-only refreshes
        diff the host shadows and apply masked writes on device."""
        if self.fault_hook is not None:
            self.fault_hook("sync")
        t0 = time.perf_counter()
        res = self._residents.get(id(csr))
        if res is None or res.ell_host is not csr.ell:
            res = self._restage(csr)
        else:
            if getattr(csr, "rewire_seq", 0) != res.rewire_seq:
                try:
                    self._rewire_sync(res, csr)
                    tr = _trace.TRACE
                    if tr is not None:
                        tr.annotate("engine.rung", "rewire")
                except Exception:
                    # any rewire failure (log gap, fault injection, ...)
                    # demotes to the restage rung — never an error
                    self._bump("device.engine.rewire_fallbacks")
                    res = self._restage(csr)
            if res.version != csr.version:
                self._incremental(res, csr)
                tr = _trace.TRACE
                if tr is not None:
                    tr.annotate("engine.rung", "incremental")
        self._bump(
            "device.engine.stage_us",
            int((time.perf_counter() - t0) * 1e6),
        )
        return res

    def _restage(self, csr) -> _Resident:
        tr = _trace.TRACE
        if tr is not None:
            tr.annotate("engine.rung", "restage")
        host_arrays = (
            csr.edge_src,
            csr.edge_dst,
            csr.edge_metric,
            csr.edge_up,
            csr.node_overloaded,
            csr.out_slot,
        )
        ell_leaves = jax.tree_util.tree_leaves(csr.ell)
        staged = _nbytes(*host_arrays) + _nbytes(
            *(np.asarray(leaf) for leaf in ell_leaves)
        )
        res = _Resident(
            topo_key=(csr.node_capacity, csr.edge_capacity),
            ell_host=csr.ell,
            version=csr.version,
            ell=jax.device_put(csr.ell),
            edge_src=jax.device_put(csr.edge_src),
            edge_dst=jax.device_put(csr.edge_dst),
            edge_metric=jax.device_put(csr.edge_metric),
            edge_up=jax.device_put(csr.edge_up),
            node_overloaded=jax.device_put(csr.node_overloaded),
            out_slot=jax.device_put(csr.out_slot),
            shadow_metric=csr.edge_metric.copy(),
            shadow_up=csr.edge_up.copy(),
            shadow_overloaded=csr.node_overloaded.copy(),
            sweep_hint=csr._sweep_hint,
            rewire_seq=getattr(csr, "rewire_seq", 0),
        )
        self._residents[id(csr)] = res
        self._bump("device.engine.full_restages")
        self._bump("device.engine.bytes_staged", staged)
        return res

    def _incremental(self, res: _Resident, csr) -> None:
        """Apply attribute deltas (metric writes / up masks / overload
        flips) on device.  Upload cost is O(changed entries), padded to a
        small power-of-two bucket — never the graph."""
        staged = 0
        for attr, shadow, host, write in (
            ("edge_metric", res.shadow_metric, csr.edge_metric,
             _masked_write_i32),
            ("edge_up", res.shadow_up, csr.edge_up, _masked_write_bool),
            ("node_overloaded", res.shadow_overloaded, csr.node_overloaded,
             _masked_write_bool),
        ):
            changed = np.flatnonzero(shadow != host)
            if changed.size == 0:
                continue
            idx = changed.astype(np.int32)
            vals = host[changed]
            idx, vals = _pad_updates(
                idx, vals, pad_val=vals.dtype.type(0)
            )
            # explicit H2D staging: the masked-write programs must never
            # see raw host arrays (the transfer-guard sanitizer disallows
            # implicit transfers on every engine dispatch path)
            idx_dev, vals_dev = jax.device_put((idx, vals))
            setattr(res, attr, write(getattr(res, attr), idx_dev, vals_dev))
            staged += _nbytes(idx, vals)
            shadow[changed] = host[changed]
        res.version = csr.version
        self._bump("device.engine.incremental_updates")
        if staged:
            self._bump("device.engine.bytes_staged", staged)

    def _rewire_sync(self, res: _Resident, csr) -> None:
        """Replay the pending tail of csr's rewire log against the
        resident: masked writes for the rewritten edge slots plus
        donated row writes for every re-encoded ELL destination row.
        Upload cost is O(touched slots + touched rows) — never the
        graph, so a bounded OCS rewire keeps full_restages == 1.

        Raises on any inconsistency (log gap after eviction, injected
        fault); sync() demotes that to a restage."""
        t0 = time.perf_counter()
        if self.fault_hook is not None:
            self.fault_hook("rewire")
        pending = [d for d in csr._rewire_log if d.seq > res.rewire_seq]
        if (
            not pending
            or pending[0].seq != res.rewire_seq + 1
            or pending[-1].seq != csr.rewire_seq
            or any(
                b.seq != a.seq + 1 for a, b in zip(pending, pending[1:])
            )
        ):
            raise RuntimeError(
                f"rewire chain gap: resident at seq {res.rewire_seq}, "
                f"log covers {[d.seq for d in pending]}"
            )
        staged = n_slots = n_rows = 0
        for delta in pending:
            staged += self._apply_rewire(res, delta)
            n_slots += len(delta.slots)
            n_rows += len(delta.ell_rows)
            self._bump("device.engine.rewires")
        res.rewire_seq = csr.rewire_seq
        # the touched slots are current in the shadows now; when nothing
        # else drifted the resident is fully at csr.version and the
        # attribute-diff rung can be skipped outright
        if (
            np.array_equal(res.shadow_metric, csr.edge_metric)
            and np.array_equal(res.shadow_up, csr.edge_up)
            and np.array_equal(res.shadow_overloaded, csr.node_overloaded)
        ):
            res.version = csr.version
        self._bump("device.engine.rewire_dispatches")
        self._bump("device.engine.rewire_slots", n_slots)
        self._bump("device.engine.rewire_rows", n_rows)
        self._bump("device.engine.rewire_bytes_staged", staged)
        self._bump("device.engine.bytes_staged", staged)
        self._bump(
            "device.engine.rewire_us",
            int((time.perf_counter() - t0) * 1e6),
        )

    def _apply_rewire(self, res: _Resident, delta) -> int:
        """Apply one RewireDelta to the resident mirror; returns bytes
        uploaded.  Slot payloads ride the element masked writes, ELL
        rows ride the donated row writes (grouped per bucket so each
        [N_b, K_b] cell compiles once)."""
        staged = 0
        for attr, idx, vals, write, shadow in (
            ("edge_src", delta.slots, delta.src, _masked_write_i32, None),
            ("edge_dst", delta.slots, delta.dst, _masked_write_i32, None),
            ("edge_metric", delta.slots, delta.metric, _masked_write_i32,
             res.shadow_metric),
            ("edge_up", delta.slots, delta.up, _masked_write_bool,
             res.shadow_up),
            ("out_slot", delta.out_idx, delta.out_val, _masked_write_i32,
             None),
        ):
            if len(idx) == 0:
                continue
            pi, pv = _pad_updates(
                idx.astype(np.int32), vals, pad_val=vals.dtype.type(0)
            )
            # explicit H2D staging — same transfer-guard discipline as
            # the attribute rung
            pi_dev, pv_dev = jax.device_put((pi, pv))
            setattr(res, attr, write(getattr(res, attr), pi_dev, pv_dev))
            staged += _nbytes(pi, pv)
            if shadow is not None:
                shadow[idx] = vals
        by_bucket: dict[int, list] = {}
        for row in delta.ell_rows:
            by_bucket.setdefault(row[0], []).append(row)
        if not by_bucket:
            return staged
        buckets = list(res.ell.buckets)
        for b_idx, rows in by_bucket.items():
            bkt = buckets[b_idx]
            row_idx = np.asarray([r[1] for r in rows], dtype=np.int32)
            nbr = np.stack([r[2] for r in rows])
            w = np.stack([r[3] for r in rows])
            eid = np.stack([r[4] for r in rows])
            ok = np.stack([r[5] for r in rows])
            tok = np.stack([r[6] for r in rows])
            row_idx, nbr, w, eid, ok, tok = _pad_rows(
                row_idx, nbr, w, eid, ok, tok
            )
            idx_dev, nbr_dev, w_dev, eid_dev, ok_dev, tok_dev = (
                jax.device_put((row_idx, nbr, w, eid, ok, tok))
            )
            buckets[b_idx] = bkt._replace(
                nbr=_masked_write_rows_i32(bkt.nbr, idx_dev, nbr_dev),
                w=_masked_write_rows_i32(bkt.w, idx_dev, w_dev),
                edge_id=_masked_write_rows_i32(bkt.edge_id, idx_dev, eid_dev),
                ok=_masked_write_rows_bool(bkt.ok, idx_dev, ok_dev),
                transit_ok=_masked_write_rows_bool(
                    bkt.transit_ok, idx_dev, tok_dev
                ),
            )
            staged += _nbytes(row_idx, nbr, w, eid, ok, tok)
        res.ell = res.ell._replace(buckets=tuple(buckets))
        return staged

    def drop(self, csr) -> None:
        """Forget `csr`'s residency (mirror retired)."""
        self._residents.pop(id(csr), None)

    # -- snapshot seams (openr_tpu/snapshot) --------------------------------

    def export_resident(self, csr) -> dict:
        """Host-side image of `csr`'s residency for EngineSnapshot.take:
        sync first (the checkpoint is always at the mirror's current
        version), then one batched explicit device_get per surface —
        the snapshot layer never touches _Resident internals."""
        res = self.sync(csr)
        names = (
            "edge_src",
            "edge_dst",
            "edge_metric",
            "edge_up",
            "node_overloaded",
            "out_slot",
        )
        fetched = jax.device_get(tuple(getattr(res, n) for n in names))
        leaves = jax.device_get(jax.tree_util.tree_leaves(res.ell))
        return {
            "topo_key": res.topo_key,
            "version": res.version,
            "rewire_seq": res.rewire_seq,
            "sweep_hint": res.sweep_hint,
            "arrays": {
                n: np.asarray(a) for n, a in zip(names, fetched)
            },
            "ell_leaves": [np.asarray(x) for x in leaves],
        }

    def install_resident(
        self,
        csr,
        state: dict,
        *,
        version: Optional[int] = None,
        rewire_seq: Optional[int] = None,
    ) -> _Resident:
        """Install a host-side resident image (export_resident shape) as
        `csr`'s device residency.  The shadows come from the image, so a
        following sync() reconciles any attribute drift between the
        checkpoint and `csr` through the ordinary incremental rung.
        `version`/`rewire_seq` override the image's position when the
        caller proved `csr`'s content already matches (the snapshot
        content-equality rung)."""
        arr = state["arrays"]
        leaves = [np.asarray(x) for x in state["ell_leaves"]]
        treedef = jax.tree_util.tree_structure(csr.ell)
        ell = jax.tree_util.tree_unflatten(
            treedef, [jax.device_put(x) for x in leaves]
        )
        staged = _nbytes(*arr.values()) + _nbytes(*leaves)
        res = _Resident(
            topo_key=tuple(state["topo_key"]),
            ell_host=csr.ell,
            version=int(
                state["version"] if version is None else version
            ),
            ell=ell,
            edge_src=jax.device_put(arr["edge_src"]),
            edge_dst=jax.device_put(arr["edge_dst"]),
            edge_metric=jax.device_put(arr["edge_metric"]),
            edge_up=jax.device_put(arr["edge_up"]),
            node_overloaded=jax.device_put(arr["node_overloaded"]),
            out_slot=jax.device_put(arr["out_slot"]),
            shadow_metric=np.asarray(arr["edge_metric"]).copy(),
            shadow_up=np.asarray(arr["edge_up"]).copy(),
            shadow_overloaded=np.asarray(arr["node_overloaded"]).copy(),
            sweep_hint=int(state.get("sweep_hint", 16)),
            rewire_seq=int(
                state["rewire_seq"] if rewire_seq is None else rewire_seq
            ),
        )
        self._residents[id(csr)] = res
        self._bump("device.engine.bytes_staged", staged)
        return res

    def prewarm(self, csr, keys) -> int:
        """AOT-compile manifest ladder keys against `csr`'s resident
        shapes (snapshot warm-start).  Lowering takes ShapeDtypeStructs,
        so no example arrays are materialized — the XLA compile is the
        cold-start cost being moved off the serving path.  Keys for a
        different topology, or already cached, are skipped.  Returns how
        many programs were actually compiled."""
        res = self._residents.get(id(csr))
        if res is None or res.ell_host is not csr.ell:
            return 0

        def sds(x):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)

        warmed = 0
        for key in keys:
            topo, s_bucket, n_words, n_sweeps, small, use_lm = key
            key = (
                tuple(topo),
                int(s_bucket),
                int(n_words),
                int(n_sweeps),
                bool(small),
                bool(use_lm),
            )
            if key in self._programs or key[0] != res.topo_key:
                continue
            n_cap = res.topo_key[0]
            args = (
                jax.ShapeDtypeStruct((n_cap, key[1]), jnp.int32),
                jax.ShapeDtypeStruct((key[1],), jnp.int32),
                jax.tree_util.tree_map(sds, res.ell),
                sds(res.edge_src),
                sds(res.edge_dst),
                sds(res.edge_metric),
                sds(res.edge_up),
                sds(res.node_overloaded),
                sds(res.out_slot),
            )
            self._program(key, args)
            warmed += 1
        return warmed

    # -- program cache ------------------------------------------------------

    def cached_program_keys(self) -> list[tuple]:
        return list(self._programs.keys())

    def _program(self, key: tuple, example_args: tuple):
        cached = self._programs.get(key)
        if cached is not None:
            self._programs.move_to_end(key)
            self._bump("device.engine.bucket_hits")
            return cached
        self._bump("device.engine.bucket_misses")
        t0 = time.perf_counter()
        _topo, _sb, n_words, n_sweeps, small, use_link_metric = key
        fn = _forward_body(small, use_link_metric, n_sweeps, n_words)
        # The packed (small) shape concatenates everything into one 1-D
        # int32 vector, so no output can alias the [N_cap, S] scratch —
        # requesting donation there would be silently dropped.  The full
        # shape returns dist in the donated layout and is aliased.
        donate = () if small else (0,)
        # AOT: lower+compile now so the jit cache never owns the
        # executable — LRU eviction below genuinely frees it
        compiled = (
            jax.jit(fn, donate_argnums=donate)
            .lower(*example_args)
            .compile()
        )
        self._program_specs[key] = (
            fn,
            jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                example_args,
            ),
            donate,
        )
        self._bump("device.engine.compiles")
        self._bump(
            "device.engine.compile_us",
            int((time.perf_counter() - t0) * 1e6),
        )
        self._programs[key] = compiled
        while len(self._programs) > self.max_programs:
            self._programs.popitem(last=False)
            self._bump("device.engine.evictions")
        return compiled

    # -- queries ------------------------------------------------------------

    def spf_results(
        self,
        csr,
        sources: list,
        use_link_metric: bool = True,
        expect_epoch: Optional[int] = None,
    ):
        """Full production pipeline through residency: distances + SP-DAG
        + bit-packed first hops -> reference-shaped SpfResults.  Same
        contract as CsrTopology.spf_from, minus the per-call staging.

        `expect_epoch` pins the csr.version the caller coalesced against:
        if the topology moved since, the query raises EpochMismatchError
        *before* any device work, so batched callers never receive routes
        computed over a topology older than the one they observed."""
        if self.fault_hook is not None:
            self.fault_hook("spf")
        if expect_epoch is not None and int(csr.version) != int(expect_epoch):
            self._bump("device.engine.epoch_invalidations")
            raise EpochMismatchError(int(expect_epoch), int(csr.version))
        if not sources:
            return {}
        tr = _trace.TRACE
        if tr is not None:
            # rung taken by a serving dispatch: the warm path is "spf";
            # sync() upgrades it to restage/rewire/incremental when the
            # residency actually moved under this query
            tr.annotate("engine.rung", "spf")
        t_query = time.perf_counter()
        bytes_before = self.counters["device.engine.bytes_staged"]
        res = self.sync(csr)

        src_ids = np.asarray(
            [csr.node_id[s] for s in sources], dtype=np.int32
        )
        s = len(sources)
        s_bucket = _s_bucket(s)
        if s_bucket > s:
            # pad with the first source: pad rows compute real (discarded)
            # results, so the convergence verdict stays meaningful
            src_ids = np.concatenate(
                [src_ids, np.full(s_bucket - s, src_ids[0], np.int32)]
            )
        # topology-wide word count (not per-batch): keeps the program key
        # stable across source sets; unset high words decode to no bits
        n_words = max(1, -(-csr.max_out_slots // 32))
        n_cap = csr.node_capacity
        small = s_bucket * n_cap <= self.small_threshold

        t0 = time.perf_counter()
        while True:
            n_sweeps = res.sweep_hint
            key = (
                res.topo_key,
                s_bucket,
                n_words,
                n_sweeps,
                small,
                use_link_metric,
            )
            src_dev = jax.device_put(src_ids)
            self._bump("device.engine.bytes_staged", _nbytes(src_ids))
            dist0_T = _dist0_T_device(
                src_dev, res.ell.new_of_old, n_cap
            )
            args = (
                dist0_T,
                src_dev,
                res.ell,
                res.edge_src,
                res.edge_dst,
                res.edge_metric,
                res.edge_up,
                res.node_overloaded,
                res.out_slot,
            )
            compiled = self._program(key, args)
            out = compiled(*args)
            # every fetch below is an explicit device_get: the engine's
            # dispatch paths run under the transfer-guard sanitizer, which
            # disallows implicit host round-trips
            if small:
                packed = jax.device_get(out)
                converged = packed[-1] == 1
            else:
                dist_j, dag_j, nh_j, ok_j = out
                converged = bool(jax.device_get(ok_j))
            if converged:
                break
            res.sweep_hint = n_sweeps * 2
            # share the learned relax depth with the host-staged path
            csr._sweep_hint = res.sweep_hint
        if small:
            n_dist = s_bucket * n_cap
            n_dag = s_bucket * csr.edge_capacity
            dist = packed[:n_dist].reshape(s_bucket, n_cap)
            dag = (
                packed[n_dist : n_dist + n_dag].reshape(
                    s_bucket, csr.edge_capacity
                )
                != 0
            )
            nh = (
                packed[n_dist + n_dag : -1]
                .view(np.uint32)
                .reshape(s_bucket, n_cap, n_words)
            )
        else:
            # one batched fetch; dist comes back in the donated [N_cap, S]
            # layout (see _forward_body) and is transposed host-side
            dist_T, dag, nh = jax.device_get((dist_j, dag_j, nh_j))
            dist = dist_T.T
        self._bump(
            "device.engine.dispatch_us",
            int((time.perf_counter() - t0) * 1e6),
        )
        self._bump("device.engine.queries")
        self.last_query_bytes = (
            self.counters["device.engine.bytes_staged"] - bytes_before
        )
        self.last_query_us = int((time.perf_counter() - t_query) * 1e6)
        return csr.to_spf_results(sources, dist[:s], dag[:s], nh[:s])

    def dispatch(self, op: str, fn: Callable, *args, **kwargs):
        """Generic dispatch front-end for device work that is not an SPF
        query (fleet product, KSP re-runs): routes through the chaos
        fault hook and the dispatch accounting without changing the
        callee's contract."""
        if self.fault_hook is not None:
            self.fault_hook(op)
        t0 = time.perf_counter()
        try:
            return fn(*args, **kwargs)
        finally:
            self._bump("device.engine.dispatches")
            self._bump(
                "device.engine.dispatch_us",
                int((time.perf_counter() - t0) * 1e6),
            )

    def run_pallas(self, kind: str, pallas_thunk, xla_thunk):
        """Engine face of the Pallas demotion contract
        (ops.pallas_kernels.run_with_fallback): binds this engine's
        counter and chaos seams so every launch, demotion and skip is
        accounted under `device.engine.pallas_*`, and an armed
        `engine:pallas` chaos fault demotes through the same path a
        real Pallas failure takes."""
        from ..ops import pallas_kernels as pk

        tr = _trace.TRACE
        if tr is None:
            return pk.run_with_fallback(
                kind,
                pallas_thunk,
                xla_thunk,
                counters=self.counters,
                fault_hook=self.fault_hook,
                mode=self.pallas_mode,
            )
        falls0 = self.counters.get("device.engine.pallas_fallbacks", 0)
        out = pk.run_with_fallback(
            kind,
            pallas_thunk,
            xla_thunk,
            counters=self.counters,
            fault_hook=self.fault_hook,
            mode=self.pallas_mode,
        )
        demoted = (
            self.counters.get("device.engine.pallas_fallbacks", 0) > falls0
        )
        if self.pallas_mode == "off":
            kernel = "xla"
        elif demoted:
            kernel = "fallback"
        else:
            kernel = "pallas"
        tr.annotate("engine.kernel", f"{kind}:{kernel}")
        return out

    # -- delta rung ----------------------------------------------------------

    def delta_bucket(self, n_cols: int, p: int) -> Optional[int]:
        """Padded slab width for an affected frontier of `n_cols` columns
        out of a `p`-wide product, or None when the frontier bound is
        exceeded (bucket >= p, or the frontier covers more than half the
        product — at that point the full fused product is cheaper and is
        the bit-exact fallback the caller must take)."""
        if n_cols <= 0:
            return None
        if 2 * n_cols > p:
            self._bump("device.engine.delta_overflow_fallbacks")
            return None
        for b in DELTA_P_BUCKETS:
            if n_cols <= b:
                if b >= p:
                    self._bump("device.engine.delta_overflow_fallbacks")
                    return None
                return b
        self._bump("device.engine.delta_overflow_fallbacks")
        return None

    def delta_register(self, nbytes: int) -> None:
        """Account the one full product upload a delta sequence starts
        from — the acceptance invariant is full_restages == 1 across a
        whole storm, everything after rides the donated delta slabs."""
        self._bump("device.engine.full_restages")
        self._bump("device.engine.bytes_staged", int(nbytes))

    def delta_dispatch(
        self,
        op: str,
        fn: Callable,
        *args,
        csr=None,
        expect_epoch: Optional[int] = None,
        bucket_key: Optional[tuple] = None,
        **kwargs,
    ):
        """Dispatch front-end for the incremental delta rung.

        Same chaos-hook + timing contract as `dispatch`, plus: an epoch
        pin (`expect_epoch` against `csr.version`, checked BEFORE device
        work so the serving coalescer's retry loop composes — a flap
        between coalescing and dispatch re-coalesces instead of relaxing
        a stale frontier) and bucket-ladder accounting (`bucket_key`
        identifies the compiled-program cell; first sighting is a miss =
        a compile, repeats are hits)."""
        if self.fault_hook is not None:
            self.fault_hook("delta_" + op)
        if (
            expect_epoch is not None
            and csr is not None
            and int(csr.version) != int(expect_epoch)
        ):
            self._bump("device.engine.epoch_invalidations")
            raise EpochMismatchError(int(expect_epoch), int(csr.version))
        if bucket_key is not None:
            if bucket_key in self._delta_buckets_seen:
                self._bump("device.engine.delta_bucket_hits")
            else:
                self._delta_buckets_seen.add(bucket_key)
                self._bump("device.engine.delta_bucket_misses")
        tr = _trace.TRACE
        if tr is not None:
            tr.annotate("engine.rung", "delta")
        t0 = time.perf_counter()
        try:
            return fn(*args, **kwargs)
        finally:
            self._bump("device.engine.delta_dispatches")
            self._bump(
                "device.engine.delta_dispatch_us",
                int((time.perf_counter() - t0) * 1e6),
            )
