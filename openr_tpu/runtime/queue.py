"""Inter-module message queues.

Functional equivalent of the reference's messaging layer
(openr/messaging/Queue.h:36-129, openr/messaging/ReplicateQueue.h:23):

- RWQueue — unbounded MPMC blocking queue; sync get() suspends the calling
  thread, async aget() suspends the calling asyncio task (the stand-in for the
  reference's fiber suspension).
- RQueue — read-only view handed to consumers.
- ReplicateQueue — single writer fans out to N per-reader queues; readers are
  created on demand and each sees every message pushed after creation.

Thread-safety: push/get may be called from any thread; aget() from any event
loop.  Async waiters are woken via call_soon_threadsafe and re-try the pop, so
no item is ever reserved for a waiter that got cancelled.
"""

from __future__ import annotations

import asyncio
import threading
from collections import deque
from typing import Any, Callable, Generic, Iterable, Optional, TypeVar

from ..analysis import race as _race
from ..analysis import sched as _sched
from ..obs import trace as _trace

T = TypeVar("T")


class QueueClosedError(RuntimeError):
    pass


class RQueue(Generic[T]):
    """Read interface (reference: RQueue openr/messaging/Queue.h:36)."""

    def __init__(self, impl: "RWQueue[T]") -> None:
        self._impl = impl

    def get(self, timeout: Optional[float] = None) -> T:
        return self._impl.get(timeout)

    async def aget(self) -> T:
        return await self._impl.aget()

    def try_get(self) -> Optional[T]:
        return self._impl.try_get()

    def size(self) -> int:
        return self._impl.size()

    def is_closed(self) -> bool:
        return self._impl.is_closed()

    def close(self) -> None:
        """Reader-side close: unblocks pending get()s with
        QueueClosedError; a ReplicateQueue prunes the dead reader on its
        next push (reference: dead-reader handling in ReplicateQueue)."""
        self._impl.close()


class RWQueue(Generic[T]):
    def __init__(
        self,
        maxlen: Optional[int] = None,
        on_shed: Optional[Callable[[T], None]] = None,
    ) -> None:
        self._items: deque[T] = deque()
        self._maxlen = maxlen
        # called with each item dropped by the bounded-queue overflow
        # policy, OUTSIDE the queue lock — lets owners turn a silent
        # drop-oldest into an explicit per-item error (serving layer)
        self._on_shed = on_shed
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False
        self._async_waiters: list[tuple[asyncio.AbstractEventLoop, asyncio.Future]] = []
        self._num_pushed = 0
        self._num_read = 0
        self._num_overflows = 0
        # OPENR_TSAN: per-item HB tokens mirroring _items (put -> matching
        # get).  None until the detector is first armed; kept positionally
        # aligned under _lock.
        self._tsan_tokens: Optional[deque] = None
        # OPENR_TRACE: per-item span-scope tokens, same discipline — the
        # pushing thread's active trace scope rides next to the item and
        # is re-adopted by whichever consumer pops it.
        self._obs_tokens: Optional[deque] = None

    # -- write side ---------------------------------------------------------

    def push(self, item: T) -> bool:
        sc = _sched.SCHED
        if sc is not None:
            # OPENR_SCHED: declare the push as a yield point (same seam the
            # TSAN put-token rides); no-op for uncontrolled threads
            sc.queue_op(self, "queue.push")
        shed: Optional[T] = None
        with self._lock:
            if self._closed:
                return False
            det = _race.TSAN
            if det is not None:
                toks = self._tsan_tokens
                if toks is None or len(toks) != len(self._items):
                    # first armed push, or items enqueued while disarmed:
                    # realign with null tokens (no HB claimed for those)
                    toks = self._tsan_tokens = deque([None] * len(self._items))
            tr = _trace.TRACE
            if tr is not None:
                otoks = self._obs_tokens
                if otoks is None or len(otoks) != len(self._items):
                    # items enqueued while disarmed carry no trace context
                    otoks = self._obs_tokens = deque([None] * len(self._items))
            if self._maxlen is not None and len(self._items) >= self._maxlen:
                # bounded queue: shed the OLDEST item (routing deltas are
                # superseded by later state; blocking the producer would
                # wedge the pushing module's event base instead)
                shed = self._items.popleft()
                self._num_overflows += 1
                if det is not None:
                    toks.popleft()
                if tr is not None:
                    otoks.popleft()
            self._items.append(item)
            if det is not None:
                toks.append(det.publish_token())
            if tr is not None:
                otoks.append(tr.carry())
            self._num_pushed += 1
            self._cond.notify()
            waiters, self._async_waiters = self._async_waiters, []
        self._wake(waiters)
        if shed is not None and self._on_shed is not None:
            # outside the lock: shed handlers complete caller futures,
            # whose done-callbacks must never run under the queue lock
            self._on_shed(shed)
        return True

    def close(self) -> None:
        sc = _sched.SCHED
        if sc is not None:
            sc.queue_op(self, "queue.close")
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
            waiters, self._async_waiters = self._async_waiters, []
        self._wake(waiters)

    @staticmethod
    def _wake(waiters: Iterable[tuple[asyncio.AbstractEventLoop, asyncio.Future]]) -> None:
        for loop, fut in waiters:
            try:
                loop.call_soon_threadsafe(
                    lambda f=fut: f.done() or f.set_result(None)
                )
            except RuntimeError:
                pass  # loop already closed

    # -- read side ----------------------------------------------------------

    def _tsan_join(self) -> None:
        """OPENR_TSAN: join the head item's put token (called under _lock,
        immediately before the matching _items.popleft())."""
        toks = self._tsan_tokens
        if toks is not None and len(toks) == len(self._items):
            tok = toks.popleft()
            det = _race.TSAN
            if det is not None and tok is not None:
                det.acquire_token(tok)

    def _obs_take(self) -> None:
        """OPENR_TRACE: pop the head item's carried trace scope (called
        under _lock, immediately before the matching _items.popleft());
        the popping thread IS the consumer, so stashing it thread-local
        hands it to the adoption point right after get() returns."""
        otoks = self._obs_tokens
        if otoks is not None and len(otoks) == len(self._items):
            tok = otoks.popleft()
            tr = _trace.TRACE
            if tr is not None:
                # set unconditionally (tok may be None): a pop must
                # CLEAR any stale carried token from an earlier pop on
                # this thread, or a later adopter would mis-attribute
                tr.set_carried(tok)

    def get(self, timeout: Optional[float] = None) -> T:
        sc = _sched.SCHED
        if sc is not None and sc.queue_get_gate(
            self, lambda: bool(self._items) or self._closed
        ):
            # OPENR_SCHED serialized path: the gate granted us only once an
            # item was available or the queue closed, and no other task can
            # run between the grant and this pop (cond.wait would block the
            # single-token world instead)
            with self._lock:
                if self._items:
                    self._num_read += 1
                    if self._tsan_tokens is not None:
                        self._tsan_join()
                    if self._obs_tokens is not None:
                        self._obs_take()
                    return self._items.popleft()
                raise QueueClosedError("queue closed")
        with self._cond:
            if not self._cond.wait_for(
                lambda: self._items or self._closed, timeout=timeout
            ):
                raise TimeoutError("queue get timed out")
            if self._items:
                self._num_read += 1
                if self._tsan_tokens is not None:
                    self._tsan_join()
                if self._obs_tokens is not None:
                    self._obs_take()
                return self._items.popleft()
            raise QueueClosedError("queue closed")

    def try_get(self) -> Optional[T]:
        sc = _sched.SCHED
        if sc is not None:
            sc.queue_op(self, "queue.get")
        with self._lock:
            if self._items:
                self._num_read += 1
                if self._tsan_tokens is not None:
                    self._tsan_join()
                if self._obs_tokens is not None:
                    self._obs_take()
                return self._items.popleft()
            if self._closed:
                raise QueueClosedError("queue closed")
            return None

    async def aget(self) -> T:
        while True:
            loop = asyncio.get_running_loop()
            with self._lock:
                if self._items:
                    self._num_read += 1
                    if self._tsan_tokens is not None:
                        self._tsan_join()
                    if self._obs_tokens is not None:
                        self._obs_take()
                    return self._items.popleft()
                if self._closed:
                    raise QueueClosedError("queue closed")
                fut: asyncio.Future = loop.create_future()
                self._async_waiters.append((loop, fut))
            try:
                await fut
            except asyncio.CancelledError:
                with self._lock:
                    self._async_waiters = [
                        (l, f) for (l, f) in self._async_waiters if f is not fut
                    ]
                raise

    # -- introspection ------------------------------------------------------

    def size(self) -> int:
        with self._lock:
            return len(self._items)

    def is_closed(self) -> bool:
        with self._lock:
            return self._closed

    def get_reader(self) -> RQueue[T]:
        return RQueue(self)

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "size": len(self._items),
                "num_pushed": self._num_pushed,
                "num_read": self._num_read,
                # canonical overflow spelling is `overflows` (matches the
                # exported queue.<name>.overflows counter; counter-duplicate
                # rule keeps the two stats surfaces from diverging again)
                "overflows": self._num_overflows,
            }


class ReplicateQueue(Generic[T]):
    """One writer, N reader queues (reference:
    openr/messaging/ReplicateQueue.h:23)."""

    def __init__(self, maxlen: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._readers: list[RWQueue[T]] = []
        self._closed = False
        self._num_writes = 0
        self._maxlen = maxlen  # applied to each per-reader queue

    def push(self, item: T) -> bool:
        sc = _sched.SCHED
        if sc is not None:
            # OPENR_SCHED: the fan-out itself is a yield point; each
            # per-reader RWQueue.push below declares its own op too
            sc.queue_op(self, "queue.push")
        with self._lock:
            if self._closed:
                return False
            # prune readers that were individually closed (dead consumers)
            self._readers = [q for q in self._readers if not q.is_closed()]
            readers = list(self._readers)
            self._num_writes += 1
        for q in readers:
            q.push(item)
        return True

    def get_reader(self) -> RQueue[T]:
        with self._lock:
            if self._closed:
                raise QueueClosedError("replicate queue closed")
            q: RWQueue[T] = RWQueue(maxlen=self._maxlen)
            self._readers.append(q)
            return RQueue(q)

    def close_reader(self, reader: RQueue[T]) -> None:
        """Detach one consumer: its queue is closed and pruned on next push
        (reference culls dead readers at push time,
        openr/messaging/ReplicateQueue.h)."""
        with self._lock:
            impl = reader._impl
            self._readers = [q for q in self._readers if q is not impl]
        impl.close()

    def get_num_readers(self) -> int:
        with self._lock:
            return len(self._readers)

    def get_num_writes(self) -> int:
        with self._lock:
            return self._num_writes

    def stats(self) -> dict[str, int]:
        """Aggregated reader stats: depth is the deepest per-reader
        backlog (the consumer the producers are actually waiting on)."""
        with self._lock:
            readers = [q for q in self._readers if not q.is_closed()]
            writes = self._num_writes
        depth = 0
        overflows = 0
        for q in readers:
            st = q.stats()
            depth = max(depth, st["size"])
            overflows += st["overflows"]
        return {
            "depth": depth,
            "writes": writes,
            "overflows": overflows,
            "readers": len(readers),
        }

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            readers = list(self._readers)
        for q in readers:
            q.close()


def queue_counters(queues: dict[str, "ReplicateQueue"]) -> dict[str, int]:
    """fb303-style counters for a named set of replicate queues (the
    daemon's inter-module fabric): queue.<name>.{depth,writes,overflows,
    readers}.  Overflow is the first thing chaos runs surface — a
    consumer wedged behind a fault shows up here before anywhere else."""
    out: dict[str, int] = {}
    for name, queue in queues.items():
        for key, val in queue.stats().items():
            out[f"queue.{name}.{key}"] = val
    return out
