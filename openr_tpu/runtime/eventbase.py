"""Module runtime: one thread + one asyncio event loop per module.

Functional equivalent of the reference's OpenrEventBase
(openr/common/OpenrEventBase.h:28) — every framework module extends this and
runs in its own thread (reference: startEventBase, openr/Main.cpp:132-163).
Fibers become asyncio tasks; timers become loop timers; the health timestamp
feeds the Watchdog exactly like getTimestamp() (OpenrEventBase.h:74).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import logging
import threading
import time
from typing import Any, Awaitable, Callable, Coroutine, Optional

from ..analysis import race as _race
from ..analysis import sched as _sched
from ..obs import trace as _trace

log = logging.getLogger(__name__)


def _tsan_handoff(fn: Callable[..., Any]) -> Callable[..., Any]:
    """OPENR_TSAN: wrap a closure about to be marshalled to another thread
    (call_soon_threadsafe and friends) with a happens-before handoff edge.
    Identity when disarmed — a single module-attribute load."""
    det = _race.TSAN
    return fn if det is None else det.wrap_handoff(fn)


def _obs_handoff(fn: Callable[..., Any]) -> Callable[..., Any]:
    """OPENR_TRACE: carry the caller's active span scope across the same
    thread handoff, so work marshalled onto a module loop keeps its
    trace attribution.  Identity when disarmed (one attribute load) or
    when the caller has no active scope."""
    tr = _trace.TRACE
    return fn if tr is None else tr.bind_scope(fn)


def _sched_submit(eb: "OpenrEventBase") -> None:
    """OPENR_SCHED: a cross-thread submit (run_in_event_base_thread /
    add_fiber_task / schedule_timeout marshalling) is a yield point for
    controlled tasks.  One module-attribute load when disarmed."""
    sc = _sched.SCHED
    if sc is not None:
        sc.handoff(eb)


def _handoff(fn: Callable[..., Any]) -> Callable[..., Any]:
    """Compose the cross-thread wrappers (trace innermost so the TSAN
    handoff edge brackets the whole marshalled closure)."""
    return _tsan_handoff(_obs_handoff(fn))


class Timeout:
    """Cancellable cross-thread timer token returned by
    OpenrEventBase.schedule_timeout."""

    def __init__(self) -> None:
        self._handle: Optional[asyncio.TimerHandle] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._cancelled = False
        self._lock = threading.Lock()

    def _arm(
        self, loop: asyncio.AbstractEventLoop, delay_s: float, fn: Callable[[], Any]
    ) -> None:
        with self._lock:
            if self._cancelled:
                return
            self._loop = loop
            self._handle = loop.call_later(delay_s, fn)

    def cancel(self) -> None:
        """Cancel from any thread.  If the timer already fired, this is a
        no-op (cross-thread cancellation is inherently racy; callbacks should
        tolerate one late firing)."""
        with self._lock:
            self._cancelled = True
            handle, loop = self._handle, self._loop
            self._handle = None
        if handle is not None and loop is not None:
            try:
                loop.call_soon_threadsafe(handle.cancel)
            except RuntimeError:
                pass  # loop closed


class OpenrEventBase:
    def __init__(self, name: str = "") -> None:
        self.name = name or type(self).__name__
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._stopped = threading.Event()
        self._stop_once = threading.Lock()
        self._stop_called = False
        self._tasks: set[asyncio.Task] = set()
        self._timestamp = time.monotonic()

    # -- lifecycle ----------------------------------------------------------

    def run(self) -> None:
        """Start the module thread and event loop; returns once running."""
        if self._thread is not None:
            raise RuntimeError(f"{self.name} already started")
        self._thread = threading.Thread(target=self._thread_main, name=self.name)
        self._thread.daemon = True
        self._thread.start()
        self._started.wait()

    def _thread_main(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        try:
            try:
                self._track(
                    loop.create_task(self._heartbeat(), name=f"{self.name}-heartbeat")
                )
                init = getattr(self, "prepare", None)
                if init is not None:
                    task = loop.create_task(init(), name=f"{self.name}-prepare")
                    self._track(task)
            finally:
                # never leave run() parked on _started if startup raised
                self._started.set()
            loop.run_forever()
            # drain: cancel outstanding tasks
            for task in list(self._tasks):
                task.cancel()
            pending = [t for t in self._tasks if not t.done()]
            if pending:
                loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True)
                )
        finally:
            loop.close()
            self._stopped.set()

    async def _heartbeat(self) -> None:
        while True:
            self._timestamp = time.monotonic()
            await asyncio.sleep(0.1)

    def stop(self) -> None:
        """Stop the loop and join the thread (callable from any thread;
        idempotent — later callers just wait for the first stop to finish)."""
        if self._loop is None:
            return
        with self._stop_once:
            first = not self._stop_called
            self._stop_called = True
        if not first:
            if threading.current_thread() is not self._thread:
                self.wait_until_stopped()
            return
        stopping = getattr(self, "stopping", None)

        def _do_stop() -> None:
            async def _graceful():
                if stopping is not None:
                    try:
                        await stopping()
                    except Exception:
                        log.exception("%s: stopping() hook failed", self.name)
                self._loop.stop()

            self._loop.create_task(_graceful())

        try:
            self._loop.call_soon_threadsafe(_handoff(_do_stop))
        except RuntimeError:
            return
        # Joining from the module's own loop thread would deadlock (the loop
        # must keep running to execute _do_stop); the stop is then async.
        if threading.current_thread() is not self._thread:
            self.wait_until_stopped()

    def wait_until_running(self, timeout: Optional[float] = None) -> bool:
        return self._started.wait(timeout)

    def wait_until_stopped(self, timeout: Optional[float] = None) -> bool:
        if self._thread is None:
            return True  # never started (e.g. startup aborted mid-way)
        ok = self._stopped.wait(timeout)
        if ok:
            self._thread.join()
        return ok

    @property
    def is_running(self) -> bool:
        return self._started.is_set() and not self._stopped.is_set()

    # -- task / timer API (reference: addFiberTask :47, scheduleTimeout) ----

    def _track(self, task: asyncio.Task) -> None:
        self._tasks.add(task)

        def _done(t: asyncio.Task) -> None:
            self._tasks.discard(t)
            if not t.cancelled():
                exc = t.exception()
                if exc is not None and not isinstance(exc, asyncio.CancelledError):
                    log.exception(
                        "%s: task %s crashed", self.name, t.get_name(), exc_info=exc
                    )

        task.add_done_callback(_done)

    def add_fiber_task(self, coro: Coroutine[Any, Any, Any], name: str = "") -> None:
        """Schedule a long-running coroutine on this module's loop (from any
        thread). Reference: addFiberTask, OpenrEventBase.h:47."""
        assert self._loop is not None, f"{self.name} not started"
        _sched_submit(self)

        def _create() -> None:
            self._track(self._loop.create_task(coro, name=name or "fiber"))

        self._loop.call_soon_threadsafe(_handoff(_create))

    def in_event_base_thread(self) -> bool:
        return threading.current_thread() is self._thread

    def run_in_event_base_thread(
        self, fn: Callable[[], Any]
    ) -> "concurrent.futures.Future[Any]":
        """Marshal a call onto this module's thread and return a future for
        the result.  Reference pattern: runInEventBaseThread + SemiFuture
        (openr/decision/Decision.cpp:1513) — the cross-thread RPC mechanism.
        Re-entrant: from the owning thread the call runs inline (blocking on
        the future there would deadlock the loop)."""
        assert self._loop is not None, f"{self.name} not started"
        _sched_submit(self)
        fut: concurrent.futures.Future = concurrent.futures.Future()
        if self.in_event_base_thread():
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)
            return fut

        def _call() -> None:
            if not fut.set_running_or_notify_cancel():
                return
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        self._loop.call_soon_threadsafe(_handoff(_call))
        return fut

    async def run_async(self, coro: Awaitable[Any]) -> Any:
        """Await a coroutine on this module's loop from another loop/thread."""
        return await asyncio.wrap_future(self.run_coroutine(coro))

    def run_coroutine(self, coro: Awaitable[Any]) -> "concurrent.futures.Future[Any]":
        assert self._loop is not None
        det = _race.TSAN
        if det is not None:
            # forward edge: caller -> coroutine body on the module loop.
            # The return edge needs no wrap — wrap_future/result() observe
            # the patched Future resolve token.
            coro = det.wrap_coro(coro)
        return asyncio.run_coroutine_threadsafe(coro, self._loop)

    def schedule_timeout(self, delay_s: float, fn: Callable[[], Any]) -> "Timeout":
        """Schedule fn after delay on this module's loop; returns a
        cancellable token (Spark-style hold timers reset constantly)."""
        assert self._loop is not None
        _sched_submit(self)
        token = Timeout()
        self._loop.call_soon_threadsafe(
            _handoff(token._arm), self._loop, delay_s, _handoff(fn)
        )
        return token

    # -- watchdog interface (reference: getTimestamp, OpenrEventBase.h:74) --

    def get_timestamp(self) -> float:
        return self._timestamp
