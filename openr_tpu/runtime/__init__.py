from .queue import QueueClosedError, ReplicateQueue, RQueue, RWQueue
from .eventbase import OpenrEventBase
from .async_util import AsyncDebounce, AsyncThrottle

__all__ = [
    "QueueClosedError",
    "RWQueue",
    "RQueue",
    "ReplicateQueue",
    "OpenrEventBase",
    "AsyncDebounce",
    "AsyncThrottle",
]
