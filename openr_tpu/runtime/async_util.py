"""Debounce / throttle primitives for batching bursty work.

Functional equivalents of the reference's AsyncDebounce
(openr/common/AsyncDebounce.h:27 — used by Decision to batch KvStore
publications before an SPF rebuild with min/max 10ms/250ms, openr/Main.cpp:526)
and AsyncThrottle (openr/common/AsyncThrottle.h:33).

Both are single-loop objects: call them only from the owning module's loop.
"""

from __future__ import annotations

import asyncio
from typing import Any, Callable, Optional


class AsyncDebounce:
    """Invoke -> callback fires after backoff_min; further invocations while
    pending double the wait (measured from the first invocation), capped at
    backoff_max.  A burst of updates thus coalesces into one callback no later
    than backoff_max after the burst began."""

    def __init__(
        self,
        backoff_min_s: float,
        backoff_max_s: float,
        callback: Callable[[], Any],
    ) -> None:
        if backoff_min_s <= 0 or backoff_max_s < backoff_min_s:
            raise ValueError("invalid debounce bounds")
        self._min = backoff_min_s
        self._max = backoff_max_s
        self._callback = callback
        self._handle: Optional[asyncio.TimerHandle] = None
        self._current_backoff = 0.0
        self._first_call_ts = 0.0

    def __call__(self) -> None:
        loop = asyncio.get_running_loop()
        now = loop.time()
        if self._handle is None:
            self._current_backoff = self._min
            self._first_call_ts = now
            self._handle = loop.call_at(now + self._min, self._fire)
        else:
            self._current_backoff = min(self._current_backoff * 2, self._max)
            deadline = self._first_call_ts + self._current_backoff
            # once capped, the deadline stops moving — don't churn the timer
            if deadline > now and deadline != self._handle.when():
                self._handle.cancel()
                self._handle = loop.call_at(deadline, self._fire)

    def _fire(self) -> None:
        self._handle = None
        self._current_backoff = 0.0
        self._callback()

    def is_scheduled(self) -> bool:
        return self._handle is not None

    def cancel(self) -> None:
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None
            self._current_backoff = 0.0


class AsyncThrottle:
    """Invoke -> callback fires after `timeout`; invocations while pending are
    absorbed into that single firing (reference: AsyncThrottle.h:33)."""

    def __init__(self, timeout_s: float, callback: Callable[[], Any]) -> None:
        self._timeout = timeout_s
        self._callback = callback
        self._handle: Optional[asyncio.TimerHandle] = None

    def __call__(self) -> None:
        if self._handle is not None:
            return
        loop = asyncio.get_running_loop()
        if self._timeout <= 0:
            self._callback()
            return
        self._handle = loop.call_later(self._timeout, self._fire)

    def _fire(self) -> None:
        self._handle = None
        self._callback()

    def is_active(self) -> bool:
        return self._handle is not None

    def cancel(self) -> None:
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None
