"""Interop surfaces toward stock openr peers (thrift binary codec + shim)."""

from .thrift_binary import (  # noqa: F401
    ADJACENCY,
    ADJACENCY_DATABASE,
    BINARY_ADDRESS,
    KEY_DUMP_PARAMS,
    KEY_GET_PARAMS,
    KEY_SET_PARAMS,
    PEER_SPEC,
    PERF_EVENT,
    PERF_EVENTS,
    PUBLICATION,
    VALUE,
    decode_message,
    decode_struct,
    encode_message,
    encode_struct,
    frame,
)
from .shim import ThriftBinaryShim  # noqa: F401
