"""Thrift Binary-protocol codec for the KvStore-facing wire structs.

The reference's control plane speaks fbthrift binary RPC
(openr/if/OpenrCtrl.thrift:204); this build's native framing is
NDJSON-RPC (docs/ARCHITECTURE.md decision record).  This module is the
round-5 interop spike closing the first half of that gap: a
table-driven *thrift Binary protocol* encoder/decoder for the ~10
structs a stock KvStore peer or client touches, plus the strict message
envelope and framed transport, so the daemon can answer a thrift-binary
`getKvStoreKeyVals(filterKeys)` call on the wire (interop.shim).

Field ids and types are transcribed from the reference IDL (cited per
spec below); tests pin hand-computed golden byte vectors so the
encoding cannot drift from the IDL silently.

Thrift Binary protocol (the stable, documented wire format):
  field   = [ttype:u8][field-id:i16 BE][value]; struct ends with 0x00
  i16/i32/i64 = big-endian two's complement; bool = u8; double = BE f64
  string/binary = [len:i32][bytes]
  map  = [ktype:u8][vtype:u8][count:i32][k v ...]
  set/list = [etype:u8][count:i32][elems...]
  strict message = [0x8001:u16][0x00][mtype:u8][name:string][seqid:i32]
Framed transport = [frame-len:i32 BE][message bytes].
"""

from __future__ import annotations

import dataclasses
import ipaddress
import struct as _s
from io import BytesIO
from typing import Any, Optional

from .. import types as T

# thrift type ids
T_STOP = 0
T_BOOL = 2
T_BYTE = 3
T_DOUBLE = 4
T_I16 = 6
T_I32 = 8
T_I64 = 10
T_STRING = 11
T_STRUCT = 12
T_MAP = 13
T_SET = 14
T_LIST = 15

# message types
MSG_CALL = 1
MSG_REPLY = 2
MSG_EXCEPTION = 3

_STRICT_VERSION = 0x80010000


class ThriftError(ValueError):
    pass


# ---------------------------------------------------------------------------
# primitive writer / reader
# ---------------------------------------------------------------------------


class _Writer:
    def __init__(self) -> None:
        self.b = BytesIO()

    def u8(self, v: int) -> None:
        self.b.write(_s.pack("!B", v))

    def i16(self, v: int) -> None:
        self.b.write(_s.pack("!h", v))

    def i32(self, v: int) -> None:
        self.b.write(_s.pack("!i", v))

    def u32(self, v: int) -> None:
        self.b.write(_s.pack("!I", v))

    def i64(self, v: int) -> None:
        self.b.write(_s.pack("!q", v))

    def double(self, v: float) -> None:
        self.b.write(_s.pack("!d", v))

    def binary(self, v: bytes) -> None:
        self.i32(len(v))
        self.b.write(v)

    def string(self, v: str) -> None:
        self.binary(v.encode("utf-8"))

    def getvalue(self) -> bytes:
        return self.b.getvalue()


class _Reader:
    def __init__(self, data: bytes) -> None:
        self.b = BytesIO(data)

    def _read(self, n: int) -> bytes:
        out = self.b.read(n)
        if len(out) != n:
            raise ThriftError("truncated thrift payload")
        return out

    def u8(self) -> int:
        return _s.unpack("!B", self._read(1))[0]

    def i16(self) -> int:
        return _s.unpack("!h", self._read(2))[0]

    def i32(self) -> int:
        return _s.unpack("!i", self._read(4))[0]

    def i64(self) -> int:
        return _s.unpack("!q", self._read(8))[0]

    def double(self) -> float:
        return _s.unpack("!d", self._read(8))[0]

    def binary(self) -> bytes:
        return self._read(self.i32())

    def string(self) -> str:
        return self.binary().decode("utf-8")


# ---------------------------------------------------------------------------
# type specs (table-driven: ("map", kspec, vspec), ("list", espec),
# ("struct", StructSpec), or a primitive ttype int)
# ---------------------------------------------------------------------------


def _ttype_of(spec) -> int:
    if isinstance(spec, int):
        return spec
    kind = spec[0]
    return {"map": T_MAP, "list": T_LIST, "set": T_SET, "struct": T_STRUCT}[
        kind
    ]


@dataclasses.dataclass(frozen=True)
class Field:
    fid: int
    name: str  # attribute on our dataclass
    spec: Any
    optional: bool = False  # unset (None) optionals are not emitted
    # encode/decode value adapters (e.g. string IP <-> BinaryAddress)
    enc: Any = None
    dec: Any = None
    default: Any = None  # value when the field is absent on decode


@dataclasses.dataclass(frozen=True)
class StructSpec:
    name: str
    cls: Any  # our dataclass (or None: decode to dict)
    fields: tuple[Field, ...]

    def field_by_id(self, fid: int) -> Optional[Field]:
        for f in self.fields:
            if f.fid == fid:
                return f
        return None


def _write_value(w: _Writer, spec, v) -> None:
    if isinstance(spec, int):
        if spec == T_BOOL:
            w.u8(1 if v else 0)
        elif spec == T_BYTE:
            w.u8(v & 0xFF)
        elif spec == T_I16:
            w.i16(v)
        elif spec == T_I32:
            w.i32(int(v))
        elif spec == T_I64:
            w.i64(int(v))
        elif spec == T_DOUBLE:
            w.double(v)
        elif spec == T_STRING:
            if isinstance(v, bytes):
                w.binary(v)
            else:
                w.string(v)
        else:
            raise ThriftError(f"unsupported ttype {spec}")
        return
    kind = spec[0]
    if kind == "struct":
        write_struct(w, spec[1], v)
    elif kind == "list" or kind == "set":
        espec = spec[1]
        w.u8(_ttype_of(espec))
        items = sorted(v) if kind == "set" else list(v)
        w.i32(len(items))
        for item in items:
            _write_value(w, espec, item)
    elif kind == "map":
        kspec, vspec = spec[1], spec[2]
        w.u8(_ttype_of(kspec))
        w.u8(_ttype_of(vspec))
        w.i32(len(v))
        for key in sorted(v):
            _write_value(w, kspec, key)
            _write_value(w, vspec, v[key])
    else:
        raise ThriftError(f"unsupported spec {spec!r}")


def _read_value(r: _Reader, spec):
    if isinstance(spec, int):
        if spec == T_BOOL:
            return r.u8() != 0
        if spec == T_BYTE:
            return r.u8()
        if spec == T_I16:
            return r.i16()
        if spec == T_I32:
            return r.i32()
        if spec == T_I64:
            return r.i64()
        if spec == T_DOUBLE:
            return r.double()
        if spec == T_STRING:
            return r.binary()
        raise ThriftError(f"unsupported ttype {spec}")
    kind = spec[0]
    if kind == "struct":
        return read_struct(r, spec[1])
    if kind in ("list", "set"):
        espec = spec[1]
        etype = r.u8()
        if etype != _ttype_of(espec):
            raise ThriftError("list element type mismatch")
        n = r.i32()
        out = [_read_value(r, espec) for _ in range(n)]
        return set(out) if kind == "set" else out
    if kind == "map":
        kspec, vspec = spec[1], spec[2]
        ktype, vtype = r.u8(), r.u8()
        if (ktype, vtype) != (_ttype_of(kspec), _ttype_of(vspec)):
            raise ThriftError("map key/value type mismatch")
        n = r.i32()
        out = {}
        for _ in range(n):
            k = _read_value(r, kspec)
            out[k] = _read_value(r, vspec)
        return out
    raise ThriftError(f"unsupported spec {spec!r}")


def _skip(r: _Reader, ttype: int) -> None:
    """Skip an unknown field (forward compatibility)."""
    if ttype == T_BOOL or ttype == T_BYTE:
        r.u8()
    elif ttype == T_I16:
        r.i16()
    elif ttype == T_I32:
        r.i32()
    elif ttype in (T_I64, T_DOUBLE):
        r.i64()
    elif ttype == T_STRING:
        r.binary()
    elif ttype == T_STRUCT:
        while True:
            ft = r.u8()
            if ft == T_STOP:
                return
            r.i16()
            _skip(r, ft)
    elif ttype in (T_LIST, T_SET):
        et = r.u8()
        for _ in range(r.i32()):
            _skip(r, et)
    elif ttype == T_MAP:
        kt, vt = r.u8(), r.u8()
        for _ in range(r.i32()):
            _skip(r, kt)
            _skip(r, vt)
    else:
        raise ThriftError(f"cannot skip ttype {ttype}")


def write_struct(w: _Writer, spec: StructSpec, obj) -> None:
    for f in spec.fields:
        v = obj.get(f.name) if isinstance(obj, dict) else getattr(obj, f.name)
        if v is None:
            # mirror the decode side: a declared default fills an omitted
            # non-optional field (decoded-domain value, so before enc)
            v = f.default
        if v is not None and f.enc is not None:
            v = f.enc(v)
        if v is None:
            if f.optional:
                continue
            raise ThriftError(f"{spec.name}.{f.name} is required")
        w.u8(_ttype_of(f.spec))
        w.i16(f.fid)
        _write_value(w, f.spec, v)
    w.u8(T_STOP)


def read_struct(r: _Reader, spec: StructSpec):
    values: dict[str, Any] = {}
    while True:
        ttype = r.u8()
        if ttype == T_STOP:
            break
        fid = r.i16()
        f = spec.field_by_id(fid)
        if f is None or _ttype_of(f.spec) != ttype:
            _skip(r, ttype)
            continue
        v = _read_value(r, f.spec)
        if f.dec is not None:
            v = f.dec(v)
        values[f.name] = v
    for f in spec.fields:
        if f.name not in values and f.default is not None:
            d = f.default
            # copy container defaults: the Field objects are shared module
            # constants, and consumers mutate decoded structs in place
            # (e.g. pub.expired_keys.append) — aliasing the spec's default
            # would poison every later decode
            if isinstance(d, (list, set, dict)):
                d = type(d)(d)
            values[f.name] = d
    if spec.cls is None:
        return values
    return spec.cls(**values)


def encode_struct(spec: StructSpec, obj) -> bytes:
    w = _Writer()
    write_struct(w, spec, obj)
    return w.getvalue()


def decode_struct(spec: StructSpec, data: bytes):
    return read_struct(_Reader(data), spec)


# ---------------------------------------------------------------------------
# struct specs for the KvStore-facing types (field ids from the
# reference IDL, cited per struct)
# ---------------------------------------------------------------------------


def _ip_to_binary_addr(ip: Optional[str]) -> Optional[dict]:
    if not ip:
        return None
    return {"addr": ipaddress.ip_address(ip).packed, "if_name": None}


def _binary_addr_to_ip(v) -> str:
    addr = v["addr"] if isinstance(v, dict) else v.addr
    return str(ipaddress.ip_address(addr)) if addr else ""


# openr/if/Network.thrift:56 BinaryAddress {1: binary addr,
# 3: optional string ifName} — decoded to a plain dict
BINARY_ADDRESS = StructSpec(
    "BinaryAddress",
    None,
    (
        Field(1, "addr", T_STRING),
        Field(3, "if_name", T_STRING, optional=True, dec=lambda b: b.decode()),
    ),
)

# openr/if/Types.thrift:555 Value — NOTE the IDL's field-id order
# (1: version, 3: originatorId, 2: optional value, 4: ttl,
# 5: ttlVersion, 6: optional hash); our ttl_ms == thrift `ttl`
VALUE = StructSpec(
    "Value",
    T.Value,
    (
        Field(1, "version", T_I64),
        Field(3, "originator_id", T_STRING, dec=lambda b: b.decode()),
        Field(2, "value", T_STRING, optional=True),
        Field(4, "ttl_ms", T_I64),
        Field(5, "ttl_version", T_I64, default=0),
        Field(6, "hash", T_I64, optional=True),
    ),
)

# openr/if/Types.thrift:897 Publication
PUBLICATION = StructSpec(
    "Publication",
    T.Publication,
    (
        Field(
            2,
            "key_vals",
            ("map", T_STRING, ("struct", VALUE)),
            dec=lambda m: {k.decode(): v for k, v in m.items()},
        ),
        Field(
            3,
            "expired_keys",
            ("list", T_STRING),
            dec=lambda xs: [x.decode() for x in xs],
            default=[],
        ),
        Field(
            4,
            "node_ids",
            ("list", T_STRING),
            optional=True,
            dec=lambda xs: [x.decode() for x in xs],
        ),
        Field(
            5,
            "tobe_updated_keys",
            ("list", T_STRING),
            optional=True,
            dec=lambda xs: [x.decode() for x in xs],
        ),
        Field(6, "flood_root_id", T_STRING, optional=True, dec=lambda b: b.decode()),
        Field(7, "area", T_STRING, dec=lambda b: b.decode(), default="0"),
    ),
)

# openr/if/Types.thrift:683 KeyGetParams {1: list<string> keys}
KEY_GET_PARAMS = StructSpec(
    "KeyGetParams",
    None,
    (
        Field(
            1,
            "keys",
            ("list", T_STRING),
            dec=lambda xs: [x.decode() for x in xs],
            default=[],
        ),
    ),
)

# openr/if/Types.thrift:647 KeySetParams
KEY_SET_PARAMS = StructSpec(
    "KeySetParams",
    None,
    (
        Field(
            2,
            "key_vals",
            ("map", T_STRING, ("struct", VALUE)),
            dec=lambda m: {k.decode(): v for k, v in m.items()},
        ),
        Field(3, "solicit_response", T_BOOL, default=True),
        Field(
            5,
            "node_ids",
            ("list", T_STRING),
            optional=True,
            dec=lambda xs: [x.decode() for x in xs],
        ),
        Field(6, "flood_root_id", T_STRING, optional=True, dec=lambda b: b.decode()),
        Field(7, "timestamp_ms", T_I64, optional=True),
    ),
)

# openr/if/Types.thrift:691 KeyDumpParams
KEY_DUMP_PARAMS = StructSpec(
    "KeyDumpParams",
    None,
    (
        Field(1, "prefix", T_STRING, dec=lambda b: b.decode(), default=""),
        Field(
            3,
            "originator_ids",
            ("set", T_STRING),
            optional=True,
            dec=lambda xs: {x.decode() for x in xs},
        ),
        Field(6, "ignore_ttl", T_BOOL, default=True),
        Field(7, "do_not_publish_value", T_BOOL, default=False),
        Field(
            2,
            "key_val_hashes",
            ("map", T_STRING, ("struct", VALUE)),
            optional=True,
            dec=lambda m: {k.decode(): v for k, v in m.items()},
        ),
        Field(4, "oper", T_I32, optional=True),
        Field(
            5,
            "keys",
            ("list", T_STRING),
            optional=True,
            dec=lambda xs: [x.decode() for x in xs],
        ),
    ),
)

# openr/if/Types.thrift:753 PeerSpec {1: peerAddr, 2: cmdUrl,
# 4: ctrlPort, 5: state}
PEER_SPEC = StructSpec(
    "PeerSpec",
    None,
    (
        Field(1, "peer_addr", T_STRING, dec=lambda b: b.decode(), default=""),
        Field(2, "cmd_url", T_STRING, optional=True, dec=lambda b: b.decode()),
        Field(4, "ctrl_port", T_I32, default=0),
        Field(5, "state", T_I32, optional=True),
    ),
)

# openr/if/Types.thrift:1254 OpenrVersions {1: version,
# 2: lowestSupportedVersion} (OpenrVersion = i32)
OPENR_VERSIONS = StructSpec(
    "OpenrVersions",
    None,
    (
        Field(1, "version", T_I32, default=0),
        Field(2, "lowest_supported_version", T_I32, default=0),
    ),
)

# openr/if/Types.thrift:29 PerfEvent {1: nodeName, 2: eventDescr,
# 3: unixTs}
PERF_EVENT = StructSpec(
    "PerfEvent",
    T.PerfEvent,
    (
        Field(1, "node_name", T_STRING, dec=lambda b: b.decode()),
        Field(2, "event_name", T_STRING, dec=lambda b: b.decode()),
        Field(3, "unix_ts_ms", T_I64),
    ),
)

# openr/if/Types.thrift:47 PerfEvents {1: list<PerfEvent> events}
PERF_EVENTS = StructSpec(
    "PerfEvents",
    T.PerfEvents,
    (Field(1, "events", ("list", ("struct", PERF_EVENT)), default=[]),),
)

# openr/if/Types.thrift:74 Adjacency — our string next-hops map to
# BinaryAddress on the wire
ADJACENCY = StructSpec(
    "Adjacency",
    T.Adjacency,
    (
        Field(1, "other_node_name", T_STRING, dec=lambda b: b.decode()),
        Field(2, "if_name", T_STRING, dec=lambda b: b.decode()),
        Field(
            3,
            "next_hop_v6",
            ("struct", BINARY_ADDRESS),
            enc=_ip_to_binary_addr,
            dec=_binary_addr_to_ip,
            optional=True,
        ),
        Field(
            5,
            "next_hop_v4",
            ("struct", BINARY_ADDRESS),
            enc=_ip_to_binary_addr,
            dec=_binary_addr_to_ip,
            optional=True,
        ),
        Field(4, "metric", T_I32),
        Field(6, "adj_label", T_I32, default=0),
        Field(7, "is_overloaded", T_BOOL, default=False),
        Field(8, "rtt_us", T_I32, default=0),
        Field(9, "timestamp_s", T_I64, default=0),
        Field(10, "weight", T_I64, default=1),
        Field(11, "other_if_name", T_STRING, dec=lambda b: b.decode(), default=""),
    ),
)

# openr/if/Types.thrift:144 AdjacencyDatabase
ADJACENCY_DATABASE = StructSpec(
    "AdjacencyDatabase",
    T.AdjacencyDatabase,
    (
        Field(1, "this_node_name", T_STRING, dec=lambda b: b.decode()),
        Field(2, "is_overloaded", T_BOOL, default=False),
        Field(3, "adjacencies", ("list", ("struct", ADJACENCY)), default=[]),
        Field(4, "node_label", T_I32, default=0),
        Field(5, "perf_events", ("struct", PERF_EVENTS), optional=True),
        Field(6, "area", T_STRING, dec=lambda b: b.decode(), default="0"),
        # soft-drain increment (Types.thrift field 9); peers that predate
        # the field simply omit it and decode to 0 (undrained)
        Field(9, "node_metric_increment_val", T_I32, default=0),
    ),
)


# ---------------------------------------------------------------------------
# route structs (Network.thrift / Types.thrift RouteDatabase) — the
# Decision/Fib query surface (round-5 shim extension)
# ---------------------------------------------------------------------------


def _pack_addr(s: str) -> bytes:
    """BinaryAddress.addr is plain `binary` on the wire
    (Network.thrift:57).  Real IPs pack to 4/16 bytes; non-IP transport
    addresses (test fabrics, in-process meshes) ride as raw UTF-8."""
    if not s:
        return b""
    try:
        return ipaddress.ip_address(s).packed
    except ValueError:
        return s.encode("utf-8")


def _unpack_addr(b: bytes) -> str:
    if not b:
        return ""
    if len(b) in (4, 16):
        return str(ipaddress.ip_address(b))
    return b.decode("utf-8", errors="replace")


def _cidr_to_ip_prefix(cidr: str) -> dict:
    net = ipaddress.ip_network(cidr, strict=False)
    return {
        "prefix_address": {"addr": net.network_address.packed, "if_name": None},
        "prefix_length": net.prefixlen,
    }


def _ip_prefix_to_cidr(v) -> str:
    addr = v["prefix_address"]["addr"]
    return f"{ipaddress.ip_address(addr)}/{v['prefix_length']}"


# openr/if/Network.thrift:61 IpPrefix {1: BinaryAddress prefixAddress,
# 2: i16 prefixLength}
IP_PREFIX = StructSpec(
    "IpPrefix",
    None,
    (
        Field(1, "prefix_address", ("struct", BINARY_ADDRESS)),
        Field(2, "prefix_length", T_I16),
    ),
)

# openr/if/Network.thrift:48 MplsAction {1: MplsActionCode action,
# 2: optional swapLabel, 3: optional pushLabels (bottom of stack first)}
MPLS_ACTION = StructSpec(
    "MplsAction",
    None,
    (
        Field(1, "action", T_I32),
        Field(2, "swap_label", T_I32, optional=True),
        Field(3, "push_labels", ("list", T_I32), optional=True),
    ),
)

# openr/if/Network.thrift:66 NextHopThrift {1: BinaryAddress address,
# 2: weight, 3: optional mplsAction, 51: metric, 53: optional area,
# 54: optional neighborNodeName} — wire dict form; the repo NextHop
# carries address/if_name separately and they merge into BinaryAddress
NEXT_HOP = StructSpec(
    "NextHopThrift",
    None,
    (
        Field(1, "address", ("struct", BINARY_ADDRESS)),
        Field(2, "weight", T_I32, default=0),
        Field(3, "mpls_action", ("struct", MPLS_ACTION), optional=True),
        Field(51, "metric", T_I32, default=0),
        Field(53, "area", T_STRING, optional=True, dec=lambda b: b.decode()),
        Field(
            54,
            "neighbor_node_name",
            T_STRING,
            optional=True,
            dec=lambda b: b.decode(),
        ),
    ),
)


def _nh_to_wire(nh) -> dict:
    action = None
    if nh.mpls_action is not None:
        action = {
            "action": int(nh.mpls_action.action),
            "swap_label": nh.mpls_action.swap_label,
            "push_labels": (
                list(nh.mpls_action.push_labels)
                if nh.mpls_action.push_labels is not None
                else None
            ),
        }
    return {
        "address": {
            "addr": _pack_addr(nh.address),
            "if_name": nh.if_name,
        },
        "weight": nh.weight,
        "mpls_action": action,
        "metric": nh.metric,
        "area": nh.area,
        "neighbor_node_name": nh.neighbor_node_name,
    }


def _wire_to_nh(v):
    addr = v["address"]["addr"]
    action = None
    if v.get("mpls_action") is not None:
        a = v["mpls_action"]
        action = T.MplsAction(
            action=T.MplsActionCode(a["action"]),
            swap_label=a.get("swap_label"),
            push_labels=(
                tuple(a["push_labels"])
                if a.get("push_labels") is not None
                else None
            ),
        )
    return T.NextHop(
        address=_unpack_addr(addr),
        if_name=v["address"].get("if_name"),
        metric=v.get("metric", 0),
        weight=v.get("weight", 0),
        area=v.get("area"),
        neighbor_node_name=v.get("neighbor_node_name"),
        mpls_action=action,
    )


def _nhs_enc(nhs):
    return [_nh_to_wire(nh) for nh in nhs]


def _nhs_dec(ws):
    return [_wire_to_nh(w) for w in ws]


# openr/if/Network.thrift:122 UnicastRoute {1: IpPrefix dest,
# 4: list<NextHopThrift> nextHops}
UNICAST_ROUTE = StructSpec(
    "UnicastRoute",
    T.UnicastRoute,
    (
        Field(
            1,
            "dest",
            ("struct", IP_PREFIX),
            enc=_cidr_to_ip_prefix,
            dec=_ip_prefix_to_cidr,
        ),
        Field(
            4,
            "next_hops",
            ("list", ("struct", NEXT_HOP)),
            enc=_nhs_enc,
            dec=_nhs_dec,
            default=[],
        ),
    ),
)

# openr/if/Network.thrift:99 MplsRoute {1: i32 topLabel,
# 4: list<NextHopThrift> nextHops}
MPLS_ROUTE = StructSpec(
    "MplsRoute",
    T.MplsRoute,
    (
        Field(1, "top_label", T_I32),
        Field(
            4,
            "next_hops",
            ("list", ("struct", NEXT_HOP)),
            enc=_nhs_enc,
            dec=_nhs_dec,
            default=[],
        ),
    ),
)

# openr/if/Types.thrift:1003 RouteDatabase {1: thisNodeName,
# 3: optional perfEvents, 4: unicastRoutes, 5: mplsRoutes}
ROUTE_DATABASE = StructSpec(
    "RouteDatabase",
    T.RouteDatabase,
    (
        Field(1, "this_node_name", T_STRING, dec=lambda b: b.decode(), default=""),
        Field(3, "perf_events", ("struct", PERF_EVENTS), optional=True),
        Field(
            4,
            "unicast_routes",
            ("list", ("struct", UNICAST_ROUTE)),
            default=[],
        ),
        Field(
            5,
            "mpls_routes",
            ("list", ("struct", MPLS_ROUTE)),
            default=[],
        ),
    ),
)


# ---------------------------------------------------------------------------
# strict message envelope + framed transport
# ---------------------------------------------------------------------------


def encode_message(name: str, mtype: int, seqid: int, payload: bytes) -> bytes:
    w = _Writer()
    w.u32(_STRICT_VERSION | mtype)  # top bit set: unsigned on the wire
    w.string(name)
    w.i32(seqid)
    return w.getvalue() + payload


def decode_message(data: bytes) -> tuple[str, int, int, _Reader]:
    r = _Reader(data)
    head = r.i32() & 0xFFFFFFFF
    if head & 0xFFFF0000 != 0x80010000:
        raise ThriftError("not a strict thrift binary message")
    mtype = head & 0xFF
    name = r.string()
    seqid = r.i32()
    return name, mtype, seqid, r


def frame(message: bytes) -> bytes:
    return _s.pack("!i", len(message)) + message


def encode_application_exception(name: str, seqid: int, text: str) -> bytes:
    """TApplicationException: {1: string message, 2: i32 type}."""
    w = _Writer()
    w.u8(T_STRING)
    w.i16(1)
    w.string(text)
    w.u8(T_I32)
    w.i16(2)
    w.i32(0)  # UNKNOWN
    w.u8(T_STOP)
    return encode_message(name, MSG_EXCEPTION, seqid, w.getvalue())
