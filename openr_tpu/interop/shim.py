"""Thrift-binary wire shim: a stock-openr-shaped listener over KvStore.

Demonstrates the cross-stack exchange the ARCHITECTURE.md decision
record scoped: a client speaking the thrift Binary protocol over framed
transport (what `thrift.TBinaryProtocol`/`TFramedTransport` produce —
the encoding a stock openr tool emits when pointed at a plain
thrift-binary endpoint) can call

    getMyNodeName()                                -> string
    getOpenrVersion()                              -> OpenrVersions
    getKvStoreKeyVals(1: list<string> filterKeys)  -> Publication
    getKvStoreKeyValsArea(1: filterKeys, 2: area)  -> Publication
    getKvStoreKeyValsFiltered[Area](1: filter, ..) -> Publication
    getKvStoreHashFiltered[Area](1: filter, ..)    -> Publication
    getKvStorePeers[Area](..)                      -> PeersMap
    setKvStoreKeyVals(1: KeySetParams, 2: area)    -> void

against this daemon (reference signatures:
openr/if/OpenrCtrl.thrift:398-492, 560, 612).  Unknown methods get a
TApplicationException, exactly as a thrift server would answer.

This deliberately does NOT implement fbthrift's rocket/header transport
(the reference's default in-fleet transport) — that remains the recorded
divergence; the shim covers the stable, documented thrift Binary+framed
stack that thrift-generated clients in any language can select.
"""

from __future__ import annotations

import asyncio
import logging
import struct as _s
from typing import Optional

from .. import types as T
from ..runtime.eventbase import OpenrEventBase
from . import thrift_binary as tb

log = logging.getLogger(__name__)

MAX_FRAME = 64 * 1024 * 1024
# getRegexCounters patterns run on the event loop against every counter
# key — cap what one client can submit (generous: fb303 regexes in the
# wild are tens of chars)
MAX_COUNTER_REGEX_LEN = 1024

# argument StructSpecs (module constants: the shim decodes at wire rate)
_GET_ARGS = tb.StructSpec(
    "getKvStoreKeyVals_args",
    None,
    (
        tb.Field(
            1,
            "filter_keys",
            ("list", tb.T_STRING),
            dec=lambda xs: [x.decode() for x in xs],
            default=[],
        ),
    ),
)
_GET_AREA_ARGS = tb.StructSpec(
    "getKvStoreKeyValsArea_args",
    None,
    _GET_ARGS.fields
    + (
        tb.Field(
            2, "area", tb.T_STRING, dec=lambda b: b.decode(), default="0"
        ),
    ),
)
_SET_ARGS = tb.StructSpec(
    "setKvStoreKeyVals_args",
    None,
    (
        tb.Field(1, "set_params", ("struct", tb.KEY_SET_PARAMS)),
        tb.Field(
            2, "area", tb.T_STRING, dec=lambda b: b.decode(), default="0"
        ),
    ),
)
_EMPTY_ARGS = tb.StructSpec("empty_args", None, ())
_FILTER_ARGS = tb.StructSpec(
    "filtered_args",
    None,
    (
        tb.Field(1, "filter", ("struct", tb.KEY_DUMP_PARAMS)),
        tb.Field(
            2, "area", tb.T_STRING, dec=lambda b: b.decode(), default="0"
        ),
    ),
)
_AREA_ARGS = tb.StructSpec(
    "area_args",
    None,
    (
        tb.Field(
            1, "area", tb.T_STRING, dec=lambda b: b.decode(), default="0"
        ),
    ),
)
_PEERS_MAP = ("map", tb.T_STRING, ("struct", tb.PEER_SPEC))
# OpenrCtrl.thrift:313 getRouteDbComputed(1: string nodeName)
_NODE_ARGS = tb.StructSpec(
    "node_args",
    None,
    (
        tb.Field(
            1, "node_name", tb.T_STRING, dec=lambda b: b.decode(), default=""
        ),
    ),
)
# OpenrCtrl.thrift:322 getUnicastRoutesFiltered(1: list<string> prefixes)
_PREFIXES_ARGS = tb.StructSpec(
    "prefixes_args",
    None,
    (
        tb.Field(
            1,
            "prefixes",
            ("list", tb.T_STRING),
            dec=lambda xs: [x.decode() for x in xs],
            default=[],
        ),
    ),
)
# OpenrCtrl.thrift:335 getMplsRoutesFiltered(1: list<i32> labels)
_LABELS_ARGS = tb.StructSpec(
    "labels_args",
    None,
    (tb.Field(1, "labels", ("list", tb.T_I32), default=[]),),
)
# fb303 getRegexCounters(1: string regex)
_REGEX_ARGS = tb.StructSpec(
    "regex_args",
    None,
    (
        tb.Field(
            1, "regex", tb.T_STRING, dec=lambda b: b.decode(), default=".*"
        ),
    ),
)
# OpenrCtrl.thrift:430 longPollKvStoreAdjArea(1: area, 2: KeyVals snapshot)
# and the deprecated area-less longPollKvStoreAdj(1: KeyVals snapshot)
_SNAPSHOT_SPEC = ("map", tb.T_STRING, ("struct", tb.VALUE))
_LONG_POLL_ARGS = tb.StructSpec(
    "longPollKvStoreAdj_args",
    None,
    (
        tb.Field(
            1,
            "snapshot",
            _SNAPSHOT_SPEC,
            dec=lambda m: {k.decode(): v for k, v in m.items()},
            default={},
        ),
    ),
)
_LONG_POLL_AREA_ARGS = tb.StructSpec(
    "longPollKvStoreAdjArea_args",
    None,
    (
        tb.Field(
            1, "area", tb.T_STRING, dec=lambda b: b.decode(), default="0"
        ),
        tb.Field(
            2,
            "snapshot",
            _SNAPSHOT_SPEC,
            dec=lambda m: {k.decode(): v for k, v in m.items()},
            default={},
        ),
    ),
)
# serving: queryPathsBatched(1: list<string> sources, 2: string area)
# -> map<source, map<dest, i64 distance>> (new capability, no reference
# RPC; rides the QueryScheduler's admission/coalescing pipeline)
_QUERY_PATHS_ARGS = tb.StructSpec(
    "queryPathsBatched_args",
    None,
    (
        tb.Field(
            1,
            "sources",
            ("list", tb.T_STRING),
            dec=lambda xs: [x.decode() for x in xs],
            default=[],
        ),
        tb.Field(
            2, "area", tb.T_STRING, dec=lambda b: b.decode(), default="0"
        ),
    ),
)
_DISTANCES_MAP = ("map", tb.T_STRING, ("map", tb.T_STRING, tb.T_I64))


class ThriftBinaryShim(OpenrEventBase):
    """Framed thrift-binary listener fronting a KvStore instance."""

    def __init__(
        self,
        kvstore,
        host: str = "::1",
        port: int = 0,
        node_name: str = "",
        decision=None,
        fib=None,
        serving=None,
        counters_fn=None,
        kvstore_updates_queue=None,
        long_poll_timeout_s: float = 20.0,
        query_timeout_s: float = 60.0,
    ) -> None:
        super().__init__(name="thrift-shim")
        self.kvstore = kvstore
        self.host = host
        self.port = port
        self.node_name = node_name
        self.decision = decision
        self.fib = fib
        # QueryScheduler (openr_tpu.serving): queryPathsBatched submits
        # into its admission queue; sheds answer as thrift exceptions
        self.serving = serving
        self.query_timeout_s = query_timeout_s
        # () -> dict[str, int]: the daemon passes the ctrl server's
        # merged per-module counter dump (fb303 getCounters semantics)
        self.counters_fn = counters_fn
        # ReplicateQueue[Publication]: longPollKvStoreAdj blocks on it
        # (same wiring as the native ctrl server's _long_poll_adj)
        self.kvstore_updates_queue = kvstore_updates_queue
        self.long_poll_timeout_s = long_poll_timeout_s
        self._server: Optional[asyncio.AbstractServer] = None

    def _fib(self):
        if self.fib is None:
            raise RuntimeError("fib module not attached")
        return self.fib

    def run(self) -> None:
        super().run()
        self.wait_until_running()
        self.run_coroutine(self._start()).result(timeout=10)

    async def _start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_conn, host=self.host, port=self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    def stop(self) -> None:
        if self._server is not None and self._loop is not None:
            server, self._server = self._server, None

            def _close() -> None:
                server.close()

            try:
                self.run_in_event_base_thread(_close).result(timeout=5)
            except Exception:
                pass
        super().stop()

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            while True:
                head = await reader.readexactly(4)
                (length,) = _s.unpack("!i", head)
                if not 0 < length <= MAX_FRAME:
                    raise tb.ThriftError(f"bad frame length {length}")
                msg = await reader.readexactly(length)
                name, mtype, seqid, r = tb.decode_message(msg)
                if mtype == tb.MSG_CALL and name in (
                    "longPollKvStoreAdj",
                    "longPollKvStoreAdjArea",
                ):
                    # long poll blocks on the kvstore updates queue: keep
                    # it on the loop (async queue reader) rather than
                    # parking an executor thread for up to the timeout
                    reply = await self._long_poll_adj(name, seqid, r)
                else:
                    # the KvStore calls block on a cross-thread Future
                    # with no timeout; off the loop thread so one
                    # busy/stopped KvStore cannot wedge every other shim
                    # connection (and stop()'s _close, which runs on this
                    # same loop)
                    reply = await asyncio.get_running_loop().run_in_executor(
                        None, self._serve, msg
                    )
                writer.write(tb.frame(reply))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        except tb.ThriftError as exc:
            log.warning("thrift shim: %s", exc)
        finally:
            writer.close()

    # -- long poll (reference: longPollKvStoreAdjArea,
    #    OpenrCtrl.thrift:430 / OpenrCtrlHandler.h:269) --------------------

    async def _long_poll_adj(self, name: str, seqid: int, r) -> bytes:
        """Resolve True when any adj: key moves beyond the client's
        version snapshot, False on timeout — the native ctrl server's
        _long_poll_adj semantics on the thrift-binary wire."""
        from ..runtime.queue import QueueClosedError
        from ..types import ADJ_MARKER

        try:
            if name == "longPollKvStoreAdjArea":
                args = tb.read_struct(r, _LONG_POLL_AREA_ARGS)
            else:
                args = tb.read_struct(r, _LONG_POLL_ARGS)
            area = args.get("area", "0")
            snapshot = {
                key: val.version
                for key, val in (args.get("snapshot") or {}).items()
            }
            queue = self.kvstore_updates_queue
            if queue is None:
                raise RuntimeError("kvstore updates queue not attached")
            loop = asyncio.get_running_loop()
            # the reader is registered BEFORE the snapshot comparison so a
            # publication racing the dump is never lost
            q_reader = queue.get_reader()
            try:
                current = await loop.run_in_executor(
                    None,
                    lambda: self.kvstore.dump_all(
                        area, key_prefixes=[ADJ_MARKER]
                    ),
                )
                changed = any(
                    snapshot.get(key) != val.version
                    for key, val in current.key_vals.items()
                )
                deadline = loop.time() + self.long_poll_timeout_s
                while not changed:
                    timeout = deadline - loop.time()
                    if timeout <= 0:
                        return self._reply(name, seqid, tb.T_BOOL, False)
                    try:
                        pub = await asyncio.wait_for(
                            q_reader.aget(), timeout
                        )
                    except (asyncio.TimeoutError, QueueClosedError):
                        return self._reply(name, seqid, tb.T_BOOL, False)
                    if pub.area != area:
                        continue
                    changed = any(
                        key.startswith(ADJ_MARKER)
                        and snapshot.get(key) != val.version
                        for key, val in pub.key_vals.items()
                    ) or any(
                        key.startswith(ADJ_MARKER)
                        for key in pub.expired_keys
                    )
                return self._reply(name, seqid, tb.T_BOOL, True)
            finally:
                queue.close_reader(q_reader)
        except tb.ThriftError:
            raise
        except Exception as exc:
            log.warning("thrift shim %s failed: %s", name, exc)
            return tb.encode_application_exception(name, seqid, str(exc))

    # -- dispatch ------------------------------------------------------------

    def _serve(self, msg: bytes) -> bytes:
        name, mtype, seqid, r = tb.decode_message(msg)
        if mtype != tb.MSG_CALL:
            return tb.encode_application_exception(
                name, seqid, f"unexpected message type {mtype}"
            )
        try:
            if name == "getMyNodeName":
                tb.read_struct(r, _EMPTY_ARGS)
                return self._reply(name, seqid, tb.T_STRING, self.node_name)
            if name == "getOpenrVersion":
                from ..ctrl.server import (
                    OPENR_LOWEST_SUPPORTED_VERSION,
                    OPENR_VERSION,
                )

                tb.read_struct(r, _EMPTY_ARGS)
                return self._reply(
                    name,
                    seqid,
                    ("struct", tb.OPENR_VERSIONS),
                    {
                        "version": OPENR_VERSION,
                        "lowest_supported_version": (
                            OPENR_LOWEST_SUPPORTED_VERSION
                        ),
                    },
                )
            if name == "getKvStoreKeyVals":
                args = tb.read_struct(r, _GET_ARGS)
                pub = self.kvstore.get_key_vals("0", args["filter_keys"])
                return self._reply(name, seqid, ("struct", tb.PUBLICATION), pub)
            if name == "getKvStoreKeyValsArea":
                args = tb.read_struct(r, _GET_AREA_ARGS)
                pub = self.kvstore.get_key_vals(
                    args["area"], args["filter_keys"]
                )
                return self._reply(name, seqid, ("struct", tb.PUBLICATION), pub)
            if name in (
                "getKvStoreKeyValsFiltered",
                "getKvStoreKeyValsFilteredArea",
                "getKvStoreHashFiltered",
                "getKvStoreHashFilteredArea",
            ):
                args = tb.read_struct(r, _FILTER_ARGS)
                filt = args["filter"]
                # the deprecated prefix field is COMMA-SEPARATED (the
                # reference folly::split's it, KvStore.cpp:649; legacy
                # breeze joins multiple --prefix args into it)
                prefixes = filt.get("keys") or [
                    p for p in (filt.get("prefix") or "").split(",") if p
                ]
                originators = filt.get("originator_ids") or []
                # FilterOperator (Types.thrift:639): OR=1 (default), AND=2
                match_all = filt.get("oper") == 2
                hash_only = bool(filt.get("do_not_publish_value"))
                if "Hash" in name:
                    pub = self.kvstore.dump_hashes(
                        args["area"], prefixes, originators
                    )
                elif match_all or hash_only:
                    # display-oriented variants (same routing as the ctrl
                    # server's _kvstore_dump_filtered): AND semantics /
                    # values withheld
                    pub = self.kvstore.dump_all(
                        args["area"],
                        key_prefixes=prefixes,
                        originator_ids=originators,
                        match_all=match_all,
                        do_not_publish_value=hash_only,
                    )
                else:
                    # the peer full-sync path: 3-way diff when the caller
                    # sent its key_val_hashes, remaining-TTL adjustment
                    # always (a dump_all here would re-arm full TTLs on
                    # the remote side every sync)
                    from ..kvstore.kvstore import KeyDumpParams

                    pub = self.kvstore.process_full_dump(
                        args["area"],
                        KeyDumpParams(
                            keys=prefixes,
                            originator_ids=originators,
                            key_val_hashes=filt.get("key_val_hashes"),
                        ),
                    )
                return self._reply(name, seqid, ("struct", tb.PUBLICATION), pub)
            if name in ("getKvStorePeers", "getKvStorePeersArea"):
                args = tb.read_struct(r, _AREA_ARGS)
                peers = self.kvstore.dump_peers(args["area"])
                wire = {
                    nm: {
                        "peer_addr": ps.peer_addr,
                        "ctrl_port": ps.ctrl_port,
                        "state": int(ps.state),
                    }
                    for nm, ps in peers.items()
                }
                return self._reply(name, seqid, _PEERS_MAP, wire)
            if name in ("getCounters", "getRegexCounters"):
                # fb303 base-service surface stock monitoring tooling
                # polls (map<string, i64>)
                import re as _re

                if name == "getRegexCounters":
                    args = tb.read_struct(r, _REGEX_ARGS)
                    regex = args.get("regex") or ""
                    # the pattern runs on the daemon event loop against
                    # every counter key: bound what one client can make
                    # it cost.  Length-capped patterns over short keys
                    # bound re backtracking; compile/match errors answer
                    # as a thrift application exception instead of
                    # killing the connection handler.
                    if len(regex) > MAX_COUNTER_REGEX_LEN:
                        raise RuntimeError(
                            "counter regex longer than "
                            f"{MAX_COUNTER_REGEX_LEN} chars"
                        )
                    try:
                        pat = _re.compile(regex)
                    except _re.error as exc:
                        raise RuntimeError(f"bad counter regex: {exc}")
                else:
                    tb.read_struct(r, _EMPTY_ARGS)
                    pat = None
                if self.counters_fn is None:
                    raise RuntimeError("counters source not attached")

                def _matches(key: str) -> bool:
                    if pat is None:
                        return True
                    try:
                        return pat.search(key) is not None
                    except Exception:  # e.g. RecursionError on
                        return False  # pathological nesting
                counters = {
                    k: int(v)
                    for k, v in self.counters_fn().items()
                    if _matches(k)
                }
                return self._reply(
                    name, seqid, ("map", tb.T_STRING, tb.T_I64), counters
                )
            if name == "getRouteDb":
                # reference: routes as tracked by the FIB module
                # (OpenrCtrl.thrift:298)
                tb.read_struct(r, _EMPTY_ARGS)
                unicast, mpls = self._fib().get_route_db()
                db = T.RouteDatabase(
                    this_node_name=self.node_name,
                    unicast_routes=unicast,
                    mpls_routes=mpls,
                )
                return self._reply(
                    name, seqid, ("struct", tb.ROUTE_DATABASE), db
                )
            if name == "getRouteDbComputed":
                # Decision-computed, any node's perspective
                # (OpenrCtrl.thrift:313, Decision.cpp:1510-1530); empty
                # nodeName = this node — served from the fleet product
                # when a warm view covers the target
                args = tb.read_struct(r, _NODE_ARGS)
                if self.decision is None:
                    raise RuntimeError("decision module not attached")
                rib = self.decision.get_route_db(args["node_name"])
                db = T.RouteDatabase(
                    this_node_name=args["node_name"] or self.node_name,
                    unicast_routes=[
                        e.to_unicast_route()
                        for e in rib.unicast_routes.values()
                    ],
                    mpls_routes=[
                        e.to_mpls_route() for e in rib.mpls_routes.values()
                    ],
                )
                return self._reply(
                    name, seqid, ("struct", tb.ROUTE_DATABASE), db
                )
            if name in ("getUnicastRoutes", "getUnicastRoutesFiltered"):
                args = (
                    tb.read_struct(r, _PREFIXES_ARGS)
                    if name.endswith("Filtered")
                    else (tb.read_struct(r, _EMPTY_ARGS) or {"prefixes": []})
                )
                routes = self._fib().get_unicast_routes(
                    args.get("prefixes") or None
                )
                return self._reply(
                    name,
                    seqid,
                    ("list", ("struct", tb.UNICAST_ROUTE)),
                    routes,
                )
            if name in ("getMplsRoutes", "getMplsRoutesFiltered"):
                args = (
                    tb.read_struct(r, _LABELS_ARGS)
                    if name.endswith("Filtered")
                    else (tb.read_struct(r, _EMPTY_ARGS) or {"labels": []})
                )
                mpls = self._fib().get_route_db()[1]
                labels = set(args.get("labels") or [])
                if labels:
                    mpls = [m for m in mpls if m.top_label in labels]
                return self._reply(
                    name, seqid, ("list", ("struct", tb.MPLS_ROUTE)), mpls
                )
            if name == "queryPathsBatched":
                # one submit per source: the scheduler's coalescer groups
                # them into one engine dispatch (same epoch, same op), so
                # an N-source call costs one device batch, not N
                args = tb.read_struct(r, _QUERY_PATHS_ARGS)
                if self.serving is None:
                    raise RuntimeError("serving module not attached")
                futs = [
                    (src, self.serving.submit(
                        "paths", area=args["area"], sources=(src,)
                    ))
                    for src in args["sources"]
                ]
                wire: dict[str, dict[str, int]] = {}
                for src, fut in futs:
                    res = fut.result(timeout=self.query_timeout_s)
                    spf = res.value.get(src, {})
                    wire[src] = {
                        dest: int(nr.metric) for dest, nr in spf.items()
                    }
                return self._reply(name, seqid, _DISTANCES_MAP, wire)
            if name == "setKvStoreKeyVals":
                args = tb.read_struct(r, _SET_ARGS)
                params = args["set_params"]
                self.kvstore.set_key_vals(
                    args["area"],
                    params["key_vals"],
                    node_ids=params.get("node_ids"),
                    flood_root_id=params.get("flood_root_id"),
                )
                return self._reply(name, seqid, None, None)
        except tb.ThriftError:
            raise
        except Exception as exc:  # surfaced as a thrift exception
            log.warning("thrift shim %s failed: %s", name, exc)
            return tb.encode_application_exception(name, seqid, str(exc))
        return tb.encode_application_exception(
            name, seqid, f"unknown method {name!r}"
        )

    @staticmethod
    def _reply(name: str, seqid: int, success_spec, value) -> bytes:
        """Reply payload: struct with the success value at field 0 (void
        replies carry an empty struct)."""
        w = tb._Writer()
        if success_spec is not None:
            w.u8(tb._ttype_of(success_spec))
            w.i16(0)
            tb._write_value(w, success_spec, value)
        w.u8(tb.T_STOP)
        return tb.encode_message(name, tb.MSG_REPLY, seqid, w.getvalue())
