"""Reduced-output all-sources SPF: the product route building consumes.

The literal all-sources [N, N] distance matrix at 100k nodes is 40 GB —
un-materializable on one chip, and nobody reads it: the reference's
buildRouteDb consumes, per router, only the distances/next-hops toward
the P prefix-originating nodes (openr/decision/Decision.cpp:615-793
createRouteForPrefix reads best-entry node distances; getNextHopsThrift's
LFA-free ECMP keeps neighbor u for destination t iff
metric(v,u) + dist(u,t) == dist(v,t), Decision.cpp:1296-1300).

So the whole-fleet product is all-sources-to-P-destinations, and on the
reversed graph that is ONE P-source SSSP:

    dist(v -> p)  ==  reverse-SSSP from p over reversed edges, read at v.

Drain semantics survive reversal exactly: the kernel blocks relaxation
through an overloaded predecessor unless its distance is 0 (ops.sssp /
ops.banded).  On the reversed graph the d==0 exception lands on the
original DESTINATION p (whose original in-edges are always usable), and
an overloaded original source v is reached by a final reverse hop whose
predecessor is v's neighbor — never blocked — while overloaded
intermediates still block as reverse-edge tails.  A case-by-case check
of (source, intermediate, destination) overload shows equality with the
forward rule; tests/test_banded.py (TestReducedAllSources) asserts it against the oracle.

The fused consumer pass then emits, per (router v, destination p), the
bit-packed ECMP next-hop set straight from the reverse distances —
gathers over a per-node out-neighbor table, no scatters — so the entire
fleet-wide route-building input is ONE device call returning
[N, P] int32 distances + [N, P, W] uint32 next-hop bitmaps.

The fast path goes further: the reverse in-edges of router v are
exactly v's forward out-edges, so the ECMP condition
``metric(v,u) + dist(u,p) == dist(v,p)`` is precisely "this reverse
relax candidate is tight".  The fused program
(_fused_progressive_banded) therefore computes the bitmap INSIDE the
final verification pass of the banded kernel — each [N, P] gather is
read once and feeds both the convergence verdict (min) and the bitmap
(compare + OR into precomputed slot bits), replacing the round-5
standalone bitmap pass that re-gathered the whole product.  The relax
itself runs the progressive while-loop (ops.banded), so one dispatch
covers relax + verify + bitmap and stops at the actual fixed point.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .sssp import INF16, INF32, clamp_metric_u16, u16_saturation_verdict


class OutEll(NamedTuple):
    """Per-node out-edge table in original node order (host-built)."""

    nbr: jax.Array  # [N, K] int32 — out-neighbor node id (pad 0)
    eid: jax.Array  # [N, K] int32 — directed edge id; -1 pad
    slot: jax.Array  # [N, K] int32 — rank among the node's sorted unique
    #   out-neighbors (parallel links share a slot); -1 pad
    n_words: int  # ceil(max_slots / 32) — static


def build_out_ell(
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    n_edges: int,
    n_nodes: int,
    out_slot: Optional[np.ndarray] = None,
) -> OutEll:
    """Vectorized out-edge table build.  `out_slot` (per-edge slot ids,
    csr._build_out_slots layout) is recomputed here when not supplied.

    Retired freelist slots (csr rewires) sit inside [:n_edges] styled as
    padding — endpoints at the pad node >= n_nodes — and are dropped
    here so they never index the [N]-sized tables."""
    src = np.asarray(edge_src[:n_edges], dtype=np.int64)
    dst = np.asarray(edge_dst[:n_edges], dtype=np.int64)
    ids = np.flatnonzero((src < n_nodes) & (dst < n_nodes))
    src, dst = src[ids], dst[ids]
    if out_slot is None:
        from ..decision.csr import _build_out_slots

        live = np.zeros(n_edges, dtype=bool)
        live[ids] = True
        out_slot, _ = _build_out_slots(
            np.asarray(edge_src), np.asarray(edge_dst), n_edges, live=live
        )
    e_slot = np.asarray(out_slot[:n_edges])[ids]
    deg = np.bincount(src, minlength=n_nodes)
    k = int(deg.max()) if ids.size else 1
    k_pad = 1
    while k_pad < max(k, 1):
        k_pad *= 2
    order = np.argsort(src, kind="stable")
    e_sorted = order
    s_sorted = src[order]
    starts = np.searchsorted(s_sorted, np.arange(n_nodes))
    pos = np.arange(len(order)) - starts[s_sorted]
    nbr = np.zeros((n_nodes, k_pad), dtype=np.int32)
    eid = np.full((n_nodes, k_pad), -1, dtype=np.int32)
    slot = np.full((n_nodes, k_pad), -1, dtype=np.int32)
    nbr[s_sorted, pos] = dst[e_sorted].astype(np.int32)
    eid[s_sorted, pos] = ids[e_sorted].astype(np.int32)
    slot[s_sorted, pos] = e_slot[e_sorted]
    max_slots = int(e_slot.max()) + 1 if ids.size else 1
    return OutEll(
        nbr=jnp.asarray(nbr),
        eid=jnp.asarray(eid),
        slot=jnp.asarray(slot),
        n_words=max(1, -(-max_slots // 32)),
    )


class EpilogueMaps(NamedTuple):
    """Reverse-slot -> forward-out-slot tables for the fused
    verify+bitmap epilogue.  Reverse in-edges of v are exactly v's
    forward out-edges: the reverse residual slot (v, k) with neighbor u
    and the reverse band edge (v-c)%N -> v each correspond to one
    forward out-edge of v, whose ECMP bit position is the rank of that
    neighbor among v's sorted unique out-neighbors (OutEll.slot).
    Host-built once per topology snapshot."""

    resid_slot: jax.Array  # [N, K] int32 — forward out-slot; -1 pad
    band_slot: jax.Array  # [B, N] int32 — forward out-slot; -1 no edge


def build_epilogue_maps(bg, out: OutEll) -> EpilogueMaps:
    """Map every reverse-graph relax slot (ops.banded.BandedGraph over
    the REVERSED edges) to the forward out-slot bit it certifies.
    Parallel forward links share a slot, and their reverse counterparts
    occupy distinct residual slots (build_banded demotes band
    duplicates), so every candidate lands on the right bit and the
    min-metric parallel link is the one whose equality fires."""
    nbr = np.asarray(out.nbr)
    eid = np.asarray(out.eid)
    slot = np.asarray(out.slot)
    n = bg.n_nodes

    def rank(u_row: np.ndarray, valid: np.ndarray) -> np.ndarray:
        """Forward out-slot of edge v -> u_row[v]; -1 where invalid."""
        m = (nbr[:n] == u_row[:, None]) & (eid[:n] >= 0)
        s = np.where(m, slot[:n], -1).max(axis=1)
        return np.where(valid, s, -1).astype(np.int32)

    rn = np.asarray(bg.resid_nbr)
    re_ = np.asarray(bg.resid_eid)
    resid_slot = np.stack(
        [rank(rn[:, k], re_[:, k] >= 0) for k in range(rn.shape[1])],
        axis=1,
    )
    ids = np.arange(n, dtype=np.int64)
    be = np.asarray(bg.band_eid)
    band_slot = np.stack(
        [
            rank(((ids - c) % n).astype(np.int32), be[b] >= 0)
            for b, c in enumerate(bg.offsets)
        ]
    )
    return EpilogueMaps(
        resid_slot=jnp.asarray(resid_slot), band_slot=jnp.asarray(band_slot)
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "check_every",
        "max_blocks",
        "depth",
        "resid_rounds",
        "small_dist",
        "chord_mode",
        "n_words",
        "pallas",
        "pallas_interpret",
    ),
)
def _fused_progressive_banded(
    dest_ids,
    bg,
    r_edge_up,  # REVERSED-graph runtime arrays (the runner's)
    r_edge_metric,
    node_overloaded,
    resid_slot,  # EpilogueMaps
    band_slot,
    init_dist,  # [N*, P] warm-start upper bound or None
    check_every: int,
    max_blocks: int,
    depth: int,
    resid_rounds: int,
    small_dist: bool,
    chord_mode: bool,
    n_words: int,
    pallas: bool = False,
    pallas_interpret: bool = False,
):
    """Relax + verify + ECMP bitmap as ONE compiled program, with the
    bitmap folded into the verification pass: the progressive while-loop
    (ops.banded) runs supersweep blocks to the fixed point, then a
    single Jacobi epilogue re-evaluates every exact relax candidate ONCE
    and uses it for BOTH the convergence verdict (min, v == d) and the
    ECMP bit (cand == d, finite) — the [N, P] product is read once, not
    re-gathered by a standalone bitmap pass.

    Correctness of the bit rule: for the forward out-edge v->u the
    reference condition metric(v,u) + dist(u,p) == dist(v,p)
    (Decision.cpp:1296-1300) is exactly "the reverse candidate through u
    is tight".  The candidate already encodes link-up and the drain
    exception (overloaded u allowed only at d(u,p) == 0), and the
    d < inf guard keeps unreachable rows bitless — a saturated cand can
    alias the INF sentinel, so equality alone is not enough.  Bits are
    meaningful only when ``converged`` is True (callers re-run
    otherwise, exactly like the distances)."""
    from .banded import _RelaxOps, make_dist0_orig

    n = bg.n_nodes
    d0 = make_dist0_orig(dest_ids, n, small_dist=small_dist)
    if init_dist is not None:
        init = init_dist[:n]
        if small_dist and init.dtype != jnp.uint16:
            init = jnp.minimum(init, INF16).astype(jnp.uint16)
        elif not small_dist and init.dtype != jnp.int32:
            init = jnp.where(
                init >= INF16, jnp.int32(INF32), init.astype(jnp.int32)
            )
        # re-pin sources to 0; elsewhere keep the caller's bound
        d0 = jnp.minimum(d0, init)
    ops = _RelaxOps(
        bg,
        r_edge_up,
        r_edge_metric,
        node_overloaded[:n],
        0 if chord_mode else depth,
        resid_rounds,
        None,
        small_dist,
        chord_mode,
        d0.dtype,
    )

    def body(state):
        d, _, i = state
        for _ in range(check_every - 1):
            d = ops.supersweep(d)
        v = ops.supersweep(d)
        return v, jnp.all(v == d), i + jnp.int32(1)

    def cond(state):
        _, conv, i = state
        return jnp.logical_and(~conv, i < max_blocks)

    d, _, blocks = jax.lax.while_loop(
        cond, body, (d0, jnp.bool_(False), jnp.int32(0))
    )

    # fused verify+bitmap epilogue (authoritative exact check: the
    # while-loop's own certificate is implied by v == d below).  With
    # the `pallas` static the epilogue runs as the hand-tiled kernel
    # (ops.pallas_kernels.fused_epilogue): one VMEM-resident product
    # tile per instance, every group unrolled against it — bit-exact by
    # construction (same where-expression, integer min).  Callers reach
    # it through run_with_fallback, never directly.
    if pallas:
        from . import pallas_kernels as pk

        bitmap, converged = pk.fused_epilogue(
            ops,
            bg,
            d,
            resid_slot,
            band_slot,
            n_words,
            interpret=pallas_interpret,
        )
        if small_dist:
            converged = u16_saturation_verdict(d, converged)
        return d, bitmap, converged, blocks
    p_dim = d.shape[1]
    fin = d < ops.inf
    v = d

    def bit_of(slot_row):
        return jnp.where(
            slot_row >= 0,
            jnp.uint32(1)
            << (jnp.maximum(slot_row, 0) % 32).astype(jnp.uint32),
            jnp.uint32(0),
        )

    # one (candidate, forward-slot-row) pair per reverse edge group;
    # thunked so only one [N, P] candidate is live at a time
    groups = [
        (functools.partial(ops.resid_cand, d, k), resid_slot[:, k])
        for k in range(ops.n_resid)
    ] + [
        (functools.partial(ops.band0_cand, d, b), band_slot[b])
        for b in range(ops.n_bands)
    ]
    if n_words == 1:
        bitmap2d = jnp.zeros((n, p_dim), dtype=jnp.uint32)
        for mk_cand, srow in groups:
            cand = mk_cand()
            on = fin & (cand == d)
            bitmap2d = bitmap2d | jnp.where(
                on, bit_of(srow)[:, None], jnp.uint32(0)
            )
            v = jnp.minimum(v, cand)
        bitmap = bitmap2d[:, :, None]
    else:
        bitmap = jnp.zeros((n, p_dim, n_words), dtype=jnp.uint32)
        for mk_cand, srow in groups:
            cand = mk_cand()
            on = fin & (cand == d)
            word_sel = (jnp.maximum(srow, 0) // 32)[:, None] == jnp.arange(
                n_words
            )[None, :]  # [N, W]
            bitmap = bitmap | jnp.where(
                on[:, :, None] & word_sel[:, None, :],
                bit_of(srow)[:, None, None],
                jnp.uint32(0),
            )
            v = jnp.minimum(v, cand)
    converged = jnp.all(v == d)
    if small_dist:
        converged = u16_saturation_verdict(d, converged)
    # blocks: executed while-loop blocks — blocks*check_every supersweeps
    # ran, so that count is a PROVEN-sufficient fixed-sweep budget for
    # this (topology, dest-set) shape; callers teach the runner's hint
    # from it so fixed-sweep consumers (sharded product, masked variants)
    # inherit the progressive run's auto-tuning
    return d, bitmap, converged, blocks


@functools.partial(jax.jit, static_argnames=("n_words",))
def ecmp_bitmap_from_reverse_dist(
    drev: jax.Array,  # [N*, P] — reverse-SSSP distances (drev[v, p] =
    #   dist(v->p)); N* is n_nodes (banded kernel) or node_capacity (ELL
    #   fallback).  Native kernel layout — no transpose on either side
    #   (round-5: the [P, N] orientation cost two 200MB-scale transposes
    #   per product round)
    out: OutEll,
    edge_metric: jax.Array,  # [E_cap] int32
    edge_up: jax.Array,  # [E_cap] bool
    node_overloaded: jax.Array,  # [N_cap] bool
    n_words: int,
) -> jax.Array:
    """[N, P, W] uint32: bit s of (v, p) set iff out-slot s of router v
    is an ECMP next-hop toward destination p — the reference's LFA-free
    condition metric(v,u) + dist(u,p) == dist(v,p)
    (openr/decision/Decision.cpp:1296-1300), evaluated fleet-wide from
    reverse distances.  Gather-only.

    Drain: the reference draws ECMP neighbors from the source's
    drain-respecting SPF tree (nextHopNodes is keyed by
    shortestPathsFromHere nextHops, Decision.cpp:1182-1260), so an
    overloaded neighbor u is a valid next-hop ONLY as the destination
    itself — the same own-source/destination exception the relax kernels
    encode, here as d(u,p) == 0."""
    n, k_pad = out.nbr.shape
    p_dim = drev.shape[1]
    d_self = drev[:n]  # [N, P]
    # uint16 domain (raw banded distances, INF16 sentinel): the gathers
    # move half the bytes.  Safe because finite d < INF16=40000 and
    # clamped metric <= WBIG16=20000 never wrap in uint16, and a finite
    # d_nbr with a usable edge implies a finite d_self (so the
    # d_nbr + w == d_self compare never matches a saturated self).
    u16 = drev.dtype == jnp.uint16
    inf = INF16 if u16 else INF32

    def slot_on(k):
        """[N, P] bool: out-slot k of every router is an ECMP hop."""
        eidk = out.eid[:, k]
        ok = (eidk >= 0) & jnp.take(edge_up, jnp.maximum(eidk, 0))
        w = jnp.take(edge_metric, jnp.maximum(eidk, 0))  # [N]
        if u16:
            w = clamp_metric_u16(w)
        nbr = out.nbr[:, k]
        d_nbr = jnp.take(drev, nbr, axis=0)  # [N, P]
        nbr_ov = jnp.take(node_overloaded, nbr)  # [N]
        return (
            ok[:, None]
            & (d_nbr < inf)
            & (d_nbr + w[:, None] == d_self)
            & (~nbr_ov[:, None] | (d_nbr == 0))
        )

    if n_words == 1:
        # single-word fast path (any topology with <=32 unique
        # out-neighbors per node): a flat uint32 OR chain, no [N, P, W]
        # broadcast scaffolding per slot
        bitmap2d = jnp.zeros((n, p_dim), dtype=jnp.uint32)
        for k in range(k_pad):
            slot = out.slot[:, k]
            bit = jnp.where(
                slot >= 0,
                jnp.uint32(1)
                << (jnp.maximum(slot, 0) % 32).astype(jnp.uint32),
                jnp.uint32(0),
            )  # [N]
            bitmap2d = bitmap2d | jnp.where(
                slot_on(k), bit[:, None], jnp.uint32(0)
            )
        return bitmap2d[:, :, None]

    bitmap = jnp.zeros((n, p_dim, n_words), dtype=jnp.uint32)
    for k in range(k_pad):
        on = slot_on(k)
        slot = out.slot[:, k]
        bit = jnp.where(
            slot >= 0,
            jnp.uint32(1) << (jnp.maximum(slot, 0) % 32).astype(jnp.uint32),
            jnp.uint32(0),
        )  # [N]
        word_sel = (jnp.maximum(slot, 0) // 32)[:, None] == jnp.arange(
            n_words
        )[None, :]  # [N, W]
        contrib = jnp.where(
            on[:, :, None] & word_sel[:, None, :],
            bit[:, None, None],
            jnp.uint32(0),
        )
        bitmap = bitmap | contrib
    return bitmap


def reduced_all_sources(
    dest_ids,
    reverse_runner,
    out: OutEll,
    edge_metric,
    edge_up,
    node_overloaded,
    n_sweeps: Optional[int] = None,
    fused: Optional[bool] = None,
    init_dist=None,
    maps: Optional[EpilogueMaps] = None,
    check_every: int = 4,
    max_blocks: int = 64,
    pallas_run=None,
):
    """Fleet-wide route-building input in one device round:
    (dist [N*, P] jax — dist[v, p] = dist(v -> p), nh_bitmap
    [N, P, W] uint32 jax, converged bool).  dist is raw uint16 with the
    INF16 sentinel when the banded kernel's small-distance mode engages
    (half the bitmap-gather bytes), int32/INF32 otherwise — consumers
    key on dtype (decision.fleet._row_i32).  The [N*, P] orientation is
    the relax kernel's NATIVE layout (round-5: the former [P, N*]
    contract paid two 200MB-scale transposes per product round), and it
    is also what consumers want — a router's row fetch is contiguous.

    `reverse_runner` is an ops.banded.SpfRunner over the REVERSED edge
    arrays (benchmarks.synthetic.reversed_topology / csr mirror).  With
    `n_sweeps` the call is non-adaptive (bench timing; caller asserts
    convergence).  Adaptive mode doubles the runner's hint on a False
    verdict — then REFINES the hint back down by bounded binary probes,
    exactly like SpfRunner.forward: a doubling overshoot would otherwise
    tax every later product round with up to 2x surplus supersweeps.

    The DEFAULT path on banded topologies (`fused=None`) is the fused
    PROGRESSIVE program (_fused_progressive_banded): relax, verify and
    bitmap in one dispatch, the relax early-exiting on-device at the
    actual fixed point (lax.while_loop over supersweep blocks of
    `check_every`) and the bitmap folded into the verification pass so
    the [N, P] product is read once.  This reverses the round-5 call:
    that fusion merely concatenated the relax with a SECOND full bitmap
    gather pass, which XLA scheduled worse than two pipelined
    dispatches; with the bitmap riding the verification gathers there
    is no second pass left to schedule, and the fixed-sweep hint (and
    its overshoot) disappears entirely.  `fused=False` forces the
    legacy two-dispatch path; `fused=True` with `n_sweeps` runs the
    legacy fixed-sweep fused program (bench timing).

    `init_dist` ([N*, P], either distance dtype) warm-starts the relax
    from a caller-PROVEN elementwise upper bound — the previous product
    of the same (node universe, dest set) after gated topology changes
    (see ops.banded.spf_forward_banded for the safety argument and
    decision.fleet for both gate directions).  A converged warm round
    equals the cold one exactly.  Banded path only (the ELL fallback
    cold-starts).

    `maps` (build_epilogue_maps) feeds the fused epilogue; built here
    on first need when not supplied — callers that rebuild repeatedly
    should build it once per topology snapshot.

    `pallas_run` routes the fused progressive program through the
    Pallas demotion contract (ops.pallas_kernels.run_with_fallback
    signature): the epilogue runs as the hand-tiled kernel when the
    policy engages, demoting to the identical lax program on any
    failure.  None means env-policy with no engine accounting (the
    engine front-end passes `DeviceResidencyEngine.run_pallas`, which
    adds the `device.engine.pallas_*` counters and the chaos seam).
    Legacy paths (`fused=False`, explicit `n_sweeps`) never engage
    Pallas — the kernel exists for the progressive epilogue only."""
    import numpy as _np

    if fused and n_sweeps is not None and init_dist is not None:
        # the legacy fixed-sweep fused program has no dist0 input
        raise ValueError("fused=True with n_sweeps does not support init_dist")

    dest_ids = jnp.asarray(_np.asarray(dest_ids, dtype=_np.int32))

    if (
        fused is not False
        and n_sweeps is None
        and reverse_runner.bg is not None
    ):
        # fast path: one progressive fused program, no sweep hint
        if maps is None:
            maps = build_epilogue_maps(reverse_runner.bg, out)
        _, _, r_met, r_up, r_ov = reverse_runner.call_arrays()

        def run_prog(
            small: bool, pallas: bool = False, interp: bool = False
        ):
            return _fused_progressive_banded(
                dest_ids,
                reverse_runner.bg,
                r_up,
                r_met,
                r_ov,
                maps.resid_slot,
                maps.band_slot,
                init_dist,
                check_every=check_every,
                max_blocks=max_blocks,
                depth=reverse_runner.depth,
                resid_rounds=reverse_runner.resid_rounds,
                small_dist=small,
                chord_mode=reverse_runner.chord_mode,
                n_words=out.n_words,
                pallas=pallas,
                pallas_interpret=interp,
            )

        prun = pallas_run
        if prun is None:
            from . import pallas_kernels as _pk

            prun = _pk.run_with_fallback
        small = reverse_runner.small_dist
        dist, bitmap, ok, blocks = prun(
            "product",
            lambda interp: run_prog(small, pallas=True, interp=interp),
            lambda: run_prog(small),
        )
        # One explicit fetch for the convergence certificate + block count:
        # the retry/hint decisions below are host control flow, and reading
        # the two scalars piecemeal (bool(ok), bool(ok), int(blocks)) would
        # block the dispatch thread up to three times per round.
        ok_h, blocks_h = jax.device_get((ok, blocks))
        if small and not ok_h:
            # saturation presents as non-convergence: latch uint16 off
            # (the SpfRunner.adapt discipline) and retry once in int32
            reverse_runner.small_allowed = False
            dist, bitmap, ok, blocks = prun(
                "product",
                lambda interp: run_prog(False, pallas=True, interp=interp),
                lambda: run_prog(False),
            )
            ok_h, blocks_h = jax.device_get((ok, blocks))
        if ok_h and init_dist is None:
            # teach the fixed-sweep hint from the cold progressive run
            # (warm runs converge in delta-sized counts — not a valid
            # cold budget, so they never write it)
            reverse_runner.hint = max(1, int(blocks_h) * check_every)
        return dist, bitmap, bool(ok_h)

    def run(sweeps: int, want_bitmap: bool):
        # the one-program fusion exists on the banded path only; the ELL
        # fallback computes the bitmap separately AFTER convergence, so
        # failed adaptive attempts never pay a discarded bitmap pass
        if want_bitmap and fused and reverse_runner.bg is not None:
            return _fused_product(
                dest_ids,
                reverse_runner,
                out,
                edge_metric,
                edge_up,
                node_overloaded,
                sweeps,
            )
        # raw uint16 distances when the banded kernel runs small: the
        # bitmap pass gathers half the bytes (ecmp_bitmap keys on dtype)
        dist, _, ok = reverse_runner.run_once(
            dest_ids,
            sweeps,
            want_dag=False,
            raw_u16=True,
            transpose=False,
            dist0=init_dist,
        )
        return dist, None, ok

    if n_sweeps is not None:
        dist, bitmap, ok = run(n_sweeps, want_bitmap=True)
    else:
        # shared adaptation machinery (double / saturation-fallback /
        # capped refine-down): SpfRunner.adapt
        def attempt(sweeps: int):
            r = run(sweeps, want_bitmap=True)
            # adapt() decides double/refine from the convergence verdict;
            # one scalar sync per attempt is the price of adaptive sweep
            # control  # openr: disable=jit-dispatch-sync
            return r, bool(r[2])

        dist, bitmap, ok = reverse_runner.adapt(
            "hint",
            attempt=attempt,
            # same adaptive-control verdict  # openr: disable=jit-dispatch-sync
            probe=lambda s: bool(run(s, want_bitmap=False)[2]),
            eff_small=lambda: reverse_runner.small_dist,
        )
    if bitmap is None:
        bitmap = ecmp_bitmap_from_reverse_dist(
            dist, out, edge_metric, edge_up, node_overloaded, out.n_words
        )
    # Contract: the certificate is a HOST bool on every return path (the
    # fused path above fetches it with device_get), so callers can branch
    # on it without paying another sync.  On the adaptive path the scalar
    # was already realized by attempt(); this bool() is a cached read.
    return dist, bitmap, bool(ok)


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_supersweeps",
        "depth",
        "resid_rounds",
        "small_dist",
        "n_words",
        "chord_mode",
    ),
)
def _fused_product_banded(
    dest_ids,
    bg,
    r_edge_src,
    r_edge_dst,
    r_edge_metric,
    r_edge_up,
    node_overloaded,
    out: OutEll,
    f_edge_metric,
    f_edge_up,
    n_supersweeps: int,
    depth: int,
    resid_rounds: int,
    small_dist: bool,
    n_words: int,
    chord_mode: bool = False,
):
    """Reverse relax + fleet ECMP bitmaps as ONE compiled program (banded
    path).  Bitmaps are computed unconditionally; on a failed convergence
    verdict the caller re-runs, wasting only the cheap bitmap pass."""
    from .banded import spf_forward_banded

    # native [N, S] == the [N*, P] drev layout, transpose-free on both
    # sides (raw uint16 when small — the bitmap pass gathers half bytes)
    dist, _, ok = spf_forward_banded(
        dest_ids,
        bg,
        r_edge_src,
        r_edge_dst,
        r_edge_metric,
        r_edge_up,
        node_overloaded,
        n_supersweeps=n_supersweeps,
        depth=depth,
        resid_rounds=resid_rounds,
        small_dist=small_dist,
        want_dag=False,
        chord_mode=chord_mode,
        raw_u16=True,
        transpose=False,
    )
    bitmap = ecmp_bitmap_from_reverse_dist(
        dist, out, f_edge_metric, f_edge_up, node_overloaded, n_words
    )
    return dist, bitmap, ok


def _fused_product(
    dest_ids,
    reverse_runner,
    out: OutEll,
    f_edge_metric,
    f_edge_up,
    node_overloaded,
    n_sweeps: int,
):
    """One-dispatch reduced product (banded path only; callers fall back
    to run_once + a post-convergence bitmap pass on ELL topologies)."""
    assert reverse_runner.bg is not None
    r_src, r_dst, r_metric, r_up, r_ov = reverse_runner.call_arrays()
    return _fused_product_banded(
        dest_ids,
        reverse_runner.bg,
        r_src,
        r_dst,
        r_metric,
        r_up,
        r_ov,
        out,
        jnp.asarray(f_edge_metric),
        jnp.asarray(f_edge_up),
        n_supersweeps=n_sweeps,
        depth=reverse_runner.depth,
        resid_rounds=reverse_runner.resid_rounds,
        small_dist=reverse_runner.small_dist,
        n_words=out.n_words,
        chord_mode=reverse_runner.chord_mode,
    )
