"""Reduced-output all-sources SPF: the product route building consumes.

The literal all-sources [N, N] distance matrix at 100k nodes is 40 GB —
un-materializable on one chip, and nobody reads it: the reference's
buildRouteDb consumes, per router, only the distances/next-hops toward
the P prefix-originating nodes (openr/decision/Decision.cpp:615-793
createRouteForPrefix reads best-entry node distances; getNextHopsThrift's
LFA-free ECMP keeps neighbor u for destination t iff
metric(v,u) + dist(u,t) == dist(v,t), Decision.cpp:1296-1300).

So the whole-fleet product is all-sources-to-P-destinations, and on the
reversed graph that is ONE P-source SSSP:

    dist(v -> p)  ==  reverse-SSSP from p over reversed edges, read at v.

Drain semantics survive reversal exactly: the kernel blocks relaxation
through an overloaded predecessor unless its distance is 0 (ops.sssp /
ops.banded).  On the reversed graph the d==0 exception lands on the
original DESTINATION p (whose original in-edges are always usable), and
an overloaded original source v is reached by a final reverse hop whose
predecessor is v's neighbor — never blocked — while overloaded
intermediates still block as reverse-edge tails.  A case-by-case check
of (source, intermediate, destination) overload shows equality with the
forward rule; tests/test_banded.py (TestReducedAllSources) asserts it against the oracle.

The fused consumer pass then emits, per (router v, destination p), the
bit-packed ECMP next-hop set straight from the reverse distances —
gathers over a per-node out-neighbor table, no scatters — so the entire
fleet-wide route-building input is ONE device call returning
[N, P] int32 distances + [N, P, W] uint32 next-hop bitmaps.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .sssp import INF32


class OutEll(NamedTuple):
    """Per-node out-edge table in original node order (host-built)."""

    nbr: jax.Array  # [N, K] int32 — out-neighbor node id (pad 0)
    eid: jax.Array  # [N, K] int32 — directed edge id; -1 pad
    slot: jax.Array  # [N, K] int32 — rank among the node's sorted unique
    #   out-neighbors (parallel links share a slot); -1 pad
    n_words: int  # ceil(max_slots / 32) — static


def build_out_ell(
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    n_edges: int,
    n_nodes: int,
    out_slot: Optional[np.ndarray] = None,
) -> OutEll:
    """Vectorized out-edge table build.  `out_slot` (per-edge slot ids,
    csr._build_out_slots layout) is recomputed here when not supplied."""
    src = np.asarray(edge_src[:n_edges], dtype=np.int64)
    dst = np.asarray(edge_dst[:n_edges], dtype=np.int64)
    if out_slot is None:
        from ..decision.csr import _build_out_slots

        out_slot, _ = _build_out_slots(
            np.asarray(edge_src), np.asarray(edge_dst), n_edges
        )
    deg = np.bincount(src, minlength=n_nodes)
    k = int(deg.max()) if n_edges else 1
    k_pad = 1
    while k_pad < max(k, 1):
        k_pad *= 2
    order = np.argsort(src, kind="stable")
    e_sorted = order
    s_sorted = src[order]
    starts = np.searchsorted(s_sorted, np.arange(n_nodes))
    pos = np.arange(len(order)) - starts[s_sorted]
    nbr = np.zeros((n_nodes, k_pad), dtype=np.int32)
    eid = np.full((n_nodes, k_pad), -1, dtype=np.int32)
    slot = np.full((n_nodes, k_pad), -1, dtype=np.int32)
    nbr[s_sorted, pos] = dst[e_sorted].astype(np.int32)
    eid[s_sorted, pos] = e_sorted.astype(np.int32)
    slot[s_sorted, pos] = out_slot[:n_edges][e_sorted]
    max_slots = int(out_slot[:n_edges].max()) + 1 if n_edges else 1
    return OutEll(
        nbr=jnp.asarray(nbr),
        eid=jnp.asarray(eid),
        slot=jnp.asarray(slot),
        n_words=max(1, -(-max_slots // 32)),
    )


@functools.partial(jax.jit, static_argnames=("n_words",))
def ecmp_bitmap_from_reverse_dist(
    drev: jax.Array,  # [P, N*] int32 — reverse-SSSP distances (dist(v->p));
    #   N* is n_nodes (banded kernel) or node_capacity (ELL fallback)
    out: OutEll,
    edge_metric: jax.Array,  # [E_cap] int32
    edge_up: jax.Array,  # [E_cap] bool
    node_overloaded: jax.Array,  # [N_cap] bool
    n_words: int,
) -> jax.Array:
    """[N, P, W] uint32: bit s of (v, p) set iff out-slot s of router v
    is an ECMP next-hop toward destination p — the reference's LFA-free
    condition metric(v,u) + dist(u,p) == dist(v,p)
    (openr/decision/Decision.cpp:1296-1300), evaluated fleet-wide from
    reverse distances.  Gather-only.

    Drain: the reference draws ECMP neighbors from the source's
    drain-respecting SPF tree (nextHopNodes is keyed by
    shortestPathsFromHere nextHops, Decision.cpp:1182-1260), so an
    overloaded neighbor u is a valid next-hop ONLY as the destination
    itself — the same own-source/destination exception the relax kernels
    encode, here as d(u,p) == 0."""
    n, k_pad = out.nbr.shape
    drev_T = drev.T  # [N*, P]
    p_dim = drev.shape[0]
    bitmap = jnp.zeros((n, p_dim, n_words), dtype=jnp.uint32)
    d_self = drev_T[:n]  # [N, P]
    for k in range(k_pad):
        eidk = out.eid[:, k]
        ok = (eidk >= 0) & jnp.take(edge_up, jnp.maximum(eidk, 0))
        w = jnp.take(edge_metric, jnp.maximum(eidk, 0))  # [N]
        nbr = out.nbr[:, k]
        d_nbr = jnp.take(drev_T, nbr, axis=0)  # [N, P]
        nbr_ov = jnp.take(node_overloaded, nbr)  # [N]
        on = (
            ok[:, None]
            & (d_nbr < INF32)
            & (d_nbr + w[:, None] == d_self)
            & (~nbr_ov[:, None] | (d_nbr == 0))
        )  # [N, P]
        slot = out.slot[:, k]
        bit = jnp.where(
            slot >= 0,
            jnp.uint32(1) << (jnp.maximum(slot, 0) % 32).astype(jnp.uint32),
            jnp.uint32(0),
        )  # [N]
        word_sel = (jnp.maximum(slot, 0) // 32)[:, None] == jnp.arange(
            n_words
        )[None, :]  # [N, W]
        contrib = jnp.where(
            on[:, :, None] & word_sel[:, None, :],
            bit[:, None, None],
            jnp.uint32(0),
        )
        bitmap = bitmap | contrib
    return bitmap


def reduced_all_sources(
    dest_ids,
    reverse_runner,
    out: OutEll,
    edge_metric,
    edge_up,
    node_overloaded,
    n_sweeps: Optional[int] = None,
):
    """Fleet-wide route-building input in one device round:
    (dist [P, N*] int32 jax — dist[p, v] = dist(v -> p), nh_bitmap
    [N, P, W] uint32 jax, converged bool).

    `reverse_runner` is an ops.banded.SpfRunner over the REVERSED edge
    arrays (benchmarks.synthetic.reversed_topology / csr mirror).  With
    `n_sweeps` the call is non-adaptive (bench timing; caller asserts
    convergence).  Adaptive mode doubles the runner's hint on a False
    verdict without re-running converged work — the distances of the
    converged attempt feed the bitmap pass directly."""
    import numpy as _np

    dest_ids = jnp.asarray(_np.asarray(dest_ids, dtype=_np.int32))
    while True:
        sweeps = n_sweeps if n_sweeps is not None else reverse_runner.hint
        dist, _, ok = reverse_runner.run_once(
            dest_ids, sweeps, want_dag=False
        )
        if n_sweeps is not None or bool(ok):
            break
        if reverse_runner.small_dist and reverse_runner.hint >= 32:
            # same uint16-saturation fallback as SpfRunner.forward
            # (keyed on the effective mode of the failed run)
            reverse_runner.small_allowed = False
        else:
            reverse_runner.hint = sweeps * 2
    bitmap = ecmp_bitmap_from_reverse_dist(
        dist, out, edge_metric, edge_up, node_overloaded, out.n_words
    )
    return dist, bitmap, ok
