from .sssp import (
    INF32,
    EllBucket,
    EllGraph,
    batched_sssp,
    batched_sssp_ell,
    build_ell,
    first_hops_ell,
    sp_dag_mask,
    sp_dag_mask_from_T,
    spf_forward_ell,
    spf_forward_ell_masked,
    spf_forward_full,
)

__all__ = [
    "INF32",
    "EllBucket",
    "EllGraph",
    "batched_sssp",
    "batched_sssp_ell",
    "build_ell",
    "first_hops_ell",
    "sp_dag_mask",
    "sp_dag_mask_from_T",
    "spf_forward_ell",
    "spf_forward_ell_masked",
    "spf_forward_full",
]
