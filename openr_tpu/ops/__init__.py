from .sssp import (
    INF32,
    batched_sssp,
    first_hop_matrix,
    sp_dag_mask,
)

__all__ = ["INF32", "batched_sssp", "sp_dag_mask", "first_hop_matrix"]
