"""Batched failure-protection kernels: SRLG what-if + TI-LFA backups.

These are the NEW capabilities unlocked by the batch dimension
(BASELINE.json configs #4/#5) — the reference computes nothing like them
(its solver answers one source at a time; what-if analysis would need a
full Decision re-run per scenario).

- `srlg_what_if`: evaluate F failure scenarios (each an edge mask, e.g.
  all members of a shared-risk link group) x S sources in ONE device
  call: dist [F, S, N].  Operators use this for maintenance planning:
  "which prefixes lose reachability / degrade if this conduit is cut?"

- `ti_lfa_backups`: per-source per-out-edge post-convergence distances:
  for each of a source's out-edges, distances with that edge (and its
  reverse) failed — exactly the state TI-LFA needs to pick loop-free
  backup next-hops and repair segments (P/Q analysis happens on these
  distance tensors).

Both reuse the fixed-point relaxation kernel (ops.sssp.batched_sssp);
the batch rows are independent, so they shard collective-free over the
"batch" mesh axis (openr_tpu.parallel).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .sssp import (
    INF32,
    batched_sssp,
    make_dist0,
    make_relax_allowed,
    sp_dag_mask,
    spf_forward_ell_masked,
)


def srlg_what_if(
    sources: jax.Array,  # [S] int32
    edge_src: jax.Array,  # [E]
    edge_dst: jax.Array,  # [E]
    edge_metric: jax.Array,  # [E]
    edge_up: jax.Array,  # [E] bool
    node_overloaded: jax.Array,  # [N] bool
    scenario_masks: jax.Array,  # [F, E] bool — True = edge SURVIVES
    ell=None,  # ops.sssp.EllGraph: run the production bucketed-ELL kernel
    runner=None,  # ops.banded.SpfRunner: band-aware fixed-sweep execution
) -> jax.Array:
    """Distances under each failure scenario: [F, S, N] int32.

    With `runner` (the production path), the (scenario x source) cross
    product flattens onto the fixed-sweep band-aware kernel and the
    result is host numpy.  With `ell`, the flattened batch runs the
    while_loop masked-ELL kernel on device; the bare edge-list fallback
    remains for tiny graphs.  Distances only: the SP-DAG nobody reads
    here is never built."""
    if runner is not None:
        _check_runner_arrays(
            runner, edge_src, edge_dst, edge_metric, edge_up, node_overloaded
        )
        f_dim = scenario_masks.shape[0]
        s_dim = sources.shape[0]
        flat_sources = jnp.tile(jnp.asarray(sources), f_dim)
        flat_masks = jnp.repeat(
            jnp.asarray(scenario_masks), s_dim, axis=0
        )
        dist, _ = runner.forward(
            flat_sources, extra_edge_mask=flat_masks, want_dag=False
        )
        return dist.reshape(f_dim, s_dim, -1)
    return _srlg_what_if_device(
        sources,
        edge_src,
        edge_dst,
        edge_metric,
        edge_up,
        node_overloaded,
        scenario_masks,
        ell,
    )


@jax.jit
def _srlg_what_if_device(
    sources,
    edge_src,
    edge_dst,
    edge_metric,
    edge_up,
    node_overloaded,
    scenario_masks,
    ell=None,
):
    n_nodes = node_overloaded.shape[0]
    if ell is not None:
        f_dim = scenario_masks.shape[0]
        s_dim = sources.shape[0]
        flat_sources = jnp.tile(sources, f_dim)  # [F*S]
        flat_masks = jnp.repeat(scenario_masks, s_dim, axis=0)  # [F*S, E]
        dist, _ = spf_forward_ell_masked(
            flat_sources,
            ell,
            edge_src,
            edge_dst,
            edge_metric,
            edge_up,
            node_overloaded,
            flat_masks,
            want_dag=False,
        )
        return dist.reshape(f_dim, s_dim, n_nodes)
    base_allowed = make_relax_allowed(
        sources, edge_src, edge_up, node_overloaded
    )  # [S, E]

    def one_scenario(mask):
        allowed = base_allowed & mask[None, :]
        return batched_sssp(
            make_dist0(sources, n_nodes), edge_src, edge_dst, edge_metric, allowed
        )

    return jax.lax.map(one_scenario, scenario_masks)


@jax.jit
def srlg_reachability_loss(
    baseline_dist: jax.Array,  # [S, N]
    scenario_dist: jax.Array,  # [F, S, N]
) -> tuple[jax.Array, jax.Array]:
    """Per scenario: (#newly-unreachable pairs, #degraded pairs)."""
    was_reachable = baseline_dist < INF32
    now_unreachable = was_reachable[None] & (scenario_dist >= INF32)
    degraded = (
        was_reachable[None]
        & (scenario_dist < INF32)
        & (scenario_dist > baseline_dist[None])
    )
    axes = (1, 2)
    return now_unreachable.sum(axes), degraded.sum(axes)


def ti_lfa_backups(
    source: jax.Array,  # scalar int32 — protected source node
    out_edge_ids: jax.Array,  # [D] int32 — source's out-edge ids (-1 pad)
    edge_src: jax.Array,  # [E]
    edge_dst: jax.Array,  # [E]
    edge_metric: jax.Array,  # [E]
    edge_up: jax.Array,  # [E] bool
    node_overloaded: jax.Array,  # [N] bool
    reverse_edge_ids: jax.Array,  # [E] int32 — id of each edge's reverse
    max_degree: int,
    ell=None,  # ops.sssp.EllGraph: run the production bucketed-ELL kernel
    runner=None,  # ops.banded.SpfRunner: band-aware fixed-sweep execution
):
    """Post-convergence SPF per protected out-edge.

    Returns (dist [D, N], dag [D, E]): row d = distances / SP-DAG with
    out_edge_ids[d] (and its reverse) removed.  A backup next-hop for
    destination v on failure of edge d is any first hop of row d's DAG;
    TI-LFA P/Q spaces and repair-segment endpoints derive from these plus
    per-neighbor distance rows (computed by the same kernel batched over
    sources).  With `runner` the masks run the band-aware fixed-sweep
    kernel and numpy arrays come back; otherwise device arrays."""
    if runner is not None:
        import numpy as _np

        _check_runner_arrays(
            runner, edge_src, edge_dst, edge_metric, edge_up, node_overloaded
        )
        d_dim = int(out_edge_ids.shape[0])
        survives = build_edge_failure_masks(
            out_edge_ids, reverse_edge_ids, edge_src.shape[0]
        )
        sources = _np.full(d_dim, int(source), dtype=_np.int32)
        return runner.forward(sources, extra_edge_mask=survives)
    return _ti_lfa_backups_device(
        source,
        out_edge_ids,
        edge_src,
        edge_dst,
        edge_metric,
        edge_up,
        node_overloaded,
        reverse_edge_ids,
        max_degree=max_degree,
        ell=ell,
    )


@functools.partial(jax.jit, static_argnames=("max_degree",))
def _ti_lfa_backups_device(
    source,
    out_edge_ids,
    edge_src,
    edge_dst,
    edge_metric,
    edge_up,
    node_overloaded,
    reverse_edge_ids,
    max_degree: int,
    ell=None,
) -> tuple[jax.Array, jax.Array]:
    del max_degree  # shape already fixed by out_edge_ids
    n_edges = edge_src.shape[0]
    d_dim = out_edge_ids.shape[0]

    edge_ids = jnp.arange(n_edges, dtype=jnp.int32)
    fail = out_edge_ids  # [D]
    fail_rev = jnp.where(
        fail >= 0, reverse_edge_ids[jnp.maximum(fail, 0)], -1
    )  # [D]
    # per-row exclusion mask: True = edge survives
    survives = (edge_ids[None, :] != fail[:, None]) & (
        edge_ids[None, :] != fail_rev[:, None]
    )  # [D, E]

    sources = jnp.broadcast_to(source, (d_dim,)).astype(jnp.int32)
    if ell is not None:
        return spf_forward_ell_masked(
            sources,
            ell,
            edge_src,
            edge_dst,
            edge_metric,
            edge_up,
            node_overloaded,
            survives,
        )
    allowed = make_relax_allowed(
        sources, edge_src, edge_up, node_overloaded, survives
    )
    n_nodes = node_overloaded.shape[0]
    dist = batched_sssp(
        make_dist0(sources, n_nodes), edge_src, edge_dst, edge_metric, allowed
    )
    dag = sp_dag_mask(dist, edge_src, edge_dst, edge_metric, allowed)
    return dist, dag


def _check_runner_arrays(
    runner, edge_src, edge_dst, edge_metric, edge_up, node_overloaded
) -> None:
    """The runner path answers from the arrays captured in the runner —
    reject a call that passes DIFFERENT arrays (e.g. a modified edge_up
    copy), which would otherwise be silently ignored."""
    import numpy as _np

    r_src, r_dst, r_metric, r_up, r_ov = runner.arrays
    for mine, theirs, name in (
        (edge_src, r_src, "edge_src"),
        (edge_dst, r_dst, "edge_dst"),
        (edge_metric, r_metric, "edge_metric"),
        (edge_up, r_up, "edge_up"),
        (node_overloaded, r_ov, "node_overloaded"),
    ):
        if _np.asarray(mine) is not _np.asarray(theirs) and not (
            _np.shares_memory(_np.asarray(mine), _np.asarray(theirs))
            or _np.array_equal(_np.asarray(mine), _np.asarray(theirs))
        ):
            raise ValueError(
                f"runner path: {name} differs from the runner's captured "
                "array; mutate the runner's arrays (or drop runner=) "
                "instead of passing a modified copy"
            )


def build_edge_failure_masks(
    out_edge_ids, reverse_edge_ids, edge_capacity: int
):
    """[D, E_cap] survives-mask for per-edge failure rows: row d excludes
    out_edge_ids[d] and its reverse (-1 pads exclude nothing).  Shared by
    ti_lfa_backups and the bench harness so the pad-guard semantics live
    in exactly one place."""
    import numpy as np

    fail = np.asarray(out_edge_ids)
    rev = np.asarray(reverse_edge_ids)
    fail_rev = np.where(fail >= 0, rev[np.maximum(fail, 0)], -1)
    edge_ids = np.arange(edge_capacity, dtype=np.int64)
    # a -1 entry (pad) must exclude NO edge: compare against -2 sentinels
    fail_cmp = np.where(fail >= 0, fail, -2)
    rev_cmp = np.where(fail_rev >= 0, fail_rev, -2)
    return (edge_ids[None, :] != fail_cmp[:, None]) & (
        edge_ids[None, :] != rev_cmp[:, None]
    )


def build_reverse_edge_ids(edge_src, edge_dst) -> "jax.Array":
    """Host helper: for each directed edge (u, v), the id of (v, u); -1 if
    absent.  O(E) dict pass over numpy arrays."""
    import numpy as np

    src = np.asarray(edge_src)
    dst = np.asarray(edge_dst)
    # Parallel links between the same node pair must pair up one-to-one:
    # the k-th (u, v) edge reverses to the k-th (v, u) edge, so a failed
    # directed edge is paired with the reverse of *its own* link instance,
    # not the first parallel link found.
    index: dict[tuple[int, int], list[int]] = {}
    occurrence = np.zeros(len(src), dtype=np.int64)
    for e in range(len(src)):
        bucket = index.setdefault((int(src[e]), int(dst[e])), [])
        occurrence[e] = len(bucket)
        bucket.append(e)
    rev = np.full(len(src), -1, dtype=np.int32)
    for e in range(len(src)):
        candidates = index.get((int(dst[e]), int(src[e])), [])
        k = int(occurrence[e])
        if k < len(candidates):
            rev[e] = candidates[k]
    return jnp.asarray(rev)
