"""Batched delta propagation for the reduced all-sources product.

A flap storm of k LinkState events today costs k (or, coalesced, still
full-width) [N, P] fleet products even though each event perturbs a
tiny frontier.  These kernels process ONE COALESCED BATCH of events as
two device programs whose relax work is proportional to the affected
frontier, not k*N*P:

1. `delta_frontier` — certify, on device, which (router, dest) entries
   of the previous converged product a batch of edge/node deltas can
   possibly have changed.  The worsening direction runs the EXACT
   support-loss rule over the OLD graph's shortest-path DAG: an entry
   is affected iff EVERY tight support (a slot achieving candidate
   equality) is either itself worsened or leads to an affected
   neighbor.  This is the sharp refinement of `affected_mask`'s
   ANY-tight-chain OR-rule — under ECMP permutation ties a worsened
   edge is tight almost everywhere, but a row that keeps ONE intact
   support keeps its distance, so the AND-rule is what stops a flap
   storm from saturating the column frontier.  Tight supports strictly
   decrease the distance (positive metrics), so the support graph is
   acyclic and the monotone fixpoint is exact, not heuristic: every
   unaffected entry retains an intact support chain of unworsened
   edges down to its source, hence its old value survives in the new
   graph.  The improvement direction fires the NEW graph's exact relax
   candidates at the improved slots against the old distances — a
   candidate with cand <= d (note: <=, an equality-creating improvement
   changes the ECMP bitmap without moving the distance) marks its
   column.  A destination column outside the union is PROVEN unchanged.

2. `delta_relax` — gather ONLY the affected destination columns (padded
   to a frontier-size bucket), re-relax them under the progressive
   on-device while_loop with the affected entries re-set to INF (the
   `_affected_init` upper-bound argument, per column), run the fused
   verify+bitmap epilogue over the [N, Pb] slab, and write the columns
   back into the DONATED full-width product with a scatter-free
   hit-matrix select.  A converged delta round equals the cold full
   product bit-for-bit on every column.

3. `delta_rows_bitmap` — after an edge-SET change, a node that gained
   or lost an out-neighbor has its per-slot bit ENCODING shifted
   (OutEll.slot is the rank among sorted unique out-neighbors) even for
   destination columns whose routes did not change.  This kernel
   re-encodes just those rows' bitmap words across all P columns from
   the (already exact) distances — the same LFA-free condition as
   `ecmp_bitmap_from_reverse_dist`, restricted to a bucketed row set,
   written back through the donated bitmap.

The decision-layer coalescer (openr_tpu.decision.delta) folds the k
pending events into the host-built slot masks these kernels consume and
falls back, bit-exactly, to the full fused product whenever the
frontier bound is exceeded or certification fails.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .sssp import INF16, INF32, clamp_metric_u16, u16_saturation_verdict


@functools.partial(
    jax.jit, static_argnames=("small_dist", "max_iters")
)
def delta_frontier(
    dist: jax.Array,  # [N*, P] — previous CONVERGED reverse product
    old_bg,  # previous topology's banded decomposition
    o_edge_up: jax.Array,  # previous reverse runtime arrays (OLD graph)
    o_edge_metric: jax.Array,
    o_node_overloaded: jax.Array,
    worsened_resid: jax.Array,  # [N, K_old] bool — OLD-layout worsened slots
    worsened_band: jax.Array,  # [B_old, N] bool
    new_bg,  # new topology's banded decomposition
    n_edge_up: jax.Array,  # new reverse runtime arrays (NEW graph)
    n_edge_metric: jax.Array,
    n_node_overloaded: jax.Array,
    improved_resid: jax.Array,  # [N, K_new] bool — NEW-layout improved slots
    improved_band: jax.Array,  # [B_new, N] bool
    small_dist: bool = False,
    max_iters: int = 128,
):
    """Certified affected frontier of one coalesced event batch.

    Returns (aff [N, P] bool, col_mask [P] bool, done bool):
    - aff: entries whose old value the WORSENED edges invalidated — the
      exact support-loss set: a row is affected iff every OLD tight
      support is worsened or leads to an affected neighbor (AND-rule
      over the acyclic tight-support DAG; see the module docstring).
    - col_mask: destination columns needing re-relax — any affected
      entry, OR any WORSENED slot that was tight (the row may keep its
      distance through an intact alternative, but the worsened slot's
      ECMP bit turns off — a route change with no distance change), OR
      any improved slot whose NEW exact candidate fires at cand <= d
      (strict improvements move distances; equality-creating ones move
      only the ECMP bitmap, hence <=).
    - done: the support-loss fixpoint was reached within max_iters;
      False means the caller MUST fall back to the full product (an
      under-propagated set is silently wrong).

    Source rows can never mark themselves (d == 0 is guarded, and a
    candidate into a pinned 0-distance source is >= 1), so dest
    re-pinning stays delta_relax's job.  Cost: bool-matrix sweeps plus
    two candidate passes — no [N, P] distance mutation happens here.
    """
    from .banded import _RelaxOps

    n = old_bg.n_nodes
    old_ops = _RelaxOps(
        old_bg,
        o_edge_up,
        o_edge_metric,
        o_node_overloaded[:n],
        0,
        1,
        None,
        small_dist,
        False,
        dist.dtype,
    )
    d_old = dist[:n]
    fin = d_old < old_ops.inf

    # bitmap-only seeds: a worsened slot that was tight had its ECMP
    # bit ON; even when the row keeps its distance through an intact
    # alternative support, that bit must turn OFF — the column needs
    # the re-relax epilogue's re-encode
    bit_off = jnp.zeros(d_old.shape, dtype=jnp.bool_)
    for k in range(old_ops.n_resid):
        tight = fin & (old_ops.resid_cand(d_old, k) == d_old)
        bit_off = bit_off | (tight & worsened_resid[:, k][:, None])
    for b in range(old_ops.n_bands):
        tight = fin & (old_ops.band0_cand(d_old, b) == d_old)
        bit_off = bit_off | (tight & worsened_band[b][:, None])

    def sweep(aff):
        # a row keeps its old value iff SOME tight support survives:
        # an unworsened slot whose supporting neighbor is unaffected
        intact = jnp.zeros(d_old.shape, dtype=jnp.bool_)
        for k in range(old_ops.n_resid):
            tight = fin & (old_ops.resid_cand(d_old, k) == d_old)
            intact = intact | (
                tight
                & ~worsened_resid[:, k][:, None]
                & ~jnp.take(aff, old_bg.resid_nbr[:, k], axis=0)
            )
        for b, c in enumerate(old_bg.offsets):
            tight = fin & (old_ops.band0_cand(d_old, b) == d_old)
            intact = intact | (
                tight
                & ~worsened_band[b][:, None]
                & ~jnp.roll(aff, c, axis=0)
            )
        return fin & (d_old > 0) & ~intact

    def body(state):
        aff, _, i = state
        new = sweep(aff)
        return new, jnp.all(new == aff), i + jnp.int32(1)

    def cond(state):
        _, settled, i = state
        return jnp.logical_and(~settled, i < max_iters)

    aff, done, _ = jax.lax.while_loop(
        cond,
        body,
        (
            jnp.zeros(d_old.shape, dtype=jnp.bool_),
            jnp.bool_(False),
            jnp.int32(0),
        ),
    )

    n = new_bg.n_nodes
    d = dist[:n]
    new_ops = _RelaxOps(
        new_bg,
        n_edge_up,
        n_edge_metric,
        n_node_overloaded[:n],
        0,
        1,
        None,
        small_dist,
        False,
        d.dtype,
    )
    # improvement firing: evaluate the NEW exact depth-0 candidates at
    # the improved slots only — unchanged slots cannot fire below the
    # old fixed point and worsened slots only raised their candidates,
    # so these are the only places a new (shorter or newly-tight) path
    # can enter
    fire = jnp.zeros(d.shape, dtype=jnp.bool_)
    for k in range(new_ops.n_resid):
        cand = new_ops.resid_cand(d, k)
        fire = fire | (
            improved_resid[:, k][:, None]
            & (cand < new_ops.inf)
            & (cand <= d)
        )
    for b in range(new_ops.n_bands):
        cand = new_ops.band0_cand(d, b)
        fire = fire | (
            improved_band[b][:, None] & (cand < new_ops.inf) & (cand <= d)
        )
    col_mask = (
        jnp.any(aff, axis=0)
        | jnp.any(bit_off, axis=0)
        | jnp.any(fire, axis=0)
    )
    return aff, col_mask, done


@functools.partial(
    jax.jit,
    donate_argnums=(0, 1),
    static_argnames=(
        "check_every",
        "max_blocks",
        "depth",
        "resid_rounds",
        "small_dist",
        "chord_mode",
        "n_words",
    ),
)
def delta_relax(
    dist: jax.Array,  # [N*, P] — DONATED previous product
    bitmap: jax.Array,  # [N, P, W] uint32 — DONATED previous bitmap
    aff: jax.Array,  # [N, P] bool — delta_frontier's affected entries
    col_idx: jax.Array,  # [Pb] int32 — affected columns, padded with
    #   col_idx[0] repeats (pad lanes compute real duplicate results, so
    #   the convergence verdict stays meaningful)
    dest_ids: jax.Array,  # [P] int32 — the product's destination ids
    bg,  # NEW topology's banded decomposition
    r_edge_up: jax.Array,  # NEW reverse runtime arrays
    r_edge_metric: jax.Array,
    node_overloaded: jax.Array,
    resid_slot: jax.Array,  # NEW EpilogueMaps
    band_slot: jax.Array,
    check_every: int = 4,
    max_blocks: int = 64,
    depth: int = 3,
    resid_rounds: int = 1,
    small_dist: bool = False,
    chord_mode: bool = False,
    n_words: int = 1,
):
    """Re-relax ONLY the affected destination columns and write them
    back into the donated full-width product.

    Per affected column the init is the old distances with the affected
    entries re-set to INF and the destination re-pinned to 0 — the
    worsening-direction upper bound (`_affected_init` safety argument:
    every kept entry has a surviving old shortest path; improvements in
    the same batch only loosen the bound).  The progressive while_loop
    then runs to the on-device fixed point and the fused verify+bitmap
    epilogue (the `_fused_progressive_banded` discipline: each [N, Pb]
    candidate is read once for both the convergence verdict and the
    ECMP bit) certifies exactness and re-encodes the columns' bitmaps
    under the NEW slot maps.

    Returns (dist' [N*, P], bitmap' [N, P, W], converged, blocks).
    Donation holds because both outputs keep the donated avals — the
    write-back is a hit-matrix select, never a scatter.  `converged`
    False (block budget ran out, or the uint16 saturation guard
    tripped) means the outputs are NOT a certified product and the
    caller must cold-rebuild — the donated inputs are gone either way.
    """
    from .banded import _RelaxOps, make_dist0_orig

    n = bg.n_nodes
    inf = jnp.uint16(INF16) if small_dist else jnp.int32(INF32)
    d_cols = jnp.take(dist[:n], col_idx, axis=1)  # [N, Pb]
    aff_cols = jnp.take(aff, col_idx, axis=1)
    init = jnp.where(aff_cols, inf, d_cols)
    sub_dest = jnp.take(dest_ids, col_idx)  # [Pb]
    d0 = jnp.minimum(
        make_dist0_orig(sub_dest, n, small_dist=small_dist), init
    )
    ops = _RelaxOps(
        bg,
        r_edge_up,
        r_edge_metric,
        node_overloaded[:n],
        0 if chord_mode else depth,
        resid_rounds,
        None,
        small_dist,
        chord_mode,
        d0.dtype,
    )

    def body(state):
        d, _, i = state
        for _ in range(check_every - 1):
            d = ops.supersweep(d)
        v = ops.supersweep(d)
        return v, jnp.all(v == d), i + jnp.int32(1)

    def cond(state):
        _, conv, i = state
        return jnp.logical_and(~conv, i < max_blocks)

    d, _, blocks = jax.lax.while_loop(
        cond, body, (d0, jnp.bool_(False), jnp.int32(0))
    )

    # fused verify+bitmap epilogue over the column slab (authoritative
    # exact check; see ops.allsources._fused_progressive_banded)
    pb = d.shape[1]
    fin = d < ops.inf
    v = d

    def bit_of(slot_row):
        return jnp.where(
            slot_row >= 0,
            jnp.uint32(1)
            << (jnp.maximum(slot_row, 0) % 32).astype(jnp.uint32),
            jnp.uint32(0),
        )

    groups = [
        (functools.partial(ops.resid_cand, d, k), resid_slot[:, k])
        for k in range(ops.n_resid)
    ] + [
        (functools.partial(ops.band0_cand, d, b), band_slot[b])
        for b in range(ops.n_bands)
    ]
    if n_words == 1:
        cb2d = jnp.zeros((n, pb), dtype=jnp.uint32)
        for mk_cand, srow in groups:
            cand = mk_cand()
            on = fin & (cand == d)
            cb2d = cb2d | jnp.where(on, bit_of(srow)[:, None], jnp.uint32(0))
            v = jnp.minimum(v, cand)
        col_bitmap = cb2d[:, :, None]
    else:
        col_bitmap = jnp.zeros((n, pb, n_words), dtype=jnp.uint32)
        for mk_cand, srow in groups:
            cand = mk_cand()
            on = fin & (cand == d)
            word_sel = (jnp.maximum(srow, 0) // 32)[:, None] == jnp.arange(
                n_words
            )[None, :]
            col_bitmap = col_bitmap | jnp.where(
                on[:, :, None] & word_sel[:, None, :],
                bit_of(srow)[:, None, None],
                jnp.uint32(0),
            )
            v = jnp.minimum(v, cand)
    converged = jnp.all(v == d)
    if small_dist:
        converged = u16_saturation_verdict(d, converged)

    # scatter-free column write-back: for full-width column p, `sel`
    # picks the slab lane that computed it (duplicate pad lanes carry
    # identical results, so max-of-matches is safe), `have` gates the
    # overwrite
    p = dist.shape[1]
    hit = col_idx[None, :] == jnp.arange(p, dtype=jnp.int32)[:, None]
    have = hit.any(axis=1)  # [P]
    sel = jnp.where(
        hit, jnp.arange(pb, dtype=jnp.int32)[None, :], 0
    ).max(axis=1)  # [P]
    new_cols = jnp.take(d, sel, axis=1)  # [N, P]
    new_dist = jnp.where(have[None, :], new_cols, dist[:n])
    # re-attach the pad rows (empty when N* == n; XLA elides the concat)
    new_dist = jnp.concatenate([new_dist, dist[n:]], axis=0)
    new_bm = jnp.where(
        have[None, :, None], jnp.take(col_bitmap, sel, axis=1), bitmap
    )
    return new_dist, new_bm, converged, blocks


@functools.partial(
    jax.jit, donate_argnums=(0,), static_argnames=("n_words",)
)
def delta_rows_bitmap(
    bitmap: jax.Array,  # [N, P, W] uint32 — DONATED current bitmap
    dist: jax.Array,  # [N*, P] — CURRENT exact reverse product
    row_idx: jax.Array,  # [Rb] int32 — rows whose out-slot map changed,
    #   padded with row_idx[0] repeats
    out_nbr: jax.Array,  # NEW OutEll tables
    out_eid: jax.Array,
    out_slot: jax.Array,
    f_edge_metric: jax.Array,  # FORWARD runtime arrays (OutEll.eid's)
    f_edge_up: jax.Array,
    node_overloaded: jax.Array,
    n_words: int = 1,
):
    """Re-encode the ECMP bitmap rows whose slot layout changed.

    The distances are already exact for every column; only the bit
    POSITIONS moved (a node gaining/losing an out-neighbor re-ranks its
    sorted unique out-neighbors).  Recompute the LFA-free condition
    (`ecmp_bitmap_from_reverse_dist`) for just the bucketed row set
    across all P columns and write the rows back through the donated
    bitmap with a hit-matrix select.  Work: O(Rb * K * P).
    """
    n = bitmap.shape[0]
    u16 = dist.dtype == jnp.uint16
    inf = INF16 if u16 else INF32
    rb = row_idx.shape[0]
    k_pad = out_nbr.shape[1]
    nbr_r = jnp.take(out_nbr, row_idx, axis=0)  # [Rb, K]
    eid_r = jnp.take(out_eid, row_idx, axis=0)
    slot_r = jnp.take(out_slot, row_idx, axis=0)
    d_self = jnp.take(dist[:n], row_idx, axis=0)  # [Rb, P]
    p_dim = d_self.shape[1]

    def slot_on(k):
        eidk = eid_r[:, k]
        ok = (eidk >= 0) & jnp.take(f_edge_up, jnp.maximum(eidk, 0))
        w = jnp.take(f_edge_metric, jnp.maximum(eidk, 0))  # [Rb]
        if u16:
            w = clamp_metric_u16(w)
        nbr = nbr_r[:, k]
        d_nbr = jnp.take(dist[:n], nbr, axis=0)  # [Rb, P]
        nbr_ov = jnp.take(node_overloaded, nbr)  # [Rb]
        return (
            ok[:, None]
            & (d_nbr < inf)
            & (d_nbr + w[:, None] == d_self)
            & (~nbr_ov[:, None] | (d_nbr == 0))
        )

    rows_bm = jnp.zeros((rb, p_dim, n_words), dtype=jnp.uint32)
    for k in range(k_pad):
        on = slot_on(k)
        slot = slot_r[:, k]
        bit = jnp.where(
            slot >= 0,
            jnp.uint32(1) << (jnp.maximum(slot, 0) % 32).astype(jnp.uint32),
            jnp.uint32(0),
        )
        word_sel = (jnp.maximum(slot, 0) // 32)[:, None] == jnp.arange(
            n_words
        )[None, :]
        rows_bm = rows_bm | jnp.where(
            on[:, :, None] & word_sel[:, None, :],
            bit[:, None, None],
            jnp.uint32(0),
        )

    hit = row_idx[None, :] == jnp.arange(n, dtype=jnp.int32)[:, None]
    have = hit.any(axis=1)  # [N]
    sel = jnp.where(
        hit, jnp.arange(rb, dtype=jnp.int32)[None, :], 0
    ).max(axis=1)  # [N]
    return jnp.where(
        have[:, None, None], jnp.take(rows_bm, sel, axis=0), bitmap
    )
