"""Band-augmented batched SSSP: the large-topology relax kernel.

The bucketed-ELL relax (ops.sssp) treats every in-edge as a row gather; on
a 100k-node WAN topology the gather traffic is ~10x less efficient than
dense vector work, and plain per-edge relaxation needs one sweep per
shortest-path hop (~24 at 100k).  This kernel exploits the structure real
topologies have: most edges lie on a few *circulant bands* — in-edges
``(v - c) mod N -> v`` for a fixed offset ``c`` (ring/skip links in a WAN
ring, row/column links in a grid).  Reference anchor: this replaces the
same per-source Dijkstra as ops.sssp (openr/decision/LinkState.cpp:809-878)
— the reference has no counterpart for the batched formulation itself.

Band edges relax as a *roll* (contiguous shift of the whole distance
matrix) — pure dense vector work, no per-index gathers.  And because a
band is a chain, min-plus *pointer jumping* applies: precompose the band
weights along 2^l-edge windows (host-free, [N,1] arrays) and relax with
shifts c, 2c, 4c, ... so a straight run of L band hops settles in
O(log L) passes instead of L sweeps.  Only the residual edges (random
chords / fabric cross-links) pay the gather price, in a uniform-K ELL
table in ORIGINAL node order (no degree permutation — bands need it).

One **supersweep** = ``resid_rounds`` residual-gather relaxes + per band
a depth-0 exact relax plus ``depth`` composed-shift relaxes.  The
fixed-point iteration runs a static number of supersweeps (fori_loop, no
host syncs) followed by one *verification* relax — depth-0 bands +
residual covers every edge with exact drain semantics, so ``converged``
really certifies the fixed point (same adaptive fixed-sweep discipline as
ops.sssp.batched_sssp_ell / decision.csr.spf_from).

Semantics (identical to ops.sssp / the host oracle):
- down edges never relax; overloaded nodes are reachable but offer no
  transit, except a row's own source (identified by dist == 0, metrics
  being >= 1).  Composed band levels conservatively skip the
  source-exception (a path *starting* at an overloaded source advances at
  depth 0 each supersweep); the verification relax applies the exact rule,
  so the fixed point reached is exactly the reference's.
- per-row edge exclusions (KSP re-runs, SRLG what-if, TI-LFA) enter the
  residual as slot masks and the bands as *cut barriers*: a composed
  window that crosses an excluded edge is blocked for that row, computed
  by the same doubling as the weights ([N, S] bool per band, in-loop).

Distances may run in uint16 (``small_dist=True``) when the caller can
bound true distances below INF16: gathers and rolls move half the bytes.
The convergence verdict guards correctness: saturated distances fail
verification and the caller falls back to int32 (see csr / bench).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

# the uint16 distance-mode constants and helpers are shared with the ELL
# kernel and live in ops.sssp (re-exported here for existing importers)
from .sssp import (
    INF16,
    INF32,
    WBIG16,
    sp_dag_mask16_from_T,
    u16_dist_to_i32,
    u16_saturation_verdict,
)

# band-weight infinity: saturating compose keeps weights <= WBIG and
# INF32 + WBIG < 2^31, so no int32 overflow anywhere
WBIG = jnp.int32(1 << 28)


@jax.tree_util.register_pytree_node_class
class BandedGraph:
    """Host-built circulant-band + residual-ELL decomposition.

    Registered as a pytree with ``offsets``/``n_nodes``/``resid_buckets``
    as STATIC aux data: band offsets drive roll shifts and loop
    structure, so they must be Python ints under jit (a new band layout
    recompiles, matching the shape-bucketed discipline of the ELL
    tables).  ``resid_buckets`` is a tuple of (lo, hi) residual-column
    ranges grouped by chord-length scale: the chord-mode supersweep
    fuses WITHIN a bucket (Jacobi) and chains ACROSS buckets
    (Gauss-Seidel), so applying the short-chord bucket first lets the
    long-chord bucket relax from already-updated distances — more
    propagation per supersweep at identical gather cost.  A single
    bucket reproduces the old all-Jacobi pass."""

    def __init__(
        self, offsets, band_eid, resid_nbr, resid_eid, n_nodes,
        resid_buckets=None,
    ):
        self.offsets = tuple(int(c) for c in offsets)
        self.band_eid = band_eid  # [B, N] int32 — edge of (v-c)%N -> v; -1
        self.resid_nbr = resid_nbr  # [N, K] int32 — residual in-nbrs (pad 0)
        self.resid_eid = resid_eid  # [N, K] int32 — residual edge ids; -1
        self.n_nodes = int(n_nodes)
        if resid_buckets is None:
            k = int(getattr(resid_nbr, "shape", (0, 1))[1])
            resid_buckets = ((0, k),)
        self.resid_buckets = tuple(
            (int(lo), int(hi)) for lo, hi in resid_buckets
        )

    def tree_flatten(self):
        return (
            (self.band_eid, self.resid_nbr, self.resid_eid),
            (self.offsets, self.n_nodes, self.resid_buckets),
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        offsets, n_nodes, resid_buckets = aux
        return cls(offsets, *children, n_nodes, resid_buckets)


def build_banded(
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    n_edges: int,
    n_nodes: int,
    min_band_frac: float = 0.125,
    max_bands: int = 8,
    max_resid_k: int = 32,
) -> Optional[BandedGraph]:
    """Detect circulant bands and build the decomposition (vectorized
    numpy, runs on topology rebuild).  Returns None when the topology has
    no useful band structure (e.g. a fat-tree) or the residual degree is
    too skewed for a uniform-K table — callers fall back to the bucketed
    ELL kernel."""
    if n_edges == 0 or n_nodes < 64:
        return None
    src = edge_src[:n_edges].astype(np.int64)
    dst = edge_dst[:n_edges].astype(np.int64)
    # retired freelist slots (csr rewires) sit inside [:n_edges] styled
    # as padding (endpoints at the pad node >= n_nodes); they are not
    # edges of the graph and must not index the [N]-sized tables
    ids = np.flatnonzero((src < n_nodes) & (dst < n_nodes))
    if ids.size == 0:
        return None
    src, dst = src[ids], dst[ids]
    off = (dst - src) % n_nodes
    vals, counts = np.unique(off, return_counts=True)
    thresh = max(int(n_nodes * min_band_frac), 32)
    cand = vals[counts >= thresh]
    if cand.size == 0:
        return None
    if cand.size > max_bands:
        top = np.argsort(-counts[counts >= thresh])[:max_bands]
        cand = cand[top]
    band_set = set(int(c) for c in cand)

    is_band = np.isin(off, cand)
    band_eid = np.full((len(cand), n_nodes), -1, dtype=np.int32)
    # one edge per (band, position); parallel band edges (same u->v twice)
    # would collide — send duplicates to the residual
    offs_sorted = sorted(band_set)
    eids = np.flatnonzero(is_band)
    rows = np.searchsorted(
        np.asarray(offs_sorted, dtype=np.int64), off[eids]
    )
    cols = dst[eids]
    # detect duplicates (parallel links): keep first, demote rest
    order = np.lexsort((eids, cols, rows))
    r_o, c_o, e_o = rows[order], cols[order], eids[order]
    dup = np.r_[False, (r_o[1:] == r_o[:-1]) & (c_o[1:] == c_o[:-1])]
    band_eid[r_o[~dup], c_o[~dup]] = ids[e_o[~dup]].astype(np.int32)
    demoted = e_o[dup]
    is_band[demoted] = False

    resid = np.flatnonzero(~is_band)
    resid_deg = np.bincount(dst[resid], minlength=n_nodes)
    k = int(resid_deg.max()) if resid.size else 0
    k_pad = 1
    while k_pad < max(k, 1):
        k_pad *= 2
    if k_pad > max_resid_k:
        return None
    # band edges must be worth the residual-table inefficiency: require
    # bands to cover enough edges that the uniform-K residual is smaller
    # than the work the bucketed ELL would do (~live edge slots)
    if n_nodes * k_pad > len(src):
        return None
    resid_nbr = np.zeros((n_nodes, k_pad), dtype=np.int32)
    resid_eid = np.full((n_nodes, k_pad), -1, dtype=np.int32)
    resid_buckets = ((0, k_pad),)
    if resid.size:
        order = np.argsort(dst[resid], kind="stable")
        r_sorted = resid[order]
        d_sorted = dst[resid][order]
        starts = np.searchsorted(d_sorted, np.arange(n_nodes))
        slot = np.arange(r_sorted.size) - starts[d_sorted]
        resid_nbr[d_sorted, slot] = src[r_sorted].astype(np.int32)
        resid_eid[d_sorted, slot] = ids[r_sorted].astype(np.int32)
        # chord-bucketed residual order: sort each row's slots by folded
        # chord length (short first) and split the columns into a
        # short-chord and a long-chord bucket where the scales separate.
        # The chord-mode supersweep chains the buckets Gauss-Seidel
        # style, so long chords jump from distances the short chords
        # already settled this sweep.
        offs = (np.arange(n_nodes, dtype=np.int64)[:, None] - resid_nbr) % (
            n_nodes
        )
        folded = np.minimum(offs, n_nodes - offs)
        folded = np.where(resid_eid >= 0, folded, np.iinfo(np.int64).max)
        col_order = np.argsort(folded, axis=1, kind="stable")
        resid_nbr = np.take_along_axis(resid_nbr, col_order, axis=1)
        resid_eid = np.take_along_axis(resid_eid, col_order, axis=1)
        folded = np.take_along_axis(folded, col_order, axis=1)
        # per-column median folded length over valid slots (columns hold
        # row-wise order statistics, so medians are nondecreasing)
        med = np.full(k_pad, np.inf)
        for k in range(k_pad):
            valid = resid_eid[:, k] >= 0
            if valid.any():
                med[k] = float(np.median(folded[valid, k]))
        is_long = med > max(16.0, float(n_nodes) ** 0.5)
        split = int(np.searchsorted(is_long, True))
        if 0 < split < k_pad:
            resid_buckets = ((0, split), (split, k_pad))
    return BandedGraph(
        offsets=tuple(offs_sorted),
        band_eid=jnp.asarray(band_eid),
        resid_nbr=jnp.asarray(resid_nbr),
        resid_eid=jnp.asarray(resid_eid),
        n_nodes=n_nodes,
        resid_buckets=resid_buckets,
    )


def make_dist0_orig(
    sources: jax.Array, n_nodes: int, small_dist: bool = False
) -> jax.Array:
    """[N, S] dist0 in original node order (dense compare, scatter-free)."""
    is_src = (
        jnp.arange(n_nodes, dtype=jnp.int32)[:, None] == sources[None, :]
    )
    if small_dist:
        return jnp.where(is_src, jnp.uint16(0), INF16)
    return jnp.where(is_src, jnp.int32(0), INF32)


def _band_tables(bg, edge_up, edge_metric, node_overloaded, depth, wbig):
    """Per-band call-time tables: depth-0 weight [N,1], overload-of-
    predecessor [N,1], and composed level weights (overload-blocked).
    All [N,1] — negligible traffic next to the [N,S] distance passes."""
    wdt = wbig.dtype
    tables = []
    for b, c in enumerate(bg.offsets):
        eid = bg.band_eid[b]
        ok = (eid >= 0) & jnp.take(edge_up, jnp.maximum(eid, 0))
        # clamp BEFORE the dtype cast: a metric >= WBIG16 must saturate to
        # the band infinity, never wrap in uint16 (callers gate small_dist
        # on max metric, but a racing in-place metric refresh must stay
        # safe — a wbig weight only masks the edge, and the int32 retry
        # path restores exactness)
        m = jnp.minimum(
            jnp.take(edge_metric, jnp.maximum(eid, 0)),
            jnp.int32(wbig),
        ).astype(wdt)
        w0 = jnp.where(ok, m, wbig)[:, None]
        ov = jnp.roll(node_overloaded, c)[:, None]  # overloaded[(v-c)%N]
        # composed weights: block transit through overloaded predecessors
        wl = jnp.where(ov, wbig, w0)
        levels = []
        for l in range(depth):
            sh = (c << l) % bg.n_nodes
            wr = jnp.roll(wl, sh, axis=0)
            wl = jnp.where(
                (wl < wbig) & (wr < wbig),
                jnp.minimum(wl + wr, wbig.astype(wdt)),
                wbig,
            )
            levels.append(wl)
        tables.append((w0, ov, levels))
    return tables


def _resid_tables(bg, edge_up, edge_metric, node_overloaded, wbig):
    wdt = wbig.dtype
    eid = bg.resid_eid
    ok = (eid >= 0) & jnp.take(edge_up, jnp.maximum(eid, 0))
    m = jnp.minimum(  # clamp before cast — see _band_tables
        jnp.take(edge_metric, jnp.maximum(eid, 0)), jnp.int32(wbig)
    ).astype(wdt)
    w = jnp.where(ok, m, wbig)  # [N, K]
    ov = jnp.take(node_overloaded, bg.resid_nbr)  # [N, K]
    return w, ov


class _RelaxOps:
    """Shared relax/verify closures over one (graph, runtime-state)
    binding — the single source of the relax semantics, consumed by the
    fixed-sweep kernel, the progressive while-loop kernel, the fused
    verify+bitmap epilogue (ops.allsources) and the warm-start
    affected-set propagation (decision.fleet).  Built INSIDE a jit
    trace; never passed across a jit boundary."""

    def __init__(
        self,
        bg: BandedGraph,
        edge_up,
        edge_metric,
        ov_n,  # [N] bool — node_overloaded already sliced to n_nodes
        depth: int,
        resid_rounds: int,
        row_allowed_T,
        small_dist: bool,
        chord_mode: bool,
        ddt,
    ) -> None:
        self.bg = bg
        self.n = bg.n_nodes
        self.chord_mode = chord_mode
        self.resid_rounds = resid_rounds
        self.ddt = ddt
        self.inf = INF16 if small_dist else INF32
        self.wbig = WBIG16 if small_dist else WBIG
        self.n_resid = int(bg.resid_nbr.shape[1])
        self.n_bands = len(bg.offsets)
        self.band_tabs = _band_tables(
            bg, edge_up, edge_metric, ov_n, depth, self.wbig
        )
        self.rw, self.rov = _resid_tables(
            bg, edge_up, edge_metric, ov_n, self.wbig
        )
        # per-row exclusions: residual slot masks + band cut positions
        if row_allowed_T is not None:
            eid = bg.resid_eid
            self.resid_excl = (eid >= 0)[:, :, None] & ~jnp.take(
                row_allowed_T, jnp.maximum(eid, 0).reshape(-1), axis=0
            ).reshape(eid.shape + (row_allowed_T.shape[1],))  # [N, K, S]
            self.band_cut0 = []
            for b in range(self.n_bands):
                be = bg.band_eid[b]
                cut = (be >= 0)[:, None] & ~jnp.take(
                    row_allowed_T, jnp.maximum(be, 0), axis=0
                )  # [N, S]
                self.band_cut0.append(cut)
        else:
            self.resid_excl = None
            self.band_cut0 = None

    def resid_cand(self, d, k):
        du = jnp.take(d, self.bg.resid_nbr[:, k], axis=0)  # [N, S]
        allow = (self.rw[:, k] < self.wbig)[:, None] & (
            ~self.rov[:, k][:, None] | (du == 0)
        )
        if self.resid_excl is not None:
            allow &= ~self.resid_excl[:, k]
        return jnp.where(
            allow & (du < self.inf),
            du + self.rw[:, k][:, None].astype(self.ddt),
            self.inf,
        )

    def relax_resid(self, d):
        for k in range(self.n_resid):
            d = jnp.minimum(d, self.resid_cand(d, k))
        return d

    def band0_cand(self, d, b):
        """Depth-0 band relax candidate with the exact source exception."""
        c = self.bg.offsets[b]
        w0, ov, _ = self.band_tabs[b]
        du = jnp.roll(d, c, axis=0)
        allow = (w0 < self.wbig) & (~ov | (du == 0))
        if self.band_cut0 is not None:
            allow = allow & ~self.band_cut0[b]
        return jnp.where(
            allow & (du < self.inf), du + w0.astype(self.ddt), self.inf
        )

    def relax_band0(self, d, b):
        return jnp.minimum(d, self.band0_cand(d, b))

    def relax_band_levels(self, d, b):
        """Composed-shift relaxes (transit-blocked; no source exception)."""
        c = self.bg.offsets[b]
        _, _, levels = self.band_tabs[b]
        cut = self.band_cut0[b] if self.band_cut0 is not None else None
        for l, wl in enumerate(levels):
            sh = (c << (l + 1)) % self.n
            du = jnp.roll(d, sh, axis=0)
            cand = jnp.where(
                (wl < self.wbig) & (du < self.inf),
                du + wl.astype(self.ddt),
                self.inf,
            )
            if cut is not None:
                # barrier: window of 2^(l+1) edges ending at v crosses a cut
                cut = cut | jnp.roll(cut, (c << l) % self.n, axis=0)
                cand = jnp.where(cut, self.inf, cand)
            d = jnp.minimum(d, cand)
        return d

    def supersweep(self, d):
        if self.chord_mode:
            # fused Jacobi passes: residual gathers fused per chord-scale
            # bucket (chained across buckets so long chords relax from
            # the short chords' freshly settled distances), then all
            # depth-0 band shifts in one min
            for lo, hi in self.bg.resid_buckets:
                cands = [self.resid_cand(d, k) for k in range(lo, hi)]
                if cands:
                    d = functools.reduce(jnp.minimum, [d] + cands)
            return functools.reduce(
                jnp.minimum,
                [d] + [self.band0_cand(d, b) for b in range(self.n_bands)],
            )
        for _ in range(self.resid_rounds):
            d = self.relax_resid(d)
        for b in range(self.n_bands):
            d = self.relax_band0(d, b)
            d = self.relax_band_levels(d, b)
        return d

    def verify(self, d):
        """One exact relax pass: v == d certifies the fixed point.
        Depth-0 bands + residual cover every edge with exact drain
        semantics.  The chord-mode supersweep is an equally exact CHECK:
        its stages are monotone non-increasing, so an unchanged
        composite means every stage — hence every single-edge candidate
        — left d unchanged."""
        if self.chord_mode:
            return self.supersweep(d)
        v = self.relax_resid(d)
        for b in range(self.n_bands):
            v = self.relax_band0(v, b)
        return v


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_supersweeps",
        "depth",
        "resid_rounds",
        "small_dist",
        "chord_mode",
    ),
)
def batched_sssp_banded(
    dist0: jax.Array,  # [N, S] — original node order (make_dist0_orig)
    bg: BandedGraph,
    edge_up: jax.Array,  # [E_cap] bool (runtime state)
    edge_metric: jax.Array,  # [E_cap] int32
    node_overloaded: jax.Array,  # [N_cap] bool (first N rows used)
    n_supersweeps: int,
    depth: int = 3,
    resid_rounds: int = 1,
    row_allowed_T: Optional[jax.Array] = None,  # [E_cap, S] bool
    small_dist: bool = False,
    chord_mode: bool = False,
):
    """Fixed-supersweep banded relaxation.  Returns (dist [N, S] in
    ORIGINAL node order, converged bool).  See module docstring.

    ``chord_mode`` swaps the sequential supersweep for the bucketed
    Jacobi form measured fastest on chord-rich small-world graphs
    (round-5 tune, wan100k P=1024): fused mins over the residual gather
    candidates (per chord-scale bucket), then ONE fused min over all
    depth-0 band shifts.  Fewer, larger fusions cut the per-sweep HBM
    traffic ~30% and the composed band levels (pure overhead when the
    supersweep count is floored by chord-hop depth) are skipped; the
    chord-mode fixed point needs a few more supersweeps, which the
    runner's adaptive hint learns.  The verification relax stays an
    exact check either way."""
    ops = _RelaxOps(
        bg,
        edge_up,
        edge_metric,
        node_overloaded[: bg.n_nodes],
        0 if chord_mode else depth,
        resid_rounds,
        row_allowed_T,
        small_dist,
        chord_mode,
        dist0.dtype,
    )
    d = jax.lax.fori_loop(
        0, n_supersweeps, lambda i, d: ops.supersweep(d), dist0
    )
    v = ops.verify(d)
    return v, jnp.all(v == d)


@functools.partial(
    jax.jit,
    static_argnames=(
        "check_every",
        "max_blocks",
        "depth",
        "resid_rounds",
        "small_dist",
        "chord_mode",
    ),
)
def batched_sssp_banded_progressive(
    dist0: jax.Array,  # [N, S] — original node order
    bg: BandedGraph,
    edge_up: jax.Array,
    edge_metric: jax.Array,
    node_overloaded: jax.Array,
    check_every: int = 4,
    max_blocks: int = 64,
    depth: int = 3,
    resid_rounds: int = 1,
    row_allowed_T: Optional[jax.Array] = None,
    small_dist: bool = False,
    chord_mode: bool = False,
):
    """Progressive on-device convergence: ``lax.while_loop`` over BLOCKS
    of ``check_every`` supersweeps, early-exiting at the actual fixed
    point instead of a host-learned sweep count.  The whole iteration
    stays one compiled program with zero host syncs; the convergence
    check (the block's last supersweep left d unchanged) costs one
    [N, S] compare per block.

    A run stops at the first block whose final supersweep is a no-op —
    supersweep(d) == d certifies the fixed point because every stage is
    monotone non-increasing (an unchanged composite means every exact
    single-edge candidate left d unchanged; composed band levels only
    ever relax along real paths, so they cannot undershoot).  Cold runs
    therefore pay at most check_every-1 supersweeps past the fixed
    point, not the adaptive hint's doubling overshoot; warm-started
    runs (dist0 an upper bound, sources re-pinned by the caller) exit
    after however few blocks the delta actually needs.  Returns
    (dist [N, S], converged); converged is False only when max_blocks
    ran out (or, for uint16 runs, when the caller's saturation guard
    trips afterwards)."""
    ops = _RelaxOps(
        bg,
        edge_up,
        edge_metric,
        node_overloaded[: bg.n_nodes],
        0 if chord_mode else depth,
        resid_rounds,
        row_allowed_T,
        small_dist,
        chord_mode,
        dist0.dtype,
    )

    def body(state):
        d, _, i = state
        for _ in range(check_every - 1):
            d = ops.supersweep(d)
        v = ops.supersweep(d)
        return v, jnp.all(v == d), i + jnp.int32(1)

    def cond(state):
        _, conv, i = state
        return jnp.logical_and(~conv, i < max_blocks)

    d, conv, _ = jax.lax.while_loop(
        cond, body, (dist0, jnp.bool_(False), jnp.int32(0))
    )
    return d, conv


@functools.partial(
    jax.jit, static_argnames=("small_dist", "max_iters")
)
def affected_mask(
    dist: jax.Array,  # [N*, S] — previous CONVERGED reverse distances
    bg: BandedGraph,  # previous topology's banded decomposition
    edge_up: jax.Array,  # previous runtime arrays (the OLD graph)
    edge_metric: jax.Array,
    node_overloaded: jax.Array,
    worsened_resid: jax.Array,  # [N, K] bool — resid slot's edge worsened
    worsened_band: jax.Array,  # [B, N] bool — band position's edge worsened
    small_dist: bool = False,
    max_iters: int = 128,
):
    """Worsening-direction warm-start support: the entries of the OLD
    fixed point that a set of worsened edges (removed / metric-increased
    / newly-drained transit) can possibly have invalidated.

    aff[v, s] is set iff some OLD tight chain into v (a chain of relax
    candidates achieving equality, i.e. a shortest-path-DAG path)
    crosses a worsened edge — propagated by OR along tight edges with a
    ``lax.while_loop`` to a CERTIFIED fixpoint (a full pass with no
    change).  The ANY-rule is a conservative superset of the exact
    "all shortest paths broken" set: re-initializing a superset to INF
    only costs extra re-relax work, never correctness, because the old
    value stays a valid upper bound wherever ANY surviving old shortest
    path avoids the worsened set.  Returns (aff [N, S] bool, done);
    done=False means max_iters ran out BEFORE the fixpoint and the
    caller MUST cold-start (an under-propagated set is silently wrong).

    Cost: one pass ≈ one depth-0 supersweep plus bool-matrix gathers —
    propagation needs only the exact depth-0 stages, so composed band
    levels are skipped (long straight band runs take one hop per pass;
    chord-rich graphs, where warm starts matter most, need few passes).
    """
    n = bg.n_nodes
    ops = _RelaxOps(
        bg,
        edge_up,
        edge_metric,
        node_overloaded[:n],
        0,
        1,
        None,
        small_dist,
        False,
        dist.dtype,
    )
    d = dist[:n]
    fin = d < ops.inf

    def sweep(aff):
        for k in range(ops.n_resid):
            tight = fin & (ops.resid_cand(d, k) == d)
            seed = worsened_resid[:, k][:, None] | jnp.take(
                aff, bg.resid_nbr[:, k], axis=0
            )
            aff = aff | (tight & seed)
        for b, c in enumerate(bg.offsets):
            tight = fin & (ops.band0_cand(d, b) == d)
            seed = worsened_band[b][:, None] | jnp.roll(aff, c, axis=0)
            aff = aff | (tight & seed)
        return aff

    def body(state):
        aff, _, i = state
        new = sweep(aff)
        return new, jnp.all(new == aff), i + jnp.int32(1)

    def cond(state):
        _, done, i = state
        return jnp.logical_and(~done, i < max_iters)

    aff0 = jnp.zeros(d.shape, dtype=jnp.bool_)
    aff, done, _ = jax.lax.while_loop(
        cond, body, (aff0, jnp.bool_(False), jnp.int32(0))
    )
    return aff, done


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_supersweeps",
        "depth",
        "resid_rounds",
        "small_dist",
        "use_link_metric",
        "want_dag",
        "chord_mode",
        "raw_u16",
        "transpose",
        "progressive",
        "check_every",
        "max_blocks",
    ),
)
def spf_forward_banded(
    sources: jax.Array,  # [S] int32 original ids
    bg: BandedGraph,
    edge_src: jax.Array,
    edge_dst: jax.Array,
    edge_metric: jax.Array,
    edge_up: jax.Array,
    node_overloaded: jax.Array,
    n_supersweeps: int,
    depth: int = 3,
    resid_rounds: int = 1,
    extra_edge_mask: Optional[jax.Array] = None,  # [S, E_cap] or [E_cap]
    small_dist: bool = False,
    use_link_metric: bool = True,
    want_dag: bool = True,
    chord_mode: bool = False,
    raw_u16: bool = False,
    transpose: bool = True,
    dist0: Optional[jax.Array] = None,  # [N, S] warm-start upper bound
    progressive: bool = False,
    check_every: int = 4,
    max_blocks: int = 64,
):
    """Banded forward pass: distances (+ optional SP-DAG) + convergence
    verdict.  Output contract matches ops.sssp.spf_forward_ell — dist
    [S, N] int32 (INF32 unreachable), dag [S, E_cap] — so callers can
    swap kernels by topology shape.

    ``progressive`` replaces the fixed ``n_supersweeps``-then-verify
    discipline with the on-device early-exit iteration
    (batched_sssp_banded_progressive): the run stops at the actual
    fixed point, ``n_supersweeps`` is ignored, and ``converged`` is
    False only when check_every*max_blocks supersweeps ran out (or the
    uint16 saturation guard trips).

    ``dist0`` warm-starts the relax from a caller-supplied ELEMENTWISE
    UPPER BOUND on the true distances ([N, S], either dtype — converted
    to the run's domain here).  Source rows are re-pinned to 0, so any
    upper bound is safe: relax candidates never drop below the true
    distance (d[u] >= true[u] gives d[u]+w >= true[v]), the iteration is
    monotone non-increasing, and the final verification sweep certifies
    the exact fixed point — a converged warm run equals the cold result
    bit-for-bit.  Callers OWN the upper-bound proof: previous-view
    distances qualify only when every change since is an improvement
    (link up, metric decrease, overload clear — decision.fleet gates
    this); after a worsening change they may undershoot and MUST NOT be
    passed (the fixed-point check cannot detect a too-low init).

    ``raw_u16`` (uint16 runs, want_dag=False only) returns dist [S, N]
    in the raw uint16 domain (INF16 unreachable) instead of int32 —
    consumers that stay on device (the reduced all-sources bitmap pass)
    then move half the bytes.  The saturation guard still gates
    ``converged``; on a False verdict callers retry via the runner's
    int32 fallback exactly as before.

    ``transpose=False`` (want_dag=False only) returns dist in the
    kernel's native [N, S] layout, skipping the 200MB-scale transposes
    on BOTH sides of the reduced all-sources product (the bitmap pass
    consumes [N, P] directly — round-5 measurement)."""
    from .sssp import make_relax_allowed_T, sp_dag_mask_from_T

    # static-arg guard (trace time): the dag path returns [S, N]
    # unconditionally, so honoring transpose=False there would silently
    # hand back transposed data whenever S == N
    assert transpose or not want_dag, (
        "transpose=False requires want_dag=False"
    )

    metric = edge_metric if use_link_metric else jnp.ones_like(edge_metric)
    extra_T = None
    if extra_edge_mask is not None:
        extra_T = (
            extra_edge_mask.T
            if extra_edge_mask.ndim == 2
            else extra_edge_mask[:, None]
        )
    row_allowed_T = None
    if extra_T is not None:
        # bands/residual already apply up/overload; the per-row mask only
        # carries the exclusions
        row_allowed_T = (
            extra_T
            if extra_T.shape[1] > 1
            else jnp.broadcast_to(extra_T, (extra_T.shape[0], sources.shape[0]))
        )
    d0 = make_dist0_orig(sources, bg.n_nodes, small_dist=small_dist)
    if dist0 is not None:
        init = dist0[: bg.n_nodes]
        if small_dist and init.dtype != jnp.uint16:
            # clamp into the uint16 domain (INF32 and anything saturated
            # map to the INF16 sentinel — still an upper bound)
            init = jnp.minimum(init, INF16).astype(jnp.uint16)
        elif not small_dist and init.dtype != jnp.int32:
            init = jnp.where(
                init >= INF16, jnp.int32(INF32), init.astype(jnp.int32)
            )
        # re-pin sources to 0; elsewhere keep the caller's bound
        d0 = jnp.minimum(d0, init)
    if progressive:
        dist, converged = batched_sssp_banded_progressive(
            d0,
            bg,
            edge_up,
            metric,
            node_overloaded,
            check_every=check_every,
            max_blocks=max_blocks,
            depth=depth,
            resid_rounds=resid_rounds,
            row_allowed_T=row_allowed_T,
            small_dist=small_dist,
            chord_mode=chord_mode,
        )
    else:
        dist, converged = batched_sssp_banded(
            d0,
            bg,
            edge_up,
            metric,
            node_overloaded,
            n_supersweeps,
            depth=depth,
            resid_rounds=resid_rounds,
            row_allowed_T=row_allowed_T,
            small_dist=small_dist,
            chord_mode=chord_mode,
        )
    dist16 = None
    if small_dist:
        # callers must already exclude metrics >= WBIG16 — those edges
        # would be masked as down here (pick_small_dist gate)
        converged = u16_saturation_verdict(dist, converged)
        dist16 = dist
        if raw_u16 and not want_dag:
            return (dist16.T if transpose else dist16), None, converged
        dist = u16_dist_to_i32(dist)
    if not want_dag:
        return (dist.T if transpose else dist), None, converged
    allowed_T = make_relax_allowed_T(
        sources, edge_src, edge_up, node_overloaded, extra_T
    )
    if dist16 is not None:
        dag = sp_dag_mask16_from_T(
            dist16, edge_src, edge_dst, metric, allowed_T
        )
        return dist.T, dag, converged
    dag = sp_dag_mask_from_T(dist, edge_src, edge_dst, metric, allowed_T)
    return dist.T, dag, converged


# ---------------------------------------------------------------------------
# Unified fixed-sweep runner (band-aware dispatch + adaptive hints)
# ---------------------------------------------------------------------------


def pick_small_dist(edge_metric, n_edges: int) -> bool:
    """uint16 distances are safe when every metric is far below WBIG16:
    the in-kernel margin check (fin_max < WBIG16) then certifies no
    saturation, because any overflowing path must first produce a finite
    distance in [WBIG16, INF16)."""
    import numpy as _np

    if n_edges == 0:
        return True
    return int(_np.asarray(edge_metric[:n_edges]).max()) < int(WBIG16) // 4


class SpfRunner:
    """Host-side adaptive execution of the fixed-sweep kernels: picks the
    banded kernel when the topology has band structure (falling back to
    the bucketed ELL otherwise), learns the per-topology sweep hint by
    doubling on a False convergence verdict, and drops uint16 distances
    for int32 when the saturation guard trips.  One instance per mirrored
    topology (csr.CsrTopology / bench Topology)."""

    def __init__(
        self,
        ell,
        bg: Optional[BandedGraph],
        edge_src,
        edge_dst,
        edge_metric,
        edge_up,
        node_overloaded,
        n_edges: int,
        hint: int = 8,
        depth: Optional[int] = None,
        resid_rounds: int = 1,
    ) -> None:
        self.ell = ell
        self.bg = bg
        self.arrays = (edge_src, edge_dst, edge_metric, edge_up, node_overloaded)
        self.n_edges = n_edges
        # measured (round-5 tune, wan100k P=1024): on chord-rich
        # small-world graphs the supersweep count is floored by CHORD hop
        # depth, so composed band levels are pure overhead, and the
        # two-pass Jacobi supersweep (chord_mode) wins another ~30% on
        # per-sweep HBM traffic (18x11.6ms vs 14x17.0ms sequential).
        # Band-dominated topologies (grids: long straight runs) still
        # need the sequential sweep with composed levels.
        self.chord_mode = False
        if depth is None:
            if bg is not None and n_edges > 0:
                resid_frac = float(
                    (np.asarray(bg.resid_eid) >= 0).sum()
                ) / float(n_edges)
                self.chord_mode = resid_frac > 0.25
                if self.chord_mode:
                    depth = 0
                else:
                    # band-dominated graphs: auto-tune the composed-shift
                    # depth to the longest straight band run (~sqrt(N)
                    # on grid-like topologies — a row/column of the
                    # grid), so a run settles in one supersweep's
                    # O(log run) composed relaxes instead of paying one
                    # hop per supersweep.  Capped at 6: each level is an
                    # extra [N, S] pass per band per supersweep, and
                    # past 2^7-hop windows the supersweep count is
                    # floored by inter-band turns anyway.
                    depth = max(
                        2,
                        min(
                            6,
                            int(
                                np.ceil(
                                    np.log2(
                                        max(4.0, float(bg.n_nodes) ** 0.5)
                                    )
                                )
                            )
                            - 1,
                        ),
                    )
            else:
                depth = 2
        self.depth = depth
        self.resid_rounds = resid_rounds
        self.hint = hint
        # masked batches (KSP re-runs, what-if exclusions) reliably need
        # DEEPER relax than unmasked ones, so they learn their own hint:
        # a shared value would let one masked doubling inflate every
        # later unmasked dispatch.  Masked consumers still share
        # hint_masked with each other — callers must adapt through
        # forward() (whose refine-down bounds the overshoot), never by
        # hand-doubling (a bench row once did, tripling a later masked
        # row on the same runner).
        self.hint_masked = hint
        # small_allowed latches off on a saturation fallback; the metric
        # bound is re-checked per run_once because the mirror refreshes
        # edge_metric IN PLACE (csr.refresh) and an oversized metric must
        # never reach the uint16 kernel (it would be masked as down).
        # Round 5: the ELL kernel gained the uint16 mode too, so both
        # paths start eligible.
        self.small_allowed = True
        # optional device-resident pin of the runtime arrays (stage())
        self._staged = None

    @property
    def small_dist(self) -> bool:
        return self.small_allowed and pick_small_dist(
            self.arrays[2], self.n_edges
        )

    def stage(self) -> None:
        """Pin the runtime arrays as device-resident buffers: every
        run_once with host numpy arrays re-uploads ~MBs of edge state
        per dispatch, which through a latency-bound transport is pure
        wall time.  EXPLICIT opt-in — `self.arrays` (numpy) stays the
        source of truth, and any caller that mutates those arrays in
        place afterwards (csr.refresh attribute updates, tests flipping
        edge_up) must unstage() or re-stage(), or dispatches read stale
        state."""
        self._staged = tuple(jnp.asarray(a) for a in self.arrays)

    def unstage(self) -> None:
        self._staged = None

    def call_arrays(self):
        """Arrays to feed a dispatch: the staged device buffers when
        pinned, else the numpy source (uploaded per call)."""
        return self._staged if self._staged is not None else self.arrays

    def adapt(self, hint_attr: str, attempt, probe, eff_small):
        """THE fixed-sweep adaptation loop, shared by every consumer
        (forward, ops.allsources.reduced_all_sources, ops.ksp): run
        `attempt(sweeps)` at the learned hint, double on a failed
        convergence verdict — after two doublings under the effective
        uint16 mode, latch small_allowed off instead (the saturation
        guard also presents as non-convergence) — then refine the hint
        back DOWN with `probe(mid)` binary steps.

        Refine-down is capped at 3 probes: doubling overshoots by up to
        2x and every later production dispatch would pay the surplus
        sweeps forever, but each distinct sweep count is a fresh XLA
        compile (~tens of seconds at 100k), so land within ~6% of
        minimal and stop.  (Raised from 2 in round 5: at wan100k the
        third probe finds 18 instead of 20 supersweeps — ~10% of the
        north-star relax — for one more one-time compile.)

        attempt(sweeps) -> (result, ok); probe(sweeps) -> ok (a cheaper
        call whose result is discarded); eff_small() -> the effective
        uint16 mode of the run that just failed (keyed on the metric
        plane actually used — an int32 run must double instead of
        repeating the identical dispatch)."""
        doubled_from: Optional[int] = None
        while True:
            sweeps = getattr(self, hint_attr)
            result, ok = attempt(sweeps)
            if ok:
                if doubled_from is not None:
                    lo, hi = doubled_from, sweeps
                    probes = 0
                    while hi - lo > 1 and probes < 3:
                        probes += 1
                        mid = (lo + hi) // 2
                        if probe(mid):
                            hi = mid
                        else:
                            lo = mid
                    setattr(self, hint_attr, hi)
                return result
            if eff_small() and sweeps >= 32:
                self.small_allowed = False
            else:
                doubled_from = sweeps
                setattr(self, hint_attr, sweeps * 2)

    def forward(
        self,
        sources,
        use_link_metric: bool = True,
        extra_edge_mask=None,
        want_dag: bool = True,
        n_sweeps: Optional[int] = None,
        metric_plane=None,
    ):
        """(dist np [S, N*], dag np|None).  With `n_sweeps`, runs exactly
        one fixed-sweep call (caller owns the hint — bench timing);
        otherwise adapts the learned hint through `adapt`.
        `metric_plane` substitutes an alternate [E_cap] metric array
        (e.g. a TE cost plane) for this call — same graph, different
        costs, no table rebuild (BASELINE config #3 dual-metric KSP)."""
        import numpy as _np

        sources = jnp.asarray(_np.asarray(sources, dtype=_np.int32))
        if n_sweeps is not None:
            dist, dag, ok = self.run_once(
                sources,
                n_sweeps,
                use_link_metric=use_link_metric,
                extra_edge_mask=extra_edge_mask,
                want_dag=want_dag,
                metric_plane=metric_plane,
            )
            if not bool(ok):
                raise RuntimeError(
                    f"fixed {n_sweeps}-sweep run did not converge"
                )
            return (
                _np.asarray(dist),
                None if dag is None else _np.asarray(dag),
            )
        hint_attr = "hint" if extra_edge_mask is None else "hint_masked"

        def eff_small() -> bool:
            return self.small_allowed and pick_small_dist(
                metric_plane if metric_plane is not None else self.arrays[2],
                self.n_edges,
            )

        def attempt(sweeps: int):
            out = self.run_once(
                sources,
                sweeps,
                use_link_metric=use_link_metric,
                extra_edge_mask=extra_edge_mask,
                want_dag=want_dag,
                metric_plane=metric_plane,
            )
            return out, bool(out[2])

        def probe(sweeps: int) -> bool:
            _, _, mid_ok = self.run_once(
                sources,
                sweeps,
                use_link_metric=use_link_metric,
                extra_edge_mask=extra_edge_mask,
                want_dag=False,
                metric_plane=metric_plane,
            )
            return bool(mid_ok)

        dist, dag, _ = self.adapt(hint_attr, attempt, probe, eff_small)
        return (
            _np.asarray(dist),
            None if dag is None else _np.asarray(dag),
        )

    def run_once(
        self,
        sources,
        n_sweeps: int,
        use_link_metric: bool = True,
        extra_edge_mask=None,
        want_dag: bool = True,
        metric_plane=None,
        raw_u16: bool = False,
        transpose: bool = True,
        dist0=None,
        progressive: bool = False,
    ):
        """One fixed-sweep device call; returns jax (dist, dag, ok).
        With ``raw_u16`` a uint16 banded run returns raw uint16
        distances (INF16 sentinel) — callers must key on dist.dtype.
        ``transpose=False`` (want_dag=False only) keeps the kernel's
        native [N, S] layout.  ``dist0`` warm-starts the banded kernel
        from a caller-proven upper bound (see spf_forward_banded; the
        ELL fallback ignores it — cold start, still exact).
        ``progressive`` (banded only) runs the early-exit while-loop
        iteration; ``n_sweeps`` is then ignored."""
        from .sssp import spf_forward_ell_sweeps

        edge_src, edge_dst, edge_metric, edge_up, node_overloaded = (
            self.call_arrays()
        )
        if metric_plane is not None:
            edge_metric = metric_plane
        # gate uint16 on the EFFECTIVE metric plane for this call (from
        # the numpy source of truth — never a device fetch)
        small = self.small_allowed and pick_small_dist(
            metric_plane if metric_plane is not None else self.arrays[2],
            self.n_edges,
        )
        if self.bg is not None:
            return spf_forward_banded(
                sources,
                self.bg,
                edge_src,
                edge_dst,
                edge_metric,
                edge_up,
                node_overloaded,
                n_supersweeps=n_sweeps,
                depth=self.depth,
                resid_rounds=self.resid_rounds,
                extra_edge_mask=(
                    None
                    if extra_edge_mask is None
                    else jnp.asarray(extra_edge_mask)
                ),
                small_dist=small,
                use_link_metric=use_link_metric,
                want_dag=want_dag,
                chord_mode=self.chord_mode,
                raw_u16=raw_u16,
                transpose=transpose,
                dist0=dist0,
                progressive=progressive,
            )
        return spf_forward_ell_sweeps(
            sources,
            self.ell,
            edge_src,
            edge_dst,
            edge_metric,
            edge_up,
            node_overloaded,
            n_sweeps=max(n_sweeps, 2),
            use_link_metric=use_link_metric,
            extra_edge_mask=(
                None
                if extra_edge_mask is None
                else jnp.asarray(extra_edge_mask)
            ),
            want_dag=want_dag,
            small_dist=small,
            raw_u16=raw_u16,
            transpose=transpose,
        )
