"""Batched single-source shortest paths on TPU.

This is the compute core replacing the reference's per-source Dijkstra
(openr/decision/LinkState.cpp:809-878 `runSpf`).  Instead of a priority queue
(inherently sequential, pointer-chasing — hostile to XLA), we use batched
frontier relaxation (Bellman-Ford iterated to fixed point):

    dist[s, v] <- min(dist[s, v], min over edges (u,v): dist[s, u] + w(u, v))

vmapped over a batch dimension `s`.  The batch rows are *independent problem
variants*: different source nodes (all-sources SPF), different link-exclusion
masks (k-shortest-path runs, SRLG what-if failure simulation), or both.
Each iteration is a dense gather + segment-min — ideal XLA/TPU work; the
fixed-point loop runs at most `graph diameter` iterations (lax.while_loop,
no host round-trips).

Semantics matched against the oracle (LinkState.run_spf):
- drained (overloaded) nodes are reachable but offer no transit: edges out of
  an overloaded node are masked unless that node is the row's source
  (reference: LinkState.cpp:829-836)
- down links never relax (reference: `!link->isUp()` skip)
- ECMP ties survive: the SP-DAG mask marks *every* edge e=(u,v) with
  dist[u] + w == dist[v], reproducing the reference's `>=` relax tie
  retention (LinkState.cpp:855-869)
- first-hop sets (`nextHops` in the reference) come from propagating
  first-hop membership along the SP-DAG to a fixed point

Distances are int32; INF32 (2^30) marks unreachable.  Metrics must be
positive and small enough that no path exceeds 2^30 (the reference uses
uint64 but real metrics are bounded by config; we document the constraint).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

INF32 = jnp.int32(1 << 30)

# uint16 distance mode, shared by the ELL and banded kernels: dist in
# [0, INF16], weights clamped to WBIG16 so INF16 + WBIG16 < 2^16 and the
# relax adds never wrap.  pick_small_dist (ops.banded) gates entry; the
# saturation verdict below certifies no true distance overflowed.
INF16 = jnp.uint32(40000).astype(jnp.uint16)
WBIG16 = jnp.uint32(20000).astype(jnp.uint16)


def clamp_metric_u16(metric: jax.Array) -> jax.Array:
    """Clamp BEFORE the cast: an oversized metric must saturate to the
    band infinity, never wrap (a racing in-place metric refresh must stay
    safe; the int32 retry path restores exactness)."""
    return jnp.minimum(metric, jnp.int32(WBIG16)).astype(jnp.uint16)


def u16_saturation_verdict(dist16: jax.Array, converged: jax.Array) -> jax.Array:
    """AND the convergence verdict with the saturation guard: with every
    weight < WBIG16, any true distance that would overflow INF16 forces
    SOME entry into the finite band [WBIG16, INF16) first, so a clean
    margin certifies no distance saturated."""
    fin_max = jnp.max(jnp.where(dist16 < INF16, dist16, jnp.uint16(0)))
    return converged & (fin_max < WBIG16)


def u16_dist_to_i32(dist16: jax.Array) -> jax.Array:
    """uint16/INF16 domain -> the int32/INF32 output contract."""
    return jnp.where(dist16 >= INF16, INF32, dist16.astype(jnp.int32))


def sp_dag_mask16_from_T(
    dist16_old_T: jax.Array,  # [N_cap, S] uint16 — ORIGINAL node ids
    edge_src: jax.Array,
    edge_dst: jax.Array,
    edge_metric: jax.Array,  # [E] int32 (clamped here)
    allowed_T: jax.Array,  # [E, S]
) -> jax.Array:
    """SP-DAG membership evaluated in the uint16 domain: the [E, S]
    gathers are the extraction's dominant cost at large S, and they move
    half the bytes here.  Valid because finite d + clamped metric < 2^16
    and saturated entries are excluded by the d_u < INF16 guard."""
    m16 = clamp_metric_u16(edge_metric)
    d_u = jnp.take(dist16_old_T, edge_src, axis=0)  # [E, S]
    d_v = jnp.take(dist16_old_T, edge_dst, axis=0)
    return (allowed_T & (d_u < INF16) & (d_u + m16[:, None] == d_v)).T


@jax.jit
def batched_sssp(
    dist0: jax.Array,  # [S, N] int32 — 0 at each row's source(s), INF32 elsewhere
    edge_src: jax.Array,  # [E] int32
    edge_dst: jax.Array,  # [E] int32
    edge_metric: jax.Array,  # [E] int32 (>0)
    relax_allowed: jax.Array,  # [S, E] bool — may this row relax along e?
) -> jax.Array:
    """Fixed-point frontier relaxation.  Returns dist [S, N] int32."""
    n_nodes = dist0.shape[1]

    def relax(dist):
        d_u = jnp.take(dist, edge_src, axis=1)  # [S, E]
        cand = jnp.where(
            relax_allowed & (d_u < INF32),
            d_u + edge_metric[None, :],
            INF32,
        )
        new = jax.vmap(
            lambda c: jax.ops.segment_min(
                c, edge_dst, num_segments=n_nodes, indices_are_sorted=True
            )
        )(cand)
        return jnp.minimum(dist, new)

    def cond(state):
        _, changed, it = state
        return changed & (it < n_nodes)  # path edge-count is bounded by N-1

    def body(state):
        dist, _, it = state
        new = relax(dist)
        return new, jnp.any(new != dist), it + 1

    dist, _, _ = jax.lax.while_loop(cond, body, (dist0, jnp.bool_(True), 0))
    return dist


def make_dist0(sources: jax.Array, n_nodes: int) -> jax.Array:
    """dist0 rows for per-row single sources.  sources: [S] int32."""
    s = sources.shape[0]
    dist0 = jnp.full((s, n_nodes), INF32, dtype=jnp.int32)
    return dist0.at[jnp.arange(s), sources].set(0)


def make_relax_allowed(
    sources: jax.Array,  # [S] int32 — row sources (for the drain exception)
    edge_src: jax.Array,  # [E]
    edge_up: jax.Array,  # [E] bool — link isUp (holds + overload + padding)
    node_overloaded: jax.Array,  # [N] bool
    extra_edge_mask: jax.Array | None = None,  # [S, E] or [E] bool, False=exclude
) -> jax.Array:
    """Row-wise relax permission combining link state, drained-node
    semantics, and per-row exclusions (KSP / what-if)."""
    transit_ok = ~node_overloaded[edge_src]  # [E]
    # a row's own source may relax its out-edges even when overloaded
    allowed = edge_up[None, :] & (
        transit_ok[None, :] | (edge_src[None, :] == sources[:, None])
    )
    if extra_edge_mask is not None:
        if extra_edge_mask.ndim == 1:
            extra_edge_mask = extra_edge_mask[None, :]
        allowed = allowed & extra_edge_mask
    return allowed


@jax.jit
def sp_dag_mask(
    dist: jax.Array,  # [S, N] int32
    edge_src: jax.Array,
    edge_dst: jax.Array,
    edge_metric: jax.Array,
    relax_allowed: jax.Array,  # [S, E]
) -> jax.Array:
    """Shortest-path DAG membership: edge e=(u,v) is on some shortest path
    from row s's source iff dist[s,u] + w(e) == dist[s,v] (and e was
    relaxable).  This reproduces the reference's tie-retaining `pathLinks`
    (every equal-cost in-edge is kept)."""
    d_u = jnp.take(dist, edge_src, axis=1)
    d_v = jnp.take(dist, edge_dst, axis=1)
    return relax_allowed & (d_u < INF32) & (d_u + edge_metric[None, :] == d_v)


# ---------------------------------------------------------------------------
# Degree-bucketed ELL formulation (the production kernel)
# ---------------------------------------------------------------------------
#
# The edge-list kernel above relaxes with a vmapped segment-min, which XLA
# lowers to scatter-min — serialized, slow on TPU (~ms per iteration even on
# a 1k-node grid).  The production kernel instead stores the graph as padded
# in-neighbor tables ("ELL" sparse format), so one relax iteration is K row
# gathers + elementwise mins — pure dense vector work, no scatters:
#
#     dist_T[v, s] <- min_k  dist_T[nbr[v, k], s] + w[v, k]
#
# Distances live TRANSPOSED ([N, S]) so the gather is a row gather
# (contiguous S-length rows — the HBM-friendly access pattern).
#
# Real topologies have skewed degree distributions (a fat-tree fabric switch
# has 100+ in-edges while racks have ~8), so one global K wastes
# N * (K_max - deg) work.  Nodes are therefore RELABELED by descending
# in-degree and partitioned into contiguous buckets of equal padded K
# (power-of-two): per-iteration work is sum_b R_b * K_b ~= 2E instead of
# N * K_max.  The permutation is internal to the ELL world; results are
# gathered back to original ids at the boundary.
#
# Drained-node semantics without per-row masks: the reference lets a row's
# *own source* relax its out-edges even when overloaded
# (LinkState.cpp:829-836).  Since all metrics are >= 1, `dist[s, u] == 0`
# identifies u as row s's source, so the exception is data-dependent and
# row-independent:  relax allowed iff  up & (~overloaded[u] | d_u == 0).
# This keeps the common path free of any [S, E] mask materialization.


class EllBucket(NamedTuple):
    """Contiguous run of (relabeled) nodes sharing padded in-degree K."""

    nbr: jax.Array  # [R, K] int32 — in-neighbor NEW ids (pad: 0, ok=False)
    w: jax.Array  # [R, K] int32 — edge metric (pad: 1)
    edge_id: jax.Array  # [R, K] int32 — original directed edge id; -1 pad
    ok: jax.Array  # [R, K] bool — slot holds a real, up edge
    transit_ok: jax.Array  # [R, K] bool — in-neighbor is not overloaded


class EllGraph(NamedTuple):
    buckets: tuple  # tuple[EllBucket, ...] — rows cover [0, N_cap) in order
    new_of_old: jax.Array  # [N_cap] int32 — old node id -> relabeled id
    old_of_new: jax.Array  # [N_cap] int32 — relabeled id -> old node id


def build_ell(
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    edge_metric: np.ndarray,
    edge_up: np.ndarray,
    node_overloaded: np.ndarray,
    n_edges: int,
    k_floor: int = 4,
) -> EllGraph:
    """Host-side ELL construction from the padded directed-edge arrays
    (vectorized numpy — runs on every topology rebuild, so no Python
    per-edge loops).

    Buckets have power-of-two K >= in-degree (capacity headroom lets
    incremental updates edit slots in place without reshaping).  The
    baked ok/transit_ok tables snapshot edge_up/node_overloaded at build
    time; the production forward passes re-derive both from the runtime
    arrays (see `batched_sssp_ell`), so link/overload flips do NOT require
    an ELL rebuild — only edge-set changes do."""
    n_cap = len(node_overloaded)
    src = np.asarray(edge_src[:n_edges], dtype=np.int64)
    dst = np.asarray(edge_dst[:n_edges], dtype=np.int64)
    deg = np.bincount(dst, minlength=n_cap)

    # stable sort by descending degree -> equal-K runs are contiguous
    old_of_new = np.argsort(-deg, kind="stable").astype(np.int32)
    new_of_old = np.empty_like(old_of_new)
    new_of_old[old_of_new] = np.arange(n_cap, dtype=np.int32)

    # padded K per node: power of two >= max(deg, k_floor)
    deg_sorted = deg[old_of_new]
    exp = np.ceil(np.log2(np.maximum(deg_sorted, 1))).astype(np.int64)
    k_node = np.maximum(np.int64(1) << exp, k_floor)

    # slot index of each edge within its destination's in-edge list.
    # Edge arrays are sorted by (dst, src) so in-edges per dst are
    # contiguous; slot = position within the run, ordered by edge id.
    starts = np.concatenate([[0], np.cumsum(deg)[:-1]])
    slot = np.arange(n_edges, dtype=np.int64) - starts[dst]

    new_dst = new_of_old[dst].astype(np.int64)  # row in permuted space
    buckets: list[EllBucket] = []
    lo = 0
    while lo < n_cap:
        k = int(k_node[lo])
        # contiguous run of equal K (k_node is non-increasing)
        hi = int(np.searchsorted(-k_node, -k, side="right"))
        r = hi - lo
        nbr = np.zeros((r, k), dtype=np.int32)
        w = np.ones((r, k), dtype=np.int32)
        eid = np.full((r, k), -1, dtype=np.int32)
        ok = np.zeros((r, k), dtype=bool)
        t_ok = np.zeros((r, k), dtype=bool)
        in_bucket = (new_dst >= lo) & (new_dst < hi)
        rows = new_dst[in_bucket] - lo
        cols = slot[in_bucket]
        es = np.flatnonzero(in_bucket)
        nbr[rows, cols] = new_of_old[src[es]]
        w[rows, cols] = edge_metric[es]
        eid[rows, cols] = es
        ok[rows, cols] = edge_up[es]
        t_ok[rows, cols] = ~node_overloaded[src[es]]
        buckets.append(EllBucket(nbr, w, eid, ok, t_ok))
        lo = hi

    return EllGraph(tuple(buckets), new_of_old, old_of_new)


def make_dist0_T(
    sources: jax.Array,
    new_of_old: jax.Array,
    n_cap: int,
    small_dist: bool = False,
) -> jax.Array:
    """Transposed-permuted dist0: [N_cap, S] with 0 at each column's source.

    Built as a dense compare, NOT a scatter: scatter ops knock the TPU
    runtime off its fast dispatch path (measured: one scatter in a session
    adds a flat ~100ms penalty to every subsequent kernel launch), so the
    production path must be scatter-free end to end."""
    rows = jnp.take(new_of_old, sources)  # [S]
    is_src = jnp.arange(n_cap, dtype=jnp.int32)[:, None] == rows[None, :]
    if small_dist:
        return jnp.where(is_src, jnp.uint16(0), INF16)
    return jnp.where(is_src, jnp.int32(0), INF32)


@functools.partial(
    jax.jit, static_argnames=("unit_metric", "check_every", "n_sweeps")
)
def batched_sssp_ell(
    dist0_T: jax.Array,  # [N_cap, S] int32 (permuted node rows)
    ell: EllGraph,
    row_allowed_T: Optional[jax.Array] = None,  # [E_cap, S] bool, or None
    unit_metric: bool = False,
    check_every: int = 1,
    edge_up: Optional[jax.Array] = None,  # [E_cap] bool (runtime state)
    node_overloaded: Optional[jax.Array] = None,  # [N_cap] bool, OLD ids
    edge_metric: Optional[jax.Array] = None,  # [E_cap] int32 (runtime state)
    n_sweeps: Optional[int] = None,
):
    """Fixed-point ELL relaxation; returns dist_T [N_cap, S] (permuted).

    With `n_sweeps` (static): runs exactly that many relax sweeps in a
    `fori_loop` plus one verification sweep, returning
    `(dist_T, converged)` — NO data-dependent loop.  A `while_loop` with a
    convergence cond forces a host sync per iteration on latency-bound
    transports (measured ~6-20ms/iteration over the TPU tunnel), so
    production callers run fixed sweeps sized by an adaptive per-topology
    hint and double on a False verdict (csr.CsrTopology.spf_from).
    Without `n_sweeps`: converges via while_loop and returns dist_T only.

    When `edge_up` / `node_overloaded` / `edge_metric` are given, slot
    permissions and weights are derived from them at call time (per-bucket
    [R, K] gathers via edge_id — negligible), so link flaps, drain flips
    and metric changes never require an ELL rebuild and can never disagree
    with the tables.  Without them the build-time snapshots baked into
    `ell` apply.

    `row_allowed_T` adds per-(row, edge) exclusions (KSP link masking, SRLG
    what-if) on top of the up/transit conditions.
    `check_every` batches the convergence reduction over that many relax
    sweeps (saves two [N, S] passes per skipped check on large problems).

    Distances run in the dtype of `dist0_T`: uint16 (INF16 sentinel,
    weights clamped to WBIG16 so adds never wrap — round-5, same
    discipline as ops.banded) halves every gather's bytes; callers gate
    on pick_small_dist and verify the saturation guard.
    """
    n_cap = dist0_T.shape[0]
    small = dist0_T.dtype == jnp.uint16
    inf = INF16 if small else INF32

    # loop-invariant slot permissions, possibly runtime-derived
    overloaded_new = (
        None
        if node_overloaded is None
        else jnp.take(node_overloaded, ell.old_of_new)
    )
    slot_ok: list = []
    slot_transit: list = []
    slot_w: list = []
    slot_allowed: list = []
    for bk in ell.buckets:
        if edge_up is None:
            ok = bk.ok
        else:
            ok = (bk.edge_id >= 0) & jnp.take(
                edge_up, jnp.maximum(bk.edge_id, 0)
            )
        if overloaded_new is None:
            transit = bk.transit_ok
        else:
            transit = ~jnp.take(overloaded_new, bk.nbr)
        w = (
            bk.w
            if edge_metric is None
            else jnp.take(edge_metric, jnp.maximum(bk.edge_id, 0))
        )
        if small:
            w = clamp_metric_u16(w)
        slot_ok.append(ok)
        slot_transit.append(transit)
        slot_w.append(w)
        if row_allowed_T is None:
            slot_allowed.append(None)
        else:
            # HOISTED: the per-row exclusion mask is loop-invariant, so
            # gather it into slot space ONCE ([R, K, S] per bucket)
            # instead of per sweep — per-index gather cost dominates the
            # sweep on TPU, and this halves the masked sweep's gathers
            r, k = bk.nbr.shape
            ej = bk.edge_id
            sa = (ej >= 0)[:, :, None] & jnp.take(
                row_allowed_T, jnp.maximum(ej, 0).reshape(-1), axis=0
            ).reshape(r, k, -1)
            slot_allowed.append(sa)

    def relax(dist_T):
        parts = []
        lo = 0
        for b, bk in enumerate(ell.buckets):
            r, k = bk.nbr.shape
            acc = jax.lax.slice_in_dim(dist_T, lo, lo + r, axis=0)
            # static unroll over slots: each step is one [R, S] row gather
            # plus elementwise min — XLA fuses the whole sweep; a fori_loop
            # with dynamic slot indexing defeats that fusion (~1000x slower
            # measured on v5e)
            for j in range(k):
                d_u = jnp.take(dist_T, bk.nbr[:, j], axis=0)  # [R, S]
                allow = slot_ok[b][:, j][:, None] & (
                    slot_transit[b][:, j][:, None] | (d_u == 0)
                )
                if slot_allowed[b] is not None:
                    allow &= slot_allowed[b][:, j]
                metric_j = (
                    (jnp.uint16(1) if small else jnp.int32(1))
                    if unit_metric
                    else slot_w[b][:, j][:, None]
                )
                cand = jnp.where(allow & (d_u < inf), d_u + metric_j, inf)
                acc = jnp.minimum(acc, cand)
            parts.append(acc)
            lo += r
        assert lo == n_cap
        return jnp.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]

    if n_sweeps is not None:
        dist_T = jax.lax.fori_loop(
            0, n_sweeps, lambda i, d: relax(d), dist0_T
        )
        verify = relax(dist_T)
        return verify, jnp.all(verify == dist_T)

    def cond(state):
        _, changed, it = state
        return changed & (it < n_cap)

    def body(state):
        dist_T, _, it = state
        new = dist_T
        for _ in range(check_every):
            new = relax(new)
        return new, jnp.any(new != dist_T), it + check_every

    dist_T, _, _ = jax.lax.while_loop(
        cond, body, (dist0_T, jnp.bool_(True), 0)
    )
    return dist_T


def ell_dist_to_old_T(dist_T: jax.Array, ell: EllGraph) -> jax.Array:
    """Permuted [N_cap, S] -> original-id [N_cap, S] (still transposed —
    callers that need [S, N] transpose at their boundary)."""
    return jnp.take(dist_T, ell.new_of_old, axis=0)


def make_relax_allowed_T(
    sources: jax.Array,  # [S]
    edge_src: jax.Array,  # [E]
    edge_up: jax.Array,  # [E]
    node_overloaded: jax.Array,  # [N]
    extra_edge_mask_T: jax.Array | None = None,  # [E, S] or [E]
) -> jax.Array:
    """Edge-major ([E, S]) variant of `make_relax_allowed` — the layout the
    transposed DAG/relax kernels consume without a transpose."""
    transit_ok = ~node_overloaded[edge_src]  # [E]
    allowed = edge_up[:, None] & (
        transit_ok[:, None] | (edge_src[:, None] == sources[None, :])
    )
    if extra_edge_mask_T is not None:
        if extra_edge_mask_T.ndim == 1:
            extra_edge_mask_T = extra_edge_mask_T[:, None]
        allowed = allowed & extra_edge_mask_T
    return allowed


@jax.jit
def sp_dag_mask_from_T(
    dist_old_T: jax.Array,  # [N_cap, S] int32 — ORIGINAL node ids
    edge_src: jax.Array,
    edge_dst: jax.Array,
    edge_metric: jax.Array,
    allowed_T: jax.Array,  # [E, S]
) -> jax.Array:
    """`sp_dag_mask` computed in edge-major space (row gathers only —
    the [S, N] column gather of the untransposed form is pathologically
    slow on TPU); returns dag [S, E]."""
    d_u = jnp.take(dist_old_T, edge_src, axis=0)  # [E, S]
    d_v = jnp.take(dist_old_T, edge_dst, axis=0)
    dag_T = allowed_T & (d_u < INF32) & (d_u + edge_metric[:, None] == d_v)
    return dag_T.T


@functools.partial(jax.jit, static_argnames=("use_link_metric",))
def spf_forward_ell(
    sources: jax.Array,  # [S] int32 (original ids)
    ell: EllGraph,
    edge_src: jax.Array,
    edge_dst: jax.Array,
    edge_metric: jax.Array,
    edge_up: jax.Array,
    node_overloaded: jax.Array,
    use_link_metric: bool = True,
) -> tuple[jax.Array, jax.Array]:
    """Production forward pass: ELL distances + edge-space SP-DAG.

    Same contract as `spf_forward` (dist [S, N_cap] original ids,
    dag [S, E_cap]) but relaxation runs on the bucketed ELL tables."""
    n_cap = node_overloaded.shape[0]
    dist_T = batched_sssp_ell(
        make_dist0_T(sources, ell.new_of_old, n_cap),
        ell,
        unit_metric=not use_link_metric,
        edge_up=edge_up,
        node_overloaded=node_overloaded,
        edge_metric=edge_metric,
    )
    dist_old_T = ell_dist_to_old_T(dist_T, ell)  # [N_cap, S]
    metric = edge_metric if use_link_metric else jnp.ones_like(edge_metric)
    allowed_T = make_relax_allowed_T(sources, edge_src, edge_up, node_overloaded)
    dag = sp_dag_mask_from_T(dist_old_T, edge_src, edge_dst, metric, allowed_T)
    return dist_old_T.T, dag


@functools.partial(
    jax.jit, static_argnames=("use_link_metric", "want_dag")
)
def spf_forward_ell_masked(
    sources: jax.Array,
    ell: EllGraph,
    edge_src: jax.Array,
    edge_dst: jax.Array,
    edge_metric: jax.Array,
    edge_up: jax.Array,
    node_overloaded: jax.Array,
    extra_edge_mask: jax.Array,  # [S, E_cap] or [E_cap] bool, False = exclude
    use_link_metric: bool = True,
    want_dag: bool = True,
) -> tuple[jax.Array, jax.Array]:
    """ELL forward with per-row edge exclusions (KSP re-runs, SRLG
    what-if).  The [S, E] mask is materialized — callers batch many
    variants, so S is the what-if dimension here.  With want_dag=False
    only distances are computed/returned (dist, None) — the what-if
    reachability analysis never reads the DAG."""
    n_cap = node_overloaded.shape[0]
    extra_T = (
        extra_edge_mask.T if extra_edge_mask.ndim == 2 else extra_edge_mask
    )
    allowed_T = make_relax_allowed_T(
        sources, edge_src, edge_up, node_overloaded, extra_T
    )
    dist_T = batched_sssp_ell(
        make_dist0_T(sources, ell.new_of_old, n_cap),
        ell,
        row_allowed_T=allowed_T,
        unit_metric=not use_link_metric,
        edge_up=edge_up,
        node_overloaded=node_overloaded,
        edge_metric=edge_metric,
    )
    dist_old_T = ell_dist_to_old_T(dist_T, ell)
    if not want_dag:
        return dist_old_T.T, None
    metric = edge_metric if use_link_metric else jnp.ones_like(edge_metric)
    dag = sp_dag_mask_from_T(dist_old_T, edge_src, edge_dst, metric, allowed_T)
    return dist_old_T.T, dag


@functools.partial(
    jax.jit, static_argnames=("n_words", "check_every", "n_sweeps")
)
def first_hops_ell(
    ell: EllGraph,
    dag_T: jax.Array,  # [E_cap, S] bool — edge-major SP-DAG (original edge ids)
    out_slot: jax.Array,  # [E_cap] int32 — slot of edge among its source
    #   node's sorted unique out-neighbors; -1 for padding
    sources: jax.Array,  # [S] int32 — original node ids
    edge_src: jax.Array,  # [E_cap] int32 — original node ids
    n_words: int,  # ceil(max_slots / 32)
    check_every: int = 1,
    n_sweeps: Optional[int] = None,
):
    """First-hop sets propagated along the SP-DAG, bit-packed.

    With static `n_sweeps`: fixed fori_loop + one verification sweep,
    returning (nh, converged) — same host-sync rationale as
    `batched_sssp_ell`.  Without: while_loop to fixed point, returns nh.

    Returns nh [S, N_cap, n_words] uint32 (ORIGINAL node ids): bit b of
    word w is set for (s, v) iff slot (32w + b) — an out-neighbor of row
    s's source — begins some shortest path to v.  Device replacement for
    the reference's per-node nextHops accumulation (runSpf addNextHops,
    LinkState.cpp:855-869); the host only decodes bits afterwards.

    Gather-only (no scatters): propagation gathers predecessor masks
    through the ELL in-edge tables; an edge leaving the row's own source
    contributes its own out-slot bit instead of the predecessor mask."""
    n_cap = ell.new_of_old.shape[0]
    s_dim = sources.shape[0]

    # per-edge initial contribution: if the edge leaves the row's source,
    # its out-slot bit, else 0
    is_src_edge = edge_src[:, None] == sources[None, :]  # [E_cap, S]

    # HOISTED loop invariants (the dag, source membership and slot-bit
    # tables never change across sweeps): gathering them per sweep used to
    # triple the sweep's gather count, and per-index gather cost dominates
    # on TPU.  Precompute per (bucket, slot):
    #   src_contrib [R, S, W] — OR-term contributed by source-leaving
    #     dag edges (constant across sweeps)
    #   use_pred    [R, K, S] — dag edges that forward the predecessor mask
    src_contrib: list = []
    use_pred: list = []
    for bk in ell.buckets:
        r, k = bk.nbr.shape
        ej_all = jnp.maximum(bk.edge_id, 0)  # [R, K]
        on_dag = jnp.take(dag_T, ej_all.reshape(-1), axis=0).reshape(
            r, k, -1
        ) & (bk.edge_id >= 0)[:, :, None]  # [R, K, S]
        from_src = jnp.take(
            is_src_edge, ej_all.reshape(-1), axis=0
        ).reshape(r, k, -1)  # [R, K, S]
        slot = jnp.take(out_slot, ej_all)  # [R, K]
        bit = jnp.where(
            slot >= 0,
            jnp.uint32(1) << (jnp.maximum(slot, 0) % 32).astype(jnp.uint32),
            jnp.uint32(0),
        )
        src_words = jnp.where(
            (jnp.maximum(slot, 0) // 32)[:, :, None]
            == jnp.arange(n_words)[None, None, :],
            bit[:, :, None],
            jnp.uint32(0),
        )  # [R, K, W]
        # OR over slots of the constant source contributions
        sc = jnp.zeros((r, on_dag.shape[2], n_words), dtype=jnp.uint32)
        for j in range(k):
            sc = sc | jnp.where(
                (on_dag[:, j] & from_src[:, j])[:, :, None],
                src_words[:, j][:, None, :],
                jnp.uint32(0),
            )
        src_contrib.append(sc)  # [R, S, W]
        use_pred.append(on_dag & ~from_src)  # [R, K, S]

    def relax(nh_T):
        # nh_T: [N_cap, S, W] uint32, permuted rows
        parts = []
        lo = 0
        for b, bk in enumerate(ell.buckets):
            r, k = bk.nbr.shape
            acc = jax.lax.slice_in_dim(nh_T, lo, lo + r, axis=0)
            acc = acc | src_contrib[b]
            for j in range(k):
                pred = jnp.take(nh_T, bk.nbr[:, j], axis=0)  # [R, S, W]
                acc = acc | jnp.where(
                    use_pred[b][:, j][:, :, None], pred, jnp.uint32(0)
                )
            parts.append(acc)
            lo += r
        assert lo == n_cap
        return jnp.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]

    nh0 = jnp.zeros((n_cap, s_dim, n_words), dtype=jnp.uint32)

    def to_original(nh_T):
        # permute rows back to original ids, reorder to [S, N, W]
        return jnp.take(nh_T, ell.new_of_old, axis=0).transpose(1, 0, 2)

    if n_sweeps is not None:
        nh_T = jax.lax.fori_loop(0, n_sweeps, lambda i, x: relax(x), nh0)
        verify = relax(nh_T)
        return to_original(verify), jnp.all(verify == nh_T)

    def cond(state):
        _, changed, it = state
        return changed & (it < n_cap)

    def body(state):
        nh_T, _, it = state
        new = nh_T
        for _ in range(check_every):
            new = relax(new)
        return new, jnp.any(new != nh_T), it + check_every

    nh_T, _, _ = jax.lax.while_loop(cond, body, (nh0, jnp.bool_(True), 0))
    return to_original(nh_T)


@functools.partial(
    jax.jit,
    static_argnames=("use_link_metric", "n_words", "check_every", "n_sweeps"),
)
def spf_forward_full(
    sources: jax.Array,  # [S] int32
    ell: EllGraph,
    edge_src: jax.Array,
    edge_dst: jax.Array,
    edge_metric: jax.Array,
    edge_up: jax.Array,
    node_overloaded: jax.Array,
    out_slot: jax.Array,  # [E_cap] int32
    n_words: int,
    use_link_metric: bool = True,
    check_every: int = 1,
    n_sweeps: Optional[int] = None,
):
    """Distances + SP-DAG + bit-packed first-hop sets in ONE device call —
    the full production forward for route building.

    With static `n_sweeps`: both fixed-point loops run fixed sweeps and
    the call returns (dist, dag, nh, converged) with a single combined
    convergence verdict (see batched_sssp_ell's host-sync rationale)."""
    n_cap = node_overloaded.shape[0]
    dist_out = batched_sssp_ell(
        make_dist0_T(sources, ell.new_of_old, n_cap),
        ell,
        unit_metric=not use_link_metric,
        check_every=check_every,
        edge_up=edge_up,
        node_overloaded=node_overloaded,
        edge_metric=edge_metric,
        n_sweeps=n_sweeps,
    )
    if n_sweeps is not None:
        dist_T, dist_ok = dist_out
    else:
        dist_T, dist_ok = dist_out, None
    dist_old_T = ell_dist_to_old_T(dist_T, ell)
    metric = edge_metric if use_link_metric else jnp.ones_like(edge_metric)
    allowed_T = make_relax_allowed_T(sources, edge_src, edge_up, node_overloaded)
    d_u = jnp.take(dist_old_T, edge_src, axis=0)
    d_v = jnp.take(dist_old_T, edge_dst, axis=0)
    dag_T = allowed_T & (d_u < INF32) & (d_u + metric[:, None] == d_v)
    nh_out = first_hops_ell(
        ell,
        dag_T,
        out_slot,
        sources,
        edge_src,
        n_words,
        check_every=check_every,
        n_sweeps=n_sweeps,
    )
    if n_sweeps is not None:
        nh, nh_ok = nh_out
        return dist_old_T.T, dag_T.T, nh, dist_ok & nh_ok
    return dist_old_T.T, dag_T.T, nh_out


@functools.partial(
    jax.jit,
    static_argnames=("use_link_metric", "n_words", "check_every", "n_sweeps"),
)
def spf_forward_full_packed(
    sources: jax.Array,
    ell: EllGraph,
    edge_src: jax.Array,
    edge_dst: jax.Array,
    edge_metric: jax.Array,
    edge_up: jax.Array,
    node_overloaded: jax.Array,
    out_slot: jax.Array,
    n_words: int,
    use_link_metric: bool = True,
    check_every: int = 1,
    n_sweeps: Optional[int] = None,
) -> jax.Array:
    """`spf_forward_full` with (dist, dag, nh[, converged]) flattened into
    ONE int32 buffer, so the host needs a single device->host transfer.
    Matters for small-S control-plane queries where per-transfer latency
    dominates (each fetch is a tunnel round trip); callers unpack by known
    sizes.  With `n_sweeps`, the final element is the convergence verdict
    (1 = fixed point reached)."""
    out = spf_forward_full(
        sources,
        ell,
        edge_src,
        edge_dst,
        edge_metric,
        edge_up,
        node_overloaded,
        out_slot,
        n_words,
        use_link_metric=use_link_metric,
        check_every=check_every,
        n_sweeps=n_sweeps,
    )
    dist, dag, nh = out[0], out[1], out[2]
    parts = [
        dist.ravel(),
        dag.ravel().astype(jnp.int32),
        jax.lax.bitcast_convert_type(nh, jnp.int32).ravel(),
    ]
    if n_sweeps is not None:
        parts.append(out[3].astype(jnp.int32)[None])
    return jnp.concatenate(parts)


@functools.partial(
    jax.jit,
    static_argnames=(
        "use_link_metric",
        "n_sweeps",
        "want_dag",
        "small_dist",
        "raw_u16",
        "transpose",
    ),
)
def spf_forward_ell_sweeps(
    sources: jax.Array,
    ell: EllGraph,
    edge_src: jax.Array,
    edge_dst: jax.Array,
    edge_metric: jax.Array,
    edge_up: jax.Array,
    node_overloaded: jax.Array,
    n_sweeps: int,
    use_link_metric: bool = True,
    extra_edge_mask: Optional[jax.Array] = None,
    want_dag: bool = True,
    small_dist: bool = False,
    raw_u16: bool = False,
    transpose: bool = True,
):
    """Fixed-sweep ELL forward: (dist [S, N_cap], dag, converged) — the
    production execution discipline (no data-dependent while_loop, which
    costs a host sync per iteration on latency-bound transports) exposed
    for dist+dag callers: bench rows and batch KSP/what-if runs on
    topologies without band structure (see ops.banded for the rest).

    ``small_dist`` runs the relax AND the DAG extraction in uint16
    (half the gather bytes; callers gate on pick_small_dist); the
    in-kernel saturation guard certifies no distance overflowed exactly
    as in ops.banded.  ``raw_u16`` additionally returns the raw uint16
    distances (INF16 sentinel) when want_dag=False — consumers key on
    dtype."""
    # static-arg guard (trace time): the dag path returns [S, N_cap]
    # unconditionally (see ops.banded.spf_forward_banded)
    assert transpose or not want_dag, (
        "transpose=False requires want_dag=False"
    )
    n_cap = node_overloaded.shape[0]
    extra_T = None
    if extra_edge_mask is not None:
        extra_T = (
            extra_edge_mask.T
            if extra_edge_mask.ndim == 2
            else extra_edge_mask[:, None]
        )
    allowed_T = make_relax_allowed_T(
        sources, edge_src, edge_up, node_overloaded, extra_T
    )
    dist_T, converged = batched_sssp_ell(
        make_dist0_T(sources, ell.new_of_old, n_cap, small_dist=small_dist),
        ell,
        row_allowed_T=allowed_T if extra_edge_mask is not None else None,
        unit_metric=not use_link_metric,
        edge_up=edge_up,
        node_overloaded=node_overloaded,
        edge_metric=edge_metric,
        n_sweeps=n_sweeps,
    )
    dist_old_T = ell_dist_to_old_T(dist_T, ell)
    dist16_old_T = None
    if small_dist:
        converged = u16_saturation_verdict(dist_old_T, converged)
        dist16_old_T = dist_old_T
        if raw_u16 and not want_dag:
            return (
                (dist_old_T.T if transpose else dist_old_T),
                None,
                converged,
            )
        dist_old_T = u16_dist_to_i32(dist_old_T)
    if not want_dag:
        return (dist_old_T.T if transpose else dist_old_T), None, converged
    metric = edge_metric if use_link_metric else jnp.ones_like(edge_metric)
    if dist16_old_T is not None:
        dag = sp_dag_mask16_from_T(
            dist16_old_T, edge_src, edge_dst, metric, allowed_T
        )
        return dist_old_T.T, dag, converged
    dag = sp_dag_mask_from_T(dist_old_T, edge_src, edge_dst, metric, allowed_T)
    return dist_old_T.T, dag, converged


@functools.partial(jax.jit, static_argnames=("use_link_metric",))
def spf_forward(
    sources: jax.Array,  # [S] int32
    edge_src: jax.Array,
    edge_dst: jax.Array,
    edge_metric: jax.Array,
    edge_up: jax.Array,
    node_overloaded: jax.Array,
    use_link_metric: bool = True,
) -> tuple[jax.Array, jax.Array]:
    """One-call forward: distances + SP-DAG for a batch of sources.
    This is the flagship jittable step (see __graft_entry__)."""
    metric = edge_metric if use_link_metric else jnp.ones_like(edge_metric)
    n_nodes = node_overloaded.shape[0]
    allowed = make_relax_allowed(sources, edge_src, edge_up, node_overloaded)
    dist = batched_sssp(make_dist0(sources, n_nodes), edge_src, edge_dst, metric, allowed)
    dag = sp_dag_mask(dist, edge_src, edge_dst, metric, allowed)
    return dist, dag
