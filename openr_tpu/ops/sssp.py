"""Batched single-source shortest paths on TPU.

This is the compute core replacing the reference's per-source Dijkstra
(openr/decision/LinkState.cpp:809-878 `runSpf`).  Instead of a priority queue
(inherently sequential, pointer-chasing — hostile to XLA), we use batched
frontier relaxation (Bellman-Ford iterated to fixed point):

    dist[s, v] <- min(dist[s, v], min over edges (u,v): dist[s, u] + w(u, v))

vmapped over a batch dimension `s`.  The batch rows are *independent problem
variants*: different source nodes (all-sources SPF), different link-exclusion
masks (k-shortest-path runs, SRLG what-if failure simulation), or both.
Each iteration is a dense gather + segment-min — ideal XLA/TPU work; the
fixed-point loop runs at most `graph diameter` iterations (lax.while_loop,
no host round-trips).

Semantics matched against the oracle (LinkState.run_spf):
- drained (overloaded) nodes are reachable but offer no transit: edges out of
  an overloaded node are masked unless that node is the row's source
  (reference: LinkState.cpp:829-836)
- down links never relax (reference: `!link->isUp()` skip)
- ECMP ties survive: the SP-DAG mask marks *every* edge e=(u,v) with
  dist[u] + w == dist[v], reproducing the reference's `>=` relax tie
  retention (LinkState.cpp:855-869)
- first-hop sets (`nextHops` in the reference) come from propagating
  first-hop membership along the SP-DAG to a fixed point

Distances are int32; INF32 (2^30) marks unreachable.  Metrics must be
positive and small enough that no path exceeds 2^30 (the reference uses
uint64 but real metrics are bounded by config; we document the constraint).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

INF32 = jnp.int32(1 << 30)


@jax.jit
def batched_sssp(
    dist0: jax.Array,  # [S, N] int32 — 0 at each row's source(s), INF32 elsewhere
    edge_src: jax.Array,  # [E] int32
    edge_dst: jax.Array,  # [E] int32
    edge_metric: jax.Array,  # [E] int32 (>0)
    relax_allowed: jax.Array,  # [S, E] bool — may this row relax along e?
) -> jax.Array:
    """Fixed-point frontier relaxation.  Returns dist [S, N] int32."""
    n_nodes = dist0.shape[1]

    def relax(dist):
        d_u = jnp.take(dist, edge_src, axis=1)  # [S, E]
        cand = jnp.where(
            relax_allowed & (d_u < INF32),
            d_u + edge_metric[None, :],
            INF32,
        )
        new = jax.vmap(
            lambda c: jax.ops.segment_min(
                c, edge_dst, num_segments=n_nodes, indices_are_sorted=True
            )
        )(cand)
        return jnp.minimum(dist, new)

    def cond(state):
        _, changed, it = state
        return changed & (it < n_nodes)  # path edge-count is bounded by N-1

    def body(state):
        dist, _, it = state
        new = relax(dist)
        return new, jnp.any(new != dist), it + 1

    dist, _, _ = jax.lax.while_loop(cond, body, (dist0, jnp.bool_(True), 0))
    return dist


def make_dist0(sources: jax.Array, n_nodes: int) -> jax.Array:
    """dist0 rows for per-row single sources.  sources: [S] int32."""
    s = sources.shape[0]
    dist0 = jnp.full((s, n_nodes), INF32, dtype=jnp.int32)
    return dist0.at[jnp.arange(s), sources].set(0)


def make_relax_allowed(
    sources: jax.Array,  # [S] int32 — row sources (for the drain exception)
    edge_src: jax.Array,  # [E]
    edge_up: jax.Array,  # [E] bool — link isUp (holds + overload + padding)
    node_overloaded: jax.Array,  # [N] bool
    extra_edge_mask: jax.Array | None = None,  # [S, E] or [E] bool, False=exclude
) -> jax.Array:
    """Row-wise relax permission combining link state, drained-node
    semantics, and per-row exclusions (KSP / what-if)."""
    transit_ok = ~node_overloaded[edge_src]  # [E]
    # a row's own source may relax its out-edges even when overloaded
    allowed = edge_up[None, :] & (
        transit_ok[None, :] | (edge_src[None, :] == sources[:, None])
    )
    if extra_edge_mask is not None:
        if extra_edge_mask.ndim == 1:
            extra_edge_mask = extra_edge_mask[None, :]
        allowed = allowed & extra_edge_mask
    return allowed


@jax.jit
def sp_dag_mask(
    dist: jax.Array,  # [S, N] int32
    edge_src: jax.Array,
    edge_dst: jax.Array,
    edge_metric: jax.Array,
    relax_allowed: jax.Array,  # [S, E]
) -> jax.Array:
    """Shortest-path DAG membership: edge e=(u,v) is on some shortest path
    from row s's source iff dist[s,u] + w(e) == dist[s,v] (and e was
    relaxable).  This reproduces the reference's tie-retaining `pathLinks`
    (every equal-cost in-edge is kept)."""
    d_u = jnp.take(dist, edge_src, axis=1)
    d_v = jnp.take(dist, edge_dst, axis=1)
    return relax_allowed & (d_u < INF32) & (d_u + edge_metric[None, :] == d_v)


@functools.partial(jax.jit, static_argnames=("n_slots",))
def first_hop_matrix(
    dag: jax.Array,  # [S, E] bool — SP-DAG membership
    dist: jax.Array,  # [S, N] int32 (for iteration bound only)
    edge_src: jax.Array,  # [E]
    edge_dst: jax.Array,  # [E]
    edge_slot: jax.Array,  # [S, E] int32 — j if edge e is source-row s's j-th
    #                         out-edge (first hop slot), else -1
    n_slots: int,
) -> jax.Array:
    """Propagate first-hop membership along the SP-DAG.

    Returns nh [S, N, D] bool: nh[s, v, j] == True iff row s's j-th out-edge
    begins some shortest path to v — the device form of the reference's
    per-node `nextHops` sets (runSpf's addNextHops accumulation,
    LinkState.cpp:855-869).
    """
    s_dim, n_nodes = dist.shape

    # init: direct DAG edges out of the source claim their own slot
    slot_onehot = (
        jax.nn.one_hot(edge_slot, n_slots, dtype=jnp.bool_)
        & dag[:, :, None]
        & (edge_slot >= 0)[:, :, None]
    )  # [S, E, D]
    nh0 = jax.vmap(
        lambda oh, dst: jax.ops.segment_max(
            oh.astype(jnp.int32), dst, num_segments=n_nodes, indices_are_sorted=True
        )
    )(slot_onehot, jnp.broadcast_to(edge_dst, (s_dim, edge_dst.shape[0])))
    nh0 = nh0.astype(jnp.bool_)  # [S, N, D]

    def cond(state):
        _, changed, it = state
        return changed & (it < n_nodes)

    def body(state):
        nh, _, it = state
        contrib = jnp.take(nh, edge_src, axis=1) & dag[:, :, None]  # [S, E, D]
        prop = jax.vmap(
            lambda c: jax.ops.segment_max(
                c.astype(jnp.int32),
                edge_dst,
                num_segments=n_nodes,
                indices_are_sorted=True,
            )
        )(contrib).astype(jnp.bool_)
        new = nh | prop
        return new, jnp.any(new != nh), it + 1

    nh, _, _ = jax.lax.while_loop(cond, body, (nh0, jnp.bool_(True), 0))
    return nh


@functools.partial(jax.jit, static_argnames=("use_link_metric",))
def spf_forward(
    sources: jax.Array,  # [S] int32
    edge_src: jax.Array,
    edge_dst: jax.Array,
    edge_metric: jax.Array,
    edge_up: jax.Array,
    node_overloaded: jax.Array,
    use_link_metric: bool = True,
) -> tuple[jax.Array, jax.Array]:
    """One-call forward: distances + SP-DAG for a batch of sources.
    This is the flagship jittable step (see __graft_entry__)."""
    metric = edge_metric if use_link_metric else jnp.ones_like(edge_metric)
    n_nodes = node_overloaded.shape[0]
    allowed = make_relax_allowed(sources, edge_src, edge_up, node_overloaded)
    dist = batched_sssp(make_dist0(sources, n_nodes), edge_src, edge_dst, metric, allowed)
    dag = sp_dag_mask(dist, edge_src, edge_dst, metric, allowed)
    return dist, dag
