"""Fused k=2 edge-disjoint shortest paths: base SPF + device path trace +
masked re-run batch in ONE compiled program.

The reference computes k-shortest edge-disjoint paths by re-running
Dijkstra with the previous paths' links excluded, tracing each path on
the host between runs (openr/decision/LinkState.cpp:763-793 getKthPaths,
traceOnePath :399-418).  Round-4 measured that through a latency-bound
transport the serial chain [base SPF] -> host trace -> [masked batch]
pays a flat per-dispatch fee each hop — for the dual-metric KSP row the
4-dispatch chain lost 3.1x on wall to the C++ baseline while the pure
kernel time was far ahead.

This module moves the path trace ON DEVICE: a fori_loop walks each
destination's shortest path backwards over the SP-DAG (first dag-true
in-edge, identical tie choice to the host's cand[0] in the
(dst, src)-sorted edge order), builds the per-destination exclusion
masks, and immediately runs the masked re-run batch — base relax, trace,
mask build, and masked relax all inside one jit, so a whole plane (or
several metric planes) costs ONE dispatch.

Banded-kernel path only (the 100k WAN rows); callers fall back to the
host chain on unbanded topologies.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .sssp import INF32


class Ksp2PlaneResult(NamedTuple):
    k1: jax.Array  # [D] int32 — shortest distance per destination
    k2: jax.Array  # [D] int32 — edge-disjoint second distance (INF32 none)
    excl: jax.Array  # [D, max_hops] int32 — excluded edge ids (pad E_cap-1)
    ok_base: jax.Array  # bool — base relax converged
    ok_masked: jax.Array  # bool — masked batch converged
    trace_ok: jax.Array  # bool — every walker terminated on src/unreachable


def build_in_start(edge_dst: np.ndarray, n_edges: int, n_nodes: int) -> np.ndarray:
    """[N+1] int32: in-edges of v are the contiguous run
    [in_start[v], in_start[v+1]) of the (dst, src)-sorted edge arrays."""
    return np.searchsorted(
        edge_dst[:n_edges], np.arange(n_nodes + 1)
    ).astype(np.int32)


def _trace_paths(
    d_row: jax.Array,  # [N] int32 — base distances from src
    dag_row: jax.Array,  # [E_cap] bool — SP-DAG of the base run
    dest_ids: jax.Array,  # [D] int32
    edge_src: jax.Array,
    in_start: jax.Array,  # [N+1] int32
    max_hops: int,
    k_in: int,
):
    """All-destination backward walk: per step each walker takes the FIRST
    dag-true in-edge of its node (== the host trace's cand[0] in the same
    sorted order) and moves to that edge's source.  Returns (excl
    [D, max_hops] int32 edge ids padded with E_cap-1, trace_ok)."""
    d = dest_ids.shape[0]
    e_cap = edge_src.shape[0]
    pad = jnp.int32(e_cap - 1)
    offs = jnp.arange(k_in, dtype=jnp.int32)

    def body(t, state):
        v, excl, err = state
        dv = jnp.take(d_row, v)  # [D]
        active = (dv > 0) & (dv < INF32)
        base = jnp.take(in_start, v)  # [D]
        deg = jnp.take(in_start, v + 1) - base
        eids = base[:, None] + offs[None, :]  # [D, K]
        valid = offs[None, :] < deg[:, None]
        eids_c = jnp.where(valid, eids, pad)
        bits = jnp.take(dag_row, eids_c) & valid  # [D, K]
        has = jnp.any(bits, axis=1)
        k_sel = jnp.argmax(bits, axis=1)
        e_sel = jnp.take_along_axis(eids_c, k_sel[:, None], axis=1)[:, 0]
        step = active & has
        excl = excl.at[:, t].set(jnp.where(step, e_sel, pad))
        v = jnp.where(step, jnp.take(edge_src, e_sel), v)
        err = err | (active & ~has)  # broken DAG
        return v, excl, err

    v0 = dest_ids
    excl0 = jnp.full((d, max_hops), pad, dtype=jnp.int32)
    err0 = jnp.zeros((d,), dtype=bool)
    v, excl, err = jax.lax.fori_loop(0, max_hops, body, (v0, excl0, err0))
    dv = jnp.take(d_row, v)
    done = (dv == 0) | (dv >= INF32)
    trace_ok = jnp.all(done) & ~jnp.any(err)
    return excl, trace_ok


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_sweeps_base",
        "n_sweeps_masked",
        "depth",
        "resid_rounds",
        "small_dist",
        "max_hops",
        "k_in",
        "chord_mode",
    ),
)
def fused_ksp2_banded(
    src: jax.Array,  # [1] int32
    dest_ids: jax.Array,  # [D] int32
    bg,  # ops.banded.BandedGraph
    edge_src: jax.Array,
    edge_dst: jax.Array,
    edge_up: jax.Array,
    node_overloaded: jax.Array,
    metric_planes: jax.Array,  # [P, E_cap] int32 — one row per cost plane
    in_start: jax.Array,  # [N+1] int32
    rev_eid: jax.Array,  # [E_cap] int32 — reverse directed edge; -1 none
    n_sweeps_base: int,
    n_sweeps_masked: int,
    depth: int,
    resid_rounds: int,
    small_dist: bool,
    max_hops: int,
    k_in: int,
    chord_mode: bool = False,
) -> list[Ksp2PlaneResult]:
    """Per metric plane: base SPF -> trace -> edge-disjoint masked batch,
    ALL planes in this one program.  Edge-disjointness excludes both
    directions of every traced link (the reference's link exclusion,
    LinkState.cpp:778-785)."""
    from .banded import spf_forward_banded

    d = dest_ids.shape[0]
    e_cap = edge_src.shape[0]
    rows = jnp.arange(d)
    results = []
    for p in range(metric_planes.shape[0]):
        metric = metric_planes[p]
        dist, dag, ok_base = spf_forward_banded(
            src,
            bg,
            edge_src,
            edge_dst,
            metric,
            edge_up,
            node_overloaded,
            n_supersweeps=n_sweeps_base,
            depth=depth,
            resid_rounds=resid_rounds,
            small_dist=small_dist,
            want_dag=True,
            chord_mode=chord_mode,
        )
        d_row = dist[0]
        dag_row = dag[0]
        excl, trace_ok = _trace_paths(
            d_row, dag_row, dest_ids, edge_src, in_start, max_hops, k_in
        )
        # row masks: excluded edges + their reverse twins (pad edge ids
        # land on E_cap-1, a permanently-down padding edge)
        rev_e = jnp.take(rev_eid, excl)
        rev_e = jnp.where(rev_e >= 0, rev_e, jnp.int32(e_cap - 1))
        mask = jnp.ones((d, e_cap), dtype=bool)
        mask = mask.at[rows[:, None], excl].set(False)
        mask = mask.at[rows[:, None], rev_e].set(False)
        srcs = jnp.broadcast_to(src[0], (d,)).astype(jnp.int32)
        dist2, _, ok_masked = spf_forward_banded(
            srcs,
            bg,
            edge_src,
            edge_dst,
            metric,
            edge_up,
            node_overloaded,
            n_supersweeps=n_sweeps_masked,
            depth=depth,
            resid_rounds=resid_rounds,
            extra_edge_mask=mask,
            small_dist=small_dist,
            want_dag=False,
            chord_mode=chord_mode,
        )
        k1 = jnp.take(d_row, dest_ids)
        k2 = dist2[rows, dest_ids]
        results.append(
            Ksp2PlaneResult(k1, k2, excl, ok_base, ok_masked, trace_ok)
        )
    return results


class FusedKsp2Runner:
    """Host driver: learns sweep hints through the runner's adaptive
    machinery, then serves whole multi-plane KSP2 questions as single
    dispatches.

    The metric planes are fixed at construction and staged
    device-resident, along with the runner's edge arrays (stage()):
    per-call re-uploads and host rescans of invariant MB-scale state
    would otherwise be charged to every 'one dispatch' call.  Callers
    that mutate the underlying topology arrays must build a fresh
    instance."""

    def __init__(
        self, runner, topo_edge_dst, n_edges, n_nodes, rev_eid, metric_planes
    ):
        from .banded import pick_small_dist

        assert runner.bg is not None, "fused KSP2 needs the banded kernel"
        e_cap = runner.arrays[0].shape[0]
        # the trace/mask pad id is E_cap-1, which must be a PADDING edge
        # (permanently down) — aliasing a real edge would silently mask
        # it for every destination and corrupt k2
        assert n_edges < e_cap, "edge capacity leaves no padding edge"
        self.runner = runner
        runner.stage()
        self.n_edges = n_edges
        self.planes_np = [np.asarray(m) for m in metric_planes]
        self.planes = jnp.stack([jnp.asarray(m) for m in self.planes_np])
        # uint16 eligibility of the staged planes, computed ONCE from the
        # host copies (run_once's small_override path)
        self.planes_small = all(
            pick_small_dist(m, n_edges) for m in self.planes_np
        )
        in_start_np = build_in_start(np.asarray(topo_edge_dst), n_edges, n_nodes)
        self.in_start = jnp.asarray(in_start_np)
        rev_full = np.full(e_cap, -1, dtype=np.int32)
        rev_full[: len(rev_eid)] = rev_eid
        self.rev_eid = jnp.asarray(rev_full)
        self.rev_eid_np = rev_full
        # degree read stays on the host copy — no device round-trip at setup
        in_deg = np.diff(in_start_np)
        self.k_in = max(1, int(in_deg.max()))
        # hop bound for the trace loop; grows adaptively when a converged
        # base leaves walkers short (run()), so later non-adaptive calls
        # reuse the learned bound
        self.learned_max_hops = 128

    def _fused_call(self, src_a, dest_a, max_hops: int) -> list[Ksp2PlaneResult]:
        r = self.runner
        edge_src, edge_dst, _metric, edge_up, node_ov = r.call_arrays()
        small = r.small_allowed and self.planes_small
        return fused_ksp2_banded(
            src_a,
            dest_a,
            r.bg,
            jnp.asarray(edge_src),
            jnp.asarray(edge_dst),
            jnp.asarray(edge_up),
            jnp.asarray(node_ov),
            self.planes,
            self.in_start,
            self.rev_eid,
            n_sweeps_base=r.hint,
            n_sweeps_masked=r.hint_masked,
            depth=r.depth,
            resid_rounds=r.resid_rounds,
            small_dist=small,
            max_hops=max_hops,
            k_in=self.k_in,
            chord_mode=r.chord_mode,
        )

    def _host_masks(self, res: list[Ksp2PlaneResult], d: int) -> list:
        """[D, E_cap] numpy exclusion masks rebuilt from each plane's
        traced edges (for warming hint_masked through forward())."""
        e_cap = self.runner.arrays[0].shape[0]
        masks = []
        for r in res:
            excl = np.asarray(r.excl)
            mask = np.ones((d, e_cap), dtype=bool)
            for i in range(d):
                ee = excl[i]
                ee = ee[ee < self.n_edges]
                mask[i, ee] = False
                rv = self.rev_eid_np[ee]
                mask[i, rv[rv >= 0]] = False
            masks.append(mask)
        return masks

    def run(
        self,
        src: int,
        dest_ids: np.ndarray,
        max_hops: int | None = None,
        adaptive: bool = True,
    ) -> list[Ksp2PlaneResult]:
        """One fused dispatch over all planes.  With `adaptive`, sweep
        hints are learned through the runner's OWN forward() machinery
        (double / uint16-saturation fallback / capped refine-down —
        SpfRunner.adapt), never by hand-doubling here: a hand-rolled
        doubling loop once inflated hint_masked for every later masked
        consumer of the shared runner (banded.py SpfRunner notes).
        Warmup costs a few extra dispatches; steady state is one."""
        r = self.runner
        if max_hops is None:
            max_hops = self.learned_max_hops
        src_np = np.asarray([src], dtype=np.int32)
        dest_np = np.asarray(dest_ids, dtype=np.int32)
        src_a = jnp.asarray(src_np)
        dest_a = jnp.asarray(dest_np)
        if adaptive:
            # learn the base hint per plane (adaptive, refined)
            for m in self.planes_np:
                r.forward(src_np, want_dag=False, metric_plane=m)
        res = self._fused_call(src_a, dest_a, max_hops)
        if not adaptive:
            return res
        n_nodes = int(self.in_start.shape[0]) - 1
        while all(bool(x.ok_base) for x in res) and not all(
            bool(x.trace_ok) for x in res
        ):
            # converged base but walkers didn't reach the source: the
            # hop bound is too small for this topology — grow it (a
            # shortest path has < N hops, so the retry terminates)
            if max_hops >= n_nodes:
                raise RuntimeError(
                    f"path trace did not terminate in {max_hops} hops"
                )
            max_hops = min(max_hops * 4, n_nodes)
            self.learned_max_hops = max_hops
            res = self._fused_call(src_a, dest_a, max_hops)
        if not all(bool(x.ok_masked) for x in res):
            # learn the masked hint on the REAL exclusion masks via
            # forward() (same adapt machinery), then redo the fused call
            srcs = np.full(len(dest_np), src, dtype=np.int32)
            for p, mask in enumerate(self._host_masks(res, len(dest_np))):
                r.forward(
                    srcs,
                    extra_edge_mask=mask,
                    want_dag=False,
                    metric_plane=self.planes_np[p],
                )
            res = self._fused_call(src_a, dest_a, max_hops)
        for x in res:
            if not (
                bool(x.ok_base) and bool(x.ok_masked) and bool(x.trace_ok)
            ):
                raise RuntimeError("fused KSP2 warmup did not converge")
        return res
