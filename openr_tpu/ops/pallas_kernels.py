"""Hand-tiled Pallas kernels for the saturating min-plus inner loops.

Every dispatch rung — fused full product, delta frontier relax, blocked
outer phase — bottoms out in the same saturating integer min-plus
contraction that XLA compiles generically.  This module hand-tiles the
two hottest bodies (PAPER.md names Pallas as the compute substrate; the
blocked-outer tiling follows the 3-D tensor Floyd-Warshall formulation
of arxiv 2310.03983, PAPERS.md):

1. `fused_epilogue_pallas` — the fused verify+bitmap epilogue of
   `ops.allsources._fused_progressive_banded`.  The lax body walks the
   relax groups (residual gathers + band rolls) re-reading the [N, P]
   product once per group output; the kernel instead holds one
   [N, 128] column tile of the product in VMEM and, per tile, unrolls
   ALL groups — min-plus candidate, ECMP-bitmap hit test, and
   fixed-point min — so the product crosses HBM once per output, not
   once per group.  Every group is normalized to one uniform row
   quadruple (gather index, weight, overloaded-predecessor, forward
   out-slot): a residual slot k contributes `bg.resid_nbr[:, k]`, a
   band of offset c contributes the roll written as the gather
   `(v - c) mod N`, which makes the band and residual relaxes the SAME
   kernel statement.

2. `blocked_outer_pallas` — phase 3 of the blocked APSP rung
   (`parallel.blocked.blocked_outer`): the rank-B outer update
   `d[i, j] = min(d[i, j], min_m(col[i, m] + row[m, j]))` over
   [tile_i, tile_j] VMEM blocks with the col/row panels streamed in per
   grid row/column.  The drain mask is folded into the row panel before
   the call (`row[m, :] = INF` where lane m is overloaded) — bit-exact
   because `min(c + INF, INF) == INF` in the saturating uint32 domain
   (operands <= 2^30, the add never wraps).

Fallback contract (same as the blocked rung): these kernels are an
OPTIONAL acceleration, never a dependency.  `run_with_fallback` demotes
to the caller-supplied XLA thunk on ANY Pallas unavailability, shape or
tile mismatch (the conformance gates below raise ValueError at trace
time, before any buffer is donated), or injected chaos fault, with
`device.engine.pallas_fallbacks` accounted; `OPENR_PALLAS=0` skips the
attempt entirely (`device.engine.pallas_skips`).  Tier-1 proves
bit-exactness against the lax kernels with `interpret=True` on CPU;
compiled mode engages only on a real TPU backend.

Bit-exactness argument, epilogue: padding rows/columns carry the INF
sentinel and padded group rows carry wbig weights, so padded candidates
are exactly INF — they set no bits (the `d < inf` guard is False) and
leave the fixed-point min at d, hence the verdict reduction over the
padded block equals the reduction over the live region.  The kernel
evaluates the identical where-expression as `_RelaxOps.resid_cand` /
`band0_cand` (weights pass through int32 exactly; wdt -> int32 -> wdt
round-trips are lossless for clamped metrics), and integer min is
exact and order-free, so bitmap and verdict match the lax epilogue
bit for bit.
"""

from __future__ import annotations

import functools
import logging
import os

import jax
import jax.numpy as jnp
from jax import lax

from .sssp import INF16, INF32

try:  # pallas is part of jax, but keep the no-hard-dependency contract
    from jax.experimental import pallas as pl

    _PALLAS_IMPORT_ERROR: Exception | None = None
except Exception as _exc:  # pragma: no cover - import guard
    pl = None  # type: ignore[assignment]
    _PALLAS_IMPORT_ERROR = _exc

log = logging.getLogger(__name__)

# saturation constants as plain ints (kernel closures; values mirror
# ops.sssp INF16/WBIG16 and ops.banded WBIG / parallel.blocked INF32)
_INF16 = int(INF16)  # 40000
_WBIG16 = 20000  # ops.sssp.WBIG16
_INF32 = int(INF32)  # 1 << 30
_WBIG32 = 1 << 28  # ops.banded.WBIG

# per-instance VMEM we are willing to ask Mosaic for before demoting;
# real TPUs have ~16 MiB and the compiler needs headroom
_VMEM_BUDGET = 12 * 1024 * 1024


# -- policy -------------------------------------------------------------------


def pallas_mode(env: str | None = None) -> str:
    """Resolve the OPENR_PALLAS knob to "off" | "interpret" | "compiled".

    Default (unset / "auto"): compiled on a TPU backend, off elsewhere —
    the interpreter is a correctness tool, not a fast path, so it never
    engages implicitly.  "1"/"on" forces the kernels on (compiled on
    TPU, interpreter elsewhere); "0"/"off" forces them off;
    "interpret"/"compiled" pin the execution mode explicitly (tests and
    the program auditor use "interpret" on CPU)."""
    if pl is None:
        return "off"
    v = (env if env is not None else os.environ.get("OPENR_PALLAS", "")) or ""
    v = v.strip().lower()
    if v in ("0", "off"):
        return "off"
    if v == "interpret":
        return "interpret"
    if v == "compiled":
        return "compiled"
    on_tpu = jax.default_backend() == "tpu"
    if v in ("1", "on"):
        return "compiled" if on_tpu else "interpret"
    if v not in ("", "auto"):
        log.warning("OPENR_PALLAS=%r not understood; treating as auto", v)
    return "compiled" if on_tpu else "off"


def run_with_fallback(
    kind: str,
    pallas_thunk,
    xla_thunk,
    *,
    counters=None,
    fault_hook=None,
    mode: str | None = None,
):
    """Run `pallas_thunk(interpret: bool)` under the graceful-demotion
    contract, or `xla_thunk()` when Pallas is off or fails.

    `kind` is "product" (fused epilogue) or "outer" (blocked rank-B
    update) and selects the success counter.  `counters`/`fault_hook`
    are the owning engine's seams (`DeviceResidencyEngine.run_pallas`
    binds them); engine-less callers get policy-only behavior with no
    accounting.  `mode` overrides the env policy (tests and the program
    auditor pass "interpret" instead of mutating the environment).

    The chaos gate fires INSIDE the try block — an armed
    `engine:pallas` fault demotes through the exact path a real Pallas
    failure takes, fallbacks counter included."""
    eff = mode if mode is not None else pallas_mode()
    if eff == "off":
        if counters is not None:
            counters["device.engine.pallas_skips"] = (
                counters.get("device.engine.pallas_skips", 0) + 1
            )
        return xla_thunk()
    try:
        if fault_hook is not None:
            fault_hook("pallas")
        out = pallas_thunk(eff == "interpret")
    except Exception:
        if counters is not None:
            counters["device.engine.pallas_fallbacks"] = (
                counters.get("device.engine.pallas_fallbacks", 0) + 1
            )
        log.warning(
            "pallas %s kernel demoted to the XLA path", kind, exc_info=True
        )
        return xla_thunk()
    if counters is not None:
        if kind == "product":
            counters["device.engine.pallas_products"] = (
                counters.get("device.engine.pallas_products", 0) + 1
            )
        else:
            counters["device.engine.pallas_outer_updates"] = (
                counters.get("device.engine.pallas_outer_updates", 0) + 1
            )
    return out


def _require_pallas() -> None:
    if pl is None:  # pragma: no cover - exercised only without pallas
        raise RuntimeError(
            f"jax.experimental.pallas unavailable: {_PALLAS_IMPORT_ERROR!r}"
        )


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


# -- kernel 1: fused verify+bitmap epilogue -----------------------------------


def _epilogue_kernel(
    idx_ref,
    w_ref,
    ov_ref,
    slot_ref,
    d_ref,
    bitmap_ref,
    vmin_ref,
    *,
    n_groups: int,
    n_words: int,
    inf: int,
    wbig: int,
):
    """One [Np, 128] product tile: unroll every relax group over the
    resident tile — candidate, bitmap hit, fixed-point min — in VMEM."""
    d = d_ref[...]  # [Np, TP] ddt
    inf_c = jnp.asarray(inf, d.dtype)
    fin = d < inf_c
    vmin = d
    words = [jnp.zeros(d.shape, jnp.uint32) for _ in range(n_words)]
    for g in range(n_groups):
        idxg = idx_ref[g, :]  # [Np] int32 — gather row per node
        wg = w_ref[g, :]  # [Np] int32 — clamped weight (wbig = unusable)
        ovg = ov_ref[g, :]  # [Np] int32 0/1 — predecessor overloaded
        sg = slot_ref[g, :]  # [Np] int32 — forward out-slot (-1 = none)
        du = jnp.take(d, idxg, axis=0)  # [Np, TP]
        allow = (wg < wbig)[:, None] & ((ovg == 0)[:, None] | (du == 0))
        cand = jnp.where(
            allow & (du < inf_c), du + wg.astype(d.dtype)[:, None], inf_c
        )
        on = fin & (cand == d)
        bit = jnp.where(
            sg >= 0,
            jnp.uint32(1) << (jnp.maximum(sg, 0) % 32).astype(jnp.uint32),
            jnp.uint32(0),
        )
        if n_words == 1:
            words[0] = words[0] | jnp.where(on, bit[:, None], jnp.uint32(0))
        else:
            wsel = jnp.maximum(sg, 0) // 32
            for wi in range(n_words):
                words[wi] = words[wi] | jnp.where(
                    on & (wsel == wi)[:, None], bit[:, None], jnp.uint32(0)
                )
        vmin = jnp.minimum(vmin, cand)
    bitmap_ref[...] = jnp.stack(words, axis=0)
    vmin_ref[...] = vmin


@functools.partial(
    jax.jit, static_argnames=("n_groups", "n_words", "interpret")
)
def fused_epilogue_pallas(
    d,  # [Np, Pp] ddt — product, padded to (mult 128, mult 128) with INF
    idx,  # [Gp, Np] int32 — gather row; pad rows/cols are neutral (0)
    w,  # [Gp, Np] int32 — clamped weight; pad = wbig (masks the edge)
    ov,  # [Gp, Np] int32 — 0/1 predecessor-overloaded; pad 0
    slot,  # [Gp, Np] int32 — forward out-slot bit position; pad -1
    *,
    n_groups: int,
    n_words: int,
    interpret: bool,
):
    """Pallas launch for the fused epilogue: grid over 128-wide product
    column tiles, group tables resident per instance.  Returns
    (bitmap [W, Np, Pp] uint32, vmin [Np, Pp] ddt); the caller slices
    off the padding and reduces `all(vmin == d)` for the verdict."""
    _require_pallas()
    np_pad, pp = d.shape
    gp = idx.shape[0]
    small = d.dtype == jnp.uint16
    inf = _INF16 if small else _INF32
    wbig = _WBIG16 if small else _WBIG32
    tp = 128
    if not interpret:
        # per-instance VMEM: d tile + vmin tile + bitmap words + tables
        vmem = (
            np_pad * tp * (2 * d.dtype.itemsize + n_words * 4)
            + 4 * gp * np_pad * 4
        )
        if vmem > _VMEM_BUDGET:
            raise ValueError(
                f"pallas epilogue: {vmem} B VMEM per instance exceeds the "
                f"{_VMEM_BUDGET} B budget (N_pad={np_pad}, groups={gp}, "
                f"words={n_words}) — demote to the XLA epilogue"
            )
    kernel = functools.partial(
        _epilogue_kernel,
        n_groups=n_groups,
        n_words=n_words,
        inf=inf,
        wbig=wbig,
    )
    tab = pl.BlockSpec((gp, np_pad), lambda j: (0, 0))
    bitmap, vmin = pl.pallas_call(
        kernel,
        grid=(pp // tp,),
        in_specs=[
            tab,  # idx
            tab,  # w
            tab,  # ov
            tab,  # slot
            pl.BlockSpec((np_pad, tp), lambda j: (0, j)),  # d
        ],
        out_specs=[
            pl.BlockSpec((n_words, np_pad, tp), lambda j: (0, 0, j)),
            pl.BlockSpec((np_pad, tp), lambda j: (0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_words, np_pad, pp), jnp.uint32),
            jax.ShapeDtypeStruct((np_pad, pp), d.dtype),
        ],
        interpret=interpret,
    )(idx, w, ov, slot, d)
    return bitmap, vmin


def _pad2(a, rows: int, cols: int, fill: int):
    return jnp.pad(
        a,
        ((0, rows - a.shape[0]), (0, cols - a.shape[1])),
        constant_values=fill,
    )


def fused_epilogue(ops, bg, d, resid_slot, band_slot, n_words, *, interpret):
    """Traced front half of kernel 1 (called INSIDE the
    `_fused_progressive_banded` jit when its `pallas` static is set):
    normalize every relax group to the uniform (idx, w, ov, slot) row
    form, pad to Mosaic-conformant tiles, launch, and strip the padding.
    Returns (bitmap [N, P, W] uint32, converged bool) matching the lax
    epilogue exactly (the small-dist saturation verdict stays with the
    caller, as in the lax path)."""
    if getattr(ops, "resid_excl", None) is not None:
        # per-row exclusion masks belong to the masked what-if variants,
        # which never reach this epilogue; refuse rather than mis-fuse
        raise ValueError("pallas epilogue does not support row exclusions")
    n, p = d.shape
    idx_rows, w_rows, ov_rows, slot_rows = [], [], [], []
    for k in range(ops.n_resid):
        idx_rows.append(bg.resid_nbr[:, k])
        w_rows.append(ops.rw[:, k].astype(jnp.int32))
        ov_rows.append(ops.rov[:, k].astype(jnp.int32))
        slot_rows.append(resid_slot[:, k])
    ids = jnp.arange(n, dtype=jnp.int32)
    for b, c in enumerate(bg.offsets):
        w0, ovb, _ = ops.band_tabs[b]
        # roll(d, c)[v] == d[(v - c) mod N]: the band relax as a gather
        idx_rows.append(jnp.remainder(ids - jnp.int32(c), jnp.int32(n)))
        w_rows.append(w0[:, 0].astype(jnp.int32))
        ov_rows.append(ovb[:, 0].astype(jnp.int32))
        slot_rows.append(band_slot[b])
    g = len(idx_rows)
    small = d.dtype == jnp.uint16
    inf = _INF16 if small else _INF32
    wbig = _WBIG16 if small else _WBIG32
    gp = _round_up(g, 8)  # int32 sublane tile
    np_pad = _round_up(n, 128)  # lane tile for the [Gp, Np] tables AND
    #   sublane multiple for both distance dtypes
    pp = _round_up(p, 128)
    idx = _pad2(jnp.stack(idx_rows), gp, np_pad, 0)
    w = _pad2(jnp.stack(w_rows), gp, np_pad, wbig)
    ovt = _pad2(jnp.stack(ov_rows), gp, np_pad, 0)
    slot = _pad2(jnp.stack(slot_rows), gp, np_pad, -1)
    dpad = jnp.pad(
        d, ((0, np_pad - n), (0, pp - p)), constant_values=inf
    )
    bitmap, vmin = fused_epilogue_pallas(
        dpad,
        idx,
        w,
        ovt,
        slot,
        n_groups=g,
        n_words=n_words,
        interpret=interpret,
    )
    # padded candidates are exactly INF == dpad there, so the verdict
    # over the padded block equals the verdict over the live region
    return (
        bitmap[:, :n, :p].transpose(1, 2, 0),
        jnp.all(vmin == dpad),
    )


# -- kernel 2: blocked rank-B outer update ------------------------------------


def _outer_kernel(d_ref, c_ref, r_ref, ov_ref, o_ref, *, b: int):
    """One [ti, tj] distance tile: rank-B saturating min-plus update
    from the resident [ti, B] col / [B, tj] row panel blocks.  The
    drain mask lands HERE, in the kernel prologue — row m of the row
    panel block lifts to INF when lane m of tile k is overloaded — so
    the launch consumes the raw panels the moment they land (the
    pipelined round hands them straight off the prefetch) instead of
    waiting on a masked staging copy."""
    d = d_ref[0]
    c = c_ref[0]
    infu = jnp.uint32(_INF32)
    ov = ov_ref[0]  # [B] int32 drain lanes of tile k
    r = jnp.where(ov[:, None] != 0, infu, r_ref[0])

    def body(m, acc):
        cm = lax.dynamic_slice_in_dim(c, m, 1, axis=1)  # [ti, 1]
        rm = lax.dynamic_slice_in_dim(r, m, 1, axis=0)  # [1, tj]
        return jnp.minimum(acc, jnp.minimum(cm + rm, infu))

    o_ref[0] = lax.fori_loop(0, b, body, d)


@functools.partial(
    jax.jit, static_argnames=("interpret",), donate_argnums=(0,)
)
def blocked_outer_pallas(
    dist, row_p, col_p, node_overloaded, k, *, interpret: bool
):
    """Pallas phase 3 of the blocked APSP round
    (`parallel.blocked.blocked_outer`, single-device meshes only): panel
    write-back in XLA, then the rank-B outer update as a tiled kernel
    over the [Np, Np] view of the tile tensor.

    The drain mask folds into the kernel PROLOGUE (`_outer_kernel`
    lifts row m of the row-panel block to INF where lane m of tile k
    is overloaded): bit-exact against the per-m `where(ov_m, INF,
    cand)` of the XLA kernel because `min(c + INF, INF) == INF` and
    uint32 never wraps for operands <= 2^30.  Integer min is exact and
    order-free, so the m-loop accumulation matches XLA's bit for bit.
    Keeping the mask out of the host-side prep means no staging copy
    of the panels sits between the (possibly prefetched) panel landing
    and the launch.

    Donation note: `dist` is donated (matching `blocked_outer`).  Every
    demotion trigger — conformance gates below, Mosaic lowering errors,
    the armed chaos fault (fired before this call) — raises at or
    before trace time, so the fallback re-runs on an intact buffer."""
    _require_pallas()
    s, t, b = dist.shape[0], dist.shape[1], dist.shape[2]
    np_ = t * b
    dist = lax.dynamic_update_index_in_dim(dist, row_p, k, axis=1)
    dist = lax.dynamic_update_index_in_dim(dist, col_p, k, axis=3)
    ov = lax.dynamic_slice_in_dim(node_overloaded, k * b, b)  # [B] bool
    rm = row_p.reshape(s, b, np_)
    cm = col_p.reshape(s, np_, b)
    # [8, B] int32 mask table (8 sublanes for Mosaic conformance; the
    # kernel reads row 0)
    ovt = jnp.zeros((8, b), jnp.int32).at[0].set(ov.astype(jnp.int32))
    d2 = dist.reshape(s, np_, np_)  # tile dims are contiguous: free view
    ti = 128 if np_ % 128 == 0 else b
    if not interpret and (ti % 128 or b % 128):
        # Mosaic tile conformance: the [ti, tj] / [ti, B] / [B, tj]
        # blocks need 128-multiple lanes (and 8-multiple sublanes, which
        # 128 covers); anything smaller demotes rather than mis-tiles
        raise ValueError(
            f"pallas blocked outer: tiles (ti={ti}, B={b}) are not "
            f"Mosaic-conformant (need multiples of 128) — demote to XLA"
        )
    if not interpret and 4 * (2 * ti * ti + 2 * ti * b + 8 * b) > _VMEM_BUDGET:
        raise ValueError(
            f"pallas blocked outer: tile ti={ti}, B={b} exceeds the "
            f"{_VMEM_BUDGET} B VMEM budget — demote to XLA"
        )
    out = pl.pallas_call(
        functools.partial(_outer_kernel, b=b),
        grid=(s, np_ // ti, np_ // ti),
        in_specs=[
            pl.BlockSpec((1, ti, ti), lambda si, i, j: (si, i, j)),
            pl.BlockSpec((1, ti, b), lambda si, i, j: (si, i, 0)),
            pl.BlockSpec((1, b, ti), lambda si, i, j: (si, 0, j)),
            pl.BlockSpec((8, b), lambda si, i, j: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, ti, ti), lambda si, i, j: (si, i, j)),
        out_shape=jax.ShapeDtypeStruct((s, np_, np_), jnp.uint32),
        input_output_aliases={0: 0},
        interpret=interpret,
    )(d2, cm, rm, ovt)
    return out.reshape(s, t, b, t, b)
