"""openr_tpu — a TPU-native link-state routing framework.

A from-scratch rebuild of the capabilities of Open/R (Meta's interior routing
platform, reference: /root/reference) designed TPU-first:

- The route-computation core (reference: openr/decision/) is a batched JAX/XLA
  compute engine: all-sources SPF as a vmapped frontier-relaxation SSSP kernel
  over a device-resident CSR topology tensor, with jitted ECMP/KSP next-hop
  extraction (openr_tpu.ops).
- The surrounding distributed machinery — neighbor discovery (spark), link
  monitoring, the replicated CRDT key-value store (kvstore), route origination
  (prefix_manager), FIB programming (fib), control API (ctrl) and operator CLI
  (cli) — is functionally equivalent to the reference but rebuilt on an
  asyncio-per-thread module runtime (openr_tpu.runtime) mirroring the
  reference's OpenrEventBase/queue architecture (openr/common/OpenrEventBase.h,
  openr/messaging/).
- Multi-chip scale-out (openr_tpu.parallel) shards the SSSP source batch and
  edge set over a jax.sharding.Mesh, replacing the reference's per-node
  replicated computation with sharded computation over ICI.
"""

__version__ = "0.1.0"
