"""Canonical byte serialization for wire types.

The reference serializes thrift structs to binary for KvStore values
(openr/kvstore/KvStore.cpp mergeKeyValues compares raw value bytes as a CRDT
tie-break).  We need the same property — a deterministic, byte-stable encoding
— so two stores serializing the same logical object always produce identical
bytes.  Canonical JSON (sorted keys, no whitespace, explicit defaults) gives
us that plus debuggability.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import typing
from typing import Any, Type, TypeVar

from . import types as T

T_ = TypeVar("T_")


def _to_jsonable(obj: Any) -> Any:
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, bytes):
        return {"__bytes__": obj.hex()}
    if isinstance(obj, enum.Enum):
        return int(obj.value)
    if dataclasses.is_dataclass(obj):
        return {
            f.name: _to_jsonable(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        }
    if isinstance(obj, dict):
        return {str(k): _to_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_to_jsonable(v) for v in obj]
    if isinstance(obj, (set, frozenset)):
        return sorted((_to_jsonable(v) for v in obj), key=repr)
    raise TypeError(f"cannot serialize {type(obj)!r}")


def _key_from_str(cls: Any, key: str) -> Any:
    """Reverse the str() applied to dict keys on encode (JSON object keys
    are always strings; dict[int, ...] fields must round-trip)."""
    if cls is int:
        return int(key)
    if isinstance(cls, type) and issubclass(cls, enum.Enum):
        return cls(int(key))
    return key


def _from_jsonable(cls: Any, data: Any) -> Any:
    if data is None:
        return None
    if isinstance(data, dict) and "__bytes__" in data:
        return bytes.fromhex(data["__bytes__"])
    # an annotation like dict[str, "X"] keeps the INNER forward reference
    # as a plain string even through typing.get_type_hints (the outer
    # eval treats it as a str literal): resolve by registry name or the
    # value silently stays a dict
    if isinstance(cls, str):
        cls = _TYPE_REGISTRY.get(cls, Any)
    elif isinstance(cls, typing.ForwardRef):
        cls = _TYPE_REGISTRY.get(cls.__forward_arg__, Any)
    # typing.get_origin/get_args normalize both typing.Optional/Union and
    # PEP-604 `X | None` unions (which carry no __origin__ themselves)
    origin = typing.get_origin(cls)
    if origin is not None:
        args = typing.get_args(cls)
        if origin is dict:
            return {
                _key_from_str(args[0], k): _from_jsonable(args[1], v)
                for k, v in data.items()
            }
        if origin is list:
            return [_from_jsonable(args[0], v) for v in data]
        if origin is tuple:
            elem = args[0] if args else Any
            return tuple(_from_jsonable(elem, v) for v in data)
        if origin in (set, frozenset):
            elem = args[0] if args else Any
            return origin(_from_jsonable(elem, v) for v in data)
        # Optional[X] / unions (either spelling): try each member
        for arg in args:
            if arg is type(None):
                continue
            try:
                return _from_jsonable(arg, data)
            except (TypeError, ValueError, KeyError):
                continue
        return data
    if isinstance(cls, type) and issubclass(cls, enum.Enum):
        return cls(data)
    if dataclasses.is_dataclass(cls):
        hints = _type_hints(cls)
        kwargs = {}
        for f in dataclasses.fields(cls):
            if f.name in data:
                kwargs[f.name] = _from_jsonable(hints[f.name], data[f.name])
        return cls(**kwargs)
    return data


_HINTS_CACHE: dict[type, dict[str, Any]] = {}


def _type_hints(cls: type) -> dict[str, Any]:
    """Memoized typing.get_type_hints: with `from __future__ import
    annotations` every hint is a STRING that get_type_hints re-parses
    with compile() per call — measured as 80% of publication-parse time
    on a 1k-node cold start before caching (the hot path deserializes
    thousands of nested dataclasses per KvStore publication)."""
    hints = _HINTS_CACHE.get(cls)
    if hints is None:
        hints = _HINTS_CACHE[cls] = typing.get_type_hints(cls)
    return hints


def dumps(obj: Any) -> bytes:
    """Serialize a wire-type dataclass to canonical bytes."""
    payload = {"__type__": type(obj).__name__, "d": _to_jsonable(obj)}
    return json.dumps(payload, sort_keys=True, separators=(",", ":")).encode()


_TYPE_REGISTRY: dict[str, type] = {
    name: getattr(T, name)
    for name in dir(T)
    if dataclasses.is_dataclass(getattr(T, name, None))
}


def loads(data: bytes, expected: Type[T_] | None = None) -> T_:
    payload = json.loads(data.decode())
    cls = _TYPE_REGISTRY[payload["__type__"]]
    if expected is not None and cls is not expected:
        raise TypeError(f"expected {expected.__name__}, got {payload['__type__']}")
    return _from_jsonable(cls, payload["d"])


def register_type(cls: type) -> type:
    """Register an out-of-module dataclass for wire (de)serialization.
    Usable as a decorator."""
    _TYPE_REGISTRY[cls.__name__] = cls
    return cls


# -- generic RPC value encoding (ctrl server wire format) -------------------
#
# Unlike dumps/loads (single known dataclass), RPC params/results are
# arbitrary compositions: dataclasses are tagged {"!t": TypeName, "!d": ...}
# so the receiver can reconstruct them without schema context.


_SENTINEL_KEYS = frozenset({"!t", "!d", "!m", "__bytes__"})


def to_wire(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {"!t": type(obj).__name__, "!d": _to_jsonable(obj)}
    if isinstance(obj, enum.Enum):
        return int(obj.value)
    if isinstance(obj, bytes):
        return {"__bytes__": obj.hex()}
    if isinstance(obj, dict):
        encoded = {str(k): to_wire(v) for k, v in obj.items()}
        if _SENTINEL_KEYS.intersection(encoded):
            # user data collides with encoding sentinels: wrap unambiguously
            return {"!m": encoded}
        return encoded
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [to_wire(v) for v in obj]
    return obj


def from_wire(data: Any) -> Any:
    if isinstance(data, dict):
        if "!t" in data:
            cls = _TYPE_REGISTRY[data["!t"]]
            return _from_jsonable(cls, data["!d"])
        if "!m" in data:
            return {k: from_wire(v) for k, v in data["!m"].items()}
        if "__bytes__" in data:
            return bytes.fromhex(data["__bytes__"])
        return {k: from_wire(v) for k, v in data.items()}
    if isinstance(data, list):
        return [from_wire(v) for v in data]
    return data
