"""LinkMonitor: the glue between the kernel, Spark, and KvStore.

Functional equivalent of the reference's LinkMonitor
(openr/link-monitor/LinkMonitor.{h,cpp}; doc
openr/docs/Protocol_Guide/LinkMonitor.md):

- consumes netlink link/addr events; maintains `InterfaceEntry` objects
  with exponential flap backoff before (re-)advertising an interface up
  (openr/link-monitor/InterfaceEntry.h);
- feeds the filtered interface database to Spark;
- converts Spark NeighborEvents into KvStore peer add/remove (PeerEvent)
  and `adj:<node>` advertisements via KvStoreClientInternal.persist_key;
- gates initial adjacency advertisement on the KvStore initial full-sync
  signal per peer (graceful-restart semantics, Main.cpp:474);
- holds drain state: node overload bit, per-link overloads, link/adj
  metric overrides — persisted as LinkMonitorState in the config store;
- optional RTT-derived adjacency metrics with NEIGHBOR_RTT_CHANGE updates.
"""

from __future__ import annotations

import logging
import re
from dataclasses import dataclass, field
from typing import Optional

from ..kvstore import KvStoreClientInternal
from ..runtime.eventbase import OpenrEventBase
from ..runtime.queue import QueueClosedError, ReplicateQueue, RQueue
from ..serializer import dumps
from ..types import (
    AddrEvent,
    Adjacency,
    AdjacencyDatabase,
    InterfaceDatabase,
    InterfaceInfo,
    KvStoreSyncEvent,
    LinkEvent,
    NeighborEvent,
    NeighborEventType,
    PeerEvent,
    PeerSpec,
    PerfEvents,
    PrefixEntry,
    PrefixType,
    PrefixUpdateRequest,
    adj_key,
)
from ..utils.backoff import ExponentialBackoff

log = logging.getLogger(__name__)

# reference: Constants::kInitialBackoff / kMaxBackoff for link flaps
LINK_FLAP_INITIAL_BACKOFF_S = 1.0
LINK_FLAP_MAX_BACKOFF_S = 60.0


AdjKey = tuple[str, str]  # (ifName, neighborNodeName)


@dataclass(slots=True)
class LinkMonitorState:
    """Persisted drain/override state (reference:
    thrift::LinkMonitorState, openr/if/Types.thrift:1148)."""

    is_overloaded: bool = False
    overloaded_links: set[str] = field(default_factory=set)
    link_metric_overrides: dict[str, int] = field(default_factory=dict)
    node_label: int = 0
    adj_metric_overrides: dict[AdjKey, int] = field(default_factory=dict)
    # soft-drain: added to every advertised adjacency metric
    # (nodeMetricIncrementVal) — steers traffic away without the hard
    # is_overloaded transit cutoff
    node_metric_increment_val: int = 0


CONFIG_KEY = "link-monitor-config"


class InterfaceEntry:
    """Interface with flap backoff (reference:
    openr/link-monitor/InterfaceEntry.h)."""

    __slots__ = ("if_name", "if_index", "is_up", "networks", "backoff", "_active_timer")

    def __init__(self, if_name: str, if_index: int = 0) -> None:
        self.if_name = if_name
        self.if_index = if_index
        self.is_up = False
        self.networks: set[str] = set()
        self.backoff = ExponentialBackoff(
            LINK_FLAP_INITIAL_BACKOFF_S, LINK_FLAP_MAX_BACKOFF_S
        )
        self._active_timer = None

    def update_status(self, is_up: bool) -> bool:
        """Returns True if the *advertised* state may have changed."""
        changed = self.is_up != is_up
        self.is_up = is_up
        if changed and not is_up:
            self.backoff.report_error()  # flap: penalize next up
        return changed

    def is_active(self) -> bool:
        """Up AND out of backoff (reference: InterfaceEntry::isActive)."""
        return self.is_up and self.backoff.can_try_now()

    def backoff_remaining_s(self) -> float:
        return self.backoff.get_time_remaining_until_retry()


class Neighbor:
    __slots__ = (
        "node_name",
        "if_name",
        "remote_if_name",
        "area",
        "rtt_us",
        "addr_v6",
        "addr_v4",
        "ctrl_port",
        "initial_synced",
        "restarting",
    )

    def __init__(self, event: NeighborEvent) -> None:
        self.node_name = event.node_name
        self.if_name = event.if_name
        self.remote_if_name = event.remote_if_name
        self.area = event.area
        self.rtt_us = event.rtt_us
        self.addr_v6 = event.neighbor_addr_v6
        self.addr_v4 = event.neighbor_addr_v4
        self.ctrl_port = event.ctrl_port
        self.initial_synced = False
        self.restarting = False


class LinkMonitor(OpenrEventBase):
    def __init__(
        self,
        node_name: str,
        *,
        # producer queues
        interface_updates_queue: ReplicateQueue[InterfaceDatabase],
        peer_updates_queue: ReplicateQueue[PeerEvent],
        prefix_updates_queue: Optional[ReplicateQueue[PrefixUpdateRequest]] = None,
        # consumer queues
        neighbor_updates: RQueue[NeighborEvent],
        kvstore_sync_events: Optional[RQueue[KvStoreSyncEvent]] = None,
        netlink_events: Optional[RQueue[object]] = None,
        # collaborators
        kvstore_client: Optional[KvStoreClientInternal] = None,
        config_store: Optional[object] = None,  # PersistentStore duck-type
        # config
        areas: tuple[str, ...] = ("0",),
        node_label: int = 0,
        enable_rtt_metric: bool = False,
        enable_perf_measurement: bool = False,
        include_if_regexes: tuple[str, ...] = (".*",),
        exclude_if_regexes: tuple[str, ...] = (),
        redistribute_if_regexes: tuple[str, ...] = (),
        assume_drained: bool = False,
        override_drain_state: bool = False,
        adj_hold_time_s: float = 0.0,
    ) -> None:
        super().__init__(name=f"link-monitor-{node_name}")
        self.node_name = node_name
        self._interface_updates_queue = interface_updates_queue
        self._peer_updates_queue = peer_updates_queue
        self._prefix_updates_queue = prefix_updates_queue
        self._neighbor_updates = neighbor_updates
        self._kvstore_sync_events = kvstore_sync_events
        self._netlink_events = netlink_events
        self.kvstore_client = kvstore_client
        self.config_store = config_store
        self.areas = areas
        self.enable_rtt_metric = enable_rtt_metric
        self.enable_perf_measurement = enable_perf_measurement
        self._include_res = [re.compile(p) for p in include_if_regexes]
        self._exclude_res = [re.compile(p) for p in exclude_if_regexes]
        self._redist_res = [re.compile(p) for p in redistribute_if_regexes]
        self._adj_hold_time_s = adj_hold_time_s
        self._adj_hold_active = adj_hold_time_s > 0

        self.state = LinkMonitorState(node_label=node_label)
        self._load_state(assume_drained, override_drain_state)
        self.interfaces: dict[str, InterfaceEntry] = {}
        self._redist_advertised: set[str] = set()
        # (area, nodeName, ifName) -> Neighbor  (parallel links are distinct
        # adjacencies; the KvStore peer lives while ANY of them is up)
        self.neighbors: dict[tuple[str, str, str], Neighbor] = {}
        self.counters: dict[str, int] = {}

    # -- persistence ---------------------------------------------------------

    def _load_state(self, assume_drained: bool, override: bool) -> None:
        loaded = False
        if self.config_store is not None:
            raw = self.config_store.load(CONFIG_KEY)
            if raw is not None:
                try:
                    import json

                    d = json.loads(raw.decode())
                    # parse completely before applying: a corrupt blob must
                    # not leave partially-applied state
                    is_overloaded = bool(d["is_overloaded"])
                    overloaded_links = set(d["overloaded_links"])
                    link_metric_overrides = {
                        k: int(v) for k, v in d["link_metric_overrides"].items()
                    }
                    node_label = int(d.get("node_label", 0))
                    node_metric_increment = int(
                        d.get("node_metric_increment_val", 0)
                    )
                    adj_metric_overrides = {}
                    for k, v in d.get("adj_metric_overrides", {}).items():
                        if_name, _, node = k.partition("|")
                        if not node:
                            raise ValueError(f"bad adj key {k!r}")
                        adj_metric_overrides[(if_name, node)] = int(v)
                    self.state.is_overloaded = is_overloaded
                    self.state.overloaded_links = overloaded_links
                    self.state.link_metric_overrides = link_metric_overrides
                    self.state.node_label = node_label or self.state.node_label
                    self.state.node_metric_increment_val = node_metric_increment
                    self.state.adj_metric_overrides = adj_metric_overrides
                    loaded = True
                except Exception:
                    log.exception("link-monitor: corrupt persisted state")
        if not loaded and assume_drained:
            self.state.is_overloaded = True
        if override:
            self.state.is_overloaded = assume_drained

    def _save_state(self) -> None:
        if self.config_store is None:
            return
        import json

        self.config_store.store(
            CONFIG_KEY,
            json.dumps(
                {
                    "is_overloaded": self.state.is_overloaded,
                    "overloaded_links": sorted(self.state.overloaded_links),
                    "link_metric_overrides": self.state.link_metric_overrides,
                    "node_label": self.state.node_label,
                    "node_metric_increment_val": (
                        self.state.node_metric_increment_val
                    ),
                    "adj_metric_overrides": {
                        f"{k[0]}|{k[1]}": v
                        for k, v in self.state.adj_metric_overrides.items()
                    },
                }
            ).encode(),
        )

    # -- lifecycle -----------------------------------------------------------

    def run(self) -> None:
        super().run()
        self.wait_until_running()
        self.run_in_event_base_thread(self._setup).result()

    def _setup(self) -> None:
        self.add_fiber_task(self._neighbor_fiber(), name="neighborUpdates")
        if self._kvstore_sync_events is not None:
            self.add_fiber_task(self._sync_events_fiber(), name="kvSyncEvents")
        if self._netlink_events is not None:
            self.add_fiber_task(self._netlink_fiber(), name="netlinkEvents")
        if self._adj_hold_active:
            self.schedule_timeout(self._adj_hold_time_s, self._adj_hold_expired)

    def _adj_hold_expired(self) -> None:
        self._adj_hold_active = False
        self.advertise_adjacencies()

    def _bump(self, counter: str, n: int = 1) -> None:
        self.counters[counter] = self.counters.get(counter, 0) + n

    # -- fibers --------------------------------------------------------------

    async def _neighbor_fiber(self) -> None:
        while True:
            try:
                event = await self._neighbor_updates.aget()
            except QueueClosedError:
                return
            try:
                self._process_neighbor_event(event)
            except Exception:
                log.exception("link-monitor: neighbor event failed")

    async def _sync_events_fiber(self) -> None:
        while True:
            try:
                event = await self._kvstore_sync_events.aget()
            except QueueClosedError:
                return
            self._process_sync_event(event)

    async def _netlink_fiber(self) -> None:
        while True:
            try:
                event = await self._netlink_events.aget()
            except QueueClosedError:
                return
            try:
                if isinstance(event, LinkEvent):
                    self._process_link_event(event)
                elif isinstance(event, AddrEvent):
                    self._process_addr_event(event)
            except Exception:
                log.exception("link-monitor: netlink event failed")

    # -- interface tracking (reference: processNetlinkEvent) ------------------

    def _if_included(self, if_name: str) -> bool:
        if any(p.fullmatch(if_name) for p in self._exclude_res):
            return False
        return any(p.fullmatch(if_name) for p in self._include_res)

    def _process_link_event(self, event: LinkEvent) -> None:
        if not self._if_included(event.if_name):
            return
        entry = self.interfaces.get(event.if_name)
        if entry is None:
            entry = self.interfaces[event.if_name] = InterfaceEntry(
                event.if_name, event.if_index
            )
        entry.if_index = event.if_index
        self._bump("link_monitor.link_event")
        if entry.update_status(event.is_up):
            if entry.is_active():
                self.advertise_interfaces()
            else:
                # flap backoff: advertise DOWN now, delay UP advertisement
                self.advertise_interfaces()
                if entry.is_up:
                    self._schedule_backoff_refresh(entry)

    def _schedule_backoff_refresh(self, entry: InterfaceEntry) -> None:
        delay = entry.backoff_remaining_s()
        if delay > 0:
            self.schedule_timeout(
                delay + 0.001, lambda: self._backoff_expired(entry.if_name)
            )

    def _backoff_expired(self, if_name: str) -> None:
        entry = self.interfaces.get(if_name)
        if entry is None:
            return
        if entry.is_active():
            self.advertise_interfaces()
        elif entry.is_up:
            self._schedule_backoff_refresh(entry)

    def _process_addr_event(self, event: AddrEvent) -> None:
        if not self._if_included(event.if_name):
            return
        entry = self.interfaces.get(event.if_name)
        if entry is None:
            entry = self.interfaces[event.if_name] = InterfaceEntry(event.if_name)
        if event.is_valid:
            entry.networks.add(event.prefix)
        else:
            entry.networks.discard(event.prefix)
        self.advertise_interfaces()
        self._advertise_redist_prefixes()

    def advertise_interfaces(self) -> None:
        """Publish the interface DB to Spark (active interfaces only count
        as up)."""
        db = InterfaceDatabase(this_node_name=self.node_name)
        for name, entry in self.interfaces.items():
            db.interfaces[name] = InterfaceInfo(
                if_name=name,
                is_up=entry.is_active(),
                if_index=entry.if_index,
                networks=sorted(entry.networks),
            )
        self._interface_updates_queue.push(db)

    def _advertise_redist_prefixes(self) -> None:
        if self._prefix_updates_queue is None or not self._redist_res:
            return
        current = {
            net
            for name, entry in self.interfaces.items()
            if entry.is_active()
            and any(p.fullmatch(name) for p in self._redist_res)
            for net in entry.networks
        }
        to_del = sorted(self._redist_advertised - current)
        self._redist_advertised = current
        self._prefix_updates_queue.push(
            PrefixUpdateRequest(
                prefixes_to_add=[
                    PrefixEntry(prefix=net, type=PrefixType.LOOPBACK)
                    for net in sorted(current)
                ],
                prefixes_to_del=to_del,
                type=PrefixType.LOOPBACK,
            )
        )

    # -- neighbor tracking (reference: neighborUpEvent/neighborDownEvent) ----

    def _node_links(self, area: str, node: str) -> list[Neighbor]:
        return [
            n
            for (a, nn, _), n in self.neighbors.items()
            if a == area and nn == node
        ]

    def _process_neighbor_event(self, event: NeighborEvent) -> None:
        key = (event.area, event.node_name, event.if_name)
        etype = event.event_type
        if etype == NeighborEventType.NEIGHBOR_UP:
            self._bump("link_monitor.neighbor_up")
            self.neighbors[key] = Neighbor(event)
            self._peer_updates_queue.push(
                PeerEvent(
                    area=event.area,
                    peers_to_add={
                        event.node_name: PeerSpec(
                            peer_addr=event.neighbor_addr_v6 or event.node_name,
                            ctrl_port=event.ctrl_port,
                        )
                    },
                )
            )
            # adjacency advertised when this peer finishes initial sync
            if self._kvstore_sync_events is None:
                self.neighbors[key].initial_synced = True
                self.advertise_adjacencies(event.area)
            else:
                # parallel link to an already-synced peer: no new sync
                # event will come, inherit synced state
                synced = any(
                    n.initial_synced
                    for n in self._node_links(event.area, event.node_name)
                )
                if synced:
                    self.neighbors[key].initial_synced = True
                    self.advertise_adjacencies(event.area)
        elif etype == NeighborEventType.NEIGHBOR_DOWN:
            self._bump("link_monitor.neighbor_down")
            self.neighbors.pop(key, None)
            if not self._node_links(event.area, event.node_name):
                # last parallel link gone: drop the KvStore peering
                self._peer_updates_queue.push(
                    PeerEvent(area=event.area, peers_to_del=[event.node_name])
                )
            self.advertise_adjacencies(event.area)
        elif etype == NeighborEventType.NEIGHBOR_RESTARTING:
            self._bump("link_monitor.neighbor_restarting")
            neighbor = self.neighbors.get(key)
            if neighbor is not None:
                neighbor.restarting = True
        elif etype == NeighborEventType.NEIGHBOR_RESTARTED:
            self._bump("link_monitor.neighbor_restarted")
            neighbor = self.neighbors.get(key)
            if neighbor is not None:
                neighbor.restarting = False
            self.advertise_adjacencies(event.area)
        elif etype == NeighborEventType.NEIGHBOR_RTT_CHANGE:
            neighbor = self.neighbors.get(key)
            if neighbor is not None:
                neighbor.rtt_us = event.rtt_us
                if self.enable_rtt_metric:
                    self.advertise_adjacencies(event.area)

    def _process_sync_event(self, event: KvStoreSyncEvent) -> None:
        """Initial-sync signal gates first adjacency advertisement
        (reference: kvStoreSyncEventsQueue wiring, Main.cpp:474)."""
        changed = False
        for neighbor in self._node_links(event.area, event.node_name):
            if not neighbor.initial_synced:
                neighbor.initial_synced = True
                changed = True
        if changed:
            self.advertise_adjacencies(event.area)

    # -- adjacency advertisement ---------------------------------------------

    def _adjacency_metric(self, neighbor: Neighbor) -> int:
        """Reference: getRttMetric + overrides precedence (adj override >
        link override > computed)."""
        override = self.state.adj_metric_overrides.get(
            (neighbor.if_name, neighbor.node_name)
        )
        if override is not None:
            return override
        link_override = self.state.link_metric_overrides.get(neighbor.if_name)
        if link_override is not None:
            return link_override
        if self.enable_rtt_metric and neighbor.rtt_us > 0:
            return max(1, neighbor.rtt_us // 100)
        return 1

    def build_adjacency_database(self, area: str) -> AdjacencyDatabase:
        adjacencies = []
        for (narea, _, _), neighbor in sorted(self.neighbors.items()):
            if narea != area or not neighbor.initial_synced:
                continue
            adjacencies.append(
                Adjacency(
                    other_node_name=neighbor.node_name,
                    if_name=neighbor.if_name,
                    other_if_name=neighbor.remote_if_name,
                    metric=self._adjacency_metric(neighbor),
                    adj_label=0,
                    is_overloaded=neighbor.if_name in self.state.overloaded_links,
                    rtt_us=neighbor.rtt_us,
                    next_hop_v6=neighbor.addr_v6,
                    next_hop_v4=neighbor.addr_v4,
                )
            )
        db = AdjacencyDatabase(
            this_node_name=self.node_name,
            adjacencies=adjacencies,
            is_overloaded=self.state.is_overloaded,
            node_label=self.state.node_label,
            area=area,
            node_metric_increment_val=self.state.node_metric_increment_val,
        )
        if self.enable_perf_measurement:
            db.perf_events = PerfEvents()
            db.perf_events.add(self.node_name, "ADJ_DB_UPDATED")
        return db

    def advertise_adjacencies(self, area: Optional[str] = None) -> None:
        if self._adj_hold_active:
            return  # cold-start hold (reference: adj_hold_time_s)
        if self.kvstore_client is None:
            return
        for a in self.areas if area is None else (area,):
            db = self.build_adjacency_database(a)
            self.kvstore_client.persist_key(a, adj_key(self.node_name), dumps(db))
            self._bump("link_monitor.advertise_adjacencies")

    # -- drain / metric control API (reference: OpenrCtrlHandler :280-298) ---

    def _update_and_advertise(self, mutate) -> None:
        def _do() -> None:
            mutate()
            self._save_state()
            self.advertise_adjacencies()

        self.run_in_event_base_thread(_do).result()

    def set_node_overload(self, overloaded: bool) -> None:
        self._update_and_advertise(
            lambda: setattr(self.state, "is_overloaded", overloaded)
        )

    def set_link_overload(self, if_name: str, overloaded: bool) -> None:
        def _mutate() -> None:
            if overloaded:
                self.state.overloaded_links.add(if_name)
            else:
                self.state.overloaded_links.discard(if_name)

        self._update_and_advertise(_mutate)

    def set_link_metric(self, if_name: str, metric: Optional[int]) -> None:
        def _mutate() -> None:
            if metric is None:
                self.state.link_metric_overrides.pop(if_name, None)
            else:
                self.state.link_metric_overrides[if_name] = metric

        self._update_and_advertise(_mutate)

    def set_adj_metric(
        self, if_name: str, node_name: str, metric: Optional[int]
    ) -> None:
        def _mutate() -> None:
            key = (if_name, node_name)
            if metric is None:
                self.state.adj_metric_overrides.pop(key, None)
            else:
                self.state.adj_metric_overrides[key] = metric

        self._update_and_advertise(_mutate)

    def set_node_label(self, label: int) -> None:
        self._update_and_advertise(
            lambda: setattr(self.state, "node_label", label)
        )

    def set_node_metric_increment(self, increment: int) -> None:
        """Soft-drain: advertise every adjacency with `increment` added to
        its metric (reference: semi-/undrain-interface increments,
        OpenrCtrlHandler::semiDrainNode).  0 restores normal costs."""
        if increment < 0:
            raise ValueError(f"negative metric increment {increment}")
        self._update_and_advertise(
            lambda: setattr(self.state, "node_metric_increment_val", increment)
        )

    # -- introspection --------------------------------------------------------

    def get_interfaces(self) -> dict[str, InterfaceInfo]:
        def _get() -> dict[str, InterfaceInfo]:
            return {
                name: InterfaceInfo(
                    if_name=name,
                    is_up=e.is_active(),
                    if_index=e.if_index,
                    networks=sorted(e.networks),
                )
                for name, e in self.interfaces.items()
            }

        return self.run_in_event_base_thread(_get).result()

    def get_adjacencies(self, area: str = "0") -> AdjacencyDatabase:
        return self.run_in_event_base_thread(
            lambda: self.build_adjacency_database(area)
        ).result()

    def get_state(self) -> LinkMonitorState:
        return self.run_in_event_base_thread(
            lambda: LinkMonitorState(
                is_overloaded=self.state.is_overloaded,
                overloaded_links=set(self.state.overloaded_links),
                link_metric_overrides=dict(self.state.link_metric_overrides),
                node_label=self.state.node_label,
                adj_metric_overrides=dict(self.state.adj_metric_overrides),
                node_metric_increment_val=self.state.node_metric_increment_val,
            )
        ).result()
