"""LinkMonitor: interface tracking + adjacency advertisement.

Functional equivalent of the reference's LinkMonitor
(openr/link-monitor/LinkMonitor.h:95).
"""

from .link_monitor import AdjKey, InterfaceEntry, LinkMonitor, LinkMonitorState

__all__ = ["AdjKey", "InterfaceEntry", "LinkMonitor", "LinkMonitorState"]
