"""Fib: route programming pipeline to the platform agent."""

from .fib import Fib, FibAgent, MockFibAgent, RouteState, longest_prefix_match

__all__ = ["Fib", "FibAgent", "MockFibAgent", "RouteState", "longest_prefix_match"]
