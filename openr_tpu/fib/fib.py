"""Fib: consumes route deltas and programs the platform agent.

Functional equivalent of the reference's Fib (openr/fib/Fib.{h,cpp}):

- fiber over the Decision route-updates queue; incremental
  add/delete programming via the FibService agent client;
- full `sync_fib` on cold start, on any programming failure (debounced
  with exponential backoff), and on agent restart detected by
  `alive_since` keep-alive polling;
- `do_not_install` routes tracked but never programmed;
- perf: end-to-end ROUTE_CONVERGENCE duration computed from the
  perf-event trail riding each update; ring buffer for `get_perf_db`;
- re-publishes programmed updates on `fib_updates_queue` for ctrl-API
  streaming subscribers.

The agent seam (`FibAgent`) is the thrift FibService surface
(openr/if/Platform.thrift:71); `MockFibAgent` mirrors
openr/tests/mocks/MockNetlinkFibHandler.
"""

from __future__ import annotations

import ipaddress
import logging
import threading
import time
from collections import deque
from typing import Iterable, Optional, Protocol

from ..decision.rib import DecisionRouteUpdate, RibMplsEntry, RibUnicastEntry
from ..obs import trace as _trace
from ..runtime.eventbase import OpenrEventBase
from ..runtime.queue import QueueClosedError, ReplicateQueue, RQueue
from ..types import MplsRoute, PerfEvents, UnicastRoute, add_perf_event
from ..utils.backoff import ExponentialBackoff

log = logging.getLogger(__name__)

# reference: Constants::kFibInitialBackoff / kFibMaxBackoff
SYNC_INITIAL_BACKOFF_S = 0.008
SYNC_MAX_BACKOFF_S = 4.096
KEEPALIVE_INTERVAL_S = 1.0  # Constants::kKeepAliveCheckInterval
PERF_DB_SIZE = 10  # reference: kPerfBufferSize
FIB_CLIENT_OPENR = 786  # thrift::FibClient::OPENR (Platform.thrift:23)


class FibAgent(Protocol):
    """thrift FibService surface (openr/if/Platform.thrift:71-160)."""

    def add_unicast_routes(self, client_id: int, routes: list[UnicastRoute]) -> None: ...
    def delete_unicast_routes(self, client_id: int, prefixes: list[str]) -> None: ...
    def add_mpls_routes(self, client_id: int, routes: list[MplsRoute]) -> None: ...
    def delete_mpls_routes(self, client_id: int, labels: list[int]) -> None: ...
    def sync_fib(self, client_id: int, routes: list[UnicastRoute]) -> None: ...
    def sync_mpls_fib(self, client_id: int, routes: list[MplsRoute]) -> None: ...
    def get_route_table_by_client(self, client_id: int) -> list[UnicastRoute]: ...
    def get_mpls_route_table_by_client(self, client_id: int) -> list[MplsRoute]: ...
    def alive_since(self) -> int: ...


class MockFibAgent:
    """In-process fake agent counting programmed routes, with fault
    injection (reference: MockNetlinkFibHandler)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.unicast: dict[int, dict[str, UnicastRoute]] = {}
        self.mpls: dict[int, dict[int, MplsRoute]] = {}
        self._alive_since = int(time.time())
        self.fail = False  # raise on every call when set
        # seeded per-call failure/restart schedule (chaos.FibChaosPlan
        # duck type: on_call(op) -> "ok" | "fail" | "restart")
        self.chaos = None
        # Bare keys are the mock's public test surface (asserted as
        # agent.counters["sync_fib"] etc.); the daemon-side dump exports
        # them convention-clean as fib.agent.<key> via Fib.get_counters.
        self.counters = {
            "add_unicast": 0,
            "del_unicast": 0,
            "sync_fib": 0,
            "add_mpls": 0,
            "del_mpls": 0,
            "sync_mpls": 0,
        }

    def _check(self, op: str = "") -> None:
        if self.fail:
            raise RuntimeError("agent unavailable (injected)")
        plan = self.chaos
        if plan is not None:
            verdict = plan.on_call(op)
            if verdict == "restart":
                # spontaneous restart: tables wiped, aliveSince bumps, and
                # the in-flight call dies like a severed thrift channel
                self.restart()
                raise RuntimeError(f"agent restarted during {op} (injected)")
            if verdict == "fail":
                raise RuntimeError(f"injected agent failure on {op}")

    def restart(self) -> None:
        """Simulate agent restart: state wiped, aliveSince bumps."""
        with self._lock:
            self.unicast.clear()
            self.mpls.clear()
            self._alive_since = int(time.time() * 1000)  # strictly increases

    def add_unicast_routes(self, client_id: int, routes: list[UnicastRoute]) -> None:
        self._check("add_unicast_routes")
        with self._lock:
            table = self.unicast.setdefault(client_id, {})
            for route in routes:
                table[route.dest] = route
            self.counters["add_unicast"] += len(routes)  # openr: disable=counter-name

    def delete_unicast_routes(self, client_id: int, prefixes: list[str]) -> None:
        self._check("delete_unicast_routes")
        with self._lock:
            table = self.unicast.setdefault(client_id, {})
            for prefix in prefixes:
                table.pop(prefix, None)
            self.counters["del_unicast"] += len(prefixes)  # openr: disable=counter-name

    def add_mpls_routes(self, client_id: int, routes: list[MplsRoute]) -> None:
        self._check("add_mpls_routes")
        with self._lock:
            table = self.mpls.setdefault(client_id, {})
            for route in routes:
                table[route.top_label] = route
            self.counters["add_mpls"] += len(routes)  # openr: disable=counter-name

    def delete_mpls_routes(self, client_id: int, labels: list[int]) -> None:
        self._check("delete_mpls_routes")
        with self._lock:
            table = self.mpls.setdefault(client_id, {})
            for label in labels:
                table.pop(label, None)
            self.counters["del_mpls"] += len(labels)  # openr: disable=counter-name

    def sync_fib(self, client_id: int, routes: list[UnicastRoute]) -> None:
        self._check("sync_fib")
        with self._lock:
            self.unicast[client_id] = {r.dest: r for r in routes}
            self.counters["sync_fib"] += 1  # openr: disable=counter-name

    def sync_mpls_fib(self, client_id: int, routes: list[MplsRoute]) -> None:
        self._check("sync_mpls_fib")
        with self._lock:
            self.mpls[client_id] = {r.top_label: r for r in routes}
            self.counters["sync_mpls"] += 1  # openr: disable=counter-name

    def get_route_table_by_client(self, client_id: int) -> list[UnicastRoute]:
        with self._lock:
            return list(self.unicast.get(client_id, {}).values())

    def get_mpls_route_table_by_client(self, client_id: int) -> list[MplsRoute]:
        with self._lock:
            return list(self.mpls.get(client_id, {}).values())

    def alive_since(self) -> int:
        self._check("alive_since")
        with self._lock:
            return self._alive_since


def longest_prefix_match(addr: str, prefixes: Iterable[str]) -> Optional[str]:
    """Reference: Fib::longestPrefixMatch (openr/fib/Fib.h:80)."""
    ip = ipaddress.ip_address(addr)
    best: Optional[str] = None
    best_len = -1
    for prefix in prefixes:
        net = ipaddress.ip_network(prefix)
        if net.version == ip.version and ip in net and net.prefixlen > best_len:
            best = prefix
            best_len = net.prefixlen
    return best


class RouteState:
    """Reference: Fib::RouteState (openr/fib/Fib.h:191)."""

    __slots__ = ("unicast_routes", "mpls_routes", "dirty", "synced")

    def __init__(self) -> None:
        self.unicast_routes: dict[str, UnicastRoute] = {}
        self.mpls_routes: dict[int, MplsRoute] = {}
        self.dirty = False
        self.synced = False


class Fib(OpenrEventBase):
    def __init__(
        self,
        node_name: str,
        route_updates: RQueue[DecisionRouteUpdate],
        agent: FibAgent,
        *,
        fib_updates_queue: Optional[ReplicateQueue[DecisionRouteUpdate]] = None,
        log_sample_queue: Optional[ReplicateQueue] = None,
        client_id: int = FIB_CLIENT_OPENR,
        dryrun: bool = False,
        enable_segment_routing: bool = True,
        keepalive_interval_s: float = KEEPALIVE_INTERVAL_S,
        sync_initial_backoff_s: float = SYNC_INITIAL_BACKOFF_S,
        sync_max_backoff_s: float = SYNC_MAX_BACKOFF_S,
    ) -> None:
        super().__init__(name=f"fib-{node_name}")
        self.node_name = node_name
        self._route_updates = route_updates
        self.agent = agent
        self._fib_updates_queue = fib_updates_queue
        self._log_sample_queue = log_sample_queue
        self.client_id = client_id
        self.dryrun = dryrun
        self.enable_segment_routing = enable_segment_routing
        self._keepalive_interval_s = keepalive_interval_s
        # shared audited backoff (utils.backoff) instead of a hand-rolled
        # doubling — the KvStore peer FSM uses the same class
        self._sync_backoff = ExponentialBackoff(
            sync_initial_backoff_s, sync_max_backoff_s
        )

        self.route_state = RouteState()
        self._do_not_install: set[str] = set()
        self._latest_alive_since: Optional[int] = None
        self._sync_timer = None
        self.perf_db: deque[PerfEvents] = deque(maxlen=PERF_DB_SIZE)
        self.counters: dict[str, int] = {}

    def _bump(self, counter: str, n: int = 1) -> None:
        self.counters[counter] = self.counters.get(counter, 0) + n

    def get_counters(self) -> dict[str, int]:
        """Own counters plus the in-process agent's programming counters
        namespaced as fib.agent.<key>, so the ctrl dump covers the whole
        programming path even when the agent is the in-process mock."""
        out = dict(self.counters)
        agent_counters = getattr(self.agent, "counters", None)
        if isinstance(agent_counters, dict):
            for key, val in agent_counters.items():
                if isinstance(key, str) and isinstance(val, int):
                    out[f"fib.agent.{key}"] = val
        return out

    # -- lifecycle -----------------------------------------------------------

    def run(self) -> None:
        super().run()
        self.wait_until_running()
        self.run_in_event_base_thread(self._setup).result()

    def _setup(self) -> None:
        self.add_fiber_task(self._route_updates_fiber(), name="routeUpdates")
        # cold start: full sync establishes agent state ownership; first
        # keep-alive fires immediately so the aliveSince baseline predates
        # any restart we must detect
        self._schedule_sync(0.0)
        self.schedule_timeout(0.0, self._keepalive_tick)

    async def _route_updates_fiber(self) -> None:
        while True:
            try:
                update = await self._route_updates.aget()
            except QueueClosedError:
                return
            tr = _trace.TRACE
            carried = tr.take_carried() if tr is not None else ()
            if carried:
                # flap-path terminal: program the routes under a
                # "fib.program" stage on each carried span, then finish
                # every trace root (the publication entered the ring here)
                spans = [
                    tr.child_open(sp, "fib.program") for sp in carried
                ]
                try:
                    with tr.activate(spans):
                        try:
                            self.process_route_updates(update)
                        except Exception:
                            log.exception("fib: route update processing failed")
                finally:
                    for sp in spans:
                        sp.finish()
                    for sp in carried:
                        tr.finish_root(sp)
                continue
            try:
                self.process_route_updates(update)
            except Exception:
                log.exception("fib: route update processing failed")

    # -- route processing (reference: processRouteUpdates/updateRoutes) ------

    def process_route_updates(self, update: DecisionRouteUpdate) -> None:
        add_perf_event(update.perf_events, self.node_name, "FIB_ROUTE_DB_RECVD")
        # update local state; a route flipping TO do_not_install must be
        # withdrawn from the agent even though it stays in our state
        newly_uninstalled: list[str] = []
        for prefix in update.unicast_routes_to_delete:
            self.route_state.unicast_routes.pop(prefix, None)
            self._do_not_install.discard(prefix)
        for prefix, entry in update.unicast_routes_to_update.items():
            self.route_state.unicast_routes[prefix] = entry.to_unicast_route()
            if entry.do_not_install:
                if prefix not in self._do_not_install:
                    newly_uninstalled.append(prefix)
                self._do_not_install.add(prefix)
            else:
                self._do_not_install.discard(prefix)
        for label in update.mpls_routes_to_delete:
            self.route_state.mpls_routes.pop(label, None)
        for entry in update.mpls_routes_to_update:
            self.route_state.mpls_routes[entry.label] = entry.to_mpls_route()

        if not self.route_state.synced:
            # initial sync still pending: it will program everything
            self.route_state.dirty = True
            return
        self._update_routes(update, newly_uninstalled)

    def _update_routes(
        self,
        update: DecisionRouteUpdate,
        newly_uninstalled: Iterable[str] = (),
    ) -> None:
        """Incremental programming (reference: updateRoutes)."""
        add_perf_event(update.perf_events, self.node_name, "FIB_DEBOUNCE")
        try:
            if not self.dryrun:
                to_add = [
                    entry.to_unicast_route()
                    for prefix, entry in update.unicast_routes_to_update.items()
                    if prefix not in self._do_not_install
                ]
                if to_add:
                    self.agent.add_unicast_routes(self.client_id, to_add)
                to_del = list(update.unicast_routes_to_delete) + list(
                    newly_uninstalled
                )
                if to_del:
                    self.agent.delete_unicast_routes(self.client_id, to_del)
                if self.enable_segment_routing:
                    if update.mpls_routes_to_update:
                        self.agent.add_mpls_routes(
                            self.client_id,
                            [e.to_mpls_route() for e in update.mpls_routes_to_update],
                        )
                    if update.mpls_routes_to_delete:
                        self.agent.delete_mpls_routes(
                            self.client_id, list(update.mpls_routes_to_delete)
                        )
            self._bump("fib.num_of_route_updates")
            self._publish_and_log(update)
        except Exception:
            log.exception("fib: incremental programming failed; scheduling sync")
            self._bump("fib.thrift.failure.add_del_route")
            self.route_state.dirty = True
            self._schedule_sync_backoff()

    def _publish_and_log(self, update: DecisionRouteUpdate) -> None:
        add_perf_event(update.perf_events, self.node_name, "OPENR_FIB_ROUTES_PROGRAMMED")
        if self._fib_updates_queue is not None:
            self._fib_updates_queue.push(update)
        self._log_perf_events(update.perf_events)

    def _log_perf_events(self, perf_events: Optional[PerfEvents]) -> None:
        """Reference: logPerfEvents (Fib.h:187) — ROUTE_CONVERGENCE."""
        if perf_events is None or not perf_events.events:
            return
        self.perf_db.append(perf_events)
        duration = perf_events.total_duration_ms()
        self._bump("fib.route_convergence_count")
        self.counters["fib.route_convergence_last_ms"] = duration
        if self._log_sample_queue is not None:
            self._log_sample_queue.push(
                {
                    "event": "ROUTE_CONVERGENCE",
                    "node": self.node_name,
                    "duration_ms": duration,
                    "events": [
                        (e.event_name, e.unix_ts_ms) for e in perf_events.events
                    ],
                }
            )

    # -- full sync (reference: syncRouteDb/syncRouteDbDebounced) -------------

    def _schedule_sync(self, delay_s: float) -> None:
        if self._sync_timer is not None:
            self._sync_timer.cancel()
        self._sync_timer = self.schedule_timeout(delay_s, self._sync_fib)

    def _schedule_sync_backoff(self) -> None:
        self._bump("fib.sync_retries")
        self._sync_backoff.report_error()
        self._schedule_sync(self._sync_backoff.get_current_backoff())

    def _sync_fib(self) -> None:
        self._sync_timer = None
        try:
            if not self.dryrun:
                routes = [
                    r
                    for prefix, r in self.route_state.unicast_routes.items()
                    if prefix not in self._do_not_install
                ]
                self.agent.sync_fib(self.client_id, routes)
                if self.enable_segment_routing:
                    self.agent.sync_mpls_fib(
                        self.client_id, list(self.route_state.mpls_routes.values())
                    )
            self._bump("fib.sync_fib_calls")
            was_dirty = self.route_state.dirty
            self.route_state.synced = True
            self.route_state.dirty = False
            self._sync_backoff.report_success()
            if was_dirty and self._fib_updates_queue is not None:
                # updates absorbed while unsynced (or failed incrementally)
                # were never published; emit the reconciled full state so
                # streaming subscribers converge
                self._fib_updates_queue.push(self._full_state_update())
        except Exception:
            log.exception("fib: syncFib failed; retrying with backoff")
            self._bump("fib.thrift.failure.sync_fib")
            self._schedule_sync_backoff()

    def _full_state_update(self) -> DecisionRouteUpdate:
        update = DecisionRouteUpdate()
        for prefix, route in self.route_state.unicast_routes.items():
            update.unicast_routes_to_update[prefix] = RibUnicastEntry(
                prefix=prefix,
                nexthops=frozenset(route.next_hops),
                do_not_install=prefix in self._do_not_install,
            )
        update.mpls_routes_to_update = [
            RibMplsEntry(label=label, nexthops=frozenset(route.next_hops))
            for label, route in self.route_state.mpls_routes.items()
        ]
        return update

    # -- keep-alive (reference: keepAliveCheck, Fib.h:181) -------------------

    def _keepalive_tick(self) -> None:
        try:
            alive_since = self.agent.alive_since()
        except Exception:
            alive_since = None
            self._bump("fib.thrift.failure.keepalive")
        if alive_since is not None:
            if (
                self._latest_alive_since is not None
                and alive_since != self._latest_alive_since
            ):
                # agent restarted: it lost all routes — full resync
                log.warning("fib: agent restart detected; resyncing")
                self._bump("fib.agent_restarts")
                self.route_state.synced = False
                self._schedule_sync(0.0)
            self._latest_alive_since = alive_since
        self.schedule_timeout(self._keepalive_interval_s, self._keepalive_tick)

    # -- introspection (reference: getRouteDb/getPerfDb) ---------------------

    def get_route_db(
        self, programmed_only: bool = False
    ) -> tuple[list[UnicastRoute], list[MplsRoute]]:
        """Tracked route state; with `programmed_only`, restricted to what
        is actually sent to the agent (do_not_install prefixes are tracked
        but never programmed, fib.py _update_routes/_sync_fib; MPLS
        programming is gated on enable_segment_routing; dryrun programs
        nothing at all)."""

        def _get():
            if programmed_only and self.dryrun:
                return [], []
            unicast = [
                r
                for p, r in self.route_state.unicast_routes.items()
                if not programmed_only or p not in self._do_not_install
            ]
            mpls = (
                []
                if programmed_only and not self.enable_segment_routing
                else list(self.route_state.mpls_routes.values())
            )
            return unicast, mpls

        return self.run_in_event_base_thread(_get).result()

    def get_unicast_routes(self, prefixes: Optional[list[str]] = None) -> list[UnicastRoute]:
        """Reference: Fib::getUnicastRoutesFiltered (openr/fib/Fib.cpp:268).

        Each filter entry is normalized through `ipaddress` (so
        "fc01::0001/64" finds the route keyed "fc01::/64") and answered
        by LONGEST-PREFIX MATCH: an exact (normalized) table hit wins,
        otherwise the most-specific table route that COVERS the queried
        prefix — so querying a host address returns its covering route,
        never a silent miss on string inequality.  Malformed filter
        entries match nothing; duplicates collapse (first occurrence
        order preserved)."""

        def _get() -> list[UnicastRoute]:
            routes = self.route_state.unicast_routes
            if not prefixes:
                return list(routes.values())
            table: list[tuple] = []
            for key in routes:
                try:
                    table.append((ipaddress.ip_network(key, strict=False), key))
                except ValueError:
                    continue
            out: list[UnicastRoute] = []
            seen: set[str] = set()
            for p in prefixes:
                try:
                    q = ipaddress.ip_network(p, strict=False)
                except ValueError:
                    continue
                best_key = None
                best_len = -1
                for net, key in table:
                    if (
                        net.version == q.version
                        and net.prefixlen <= q.prefixlen
                        and q.network_address in net
                        and net.prefixlen > best_len
                    ):
                        best_key, best_len = key, net.prefixlen
                if best_key is not None and best_key not in seen:
                    seen.add(best_key)
                    out.append(routes[best_key])
            return out

        return self.run_in_event_base_thread(_get).result()

    def get_perf_db(self) -> list[PerfEvents]:
        return self.run_in_event_base_thread(lambda: list(self.perf_db)).result()
