"""Watchdog: module-thread liveness + memory guard.

Functional equivalent of the reference's Watchdog
(openr/watchdog/Watchdog.{h,cpp}:24-122): every module event base is
registered (`add_evb`, wired in startEventBase — Main.cpp:153); the
watchdog thread samples each module's heartbeat timestamp and the process
RSS, and fires a crash (os.abort for supervisor restart — or a callback in
tests) on thread stall or memory explosion.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional

from ..runtime.eventbase import OpenrEventBase
from .monitor import SystemMetrics

log = logging.getLogger(__name__)


class Watchdog:
    def __init__(
        self,
        *,
        interval_s: float = 20.0,
        thread_timeout_s: float = 300.0,
        max_memory_bytes: int = 800 * 1024 * 1024,
        on_crash: Optional[Callable[[str], None]] = None,
    ) -> None:
        self._interval_s = interval_s
        self._thread_timeout_s = thread_timeout_s
        self._max_memory_bytes = max_memory_bytes
        self._on_crash = on_crash
        self._evbs: list[OpenrEventBase] = []
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.fired: Optional[str] = None
        self.counters: dict[str, int] = {
            "watchdog.stall_events": 0,
            "watchdog.fired": 0,
        }

    def get_counters(self) -> dict[str, int]:
        with self._lock:
            return dict(self.counters)

    def add_evb(self, evb: OpenrEventBase) -> None:
        """Reference: Watchdog::addEvb (Watchdog.h:32)."""
        with self._lock:
            self._evbs.append(evb)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="watchdog")
        self._thread.daemon = True
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(self._interval_s):
            self.check_once()

    def check_once(self) -> None:
        now = time.monotonic()
        with self._lock:
            evbs = list(self._evbs)
        # scan EVERY module before deciding: one wedged thread must not
        # mask another stall or the memory check (an early return here
        # previously skipped both)
        reasons: list[str] = []
        stalls = 0
        for evb in evbs:
            if not evb.is_running:
                continue
            stall = now - evb.get_timestamp()
            if stall > self._thread_timeout_s:
                stalls += 1
                reasons.append(f"thread {evb.name!r} stalled for {stall:.0f}s")
        rss = SystemMetrics.rss_bytes()
        if rss is not None and rss > self._max_memory_bytes:
            reasons.append(
                f"memory limit exceeded: rss={rss} > {self._max_memory_bytes}"
            )
        if stalls:
            with self._lock:
                self.counters["watchdog.stall_events"] += stalls
        if reasons:
            self._fire_crash("; ".join(reasons))

    def _fire_crash(self, reason: str) -> None:
        """Reference: Watchdog::fireCrash (Watchdog.cpp:110-122) — abort so
        the supervisor (systemd) restarts the daemon."""
        log.critical("watchdog: %s", reason)
        self.fired = reason
        with self._lock:
            self.counters["watchdog.fired"] += 1
        if self._on_crash is not None:
            self._on_crash(reason)
        else:
            os.abort()
