"""Monitor: event-log sink + process counters.

Functional equivalent of the reference's Monitor
(openr/monitor/Monitor.h:17, MonitorBase.h:32, SystemMetrics.h:23,
LogSample.h:43): consumes the LogSample queue, keeps a bounded recent-event
ring, exports process counters (uptime, RSS, CPU time).
"""

from __future__ import annotations

import json
import logging
import os
import resource
import time
from collections import deque
from typing import Any, Optional

from ..runtime.eventbase import OpenrEventBase
from ..runtime.queue import QueueClosedError, RQueue

log = logging.getLogger(__name__)

MAX_LOG_EVENTS = 100  # reference: MonitorBase maxLogEvents


class LogSample:
    """Structured JSON event builder (reference: LogSample.h:43)."""

    def __init__(self, **values: Any) -> None:
        self.values: dict[str, Any] = {"time": int(time.time()), **values}

    def add(self, key: str, value: Any) -> "LogSample":
        self.values[key] = value
        return self

    def to_json(self) -> str:
        return json.dumps(self.values, sort_keys=True)


class SystemMetrics:
    """RSS / CPU from rusage (reference: SystemMetrics.h:23-41)."""

    @staticmethod
    def rss_bytes() -> Optional[int]:
        try:
            with open(f"/proc/{os.getpid()}/statm") as f:
                pages = int(f.read().split()[1])
            return pages * os.sysconf("SC_PAGE_SIZE")
        except (OSError, ValueError, IndexError):
            ru = resource.getrusage(resource.RUSAGE_SELF)
            return ru.ru_maxrss * 1024 if ru.ru_maxrss else None

    @staticmethod
    def cpu_seconds() -> float:
        ru = resource.getrusage(resource.RUSAGE_SELF)
        return ru.ru_utime + ru.ru_stime


class Monitor(OpenrEventBase):
    def __init__(
        self,
        node_name: str,
        log_sample_queue: RQueue,
        *,
        counter_interval_s: float = 10.0,
        syslog: bool = False,
    ) -> None:
        super().__init__(name=f"monitor-{node_name}")
        self.node_name = node_name
        self._log_samples = log_sample_queue
        self._counter_interval_s = counter_interval_s
        self._syslog = syslog
        self._start_time = time.time()
        self.recent_events: deque = deque(maxlen=MAX_LOG_EVENTS)
        self._process_counters: dict[str, int] = {}

    def run(self) -> None:
        super().run()
        self.wait_until_running()
        self.run_in_event_base_thread(self._setup).result()

    def _setup(self) -> None:
        self.add_fiber_task(self._log_fiber(), name="logSamples")
        self._update_counters()

    async def _log_fiber(self) -> None:
        while True:
            try:
                sample = await self._log_samples.aget()
            except QueueClosedError:
                return
            self.process_event_log(sample)

    def process_event_log(self, sample: Any) -> None:
        """Reference: MonitorBase::processEventLog — record + syslog."""
        if isinstance(sample, LogSample):
            rendered = sample.to_json()
        elif isinstance(sample, dict):
            rendered = json.dumps(sample, sort_keys=True, default=str)
        else:
            rendered = str(sample)
        self.recent_events.append(rendered)
        if self._syslog:
            log.info("event-log: %s", rendered)

    def _update_counters(self) -> None:
        """Reference: Monitor periodic process counters."""
        self._process_counters["monitor.uptime_s"] = int(
            time.time() - self._start_time
        )
        rss = SystemMetrics.rss_bytes()
        if rss is not None:
            self._process_counters["monitor.process_rss_bytes"] = rss
        self._process_counters["monitor.process_cpu_ms"] = int(
            SystemMetrics.cpu_seconds() * 1000
        )
        self.schedule_timeout(self._counter_interval_s, self._update_counters)

    def get_counters(self) -> dict[str, int]:
        return dict(self._process_counters)

    def get_event_logs(self) -> list[str]:
        return self.run_in_event_base_thread(
            lambda: list(self.recent_events)
        ).result()
