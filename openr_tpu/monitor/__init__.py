"""Monitor + Watchdog: observability and liveness."""

from .monitor import LogSample, Monitor, SystemMetrics
from .watchdog import Watchdog

__all__ = ["LogSample", "Monitor", "SystemMetrics", "Watchdog"]
