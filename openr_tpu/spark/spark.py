"""Spark: the neighbor-discovery event base.

Functional equivalent of the reference's Spark (openr/spark/Spark.{h,cpp};
FSM documented in openr/docs/Protocol_Guide/Spark.md "State Transition
Map"):

- per-(interface, neighbor) FSM: IDLE / WARM / NEGOTIATE / ESTABLISHED /
  RESTART, transitions exactly per the reference's table;
- SparkHelloMsg per interface (neighbor solicitation + visibility
  reflection for RTT), SparkHandshakeMsg per neighbor (hold/GR time and
  area negotiation), SparkHeartbeatMsg per interface (keep-alive);
- RTT from reflected timestamps, smoothed through StepDetector ->
  NEIGHBOR_RTT_CHANGE events;
- graceful restart: HELLO_RCVD_RESTART -> RESTART state + GR hold timer;
  `flood_restarting_msg` announces our own restart.
"""

from __future__ import annotations

import enum
import hashlib
import logging
import random
import re
import time
from dataclasses import dataclass, field
from typing import Optional

from ..obs import trace as _trace
from ..runtime.eventbase import OpenrEventBase
from ..runtime.queue import QueueClosedError, ReplicateQueue, RQueue
from ..serializer import dumps, loads
from ..types import (
    InterfaceDatabase,
    NeighborEvent,
    NeighborEventType,
    ReflectedNeighborInfo,
    SparkHandshakeMsg,
    SparkHelloMsg,
    SparkHeartbeatMsg,
    SparkPacket,
)
from ..utils.step_detector import StepDetector
from .io_provider import IoProvider

log = logging.getLogger(__name__)


class SparkNeighState(enum.IntEnum):
    IDLE = 0
    WARM = 1
    NEGOTIATE = 2
    ESTABLISHED = 3
    RESTART = 4


class SparkNeighEvent(enum.IntEnum):
    HELLO_RCVD_INFO = 0
    HELLO_RCVD_NO_INFO = 1
    HELLO_RCVD_RESTART = 2
    HEARTBEAT_RCVD = 3
    HANDSHAKE_RCVD = 4
    HEARTBEAT_TIMER_EXPIRE = 5
    NEGOTIATE_TIMER_EXPIRE = 6
    GR_TIMER_EXPIRE = 7
    NEGOTIATION_FAILURE = 8


S = SparkNeighState
E = SparkNeighEvent
# reference FSM table (Spark.md "State Transition Map")
_FSM: dict[tuple[SparkNeighState, SparkNeighEvent], SparkNeighState] = {
    (S.IDLE, E.HELLO_RCVD_INFO): S.WARM,
    (S.IDLE, E.HELLO_RCVD_NO_INFO): S.WARM,
    (S.WARM, E.HELLO_RCVD_INFO): S.NEGOTIATE,
    (S.NEGOTIATE, E.HANDSHAKE_RCVD): S.ESTABLISHED,
    (S.NEGOTIATE, E.NEGOTIATE_TIMER_EXPIRE): S.WARM,
    (S.NEGOTIATE, E.NEGOTIATION_FAILURE): S.WARM,
    (S.ESTABLISHED, E.HELLO_RCVD_NO_INFO): S.IDLE,
    (S.ESTABLISHED, E.HELLO_RCVD_RESTART): S.RESTART,
    (S.ESTABLISHED, E.HEARTBEAT_RCVD): S.ESTABLISHED,
    (S.ESTABLISHED, E.HEARTBEAT_TIMER_EXPIRE): S.IDLE,
    (S.RESTART, E.HELLO_RCVD_INFO): S.ESTABLISHED,
    (S.RESTART, E.GR_TIMER_EXPIRE): S.IDLE,
}


@dataclass(slots=True)
class AreaConfig:
    """Reference: thrift::AreaConfig (openr/if/OpenrConfig.thrift:322)."""

    area_id: str = "0"
    interface_regexes: list[str] = field(default_factory=lambda: [".*"])
    neighbor_regexes: list[str] = field(default_factory=lambda: [".*"])

    def matches(self, if_name: str, neighbor: str) -> bool:
        return any(re.fullmatch(p, if_name) for p in self.interface_regexes) and any(
            re.fullmatch(p, neighbor) for p in self.neighbor_regexes
        )


@dataclass(slots=True)
class SparkConfig:
    """Reference: thrift::SparkConfig (openr/if/OpenrConfig.thrift:116)."""

    hello_time_s: float = 20.0
    fastinit_hello_time_s: float = 0.5
    keepalive_time_s: float = 2.0  # heartbeat send interval
    hold_time_s: float = 10.0  # heartbeat hold
    graceful_restart_time_s: float = 30.0
    negotiate_hold_time_s: float = 1.0
    step_detector_fast_window_size: int = 10
    step_detector_slow_window_size: int = 60
    step_detector_lower_threshold_pct: float = 0.4
    step_detector_upper_threshold_pct: float = 0.6
    step_detector_abs_threshold: int = 500


class SparkNeighbor:
    """Reference: Spark::SparkNeighbor (openr/spark/Spark.h:273)."""

    __slots__ = (
        "node_name",
        "if_name",
        "state",
        "area",
        "seq_num",
        "transport_addr_v6",
        "transport_addr_v4",
        "ctrl_port",
        "kvstore_port",
        "rtt_us",
        "rtt_latest_us",
        "step_detector",
        "remote_if_name",
        "last_hello_sent_ts_us",
        "last_nbr_hello_rcvd_ts_us",
        "last_nbr_hello_sent_ts_us",
        "heartbeat_hold_timer",
        "negotiate_hold_timer",
        "gr_hold_timer",
        "gr_hold_time_ms",
        "hold_time_ms",
        "seen_restarting",
    )

    def __init__(self, node_name: str, if_name: str) -> None:
        self.node_name = node_name
        self.if_name = if_name
        self.state = SparkNeighState.IDLE
        self.area = ""
        self.seq_num = 0
        self.transport_addr_v6 = ""
        self.transport_addr_v4 = ""
        self.ctrl_port = 0
        self.kvstore_port = 0
        self.rtt_us = 0
        self.rtt_latest_us = 0
        self.remote_if_name = ""
        self.step_detector: Optional[StepDetector] = None
        self.last_hello_sent_ts_us = 0
        self.last_nbr_hello_rcvd_ts_us = 0
        self.last_nbr_hello_sent_ts_us = 0
        self.heartbeat_hold_timer = None
        self.negotiate_hold_timer = None
        self.gr_hold_timer = None
        self.gr_hold_time_ms = 0
        self.hold_time_ms = 0
        self.seen_restarting = False


class Spark(OpenrEventBase):
    def __init__(
        self,
        node_name: str,
        interface_updates: RQueue[InterfaceDatabase],
        neighbor_updates_queue: ReplicateQueue[NeighborEvent],
        io_provider: IoProvider,
        *,
        config: Optional[SparkConfig] = None,
        areas: Optional[list[AreaConfig]] = None,
        domain: str = "openr",
        ctrl_port: int = 2018,
        kvstore_port: int = 60002,
        v4_addr: str = "",
        v6_addr: str = "",
    ) -> None:
        super().__init__(name=f"spark-{node_name}")
        self.node_name = node_name
        self.domain = domain
        self.config = config or SparkConfig()
        self.areas = areas or [AreaConfig()]
        self.ctrl_port = ctrl_port
        self.kvstore_port = kvstore_port
        digest = int.from_bytes(
            hashlib.blake2b(node_name.encode(), digest_size=2).digest(), "big"
        )
        self.v4_addr = v4_addr or f"169.254.{digest % 250 + 1}.{digest // 256 % 250 + 1}"
        self.v6_addr = v6_addr or f"fe80::{node_name}"
        self._interface_updates = interface_updates
        self._neighbor_updates_queue = neighbor_updates_queue
        self.io = io_provider
        # if_name -> {neighbor_name -> SparkNeighbor}
        self.neighbors: dict[str, dict[str, SparkNeighbor]] = {}
        self._interfaces: set[str] = set()
        self._hello_timers: dict[str, object] = {}
        self._heartbeat_timers: dict[str, object] = {}
        self._seq_num = 0
        self._restarting = False
        self._fastinit_rounds: dict[str, int] = {}
        self.counters: dict[str, int] = {}
        self._max_fastinit_rounds = 10

    # -- lifecycle -----------------------------------------------------------

    def run(self) -> None:
        super().run()
        self.wait_until_running()
        self.run_in_event_base_thread(self._setup).result()

    def _setup(self) -> None:
        self.io.attach(self.node_name)
        self.add_fiber_task(self._recv_fiber(), name="sparkRecv")
        self.add_fiber_task(self._interface_fiber(), name="ifUpdates")

    def _bump(self, counter: str, n: int = 1) -> None:
        self.counters[counter] = self.counters.get(counter, 0) + n

    # -- fibers --------------------------------------------------------------

    async def _recv_fiber(self) -> None:
        while True:
            pkt = await self.io.recv()
            try:
                self._process_packet(pkt.if_name, pkt.data, pkt.recv_ts_us)
            except Exception:
                log.exception("spark: bad packet on %s", pkt.if_name)
                self._bump("spark.parse_error")

    async def _interface_fiber(self) -> None:
        while True:
            try:
                if_db = await self._interface_updates.aget()
            except QueueClosedError:
                return
            self.process_interface_updates(if_db)

    # -- interface tracking (reference: processInterfaceUpdates) -------------

    def process_interface_updates(self, if_db: InterfaceDatabase) -> None:
        up_ifs = {
            name for name, info in if_db.interfaces.items() if info.is_up
        }
        for if_name in up_ifs - self._interfaces:
            self._add_interface(if_name)
        for if_name in self._interfaces - up_ifs:
            self._remove_interface(if_name)

    def _add_interface(self, if_name: str) -> None:
        self._interfaces.add(if_name)
        self.neighbors.setdefault(if_name, {})
        self.io.add_interface(if_name)
        # fast-init hellos solicit immediate responses
        self._fastinit_rounds[if_name] = 0
        self._schedule_hello(if_name, fastinit=True)
        self._schedule_heartbeat(if_name)

    def _remove_interface(self, if_name: str) -> None:
        self._interfaces.discard(if_name)
        for timers in (self._hello_timers, self._heartbeat_timers):
            timer = timers.pop(if_name, None)
            if timer is not None:
                timer.cancel()
        for neighbor in list(self.neighbors.get(if_name, {}).values()):
            if neighbor.state in (
                SparkNeighState.ESTABLISHED,
                SparkNeighState.RESTART,
            ):
                self._neighbor_down(neighbor, NeighborEventType.NEIGHBOR_DOWN)
            else:
                # disarm orphaned timers so they can't fire against a
                # future re-established neighbor with the same key
                for attr in (
                    "heartbeat_hold_timer",
                    "negotiate_hold_timer",
                    "gr_hold_timer",
                ):
                    self._cancel_timer(neighbor, attr)
        self.neighbors.pop(if_name, None)
        self.io.remove_interface(if_name)

    # -- senders (reference: Spark.h:180-193) --------------------------------

    def _schedule_hello(self, if_name: str, fastinit: bool = False) -> None:
        if if_name not in self._interfaces:
            return
        existing = self._hello_timers.pop(if_name, None)
        if existing is not None:
            existing.cancel()
        period = (
            self.config.fastinit_hello_time_s
            if fastinit
            else self.config.hello_time_s
        )
        # jitter avoids synchronized multicast bursts
        period *= random.uniform(0.9, 1.1)
        self._hello_timers[if_name] = self.schedule_timeout(
            period, lambda: self._hello_tick(if_name, fastinit)
        )

    def _hello_tick(self, if_name: str, was_fastinit: bool) -> None:
        if if_name not in self._interfaces:
            return
        self.send_hello(if_name)
        # stay in fastinit until a neighbor is past WARM, bounded by a
        # round budget so an idle port decays to the slow hello rate
        rounds = self._fastinit_rounds.get(if_name, 0) + 1
        self._fastinit_rounds[if_name] = rounds
        fastinit = (
            was_fastinit
            and rounds < self._max_fastinit_rounds
            and not any(
                n.state
                in (SparkNeighState.NEGOTIATE, SparkNeighState.ESTABLISHED)
                for n in self.neighbors.get(if_name, {}).values()
            )
        )
        self._schedule_hello(if_name, fastinit=fastinit)

    def send_hello(
        self, if_name: str, restarting: bool = False, solicit: bool = False
    ) -> None:
        self._seq_num += 1
        # CLOCK_REALTIME: the RTT timestamp domain must match the
        # transport's KERNEL rx timestamps (UdpIoProvider SO_TIMESTAMPNS,
        # reference: Spark.cpp:447-448) — t4 (kernel rx) and t1 (this
        # send stamp) difference only makes sense on one clock.  NTP
        # steps between t1 and t4 produce outliers; StepDetector exists
        # to filter exactly those.
        now_us = int(time.clock_gettime(time.CLOCK_REALTIME) * 1e6)
        neighbor_infos = {}
        for name, neighbor in self.neighbors.get(if_name, {}).items():
            neighbor_infos[name] = ReflectedNeighborInfo(
                last_nbr_msg_sent_ts_us=neighbor.last_nbr_hello_sent_ts_us,
                last_my_msg_rcvd_ts_us=neighbor.last_nbr_hello_rcvd_ts_us,
            )
        msg = SparkHelloMsg(
            domain_name=self.domain,
            node_name=self.node_name,
            if_name=if_name,
            seq_num=self._seq_num,
            neighbor_infos=neighbor_infos,
            solicit_response=solicit,
            restarting=restarting or self._restarting,
            sent_ts_us=now_us,
        )
        for neighbor in self.neighbors.get(if_name, {}).values():
            neighbor.last_hello_sent_ts_us = now_us
        self.io.send(if_name, dumps(SparkPacket(hello=msg)))
        self._bump("spark.hello.packets_sent")

    def _send_handshake(self, if_name: str, neighbor_name: str, established: bool) -> None:
        msg = SparkHandshakeMsg(
            node_name=self.node_name,
            is_adjacency_established=established,
            hold_time_ms=int(self.config.hold_time_s * 1000),
            gr_hold_time_ms=int(self.config.graceful_restart_time_s * 1000),
            transport_addr_v6=self.v6_addr,
            transport_addr_v4=self.v4_addr,
            openr_ctrl_port=self.ctrl_port,
            kvstore_cmd_port=self.kvstore_port,
            area=self._negotiate_area(if_name, neighbor_name) or "",
            neighbor_node_name=neighbor_name,
        )
        self.io.send(if_name, dumps(SparkPacket(handshake=msg)))
        self._bump("spark.handshake.packets_sent")

    def _schedule_heartbeat(self, if_name: str) -> None:
        existing = self._heartbeat_timers.pop(if_name, None)
        if existing is not None:
            existing.cancel()
        self._heartbeat_timers[if_name] = self.schedule_timeout(
            self.config.keepalive_time_s * random.uniform(0.9, 1.1),
            lambda: self._heartbeat_tick(if_name),
        )

    def _heartbeat_tick(self, if_name: str) -> None:
        if if_name not in self._interfaces:
            return
        self._seq_num += 1
        msg = SparkHeartbeatMsg(
            node_name=self.node_name,
            seq_num=self._seq_num,
            hold_time_ms=int(self.config.hold_time_s * 1000),
        )
        self.io.send(if_name, dumps(SparkPacket(heartbeat=msg)))
        self._bump("spark.heartbeat.packets_sent")
        self._schedule_heartbeat(if_name)

    # -- receive path --------------------------------------------------------

    def _process_packet(self, if_name: str, data: bytes, recv_ts_us: int) -> None:
        if if_name not in self._interfaces:
            return
        packet = loads(data, SparkPacket)
        if packet.hello is not None:
            self._process_hello(if_name, packet.hello, recv_ts_us)
        elif packet.handshake is not None:
            self._process_handshake(if_name, packet.handshake)
        elif packet.heartbeat is not None:
            self._process_heartbeat(if_name, packet.heartbeat)

    def _fsm(self, neighbor: SparkNeighbor, event: SparkNeighEvent) -> bool:
        """Apply an FSM transition; returns False for invalid (ignored)
        events (the reference CHECKs; we tolerate + count)."""
        new_state = _FSM.get((neighbor.state, event))
        if new_state is None:
            self._bump("spark.invalid_state_transition")
            return False
        if new_state != neighbor.state:
            log.debug(
                "spark[%s]: %s/%s %s -> %s on %s",
                self.node_name,
                neighbor.if_name,
                neighbor.node_name,
                neighbor.state.name,
                new_state.name,
                event.name,
            )
        neighbor.state = new_state
        return True

    def _process_hello(
        self, if_name: str, hello: SparkHelloMsg, recv_ts_us: int
    ) -> None:
        """Reference: processHelloMsg (openr/spark/Spark.cpp)."""
        if hello.node_name == self.node_name:
            return  # our own multicast echo
        if hello.domain_name != self.domain:
            self._bump("spark.hello.invalid_domain")
            return
        self._bump("spark.hello.packets_recv")

        neighbors = self.neighbors.setdefault(if_name, {})
        neighbor = neighbors.get(hello.node_name)
        if neighbor is None:
            neighbor = neighbors[hello.node_name] = SparkNeighbor(
                hello.node_name, if_name
            )
            # a brand-new neighbor: restart fast hellos to converge quickly
            self._fastinit_rounds[if_name] = 0
            self._schedule_hello(if_name, fastinit=True)

        neighbor.last_nbr_hello_rcvd_ts_us = recv_ts_us
        neighbor.last_nbr_hello_sent_ts_us = hello.sent_ts_us
        neighbor.remote_if_name = hello.if_name
        neighbor.seq_num = hello.seq_num

        my_info = hello.neighbor_infos.get(self.node_name)
        seen_me = my_info is not None

        # RTT: (t4 - t1) - (t3 - t2) where t1 = my hello sent, t2 = their
        # receipt of it, t3 = their hello sent, t4 = my receipt
        if seen_me and my_info.last_my_msg_rcvd_ts_us and my_info.last_nbr_msg_sent_ts_us:
            rtt_us = (recv_ts_us - my_info.last_nbr_msg_sent_ts_us) - (
                hello.sent_ts_us - my_info.last_my_msg_rcvd_ts_us
            )
            if rtt_us > 0:
                self._update_rtt(neighbor, rtt_us)

        state = neighbor.state
        if state == SparkNeighState.IDLE:
            event = (
                SparkNeighEvent.HELLO_RCVD_INFO
                if seen_me
                else SparkNeighEvent.HELLO_RCVD_NO_INFO
            )
            self._fsm(neighbor, event)
            if neighbor.state == SparkNeighState.WARM and seen_me:
                # already mutually visible: go straight to NEGOTIATE
                self._fsm(neighbor, SparkNeighEvent.HELLO_RCVD_INFO)
                self._start_negotiate(neighbor)
        elif state == SparkNeighState.WARM:
            if seen_me:
                self._fsm(neighbor, SparkNeighEvent.HELLO_RCVD_INFO)
                self._start_negotiate(neighbor)
        elif state == SparkNeighState.ESTABLISHED:
            if hello.restarting:
                self._neighbor_restarting(neighbor)
            elif not seen_me:
                # neighbor no longer sees us (e.g. it restarted fast)
                self._fsm(neighbor, SparkNeighEvent.HELLO_RCVD_NO_INFO)
                self._neighbor_down(neighbor, NeighborEventType.NEIGHBOR_DOWN)
                self._schedule_hello(if_name, fastinit=True)
        elif state == SparkNeighState.RESTART:
            if seen_me and not hello.restarting:
                self._fsm(neighbor, SparkNeighEvent.HELLO_RCVD_INFO)
                self._cancel_timer(neighbor, "gr_hold_timer")
                self._start_heartbeat_hold(neighbor)
                self._publish_event(
                    NeighborEventType.NEIGHBOR_RESTARTED, neighbor
                )

        if hello.solicit_response:
            self.send_hello(if_name)

    def _start_negotiate(self, neighbor: SparkNeighbor) -> None:
        area = self._negotiate_area(neighbor.if_name, neighbor.node_name)
        if area is None:
            self._bump("spark.negotiate.area_mismatch")
            self._fsm(neighbor, SparkNeighEvent.NEGOTIATION_FAILURE)
            return
        neighbor.area = area
        self._send_handshake(neighbor.if_name, neighbor.node_name, False)
        self._cancel_timer(neighbor, "negotiate_hold_timer")
        neighbor.negotiate_hold_timer = self.schedule_timeout(
            self.config.negotiate_hold_time_s,
            lambda: self._negotiate_expired(neighbor),
        )

    def _negotiate_expired(self, neighbor: SparkNeighbor) -> None:
        neighbor.negotiate_hold_timer = None
        if neighbor.state == SparkNeighState.NEGOTIATE:
            self._fsm(neighbor, SparkNeighEvent.NEGOTIATE_TIMER_EXPIRE)

    def _negotiate_area(self, if_name: str, neighbor_name: str) -> Optional[str]:
        """First matching area config wins (reference: getNeighborArea)."""
        for area_cfg in self.areas:
            if area_cfg.matches(if_name, neighbor_name):
                return area_cfg.area_id
        return None

    def _process_handshake(self, if_name: str, msg: SparkHandshakeMsg) -> None:
        """Reference: processHandshakeMsg."""
        if msg.node_name == self.node_name:
            return
        if (
            msg.neighbor_node_name is not None
            and msg.neighbor_node_name != self.node_name
        ):
            return  # destined to someone else on the segment
        self._bump("spark.handshake.packets_recv")
        neighbor = self.neighbors.get(if_name, {}).get(msg.node_name)
        if neighbor is None:
            return

        # reply (once) so the peer can establish too
        if not msg.is_adjacency_established:
            self._send_handshake(if_name, msg.node_name, True)

        if neighbor.state != SparkNeighState.NEGOTIATE:
            return

        # area must agree (reference: area negotiation check)
        my_area = self._negotiate_area(if_name, msg.node_name)
        if my_area is None or (msg.area and msg.area != my_area):
            self._fsm(neighbor, SparkNeighEvent.NEGOTIATION_FAILURE)
            self._cancel_timer(neighbor, "negotiate_hold_timer")
            return

        neighbor.area = my_area
        neighbor.transport_addr_v6 = msg.transport_addr_v6
        neighbor.transport_addr_v4 = msg.transport_addr_v4
        neighbor.ctrl_port = msg.openr_ctrl_port
        neighbor.kvstore_port = msg.kvstore_cmd_port
        neighbor.hold_time_ms = msg.hold_time_ms
        neighbor.gr_hold_time_ms = msg.gr_hold_time_ms
        self._fsm(neighbor, SparkNeighEvent.HANDSHAKE_RCVD)
        self._cancel_timer(neighbor, "negotiate_hold_timer")
        self._start_heartbeat_hold(neighbor)
        self._publish_event(NeighborEventType.NEIGHBOR_UP, neighbor)

    def _process_heartbeat(self, if_name: str, msg: SparkHeartbeatMsg) -> None:
        """Reference: processHeartbeatMsg — refresh hold timer."""
        if msg.node_name == self.node_name:
            return
        neighbor = self.neighbors.get(if_name, {}).get(msg.node_name)
        if neighbor is None or neighbor.state != SparkNeighState.ESTABLISHED:
            return
        self._fsm(neighbor, SparkNeighEvent.HEARTBEAT_RCVD)
        self._start_heartbeat_hold(neighbor)

    # -- timers / events -----------------------------------------------------

    def _cancel_timer(self, neighbor: SparkNeighbor, attr: str) -> None:
        timer = getattr(neighbor, attr)
        if timer is not None:
            timer.cancel()
            setattr(neighbor, attr, None)

    def _start_heartbeat_hold(self, neighbor: SparkNeighbor) -> None:
        self._cancel_timer(neighbor, "heartbeat_hold_timer")
        hold_s = (
            neighbor.hold_time_ms / 1000.0
            if neighbor.hold_time_ms
            else self.config.hold_time_s
        )
        neighbor.heartbeat_hold_timer = self.schedule_timeout(
            hold_s, lambda: self._heartbeat_hold_expired(neighbor)
        )

    def _heartbeat_hold_expired(self, neighbor: SparkNeighbor) -> None:
        neighbor.heartbeat_hold_timer = None
        if neighbor.state == SparkNeighState.ESTABLISHED:
            self._fsm(neighbor, SparkNeighEvent.HEARTBEAT_TIMER_EXPIRE)
            self._neighbor_down(neighbor, NeighborEventType.NEIGHBOR_DOWN)
            self._schedule_hello(neighbor.if_name, fastinit=True)

    def _neighbor_restarting(self, neighbor: SparkNeighbor) -> None:
        """ESTABLISHED -> RESTART with GR hold (reference: GR handling)."""
        self._fsm(neighbor, SparkNeighEvent.HELLO_RCVD_RESTART)
        self._cancel_timer(neighbor, "heartbeat_hold_timer")
        gr_s = (
            neighbor.gr_hold_time_ms / 1000.0
            if neighbor.gr_hold_time_ms
            else self.config.graceful_restart_time_s
        )
        self._cancel_timer(neighbor, "gr_hold_timer")
        neighbor.gr_hold_timer = self.schedule_timeout(
            gr_s, lambda: self._gr_expired(neighbor)
        )
        self._publish_event(NeighborEventType.NEIGHBOR_RESTARTING, neighbor)

    def _gr_expired(self, neighbor: SparkNeighbor) -> None:
        neighbor.gr_hold_timer = None
        if neighbor.state == SparkNeighState.RESTART:
            self._fsm(neighbor, SparkNeighEvent.GR_TIMER_EXPIRE)
            self._neighbor_down(neighbor, NeighborEventType.NEIGHBOR_DOWN)
            self._schedule_hello(neighbor.if_name, fastinit=True)

    def _neighbor_down(
        self, neighbor: SparkNeighbor, event_type: NeighborEventType
    ) -> None:
        for attr in ("heartbeat_hold_timer", "negotiate_hold_timer", "gr_hold_timer"):
            self._cancel_timer(neighbor, attr)
        self._publish_event(event_type, neighbor)
        neighbor.state = SparkNeighState.IDLE

    def _update_rtt(self, neighbor: SparkNeighbor, rtt_us: int) -> None:
        """RTT smoothing through StepDetector; significant changes publish
        NEIGHBOR_RTT_CHANGE (reference: kernel-timestamped RTT ->
        StepDetector, openr/spark/Spark.h:273)."""
        neighbor.rtt_latest_us = rtt_us
        if neighbor.step_detector is None:
            cfg = self.config
            neighbor.step_detector = StepDetector(
                fast_window_size=cfg.step_detector_fast_window_size,
                slow_window_size=cfg.step_detector_slow_window_size,
                lower_threshold_pct=cfg.step_detector_lower_threshold_pct,
                upper_threshold_pct=cfg.step_detector_upper_threshold_pct,
                abs_threshold=cfg.step_detector_abs_threshold,
            )
            neighbor.rtt_us = rtt_us
        if neighbor.step_detector.add_value(rtt_us):
            neighbor.rtt_us = rtt_us
            if neighbor.state == SparkNeighState.ESTABLISHED:
                self._publish_event(
                    NeighborEventType.NEIGHBOR_RTT_CHANGE, neighbor
                )

    def _publish_event(
        self, event_type: NeighborEventType, neighbor: SparkNeighbor
    ) -> None:
        event = NeighborEvent(
            event_type=event_type,
            node_name=neighbor.node_name,
            if_name=neighbor.if_name,
            remote_if_name=neighbor.remote_if_name,
            area=neighbor.area,
            neighbor_addr_v6=neighbor.transport_addr_v6,
            neighbor_addr_v4=neighbor.transport_addr_v4,
            ctrl_port=neighbor.ctrl_port,
            rtt_us=neighbor.rtt_us,
            kvstore_port=neighbor.kvstore_port,
        )
        tr = _trace.TRACE
        if tr is not None:
            # trace-context birth: a neighbor transition entering the
            # module fabric.  The root is finished immediately after the
            # push (shallow trace — downstream link-monitor work shows
            # up as the kvstore publications it causes), so it lands in
            # the ring even if no consumer adopts it.
            root = tr.root(
                "spark.neighbor_event",
                event=event_type.name,
                node=neighbor.node_name,
            )
            if root is not None:
                with tr.activate((root,)):
                    self._neighbor_updates_queue.push(event)
                tr.finish(root)
                return
        self._neighbor_updates_queue.push(event)

    # -- public API (reference: Spark.h:99-105) ------------------------------

    def flood_restarting_msg(self) -> None:
        """Announce our own graceful restart on all interfaces."""

        def _flood() -> None:
            self._restarting = True
            for if_name in self._interfaces:
                self.send_hello(if_name, restarting=True)

        self.run_in_event_base_thread(_flood).result()

    def get_neighbors(self) -> list[SparkNeighbor]:
        return self.run_in_event_base_thread(
            lambda: [
                n for by_if in self.neighbors.values() for n in by_if.values()
            ]
        ).result()

    def get_neigh_state(
        self, if_name: str, neighbor_name: str
    ) -> Optional[SparkNeighState]:
        return self.run_in_event_base_thread(
            lambda: (
                n.state
                if (n := self.neighbors.get(if_name, {}).get(neighbor_name))
                else None
            )
        ).result()
