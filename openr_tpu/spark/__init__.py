"""Spark: neighbor discovery over link-local multicast.

Functional equivalent of the reference's Spark (openr/spark/): hello /
handshake / heartbeat protocol with a 5-state per-neighbor FSM
(IDLE/WARM/NEGOTIATE/ESTABLISHED/RESTART), RTT measurement, area
negotiation, and graceful-restart support, over a mockable IoProvider.
"""

from .io_provider import IoProvider, MockIoProvider, UdpIoProvider
from .spark import Spark, SparkNeighState, SparkConfig, AreaConfig

__all__ = [
    "AreaConfig",
    "IoProvider",
    "MockIoProvider",
    "Spark",
    "SparkConfig",
    "SparkNeighState",
    "UdpIoProvider",
]
