"""IoProvider: the Spark packet transport seam.

The reference virtualizes raw socket syscalls (openr/spark/IoProvider.h:27)
so Spark I/O can be mocked.  Here the seam sits one level higher — at
message granularity — which keeps Spark itself transport-agnostic:

- `MockIoProvider` is an in-process fabric with per-link latency and
  dynamic connectivity (functional equivalent of
  openr/tests/mocks/MockIoProvider.h:41, the backbone of clusterless
  multi-node tests).
- `UdpIoProvider` sends/receives over IPv6 link-local multicast (ff02::1)
  UDP like the real daemon.
"""

from __future__ import annotations

import asyncio
import logging
import socket
import struct
import threading
import time
from dataclasses import dataclass
from typing import Optional, Protocol

log = logging.getLogger(__name__)

MCAST_GROUP = "ff02::1"
DEFAULT_UDP_PORT = 6666  # reference: Constants::kUdpPort

# SOL_SOCKET option/cmsg id for nanosecond kernel receive timestamps
# (linux: SO_TIMESTAMPNS_OLD; cmsg SCM_TIMESTAMPNS carries a timespec);
# prefer the stdlib constant where exposed — 35 is the mainstream-Linux
# value only
SO_TIMESTAMPNS = getattr(socket, "SO_TIMESTAMPNS", 35)
_TIMESPEC = struct.Struct("@qq")  # tv_sec, tv_nsec


def _realtime_us() -> int:
    """The RxPacket timestamp domain is CLOCK_REALTIME microseconds —
    the clock kernel SO_TIMESTAMPNS stamps arrive on (Spark's send
    stamps use the same clock; see spark.send_hello)."""
    return int(time.clock_gettime(time.CLOCK_REALTIME) * 1e6)


@dataclass(slots=True)
class RxPacket:
    if_name: str  # interface the packet arrived on
    data: bytes
    src_addr: str  # sender's link-local address
    recv_ts_us: int  # kernel/fabric receive timestamp (RTT measurement)


class IoProvider(Protocol):
    def attach(self, node_name: str) -> None:
        """Register this endpoint (called once by Spark)."""
        ...

    def add_interface(self, if_name: str) -> None: ...

    def remove_interface(self, if_name: str) -> None: ...

    def send(self, if_name: str, data: bytes) -> None:
        """Multicast `data` out of `if_name`."""
        ...

    async def recv(self) -> RxPacket: ...

    def close(self) -> None: ...


class MockIoProvider:
    """In-process fabric.  connect_pairs maps (nodeA, ifA) <-> (nodeB, ifB)
    with a latency; packets sent on an interface are delivered to every
    connected interface after that latency."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # (node, if) -> endpoint
        self._endpoints: dict[tuple[str, str], "_MockEndpoint"] = {}
        # (node, if) -> list of ((node, if), latency_s)
        self._links: dict[tuple[str, str], list[tuple[tuple[str, str], float]]] = {}

    def endpoint(self, node_name: str) -> "_MockEndpoint":
        return _MockEndpoint(self, node_name)

    def connect(
        self,
        node_a: str,
        if_a: str,
        node_b: str,
        if_b: str,
        latency_s: float = 0.0,
    ) -> None:
        with self._lock:
            self._links.setdefault((node_a, if_a), []).append(
                ((node_b, if_b), latency_s)
            )
            self._links.setdefault((node_b, if_b), []).append(
                ((node_a, if_a), latency_s)
            )

    def disconnect(self, node_a: str, if_a: str, node_b: str, if_b: str) -> None:
        with self._lock:
            self._links[(node_a, if_a)] = [
                l
                for l in self._links.get((node_a, if_a), [])
                if l[0] != (node_b, if_b)
            ]
            self._links[(node_b, if_b)] = [
                l
                for l in self._links.get((node_b, if_b), [])
                if l[0] != (node_a, if_a)
            ]

    def _register(self, node: str, if_name: str, ep: "_MockEndpoint") -> None:
        with self._lock:
            self._endpoints[(node, if_name)] = ep

    def _unregister(self, node: str, if_name: str) -> None:
        with self._lock:
            self._endpoints.pop((node, if_name), None)

    def _deliver(self, src: tuple[str, str], data: bytes) -> None:
        with self._lock:
            targets = [
                (self._endpoints.get(dst), dst, latency)
                for dst, latency in self._links.get(src, [])
            ]
        for ep, dst, latency in targets:
            if ep is None:
                continue
            ep._enqueue_after(latency, dst[1], data, f"fe80::{src[0]}")


class _MockEndpoint:
    """Per-node view of the mock fabric (implements IoProvider)."""

    def __init__(self, fabric: MockIoProvider, node_name: str) -> None:
        self._fabric = fabric
        self.node_name = node_name
        self._interfaces: set[str] = set()
        self._queue: asyncio.Queue[RxPacket] = asyncio.Queue()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._closed = False

    def attach(self, node_name: str) -> None:
        self.node_name = node_name
        self._loop = asyncio.get_running_loop()

    def add_interface(self, if_name: str) -> None:
        self._interfaces.add(if_name)
        self._fabric._register(self.node_name, if_name, self)

    def remove_interface(self, if_name: str) -> None:
        self._interfaces.discard(if_name)
        self._fabric._unregister(self.node_name, if_name)

    def send(self, if_name: str, data: bytes) -> None:
        if if_name in self._interfaces:
            self._fabric._deliver((self.node_name, if_name), data)

    def _enqueue_after(
        self, latency_s: float, if_name: str, data: bytes, src_addr: str
    ) -> None:
        loop = self._loop
        if loop is None or self._closed or loop.is_closed():
            return
        # stamp NOW (+ simulated wire latency), not when the receiver's
        # event loop gets around to the callback — the fabric models the
        # KERNEL timestamping point (SO_TIMESTAMPNS), so receiver-side
        # scheduler load must not inflate RTTs
        arrival_ts_us = _realtime_us() + int(latency_s * 1e6)

        def _put() -> None:
            if self._closed or if_name not in self._interfaces:
                return
            self._queue.put_nowait(
                RxPacket(
                    if_name=if_name,
                    data=data,
                    src_addr=src_addr,
                    recv_ts_us=arrival_ts_us,
                )
            )

        if latency_s > 0:
            loop.call_soon_threadsafe(lambda: loop.call_later(latency_s, _put))
        else:
            loop.call_soon_threadsafe(_put)

    async def recv(self) -> RxPacket:
        return await self._queue.get()

    def close(self) -> None:
        self._closed = True
        for if_name in list(self._interfaces):
            self.remove_interface(if_name)


class UdpIoProvider:
    """Real IPv6 link-local multicast transport.

    ONE wildcard-bound socket with IPV6_RECVPKTINFO: the kernel reports the
    arrival interface per datagram (ancillary IPV6_PKTINFO), so packets are
    attributed to the right interface — per-interface wildcard binds would
    collide (EADDRINUSE) and attribute datagrams arbitrarily.  ff02::1 is
    joined per tracked interface; sends pin the egress interface via
    sendmsg ancillary pktinfo.  Reference: openr/spark/IoProvider.h
    syscalls + SparkWrapper socket setup."""

    def __init__(self, port: int = DEFAULT_UDP_PORT) -> None:
        self.port = port
        self.send_failures = 0
        self._sock: Optional[socket.socket] = None
        self._if_index: dict[str, int] = {}  # name -> index
        self._if_name: dict[int, str] = {}  # index -> name
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._queue: asyncio.Queue[RxPacket] = asyncio.Queue()
        self.node_name = ""

    def attach(self, node_name: str) -> None:
        self.node_name = node_name
        self._loop = asyncio.get_running_loop()
        sock = socket.socket(socket.AF_INET6, socket.SOCK_DGRAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.setsockopt(socket.IPPROTO_IPV6, socket.IPV6_RECVPKTINFO, 1)
        sock.setsockopt(socket.IPPROTO_IPV6, socket.IPV6_MULTICAST_LOOP, 0)
        # kernel rx timestamps: RTTs measured from the moment the packet
        # hit the host, not when the event loop drained it (reference:
        # Spark.cpp:447-448 SO_TIMESTAMPNS + recvmsg cmsg)
        try:
            sock.setsockopt(socket.SOL_SOCKET, SO_TIMESTAMPNS, 1)
        except OSError:
            pass  # stamped in userspace below
        sock.bind(("::", self.port))
        sock.setblocking(False)
        self._sock = sock
        self._loop.add_reader(sock.fileno(), self._on_readable)

    def _on_readable(self) -> None:
        assert self._sock is not None
        while True:
            try:
                data, ancdata, _flags, addr = self._sock.recvmsg(
                    65535,
                    socket.CMSG_SPACE(20) + socket.CMSG_SPACE(_TIMESPEC.size),
                )
            except BlockingIOError:
                return
            if_index = 0
            recv_ts_us = 0
            for level, ctype, cdata in ancdata:
                if (
                    level == socket.IPPROTO_IPV6
                    and ctype == socket.IPV6_PKTINFO
                    and len(cdata) >= 20
                ):
                    if_index = struct.unpack_from("@I", cdata, 16)[0]
                elif (
                    level == socket.SOL_SOCKET
                    and ctype == SO_TIMESTAMPNS  # SCM_TIMESTAMPNS
                    and len(cdata) >= _TIMESPEC.size
                ):
                    sec, nsec = _TIMESPEC.unpack_from(cdata, 0)
                    recv_ts_us = sec * 1_000_000 + nsec // 1_000
            if_name = self._if_name.get(if_index)
            if if_name is None:
                continue  # not a tracked interface
            self._queue.put_nowait(
                RxPacket(
                    if_name=if_name,
                    data=data,
                    src_addr=addr[0],
                    recv_ts_us=recv_ts_us or _realtime_us(),
                )
            )

    def add_interface(self, if_name: str) -> None:
        if if_name in self._if_index or self._sock is None:
            return
        if_index = socket.if_nametoindex(if_name)
        mreq = socket.inet_pton(socket.AF_INET6, MCAST_GROUP) + struct.pack(
            "@I", if_index
        )
        self._sock.setsockopt(socket.IPPROTO_IPV6, socket.IPV6_JOIN_GROUP, mreq)
        self._if_index[if_name] = if_index
        self._if_name[if_index] = if_name

    def remove_interface(self, if_name: str) -> None:
        if_index = self._if_index.pop(if_name, None)
        if if_index is None or self._sock is None:
            return
        self._if_name.pop(if_index, None)
        mreq = socket.inet_pton(socket.AF_INET6, MCAST_GROUP) + struct.pack(
            "@I", if_index
        )
        try:
            self._sock.setsockopt(
                socket.IPPROTO_IPV6, socket.IPV6_LEAVE_GROUP, mreq
            )
        except OSError:
            pass

    def send(self, if_name: str, data: bytes) -> None:
        if_index = self._if_index.get(if_name)
        if if_index is None or self._sock is None:
            return
        try:
            self._sock.sendto(data, (MCAST_GROUP, self.port, 0, if_index))
        except OSError as exc:
            # transient interface conditions (IPv6 DAD still running,
            # link-down race) make multicast sends fail with
            # EADDRNOTAVAIL/ENETDOWN; a raised send would unwind Spark's
            # timer callback and permanently stop the hello chain.  The
            # reference IoProvider surfaces errno and Spark logs+continues
            # (the next periodic hello retries) — match that.
            self.send_failures += 1
            if self.send_failures % 16 == 1:  # rate-limited: DAD spams
                log.warning(
                    "spark udp send on %s failing (%d so far): %s",
                    if_name,
                    self.send_failures,
                    exc,
                )

    async def recv(self) -> RxPacket:
        return await self._queue.get()

    def close(self) -> None:
        if self._sock is not None:
            if self._loop is not None and not self._loop.is_closed():
                try:
                    self._loop.remove_reader(self._sock.fileno())
                except (ValueError, OSError):
                    pass
            self._sock.close()
            self._sock = None
        self._if_index.clear()
        self._if_name.clear()
