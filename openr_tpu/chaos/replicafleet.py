"""Seeded replica-fleet chaos: kill, restart, and partition replicas
behind a ReplicaRouter under open-loop load.

A ReplicaFleetController builds K scheduler replicas (each an
`EngineBatchBackend` over its own LinkState mirror, fed the identical
update stream — the in-process stand-in for KvStore full-mesh
replication) behind one `serving.ReplicaRouter`, then replays a
deterministic fault schedule against them while `OpenLoopLoadGen`
drives session-pinned queries through the front door:

- **kill/restart** — a replica's handle starts refusing traffic
  (`ReplicaUnavailableError`) and its scheduler stops mid-burst, so
  in-flight queries shed there and re-route; restart brings a fresh
  scheduler up over the same mirror and the router's liveness probe
  revives it.
- **partition** — the handle is unreachable AND stops receiving
  topology updates, so on heal it is both revived and behind (the
  epoch-lag case, not just the dead case).
- **scripted lag** — one replica is held a round behind on purpose,
  then a pinned session is marched across the fleet: round-robin is
  guaranteed to land it on the lagged replica, whose stale answer the
  router must re-route (`serving.router.epoch_reroutes`), never
  deliver.

Every scripted action is logged through ChaosScenario into the shared
ChaosEventLog scenario stream, so two runs from the same seed replay
bit-for-bit (`ChaosEventLog.matches`) — reply counts and retry counts
are timing-dependent on a loaded box and are deliberately NOT logged.
Correctness is judged per reply against a host Dijkstra oracle cached
at every epoch the truth topology ever occupied: an answer is only
right if it is bit-exact *at the epoch it claims* (`QueryResult.epoch`),
which is what makes cross-replica consistency checkable rather than
hoped-for.
"""

from __future__ import annotations

import concurrent.futures
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ..decision.link_state import LinkState
from ..serving import (
    EngineBatchBackend,
    QueryScheduler,
    QueryShedError,
    ReplicaRouter,
    ReplicaUnavailableError,
)
from ..types import AdjacencyDatabase
from .chaos import ChaosEventLog
from .flapstorm import _adj, _base_metric
from .overload import LoadReport, OpenLoopLoadGen
from .scenario import ChaosScenario

_RING_OFFSETS = (1, -1, 2, -2)
_WORSE_METRIC = 70


class ChaosReplicaHandle:
    """Router-facing replica handle with kill/partition fault flags.

    `killed`/`partitioned` make `submit` resolve to
    `ReplicaUnavailableError` (the async shape a dead connection has)
    and make the `epoch` liveness probe raise, so the router sees the
    same failure surface a real dead/unreachable daemon would present.
    `get_counters` stays readable — the post-mortem ledger survives the
    fault, like a metrics store would.
    """

    def __init__(self, name: str, scheduler, ls: LinkState) -> None:
        self.name = name
        self.scheduler = scheduler
        self.ls = ls
        self.killed = False
        self.partitioned = False
        self.applied = 0  # index into the fleet's update stream

    def submit(self, op: str, **kw) -> "concurrent.futures.Future":
        if self.killed or self.partitioned:
            fut: "concurrent.futures.Future" = concurrent.futures.Future()
            fut.set_exception(
                ReplicaUnavailableError(f"{self.name} unreachable")
            )
            return fut
        return self.scheduler.submit(op, **kw)

    def epoch(self, area: str = "0") -> int:
        if self.killed or self.partitioned:
            raise ReplicaUnavailableError(f"{self.name} unreachable")
        return int(self.ls.version)

    def get_counters(self) -> dict:
        return self.scheduler.get_counters()


@dataclass
class ReplicaFleetResult:
    rounds: int
    submitted: int  # open-loop + scripted pin-segment queries
    replied: int
    shed: int
    errors: int
    bit_exact: bool  # every reply exact vs the oracle AT ITS EPOCH
    mismatches: int
    unknown_epochs: int  # replies claiming an epoch the truth never had
    pin_violations: int  # per-session epoch regressions (must be 0)
    ledger_ok: bool  # router counters reconcile with the load report
    epochs_served: list = field(default_factory=list)
    counters: dict = field(default_factory=dict)

    @property
    def accounted(self) -> int:
        return self.replied + self.shed + self.errors


class ReplicaFleetController:
    """Replayable kill/restart/partition schedule over a replica fleet."""

    def __init__(
        self,
        seed: int = 0,
        n: int = 16,
        replicas: int = 3,
        rounds: int = 8,
        clients: int = 8,
        per_client: int = 7,
        kill_round: int = 2,
        restart_round: int = 4,
        partition_round: int = 5,
        heal_round: int = 6,
        lag_rounds: tuple = (3, 6),
        scaleout_round: Optional[int] = None,
        scalein_round: Optional[int] = None,
        hedge_after_s: Optional[float] = 0.02,
        spf_backend=None,
        log_: Optional[ChaosEventLog] = None,
    ) -> None:
        self.seed = int(seed)
        self.n = int(n)
        self.replicas = int(replicas)
        self.rounds = int(rounds)
        self.clients = int(clients)
        self.per_client = int(per_client)
        self.kill_round = kill_round
        self.restart_round = restart_round
        self.partition_round = partition_round
        self.heal_round = heal_round
        self.lag_rounds = tuple(lag_rounds)
        # elastic membership schedule (None keeps legacy timelines
        # byte-identical): scale-out joins a snapshot-warm-started
        # replica mid-burst; scale-in removes and kills the youngest
        # JOINED replica (never a scripted fault target)
        self.scaleout_round = scaleout_round
        self.scalein_round = scalein_round
        self.hedge_after_s = hedge_after_s
        self.spf_backend = spf_backend
        self.log = log_ if log_ is not None else ChaosEventLog()
        self.scenario = ChaosScenario(self.log)
        # fault targets (deterministic): kill replica 1, partition the
        # last, lag replica 0 — disjoint for K >= 3, clamped below it
        self.kill_idx = 1 % self.replicas
        self.partition_idx = (self.replicas - 1) % self.replicas
        self.lag_idx = 0
        self._minted = self.replicas  # next replica name index
        self._joined: list[ChaosReplicaHandle] = []

    # -- topology --------------------------------------------------------------

    def _name(self, i: int) -> str:
        return f"f{i % self.n:03d}"

    def _node_db(self, i: int, flapped: dict) -> AdjacencyDatabase:
        me = self._name(i)
        adjs = []
        for d in _RING_OFFSETS:
            j = (i + d) % self.n
            metric = _base_metric(i, j)
            if d == 1 and i in flapped:
                metric = flapped[i]
            adjs.append(_adj(me, self._name(j), metric))
        return AdjacencyDatabase(
            this_node_name=me,
            adjacencies=adjs,
            is_overloaded=False,
            node_label=0,
            area="0",
        )

    def _build_ls(self) -> LinkState:
        ls = LinkState("0")
        for i in range(self.n):
            ls.update_adjacency_database(self._node_db(i, {}))
        return ls

    # -- oracle ----------------------------------------------------------------

    def _cache_oracle(self, truth: LinkState, oracle: dict) -> None:
        """Snapshot {src: {dest: (metric, next_hops)}} for every source
        at the truth's CURRENT epoch.  Replies are later judged against
        the snapshot matching their claimed epoch."""
        epoch = int(truth.version)
        if epoch in oracle:
            return
        snap = {}
        for src in truth.node_names:
            res = truth.run_spf(src)
            snap[src] = {
                dest: (entry.metric, frozenset(entry.next_hops))
                for dest, entry in res.items()
            }
        oracle[epoch] = snap

    # -- fleet plumbing ----------------------------------------------------------

    def _catch_up(self, handle: ChaosReplicaHandle, updates: list) -> None:
        for db in updates[handle.applied :]:
            handle.ls.update_adjacency_database(db)
        handle.applied = len(updates)

    def _kill(self, handle: ChaosReplicaHandle) -> None:
        handle.killed = True
        # the dying process takes its in-flight work down loudly: the
        # scheduler's stop() resolves every inflight future (shed), and
        # the router re-routes each one
        handle.scheduler.stop()

    def _restart(self, handle: ChaosReplicaHandle, updates: list) -> None:
        backend = handle.scheduler.backend
        handle.scheduler = QueryScheduler(backend)
        handle.scheduler.run()
        self._catch_up(handle, updates)
        handle.killed = False

    def _scale_out_prepare(self, handles: list, updates: list):
        """Build and warm-start the joining replica while the fleet is
        quiescent (between bursts): built like the initial fleet, caught
        up on the update stream, snapshot-warm-started from replica 0's
        device engine (install or accounted cold — see openr_tpu/
        snapshot).  Quiescence is load-bearing for the replay contract:
        the donor engine is not mid-dispatch, so the restore mode is a
        pure function of the seed's update stream (same stream -> same
        mirror content -> same rung).  Returns (handle, mode); the
        router join itself happens mid-burst in _scale_out_join."""
        i = self._minted
        self._minted += 1
        ls = self._build_ls()
        backend = EngineBatchBackend({"0": ls}, spf_backend=self.spf_backend)
        sched = QueryScheduler(backend)
        sched.run()
        handle = ChaosReplicaHandle(f"replica-{i}", sched, ls)
        self._catch_up(handle, updates)
        mode = "skipped"
        donor = handles[0].scheduler.backend
        d_spf = getattr(donor, "spf", None)
        j_spf = backend.spf
        # a shared spf_backend means one engine and one mirror cache —
        # nothing to warm-start across
        if (
            hasattr(d_spf, "csr_mirror")
            and hasattr(j_spf, "csr_mirror")
            and d_spf is not j_spf
        ):
            try:
                from ..snapshot import EngineSnapshot

                snap = EngineSnapshot.take(
                    d_spf.engine, d_spf.csr_mirror(handles[0].ls)
                )
                mode = snap.restore(j_spf.engine, j_spf.csr_mirror(ls))
            except Exception:  # noqa: BLE001 — warm start is best-effort
                mode = "skipped"
        return handle, mode

    def _scale_out_join(self, handles: list, router, handle) -> None:
        """Add the prepared replica to the live router mid-burst: the
        membership swap and the dispatch-ledger extension are what the
        join exercises under load."""
        handles.append(handle)
        self._joined.append(handle)
        router.add_replica(handle)

    def _scale_in(self, handles: list, router) -> Optional[str]:
        """Remove and kill the youngest joined replica under load: the
        router stops picking it immediately and folds its final counters,
        then its scheduler dies loudly (in-flight work sheds and
        re-routes).  Scripted fault targets are never scale-in victims,
        so the kill/partition/lag schedule stays index-stable."""
        if not self._joined:
            return None
        handle = self._joined.pop()
        handles.remove(handle)
        router.remove_replica(handle.name)
        handle.killed = True
        handle.scheduler.stop()
        return handle.name

    # -- run ---------------------------------------------------------------------

    def run(self) -> ReplicaFleetResult:
        rng = random.Random(self.seed)
        sc = self.scenario

        truth = self._build_ls()
        updates: list[AdjacencyDatabase] = []
        flapped: dict[int, int] = {}
        handles: list[ChaosReplicaHandle] = []
        for i in range(self.replicas):
            ls = self._build_ls()
            backend = EngineBatchBackend(
                {"0": ls}, spf_backend=self.spf_backend
            )
            sched = QueryScheduler(backend)
            sched.run()
            handles.append(ChaosReplicaHandle(f"replica-{i}", sched, ls))
        assert all(h.ls.version == truth.version for h in handles)

        router = ReplicaRouter(handles, hedge_after_s=self.hedge_after_s)
        router.pin_trace = []

        oracle: dict[int, dict] = {}
        self._cache_oracle(truth, oracle)

        # reply-side accounting shared by the open-loop generator and
        # the scripted pin segment
        check_lock = threading.Lock()
        acct = {
            "mismatches": 0,
            "unknown_epochs": 0,
            "epochs": set(),
            "manual_submitted": 0,
            "manual_replied": 0,
            "manual_shed": 0,
            "manual_errors": 0,
        }

        def check_reply(meta, res) -> None:
            op, src, _session = meta
            if op != "paths":
                return
            with check_lock:
                acct["epochs"].add(int(res.epoch))
                snap = oracle.get(int(res.epoch))
                if snap is None:
                    acct["unknown_epochs"] += 1
                    return
                got = res.value.get(src)
                want = snap.get(src, {})
                got_view = (
                    {}
                    if got is None
                    else {
                        dest: (entry.metric, frozenset(entry.next_hops))
                        for dest, entry in got.items()
                    }
                )
                if got_view != want:
                    acct["mismatches"] += 1

        sc.step(
            f"fleet:init:n={self.n}:replicas={self.replicas}"
            f":epoch={truth.version}"
        )
        loadgen = OpenLoopLoadGen(
            router,
            truth.node_names,
            seed=self.seed,
            clients=self.clients,
            sessions=True,
            on_reply=check_reply,
        )
        reports: list[LoadReport] = []

        def run_burst(r: int, concurrent_fault=None) -> None:
            """One open-loop burst; `concurrent_fault` (if any) runs
            mid-burst on the controller thread, so its scripted step
            keeps a deterministic position in the event log."""
            sc.step(f"fleet:burst:{r}:clients={self.clients}"
                    f":per_client={self.per_client}")
            if concurrent_fault is None:
                reports.append(loadgen.run_burst(self.per_client))
            else:
                box: dict = {}

                def _bg() -> None:
                    box["report"] = loadgen.run_burst(self.per_client)

                t = threading.Thread(target=_bg, name=f"fleet-burst-{r}")
                t.start()
                concurrent_fault()
                t.join()
                reports.append(box["report"])
            sc.step(f"fleet:burst:{r}:done")

        def manual_query(src: str, session: str):
            acct["manual_submitted"] += 1
            fut = router.submit("paths", sources=(src,), session=session)
            try:
                res = fut.result(timeout=30)
            except QueryShedError:
                acct["manual_shed"] += 1
                return None
            except concurrent.futures.TimeoutError:
                # an unresolved future IS a silent drop: leave it
                # unaccounted so accounted == submitted fails loudly
                return None
            except Exception:  # noqa: BLE001
                acct["manual_errors"] += 1
                return None
            acct["manual_replied"] += 1
            check_reply(("paths", src, session), res)
            return res

        pin_seq = 0

        def pin_segment() -> None:
            """Deterministic epoch-reroute forcing: pin a session at the
            fleet-head epoch, then march it around the round-robin until
            it lands on the lagged replica — whose stale answer must be
            re-routed, never delivered."""
            nonlocal pin_seq
            head = int(truth.version)
            session = f"pin-{pin_seq}"
            pin_seq += 1
            src = truth.node_names[0]
            sc.step(f"fleet:pin:{session}:epoch={head}")
            k = len(handles)
            for _ in range(4 * k):
                res = manual_query(src, session)
                if res is not None and int(res.epoch) >= head:
                    break
            for _ in range(3 * k):
                manual_query(src, session)
            sc.step(f"fleet:pin:{session}:done")

        for r in range(self.rounds):
            # scripted faults first, in a deterministic order
            if r == self.restart_round:
                sc.step(f"fleet:restart:replica-{self.kill_idx}:{r}")
                self._restart(handles[self.kill_idx], updates)
                router.probe_replicas()
            if r == self.heal_round:
                sc.step(f"fleet:heal:replica-{self.partition_idx}:{r}")
                h = handles[self.partition_idx]
                h.partitioned = False
                self._catch_up(h, updates)
                router.probe_replicas()
            if r == self.partition_round:
                sc.step(f"fleet:partition:replica-{self.partition_idx}:{r}")
                handles[self.partition_idx].partitioned = True
            if r == self.scalein_round:
                gone = self._scale_in(handles, router)
                sc.step(f"fleet:scalein:{gone or 'noop'}:{r}")

            # one topology flap per round: exactly one epoch bump, so
            # every epoch the fleet can answer at has an oracle snapshot
            node = rng.randrange(self.n)
            if node in flapped:
                del flapped[node]
                sc.step(f"fleet:flap:{r}:{node}:restore")
            else:
                flapped[node] = _WORSE_METRIC
                sc.step(f"fleet:flap:{r}:{node}:worsen")
            db = self._node_db(node, flapped)
            truth.update_adjacency_database(db)
            updates.append(db)
            self._cache_oracle(truth, oracle)

            # replicate, holding back the lagged / unreachable replicas
            lagging = r in self.lag_rounds
            for i, h in enumerate(handles):
                if h.killed or h.partitioned:
                    continue
                if lagging and i == self.lag_idx:
                    continue
                self._catch_up(h, updates)
            if lagging:
                sc.step(f"fleet:lag:replica-{self.lag_idx}:{r}")

            if r == self.kill_round:

                def kill_mid_burst(r=r) -> None:
                    # let some of the burst land in the victim's queue
                    # first, so in-flight shed-and-re-route is exercised
                    # alongside the fail-fast path for later submissions
                    time.sleep(0.05)
                    sc.step(
                        f"fleet:kill:replica-{self.kill_idx}:{r}",
                        lambda: self._kill(handles[self.kill_idx]),
                    )

                run_burst(r, concurrent_fault=kill_mid_burst)
            elif r == self.scaleout_round:
                # warm-start on a quiescent fleet: the donor engine is
                # not mid-dispatch, so the restore mode is a pure
                # function of the seed's update stream — which makes it
                # part of the replay contract
                joiner, mode = self._scale_out_prepare(handles, updates)
                sc.step(f"fleet:scaleout:{r}:{mode}")

                def scaleout_mid_burst(r=r, joiner=joiner) -> None:
                    # let the burst saturate the old fleet first, so the
                    # router join really happens under load
                    time.sleep(0.05)
                    sc.step(
                        f"fleet:scaleout:join:{r}",
                        lambda: self._scale_out_join(handles, router, joiner),
                    )

                run_burst(r, concurrent_fault=scaleout_mid_burst)
            else:
                run_burst(r)

            if lagging:
                pin_segment()
                self._catch_up(handles[self.lag_idx], updates)
                sc.step(f"fleet:lag:replica-{self.lag_idx}:{r}:caught_up")

        # settle: everyone reachable catches up; final burst on a
        # healthy fleet
        sc.step("fleet:settle")
        router.probe_replicas()
        for h in handles:
            if not h.killed and not h.partitioned:
                self._catch_up(h, updates)
        run_burst(self.rounds)

        # stop the fleet BEFORE reading the ledger: scheduler stop()
        # joins the executor threads, so every router callback (and its
        # counter bumps) has finished when the counters are read
        router.stop()
        for h in handles:
            if not h.killed:
                h.scheduler.stop()
        counters = router.get_counters()

        submitted = sum(rep.submitted for rep in reports) + acct[
            "manual_submitted"
        ]
        replied = sum(rep.replied for rep in reports) + acct["manual_replied"]
        shed = sum(rep.shed for rep in reports) + acct["manual_shed"]
        errors = sum(rep.errors for rep in reports) + acct["manual_errors"]

        # per-session monotonicity, in acceptance order (the router's
        # pin_trace is appended under its lock at each accepted reply)
        pin_violations = 0
        last: dict = {}
        for session, epoch in router.pin_trace:
            if epoch < last.get(session, -1):
                pin_violations += 1
            last[session] = epoch

        # dispatch ledger: first dispatches are the non-shed submissions,
        # and every re-dispatch is in exactly one named bucket
        from ..serving.router import dispatch_ledger_closes

        ledger_ok = dispatch_ledger_closes(counters, submitted)

        bit_exact = (
            acct["mismatches"] == 0 and acct["unknown_epochs"] == 0
        )
        sc.step(f"fleet:settled:{'exact' if bit_exact else 'DIVERGED'}")
        return ReplicaFleetResult(
            rounds=self.rounds,
            submitted=submitted,
            replied=replied,
            shed=shed,
            errors=errors,
            bit_exact=bit_exact,
            mismatches=acct["mismatches"],
            unknown_epochs=acct["unknown_epochs"],
            pin_violations=pin_violations,
            ledger_ok=ledger_ok,
            epochs_served=sorted(acct["epochs"]),
            counters=counters,
        )
