"""Seeded flap-storm scenario for the incremental delta SPF rung.

A FlapStormScenario replays a deterministic 1k-event link-flap sequence
(metric worsen/restore + adjacency down/up on a small set of flappy
links) against an engine-backed FleetViewCache(delta=True), coalescing
each chunk of pending events into ONE delta rebuild.  The storm proves
the tentpole's serving claims end to end:

- every event is recorded in the ChaosEventLog scenario stream, so two
  runs from the same seed replay bit-for-bit (ChaosEventLog.matches);
- the post-storm product must be bit-exact against a cold host-oracle
  rebuild of the final snapshot (a fresh, engine-less FleetViewCache);
- the engine's ``full_restages`` must stay at 1 — the initial upload —
  because every chunk lands through the donated delta programs.

The topology is WAN-shaped on purpose: a ring with +-1/+-2 local links
plus +-16 chord bands, under deterministic per-direction ASYMMETRIC
metrics (hashed, stable across rebuilds).  Heterogeneous metrics kill
the ECMP permutation ties of a uniform ring — with unique path costs
each link is tight toward a bounded set of destinations instead of
half of everything.  The labeled destinations form a CLUSTER on the
arc of the ring opposite the flappy links: traffic toward the cluster
funnels through the chord bands, so the flapped local links carry
almost none of it and the support-loss frontier of a whole chunk of
coalesced events stays far below the bucket-ladder overflow bound —
exactly the regime the delta rung is built for.  Storms that flap
links serving a large destination share (uniform metrics, or labels
spread across the whole ring) genuinely change a large fraction of the
columns; those overflow the frontier bound and take the bit-exact
full-product fallback instead — that path is covered by
tests/test_delta.py.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Optional

from ..decision.fleet import FleetViewCache, fleet_destinations
from ..decision.link_state import LinkState
from ..decision.prefix_state import PrefixState
from ..device.engine import DeviceResidencyEngine
from ..types import Adjacency, AdjacencyDatabase, PrefixEntry
from .chaos import SCENARIO_STREAM, ChaosEventLog

_WORSE_METRIC = 90
_KINDS = ("worsen", "restore", "down", "up")
_OFFSETS = (1, -1, 2, -2, 16, -16)


def _base_metric(i: int, j: int) -> int:
    """Deterministic per-direction metric in 1..10 — WAN-style
    heterogeneous weights, stable across scenario and oracle builds."""
    return 1 + (i * 2654435761 + j * 40503) % 10


def _adj(me: str, other: str, metric: int) -> Adjacency:
    return Adjacency(
        other_node_name=other,
        if_name=f"{me}/{other}",
        other_if_name=f"{other}/{me}",
        metric=metric,
        next_hop_v6=f"fe80::{other}",
        next_hop_v4=f"10.0.0.1",
    )


@dataclass
class FlapStormResult:
    events: int
    chunks: int
    delta_updates: int
    delta_noops: int
    delta_fallbacks: int
    delta_dispatches: int
    full_restages: int
    bit_exact: bool
    chunk_modes: list = field(default_factory=list)
    counters: dict = field(default_factory=dict)


class FlapStormScenario:
    """Replayable flap storm over a labeled ring through the delta rung."""

    def __init__(
        self,
        seed: int = 0,
        n: int = 128,
        flappy_links: int = 4,
        events: int = 1000,
        chunks: int = 4,
        log_: Optional[ChaosEventLog] = None,
    ) -> None:
        assert events % chunks == 0
        self.seed = seed
        self.n = n
        self.events = events
        self.chunks = chunks
        self.log = log_ if log_ is not None else ChaosEventLog()
        # fixed flappy set: the +1 out-edge of `flappy_links` adjacent
        # even nodes — clustered so the event frontiers overlap, and on
        # the arc opposite the labeled destination cluster so the
        # flapped links carry almost no destination-bound traffic
        self.flappy = tuple(2 * i for i in range(flappy_links))
        # labeled destination cluster: the far arc [n/2, n - n/8)
        self.label_lo = n // 2
        self.label_hi = n - n // 8

    # -- topology ------------------------------------------------------------

    def _name(self, i: int) -> str:
        return f"c{i % self.n:03d}"

    def _node_db(self, i: int, state: dict) -> AdjacencyDatabase:
        me = self._name(i)
        st = state.get(i, {"metric": None, "up": True})
        adjs = []
        for d in _OFFSETS:
            j = (i + d) % self.n
            metric = _base_metric(i, j)
            if d == 1 and i in state:
                if not st["up"]:
                    continue
                if st["metric"] is not None:
                    metric = st["metric"]
            adjs.append(_adj(me, self._name(i + d), metric))
        labeled = self.label_lo <= (i % self.n) < self.label_hi
        return AdjacencyDatabase(
            this_node_name=me,
            adjacencies=adjs,
            is_overloaded=False,
            node_label=1000 + i if labeled else 0,
            area="0",
        )

    def _build_ls(self, state: dict) -> LinkState:
        ls = LinkState("0")
        for i in range(self.n):
            ls.update_adjacency_database(self._node_db(i, state))
        return ls

    def _prefix_state(self) -> PrefixState:
        ps = PrefixState()
        ps.update_prefix(
            self._name(self.label_lo), "0", PrefixEntry(prefix="::1:0/112")
        )
        ps.update_prefix(
            self._name(self.label_hi - 1),
            "0",
            PrefixEntry(prefix="::2:0/112"),
        )
        return ps

    # -- storm ---------------------------------------------------------------

    def run(self) -> FlapStormResult:
        rng = random.Random(self.seed)
        counters: dict[str, int] = {}

        def bump(name: str, delta: int = 1) -> None:
            counters[name] = counters.get(name, 0) + delta

        state: dict[int, dict] = {}
        ls = self._build_ls(state)
        ps = self._prefix_state()
        dests = fleet_destinations(ls, ps)
        engine = DeviceResidencyEngine()
        cache = FleetViewCache(delta=True, bump=bump)

        self.log.append(SCENARIO_STREAM, f"storm:init:n={self.n}")
        view = cache.view(ls, dests, engine=engine)
        # account the one-and-only full upload of the resident product
        engine.delta_register(
            view._dist_dev.nbytes + view._bitmap_dev.nbytes
        )

        chunk_modes = []
        per_chunk = self.events // self.chunks
        for c in range(self.chunks):
            for _ in range(per_chunk):
                node = self.flappy[rng.randrange(len(self.flappy))]
                kind = _KINDS[rng.randrange(len(_KINDS))]
                st = state.setdefault(node, {"metric": None, "up": True})
                if kind == "worsen":
                    st["metric"] = _WORSE_METRIC
                elif kind == "restore":
                    st["metric"] = None
                elif kind == "down":
                    st["up"] = False
                else:
                    st["up"] = True
                ls.update_adjacency_database(self._node_db(node, state))
                self.log.append(SCENARIO_STREAM, f"flap:{node}:{kind}")
            # the chunk's k pending events coalesce into ONE rebuild
            view = cache.view(ls, dests, engine=engine)
            chunk_modes.append(view.warm_mode)
            self.log.append(
                SCENARIO_STREAM, f"chunk:{c}:{view.warm_mode}"
            )

        # post-storm convergence: bit-exact against a cold host-oracle
        # rebuild of the final snapshot on a fresh, engine-less cache
        import numpy as np

        oracle = FleetViewCache().view(self._build_ls(state), dests)
        bit_exact = bool(
            np.array_equal(
                np.asarray(view._dist_dev), np.asarray(oracle._dist_dev)
            )
            and np.array_equal(
                np.asarray(view._bitmap_dev),
                np.asarray(oracle._bitmap_dev),
            )
        )
        self.log.append(
            SCENARIO_STREAM,
            f"storm:settled:{'exact' if bit_exact else 'DIVERGED'}",
        )
        return FlapStormResult(
            events=self.events,
            chunks=self.chunks,
            delta_updates=counters.get("decision.delta.updates", 0),
            delta_noops=counters.get("decision.delta.noop_updates", 0),
            delta_fallbacks=counters.get("decision.delta.fallbacks", 0),
            delta_dispatches=engine.counters[
                "device.engine.delta_dispatches"
            ],
            full_restages=engine.counters["device.engine.full_restages"],
            bit_exact=bit_exact,
            chunk_modes=chunk_modes,
            counters=counters,
        )
