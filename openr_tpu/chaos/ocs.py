"""Seeded OCS reconfiguration chaos scenario for the rewire rung.

An OcsController replays a deterministic schedule of rolling edge-set
swaps — the event stream an optical-circuit-switch fabric emits when it
reprograms its logical topology — against one persistent CsrTopology
mirror and DeviceResidencyEngine, interleaved with attribute metric
flaps and one armed mid-rewire device fault.  The scenario proves the
tentpole's robustness claims end to end:

- every action is recorded through ChaosScenario into the shared
  ChaosEventLog scenario stream, so two runs from the same seed replay
  bit-for-bit (ChaosEventLog.matches);
- every post-rewire SPF product is bit-exact against the host Dijkstra
  oracle (LinkState.run_spf), and the post-heal all-sources sweep is
  asserted the same way — the oracle cannot be perturbed by the chaos
  under test;
- bounded rewires ride the engine's masked-write rewire rung (one full
  restage for the initial upload), while the injected mid-rewire fault
  must demote cleanly to a second restage with `rewire_fallbacks`
  accounted — the degradation ladder, not an error.

The topology is WAN-shaped: a ring with +-1/+-2 local links under the
flap-storm's deterministic asymmetric metrics, plus a reprogrammable
chord matching (every node starts with exactly one chord, so every ELL
row is built with headroom for the chord churn that follows).  Chord
swaps are capacity-bounded by construction — per-node chord degree is
capped — so the schedule never trips the rebuild fallback except where
the scenario injects one on purpose.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Optional

from ..decision.csr import CsrTopology
from ..decision.link_state import LinkState
from ..device.engine import DeviceResidencyEngine
from ..types import Adjacency, AdjacencyDatabase
from .chaos import ChaosEventLog
from .flapstorm import _adj, _base_metric
from .scenario import ChaosScenario

_RING_OFFSETS = (1, -1, 2, -2)
_WORSE_METRIC = 70
# per-node chord-degree cap: ring in-degree 4 + 1 build-time chord puts
# every ELL row in the K=8 bucket, so up to 4 chords per node re-encode
# in place; the cap stays one under that for slack
_CHORD_DEG_CAP = 3


@dataclass
class OcsRewireResult:
    rounds: int
    rewires: int  # deltas applied on device
    rewire_dispatches: int
    rewire_fallbacks: int
    full_restages: int
    flaps: int
    links_swapped: int
    bit_exact: bool  # every round AND the post-heal sweep
    round_exact: list = field(default_factory=list)
    counters: dict = field(default_factory=dict)


class OcsController:
    """Replayable rolling-rewire schedule over a chorded WAN ring."""

    def __init__(
        self,
        seed: int = 0,
        n: int = 32,
        rounds: int = 12,
        swaps_per_round: int = 2,
        flaps_per_round: int = 2,
        fault_round: Optional[int] = None,
        log_: Optional[ChaosEventLog] = None,
    ) -> None:
        self.seed = seed
        self.n = n
        self.rounds = rounds
        self.swaps_per_round = swaps_per_round
        self.flaps_per_round = flaps_per_round
        # arm the mid-rewire device fault at this round (-1: never;
        # None: mid-schedule, so healthy rewires surround the demotion)
        self.fault_round = (
            fault_round if fault_round is not None else rounds // 2
        )
        self.log = log_ if log_ is not None else ChaosEventLog()
        self.scenario = ChaosScenario(self.log)

    # -- topology ------------------------------------------------------------

    def _name(self, i: int) -> str:
        return f"w{i % self.n:03d}"

    def _chord_metric(self, i: int, j: int) -> int:
        return 3 + (i * 40503 + j * 2654435761) % 7

    def _node_db(
        self, i: int, chords: set, flapped: dict
    ) -> AdjacencyDatabase:
        me = self._name(i)
        adjs = []
        for d in _RING_OFFSETS:
            j = (i + d) % self.n
            metric = _base_metric(i, j)
            if d == 1 and i in flapped:
                metric = flapped[i]
            adjs.append(_adj(me, self._name(j), metric))
        for a, b in sorted(chords):
            if i == a or i == b:
                j = b if i == a else a
                adjs.append(
                    _adj(me, self._name(j), self._chord_metric(a, b))
                )
        return AdjacencyDatabase(
            this_node_name=me,
            adjacencies=adjs,
            is_overloaded=False,
            node_label=0,
            area="0",
        )

    def _initial_chords(self) -> set:
        # perfect matching i <-> i + n/2: one chord per node
        return {(i, i + self.n // 2) for i in range(self.n // 2)}

    def _push(self, ls: LinkState, chords: set, flapped: dict) -> None:
        for i in range(self.n):
            ls.update_adjacency_database(self._node_db(i, chords, flapped))

    def _build_ls(self, chords: set, flapped: dict) -> LinkState:
        ls = LinkState("0")
        self._push(ls, chords, flapped)
        return ls

    def _chord_candidates(self, chords: set) -> list:
        deg: dict[int, int] = {}
        for a, b in chords:
            deg[a] = deg.get(a, 0) + 1
            deg[b] = deg.get(b, 0) + 1
        out = []
        for a in range(self.n):
            for b in range(a + 2, self.n):
                if (a, b) in chords or (a == 0 and b == self.n - 1):
                    continue  # existing chord / ring edge
                if b - a in (1, 2) or self.n - (b - a) in (1, 2):
                    continue  # ring +-1/+-2 edge
                if (
                    deg.get(a, 0) >= _CHORD_DEG_CAP
                    or deg.get(b, 0) >= _CHORD_DEG_CAP
                ):
                    continue
                out.append((a, b))
        return out

    # -- schedule ------------------------------------------------------------

    def run(self) -> OcsRewireResult:
        rng = random.Random(self.seed)
        sc = self.scenario
        chords = self._initial_chords()
        flapped: dict[int, int] = {}

        ls = self._build_ls(chords, flapped)
        sc.step(f"ocs:init:n={self.n}:chords={len(chords)}")
        csr = CsrTopology.from_link_state(ls)
        engine = DeviceResidencyEngine()
        names = ls.node_names

        fault = {"armed": False, "fired": 0}

        def fault_hook(op: str) -> None:
            if op == "rewire" and fault["armed"]:
                fault["armed"] = False
                fault["fired"] += 1
                raise RuntimeError("ocs: injected mid-rewire device fault")

        engine.fault_hook = fault_hook

        def query_exact(round_no: int) -> bool:
            sources = [
                names[(round_no * 7 + k) % self.n] for k in range(3)
            ]
            got = engine.spf_results(csr, sources)
            for s in sources:
                oracle = ls.run_spf(s)
                res = got[s]
                if {k: v.metric for k, v in oracle.items()} != {
                    k: v.metric for k, v in res.items()
                }:
                    return False
                for node in oracle:
                    if oracle[node].next_hops != res[node].next_hops:
                        return False
            return True

        # first contact: the one legitimate full staging
        round_exact = [query_exact(0)]
        links_swapped = flaps = 0

        for r in range(self.rounds):
            # rolling swaps: retire + program `swaps_per_round` circuits
            for _ in range(self.swaps_per_round):
                victim = rng.choice(sorted(chords))
                chords.discard(victim)
                fresh = rng.choice(self._chord_candidates(chords))
                chords.add(fresh)
                links_swapped += 1
                sc.step(
                    f"ocs:swap:{r}:{victim[0]}-{victim[1]}"
                    f"->{fresh[0]}-{fresh[1]}"
                )
            # interleaved attribute flaps on ring +1 links
            for _ in range(self.flaps_per_round):
                node = rng.randrange(self.n)
                if node in flapped:
                    del flapped[node]
                    sc.step(f"ocs:flap:{r}:{node}:restore")
                else:
                    flapped[node] = _WORSE_METRIC
                    sc.step(f"ocs:flap:{r}:{node}:worsen")
                flaps += 1
            if r == self.fault_round:
                fault["armed"] = True
                sc.step(f"ocs:fault:armed:{r}")
            self._push(ls, chords, flapped)
            rewired = csr.refresh(ls)
            sc.step(f"ocs:refresh:{r}:{'rewire' if rewired else 'rebuild'}")
            round_exact.append(query_exact(r + 1))
            if fault["fired"] and not fault["armed"]:
                # observable demotion: log once, the round after firing
                sc.step(f"ocs:fault:fired:{r}")
                fault["fired"] = 0

        # heal: restore every flapped metric, keep the final chord set
        sc.step(f"ocs:heal:restore_flaps:{len(flapped)}")
        flapped.clear()
        self._push(ls, chords, flapped)
        csr.refresh(ls)

        # post-heal convergence: every source bit-exact vs the oracle
        heal_exact = True
        got = engine.spf_results(csr, names)
        for s in names:
            oracle = ls.run_spf(s)
            res = got[s]
            if {k: v.metric for k, v in oracle.items()} != {
                k: v.metric for k, v in res.items()
            }:
                heal_exact = False
                break
            for node in oracle:
                if oracle[node].next_hops != res[node].next_hops:
                    heal_exact = False
                    break
        round_exact.append(heal_exact)
        bit_exact = all(round_exact)
        sc.step(
            f"ocs:settled:{'exact' if bit_exact else 'DIVERGED'}"
        )

        c = engine.get_counters()
        return OcsRewireResult(
            rounds=self.rounds,
            rewires=c["device.engine.rewires"],
            rewire_dispatches=c["device.engine.rewire_dispatches"],
            rewire_fallbacks=c["device.engine.rewire_fallbacks"],
            full_restages=c["device.engine.full_restages"],
            flaps=flaps,
            links_swapped=links_swapped,
            bit_exact=bit_exact,
            round_exact=round_exact,
            counters=c,
        )
